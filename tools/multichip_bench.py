#!/usr/bin/env python
"""MULTICHIP_r06 grid runner: the universe-scaling evidence table.

Runs ``bench.py --config riskmodel --inner`` once per (universe N,
device count) cell — each cell a fresh subprocess so ``--devices``
can set ``XLA_FLAGS=--xla_force_host_platform_device_count`` before
jax imports — and writes one JSON artifact holding every cell record
plus the derived eigen-stage speedup matrix.

The committed ``MULTICHIP_r06.json`` was produced on this repo's CI
container, where the 8 "devices" are XLA *host* devices multiplexed
onto the physical CPU cores actually present (``host_cpu_count`` in
every cell; 1 on the container).  On such a box the wall-clock speedup
from sharding is bounded by physical parallelism, not by the sharding
itself — the honest quantity the grid pins down there is the per-device
batch reduction (``eigen_rows_per_device``), which is what converts to
wall speedup one-for-one on real multi-chip hardware, plus the proof
that the sharded program scales to N=5000 at all without a host-side
full panel.  Run the same command on a TPU pod slice to regenerate the
table with real chips.

Usage::

    python tools/multichip_bench.py                      # full grid
    python tools/multichip_bench.py --universes 300 --devices 1,2
    BENCH_SMOKE_T=32 is honored via --smoke-t 32 (cells then carry a
    ``_t32`` universe-name suffix so they can never masquerade as the
    full-history record; see data/synthetic.py::resolve_universe).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _parse_ints(s: str) -> list:
    return [int(x) for x in s.replace(" ", "").split(",") if x]


def run_cell(universe: int, devices: int, platform: str, timeout: float,
             smoke_t: int | None) -> dict:
    """One grid cell = one fresh ``bench.py --inner`` subprocess.  Returns
    the bench record, or an ``{"error": ...}`` stub on failure — a torn
    cell must not lose the rest of the grid."""
    cmd = [sys.executable, os.path.join(REPO, "bench.py"),
           "--config", "riskmodel", "--inner", "--platform", platform,
           "--universe", str(universe), "--devices", str(devices)]
    env = dict(os.environ)
    if smoke_t is not None:
        env["BENCH_SMOKE_T"] = str(smoke_t)
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, env=env, cwd=REPO)
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout:.0f}s",
                "universe_n": universe, "devices": devices}
    wall = time.perf_counter() - t0
    rec = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and "metric" in obj:
                rec = obj
                break
    if proc.returncode != 0 or rec is None:
        return {"error": f"rc={proc.returncode}",
                "universe_n": universe, "devices": devices,
                "stderr_tail": proc.stderr[-800:]}
    rec["cell_wall_s"] = round(wall, 1)  # includes compile + subprocess
    return rec


def build_grid(universes, devices, platform="cpu", timeout=3600.0,
               smoke_t=None, echo=print) -> dict:
    cells = []
    for n in universes:
        for d in devices:
            echo(f"multichip: N={n} devices={d} ...")
            rec = run_cell(n, d, platform, timeout, smoke_t)
            tag = (f"eigen={rec.get('eigen_stage_wall_s')}s "
                   f"e2e={rec.get('e2e_wall_s')}s"
                   if "error" not in rec else rec["error"])
            echo(f"multichip: N={n} devices={d} -> {tag}")
            cells.append(rec)

    # eigen-stage speedup of each cell over its universe's 1-device cell
    # (the ISSUE-11 acceptance quantity), plus the per-device eigh-batch
    # row count — the hardware-independent scaling fact
    def _cell(n, d):
        for rec in cells:
            if rec.get("universe_n") == n and rec.get("devices") == d \
                    and "error" not in rec:
                return rec
        return None

    speedups = {}
    for n in universes:
        base = _cell(n, 1)
        row = {}
        for d in devices:
            cur = _cell(n, d)
            if base and cur and base.get("eigen_stage_wall_s") \
                    and cur.get("eigen_stage_wall_s"):
                row[str(d)] = round(base["eigen_stage_wall_s"]
                                    / cur["eigen_stage_wall_s"], 2)
            if cur and cur.get("padded_t"):
                cur["eigen_rows_per_device"] = cur["padded_t"] // max(
                    cur.get("mesh", {}).get("date", d), 1)
        speedups[str(n)] = row

    target_n, target_d = max(universes), max(devices)
    got = speedups.get(str(target_n), {}).get(str(target_d))
    return {
        "schema": "multichip/r06",
        "generated_by": "tools/multichip_bench.py",
        "platform": platform,
        "host_cpu_count": os.cpu_count(),
        "smoke_t": smoke_t,
        "note": ("virtual XLA host devices share the physical cores below "
                 "host_cpu_count; on a 1-core container the wall-clock "
                 "speedup column is flat by construction and the scaling "
                 "evidence is eigen_rows_per_device (the per-device batch "
                 "each chip would own on real hardware)"),
        "cells": cells,
        "eigen_stage_speedup_vs_1dev": speedups,
        "acceptance": {
            "quantity": "eigen_stage_speedup_vs_1dev"
                        f"[{target_n}][{target_d}]",
            "target": 2.0,
            "measured": got,
            "met_on_this_host": bool(got is not None and got >= 2.0),
            "physical_parallelism_bound": os.cpu_count(),
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--universes", default="300,1000,5000")
    ap.add_argument("--devices", default="1,2,8")
    ap.add_argument("--platform", default="cpu")
    ap.add_argument("--timeout", type=float, default=3600.0,
                    help="per-cell subprocess timeout (s)")
    ap.add_argument("--smoke-t", type=int, default=None,
                    help="bound history length via BENCH_SMOKE_T (cells "
                         "get a _t<N> universe-name suffix)")
    ap.add_argument("--out", default=os.path.join(REPO, "MULTICHIP_r06.json"))
    args = ap.parse_args(argv)

    grid = build_grid(_parse_ints(args.universes), _parse_ints(args.devices),
                      platform=args.platform, timeout=args.timeout,
                      smoke_t=args.smoke_t)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(grid, f, indent=1, sort_keys=True)
        f.write("\n")
    errs = [c for c in grid["cells"] if "error" in c]
    print(f"multichip: wrote {args.out} "
          f"({len(grid['cells'])} cells, {len(errs)} failed)")
    return 1 if errs else 0


if __name__ == "__main__":
    raise SystemExit(main())
