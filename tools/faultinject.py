"""Deterministic fault-injection harness for the hardened serving loop.

Drives the seeded scenario matrix (``mfm_tpu/utils/chaos.py::plan_suite``)
against a real daily-serving sequence — synthetic history -> fenced
checkpoint -> per-slab ``append_risk_pipeline`` updates — and asserts the
recovery contracts the production loop promises (docs/SERVING.md):

- **Torn / corrupt checkpoints** (truncate-*, corrupt-*): the fenced load
  refuses the damaged file with :class:`ArtifactCorruptError`; restoring
  the previous generation and replaying the append reproduces the
  fault-free run BITWISE.
- **Crash mid-write** (kill-*): a real ``mfm-tpu risk --update`` subprocess
  is SIGKILLed at a named protocol point (``MFM_CHAOS_KILL``).  Killed
  after the tmp write: the old checkpoint still loads and the replay is
  bitwise the fault-free run.  Killed after the rename (pointer not yet
  swapped): the NEW checkpoint loads, the pointer heals forward, and the
  subsequent slab is bitwise the fault-free run — proving the subprocess's
  checkpoint is interchangeable with the in-process one.
- **Poisoned slabs** (nan-slab, outlier-slab, universe-collapse): the bad
  date is quarantined with a reported reason, and every healthy date's
  outputs — plus the final carries — are bitwise what a run that NEVER SAW
  the poisoned date produces (the carry-freeze contract).
- **Flaky transport** (flaky-store): ``with_retry`` over a
  :class:`FlakyStore` recovers from transient errors on the documented
  backoff schedule and re-raises non-retryable errors immediately.
- **Crash at the manifest write** (kill-at-manifest): SIGKILL between the
  run manifest's tmp write and its rename (``run_manifest.after_tmp``).
  Telemetry must never endanger the data: the already-fenced checkpoint
  loads clean and replays bitwise, no torn ``run_manifest.json`` is left
  behind, and the next healthy run writes a manifest ``mfm-tpu doctor``
  accepts.
- **Crash mid eigen-carry save** (eigen-kill-mid-update): the incremental
  eigen state (``config.eigen_incremental``: prefix moments + frozen
  draws) rides the same fenced npz — SIGKILL after the tmp write leaves
  the prior generation byte-identical, the reloaded eigen carry bitwise,
  the replay bitwise the fault-free run, and the directory doctor-green.
- **Steady state**: after warmup, the per-date guarded serving loop stays
  within ONE jit compile (``assert_max_compiles``).
- **Query-service faults** (query-*): the request side of the stack
  (serve/query.py + serve/server.py).  A real ``mfm-tpu serve`` subprocess
  is SIGKILLed mid-stream and its durable responses must be a bitwise
  prefix of the clean replay; poisoned request slabs dead-letter with the
  right reasons while healthy answers stay bitwise; a queue-overflow storm
  sheds EXACTLY the oldest requests and serves the survivors bitwise; a
  checkpoint hot-swap under load answers each batch bitwise from its own
  generation and a corrupt swap trips the breaker (``fence_audit``); and
  the steady-state query loop holds ``assert_max_compiles(1)`` per padded
  batch bucket with telemetry on.

Everything is seeded (fault plans, synthetic panel); a failing plan
replays exactly.  Exit 0 iff every plan passes; ``--out`` writes the JSON
report.

    JAX_PLATFORMS=cpu python tools/faultinject.py --out /tmp/faults.json
    python tools/faultinject.py --plans nan-slab,kill-after-tmp
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import random
import shutil
import signal
import subprocess
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

# harness geometry: enough dates for warmup + three 4-date serving slabs,
# small enough that the whole matrix runs in minutes on CPU
T_TOTAL, T_HIST, SLAB = 44, 32, 4
N_STOCKS, N_IND, N_STYLES = 20, 3, 2
EIGEN_SIMS = 8


def _config():
    from mfm_tpu.config import PipelineConfig, QuarantinePolicy, RiskModelConfig

    # eigen_sim_length pinned: a checkpoint freezes its Monte-Carlo draws,
    # and only a pinned length keeps the replay on the same draws
    return PipelineConfig(
        risk=RiskModelConfig(eigen_n_sims=EIGEN_SIMS, eigen_sim_length=T_TOTAL,
                             quarantine=QuarantinePolicy(enabled=True)),
        dtype="float32",
    )


def _make_tables(seed: int):
    """Synthetic barra table split into history + cumulative slab tables
    (``append_risk_pipeline`` takes the full table and serves the dates
    past the checkpoint)."""
    from mfm_tpu.data.synthetic import synthetic_barra_table

    df, _ = synthetic_barra_table(T=T_TOTAL, N=N_STOCKS, P=N_IND,
                                  Q=N_STYLES, seed=seed)
    dates = sorted(df["date"].unique())
    cuts = [dates[T_HIST - 1]] + [dates[T_HIST + (i + 1) * SLAB - 1]
                                  for i in range((T_TOTAL - T_HIST) // SLAB)]
    hist = df[df["date"] <= cuts[0]]
    slabs = [df[df["date"] <= c] for c in cuts[1:]]
    slab_dates = [dates[T_HIST + i * SLAB: T_HIST + (i + 1) * SLAB]
                  for i in range(len(slabs))]
    return df, hist, slabs, slab_dates


def _carries(state):
    import jax

    # copy=True: on CPU the numpy conversion can alias the device buffer,
    # and these snapshots must outlive the donating update calls that
    # recycle it.  The eigen-carry leaves are None outside
    # config.eigen_incremental and flatten to nothing, so non-incremental
    # plans see the same three carries as before
    return [np.array(x, copy=True) for x in jax.tree_util.tree_leaves(
        (state.nw_carry, state.vr_num, state.vr_den,
         state.eig_R, state.eig_p, state.eig_n))]


def _outputs_by_date(res):
    """{date -> {field -> (row,) array}} over the appended slab."""
    from mfm_tpu.pipeline import date_stamp

    out = {}
    for i, d in enumerate(res.arrays.dates):
        out[date_stamp(d)] = {
            f: np.array(np.asarray(getattr(res.outputs, f))[i], copy=True)
            for f in res.outputs._fields}
    return out


def _init_checkpoint(workdir: str, hist, cfg) -> str:
    from mfm_tpu.pipeline import run_risk_pipeline, save_pipeline_state

    res = run_risk_pipeline(barra_df=hist, config=cfg, with_state=True)
    path = os.path.join(workdir, "state.npz")
    save_pipeline_state(path, res)
    return path


def _append(path: str, table, cfg, force: bool = False):
    from mfm_tpu.pipeline import append_risk_pipeline, save_pipeline_state

    res = append_risk_pipeline(path, table, config=cfg, force=force)
    save_pipeline_state(path, res)
    return res


def _snapshot(workdir: str, tag: str):
    """Copy the checkpoint AND its fencing pointer as one consistent pair."""
    snap = os.path.join(workdir, f"snap_{tag}")
    os.makedirs(snap, exist_ok=True)
    for f in ("state.npz", "latest.json"):
        shutil.copy(os.path.join(workdir, f), os.path.join(snap, f))
    return snap


def _restore(workdir: str, snap: str, pointer: bool = True):
    shutil.copy(os.path.join(snap, "state.npz"),
                os.path.join(workdir, "state.npz"))
    if pointer:
        shutil.copy(os.path.join(snap, "latest.json"),
                    os.path.join(workdir, "latest.json"))


def _assert_outputs_equal(got: dict, want: dict, dates, what: str):
    for d in dates:
        for f, w in want[d].items():
            g = got[d][f]
            if not np.array_equal(g, w, equal_nan=True):
                raise AssertionError(
                    f"{what}: output {f!r} at {d} diverged from the "
                    f"fault-free run (max |diff| "
                    f"{np.nanmax(np.abs(g.astype(np.float64) - w.astype(np.float64)))})")


def _assert_carries_equal(got, want, what: str):
    for i, (g, w) in enumerate(zip(got, want)):
        if not np.array_equal(g, w, equal_nan=True):
            raise AssertionError(
                f"{what}: carry leaf {i} diverged from the fault-free run")


class Baseline:
    """The fault-free serving sequence, snapshotted after every stage so a
    plan can start from any point with a consistent (file, pointer) pair."""

    def __init__(self, workdir: str, seed: int):
        self.cfg = _config()
        self.full, self.hist, self.slabs, self.slab_dates = _make_tables(seed)
        self.dir = os.path.join(workdir, "baseline")
        os.makedirs(self.dir)
        self.path = _init_checkpoint(self.dir, self.hist, self.cfg)
        self.snaps = [_snapshot(self.dir, "hist")]
        self.outputs, self.reports, self.carries = [], [], []
        for i, table in enumerate(self.slabs):
            res = _append(self.path, table, self.cfg)
            if res.report is None:
                raise AssertionError("baseline lost its guard report")
            q = np.asarray(res.report.quarantined)
            if q.any():
                raise AssertionError(
                    f"baseline slab {i} quarantined {int(q.sum())} clean "
                    "date(s) — guard thresholds are mis-tuned for the "
                    "synthetic panel")
            self.outputs.append(_outputs_by_date(res))
            self.reports.append(res.report)
            self.carries.append(_carries(res.state))
            self.snaps.append(_snapshot(self.dir, f"slab{i}"))


def _fresh_workdir(root: str, plan_name: str, snap: str) -> str:
    d = os.path.join(root, plan_name)
    os.makedirs(d)
    _restore(d, snap)
    return d


# -- plan runners ------------------------------------------------------------

def run_byte_fault(plan, base: Baseline, root: str) -> dict:
    """truncate-* / corrupt-*: damaged checkpoint refused, previous
    generation replays bitwise."""
    from mfm_tpu.data.artifacts import ArtifactCorruptError, load_risk_state
    from mfm_tpu.utils.chaos import corrupt_file, truncate_file

    d = _fresh_workdir(root, plan.name, base.snaps[1])  # state after slab 0
    path = os.path.join(d, "state.npz")
    if plan.kind == "truncate":
        frac = plan.param("frac")
        n = (int(frac * os.path.getsize(path)) if frac is not None
             else int(plan.param("n_bytes")))
        truncate_file(path, n)
    else:
        corrupt_file(path, int(plan.param("n_bytes")), plan.seed)
    try:
        load_risk_state(path)
    except ArtifactCorruptError as err:
        detected = str(err)
    else:
        raise AssertionError(f"{plan.name}: corrupt checkpoint loaded clean")
    # recovery: previous generation (the slab-0 producer's input) + replay
    _restore(d, base.snaps[0])
    res = _append(path, base.slabs[0], base.cfg)
    _assert_outputs_equal(_outputs_by_date(res), base.outputs[0],
                          base.slab_dates[0], plan.name)
    _assert_carries_equal(_carries(res.state), base.carries[0], plan.name)
    return {"detected": detected.split(" — ")[0]}


def run_kill(plan, base: Baseline, root: str) -> dict:
    """kill-*: SIGKILL a real `risk --update` subprocess at a protocol
    point, then prove the recovery the fence promises."""
    from mfm_tpu.data.artifacts import load_risk_state, read_pointer

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])  # state after history
    path = os.path.join(d, "state.npz")
    table_csv = os.path.join(d, "slab0.csv")
    base.slabs[0].to_csv(table_csv, index=False)
    cmd = [sys.executable, "-m", "mfm_tpu.cli", "risk",
           "--barra", table_csv, "--update", path, "--quarantine",
           "--eigen-sims", str(EIGEN_SIMS),
           "--eigen-sim-length", str(T_TOTAL),
           "--out", os.path.join(d, "tables")]
    env = {**os.environ, "MFM_CHAOS_KILL": point, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the subprocess to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    state, meta = load_risk_state(path)  # fenced: must load clean
    ptr = read_pointer(path)
    if point == "save_artifact.after_tmp":
        # old checkpoint must be intact and untouched; replay is bitwise
        if meta["last_date"] != str(base.hist["date"].max()):
            raise AssertionError(f"{plan.name}: checkpoint advanced past a "
                                 "write that never completed")
        res = _append(path, base.slabs[0], base.cfg)
        _assert_outputs_equal(_outputs_by_date(res), base.outputs[0],
                              base.slab_dates[0], plan.name)
        _assert_carries_equal(_carries(res.state), base.carries[0], plan.name)
        healed = False
    else:  # after_rename: new file live, pointer was stale -> healed forward
        if meta["last_date"] != base.slab_dates[0][-1]:
            raise AssertionError(f"{plan.name}: renamed checkpoint does not "
                                 "carry the appended dates")
        if read_pointer(path)["generation"] != meta["generation"]:
            raise AssertionError(f"{plan.name}: pointer not healed forward")
        ptr = read_pointer(path)
        # the subprocess's checkpoint must be interchangeable with the
        # in-process one: carries bitwise, and the NEXT slab bitwise
        _assert_carries_equal(_carries(state), base.carries[0],
                              f"{plan.name} (subprocess checkpoint)")
        res = _append(path, base.slabs[1], base.cfg)
        _assert_outputs_equal(_outputs_by_date(res), base.outputs[1],
                              base.slab_dates[1], plan.name)
        healed = True
    return {"killed_at": point, "pointer": ptr, "pointer_healed": healed}


def run_eigen_kill(plan, base: Baseline, root: str) -> dict:
    """eigen-kill-mid-update: SIGKILL between the checkpoint's tmp write and
    its rename while the state carries the INCREMENTAL eigen leaves
    (config.eigen_incremental=True: eig_R/eig_p/eig_n prefix moments + the
    frozen draw tensor).  The carry rides the same fenced npz as every
    other leaf, so the crash must leave the prior generation byte-identical
    on disk, the fenced load must hand back the same eigen carry bitwise,
    the replay must land on the fault-free outputs AND eigen carry bitwise,
    and a post-crash CLI update must leave a doctor-green directory."""
    import dataclasses

    from mfm_tpu.data.artifacts import load_risk_state

    point = plan.param("point")
    d = os.path.join(root, plan.name)
    os.makedirs(d)
    icfg = dataclasses.replace(base.cfg, risk=dataclasses.replace(
        base.cfg.risk, eigen_sim_length=None, eigen_incremental=True))
    path = _init_checkpoint(d, base.hist, icfg)
    state0, _ = load_risk_state(path)
    if state0.eig_R is None or state0.eig_draws is None:
        raise AssertionError(f"{plan.name}: history checkpoint carries no "
                             "eigen carry — eigen_incremental did not engage")
    eig0 = [np.array(x, copy=True) for x in
            (state0.eig_R, state0.eig_p, state0.eig_n)]

    # fault-free reference for slab 0, then rewind to the history snapshot
    snap = _snapshot(d, "hist")
    ref = _append(path, base.slabs[0], icfg)
    ref_outputs = _outputs_by_date(ref)
    ref_carries = _carries(ref.state)
    _restore(d, snap)
    with open(path, "rb") as fh:
        pre_bytes = fh.read()

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _update_cmd(slab_csv, table):
        table.to_csv(slab_csv, index=False)
        return [sys.executable, "-m", "mfm_tpu.cli", "risk",
                "--barra", slab_csv, "--update", path, "--quarantine",
                "--eigen-sims", str(EIGEN_SIMS), "--eigen-incremental",
                "--out", os.path.join(d, "tables")]

    cmd = _update_cmd(os.path.join(d, "slab0.csv"), base.slabs[0])
    proc = subprocess.run(cmd, env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the subprocess to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")

    # prior generation byte-identical on disk — the tmp write touched
    # nothing but its own tmp file
    with open(path, "rb") as fh:
        post_bytes = fh.read()
    if post_bytes != pre_bytes:
        raise AssertionError(f"{plan.name}: the checkpoint bytes changed "
                             "under a write that never renamed")
    state, meta = load_risk_state(path)  # fenced: must load clean
    if meta["last_date"] != str(base.hist["date"].max()):
        raise AssertionError(f"{plan.name}: checkpoint advanced past a "
                             "write that never completed")
    for got, want, name in zip((state.eig_R, state.eig_p, state.eig_n),
                               eig0, ("eig_R", "eig_p", "eig_n")):
        if np.asarray(got).tobytes() != want.tobytes():
            raise AssertionError(f"{plan.name}: reloaded eigen carry leaf "
                                 f"{name} is not bitwise the pre-crash one")

    # replay: bitwise the fault-free run, eigen carry included (_carries
    # picks up the eig leaves under eigen_incremental)
    res = _append(path, base.slabs[0], icfg)
    _assert_outputs_equal(_outputs_by_date(res), ref_outputs,
                          base.slab_dates[0], plan.name)
    _assert_carries_equal(_carries(res.state), ref_carries, plan.name)

    # the next slab through the real CLI must succeed and leave a
    # doctor-green directory (manifest + fenced checkpoint)
    cmd2 = _update_cmd(os.path.join(d, "slab1.csv"), base.slabs[1])
    proc2 = subprocess.run(cmd2, env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash update failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    _, meta2 = load_risk_state(path)
    if meta2["last_date"] != base.slab_dates[1][-1]:
        raise AssertionError(f"{plan.name}: post-crash CLI update did not "
                             "carry the appended dates")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor rejects the post-crash "
                             f"state\n{doc.stdout[-2000:]}")
    return {"killed_at": point, "prior_state": "byte-identical",
            "replay": "bitwise", "doctor": "green"}


def run_shard_kill(plan, base: Baseline, root: str) -> dict:
    """shard-kill-mid-append: SIGKILL a ``risk --update --mesh DxS``
    subprocess between the checkpoint's tmp write and its rename — the ONE
    update step ran SHARDED (slab panels sharded over the mesh, state
    replicated; PR 11's scaling path).  Sharding must change nothing about
    the crash story: the prior generation stays byte-identical on disk,
    the fenced load is clean, and an (unsharded) in-process replay lands
    bitwise on the fault-free outputs and carries — proving the sharded
    subprocess's aborted step left no side effects AND that a sharded
    update is checkpoint-interchangeable with a single-device one."""
    from mfm_tpu.data.artifacts import load_risk_state

    point = plan.param("point")
    mesh = plan.param("mesh", "2x2")
    nd, _, ns = mesh.partition("x")
    n_dev = int(nd) * int(ns or 1)
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    with open(path, "rb") as fh:
        pre_bytes = fh.read()
    table_csv = os.path.join(d, "slab0.csv")
    base.slabs[0].to_csv(table_csv, index=False)
    cmd = [sys.executable, "-m", "mfm_tpu.cli", "risk",
           "--barra", table_csv, "--update", path, "--quarantine",
           "--mesh", mesh,
           "--eigen-sims", str(EIGEN_SIMS),
           "--eigen-sim-length", str(T_TOTAL),
           "--out", os.path.join(d, "tables")]
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        flags += f" --xla_force_host_platform_device_count={n_dev}"
    env = {**os.environ, "MFM_CHAOS_KILL": point, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": flags,
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the sharded subprocess to die by "
            f"SIGKILL at {point}, got rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}")

    # the fence's whole promise, now under a mesh: the tmp write touched
    # nothing but its own tmp file
    with open(path, "rb") as fh:
        post_bytes = fh.read()
    if post_bytes != pre_bytes:
        raise AssertionError(f"{plan.name}: checkpoint bytes changed under "
                             "a sharded write that never renamed")
    _, meta = load_risk_state(path)  # fenced: must load clean
    if meta["last_date"] != str(base.hist["date"].max()):
        raise AssertionError(f"{plan.name}: checkpoint advanced past a "
                             "sharded write that never completed")
    res = _append(path, base.slabs[0], base.cfg)
    _assert_outputs_equal(_outputs_by_date(res), base.outputs[0],
                          base.slab_dates[0], plan.name)
    _assert_carries_equal(_carries(res.state), base.carries[0], plan.name)
    return {"killed_at": point, "mesh": mesh,
            "prior_state": "byte-identical", "replay": "bitwise"}


def run_kill_manifest(plan, base: Baseline, root: str) -> dict:
    """kill-at-manifest: SIGKILL between the manifest's tmp write and its
    rename.  The checkpoint (written and fenced BEFORE the manifest) must be
    untouched, no torn manifest may exist, and the next run's manifest must
    pass the doctor audit."""
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.obs.manifest import read_run_manifest

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _update_cmd(slab_csv, table):
        table.to_csv(slab_csv, index=False)
        return [sys.executable, "-m", "mfm_tpu.cli", "risk",
                "--barra", slab_csv, "--update", path, "--quarantine",
                "--eigen-sims", str(EIGEN_SIMS),
                "--eigen-sim-length", str(T_TOTAL),
                "--out", os.path.join(d, "tables")]

    cmd = _update_cmd(os.path.join(d, "slab0.csv"), base.slabs[0])
    proc = subprocess.run(cmd, env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the subprocess to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    man_path = os.path.join(d, "run_manifest.json")
    if os.path.exists(man_path):
        raise AssertionError(f"{plan.name}: a manifest exists despite the "
                             "kill before its rename — the write is not "
                             "tmp-then-rename atomic")
    # the checkpoint was fenced and swapped BEFORE the manifest write: it
    # must carry the appended slab and be interchangeable with the
    # in-process run (carries bitwise, next slab bitwise)
    state, meta = load_risk_state(path)
    if meta["last_date"] != base.slab_dates[0][-1]:
        raise AssertionError(f"{plan.name}: checkpoint does not carry the "
                             "appended dates — manifest kill corrupted it")
    _assert_carries_equal(_carries(state), base.carries[0],
                          f"{plan.name} (subprocess checkpoint)")
    res = _append(path, base.slabs[1], base.cfg)
    _assert_outputs_equal(_outputs_by_date(res), base.outputs[1],
                          base.slab_dates[1], plan.name)
    # the next CLI run must leave a valid, doctor-clean manifest behind
    cmd2 = _update_cmd(os.path.join(d, "slab2.csv"), base.slabs[2])
    proc2 = subprocess.run(cmd2, env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash update failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    man = read_run_manifest(man_path)   # raises ManifestError if torn
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor rejects the post-crash "
                             f"manifest\n{doc.stdout[-2000:]}")
    return {"killed_at": point, "manifest_after_crash": "absent",
            "recovered_manifest_health": man["health"]["status"]}


def run_trace_kill(plan, base: Baseline, root: str) -> dict:
    """trace-kill-mid-flush: SIGKILL between the Chrome-trace tmp write and
    its rename — the trace is the LAST artifact a ``--metrics-dir`` run
    flushes, so the checkpoint and manifest (fenced before it) must be
    untouched (carries bitwise, next slab bitwise), no torn trace.json may
    exist, and a clean rerun must leave a parseable trace plus a
    doctor-green directory."""
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.obs.manifest import read_run_manifest
    from mfm_tpu.obs.trace import parse_chrome_trace

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    mdir = os.path.join(d, "metrics")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _update_cmd(slab_csv, table):
        table.to_csv(slab_csv, index=False)
        return [sys.executable, "-m", "mfm_tpu.cli", "risk",
                "--barra", slab_csv, "--update", path, "--quarantine",
                "--eigen-sims", str(EIGEN_SIMS),
                "--eigen-sim-length", str(T_TOTAL),
                "--metrics-dir", mdir,
                "--out", os.path.join(d, "tables")]

    cmd = _update_cmd(os.path.join(d, "slab0.csv"), base.slabs[0])
    proc = subprocess.run(cmd, env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the subprocess to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    trace_path = os.path.join(mdir, "trace.json")
    if os.path.exists(trace_path):
        raise AssertionError(f"{plan.name}: a trace.json exists despite the "
                             "kill before its rename — the flush is not "
                             "tmp-then-rename atomic")
    # the checkpoint and manifest were fenced BEFORE the trace flush: the
    # slab must be carried, replay must be bitwise, the manifest must read
    # cleanly and already carry its root trace_id
    state, meta = load_risk_state(path)
    if meta["last_date"] != base.slab_dates[0][-1]:
        raise AssertionError(f"{plan.name}: checkpoint does not carry the "
                             "appended dates — trace kill corrupted it")
    _assert_carries_equal(_carries(state), base.carries[0],
                          f"{plan.name} (subprocess checkpoint)")
    man = read_run_manifest(os.path.join(d, "run_manifest.json"))
    if not man.get("trace_id"):
        raise AssertionError(f"{plan.name}: manifest fenced before the "
                             "trace flush carries no root trace_id")
    res = _append(path, base.slabs[1], base.cfg)
    _assert_outputs_equal(_outputs_by_date(res), base.outputs[1],
                          base.slab_dates[1], plan.name)
    # a clean rerun must flush a parseable, Perfetto-loadable trace
    cmd2 = _update_cmd(os.path.join(d, "slab2.csv"), base.slabs[2])
    proc2 = subprocess.run(cmd2, env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash update failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    with open(trace_path, encoding="utf-8") as fh:
        text = fh.read()
    try:
        events = parse_chrome_trace(text)
    except ValueError as err:
        raise AssertionError(f"{plan.name}: recovered trace.json fails the "
                             f"schema check: {err}")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor rejects the post-crash "
                             f"state\n{doc.stdout[-2000:]}")
    return {"killed_at": point, "trace_after_crash": "absent",
            "recovered_trace_events": len(events),
            "manifest_trace_id": man["trace_id"]}


_FLIGHTREC_DRIVER = """\
import sys
from mfm_tpu.obs import flightrec as fr
fr.arm(sys.argv[1])
fr.record_event("batch_error", trace_id="df" * 16, kind_of="query",
                scenario="base", n=4, detail="staged batch failure")
fr.record_event("breaker_open", reason="failures")
out = fr.trigger_dump("breaker_open",
                      state={"breaker": {"state": "open",
                                         "open_reason": "failures"}})
print(out, flush=True)
"""


def run_flightrec_kill(plan, base: Baseline, root: str) -> dict:
    """flightrec-kill-mid-dump: SIGKILL between the flight recorder's tmp
    write and its rename.  The postmortem writer runs INSIDE the serving
    process next to the checkpoint, so the drill must prove a crash
    mid-dump leaves no torn ``flightrec.json``, does not touch the
    checkpoint bytes, and that a clean re-trigger writes a dump
    :func:`read_flightrec` accepts (carrying the staged breaker trigger
    and the triggering request's trace id) with the directory still
    doctor-green."""
    import hashlib

    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.obs.flightrec import FLIGHTREC_NAME, read_flightrec

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    frec_path = os.path.join(d, FLIGHTREC_NAME)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _ckpt_hash():
        with open(path, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()

    before = _ckpt_hash()
    driver = os.path.join(d, "frec_driver.py")
    with open(driver, "w", encoding="utf-8") as fh:
        fh.write(_FLIGHTREC_DRIVER)
    cmd = [sys.executable, driver, frec_path]
    proc = subprocess.run(cmd, env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the dump driver to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    if os.path.exists(frec_path):
        raise AssertionError(f"{plan.name}: a flightrec.json exists despite "
                             "the kill before its rename — the dump is not "
                             "tmp-then-rename atomic")
    if _ckpt_hash() != before:
        raise AssertionError(f"{plan.name}: the flightrec dump touched the "
                             "checkpoint bytes")
    # a clean re-trigger must land a parseable postmortem stamped with the
    # staged trigger and the triggering request's trace id
    proc2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash dump failed "
                            f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    try:
        rec = read_flightrec(frec_path)
    except ValueError as err:
        raise AssertionError(f"{plan.name}: recovered flightrec.json fails "
                             f"the schema check: {err}")
    if rec["trigger"] != "breaker_open":
        raise AssertionError(f"{plan.name}: dump trigger is "
                             f"{rec['trigger']!r}, wanted 'breaker_open'")
    if rec.get("trace_id") != "df" * 16:
        raise AssertionError(f"{plan.name}: dump lost the triggering "
                             f"request's trace id ({rec.get('trace_id')!r})")
    if len(rec["events"]) < 2:
        raise AssertionError(f"{plan.name}: dump carries "
                             f"{len(rec['events'])} events, wanted >= 2")
    # the checkpoint the recorder dumped beside must still be fully usable:
    # the CLI appends a slab, the carries and next slab replay bitwise
    slab_csv = os.path.join(d, "slab0.csv")
    base.slabs[0].to_csv(slab_csv, index=False)
    upd = subprocess.run(
        [sys.executable, "-m", "mfm_tpu.cli", "risk",
         "--barra", slab_csv, "--update", path, "--quarantine",
         "--eigen-sims", str(EIGEN_SIMS),
         "--eigen-sim-length", str(T_TOTAL),
         "--out", os.path.join(d, "tables")],
        env=env, capture_output=True, text=True, timeout=600)
    if upd.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash update failed "
                             f"rc={upd.returncode}\n{upd.stderr[-2000:]}")
    state, meta = load_risk_state(path)
    if meta["last_date"] != base.slab_dates[0][-1]:
        raise AssertionError(f"{plan.name}: checkpoint does not carry the "
                             "appended dates after the crash drill")
    _assert_carries_equal(_carries(state), base.carries[0],
                          f"{plan.name} (subprocess checkpoint)")
    res = _append(path, base.slabs[1], base.cfg)
    _assert_outputs_equal(_outputs_by_date(res), base.outputs[1],
                          base.slab_dates[1], plan.name)
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor rejects the post-crash "
                             f"state\n{doc.stdout[-2000:]}")
    return {"killed_at": point, "flightrec_after_crash": "absent",
            "recovered_trigger": rec["trigger"],
            "recovered_events": len(rec["events"]),
            "recovered_trace_id": rec["trace_id"]}


_POISON_OK_REASONS = {
    # NaN returns are dropped by the frame->arrays conversion, so a
    # NaN-poisoned CSV date manifests as universe collapse downstream of
    # the ETL; the raw-array nan_density path is proven in
    # tests/test_quarantine.py
    "nan_slab": {"nan_density", "universe_collapse"},
    "outlier_slab": {"ret_outlier"},
    "universe_slab": {"universe_collapse"},
}


def run_poison(plan, base: Baseline, root: str) -> dict:
    """nan-slab / outlier-slab / universe-collapse: the poisoned date is
    quarantined with a reported reason; healthy dates and the final carries
    are bitwise a run that never saw it."""
    from mfm_tpu.serve.guard import reason_names

    rng = np.random.default_rng(plan.seed)
    bad_date = base.slab_dates[0][2]
    table = base.slabs[0].copy()
    mask = table["date"] == bad_date
    stocks = table.loc[mask, "stocknames"].unique()
    if plan.kind == "nan_slab":
        # 60% of the date's stocks, not the plan's full frac: all-NaN rows
        # would drop the DATE itself in the frame->arrays conversion and
        # the guard would never see it
        hit = rng.choice(stocks, size=max(1, int(round(0.6 * len(stocks)))),
                         replace=False)
        table.loc[mask & table["stocknames"].isin(hit), "ret"] = np.nan
    elif plan.kind == "outlier_slab":
        k = max(1, int(round(float(plan.param("frac", 0.3)) * len(stocks))))
        hit = rng.choice(stocks, size=k, replace=False)
        sel = mask & table["stocknames"].isin(hit)
        table.loc[sel, "ret"] = 0.5 * rng.choice([-1.0, 1.0], size=int(sel.sum()))
    else:  # universe_slab
        keep = float(plan.param("keep_frac", 0.2))
        hit = rng.choice(stocks, size=int(round((1 - keep) * len(stocks))),
                         replace=False)
        table = table[~(mask & table["stocknames"].isin(hit))]

    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    res = _append(path, table, base.cfg)
    rep = res.report
    by_date = _outputs_by_date(res)
    dates = [s for s in by_date]
    q = {dt: bool(np.asarray(rep.quarantined)[i])
         for i, dt in enumerate(dates)}
    if not q.get(bad_date):
        raise AssertionError(f"{plan.name}: poisoned date {bad_date} was "
                             "NOT quarantined")
    reasons = reason_names(int(np.asarray(rep.reasons)[dates.index(bad_date)]))
    if not set(reasons) & _POISON_OK_REASONS[plan.kind]:
        raise AssertionError(
            f"{plan.name}: expected a reason in "
            f"{sorted(_POISON_OK_REASONS[plan.kind])}, got {reasons}")
    healthy = [dt for dt in dates if not q[dt]]
    if [dt for dt in dates if q[dt]] != [bad_date]:
        raise AssertionError(f"{plan.name}: quarantined more than the "
                             f"poisoned date: {[d for d in dates if q[d]]}")
    # the carry-freeze contract: a run that NEVER saw the poisoned date
    d2 = _fresh_workdir(root, plan.name + "-ref", base.snaps[0])
    ref = _append(os.path.join(d2, "state.npz"),
                  base.slabs[0][base.slabs[0]["date"] != bad_date], base.cfg)
    _assert_outputs_equal(by_date, _outputs_by_date(ref), healthy, plan.name)
    _assert_carries_equal(_carries(res.state), _carries(ref.state), plan.name)
    # served_cov at the quarantined date is the last healthy covariance
    served = np.asarray(rep.served_cov)[dates.index(bad_date)]
    prev = by_date[healthy[1]]  # the healthy date right before bad_date
    if not np.array_equal(served,
                          np.asarray(rep.served_cov)[dates.index(healthy[1])]):
        raise AssertionError(f"{plan.name}: degraded serve is not the last "
                             "healthy covariance")
    del prev
    stale = int(np.asarray(rep.staleness)[dates.index(bad_date)])
    return {"quarantined": bad_date, "reasons": reasons, "staleness": stale}


def run_flaky_store(plan, base: Baseline, root: str) -> dict:
    """flaky-store: with_retry + FlakyStore recover on the documented
    schedule; non-retryable errors surface immediately."""
    import pandas as pd

    from mfm_tpu.data.etl import PanelStore, with_retry
    from mfm_tpu.utils.chaos import FlakyStore

    d = os.path.join(root, plan.name)
    os.makedirs(d)
    store = PanelStore(os.path.join(d, "store"))
    n_failures = int(plan.param("n_failures", 2))
    fs = FlakyStore(store, n_failures=n_failures, methods=("insert",))
    df = pd.DataFrame({"ts_code": ["a", "b"], "trade_date": [1, 1],
                       "x": [1.0, 2.0]})
    delays = []
    inserted = with_retry(
        lambda: fs.insert("t", df, unique=("ts_code", "trade_date")),
        attempts=n_failures + 1, backoff_s=0.25, sleep=delays.append,
        exponential=True, jitter=0.5, seed=plan.seed,
        retryable=(ConnectionError,))
    if inserted != 2 or len(store.read("t")) != 2:
        raise AssertionError(f"{plan.name}: retries did not complete the "
                             f"insert (inserted={inserted})")
    if len(delays) != n_failures:
        raise AssertionError(f"{plan.name}: expected {n_failures} backoff "
                             f"sleeps, saw {len(delays)}")
    for i, dl in enumerate(delays):
        lo, hi = 0.25 * 2 ** i * 0.5, 0.25 * 2 ** i * 1.5
        if not lo <= dl <= hi:
            raise AssertionError(f"{plan.name}: delay {i} = {dl} outside "
                                 f"the jittered exponential band [{lo}, {hi}]")
    # a non-retryable error must pass through with zero sleeps
    bombs = FlakyStore(store, n_failures=1, methods=("insert",),
                       exc_factory=TypeError)
    hard_delays = []
    try:
        with_retry(lambda: bombs.insert("t", df), attempts=3, backoff_s=0.25,
                   sleep=hard_delays.append, retryable=(ConnectionError,))
    except TypeError:
        pass
    else:
        raise AssertionError(f"{plan.name}: non-retryable error was retried")
    if hard_delays:
        raise AssertionError(f"{plan.name}: slept before re-raising a "
                             "non-retryable error")
    return {"injected_failures": n_failures,
            "backoff_schedule_s": [round(x, 4) for x in delays]}


def run_steady_state(base: Baseline, root: str) -> dict:
    """After warmup, the per-date guarded serving loop compiles at most
    once across an arbitrary number of dates (the <=1-compile contract)."""
    from mfm_tpu.utils.contracts import assert_max_compiles

    full = base.full
    dates = sorted(full["date"].unique())
    d = _fresh_workdir(root, "steady-state", base.snaps[0])
    path = os.path.join(d, "state.npz")
    # warmup: first 1-date append compiles the (T=1)-shaped guarded step
    _append(path, full[full["date"] <= dates[T_HIST]], base.cfg)
    with assert_max_compiles(1, "steady-state guarded serving loop") as c:
        for t in range(T_HIST + 1, T_HIST + 4):
            _append(path, full[full["date"] <= dates[t]], base.cfg)
    return {"dates_served": 3, "compiles": c.count}


# -- query-service plans -----------------------------------------------------

def _query_engine(path: str):
    """Factor-space engine over a guarded checkpoint (what `mfm-tpu serve`
    builds).  A fresh instance per call: baselines must not share jit-donated
    operands with the server under test."""
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.serve import QueryEngine

    state, meta = load_risk_state(path)
    return QueryEngine.from_risk_state(state, meta)


def _query_requests(seed: int, n: int, k: int,
                    deadline_s: float = 600.0) -> list:
    """Seeded JSONL request lines (ids q0..q{n-1}, K factor exposures).
    Deadlines are generous: these plans assert recovery determinism, not
    wall-clock behaviour."""
    rng = np.random.default_rng(seed)
    return [json.dumps({"id": f"q{i}",
                        "weights": np.round(rng.normal(0.0, 1.0, k),
                                            6).tolist(),
                        "deadline_s": deadline_s}, sort_keys=True)
            for i in range(n)]


def run_query_kill(plan, base: Baseline, root: str) -> dict:
    """query-kill-mid-batch: SIGKILL a real `mfm-tpu serve` subprocess at
    the end of a named batch.  Responses emitted before the kill are
    durable (flushed per drain), and the clean replay's prefix must match
    them byte-for-byte — same floats, same order."""
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    k = _query_engine(path).K
    req = os.path.join(d, "req.jsonl")
    with open(req, "w") as fh:
        fh.write("\n".join(_query_requests(plan.seed, 24, k)) + "\n")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _serve_cmd(out_name):
        return [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
                "--input", req, "--output", os.path.join(d, out_name),
                "--dead-letter", os.path.join(d, "dead_letter.jsonl"),
                "--batch-max", "8", "--deadline-s", "600", "--gulp",
                # fsync per emit: the durable-prefix assertion below then
                # covers ServePolicy.fsync_emits, not just Python's flush
                "--fsync-emits"]

    kill_env = {**env, "MFM_CHAOS_KILL": plan.param("point"),
                "MFM_CHAOS_KILL_MATCH": plan.param("match")}
    proc = subprocess.run(_serve_cmd("resp_killed.jsonl"), env=kill_env,
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the serve loop to die by SIGKILL at "
            f"{plan.param('match')}, got rc={proc.returncode}\n"
            f"{proc.stderr[-2000:]}")
    with open(os.path.join(d, "resp_killed.jsonl")) as fh:
        survivors = [ln for ln in fh.read().splitlines() if ln]
    # killed at the END of batch 1's drain: batch 0's 8 responses were
    # emitted and flushed, batch 1's were computed but never written
    if len(survivors) != 8:
        raise AssertionError(f"{plan.name}: expected batch 0's 8 durable "
                             f"responses before the kill, found "
                             f"{len(survivors)}")
    proc2 = subprocess.run(_serve_cmd("resp_clean.jsonl"), env=env,
                           capture_output=True, text=True, timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: clean replay failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    with open(os.path.join(d, "resp_clean.jsonl")) as fh:
        clean = [ln for ln in fh.read().splitlines() if ln]
    if len(clean) != 24:
        raise AssertionError(f"{plan.name}: clean replay answered "
                             f"{len(clean)}/24 requests")
    if survivors != clean[:len(survivors)]:
        raise AssertionError(f"{plan.name}: pre-kill responses diverge from "
                             "the clean replay's prefix — the query loop is "
                             "not deterministic across restarts")
    return {"killed_at": plan.param("match"),
            "durable_responses": len(survivors)}


def run_query_poison(plan, base: Baseline, root: str) -> dict:
    """query-poison-slab: malformed requests dead-letter with the right
    reason bits and never reach the device; the healthy requests' answers
    are byte-for-byte the all-clean run's."""
    import io

    from mfm_tpu.serve import QueryServer, ServePolicy

    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    engine = _query_engine(path)
    k = engine.K
    clean = _query_requests(plan.seed, 18, k)
    poison = [
        ('{"id": "p-json", "weights": [0.1,', None, "schema"),
        (json.dumps({"id": "p-missing"}), "p-missing", "schema"),
        (json.dumps({"id": "p-nan", "weights": [float("nan")] * k}),
         "p-nan", "nan_weight"),
        (json.dumps({"id": "p-short", "weights": [0.5]}),
         "p-short", "short_weights"),
        (json.dumps({"id": "p-dtype", "weights": ["x"] * k}),
         "p-dtype", "dtype"),
        (json.dumps({"id": "p-bench", "weights": [0.1] * k,
                     "benchmark": "nope"}), "p-bench", "unknown_benchmark"),
    ]
    if len(poison) != int(plan.param("n_poison", len(poison))):
        raise AssertionError(f"{plan.name}: plan expects "
                             f"{plan.param('n_poison')} poisoned requests, "
                             f"harness built {len(poison)}")
    # interleave one poisoned request every 3 clean ones — the dead-letter
    # path must not disturb the batching of the requests around it
    lines = []
    for i, ln in enumerate(clean):
        if i % 3 == 0 and i // 3 < len(poison):
            lines.append(poison[i // 3][0])
        lines.append(ln)
    policy = ServePolicy(batch_max=8, default_deadline_s=600.0)
    dl = os.path.join(d, "dead_letter.jsonl")
    buf = io.StringIO()
    QueryServer(engine, policy, health="ok",
                dead_letter_path=dl).run(iter(lines), buf, gulp=True)
    resps = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    with open(dl) as fh:
        records = [json.loads(ln) for ln in fh.read().splitlines()]
    got = sorted(((r["id"], tuple(r["reasons"])) for r in records), key=str)
    want = sorted(((rid, (reason,)) for _, rid, reason in poison), key=str)
    if got != want:
        raise AssertionError(f"{plan.name}: dead-letter records {got} != "
                             f"expected {want}")
    ok = {r["id"]: r for r in resps if r["outcome"] == "ok"}
    if set(ok) != {f"q{i}" for i in range(len(clean))}:
        raise AssertionError(f"{plan.name}: healthy requests not all "
                             f"answered ok: {sorted(ok)}")
    # reference: a run that never saw the poison — identical batches, so
    # identical bytes per healthy id
    buf2 = io.StringIO()
    QueryServer(_query_engine(path), policy,
                health="ok").run(iter(clean), buf2, gulp=True)
    ref = {r["id"]: r for r in
           (json.loads(ln) for ln in buf2.getvalue().splitlines())}
    for rid, resp in ok.items():
        if resp != ref[rid]:
            raise AssertionError(f"{plan.name}: healthy response {rid} "
                                 "diverged from the poison-free run")
    return {"dead_lettered": len(records), "healthy_ok": len(ok)}


def run_query_overflow(plan, base: Baseline, root: str) -> dict:
    """query-overflow-storm: a storm past the admission bound sheds
    EXACTLY the oldest requests, in order, and the survivors' answers are
    bitwise the engine's own."""
    import io

    from mfm_tpu.obs.instrument import serve_summary_from_registry
    from mfm_tpu.serve import QueryServer, ServePolicy

    queue_max = int(plan.param("queue_max", 8))
    storm = int(plan.param("storm", 24))
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    engine = _query_engine(path)
    lines = _query_requests(plan.seed, storm, engine.K)
    policy = ServePolicy(queue_max=queue_max, batch_max=queue_max,
                         default_deadline_s=600.0)
    before = serve_summary_from_registry()
    buf = io.StringIO()
    summary = QueryServer(engine, policy,
                          health="ok").run(iter(lines), buf, gulp=True)
    resps = [json.loads(ln) for ln in buf.getvalue().splitlines()]
    shed = [r["id"] for r in resps if r["outcome"] == "shed"]
    n_shed = storm - queue_max
    if shed != [f"q{i}" for i in range(n_shed)]:
        raise AssertionError(f"{plan.name}: shed set/order {shed} is not "
                             f"oldest-first q0..q{n_shed - 1}")
    ok = {r["id"]: r for r in resps if r["outcome"] == "ok"}
    if set(ok) != {f"q{i}" for i in range(n_shed, storm)}:
        raise AssertionError(f"{plan.name}: survivors {sorted(ok)} are not "
                             f"the newest {queue_max} requests")
    # in-process registry is cumulative across plans: assert the DELTA
    if summary["shed_total"] - before["shed_total"] != n_shed:
        raise AssertionError(f"{plan.name}: shed_total counted "
                             f"{summary['shed_total'] - before['shed_total']}"
                             f", expected {n_shed}")
    ref = _query_engine(path)
    W = np.array([json.loads(lines[i])["weights"]
                  for i in range(n_shed, storm)], ref.dtype)
    res = ref.query(W)
    for j, i in enumerate(range(n_shed, storm)):
        r = ok[f"q{i}"]
        if (r["total_vol"] != float(res.total_vol[j])
                or r["contribution"] != np.asarray(
                    res.contribution[j]).tolist()):
            raise AssertionError(f"{plan.name}: survivor q{i} diverged from "
                                 "the engine's own answer")
    return {"shed": n_shed, "served": queue_max}


def run_query_swap(plan, base: Baseline, root: str) -> dict:
    """query-ckpt-swap: hot-swap the engine under load — each batch must
    answer bitwise from its OWN checkpoint generation; a swap to a corrupt
    checkpoint force-opens the breaker (fence_audit) and the queued work is
    rejected with a retry-after, never computed on the bad state."""
    import io

    from mfm_tpu.data.artifacts import ArtifactCorruptError, load_risk_state
    from mfm_tpu.serve import QueryServer, ServePolicy
    from mfm_tpu.utils.chaos import corrupt_file

    d = _fresh_workdir(root, plan.name, base.snaps[0])           # gen A
    d2 = _fresh_workdir(root, plan.name + "-next", base.snaps[1])  # gen B
    path_a = os.path.join(d, "state.npz")
    path_b = os.path.join(d2, "state.npz")
    engine_a = _query_engine(path_a)
    engine_b = _query_engine(path_b)
    # gen B again, corrupted: the swap that must NOT be served
    d3 = _fresh_workdir(root, plan.name + "-corrupt", base.snaps[1])
    path_c = os.path.join(d3, "state.npz")
    corrupt_file(path_c, int(plan.param("corrupt_bytes", 8)), plan.seed)
    try:
        load_risk_state(path_c)
    except ArtifactCorruptError as err:
        fence_err = err
    else:
        raise AssertionError(f"{plan.name}: corrupted swap target loaded "
                             "clean")

    steps = [None, {"engine": engine_b, "health": "ok"}, fence_err]

    def reload_fn():
        step = steps.pop(0) if steps else None
        if isinstance(step, Exception):
            raise step
        return step

    lines = _query_requests(plan.seed, 24, engine_a.K)
    policy = ServePolicy(batch_max=8, default_deadline_s=600.0)
    buf = io.StringIO()
    server = QueryServer(engine_a, policy, health="ok", reload_fn=reload_fn)
    server.run(iter(lines), buf, gulp=True)
    byid = {r["id"]: r for r in
            (json.loads(ln) for ln in buf.getvalue().splitlines())}
    W = np.array([json.loads(ln)["weights"] for ln in lines], np.float64)
    ref_a = _query_engine(path_a).query(W[:8].astype(engine_a.dtype))
    ref_b = _query_engine(path_b).query(W[8:16].astype(engine_b.dtype))
    # the reference must be discriminating: gen B answers these weights
    # differently than gen A would, so a silently-failed swap cannot pass
    decoy = _query_engine(path_a).query(W[8:16].astype(engine_a.dtype))
    if np.array_equal(np.asarray(ref_b.total_vol),
                      np.asarray(decoy.total_vol)):
        raise AssertionError(f"{plan.name}: generations A and B answer "
                             "identically — the swap check proves nothing")
    for start, ref, eng in ((0, ref_a, engine_a), (8, ref_b, engine_b)):
        for j in range(8):
            r = byid[f"q{start + j}"]
            if r["outcome"] != "ok":
                raise AssertionError(f"{plan.name}: q{start + j} answered "
                                     f"{r['outcome']}, expected ok")
            if (r["total_vol"] != float(ref.total_vol[j])
                    or r["staleness"] != int(eng.staleness)):
                raise AssertionError(
                    f"{plan.name}: q{start + j} not served bitwise from its "
                    "own checkpoint generation")
    for i in range(16, 24):
        r = byid[f"q{i}"]
        if r["outcome"] != "rejected" or r.get("breaker") != "fence_audit" \
                or not r.get("retry_after_s", 0) > 0:
            raise AssertionError(f"{plan.name}: q{i} after the corrupt swap "
                                 f"got {r}, expected a fence_audit rejection "
                                 "with retry-after")
    if server.breaker.state != "open" \
            or server.breaker.open_reason != "fence_audit":
        raise AssertionError(f"{plan.name}: breaker ended "
                             f"{server.breaker.state}/"
                             f"{server.breaker.open_reason}, expected "
                             "open/fence_audit")
    return {"swapped_at_batch": 1, "breaker": "fence_audit", "rejected": 8}


def run_query_steady(plan, base: Baseline, root: str) -> dict:
    """query-steady-state: after one warmup round per bucket, an arbitrary
    number of same-bucket query batches — telemetry recording on every
    drain — compiles at most once more (the per-bucket <=1-compile
    contract of serve/query.py)."""
    from mfm_tpu.serve import QueryServer, ServePolicy, bucket_for
    from mfm_tpu.utils.contracts import assert_max_compiles

    d = _fresh_workdir(root, plan.name, base.snaps[0])
    engine = _query_engine(os.path.join(d, "state.npz"))
    rounds = int(plan.param("rounds", 6))
    sizes = (5, 20)     # buckets 8 and 32 on the default ladder
    policy = ServePolicy(batch_max=64, default_deadline_s=600.0)
    server = QueryServer(engine, policy, health="ok")

    def run_round(r):
        for s in sizes:
            for ln in _query_requests(plan.seed + 31 * r + s, s, engine.K):
                server.submit_line(ln)
            out = server.drain()
            if len(out) != s or any(x["outcome"] != "ok" for x in out):
                raise AssertionError(
                    f"{plan.name}: round {r} size {s} answered "
                    f"{[x['outcome'] for x in out]}")

    run_round(0)   # warmup: compiles each bucket once
    with assert_max_compiles(1, "steady-state query loop") as c:
        for r in range(1, rounds):
            run_round(r)
    return {"rounds": rounds,
            "buckets": [bucket_for(s) for s in sizes],
            "steady_compiles": c.count}


# -- scenario-engine plans ---------------------------------------------------

def _manifest_modulo_summary(path: str) -> str:
    """Canonical JSON of a scenario manifest with its ONE volatile block
    (the obs latency summary) removed — the bitwise-replay comparison key."""
    with open(path, encoding="utf-8") as fh:
        m = json.load(fh)
    m.pop("summary", None)
    return json.dumps(m, sort_keys=True)


def run_scenario_kill(plan, base: Baseline, root: str) -> dict:
    """scenario-kill-mid-batch: SIGKILL a real `mfm-tpu scenario run`
    subprocess between the manifest's tmp write and its rename.  No torn
    ``scenario_manifest.json`` may exist, the clean re-run must write one
    ``doctor --scenarios`` accepts, and two clean runs must be byte-equal
    modulo the volatile obs summary block (the bitwise-replay contract)."""
    from mfm_tpu.scenario.manifest import (
        read_scenario_manifest, scenario_manifest_path_for,
    )

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _cmd(out_dir):
        return [sys.executable, "-m", "mfm_tpu.cli", "scenario", "run", path,
                "--preset", "crash-2015-analog", "--preset", "corr-meltup",
                "--preset", "covid-2020-analog", "--out", out_dir]

    proc = subprocess.run(_cmd(d), env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the scenario run to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    mpath = scenario_manifest_path_for(d)
    if os.path.exists(mpath):
        raise AssertionError(f"{plan.name}: a scenario manifest exists "
                             "despite the kill before its rename — the "
                             "write is not tmp-then-rename atomic")
    # clean re-run: manifest lands, doctor accepts it
    proc2 = subprocess.run(_cmd(d), env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash scenario run failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    man = read_scenario_manifest(mpath)   # raises on a torn manifest
    if man["n_ok"] != 3 or man["n_rejected"] != 0:
        raise AssertionError(f"{plan.name}: recovered run answered "
                             f"n_ok={man['n_ok']}, expected 3")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--scenarios"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --scenarios rejects the "
                             f"post-crash manifest\n{doc.stdout[-2000:]}")
    # bitwise replay: a second clean run produces the same manifest modulo
    # the volatile obs summary
    d2 = os.path.join(root, plan.name + "-replay")
    os.makedirs(d2)
    proc3 = subprocess.run(_cmd(d2), env=env, capture_output=True, text=True,
                           timeout=600)
    if proc3.returncode != 0:
        raise AssertionError(f"{plan.name}: replay run failed "
                             f"rc={proc3.returncode}\n{proc3.stderr[-2000:]}")
    if _manifest_modulo_summary(mpath) != _manifest_modulo_summary(
            scenario_manifest_path_for(d2)):
        raise AssertionError(f"{plan.name}: two clean scenario runs diverge "
                             "(modulo the obs summary) — the batch is not "
                             "bitwise-replayable")
    return {"killed_at": point, "manifest_after_crash": "absent",
            "recovered_n_ok": man["n_ok"]}


def run_sweep_kill(plan, base: Baseline, root: str) -> dict:
    """sweep-kill-mid-stream: SIGKILL a real `mfm-tpu scenario sweep`
    subprocess between the sweep manifest's tmp write and its rename.
    No torn ``sweep_manifest.json`` may exist, the checkpoint's bytes
    must be untouched by the crash, the clean seeded re-run must write a
    manifest ``doctor --scenarios`` accepts, and two clean runs must be
    byte-equal modulo the volatile obs summary block (the seeded-replay
    contract of the streaming sweep)."""
    from mfm_tpu.scenario.sweep import (
        read_sweep_manifest, sweep_manifest_path_for,
    )

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    ckpt_before = hashlib.sha256(open(path, "rb").read()).hexdigest()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _cmd(out_dir):
        # small bounded sweep, refinement off: the plan probes the write
        # protocol, not the throughput
        return [sys.executable, "-m", "mfm_tpu.cli", "scenario", "sweep",
                path, "--n", "512", "--chunk", "128", "--seed", "11",
                "--top-k", "4", "--no-refine", "--out", out_dir]

    proc = subprocess.run(_cmd(d), env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the sweep to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    mpath = sweep_manifest_path_for(d)
    if os.path.exists(mpath):
        raise AssertionError(f"{plan.name}: a sweep manifest exists "
                             "despite the kill before its rename — the "
                             "write is not tmp-then-rename atomic")
    ckpt_after = hashlib.sha256(open(path, "rb").read()).hexdigest()
    if ckpt_after != ckpt_before:
        raise AssertionError(f"{plan.name}: the crashed sweep mutated the "
                             "checkpoint — sweeps must be read-only "
                             "against the fenced store")
    # clean re-run: manifest lands, doctor accepts it
    proc2 = subprocess.run(_cmd(d), env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash sweep failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    man = read_sweep_manifest(mpath)      # raises on a torn manifest
    counts = man["sweep"]["counts"]
    if counts["n_ok"] < 512:
        raise AssertionError(f"{plan.name}: recovered sweep answered "
                             f"n_ok={counts['n_ok']}, expected >= 512")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--scenarios"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --scenarios rejects the "
                             f"post-crash sweep manifest\n{doc.stdout[-2000:]}")
    # seeded replay: a second clean run produces the same manifest modulo
    # the volatile obs summary
    d2 = os.path.join(root, plan.name + "-replay")
    os.makedirs(d2)
    proc3 = subprocess.run(_cmd(d2), env=env, capture_output=True, text=True,
                           timeout=600)
    if proc3.returncode != 0:
        raise AssertionError(f"{plan.name}: replay sweep failed "
                             f"rc={proc3.returncode}\n{proc3.stderr[-2000:]}")
    if _manifest_modulo_summary(mpath) != _manifest_modulo_summary(
            sweep_manifest_path_for(d2)):
        raise AssertionError(f"{plan.name}: two clean seeded sweeps "
                             "diverge (modulo the obs summary) — the "
                             "stream is not seeded-replayable")
    return {"killed_at": point, "manifest_after_crash": "absent",
            "checkpoint": "bytes untouched",
            "recovered_n_ok": int(counts["n_ok"])}


def run_scenario_poison(plan, base: Baseline, root: str) -> dict:
    """scenario-poison-spec: poisoned specs (NaN shock, corr stress past
    -1, negative vol regime) are rejected per-lane with reported problems
    while their healthy batchmates' covariances stay byte-equal to a run
    that never saw the poison — the lane-isolation contract of the
    batched kernel."""
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.scenario import ScenarioBuilder, ScenarioEngine, preset

    d = _fresh_workdir(root, plan.name, base.snaps[0])
    state, meta = load_risk_state(os.path.join(d, "state.npz"))
    engine = ScenarioEngine.from_risk_state(state, meta)
    f0 = engine.factor_names[0]
    healthy = [preset("crash-2015-analog"), preset("corr-meltup"),
               ScenarioBuilder("shock-one").shock(f0, add=1e-3).build(),
               ScenarioBuilder("identity").build()]
    poison = [
        ScenarioBuilder("p-nan").shock(f0, add=float("nan")).build(),
        ScenarioBuilder("p-corr").correlation(-1.5).build(),
        ScenarioBuilder("p-vol").vol_regime(-1.0).build(),
    ]
    if len(poison) != int(plan.param("n_poison", len(poison))):
        raise AssertionError(f"{plan.name}: plan expects "
                             f"{plan.param('n_poison')} poisoned specs, "
                             f"harness built {len(poison)}")
    # interleave the poison through the batch: lane isolation must not
    # depend on where the bad lanes sit
    mixed = [poison[0], healthy[0], healthy[1], poison[1], healthy[2],
             poison[2], healthy[3]]
    results = {r.spec.name: r for r in engine.run(mixed)}
    for p in poison:
        r = results[p.name]
        if r.status != "rejected" or not r.problems:
            raise AssertionError(f"{plan.name}: poisoned spec {p.name} was "
                                 f"{r.status} with problems {r.problems}, "
                                 "expected a reported rejection")
        if r.cov is not None:
            raise AssertionError(f"{plan.name}: rejected spec {p.name} "
                                 "still produced a covariance")
    # reference: a fresh engine that never saw the poison
    ref_engine = ScenarioEngine.from_risk_state(*load_risk_state(
        os.path.join(d, "state.npz")))
    ref = {r.spec.name: r for r in ref_engine.run(healthy)}
    for h in healthy:
        got, want = results[h.name], ref[h.name]
        if not got.ok or not want.ok:
            raise AssertionError(f"{plan.name}: healthy spec {h.name} "
                                 f"answered {got.status}/{want.status}")
        if got.cov.tobytes() != want.cov.tobytes():
            raise AssertionError(f"{plan.name}: healthy spec {h.name}'s "
                                 "covariance diverged from the poison-free "
                                 "run — lanes are not isolated")
    return {"rejected": [p.name for p in poison],
            "healthy_bitwise": [h.name for h in healthy]}


def run_grad_kill(plan, base: Baseline, root: str) -> dict:
    """grad-kill-mid-solve: SIGKILL a real `mfm-tpu grad sensitivity`
    subprocess between the grad report's tmp write and its rename.  No
    torn ``grad_report.json`` may exist, the checkpoint's bytes must be
    untouched (the grad path only READS the state), and a clean re-run
    must write a report ``read_grad_report`` accepts plus a manifest
    ``doctor --scenarios`` is green on."""
    from mfm_tpu.grad.report import grad_report_path_for, read_grad_report

    point = plan.param("point")
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    with open(path, "rb") as fh:
        state_bytes = fh.read()

    cmd = [sys.executable, "-m", "mfm_tpu.cli", "grad", "sensitivity", path,
           "--preset", "covid-2020-analog", "--out", d]
    proc = subprocess.run(cmd, env={**env, "MFM_CHAOS_KILL": point},
                          capture_output=True, text=True, timeout=600)
    if proc.returncode != -signal.SIGKILL:
        raise AssertionError(
            f"{plan.name}: expected the grad run to die by SIGKILL at "
            f"{point}, got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    rpath = grad_report_path_for(d)
    if os.path.exists(rpath):
        raise AssertionError(f"{plan.name}: a grad report exists despite "
                             "the kill before its rename — the write is "
                             "not tmp-then-rename atomic")
    with open(path, "rb") as fh:
        if fh.read() != state_bytes:
            raise AssertionError(f"{plan.name}: the checkpoint's bytes "
                                 "changed under a read-only grad run")
    # clean re-run: report lands and parses, manifest is doctor-green
    proc2 = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: post-crash grad run failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    rep = read_grad_report(rpath)   # raises on a torn report
    if rep["grad_kind"] != "sensitivity" or rep["n_entries"] != 1:
        raise AssertionError(f"{plan.name}: recovered report answered "
                             f"kind={rep['grad_kind']} "
                             f"n_entries={rep['n_entries']}, expected one "
                             "sensitivity entry")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--scenarios"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --scenarios rejects the "
                             f"post-crash directory\n{doc.stdout[-2000:]}")
    return {"killed_at": point, "report_after_crash": "absent",
            "recovered_entries": rep["n_entries"]}


def run_fleet_kill(plan, base: Baseline, root: str) -> dict:
    """fleet-kill-replica: SIGKILL one of three worker replicas mid-drain
    (after it computed a batch, before its envelopes hit the pipe).  The
    survivors must keep answering — the front end re-dispatches the dead
    replica's in-flight batch, every request id gets a response bitwise
    equal to the single-process replay, the merged fleet manifest counts
    the loss while its delivery audit still balances, the checkpoint's
    bytes are untouched, and ``doctor --serve`` stays green."""
    n_replicas = int(plan.param("replicas", 3))
    victim = int(plan.param("replica", 1))
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    k = _query_engine(path).K
    req = os.path.join(d, "req.jsonl")
    # 96 requests / batch-max 8 = 12 batches.  The EWMA router hands the
    # first cycle to each fresh replica in index order, then routes by
    # batch wall with a starve_rounds=4 starvation guard — so the victim
    # (replica 1) is GUARANTEED its second batch (the MATCH=batch1 kill
    # point) by dispatch ~6 at the latest, with batches still queued
    # behind it for the survivors to absorb
    with open(req, "w") as fh:
        fh.write("\n".join(_query_requests(plan.seed, 96, k)) + "\n")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    with open(path, "rb") as fh:
        state_bytes = fh.read()

    fleet_cmd = [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
                 "--input", req, "--output", os.path.join(d, "resp_fleet.jsonl"),
                 "--replicas", str(n_replicas), "--batch-max", "8",
                 "--deadline-s", "600"]
    kill_env = {**env, "MFM_CHAOS_KILL": plan.param("point"),
                "MFM_CHAOS_KILL_MATCH": plan.param("match"),
                "MFM_CHAOS_KILL_REPLICA": str(victim)}
    proc = subprocess.run(fleet_cmd, env=kill_env, capture_output=True,
                          text=True, timeout=600)
    if proc.returncode != 0:
        raise AssertionError(
            f"{plan.name}: the front end must survive a replica's death, "
            f"got rc={proc.returncode}\n{proc.stderr[-2000:]}")
    with open(path, "rb") as fh:
        if fh.read() != state_bytes:
            raise AssertionError(f"{plan.name}: the checkpoint's bytes "
                                 "changed under a read-only serving fleet")
    shard = os.path.join(d, f"serve_manifest.r{victim}.json")
    if os.path.exists(shard):
        raise AssertionError(f"{plan.name}: the SIGKILLed replica left a "
                             "manifest shard — it was not killed mid-drain")
    fman = json.load(open(os.path.join(d, "fleet_manifest.json")))
    fleet = fman["fleet"]
    lost = [r["replica"] for r in fleet["replicas"] if r["lost"]]
    if lost != [victim]:
        raise AssertionError(f"{plan.name}: merged manifest counts lost "
                             f"replicas {lost}, expected [{victim}]")
    if not fleet["audit"]["consistent"]:
        raise AssertionError(
            f"{plan.name}: delivery audit broken — delivered "
            f"{fleet['audit']['delivered_total']} (replicas "
            f"{fleet['audit']['replica_outcomes_sum']} + frontend-local "
            f"{fleet['audit']['frontend_local_total']}) of "
            f"{fleet['audit']['accepted_total']} accepted requests (the "
            "re-dispatch dropped the dead replica's batch)")
    # the chaos point fires AFTER the victim computed its batch but
    # BEFORE the envelopes hit the pipe — so exactly that in-flight
    # batch (8 requests) MUST show up in the transport redispatch
    # counters the manifest totals for the doctor audit
    if fleet["transport"]["redispatches"] < 8:
        raise AssertionError(
            f"{plan.name}: transport counters show "
            f"{fleet['transport']['redispatches']} redispatched requests, "
            "expected the victim's full in-flight batch (8)")

    # single-process replay: the fleet's answers must be its prefix-free
    # equal — same ids, same floats, same order
    clean_cmd = [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
                 "--input", req, "--output", os.path.join(d, "resp_clean.jsonl"),
                 "--batch-max", "8", "--deadline-s", "600", "--gulp"]
    proc2 = subprocess.run(clean_cmd, env=env, capture_output=True,
                           text=True, timeout=600)
    if proc2.returncode != 0:
        raise AssertionError(f"{plan.name}: single-process replay failed "
                             f"rc={proc2.returncode}\n{proc2.stderr[-2000:]}")
    with open(os.path.join(d, "resp_fleet.jsonl")) as fh:
        fleet_resp = [ln for ln in fh.read().splitlines() if ln]
    with open(os.path.join(d, "resp_clean.jsonl")) as fh:
        clean_resp = [ln for ln in fh.read().splitlines() if ln]
    if len(fleet_resp) != 96:
        raise AssertionError(f"{plan.name}: fleet answered "
                             f"{len(fleet_resp)}/96 requests")
    if fleet_resp != clean_resp:
        diverge = sum(1 for a, b in zip(fleet_resp, clean_resp) if a != b)
        raise AssertionError(
            f"{plan.name}: {diverge} fleet responses diverge from the "
            "single-process replay — re-dispatch is not deterministic")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--serve"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --serve rejects the "
                             f"post-kill directory\n{doc.stdout[-2000:]}")
    return {"killed_replica": victim, "killed_at": plan.param("match"),
            "survivors": n_replicas - 1, "responses": len(fleet_resp),
            "replay": "bitwise", "doctor": "green"}


def _worker_pids(fe_pid: int, n: int, deadline_s: float = 240.0) -> dict:
    """``{worker_id: pid}`` of a live frontend's spawned worker children,
    read off /proc (the drill signals them directly, bypassing the
    frontend — that is the point: the frontend must DISCOVER the faults)."""
    pids: dict = {}
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s and len(pids) < n:
        try:
            with open(f"/proc/{fe_pid}/task/{fe_pid}/children") as fh:
                kids = fh.read().split()
        except OSError:
            kids = []
        for cpid in kids:
            try:
                with open(f"/proc/{cpid}/cmdline", "rb") as fh:
                    argv = fh.read().split(b"\0")
            except OSError:
                continue
            if b"--worker-id" in argv:
                wid = int(argv[argv.index(b"--worker-id") + 1])
                pids[wid] = int(cpid)
        if len(pids) < n:
            time.sleep(0.2)
    if len(pids) < n:
        raise AssertionError(f"found {len(pids)}/{n} worker children of "
                             f"frontend pid {fe_pid}")
    return pids


def _drive_fleet_storm(plan, d: str, path: str, lines: list, env: dict,
                       n_replicas: int, mid_storm) -> list:
    """Feed ``lines`` to a live ``serve --replicas N`` frontend over its
    stdin in two halves, calling ``mid_storm(worker_pids)`` between them
    (after the first half's responses are durable, so no batch is in
    flight when the signals land).  Returns the response lines; the
    frontend must exit 0 whatever ``mid_storm`` did to its workers."""
    out = os.path.join(d, "resp_fleet.jsonl")
    cmd = [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
           "--output", out, "--replicas", str(n_replicas),
           "--batch-max", "8", "--deadline-s", "600",
           # the SIGSTOP lands while every worker is idle (first half
           # durable), so the HEARTBEAT is what must detect it: idle
           # workers are pinged after 0.5 s and quarantined 1 s later.
           # The per-I/O deadline stays generous — a worker's first
           # batch pays its jit compile in silence, and a 2 s budget
           # falsely wedges it before the storm even starts
           "--worker-timeout-s", "60", "--heartbeat-s", "0.5",
           "--heartbeat-timeout-s", "1"]
    proc = subprocess.Popen(cmd, env=env, stdin=subprocess.PIPE,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.PIPE, text=True)
    try:
        pids = _worker_pids(proc.pid, n_replicas)
        half = len(lines) // 2
        proc.stdin.write("\n".join(lines[:half]) + "\n")
        proc.stdin.flush()
        t0 = time.monotonic()
        while time.monotonic() - t0 < 240.0:
            try:
                with open(out, encoding="utf-8") as fh:
                    if sum(1 for ln in fh if ln.strip()) >= half:
                        break
            except OSError:
                pass
            if proc.poll() is not None:
                raise AssertionError(
                    f"{plan.name}: frontend died during the first half "
                    f"(rc={proc.returncode})\n{proc.stderr.read()[-2000:]}")
            time.sleep(0.2)
        else:
            raise AssertionError(f"{plan.name}: first {half} responses "
                                 "never became durable")
        mid_storm(pids)
        # idle past --heartbeat-s before releasing the second half: every
        # worker's last I/O goes stale, so the router PINGS each pick
        # before trusting it — the SIGSTOPped worker misses its pong
        # within --heartbeat-timeout-s instead of burning the full
        # --worker-timeout-s batch deadline.  This is the detection
        # bound the drill certifies: heartbeat interval + timeout.
        time.sleep(1.0)
        proc.stdin.write("\n".join(lines[half:]) + "\n")
        proc.stdin.close()
        rc = proc.wait(timeout=600)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        # a SIGSTOPped worker never dies with its parent — resume-by-kill
        # any stragglers so the scratch tree can be reaped
        for pid in list(locals().get("pids", {}).values()):
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
    if rc != 0:
        raise AssertionError(
            f"{plan.name}: the frontend must survive the storm, got "
            f"rc={rc}\n{proc.stderr.read()[-2000:]}")
    with open(out, encoding="utf-8") as fh:
        return [ln for ln in fh.read().splitlines() if ln]


def _fleet_clean_by_id(plan, d: str, path: str, req_lines: list,
                       env: dict) -> dict:
    """id -> response line of the fault-free single-process replay."""
    req = os.path.join(d, "req.jsonl")
    with open(req, "w") as fh:
        fh.write("\n".join(req_lines) + "\n")
    clean_cmd = [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
                 "--input", req, "--output", os.path.join(d, "resp_clean.jsonl"),
                 "--batch-max", "8", "--deadline-s", "600", "--gulp"]
    proc = subprocess.run(clean_cmd, env=env, capture_output=True,
                          text=True, timeout=600)
    if proc.returncode != 0:
        raise AssertionError(f"{plan.name}: fault-free replay failed "
                             f"rc={proc.returncode}\n{proc.stderr[-2000:]}")
    with open(os.path.join(d, "resp_clean.jsonl")) as fh:
        return {json.loads(ln)["id"]: ln
                for ln in fh.read().splitlines() if ln}


def _assert_fleet_bitwise_by_id(plan, fleet_resp: list, clean: dict,
                                n: int) -> None:
    """Live feeding makes batch boundaries timing-dependent, so the
    survivors' answers are compared BY REQUEST ID, not by line order —
    the per-id bytes are still the single-process replay's."""
    got = {json.loads(ln)["id"]: ln for ln in fleet_resp}
    if len(got) != n:
        raise AssertionError(f"{plan.name}: fleet answered {len(got)}/{n} "
                             "request ids")
    diverged = [rid for rid, ln in got.items() if clean.get(rid) != ln]
    if diverged:
        raise AssertionError(
            f"{plan.name}: {len(diverged)} responses diverge from the "
            f"fault-free replay (first: {sorted(diverged)[0]}) — "
            "re-dispatch after the storm is not deterministic")


def run_fleet_kill_host(plan, base: Baseline, root: str) -> dict:
    """fleet-kill-host: the multi-host headline drill.  2 simulated hosts
    x 2 workers; mid-storm both of host 1's workers die by SIGKILL while
    worker ``wedge`` (on host 0) is SIGSTOPped — wedged, not dead.  The
    surviving worker must answer everything (bitwise-by-id the fault-free
    replay's), the manifest must count 2 lost + 1 wedged with a balanced
    audit and the redispatches in its transport block, the checkpoint's
    bytes stay untouched, and ``doctor --serve`` stays green."""
    hosts = int(plan.param("hosts", 2))
    wph = int(plan.param("workers_per_host", 2))
    kill_host = int(plan.param("kill_host", 1))
    wedge = int(plan.param("wedge", 1))
    n = int(plan.param("n", 64))
    n_replicas = hosts * wph
    victims = [j for j in range(n_replicas) if j // wph == kill_host]
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    k = _query_engine(path).K
    lines = _query_requests(plan.seed, n, k)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}
    with open(path, "rb") as fh:
        state_bytes = fh.read()

    def mid_storm(pids):
        os.kill(pids[wedge], signal.SIGSTOP)
        for j in victims:
            os.kill(pids[j], signal.SIGKILL)

    fleet_resp = _drive_fleet_storm(plan, d, path, lines, env,
                                    n_replicas, mid_storm)
    with open(path, "rb") as fh:
        if fh.read() != state_bytes:
            raise AssertionError(f"{plan.name}: the checkpoint's bytes "
                                 "changed under a read-only serving fleet")
    clean = _fleet_clean_by_id(plan, d, path, lines, env)
    _assert_fleet_bitwise_by_id(plan, fleet_resp, clean, n)

    fman = json.load(open(os.path.join(d, "fleet_manifest.json")))
    fleet = fman["fleet"]
    lost = sorted(r["replica"] for r in fleet["replicas"] if r["lost"])
    wedged = sorted(r["replica"] for r in fleet["replicas"] if r["wedged"])
    if not set(victims) <= set(lost):
        raise AssertionError(f"{plan.name}: manifest counts lost {lost}, "
                             f"expected at least {victims}")
    if wedged != [wedge]:
        raise AssertionError(f"{plan.name}: manifest counts wedged "
                             f"{wedged}, expected [{wedge}] — the "
                             "SIGSTOPped worker was not detected as such")
    tr = fleet["transport"]
    # NOTE: whether dead workers cost a REDISPATCH (batch sent, EOF on
    # the reply) or are caught by the pre-dispatch heartbeat (no batch
    # ever sent) is a timing race this drill does not pin down —
    # fleet-kill-replica pins the guaranteed-redispatch case via its
    # in-worker chaos point.  The wedge, though, can only be discovered
    # by a bounded mechanism, and that discovery must be on the books:
    if tr["heartbeat_misses"] + tr["io_timeouts"] < 1:
        raise AssertionError(f"{plan.name}: the wedge left no heartbeat "
                             "miss or I/O timeout in the counters")
    if not fleet["audit"]["consistent"]:
        raise AssertionError(
            f"{plan.name}: delivery audit broken — delivered "
            f"{fleet['audit']['delivered_total']} of "
            f"{fleet['audit']['accepted_total']} accepted")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--serve"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --serve rejects the "
                             f"post-storm directory\n{doc.stdout[-2000:]}")
    return {"killed_host": kill_host, "killed_workers": victims,
            "wedged_worker": wedge, "responses": len(fleet_resp),
            "redispatches": tr["redispatches"],
            "replay": "bitwise-by-id", "doctor": "green"}


def run_fleet_wedge(plan, base: Baseline, root: str) -> dict:
    """fleet-wedge-worker: SIGSTOP one of three workers mid-storm —
    nothing killed, nothing closed, the failure an EOF check cannot see.
    The heartbeat ping (or the per-I/O deadline on its next batch) must
    quarantine it, its batch re-dispatches like a death, every request is
    answered bitwise-by-id, and the wedge is visible in the manifest
    (wedged flag + heartbeat_misses/io_timeouts) with the audit intact."""
    n_replicas = int(plan.param("replicas", 3))
    wedge = int(plan.param("wedge", 1))
    n = int(plan.param("n", 48))
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    k = _query_engine(path).K
    lines = _query_requests(plan.seed, n, k)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def mid_storm(pids):
        os.kill(pids[wedge], signal.SIGSTOP)

    fleet_resp = _drive_fleet_storm(plan, d, path, lines, env,
                                    n_replicas, mid_storm)
    clean = _fleet_clean_by_id(plan, d, path, lines, env)
    _assert_fleet_bitwise_by_id(plan, fleet_resp, clean, n)

    fman = json.load(open(os.path.join(d, "fleet_manifest.json")))
    fleet = fman["fleet"]
    wedged = sorted(r["replica"] for r in fleet["replicas"] if r["wedged"])
    if wedged != [wedge]:
        raise AssertionError(f"{plan.name}: manifest counts wedged "
                             f"{wedged}, expected [{wedge}]")
    tr = fleet["transport"]
    # the storm driver idles past --heartbeat-s before the second half,
    # so discovery MUST come from the ping (fast path), never the 60 s
    # batch deadline — a drill that quietly fell through to the I/O
    # timeout would certify the wrong detection bound
    if tr["heartbeat_misses"] < 1:
        raise AssertionError(f"{plan.name}: the wedge was not caught by "
                             "a heartbeat miss — detection fell through "
                             "to the batch I/O deadline")
    if not fleet["audit"]["consistent"]:
        raise AssertionError(
            f"{plan.name}: delivery audit broken — delivered "
            f"{fleet['audit']['delivered_total']} of "
            f"{fleet['audit']['accepted_total']} accepted")
    doc = subprocess.run([sys.executable, "-m", "mfm_tpu.cli", "doctor", d,
                          "--serve"],
                         env=env, capture_output=True, text=True, timeout=600)
    if doc.returncode != 0:
        raise AssertionError(f"{plan.name}: doctor --serve rejects the "
                             f"post-wedge directory\n{doc.stdout[-2000:]}")
    return {"wedged_worker": wedge, "survivors": n_replicas - 1,
            "responses": len(fleet_resp),
            "heartbeat_misses": tr["heartbeat_misses"],
            "io_timeouts": tr["io_timeouts"],
            "replay": "bitwise-by-id", "doctor": "green"}


def run_cache_stale(plan, base: Baseline, root: str) -> dict:
    """cache-stale-generation: the response cache must never outlive its
    checkpoint generation.  Phase 1 (in-process): a pure repeat stream is
    all hits — it never drains, so the fence can only move via the
    throttled hit-path reload poll.  After a hot swap to gen B no response
    body may equal the pre-reload cached body, and the stream must re-warm
    on gen B (exactly one miss, then hits again under the new fence).
    Phase 2 (subprocess): SIGKILL a real `risk --update` after the tmp
    write (torn publish — the pointer never flipped), then a cache-ON
    ``--watch`` serve over a repeat stream must replay byte-for-byte per
    request id against a cache-OFF run: the torn tmp moves neither the
    fence nor a single float."""
    from mfm_tpu.data.artifacts import read_pointer
    from mfm_tpu.obs.manifest import read_run_manifest
    from mfm_tpu.serve import (
        Coalescer, QueryServer, ResponseCache, ServePolicy,
    )

    repeats = int(plan.param("repeats", 6))
    d = _fresh_workdir(root, plan.name, base.snaps[0])             # gen A
    d2 = _fresh_workdir(root, plan.name + "-next", base.snaps[1])  # gen B
    path_a = os.path.join(d, "state.npz")
    path_b = os.path.join(d2, "state.npz")
    engine_a = _query_engine(path_a)
    engine_b = _query_engine(path_b)
    k = engine_a.K
    w = np.round(np.random.default_rng(plan.seed).normal(0.0, 1.0, k), 6)
    ref_a = _query_engine(path_a).query(w[None].astype(engine_a.dtype))
    ref_b = _query_engine(path_b).query(w[None].astype(engine_b.dtype))
    # the reference must be discriminating: if both generations answer the
    # repeat body identically, a stale hit would be invisible
    if np.array_equal(np.asarray(ref_a.total_vol),
                      np.asarray(ref_b.total_vol)):
        raise AssertionError(f"{plan.name}: generations A and B answer "
                             "identically — the staleness check proves "
                             "nothing")

    gen_a = int((read_pointer(path_a) or {}).get("generation") or 0)
    cache = ResponseCache(64, 1 << 20, generation=gen_a)
    flips = {"armed": False, "done": False}

    def reload_fn():
        if not flips["armed"] or flips["done"]:
            return None
        flips["done"] = True
        # what the CLI's watch closure does: bump the fence BEFORE the
        # engine swap lands
        cache.set_fence(generation=gen_a + 1)
        return {"engine": engine_b, "health": "ok"}

    t = {"now": 0.0}
    server = QueryServer(engine_a,
                         ServePolicy(batch_max=8, default_deadline_s=600.0),
                         health="ok", reload_fn=reload_fn)
    co = Coalescer(server, linger_s=1.0, clock=lambda: t["now"], cache=cache)
    wlist = w.tolist()

    def ask(tag, i):
        line = json.dumps({"id": f"{tag}{i}", "weights": wlist,
                           "deadline_s": 600.0}, sort_keys=True)
        pairs = co.submit(line) + co.flush()
        if len(pairs) != 1 or pairs[0][1].get("outcome") != "ok":
            raise AssertionError(f"{plan.name}: {tag}{i} answered "
                                 f"{[p[1] for p in pairs]}, expected one ok")
        return pairs[0][1]

    def body(r):
        return json.dumps({f: v for f, v in r.items()
                           if f not in ("id", "trace_id")}, sort_keys=True)

    pre = [ask("pre", i) for i in range(repeats)]
    s0 = cache.stats()
    if (s0["misses"], s0["hits"]) != (1, repeats - 1):
        raise AssertionError(f"{plan.name}: pre-reload repeat stream was "
                             f"not 1 miss + {repeats - 1} hits: {s0}")
    for i, r in enumerate(pre):
        if r["total_vol"] != float(ref_a.total_vol[0]):
            raise AssertionError(f"{plan.name}: pre{i} not served bitwise "
                                 "from gen A")

    # arm the swap and advance the fake clock past the linger budget: the
    # FIRST post submit's throttled hit-path poll must perform the reload
    # (the stream is all-hits — nothing else ever drains)
    flips["armed"] = True
    t["now"] = 5.0
    post = [ask("post", i) for i in range(repeats)]
    if not flips["done"]:
        raise AssertionError(f"{plan.name}: the hit-path poll never ran "
                             "the reload — an all-hits stream would serve "
                             "a retired generation forever")
    stale = {body(r) for r in pre}
    for i, r in enumerate(post):
        if body(r) in stale:
            raise AssertionError(f"{plan.name}: post{i} served the "
                                 "pre-reload cached body after the "
                                 "generation fence moved")
        if r["total_vol"] != float(ref_b.total_vol[0]):
            raise AssertionError(f"{plan.name}: post{i} not served bitwise "
                                 "from gen B")
    s1 = cache.stats()
    if (s1["misses"] - s0["misses"],
            s1["hits"] - s0["hits"]) != (1, repeats - 1):
        raise AssertionError(f"{plan.name}: post-reload stream did not "
                             f"re-warm under the new fence (want 1 miss + "
                             f"{repeats - 1} hits): {s1} vs {s0}")

    # -- phase 2: torn publish under a cache-fronted --watch serve -----------
    point = plan.param("point")
    dk = _fresh_workdir(root, plan.name + "-kill", base.snaps[0])
    path = os.path.join(dk, "state.npz")
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": repo_root}

    def _update_cmd(slab_idx):
        table_csv = os.path.join(dk, f"slab{slab_idx}.csv")
        base.slabs[slab_idx].to_csv(table_csv, index=False)
        return [sys.executable, "-m", "mfm_tpu.cli", "risk",
                "--barra", table_csv, "--update", path, "--quarantine",
                "--eigen-sims", str(EIGEN_SIMS),
                "--eigen-sim-length", str(T_TOTAL),
                "--out", os.path.join(dk, "tables")]

    # a clean slab-0 update first: it leaves a healthy run manifest beside
    # the checkpoint, so the serve below stamps health=ok (an "unknown"
    # verdict marks every response degraded, hence uncacheable)
    ok_upd = subprocess.run(_update_cmd(0), env=env, capture_output=True,
                            text=True, timeout=600)
    if ok_upd.returncode != 0:
        raise AssertionError(f"{plan.name}: the healthy slab-0 update "
                             f"failed rc={ok_upd.returncode}\n"
                             f"{ok_upd.stderr[-2000:]}")
    # the tiny synthetic panel legitimately trips factor_ret_outlier_frac,
    # which would stamp every response degraded (uncacheable) and open the
    # breaker — overwrite the verdict through the real manifest API so the
    # serve below sees the healthy-shop precondition this plan is about
    from mfm_tpu.obs.manifest import write_run_manifest
    rman = read_run_manifest(dk)
    rman["health"] = {"status": "ok", "checks": {}}
    write_run_manifest(dk, rman)
    with open(path, "rb") as fh:
        state_bytes = fh.read()
    upd = subprocess.run(_update_cmd(1),
                         env={**env, "MFM_CHAOS_KILL": point},
                         capture_output=True, text=True, timeout=600)
    if upd.returncode != -signal.SIGKILL:
        raise AssertionError(f"{plan.name}: expected the update to die by "
                             f"SIGKILL at {point}, got rc={upd.returncode}\n"
                             f"{upd.stderr[-2000:]}")
    with open(path, "rb") as fh:
        if fh.read() != state_bytes:
            raise AssertionError(f"{plan.name}: the torn publish mutated "
                                 "the live checkpoint's bytes")

    rng = np.random.default_rng(plan.seed + 1)
    bodies = [np.round(rng.normal(0.0, 1.0, k), 6).tolist()
              for _ in range(4)]
    n = 4 * repeats
    lines = [json.dumps({"id": f"r{i}", "weights": bodies[i % 4],
                         "deadline_s": 600.0}, sort_keys=True)
             for i in range(n)]
    req = os.path.join(dk, "req.jsonl")
    with open(req, "w") as fh:
        fh.write("\n".join(lines) + "\n")

    def _serve(out_name, *extra):
        # no --gulp: gulp mode admits ALL lines before the first drain,
        # so nothing would ever hit — batch-max 8 over 4 distinct bodies
        # computes the first two batches' worth and hits the rest
        cmd = [sys.executable, "-m", "mfm_tpu.cli", "serve", path,
               "--input", req, "--output", os.path.join(dk, out_name),
               "--batch-max", "8", "--deadline-s", "600", "--watch",
               *extra]
        proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                              timeout=600)
        if proc.returncode != 0:
            raise AssertionError(f"{plan.name}: serve "
                                 f"{extra or ('cache on',)} failed "
                                 f"rc={proc.returncode}\n"
                                 f"{proc.stderr[-2000:]}")
        with open(os.path.join(dk, out_name)) as fh:
            return {json.loads(ln)["id"]: ln
                    for ln in fh.read().splitlines() if ln}

    on = _serve("resp_cache_on.jsonl")
    # read the cache-on manifest NOW — the cache-off replay overwrites it
    man = read_run_manifest(os.path.join(dk, "serve_manifest.json"))
    cb = (man.get("serve") or {}).get("cache") or {}
    if not cb.get("hits_total"):
        raise AssertionError(f"{plan.name}: the cache-on run recorded no "
                             "hits — the bitwise replay proves nothing")
    off = _serve("resp_cache_off.jsonl", "--no-cache")
    ids = {f"r{i}" for i in range(n)}
    if set(on) != ids or set(off) != ids:
        raise AssertionError(f"{plan.name}: answered {len(on)} cached / "
                             f"{len(off)} uncached of {n} requests")
    diverged = [i for i in sorted(ids) if on[i] != off[i]]
    if diverged:
        raise AssertionError(f"{plan.name}: {len(diverged)} responses "
                             f"diverge between the cache-on and cache-off "
                             f"runs (first: {diverged[0]}) — the torn "
                             "publish perturbed the cache-fronted replay")
    return {"reload": "fence moved via hit-path poll",
            "pre_hits": repeats - 1, "rewarm_misses": 1,
            "killed_at": point, "cache_on_hits": int(cb["hits_total"]),
            "replay": "bitwise per id", "responses": n}


# -- schedule-perturbation plans (mfmsync's runtime half) --------------------

def _resp_body(resp: dict) -> str:
    """Canonical response body with the identity keys stripped — the
    byte-identity unit every schedule drill compares on."""
    return json.dumps({f: v for f, v in resp.items()
                       if f not in ("id", "trace_id")}, sort_keys=True)


def run_sync_schedule_coalescer(plan, base: Baseline, root: str) -> dict:
    """sync-schedule-coalescer: the coalescer's bitwise contract must
    survive adversarial flush/submit interleavings.  Phase 1
    (deterministic): the coalescer's RLock/Condition are transplanted
    with DetScheduler primitives and a seed sweep explores hostile
    schedules of T submitter threads racing an explicit flusher — every
    request id must be answered exactly once, byte-equal the sequential
    loop.  Phase 2 (live): a real SocketFrontend serves the same engine
    while trafficgen's closed-loop hammer pins T client connections on
    it; each thread asserts in-order responses on its own connection
    (one in flight -> order IS the protocol) and the union replays
    bitwise per id against the sequential reference."""
    from mfm_tpu.serve import Coalescer, QueryServer, ServePolicy
    from mfm_tpu.serve.frontend import SocketFrontend
    from mfm_tpu.utils.sched import DetCondition, DetRLock, DetScheduler

    tools_dir = os.path.dirname(os.path.abspath(__file__))
    if tools_dir not in sys.path:
        sys.path.insert(0, tools_dir)
    from trafficgen import hammer

    seeds = int(plan.param("seeds", 10))
    n_threads = int(plan.param("threads", 3))
    n = int(plan.param("n", 12))
    d = _fresh_workdir(root, plan.name, base.snaps[0])
    path = os.path.join(d, "state.npz")
    k = _query_engine(path).K
    lines = _query_requests(plan.seed, n, k)

    def make_co(deliver=None):
        server = QueryServer(_query_engine(path),
                             ServePolicy(batch_max=4,
                                         default_deadline_s=600.0),
                             health="ok")
        # frozen clock + huge linger: flushes happen only at batch_max
        # and at explicit flush() calls, so a schedule fully determines
        # the batch shapes
        return Coalescer(server, linger_s=600.0, clock=lambda: 0.0,
                         deliver=deliver)

    def sequential(ls) -> dict:
        co = make_co()
        pairs = []
        for ln in ls:
            pairs += co.submit(ln)
        pairs += co.flush()
        out = {r["id"]: _resp_body(r) for _o, r in pairs}
        if len(out) != len(ls):
            raise AssertionError(f"{plan.name}: sequential reference "
                                 f"answered {len(out)}/{len(ls)}")
        return out

    ref = sequential(lines)
    for sd in range(seeds):
        s = DetScheduler(plan.seed + sd)
        co = make_co()
        co._lock = DetRLock(s, "coalesce")
        co._wake = DetCondition(s, co._lock)
        got: list = []

        def submitter(sl):
            for ln in sl:
                got.extend(co.submit(ln))

        def flusher():
            for _ in range(n):
                got.extend(co.flush())

        for i in range(n_threads):
            s.spawn(submitter, lines[i::n_threads], name=f"sub{i}")
        s.spawn(flusher, name="flusher")
        s.run()
        # final drain on the main thread with real primitives (the
        # scheduler's are only usable from spawned workers)
        co._lock = threading.RLock()
        co._wake = threading.Condition(co._lock)
        got.extend(co.flush())
        by_id: dict = {}
        for _origin, r in got:
            if r["id"] in by_id:
                raise AssertionError(f"{plan.name}: seed {sd} answered "
                                     f"{r['id']} twice")
            by_id[r["id"]] = _resp_body(r)
        if set(by_id) != set(ref):
            missing = sorted(set(ref) - set(by_id))
            raise AssertionError(f"{plan.name}: seed {sd} dropped "
                                 f"{missing[:4]}")
        diverged = [i for i in sorted(ref) if by_id[i] != ref[i]]
        if diverged:
            raise AssertionError(f"{plan.name}: seed {sd}: "
                                 f"{len(diverged)} responses diverge "
                                 f"from the sequential loop under this "
                                 f"interleaving (first: {diverged[0]})")

    # -- phase 2: live socket frontend under the closed-loop hammer ----------
    h_threads = int(plan.param("hammer_threads", 4))
    h_n = int(plan.param("hammer_n", 32))
    h_lines = _query_requests(plan.seed + 1, h_n, k)
    ref2 = sequential(h_lines)
    fe = SocketFrontend("127.0.0.1", 0)
    server = QueryServer(_query_engine(path),
                         ServePolicy(batch_max=4, default_deadline_s=600.0),
                         health="ok")
    fe.backend = Coalescer(server, linger_s=0.005, deliver=fe.deliver)
    host, port = fe.listen()
    accept_thread = fe.start()
    try:
        rep = hammer((host, port), [h_lines[i::h_threads]
                                    for i in range(h_threads)])
    finally:
        fe.stop()
        accept_thread.join(timeout=10.0)
    if set(rep["responses"]) != set(ref2):
        raise AssertionError(f"{plan.name}: hammer answered "
                             f"{len(rep['responses'])}/{len(ref2)}")
    diverged = [i for i in sorted(ref2)
                if _resp_body(json.loads(rep["responses"][i])) != ref2[i]]
    if diverged:
        raise AssertionError(f"{plan.name}: {len(diverged)} hammered "
                             f"responses diverge from the sequential "
                             f"loop (first: {diverged[0]})")
    return {"det_seeds": seeds, "det_threads": n_threads, "requests": n,
            "hammer_threads": h_threads, "hammer_requests": h_n,
            "replay": "bitwise per id (both phases)"}


def run_sync_schedule_cache(plan, base: Baseline, root: str) -> dict:
    """sync-schedule-cache: a concurrent hit/miss/reload storm on the
    response cache under deterministic schedules.  T workers race
    lookup/put over a small repeat-heavy body pool while a fencer thread
    moves the generation fence mid-storm, all serialized by a seeded
    DetScheduler through an instrumented cache lock.  Contracts: every
    hit is byte-equal the cold body OF ITS OWN GENERATION, the LRU
    bounds (entries AND resident bytes) hold at every step, per-worker
    observed generations are monotone (the fence never serves stale),
    and the post-storm stream re-warms under the new fence."""
    from mfm_tpu.serve import Coalescer, QueryServer, ResponseCache, \
        ServePolicy
    from mfm_tpu.utils.sched import DetLock, DetScheduler

    seeds = int(plan.param("seeds", 10))
    n_threads = int(plan.param("threads", 3))
    ops = int(plan.param("ops", 10))
    n_bodies = int(plan.param("bodies", 6))
    max_entries = int(plan.param("max_entries", 4))
    max_bytes = int(plan.param("max_bytes", 4096))

    d_a = _fresh_workdir(root, plan.name, base.snaps[0])       # gen 0
    d_b = _fresh_workdir(root, plan.name + "-next", base.snaps[1])
    path_a = os.path.join(d_a, "state.npz")
    path_b = os.path.join(d_b, "state.npz")
    k = _query_engine(path_a).K
    rng = np.random.default_rng(plan.seed)
    bodies = [{"weights": np.round(rng.normal(0.0, 1.0, k), 6).tolist(),
               "deadline_s": 600.0} for _ in range(n_bodies)]

    def line_for(bi: int, rid: str) -> str:
        return json.dumps({"id": rid, **bodies[bi]}, sort_keys=True)

    def cold_bodies(path: str) -> list:
        server = QueryServer(_query_engine(path),
                             ServePolicy(batch_max=8,
                                         default_deadline_s=600.0),
                             health="ok")
        co = Coalescer(server, linger_s=600.0, clock=lambda: 0.0)
        out = []
        for i in range(n_bodies):
            pairs = co.submit(line_for(i, f"ref{i}")) + co.flush()
            if len(pairs) != 1 or pairs[0][1].get("outcome") != "ok":
                raise AssertionError(f"{plan.name}: cold ref {i} not ok")
            out.append(pairs[0][1])
        return out

    ref = {0: cold_bodies(path_a), 1: cold_bodies(path_b)}
    for i in range(n_bodies):
        if _resp_body(ref[0][i]) == _resp_body(ref[1][i]):
            raise AssertionError(f"{plan.name}: generations answer body "
                                 f"{i} identically — staleness would be "
                                 "invisible")

    hit_gens: dict = {0: 0, 1: 0}
    for sd in range(seeds):
        s = DetScheduler(plan.seed + sd)
        cache = ResponseCache(max_entries, max_bytes, generation=0)
        cache._lock = DetLock(s, "cache")
        events: list = []

        def worker(w: int):
            wrng = random.Random((plan.seed, sd, w))
            last_gen = -1
            for j in range(ops):
                bi = wrng.randrange(n_bodies)
                line = line_for(bi, f"c{w}x{j}")
                resp, tok = cache.lookup(line)
                if tok is None:
                    raise AssertionError(f"{plan.name}: body {bi} "
                                         "uncacheable")
                gen = tok[1]        # the key carries its generation
                if gen < last_gen:
                    raise AssertionError(
                        f"{plan.name}: seed {sd} worker {w} went "
                        f"backwards across the fence ({last_gen} -> "
                        f"{gen}) — stale generation served")
                last_gen = gen
                if resp is None:
                    filled = dict(ref[gen][bi])
                    filled["id"] = f"c{w}x{j}"
                    cache.put(tok, filled)
                    events.append(("miss", gen))
                else:
                    if _resp_body(resp) != _resp_body(ref[gen][bi]):
                        raise AssertionError(
                            f"{plan.name}: seed {sd} worker {w}: hit on "
                            f"body {bi} is not byte-equal the gen-{gen} "
                            "cold response")
                    events.append(("hit", gen))
                if len(cache) > max_entries:
                    raise AssertionError(f"{plan.name}: entry bound "
                                         f"blown: {len(cache)}")
                if cache.resident_bytes > max_bytes:
                    raise AssertionError(f"{plan.name}: byte bound "
                                         f"blown: {cache.resident_bytes}")

        def fencer():
            # park mid-storm before fencing: the fencer has far fewer
            # scheduling points than the workers, so without the idle
            # yields it would almost always fence before the first
            # repeat hit and the gen-0 side would go untested
            for _ in range(ops * n_threads // 2):
                s.yield_point("fencer-idle")
            cache.set_fence(generation=1)
            events.append(("fence", 1))

        for w in range(n_threads):
            s.spawn(worker, w, name=f"w{w}")
        s.spawn(fencer, name="fencer")
        s.run()
        for kind, gen in events:
            if kind == "hit":
                hit_gens[gen] += 1
        # post-storm: the stream must re-warm under the new fence
        cache._lock = threading.Lock()
        r0, t0 = cache.lookup(line_for(0, "rewarm0"))
        if t0[1] != 1:
            raise AssertionError(f"{plan.name}: fence did not move")
        if r0 is not None and _resp_body(r0) != _resp_body(ref[1][0]):
            raise AssertionError(f"{plan.name}: post-storm hit served a "
                                 "stale body across the fence")
    if not hit_gens[0] or not hit_gens[1]:
        raise AssertionError(f"{plan.name}: storm produced no hits on "
                             f"one side of the fence ({hit_gens}) — the "
                             "byte-equality check proved nothing")
    return {"det_seeds": seeds, "workers": n_threads,
            "ops_per_worker": ops, "bodies": n_bodies,
            "hits_gen0": hit_gens[0], "hits_gen1": hit_gens[1],
            "bounds": f"entries<={max_entries}, bytes<={max_bytes}",
            "fence": "monotone per worker, re-warm confirmed"}


RUNNERS = {"truncate": run_byte_fault, "corrupt": run_byte_fault,
           "kill": run_kill, "kill_manifest": run_kill_manifest,
           "nan_slab": run_poison, "outlier_slab": run_poison,
           "universe_slab": run_poison, "flaky_store": run_flaky_store,
           "query_kill": run_query_kill, "query_poison": run_query_poison,
           "query_overflow": run_query_overflow, "query_swap": run_query_swap,
           "query_steady": run_query_steady,
           "scenario_kill": run_scenario_kill,
           "scenario_poison": run_scenario_poison,
           "sweep_kill": run_sweep_kill,
           "trace_kill": run_trace_kill, "eigen_kill": run_eigen_kill,
           "flightrec_kill": run_flightrec_kill,
           "shard_kill": run_shard_kill, "grad_kill": run_grad_kill,
           "fleet_kill": run_fleet_kill,
           "fleet_kill_host": run_fleet_kill_host,
           "fleet_wedge": run_fleet_wedge,
           "cache_stale": run_cache_stale,
           "sync_schedule_coalescer": run_sync_schedule_coalescer,
           "sync_schedule_cache": run_sync_schedule_cache}


def main(argv=None) -> int:
    from mfm_tpu.utils.chaos import plan_suite

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--seed", type=int, default=0,
                    help="master seed for the panel AND the fault plans")
    ap.add_argument("--plans", default=None,
                    help="comma-separated plan names (default: all, plus "
                         "the steady-state compile check)")
    ap.add_argument("--out", default=None, metavar="FILE.json",
                    help="write the full JSON report here too")
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory for post-mortems")
    args = ap.parse_args(argv)

    plans = plan_suite(args.seed)
    if args.plans:
        want = set(args.plans.split(","))
        unknown = want - {p.name for p in plans} - {"steady-state"}
        if unknown:
            raise SystemExit(f"unknown plan(s): {sorted(unknown)} "
                             f"(have: {[p.name for p in plans]})")
        plans = tuple(p for p in plans if p.name in want)

    root = tempfile.mkdtemp(prefix="mfm_faultinject_")
    results = []
    try:
        t0 = time.perf_counter()
        base = Baseline(root, args.seed)
        # the carries are host copies by construction (_carries); summing
        # their sizes through asarray is the R5-visible proof that the
        # baseline span closes on materialized data, and sizes the state
        # the plans replay from
        carry_bytes = int(sum(np.asarray(c).nbytes
                              for c in base.carries[-1]))
        baseline_s = time.perf_counter() - t0
        for plan in plans:
            t0 = time.perf_counter()
            rec = {"plan": plan.name, "kind": plan.kind, "seed": plan.seed}
            try:
                rec.update(RUNNERS[plan.kind](plan, base, root))
                rec["status"] = "pass"
            except AssertionError as err:
                rec["status"] = "FAIL"
                rec["error"] = str(err)
            rec["wall_s"] = round(time.perf_counter() - t0, 3)
            results.append(rec)
            print(json.dumps(rec), flush=True)
        if args.plans is None or "steady-state" in args.plans:
            t0 = time.perf_counter()
            rec = {"plan": "steady-state", "kind": "compile_contract"}
            try:
                rec.update(run_steady_state(base, root))
                rec["status"] = "pass"
            except AssertionError as err:
                rec["status"] = "FAIL"
                rec["error"] = str(err)
            rec["wall_s"] = round(time.perf_counter() - t0, 3)
            results.append(rec)
            print(json.dumps(rec), flush=True)
    finally:
        if args.keep:
            print(f"scratch kept at {root}", file=sys.stderr)
        else:
            shutil.rmtree(root, ignore_errors=True)

    failed = [r["plan"] for r in results if r["status"] != "pass"]
    summary = {"plans": len(results), "failed": failed,
               "baseline_wall_s": round(baseline_s, 3),
               "baseline_carry_bytes": carry_bytes}
    print(json.dumps(summary))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump({"summary": summary, "results": results}, fh, indent=1)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
