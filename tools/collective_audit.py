"""Audit the XLA collectives behind the sharded pipeline stages.

The mesh layout doctrine (``mfm_tpu/parallel/mesh.py``) makes concrete,
checkable claims: the cross-sectional regression's stock-axis reductions
become all-reduces (riding ICI), the rolling kernels' stock-only layout
needs NO communication at all, and no stage ever moves a full (T, N) panel
between devices.  One carve-out is explicit: XLA's eigh is not
batch-partitionable, so the hoisted batched decompositions gather their
tiny (T, K, K) normal/covariance batches — a bounded K^2-sized gather of
doctrine-replicated small matrices, not panel movement.  This tool compiles
each stage for real mesh shapes on the 8-virtual-device CPU backend and
reports every collective op XLA inserted — kind, count, and operand size —
so the doctrine is inspectable evidence instead of a docstring claim
(SURVEY.md §2.4: the reference has no communication backend; this is ours).

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/collective_audit.py            # prints a JSON report

Exit code is 0 iff the structural invariants hold (rolling: zero
collectives; all stages: largest collective strictly smaller than the full
panel).
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = f"{os.environ.get('XLA_FLAGS', '')} {_FLAG}".strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # the env var is unreliable here

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mfm_tpu.config import RiskModelConfig  # noqa: E402
from mfm_tpu.models.risk_model import RiskModel  # noqa: E402
from mfm_tpu.ops.rolling import rolling_beta_hsigma  # noqa: E402
from mfm_tpu.parallel.mesh import (  # noqa: E402
    PIPELINE_SPECS,
    make_mesh,
    panel_sharding,
)

# optimized-HLO collective ops and their result types — plain or variadic:
#   %all-reduce.3 = f32[8,42]{1,0} all-reduce(...)
#   %all-reduce.9 = (f32[16,5]{1,0}, f32[16,3]{1,0}) all-reduce(...)
_COLLECTIVE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?\("
)
_SHAPE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2}


def _type_bytes(type_str: str) -> int:
    """Total bytes across every array in a (possibly tuple) HLO result type."""
    total = 0
    for dtype, dims in _SHAPE.findall(type_str):
        n = int(np.prod([int(d) for d in dims.split(",")])) if dims else 1
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def audit_hlo(text: str) -> dict:
    """Count collectives in optimized HLO and size their results."""
    found = []
    for type_str, kind, suffix in _COLLECTIVE.findall(text):
        if suffix == "-done":  # async pair: count the -start only
            continue
        found.append({"kind": kind, "bytes": _type_bytes(type_str)})
    by_kind: dict[str, int] = {}
    for f in found:
        by_kind[f["kind"]] = by_kind.get(f["kind"], 0) + 1
    reduces = ("all-reduce", "reduce-scatter")
    return {
        "total": len(found),
        "by_kind": by_kind,
        "largest_bytes": max((f["bytes"] for f in found), default=0),
        "largest_non_reduce_bytes": max(
            (f["bytes"] for f in found if f["kind"] not in reduces),
            default=0),
        "non_reduce_kinds": sorted({f["kind"] for f in found
                                    if f["kind"] not in reduces}),
    }


def check_invariants(regression: dict, full_pipeline: dict,
                     rolling_beta: dict, *, panel_bytes: int,
                     eigh_gather_budget: int) -> dict:
    """Evaluate the mesh-layout doctrine on audited stage HLO.

    Takes the :func:`audit_hlo` summaries of the three compiled stages and
    returns the named structural invariants plus an overall ``ok``.  Pure
    and importable: tests assert the doctrine in-process on whatever HLO
    they compiled, no subprocess and no report plumbing.

    One structural exception is carved out explicitly rather than hidden:
    XLA's eigh (QDWH) is not batch-partitionable on this jaxlib, so the
    hoisted batched pseudo-inverse/eigen decompositions gather their tiny
    (T, K, K) matrix batches (plus QDWH's (2K, 2K) workspace) onto every
    device.  That is a K^2-sized gather of replicated-by-doctrine small
    matrices, NOT (T, N) panel movement — bound it by ``eigh_gather_budget``
    and reject anything larger.
    """
    inv = {
        "rolling_is_communication_free": rolling_beta["total"] == 0,
        "no_full_panel_collective": all(
            e["largest_bytes"] < max(panel_bytes, eigh_gather_budget)
            for e in (regression, full_pipeline)),
        # the regression stage communicates through reductions only, except
        # the bounded all-gather feeding the batched eigh
        "regression_is_reduce_only": (
            set(regression["non_reduce_kinds"]) <= {"all-gather"}
            and regression["largest_non_reduce_bytes"] <= eigh_gather_budget),
    }
    inv["ok"] = all(inv.values())
    return inv


def compiled_text(fn, mesh, arg_specs, *args) -> str:
    shardings = [jax.NamedSharding(mesh, s) for s in arg_specs]
    placed = [jax.device_put(a, s) for a, s in zip(args, shardings)]
    return jax.jit(fn).lower(*placed).compile().as_text()


def build_report(T=192, N=96, P=8, Q=4, meshes=((8, 1), (4, 2), (2, 4))):
    # the audit is a structural check of the f32 production fast path; x64
    # (the test suite's golden-parity mode) changes GSPMD's decisions —
    # f64 batches are Pallas-ineligible and the partitioner inserts extra
    # gathers — so pin it off for the duration of the build
    from jax.experimental import disable_x64

    with disable_x64():
        return _build_report(T, N, P, Q, meshes)


def _build_report(T, N, P, Q, meshes):
    from jax.sharding import PartitionSpec as Sp

    rng = np.random.default_rng(0)
    ret = jnp.asarray(rng.normal(0, 0.02, (T, N)))
    cap = jnp.asarray(rng.lognormal(10, 1, (T, N)))
    styles = jnp.asarray(rng.normal(0, 1, (T, N, Q)))
    industry = jnp.asarray(rng.integers(0, P, (T, N)))
    valid = jnp.asarray(rng.random((T, N)) > 0.05)
    mkt = jnp.asarray(rng.normal(0, 0.01, T))
    cfg = RiskModelConfig(eigen_n_sims=4, eigen_sim_length=64)
    K = 1 + P + Q
    sim = jnp.asarray(rng.normal(size=(4, K, 64)))
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 63.0

    def regression(ret, cap, styles, industry, valid):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=P, config=cfg)
        return m.reg_by_time()[:2]

    def full(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=P, config=cfg)
        return m.run(sim_covs=sim_covs)

    def rolling(ret, mkt):
        return rolling_beta_hsigma(ret, mkt, window=64, half_life=16,
                                   min_periods=8)

    panel_bytes = int(ret.size * ret.dtype.itemsize)
    report = {"shape": {"T": T, "N": N, "K": K},
              "panel_bytes": panel_bytes, "meshes": {}}
    ok = True
    # the canonical cross-sectional layouts, by argument name (mesh.py)
    xsec_specs = [PIPELINE_SPECS[k]
                  for k in ("ret", "cap", "styles", "industry", "valid")]
    for nd, ns in meshes:
        mesh = make_mesh(nd, ns)
        entry = {}
        entry["regression"] = audit_hlo(compiled_text(
            regression, mesh, xsec_specs,
            ret, cap, styles, industry, valid))
        entry["full_pipeline"] = audit_hlo(compiled_text(
            full, mesh, xsec_specs + [PIPELINE_SPECS["sim_covs"]],
            ret, cap, styles, industry, valid, sim_covs))
        roll_spec = panel_sharding(mesh, rolling=True).spec
        entry["rolling_beta"] = audit_hlo(compiled_text(
            rolling, mesh, [roll_spec, Sp()], ret, mkt))

        # doctrine invariants (see check_invariants for the eigh carve-out)
        eigh_gather_budget = T * (2 * K) * (2 * K) * 8  # f64 upper bound
        entry["eigh_gather_budget_bytes"] = eigh_gather_budget
        inv = check_invariants(
            entry["regression"], entry["full_pipeline"],
            entry["rolling_beta"], panel_bytes=panel_bytes,
            eigh_gather_budget=eigh_gather_budget)
        entry.update((k, v) for k, v in inv.items() if k != "ok")
        ok &= inv["ok"]
        report["meshes"][f"{nd}x{ns}"] = entry
    report["invariants_hold"] = ok
    return report


if __name__ == "__main__":
    rep = build_report()
    print(json.dumps(rep, indent=1))
    sys.exit(0 if rep["invariants_hold"] else 1)
