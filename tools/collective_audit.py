#!/usr/bin/env python
"""DEPRECATED shim — the collective audit moved into the analysis package.

The communication-layout audit now lives in
``mfm_tpu/analysis/collectives.py``, where it runs as pass A3 of the full
static audit (``python tools/mfmaudit.py``, ``mfm-tpu audit``) over EVERY
registered jit entrypoint instead of just the three pipeline stages this
script covered.  This wrapper re-exports the public surface so existing
imports (tests/test_collective_audit.py, external scripts) and the
standalone report mode keep working; new code should import
``mfm_tpu.analysis.collectives`` directly.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/collective_audit.py            # prints a JSON report

Exit code is 0 iff the structural invariants hold, exactly as before.
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FLAG = "--xla_force_host_platform_device_count=8"
if _FLAG not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = f"{os.environ.get('XLA_FLAGS', '')} {_FLAG}".strip()

from mfm_tpu.analysis.collectives import (  # noqa: E402,F401
    audit_hlo,
    build_report,
    check_invariants,
    compiled_text,
    eigh_gather_budget,
)

if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")  # the env var is unreliable here
    rep = build_report()
    print(json.dumps(rep, indent=1))
    sys.exit(0 if rep["invariants_hold"] else 1)
