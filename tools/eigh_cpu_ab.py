"""A/B the two CPU batched-eigh solvers: LAPACK syevd vs vectorized Jacobi.

The eigen Monte-Carlo's CPU fallback decomposes huge batches of small
symmetric matrices ((T*M, K, K) = 139,000 x 42 x 42 at CSI300 scale).
LAPACK handles them one matrix at a time (XLA loops the custom call) while
``jacobi_eigh`` (ops/eigh.py) is pure-JAX and vectorizes every rotation
across the whole batch — the same trade the Pallas TPU kernel wins on.
This sweep measures where (if anywhere) the crossover sits on THIS host,
and is the evidence behind ``MFM_EIGH_CPU_JACOBI_BATCH``'s default:

    python tools/eigh_cpu_ab.py                # prints a JSON report
    python tools/eigh_cpu_ab.py --k 42 --batches 64,1024,16384

Measured verdict (2026-08-05, 64-core container, f32 K=42): multithreaded
LAPACK beats the vectorized Jacobi at EVERY batch size (B=1024: 0.36 s vs
4.43 s) — XLA's loop-of-custom-calls parallelizes across cores, and the
Jacobi path burns ~K/2 full-batch sweeps of dense (B, K, K) rotations on
a backend with no VPU to amortize them.  Hence the threshold defaults to
OFF (``ops/eigh.py::cpu_jacobi_batch_threshold``): set the env var only on
hosts where this sweep says otherwise (e.g. single-thread-pinned CI).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from mfm_tpu.ops.eigh import jacobi_eigh  # noqa: E402


def _time(fn, *args, reps=3):
    jax.block_until_ready(fn(*args))  # compile + warmup
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append(time.perf_counter() - t0)
    return min(times)


def sweep(k: int, batches, dtype=jnp.float32, sweeps: int | None = None):
    rng = np.random.default_rng(0)
    rows = []
    lapack = jax.jit(lambda a: jnp.linalg.eigh(a)[0].sum())
    jacobi = jax.jit(
        lambda a: jacobi_eigh(a, sweeps=sweeps, canonical_signs=False)[0].sum())
    for b in batches:
        x = rng.standard_normal((b, k, k)).astype(np.float32)
        a = jnp.asarray((x + x.transpose(0, 2, 1)) / 2, dtype)
        t_lapack = _time(lapack, a)
        t_jacobi = _time(jacobi, a)
        rows.append({"batch": b, "k": k,
                     "lapack_s": round(t_lapack, 4),
                     "jacobi_s": round(t_jacobi, 4),
                     "jacobi_over_lapack": round(t_jacobi / t_lapack, 2)})
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--k", type=int, default=42)
    ap.add_argument("--batches", default="64,256,1024,4096,16384",
                    help="comma-separated batch sizes")
    ap.add_argument("--sweeps", type=int, default=None,
                    help="Jacobi sweep cap (default: solver auto)")
    args = ap.parse_args()
    batches = [int(b) for b in args.batches.split(",")]
    rows = sweep(args.k, batches, sweeps=args.sweeps)
    # the actionable summary: the smallest batch where Jacobi wins, if any —
    # that is the value to export as MFM_EIGH_CPU_JACOBI_BATCH on this host
    crossover = next((r["batch"] for r in rows
                      if r["jacobi_s"] < r["lapack_s"]), None)
    print(json.dumps({"rows": rows, "jacobi_wins_from_batch": crossover,
                      "recommended_env": (
                          f"MFM_EIGH_CPU_JACOBI_BATCH={crossover}"
                          if crossover else "unset (LAPACK wins everywhere)")},
                     indent=1))


if __name__ == "__main__":
    main()
