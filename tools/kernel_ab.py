"""A/B experiments to run on the real TPU (tunnel was down for the rest of
round 4's second session — run these the moment it answers):

1. weighted Jacobi kernel eigenvector layout (vt_rows=False vs True —
   strided column slices vs contiguous rows-pass tile sets; pick the faster
   as the default in ops/eigh.py::batched_eigh_weighted_diag)
2. scan-vs-block rolling kernels at CSI300 and all-A shapes (BASELINE.md's
   pending TPU numbers for the O(T*N) scan path)
3. v_compose2 (round 4 third session): two vt row passes fused into one
   4-term restack — bitwise-identical outputs in INTERPRET mode (pinned in
   tests/test_eigh.py); Mosaic-compiled hardware may schedule the fused
   restack differently, so the A/B below also checks hardware equality
   (allclose at f32 ulp scale) before the variant may be promoted to the
   batched_eigh_weighted_diag default
"""
import sys
import time

import numpy as np

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu
from mfm_tpu.ops.rolling import rolling_beta_hsigma


def force(x):
    if isinstance(x, tuple):
        x = x[0]
    return float(np.asarray(jnp.nansum(x)))


def t3(fn, *a):
    force(fn(*a))
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        force(fn(*a))
        ts.append(time.perf_counter() - t0)
    return min(ts)


# --- weighted kernel V layout A/B (the eigen stage's dominant cost) ---
on_tpu = jax.default_backend() == "tpu"
K, B, sweeps = 42, 1390 * 100, 4
if not on_tpu:
    B = 1390  # CPU records the XLA side + interpret parity only (below)
X = jax.random.normal(jax.random.key(0), (B, 64, K), jnp.float32)
A = jnp.einsum("bnk,bnl->bkl", X, X) / 64
d0 = jnp.abs(jax.random.normal(jax.random.key(1), (B, K), jnp.float32))

if on_tpu:
    for vt, comp in ((False, False), (True, False), (True, True)):
        f = jax.jit(lambda A, d0, vt=vt, comp=comp: sum(map(jnp.sum,
            jacobi_eigh_weighted_diag_tpu(A, d0, sweeps=sweeps, vt_rows=vt,
                                          v_compose2=comp))))
        print(f"weighted kernel vt_rows={vt} v_compose2={comp}: "
              f"{t3(f, A, d0):.4f} s", flush=True)

# --- Pallas kernel vs XLA dispatch for the same weighted-diag consumer ---
# This is the dispatch decision ops/eigh.py::batched_eigh_weighted_diag
# makes per backend; record both sides wherever this script runs.  On CPU
# the Pallas kernel only exists in interpret mode (orders of magnitude
# slower — record it for the parity evidence, never as a timing), so the
# CPU A/B times the XLA path against the pure-JAX Brent-Luk Jacobi batch,
# the same algorithm the Pallas kernel implements.
from mfm_tpu.ops.eigh import batched_eigh_weighted_diag  # noqa: E402

ab_B = B if on_tpu else 1390  # CPU: one date block keeps the A/B minutes-free
Aab, dab = A[:ab_B], d0[:ab_B]
fx = jax.jit(lambda A, d0: sum(map(jnp.sum, batched_eigh_weighted_diag(
    A, d0, sweeps=sweeps))))
print(f"weighted diag XLA dispatch  (B={ab_B}): {t3(fx, Aab, dab):.4f} s",
      flush=True)
if on_tpu:
    fp = jax.jit(lambda A, d0: sum(map(jnp.sum, batched_eigh_weighted_diag(
        A, d0, sweeps=sweeps, prefer_pallas=True))))
    print(f"weighted diag Pallas kernel (B={ab_B}): {t3(fp, Aab, dab):.4f} s",
          flush=True)
else:
    few = slice(0, 8)  # interpret mode: parity evidence only
    wx, hx = batched_eigh_weighted_diag(Aab[few], dab[few], sweeps=sweeps)
    wi, hi = jacobi_eigh_weighted_diag_tpu(Aab[few], dab[few], sweeps=sweeps,
                                           interpret=True)
    order = jnp.argsort(wi, axis=-1)
    wi = jnp.take_along_axis(wi, order, axis=-1)
    hi = jnp.take_along_axis(hi, order, axis=-1)
    rel = max(float(jnp.max(jnp.abs(a - b)) / (jnp.max(jnp.abs(a)) + 1e-30))
              for a, b in ((wx, wi), (hx, hi)))
    print(f"weighted diag Pallas interpret-mode parity vs XLA: "
          f"max_rel={rel:.3e} (timing not meaningful off-TPU)", flush=True)

# hardware equality gate for v_compose2 (interpret-mode pins don't bind
# Mosaic's schedule): the fused restack must match the two-pass variant on
# THIS backend before it may become the default
if on_tpu:
    small = slice(0, 1390)  # one date-block is plenty for an equality verdict
    f2 = jax.jit(lambda A, d0, comp: jacobi_eigh_weighted_diag_tpu(
        A, d0, sweeps=sweeps, vt_rows=True, v_compose2=comp),
        static_argnums=2)
    ref_out = f2(A[small], d0[small], False)
    new_out = f2(A[small], d0[small], True)
    worst = max(float(jnp.max(jnp.abs(r - n)) / (jnp.max(jnp.abs(r)) + 1e-30))
                for r, n in zip(ref_out, new_out))
    print(f"v_compose2 hardware equality vs two-pass: max_rel={worst:.3e} "
          f"({'OK (promotable)' if worst < 1e-5 else 'MISMATCH'})",
          flush=True)

# --- Newey-West: serial scan vs associative (sequence-parallel) ---
# single-chip A/B: the associative form's O(log T) depth trades more total
# FLOPs for parallelism, so on ONE chip the serial scan usually wins; the
# associative form's case is a date-sharded mesh (tests/test_sharding.py
# pins equality there).  Record both at CSI300 and all-A T.
from mfm_tpu.models.newey_west import newey_west_expanding  # noqa: E402

for T, K in ((1390, 42), (2500, 42)):
    f = jnp.asarray(np.random.default_rng(2).standard_normal((T, K)) * 0.01,
                    jnp.float32)
    for method in ("scan", "associative"):
        g = jax.jit(lambda r, m=method: newey_west_expanding(r, 2, 252.0,
                                                             method=m)[0])
        print(f"newey_west[{method}] T={T} K={K}: {t3(g, f):.4f} s",
              flush=True)

# --- scan vs block rolling ---
rng = np.random.default_rng(0)
for T, N in ((1390, 300), (2500, 5000)):
    x = rng.normal(0.001, 0.02, (T, N)).astype(np.float32)
    x[rng.random((T, N)) < 0.1] = np.nan
    xj = jnp.asarray(x)
    mkt = jnp.asarray(rng.normal(0.0005, 0.01, T).astype(np.float32))
    for impl in ("scan", "block"):
        blk = 64 if N == 300 else 16
        f = jax.jit(lambda y, m, i=impl, b=blk: rolling_beta_hsigma(
            y, m, impl=i, block=b))
        print(f"beta_hsigma[{impl}] {T}x{N}: {t3(f, xj, mkt):.4f} s",
              flush=True)
