#!/bin/bash
# Run every BASELINE.json bench config on the current backend and collect the
# JSON lines in OUTDIR (default /tmp/bench_all).  Pair with
# tools/refresh_hardware_evidence.sh for the parity gates.
#
#   tools/bench_all.sh [OUTDIR]
#
# Configs (bench.py): default = config 1 (risk model e2e, the driver metric),
# beta, factors, alla, alpha, query, scenario, grad, fleet, cache.  Each
# prints ONE JSON line; a
# dead TPU tunnel falls back to CPU with an `errors` field rather than
# hanging.
#
# The config-1 record also carries the serving metrics: daily_update_latency_s
# (one-date append to the resumable state), guarded_update_latency_s +
# guard_overhead_frac (the same append through the production input guards,
# docs/SERVING.md), and the observed quarantine_rate (0.0 on the clean
# synthetic panel — the guards-are-free evidence).
set -eo pipefail
cd "$(dirname "$0")/.."
out=${1:-/tmp/bench_all}
mkdir -p "$out"

# numbers captured from a tree that violates the JAX doctrine (s64 loop
# counters, unforced timing spans, ...) are not evidence — gate first
python tools/mfmlint.py --strict \
  || { echo "mfmlint violations — fix or baseline before benching" >&2
       exit 1; }

# ... and the IR-level proofs next to the AST-level ones: donation aliasing,
# wide dtypes, collectives, recompile surface, memory budgets (device-free,
# lowering only — runs fine before the backend probe below)
JAX_PLATFORMS=cpu python tools/mfmaudit.py --strict \
  || { echo "mfmaudit violations — fix, re-budget, or baseline before benching" >&2
       exit 1; }

# ... and the concurrency doctrine beside them: serving-fleet numbers from a
# tree with an unguarded shared field, a lock-order cycle, or blocking under
# a lock (mfmsync S1-S3) measure a race, not the service
JAX_PLATFORMS=cpu python tools/mfmsync.py --strict \
  || { echo "mfmsync violations — fix or baseline before benching" >&2
       exit 1; }

# probe the backend ONCE here: each bench.py run would otherwise repeat its
# own multi-attempt probe (~6.5 min per config against a dead tunnel);
# a dead tunnel pins every config straight to the CPU fallback instead
plat=()
timeout 90 python -c "import jax; assert jax.devices()[0].platform in ('tpu', 'axon')" \
  || { echo "TPU backend not reachable — running the CPU fallback" >&2
       plat=(--platform cpu); }

python bench.py                  "${plat[@]}" | tail -1 > "$out/config1_risk.json"
python bench.py --config beta    "${plat[@]}" | tail -1 > "$out/config2_beta.json"
python bench.py --config factors "${plat[@]}" | tail -1 > "$out/config3_factors.json"
python bench.py --config alla    "${plat[@]}" | tail -1 > "$out/config4_alla.json"
python bench.py --config alpha   "${plat[@]}" | tail -1 > "$out/config5_alpha.json"
python bench.py --config query   "${plat[@]}" | tail -1 > "$out/config6_query.json"
python bench.py --config scenario "${plat[@]}" | tail -1 > "$out/config7_scenario.json"
python bench.py --config sweep   "${plat[@]}" | tail -1 > "$out/config11_sweep.json"
python bench.py --config grad    "${plat[@]}" | tail -1 > "$out/config8_grad.json"
python bench.py --config fleet   "${plat[@]}" | tail -1 > "$out/config9_fleet.json"
python bench.py --config cache   "${plat[@]}" | tail -1 > "$out/config10_cache.json"
# config 12 always measures the TCP transport on-host: workers are real
# subprocesses, so the multi-host number is the wire + dispatch overhead
python bench.py --config fleet_mh --platform cpu | tail -1 > "$out/config12_fleet_mh.json"

# universe-scaling smoke (slow; skip with MFM_SKIP_UNIVERSE_SMOKE=1): the
# full A-share universe (N=5000) on an 8-device host mesh, time-bounded by
# BENCH_SMOKE_T so it proves the sharded path compiles and runs end to end
# rather than re-measuring the committed grid (tools/multichip_bench.py
# regenerates MULTICHIP_r06.json).  Pinned to --platform cpu: the knob under
# test is host-device sharding, not the TPU tunnel.  The record's universe
# is renamed "alla_t64" by the smoke bound, and bench_all does not perfgate
# it — smoke-T walls are not comparable to the full-T trajectory.
if [ -z "${MFM_SKIP_UNIVERSE_SMOKE:-}" ]; then
  BENCH_SMOKE_T=64 python bench.py --config riskmodel --universe 5000 \
      --devices 8 --platform cpu | tail -1 > "$out/config1_universe5000.json" \
    || { echo "universe-scaling smoke failed — sharded N=5000 path broken" >&2
         exit 1; }
fi

# eigen-stage evidence sweep (tools/profile_eigen.py --json): the
# chunk x batch_hint x dtype grid with XLA cost analysis per cell — the
# committed EIGEN_SWEEP_r*.json files are snapshots of this output, and a
# dispatch change in ops/eigh.py should cite a cell from a fresh run
python tools/profile_eigen.py --json "$out/eigen_sweep.json" \
  --t 256 --sims 50 --chunks 64,128,none --batch-hints auto,init \
  --dtypes f32,bf16 \
  || { echo "eigen sweep failed — kernel-path evidence incomplete" >&2
       exit 1; }

# perf-regression sentinel: gate the fresh records against the committed
# BENCH_r*.json trajectory (tools/perfgate.py; per-metric tolerance bands,
# same-backend baselines only).  A regression fails the sweep — slower
# numbers are a finding, not evidence to file.
for rec in "$out/config1_risk.json" "$out/config6_query.json" \
           "$out/config7_scenario.json" "$out/config8_grad.json" \
           "$out/config9_fleet.json" "$out/config10_cache.json" \
           "$out/config12_fleet_mh.json"; do
  python tools/perfgate.py "$rec" \
    || { echo "perfgate: $rec regressed vs the BENCH_r*.json trajectory" >&2
         exit 1; }
done

# the query-service and scenario numbers are only evidence if the services
# actually recover: gate configs 6+7 on their chaos plans (bitwise restart
# recovery, dead-letter quarantine, shed ordering, breaker-on-corrupt-swap,
# the <=1-compile-per-bucket steady state, scenario-manifest crash
# atomicity, per-lane poison isolation, and trace-flush crash atomicity —
# a SIGKILL mid trace.json flush must tear neither trace nor checkpoint),
# plus the incremental-eigen carry: a SIGKILL mid eigen-carry checkpoint
# save must leave the prior state bitwise-intact and doctor-green, and the
# sharded append: a SIGKILL mid `--append --mesh 2x2` must prove the mesh
# changes nothing about the fence (prior bytes identical, replay bitwise),
# and the grad report: a SIGKILL between grad_report.json's tmp write and
# rename must tear neither report nor checkpoint (config 8's evidence),
# and the serving fleet: SIGKILL 1 of 3 worker replicas mid-drain — the
# survivors keep answering, every response bitwise the single-process
# replay's, the merged fleet manifest counts the loss (config 9's evidence),
# and the response cache: a hot reload mid repeat-stream must move the
# generation fence (no post-reload answer equals a pre-reload cached
# body), and after a SIGKILL-torn checkpoint publish a cache-on serve
# must replay byte-for-byte against a cache-off run (config 10's
# evidence), and the streaming sweep: SIGKILL between the sweep
# manifest's tmp write and its rename — no torn sweep_manifest.json,
# checkpoint bytes untouched, seeded re-run byte-equal modulo the obs
# summary (config 11's evidence), and the schedule drills: adversarial
# deterministic interleavings (mfm_tpu/utils/sched.py) plus a live
# closed-loop socket hammer must keep the coalescer responses bitwise the
# sequential loop per id, and a concurrent hit/miss/reload storm must keep
# cache hits byte-equal cold with the LRU bounds and generation fence
# intact — the runtime confirmation of mfmsync's static findings, and the
# multi-host fleet: SIGKILL an entire 2-worker host mid-storm while a
# third worker sits SIGSTOPped (wedged, not dead) — heartbeats must
# quarantine the silent worker, survivors answer everything bitwise
# by id, and the merged manifest's transport counters stay audit-
# consistent (config 12's evidence), and the flight recorder: SIGKILL
# between the postmortem dump's tmp write and its rename — no torn
# flightrec.json, checkpoint bytes untouched, a clean re-trigger parses
# with the staged breaker trigger + trace id, doctor stays green
python tools/faultinject.py --plans \
  query-kill-mid-batch,query-poison-slab,query-overflow-storm,query-ckpt-swap,query-steady-state,scenario-kill-mid-batch,scenario-poison-spec,trace-kill-mid-flush,eigen-kill-mid-update,shard-kill-mid-append,grad-kill-mid-solve,fleet-kill-replica,fleet-kill-host,fleet-wedge-worker,cache-stale-generation,sweep-kill-mid-stream,sync-schedule-coalescer,sync-schedule-cache,flightrec-kill-mid-dump \
  || { echo "query/scenario/trace/grad/fleet/cache/sweep/schedule chaos plans failed — config6/7/8/9/10/11 numbers are not evidence" >&2
       exit 1; }

cat "$out"/config*.json
