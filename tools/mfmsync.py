#!/usr/bin/env python
"""mfmsync — lock-discipline & shared-state static analysis CLI.

Thin shim over mfm_tpu.analysis.sync so the checker can run standalone
(pre-commit, CI) without installing the package.  Same exit convention
as mfmlint/mfmaudit: 0 clean, 1 on new findings (or stale baseline
entries under --strict).
"""
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from mfm_tpu.analysis.sync import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
