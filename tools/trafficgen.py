#!/usr/bin/env python
"""Deterministic traffic generator for the serving fleet.

Produces seeded JSONL request streams with a mixed shape — plain risk
queries, benchmark (active-risk) queries, scenario-tagged queries and
construction solves — and drives them at a target arrival rate:

- **open loop**: requests arrive on a fixed schedule (``i / rate``)
  regardless of how fast the service answers — the honest way to measure
  sustained QPS and tail latency (a closed loop self-throttles and hides
  queueing collapse).
- **closed loop**: N virtual clients each keep exactly one request in
  flight — the throughput ceiling under coordinated back-pressure.

Everything is seeded: the same (seed, n, k, mix) produces byte-identical
request lines, which is what lets ``bench.py --config fleet`` prove the
coalesced responses bitwise-equal against the sequential loop, and lets
the ``fleet-kill-replica`` chaos drill replay deterministically.

``--zipf ALPHA --distinct N`` switches to repeat-heavy traffic: bodies
draw Zipf(ALPHA) from a pool of N unique requests (ids stay unique) —
the stream shape the content-addressed response cache is built for.

As a script, writes the request stream to stdout (pipe into
``mfm-tpu serve`` or a socket with ``nc``):

    python tools/trafficgen.py --seed 7 --n 1000 --k 42 > req.jsonl
    python tools/trafficgen.py --seed 7 --n 20000 --k 42 \\
        --zipf 1.0 --distinct 150 > zipf.jsonl
"""

from __future__ import annotations

import argparse
import json
import socket
import sys
import threading
import time

import numpy as np

#: request-shape mix: (plain query, benchmark query, scenario-tagged,
#: construct, sweep) — must sum to 1.  Sweeps default to a thin slice:
#: each one is a bounded streaming batch job (hundreds of scenarios),
#: ~100x a risk query, and they are cache-exempt by contract.
DEFAULT_MIX = (0.53, 0.20, 0.15, 0.10, 0.02)

#: the sweep slice's admission-bounded spec shape: small sampler, a
#: handful of chunks (n/chunk = 4 donated jit calls per request).  The
#: seed varies per line so every sweep body is unique — repeat-heavy
#: (Zipf) streams still exercise the cache-exemption path via the pool.
SWEEP_REQ = {"sampler": "uniform", "n": 512, "chunk": 128, "top_k": 4,
             "bins": 64}


def gen_requests(seed: int, n: int, k: int, *, mix=DEFAULT_MIX,
                 benchmark: str = "idx", scenario: str | None = None,
                 deadline_s: float = 600.0) -> list:
    """``n`` seeded JSONL request lines (ids ``t0..t{n-1}``), mixed per
    ``mix``.  ``scenario=None`` drops the scenario slice into plain
    queries (for servers without a scenario table).  Weights round to 6
    decimals so lines are platform-stable."""
    if abs(sum(mix) - 1.0) > 1e-9 or len(mix) != 5:
        raise ValueError(f"mix must be 5 fractions summing to 1, got {mix}")
    rng = np.random.default_rng(seed)
    kinds = rng.choice(5, size=n, p=np.asarray(mix, dtype=np.float64))
    lines = []
    for i in range(n):
        req = {"id": f"t{i}",
               "weights": np.round(0.2 * rng.standard_normal(k), 6).tolist(),
               "deadline_s": deadline_s}
        kind = int(kinds[i])
        if kind == 1:
            req["benchmark"] = benchmark
        elif kind == 2 and scenario is not None:
            req["scenario"] = scenario
        elif kind == 3:
            req["construct"] = {"solver": "min_vol" if i % 2 else
                                "risk_parity"}
        elif kind == 4:
            req["sweep"] = {**SWEEP_REQ, "seed": i}
        lines.append(json.dumps(req, sort_keys=True))
    return lines


def gen_zipf_requests(seed: int, n: int, k: int, *, alpha: float = 1.0,
                      distinct: int = 100, mix=DEFAULT_MIX,
                      benchmark: str = "idx", scenario: str | None = None,
                      deadline_s: float = 600.0) -> list:
    """``n`` seeded lines drawn Zipf(``alpha``) from a pool of
    ``distinct`` unique request BODIES (all five request kinds, per
    ``mix``).  Every emitted line keeps a unique id ``t{i}`` — only the
    id differs between repeats, which is exactly the shape the
    content-addressed response cache keys on (identity excluded).
    ``alpha=1.0, distinct=100`` sends ~19% of traffic to rank 1; the
    same (seed, n, k, alpha, distinct, mix) is byte-identical."""
    if distinct < 1:
        raise ValueError(f"distinct must be >= 1, got {distinct}")
    if alpha < 0:
        raise ValueError(f"alpha must be >= 0, got {alpha}")
    pool = [json.loads(line) for line in
            gen_requests(seed, distinct, k, mix=mix, benchmark=benchmark,
                         scenario=scenario, deadline_s=deadline_s)]
    for body in pool:
        body.pop("id", None)
    ranks = np.arange(1, distinct + 1, dtype=np.float64)
    p = ranks ** -float(alpha)
    p /= p.sum()
    # a separate stream from the pool's so adding draws never perturbs
    # the pool bodies themselves
    draws = np.random.default_rng((seed, 0x21F)).choice(
        distinct, size=n, p=p)
    lines = []
    for i, d in enumerate(draws):
        req = dict(pool[int(d)])
        req["id"] = f"t{i}"
        lines.append(json.dumps(req, sort_keys=True))
    return lines


def open_loop(submit, lines, rate: float, *,
              clock=time.monotonic, sleep=time.sleep) -> dict:
    """Drive ``submit(line, ordinal)`` on the fixed arrival schedule
    ``t0 + i/rate``.  Never skips a request when behind — a too-slow
    service sees the backlog, which is the point of open loop.  Returns
    the schedule: ``{"t0", "arrivals": [...], "offered_rate"}`` (arrival
    = the scheduled time, the honest latency origin)."""
    t0 = clock()
    arrivals = []
    for i, line in enumerate(lines):
        due = t0 + i / rate
        now = clock()
        if due > now:
            sleep(due - now)
        arrivals.append(due)
        submit(line, i)
    return {"t0": t0, "arrivals": arrivals, "offered_rate": float(rate)}


def closed_loop(submit_and_wait, lines, concurrency: int) -> dict:
    """``concurrency`` virtual clients, one request in flight each.
    ``submit_and_wait(line, ordinal)`` must block until the response.
    Returns ``{"wall_s", "qps", "n"}``."""
    it = iter(enumerate(lines))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                try:
                    i, line = next(it)
                except StopIteration:
                    return
            submit_and_wait(line, i)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    return {"wall_s": wall, "qps": len(lines) / wall if wall else 0.0,
            "n": len(lines)}


def hammer(addr, lines_per_thread, *, timeout_s: float = 120.0) -> dict:
    """Closed-loop hammer against a live socket frontend: one TCP
    connection per client thread, exactly one request in flight each.

    The JSONL protocol answers every request with exactly one line on
    the same connection, so with one request in flight the response
    order IS assertable: each thread requires ``resp["id"] == sent id``
    line-for-line, which is how the ``sync-schedule-coalescer`` drill
    detects a cross-connection delivery mixup or a dropped response
    under adversarial flush/submit interleavings.

    ``lines_per_thread`` is a list of request-line lists, one per
    thread.  Returns per-thread ordered ``(id, response-line)`` pairs
    plus a flat ``id -> response-line`` map for bitwise comparison
    against a sequential reference run.  Raises the first per-thread
    assertion failure after all threads finish.
    """
    host, port = addr
    results: dict = {}
    errors: list = []

    def client(tix: int, lines) -> None:
        got = []
        try:
            with socket.create_connection((host, int(port)),
                                          timeout=timeout_s) as sk:
                rf = sk.makefile("r", encoding="utf-8")
                for line in lines:
                    sent = json.loads(line)["id"]
                    sk.sendall((line + "\n").encode("utf-8"))
                    resp_line = rf.readline()
                    if not resp_line:
                        raise AssertionError(
                            f"hammer thread {tix}: connection closed "
                            f"with {sent!r} in flight")
                    resp = json.loads(resp_line)
                    if resp.get("id") != sent:
                        raise AssertionError(
                            f"hammer thread {tix}: response order "
                            f"violated — sent {sent!r}, got "
                            f"{resp.get('id')!r}")
                    got.append((sent, resp_line.rstrip("\n")))
        except BaseException as exc:
            errors.append((tix, exc))
        results[tix] = got

    threads = [threading.Thread(target=client, args=(i, lines),
                                name=f"hammer-{i}", daemon=True)
               for i, lines in enumerate(lines_per_thread)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if errors:
        tix, exc = sorted(errors)[0]
        raise AssertionError(f"hammer thread {tix} failed: {exc}") from (
            exc if isinstance(exc, Exception) else None)
    flat = {rid: line for got in results.values() for rid, line in got}
    n = sum(len(g) for g in results.values())
    return {"threads": len(threads), "n": n, "wall_s": wall,
            "qps": n / wall if wall else 0.0,
            "per_thread": {i: results[i] for i in sorted(results)},
            "responses": flat}


def partition_hosts(lines, hosts: int) -> list:
    """Split one seeded stream across ``hosts`` client hosts: host ``h``
    sends ``lines[h::hosts]``.  Striding (not chunking) keeps every host's
    slice the same shape mix and arrival density, so per-host latency
    percentiles are comparable — and the union of the partitions is the
    original stream, so a fleet answering the partitioned run stays
    bitwise-comparable per id to the single-host replay."""
    if hosts < 1:
        raise ValueError(f"hosts must be >= 1, got {hosts}")
    return [lines[h::hosts] for h in range(hosts)]


def host_of(ordinal: int, hosts: int) -> int:
    """The client host an ordinal belongs to under :func:`partition_hosts`
    striding (ordinal i came from host ``i % hosts``)."""
    return int(ordinal) % int(hosts)


def per_host_latency(arrivals, completions, hosts: int) -> dict:
    """:func:`latency_stats` per client host of the striped partition:
    ``{"host0": {...}, ...}``.  One slow or partitioned host shows up as
    ITS percentiles degrading while the others hold — the merged stats
    alone cannot distinguish that from uniform slowdown."""
    out = {}
    for h in range(int(hosts)):
        harr = arrivals[h::hosts]
        hcomp = {i // hosts: completions[i]
                 for i in completions if host_of(i, hosts) == h}
        out[f"host{h}"] = latency_stats(harr, hcomp)
    return out


def latency_stats(arrivals, completions) -> dict:
    """p50/p99/max of (completion - arrival) for matched ordinals.
    ``completions`` maps ordinal -> completion clock time; unanswered
    ordinals are excluded (and counted)."""
    lats = sorted(completions[i] - arrivals[i]
                  for i in completions if i < len(arrivals))
    if not lats:
        return {"n": 0, "unanswered": len(arrivals)}

    def q(p):
        return lats[min(len(lats) - 1, int(p * len(lats)))]
    return {"n": len(lats),
            "unanswered": len(arrivals) - len(lats),
            "p50_s": round(q(0.50), 6),
            "p99_s": round(q(0.99), 6),
            "max_s": round(lats[-1], 6)}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="emit a seeded mixed JSONL request stream")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--n", type=int, default=1000)
    ap.add_argument("--k", type=int, required=True,
                    help="factor count of the served engine (weights "
                         "length)")
    ap.add_argument("--mix", default=",".join(str(m) for m in DEFAULT_MIX),
                    help="plain,benchmark,scenario,construct,sweep "
                         f"fractions (default {DEFAULT_MIX})")
    ap.add_argument("--benchmark", default="idx")
    ap.add_argument("--scenario", default=None,
                    help="scenario tag for the scenario slice (default: "
                         "fold into plain queries)")
    ap.add_argument("--deadline-s", type=float, default=600.0)
    ap.add_argument("--zipf", type=float, default=None, metavar="ALPHA",
                    help="draw bodies Zipf(ALPHA) from a --distinct pool "
                         "instead of all-unique traffic (repeat-heavy "
                         "streams for the response cache; ids stay "
                         "unique)")
    ap.add_argument("--distinct", type=int, default=100,
                    help="unique request bodies in the Zipf pool "
                         "(default 100; only with --zipf)")
    ap.add_argument("--hammer", type=int, default=None, metavar="T",
                    help="instead of printing the stream, drive it "
                         "closed-loop from T client threads (per host) "
                         "against --connect, asserting per-thread "
                         "response order; responses go to stdout, a "
                         "stats line to stderr")
    ap.add_argument("--connect", default=None, metavar="HOST:PORT[,..]",
                    help="socket frontend address(es) for --hammer; a "
                         "comma list drives one address per client host "
                         "(--hosts must match its length)")
    ap.add_argument("--hosts", type=int, default=1, metavar="N",
                    help="partition the stream across N client hosts "
                         "(host h sends lines h::N); stream mode needs "
                         "--out-prefix, hammer mode one --connect "
                         "address per host; stats come back per host")
    ap.add_argument("--out-prefix", default=None, metavar="PATH",
                    help="with --hosts N in stream mode, write host h's "
                         "partition to PATH.host{h}.jsonl instead of "
                         "stdout")
    args = ap.parse_args(argv)
    if (args.hammer is None) != (args.connect is None):
        ap.error("--hammer and --connect go together")
    if args.hammer is not None and args.hammer < 1:
        ap.error("--hammer needs at least one thread")
    if args.hosts < 1:
        ap.error("--hosts needs at least one host")
    if args.hosts > 1 and args.hammer is None and args.out_prefix is None:
        ap.error("--hosts N in stream mode needs --out-prefix")
    mix = tuple(float(x) for x in args.mix.split(","))
    if args.zipf is not None:
        lines = gen_zipf_requests(args.seed, args.n, args.k,
                                  alpha=args.zipf, distinct=args.distinct,
                                  mix=mix, benchmark=args.benchmark,
                                  scenario=args.scenario,
                                  deadline_s=args.deadline_s)
    else:
        lines = gen_requests(args.seed, args.n, args.k, mix=mix,
                             benchmark=args.benchmark,
                             scenario=args.scenario,
                             deadline_s=args.deadline_s)
    if args.hammer is not None:
        addrs = []
        for spec in args.connect.split(","):
            host, _, port = spec.strip().rpartition(":")
            addrs.append((host or "127.0.0.1", int(port)))
        if len(addrs) != args.hosts:
            ap.error(f"--hosts {args.hosts} needs {args.hosts} --connect "
                     f"address(es), got {len(addrs)}")
        by_host = partition_hosts(lines, args.hosts)
        reports: dict = {}

        def drive(h):
            slab = by_host[h]
            per_thread = [slab[i::args.hammer] for i in range(args.hammer)]
            reports[h] = hammer(addrs[h], per_thread)

        drivers = [threading.Thread(target=drive, args=(h,),
                                    name=f"hammer-host{h}", daemon=True)
                   for h in range(args.hosts)]
        for t in drivers:
            t.start()
        for t in drivers:
            t.join()
        responses = {}
        for rep in reports.values():
            responses.update(rep["responses"])
        for rid in sorted(responses):
            sys.stdout.write(responses[rid] + "\n")
        n = sum(rep["n"] for rep in reports.values())
        wall = max(rep["wall_s"] for rep in reports.values())
        stats = {"hosts": args.hosts, "threads_per_host": args.hammer,
                 "n": n, "wall_s": round(wall, 4),
                 "qps": round(n / wall if wall else 0.0, 2),
                 "per_host": {f"host{h}": {
                     "n": reports[h]["n"],
                     "wall_s": round(reports[h]["wall_s"], 4),
                     "qps": round(reports[h]["qps"], 2)}
                     for h in sorted(reports)}}
        print(json.dumps(stats, sort_keys=True), file=sys.stderr)
        return 0
    if args.hosts > 1:
        for h, slab in enumerate(partition_hosts(lines, args.hosts)):
            with open(f"{args.out_prefix}.host{h}.jsonl", "w",
                      encoding="utf-8") as fh:
                for line in slab:
                    fh.write(line + "\n")
        return 0
    for line in lines:
        sys.stdout.write(line + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
