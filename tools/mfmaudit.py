#!/usr/bin/env python
"""Executable wrapper for the IR-level static audit (mfm_tpu/analysis/).

Usage:
  python tools/mfmaudit.py [--strict] [--json FILE] [--passes A1,A3]
                           [--baseline FILE] [--budgets FILE]
                           [--write-budgets]

Lowers every registered jit entrypoint across the declared config matrix
and runs the five passes (A1 donation-aliasing proof, A2 wide-dtype /
host-callback audit, A3 collective audit, A4 recompile-surface
enumeration, A5 static memory budgets).  Nothing executes: the audit is
device-free by construction, so this wrapper pins the CPU backend and a
fixed 8-way host-device split BEFORE jax is imported — the same audit on
a TPU host and in CI must lower the same programs.

Kept as a thin shim so the same passes are importable
(`mfm_tpu.analysis.run_audit` in tests, `mfm-tpu audit` on the CLI) and
runnable standalone from tools/bench_all.sh next to mfmlint.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "jax" not in sys.modules:   # under pytest, conftest already pinned these
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    _FLAG = "--xla_force_host_platform_device_count=8"
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = \
            f"{os.environ.get('XLA_FLAGS', '')} {_FLAG}".strip()

from mfm_tpu.analysis.run import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
