"""Profile the eigen stage: stage-split timings and a reproducible
chunk x batch_hint x dtype sweep.

Two modes:

  python tools/profile_eigen.py
      the original ad-hoc stage split (f0 eigh / G assembly / simulated
      eigh / full stage) at the CSI300 shape on the current backend.

  python tools/profile_eigen.py --json EIGEN_SWEEP.json \
      --t 256 --sims 40 --chunks 32,64,none --batch-hints auto \
      --dtypes f32,bf16
      sweep the full eigen stage over date-chunk sizes, solver
      batch_hints and Monte-Carlo dtypes; each cell records the measured
      wall, the compiled program's cost analysis
      (mfm_tpu.obs.profile.compiled_cost: flops / bytes accessed) and the
      derived GFLOP/s, into a JSON document bench_all.sh checks in as
      EIGEN_SWEEP_r*.json.  The sweep is the evidence base for dispatch
      changes in ops/eigh.py — a claim like "sweep-count overshoot" or
      "chunk X beats chunk Y" should cite a sweep cell, not a hunch.

The per-cell record is self-describing (shape, dtype, backend, sweeps),
so sweeps from different hosts/backends are comparable side by side.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

from mfm_tpu.models.eigen import (
    eigen_risk_adjust_by_time,
    sim_sweeps_for,
    simulated_eigen_covs,
)
from mfm_tpu.ops.eigh import _sweeps_for, batched_eigh, batched_eigh_weighted_diag


def _force(x):
    return float(np.asarray(jnp.nansum(x)))


def _t3(fn, *a, repeats=3):
    _force(fn(*a))  # compile + warm
    ts = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        _force(fn(*a))
        ts.append(time.perf_counter() - t0)
    return min(ts)


def _panel(T, K, dtype):
    X = jax.random.normal(jax.random.key(0), (T, 200, K), dtype)
    covs = jnp.einsum("tnk,tnl->tkl", X, X) / 200
    return covs, jnp.ones((T,), bool)


def stage_split(args):
    """The original ad-hoc breakdown, kept as the default mode."""
    T, K, M = args.t, args.k, args.sims
    dtype = jnp.float32
    covs, valid = _panel(T, K, dtype)
    sim_covs = simulated_eigen_covs(jax.random.key(1), K, T, M, dtype)
    sweeps = sim_sweeps_for(K, dtype, T)
    print("sim sweeps:", sweeps, "full:", _sweeps_for(K, dtype))

    @jax.jit
    def f0_eigh(c):
        D0, U0 = batched_eigh(c)
        return jnp.sum(D0) + jnp.sum(U0)

    @jax.jit
    def g_form(c, sc):
        D0, U0 = batched_eigh(c)
        s = jnp.sqrt(jnp.maximum(D0, 0.0))
        G = s[:, None, :, None] * sc[None] * s[:, None, None, :]
        return jnp.sum(G)

    @jax.jit
    def sim_eigh(c, sc):
        D0, U0 = batched_eigh(c)
        s = jnp.sqrt(jnp.maximum(D0, 0.0))
        G = s[:, None, :, None] * sc[None] * s[:, None, None, :]
        Dm, Dm_hat = batched_eigh_weighted_diag(G, D0[:, None, :],
                                                sweeps=sweeps)
        return jnp.sum(Dm) + jnp.sum(Dm_hat)

    @jax.jit
    def full(c, v, sc):
        out, ok = eigen_risk_adjust_by_time(c, v, sc, sim_length=T)
        return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))

    print("f0_eigh        :", round(_t3(f0_eigh, covs), 4))
    print("  +G_form      :", round(_t3(g_form, covs, sim_covs), 4))
    print("  +sim_eigh    :", round(_t3(sim_eigh, covs, sim_covs), 4))
    print("full stage     :", round(_t3(full, covs, valid, sim_covs), 4))


def _parse_chunks(spec, T):
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if tok in ("none", "full"):
            out.append(None)
        else:
            out.append(min(int(tok), T))
    # dedup preserving order (min() above can collapse entries)
    seen, uniq = set(), []
    for c in out:
        if c not in seen:
            seen.add(c)
            uniq.append(c)
    return uniq


def _parse_hints(spec, T, M):
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if tok == "auto":
            out.append(None)          # let the chunked stream derive c*M
        elif tok == "init":
            out.append(T * M)         # the init-pinned dispatch hint
        else:
            out.append(int(tok))
    return out


_DTYPES = {"f32": None, "bf16": "bfloat16"}


def sweep(args):
    T, K, M = args.t, args.k, args.sims
    dtype = jnp.float32
    covs, valid = _panel(T, K, dtype)
    sweeps = sim_sweeps_for(K, dtype, T)
    from mfm_tpu.obs.profile import compiled_cost

    cells = []
    for dkey in args.dtypes.split(","):
        mc_dtype = _DTYPES[dkey.strip()]
        sim_covs = simulated_eigen_covs(jax.random.key(1), K, T, M, dtype,
                                        mc_dtype=mc_dtype)
        for chunk in _parse_chunks(args.chunks, T):
            for hint in _parse_hints(args.batch_hints, T, M):
                def stage(c, v, sc, *, _chunk=chunk, _hint=hint, _md=mc_dtype):
                    out, ok = eigen_risk_adjust_by_time(
                        c, v, sc, sim_length=T, sim_sweeps=sweeps,
                        chunk=_chunk, batch_hint=_hint, mc_dtype=_md)
                    return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))

                jitted = jax.jit(stage)
                wall = _t3(jitted, covs, valid, sim_covs,
                           repeats=args.repeats)
                cost = compiled_cost(stage, covs, valid, sim_covs) or {}
                flops = cost.get("flops")
                cell = {
                    "chunk": chunk,
                    "batch_hint": hint,
                    "mc_dtype": mc_dtype or "float32",
                    "wall_s": round(wall, 5),
                    "flops": flops,
                    "bytes_accessed": cost.get("bytes_accessed"),
                    "gflops_per_s": (round(flops / wall / 1e9, 2)
                                     if flops else None),
                }
                cells.append(cell)
                print(json.dumps(cell), flush=True)

    doc = {
        "tool": "profile_eigen",
        "shape": {"T": T, "K": K, "n_sims": M},
        "sim_sweeps": sweeps,
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0].device_kind),
        "jax": jax.__version__,
        "repeats": args.repeats,
        "cells": cells,
    }
    with open(args.json, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {len(cells)} cells -> {args.json}")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--json", default=None, metavar="OUT.json",
                   help="run the chunk x batch_hint x dtype sweep and write "
                        "this JSON document (default: ad-hoc stage split)")
    p.add_argument("--t", type=int, default=1390, help="dates (default CSI300)")
    p.add_argument("--k", type=int, default=42, help="factors")
    p.add_argument("--sims", type=int, default=100, help="Monte-Carlo sims")
    p.add_argument("--chunks", default="64,256,none",
                   help="comma list of date-chunk sizes; 'none' = full batch")
    p.add_argument("--batch-hints", default="auto,init",
                   help="comma list of solver batch hints; 'auto' = the "
                        "chunked stream's own c*M, 'init' = the init-pinned "
                        "T*M dispatch hint")
    p.add_argument("--dtypes", default="f32",
                   help="comma list from {f32, bf16}: Monte-Carlo dtype")
    p.add_argument("--repeats", type=int, default=3)
    args = p.parse_args(argv)
    if args.json:
        sweep(args)
    else:
        stage_split(args)


if __name__ == "__main__":
    main()
