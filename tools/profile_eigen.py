"""Split the eigen stage's wall into its internal parts on the current backend."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from mfm_tpu.models.eigen import simulated_eigen_covs, sim_sweeps_for
from mfm_tpu.ops.eigh import batched_eigh, batched_eigh_weighted_diag, _sweeps_for

T, N, K, M = 1390, 300, 42, 100
dtype = jnp.float32
key = jax.random.key(0)
X = jax.random.normal(key, (T, 200, K), dtype)
covs = jnp.einsum("tnk,tnl->tkl", X, X) / 200
valid = jnp.ones((T,), bool)
sim_covs = simulated_eigen_covs(jax.random.key(1), K, T, M, dtype)
sweeps = sim_sweeps_for(K, dtype, T)
print("sim sweeps:", sweeps, "full:", _sweeps_for(K, dtype))


# bench.py owns the tunnel-aware timing helpers (block_until_ready does not
# block on this TPU tunnel; timings must force a scalar host transfer)
from bench import _force as force, _time3 as t3  # noqa: E402


@jax.jit
def f0_eigh(c):
    D0, U0 = batched_eigh(c)
    return jnp.sum(D0) + jnp.sum(U0)


@jax.jit
def g_form(c, sc):
    D0, U0 = batched_eigh(c)
    s = jnp.sqrt(jnp.maximum(D0, 0.0))
    G = s[:, None, :, None] * sc[None] * s[:, None, None, :]
    return jnp.sum(G)


@jax.jit
def sim_eigh(c, sc):
    # the production consumer shape: fused (Dm, Dm_hat), no W materialized
    D0, U0 = batched_eigh(c)
    s = jnp.sqrt(jnp.maximum(D0, 0.0))
    G = s[:, None, :, None] * sc[None] * s[:, None, None, :]
    Dm, Dm_hat = batched_eigh_weighted_diag(G, D0[:, None, :], sweeps=sweeps)
    return jnp.sum(Dm) + jnp.sum(Dm_hat)


@jax.jit
def full(c, v, sc):
    from mfm_tpu.models.eigen import eigen_risk_adjust_by_time
    out, ok = eigen_risk_adjust_by_time(c, v, sc, sim_length=T)
    return jnp.sum(jnp.where(jnp.isfinite(out), out, 0.0))


print("f0_eigh        :", round(t3(f0_eigh, covs), 4))
print("  +G_form      :", round(t3(g_form, covs, sim_covs), 4))
print("  +sim_eigh    :", round(t3(sim_eigh, covs, sim_covs), 4))
print("full stage     :", round(t3(full, covs, valid, sim_covs), 4))
