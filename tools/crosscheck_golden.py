"""Independent end-to-end factor cross-validation -> CROSSCHECK.json.

The reference's only external QC is a notebook comparison of its size /
beta / momentum series against jqdatasdk's factor service
(``/root/reference/beta.ipynb`` cells 29-30).  No vendor data can enter
this image, so this tool closes the same loop with the strongest available
independent producer: a PANDAS-ONLY pipeline built from the test-suite
goldens (``tests/golden.py`` rolling/post functions + the
``tests/test_prepare._golden_master`` merge_asof chain), computed
end-to-end from the same raw synthetic store the framework reads — two
implementations that share no arrays, no prepare code, and no kernels,
meeting only at the raw collections.

    python tools/crosscheck_golden.py --profile quick --out CROSSCHECK.json
    python tools/crosscheck_golden.py --profile full  --out CROSSCHECK.json

``full`` runs the reference's production windows (252/504-day) over a
700-date store; ``quick`` is the hermetic CI profile (reduced windows,
130 dates, ~30 s).  Exit 0 iff every factor passes the agreement gates.

Real-data procedure (mirroring beta.ipynb cells 29-30): export the vendor
table (jqdatasdk ``get_factor_values`` or a Barra delivery) to CSV with
(trade_date, ts_code, factor...) columns, then

    python -m mfm_tpu.cli crosscheck --ours results/barra_data.csv \
        --external vendor.csv --date-col date --code-col stocknames

and hold the report to the same gates this tool applies.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np
import pandas as pd

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "tests"))

import golden  # noqa: E402  (tests/golden.py — the independent implementation)
from test_prepare import _golden_master  # noqa: E402

from mfm_tpu.config import FactorConfig, PipelineConfig, RollingSpec  # noqa: E402
from mfm_tpu.data.etl import PanelStore  # noqa: E402
from mfm_tpu.data.prepare import (  # noqa: E402
    latest_index_constituents, prepare_factor_inputs,
)
from mfm_tpu.data.synthetic import synthetic_collections  # noqa: E402
from mfm_tpu.pipeline import BARRA_OUTPUT_STYLES, run_factor_pipeline  # noqa: E402
from mfm_tpu.utils.crosscheck import crosscheck_factors  # noqa: E402

SUB_FACTORS = ("SIZE", "BETA", "HSIGMA", "RSTR", "DASTD", "CMRA", "NLSIZE",
               "BP", "STOM", "STOQ", "STOA", "CETOP", "ETOP", "YOYProfit",
               "YOYSales", "MLEV", "DTOA", "BLEV")

#: agreement gates: both sides are float64 and reproduce the same contract,
#: so corr must be ~1 to the last digit and raw values must agree to fp
#: noise; coverage differences would mean the two prepares disagree on
#: which (date, stock) cells exist
GATES = {"pearson": 0.9999, "rank_corr": 0.999, "mean_abs_diff": 1e-7,
         "coverage": 0.999}


def reduced_config() -> FactorConfig:
    """The hermetic profile's windows (same shape the factor golden tests
    use): every rolling factor reaches its valid regime within ~60 dates."""
    return FactorConfig(
        beta=RollingSpec(window=40, half_life=10, min_periods=8),
        rstr_total=60, rstr_lag=5, rstr_half_life=15, rstr_min_periods=8,
        dastd=RollingSpec(window=40, half_life=8, min_periods=8),
        cmra_window=30,
        stom=RollingSpec(window=10, min_periods=7),
        stoq=RollingSpec(window=21, min_periods=14),
        stoa=RollingSpec(window=42, min_periods=21),
    )


def golden_factor_table(store, cfg: FactorConfig,
                        index_code: str = "000300.SH") -> pd.DataFrame:
    """The pandas path: store -> merge_asof master -> per-stock rolling
    goldens -> per-date post-processing -> barra output schema."""
    uni = latest_index_constituents(store, index_code)
    m = _golden_master(store, uni, index_code)

    idx = store.read("index_daily_prices")
    idx = idx[idx.ts_code == index_code].copy()
    idx["trade_date"] = pd.to_datetime(idx.trade_date.astype(str),
                                       format="%Y%m%d")
    idx = idx.sort_values("trade_date")
    mkt_by_date = dict(zip(idx.trade_date, idx.close.pct_change()))

    frames = []
    for code, g in m.groupby("ts_code", observed=True):
        g = g.sort_values("trade_date").reset_index(drop=True)
        close = g["close"]
        ret = close.pct_change()
        log_ret = np.log(close).diff()
        market = pd.Series(g["trade_date"].map(mkt_by_date), dtype=float)

        beta, hsigma = golden.golden_beta_hsigma(
            ret, market, T=cfg.beta.window, hl=cfg.beta.half_life,
            minp=cfg.beta.min_periods)
        f = pd.DataFrame({
            "trade_date": g["trade_date"], "ts_code": code,
            "capital": g["circ_mv"].to_numpy(),
            "next_ret": ret.shift(-1).to_numpy(),
            "BETA": beta, "HSIGMA": hsigma,
            "RSTR": golden.golden_rstr(
                log_ret, T=cfg.rstr_total, L=cfg.rstr_lag,
                hl=cfg.rstr_half_life, minp=cfg.rstr_min_periods),
            "DASTD": golden.golden_dastd(
                ret - market, T=cfg.dastd.window, hl=cfg.dastd.half_life,
                minp=cfg.dastd.min_periods),
            "CMRA": golden.golden_cmra(log_ret, T=cfg.cmra_window),
            "SIZE": np.log(g["total_mv"].to_numpy()),
        })
        dtv = g["turnover_rate"] / 100.0
        for name, spec in (("STOM", cfg.stom), ("STOQ", cfg.stoq),
                           ("STOA", cfg.stoa)):
            base = dtv.rolling(spec.window,
                               min_periods=spec.min_periods).sum()
            f[name] = np.log(base.replace(0, np.nan)).to_numpy()

        pb = g["pb"].to_numpy()
        f["BP"] = np.where(pb > 0, 1.0 / pb, np.nan)
        pe = g["pe_ttm"].to_numpy()
        f["ETOP"] = np.where(pe > 0, 1.0 / pe, np.nan)
        f["YOYProfit"] = g["q_profit_yoy"].to_numpy() / 100.0
        f["YOYSales"] = g["q_sales_yoy"].to_numpy() / 100.0
        mv = g["total_mv"].to_numpy()
        ncl = g["total_ncl"].to_numpy()
        book = g["total_hldr_eqy_inc_min_int"].to_numpy()
        mlev = (mv + ncl) / mv
        f["MLEV"] = np.where(np.isinf(mlev), np.nan, mlev)
        f["DTOA"] = g["debt_to_assets"].to_numpy()
        f["BLEV"] = np.where(book > 0, (book + ncl) / book, np.nan)

        # TTM cashflow: rolling-4 sum over DISTINCT reports, joined back by
        # report period (factor_calculator.py:392-412)
        rep = g.dropna(subset=["end_date"]).drop_duplicates("end_date")
        ttm_by_rep = dict(zip(
            rep["end_date"],
            rep["n_cashflow_act"].rolling(4, min_periods=4).sum()))
        ttm = g["end_date"].map(ttm_by_rep).to_numpy(float)
        f["CETOP"] = np.where((mv > 0) & (ttm > 0), ttm / mv, np.nan)
        frames.append(f)

    # per-date stages need group order == row order: sort by date first
    long = (pd.concat(frames, ignore_index=True)
            .sort_values(["trade_date", "ts_code"], kind="stable")
            .reset_index(drop=True))
    long["NLSIZE"] = golden.golden_nlsize(long[["trade_date", "SIZE"]])

    long = golden.golden_winsorize(long, list(SUB_FACTORS),
                                   n_std=cfg.winsorize_n_std)
    for name, comps, weights in cfg.composite:
        long[name] = golden.golden_composite(long, list(comps), list(weights))
    for target, regs in cfg.ortho_rules:
        long[target] = golden.golden_ortho(long, target, list(regs))

    out = long[["trade_date", "ts_code", "capital", "next_ret"]].rename(
        columns={"trade_date": "date", "ts_code": "stocknames",
                 "next_ret": "ret"})
    for src, dst in BARRA_OUTPUT_STYLES:
        out[dst] = long[src]
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(prog="crosscheck_golden")
    ap.add_argument("--profile", choices=["quick", "full"], default="full")
    ap.add_argument("--out", default="CROSSCHECK.json")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--platform", default="cpu", metavar="cpu|tpu",
                    help="JAX platform for the framework side (default cpu: "
                         "this is float64 QC — TPU has no native f64, and "
                         "an unpinned default would hang on a dead tunnel)")
    args = ap.parse_args(argv)

    import jax

    # config API, not the env var: site hooks that pre-register the TPU
    # plugin override JAX_PLATFORMS (tools/tpu_parity.py, same pitfall)
    jax.config.update("jax_platforms", args.platform)
    # the comparison is float64-vs-float64 (the golden side is numpy f64);
    # without x64 the framework would silently truncate to f32 and the
    # mean_abs_diff gate would measure precision, not agreement
    jax.config.update("jax_enable_x64", True)

    if args.profile == "quick":
        T, N, cfg = 130, 15, reduced_config()
    else:
        T, N, cfg = 700, 30, FactorConfig()

    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as tmp:
        store = PanelStore(os.path.join(tmp, "store"))
        synthetic_collections(store, T=T, N=N, n_industries=5,
                              seed=args.seed)

        prep = prepare_factor_inputs(store)
        ours, _ = run_factor_pipeline(
            prep.fields, prep.index_close, prep.industry_l1, prep.dates,
            prep.stocks,
            PipelineConfig(factors=cfg, dtype="float64"))
        ours = ours.copy()
        ours["date"] = pd.to_datetime(ours["date"])

        gold = golden_factor_table(store, cfg)

    styles = [dst for _, dst in BARRA_OUTPUT_STYLES]
    rep = crosscheck_factors(ours, gold, factors=styles + ["ret", "capital"],
                             date_col="date", code_col="stocknames")

    failures = []
    for fac, r in rep.iterrows():
        # gate on plain host floats: the asarray/.item() round-trip is also
        # the R5-visible proof that the timed span closes on materialized
        # parity stats, not pending device work
        r = {k: np.asarray(v, np.float64).item() for k, v in r.items()}
        if r["n_overlap"] == 0:
            failures.append(f"{fac}:no_overlap")
            continue
        if not r["pearson"] >= GATES["pearson"]:
            failures.append(f"{fac}:pearson")
        if not r["rank_corr"] >= GATES["rank_corr"]:
            failures.append(f"{fac}:rank_corr")
        if not r["mean_abs_diff"] <= GATES["mean_abs_diff"]:
            failures.append(f"{fac}:mean_abs_diff")
        if not min(r["coverage_ours"], r["coverage_ext"]) >= GATES["coverage"]:
            failures.append(f"{fac}:coverage")

    doc = {
        "tool": "tools/crosscheck_golden.py",
        "profile": args.profile,
        "workload": {"dates": T, "stocks": N, "seed": args.seed,
                     "windows": "reference defaults (252/504-day)"
                     if args.profile == "full" else "reduced CI windows"},
        "producers": {
            "ours": "store -> mfm_tpu prepare (vectorized searchsorted PIT "
                    "joins) -> FactorEngine (row-space scan kernels) -> "
                    "post (winsorize/composite/ortho) -> barra table",
            "external": "same store -> pandas merge_asof master "
                        "(tests/test_prepare._golden_master) -> per-stock "
                        "pandas rolling goldens (tests/golden.py) -> "
                        "per-date pandas post -> barra schema",
        },
        "gates": GATES,
        "per_factor": {
            fac: {k: (None if isinstance(v, float) and not np.isfinite(v)
                      else (float(v) if isinstance(v, (float, np.floating))
                            else int(v)))
                  for k, v in r.items()}
            for fac, r in rep.iterrows()},
        "failed": failures,
        "verdict": {"parity": not failures},
        "wall_s": round(time.perf_counter() - t0, 2),
        "real_data_procedure": (
            "mirror /root/reference/beta.ipynb cells 29-30: export the "
            "vendor factor table (jqdatasdk get_factor_values / Barra "
            "delivery) to CSV, then `python -m mfm_tpu.cli crosscheck "
            "--ours results/barra_data.csv --external vendor.csv` and hold "
            "the report to the gates above (rank_corr tolerates vendor "
            "winsorization/standardization differences; pearson and "
            "mean_abs_diff only bind when normalizations match)"),
    }
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps({"parity": not failures, "failed": failures,
                      "out": args.out, "wall_s": doc["wall_s"]}))
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
