#!/usr/bin/env python
"""Executable wrapper for the doctrine linter (mfm_tpu/lint.py).

Usage:  python tools/mfmlint.py [paths...] [--strict] [--baseline FILE]

Kept as a thin shim so the same pass is importable (`mfm_tpu.lint.run_lint`
in tests, `mfm-tpu lint` on the CLI) and runnable before any heavyweight
import: the linter pulls in neither jax nor numpy.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mfm_tpu.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
