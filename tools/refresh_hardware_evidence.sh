#!/bin/bash
# Re-capture ALL hardware-parity evidence on the current backend pair and
# merge the verdicts into one JSON.  Run from the repo root with the TPU
# tunnel up:
#
#   tools/refresh_hardware_evidence.sh [OUTDIR]
#
# Produces OUTDIR (default /tmp/hw_evidence) with the raw .npz captures and
# OUTDIR/summary.json holding the gate verdicts + the bench line:
#   - risk stack, float64, gate 1e-5   (the reference-precision contract)
#   - factor pipeline, float64, gate 1e-5
#   - risk stack, float32, per-stage budgets (tools/parity_budget.json)
#   - factor pipeline, float32, per-stage budgets
# The f32 budget gates bound the production fast path's drift between
# backends so a kernel/layout experiment cannot silently regress the tails.
# A dead tunnel fails fast at the probe instead of hanging.
set -e
cd "$(dirname "$0")/.."
out=${1:-/tmp/hw_evidence}
mkdir -p "$out"

timeout 90 python -c "import jax; assert jax.devices()[0].platform == 'tpu'" \
  || { echo "TPU backend not reachable — aborting" >&2; exit 1; }

python tools/tpu_parity.py run --x64 --out "$out/risk_tpu64.npz"
python tools/tpu_parity.py run --x64 --platform cpu --out "$out/risk_cpu64.npz"
python tools/tpu_parity.py compare "$out/risk_tpu64.npz" "$out/risk_cpu64.npz" \
  --gate 1e-5 > "$out/compare_risk64.json" || true

python tools/tpu_parity.py run --stage factors --x64 --out "$out/fac_tpu64.npz"
python tools/tpu_parity.py run --stage factors --x64 --platform cpu \
  --out "$out/fac_cpu64.npz"
python tools/tpu_parity.py compare "$out/fac_tpu64.npz" "$out/fac_cpu64.npz" \
  --gate 1e-5 > "$out/compare_factors64.json" || true

python tools/tpu_parity.py run --stage factors --out "$out/fac_tpu32.npz"
python tools/tpu_parity.py run --stage factors --platform cpu \
  --out "$out/fac_cpu32.npz"
python tools/tpu_parity.py compare "$out/fac_tpu32.npz" "$out/fac_cpu32.npz" \
  --budget tools/parity_budget.json > "$out/compare_factors32.json" || true

python tools/tpu_parity.py run --out "$out/risk_tpu32.npz"
python tools/tpu_parity.py run --platform cpu --out "$out/risk_cpu32.npz"
python tools/tpu_parity.py compare "$out/risk_tpu32.npz" "$out/risk_cpu32.npz" \
  --budget tools/parity_budget.json > "$out/compare_risk32.json" || true

python bench.py --profile-dir "$out/trace" > "$out/bench.json"

OUT="$out" python - <<'EOF'
import json, os, sys
out = os.environ["OUT"]
summary = {}
for key, name in (("risk_f64_gate_1e-5", "compare_risk64.json"),
                  ("factors_f64_gate_1e-5", "compare_factors64.json"),
                  ("factors_f32_budget", "compare_factors32.json"),
                  ("risk_f32_budget", "compare_risk32.json"),
                  ("bench", "bench.json")):
    with open(os.path.join(out, name)) as fh:
        recs = [json.loads(l) for l in fh.read().splitlines() if l.strip()]
    if not recs:
        # `|| true` above only tolerates a FAILING-GATE verdict (which still
        # prints JSON); an empty file means the compare died hard
        sys.exit(f"{name} is empty — the capture/compare errored; "
                 "no evidence recorded")
    summary[key] = recs
b = summary["bench"][-1]
if b.get("backend") != "tpu" or b.get("value") is None:
    sys.exit(f"bench record is not a TPU measurement: {b} — tunnel dropped "
             "mid-run?")
with open(os.path.join(out, "summary.json"), "w") as fh:
    json.dump(summary, fh, indent=1)
print(os.path.join(out, "summary.json"))
EOF
