#!/bin/bash
# Probe the TPU tunnel until it answers, then capture everything this round
# still wants from real hardware, in priority order:
#
#   1. tools/refresh_hardware_evidence.sh  (parity gates + config-1 bench —
#      re-captures PARITY_TPU.json under the current kernel defaults)
#   2. bench.py --config alla   (the scan-path all-A number, BASELINE.md row 4)
#   3. bench.py --config alpha  (config-5 refresh)
#   4. bench.py --config riskmodel  (daily_update_latency on real hardware —
#      the CPU-host 242x update-vs-rebuild ratio in docs/QUICKSTART.md wants
#      a TPU number; the update step is eigen-bound so expect it to widen)
#
# Outputs land in OUTDIR (default /tmp/tpu_watch); run `git diff` afterwards —
# refresh_hardware_evidence.sh edits PARITY_TPU.json in place when gates pass.
#
#   tools/tpu_watch.sh [OUTDIR] [MAX_WAIT_S]
set -uo pipefail
cd "$(dirname "$0")/.."
out=${1:-/tmp/tpu_watch}
max_wait=${2:-28800}
mkdir -p "$out"

# hardware evidence from a doctrine-violating tree is not evidence — gate
# before burning hours waiting on the tunnel (no -e here: abort explicitly)
python tools/mfmlint.py --strict \
  || { echo "mfmlint violations — fix or baseline before capturing" >&2
       exit 1; }

start=$(date +%s)
while true; do
  if timeout 90 python -c \
      "import jax; assert jax.devices()[0].platform in ('tpu', 'axon')" \
      2>/dev/null; then
    echo "tunnel up at $(date -Is)" | tee "$out/status"
    break
  fi
  now=$(date +%s)
  if (( now - start > max_wait )); then
    echo "gave up after ${max_wait}s" | tee "$out/status"
    exit 1
  fi
  sleep 60
done

bash tools/refresh_hardware_evidence.sh > "$out/refresh.log" 2>&1 \
  || echo "refresh_hardware_evidence FAILED (see refresh.log)" >> "$out/status"
python bench.py --config alla 2> "$out/alla.err" | tail -1 > "$out/config4_alla.json" \
  || echo "alla bench FAILED (see alla.err)" >> "$out/status"
# cold-vs-cache-hit discipline: a machine that benched before has a warm
# ~/.cache/mfm_tpu/xla, which would silently turn the "cold" compile_s into
# a deserialization number — so the cold run gets a FRESH cache dir (cold
# compile, populates it) and the rerun reuses that same dir (true cache hit)
fresh_cache="$out/xla_cache_fresh"
rm -rf "$fresh_cache"
MFM_COMPILATION_CACHE="$fresh_cache" python bench.py --config alpha \
  2> "$out/alpha.err" | tail -1 > "$out/config5_alpha.json" \
  || echo "alpha bench FAILED (see alpha.err)" >> "$out/status"
# same cold-compile discipline as the alpha bench above: its own fresh
# cache dir, so a previously-warmed ~/.cache/mfm_tpu/xla can't turn this
# compile_s into a silent deserialization number
fresh_cache_alla="$out/xla_cache_fresh_alla"
rm -rf "$fresh_cache_alla"
MFM_COMPILATION_CACHE="$fresh_cache_alla" python bench.py --config alpha_alla \
  2> "$out/alpha_alla.err" | tail -1 > "$out/config5_alpha_alla.json" \
  || echo "alpha_alla bench FAILED (see alpha_alla.err)" >> "$out/status"
# cache-hit rerun: same config + same cache dir in a FRESH process —
# compile_s now measures the persistent-cache deserialization path
MFM_COMPILATION_CACHE="$fresh_cache" python bench.py --config alpha \
  2> "$out/alpha2.err" | tail -1 > "$out/config5_alpha_rerun.json" \
  || echo "alpha cache-hit rerun FAILED (see alpha2.err)" >> "$out/status"
# kernel A/B queue: v_compose2 promotion decision + NW scan-vs-associative
python tools/kernel_ab.py > "$out/kernel_ab.log" 2>&1 \
  || echo "kernel_ab FAILED (see kernel_ab.log)" >> "$out/status"
# incremental update path: daily_update_latency / update_speedup_vs_e2e on
# real hardware (QUICKSTART's daily-serving table carries the CPU-host number)
python bench.py --config riskmodel 2> "$out/riskmodel.err" \
  | tail -1 > "$out/config1_riskmodel_update.json" \
  || echo "riskmodel update bench FAILED (see riskmodel.err)" >> "$out/status"
# a capture that fell back to CPU is NOT evidence — flag it
grep -L '"backend": "tpu"' "$out"/config*.json 2>/dev/null \
  | sed 's/$/: backend is not tpu/' >> "$out/status"
echo "capture finished at $(date -Is) (check status lines above for failures)" \
  >> "$out/status"
cat "$out"/config*.json
