"""Hardware parity gate: full risk pipeline, TPU vs CPU/LAPACK reference.

The test suite proves parity of every kernel against loopy NumPy goldens on
CPU; this tool closes the remaining gap — that the *TPU* execution path
(Pallas Jacobi eigh, MXU matmuls, fused XLA programs) produces the same
numbers as the CPU path on the full CSI300-shaped workload.  Run it twice,
then compare:

    python tools/tpu_parity.py run --out /tmp/parity_tpu.npz           # on TPU
    python tools/tpu_parity.py run --platform cpu --out /tmp/parity_cpu.npz
    python tools/tpu_parity.py compare /tmp/parity_tpu.npz /tmp/parity_cpu.npz

``run --stage factors`` captures the other half of the workload (the
16-factor pipeline + post-processing on a synthetic market panel) with the
same compare/gate machinery.

(use ``--platform cpu``, not ``JAX_PLATFORMS=cpu``: a site hook that
pre-registers the TPU plugin wins over the env var, and the compare would
silently diff TPU against itself — the verdict line's ``platforms`` field is
the check that both backends really ran)

``compare`` prints one JSON line per stage with max/median relative
difference over valid dates and exits nonzero if any stage exceeds
``--gate`` (default 1e-5, the framework's parity contract vs the float64
reference; TPU-vs-CPU f32 differences sit well below it).  For the f32
fast path — where drift is measured, not subject to the 1e-5 contract —
``compare --budget tools/parity_budget.json`` gates each stage against its
own frozen max_rel/median_rel ceiling instead, so a kernel or layout
experiment cannot silently regress the tails.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run(args):
    import jax

    if args.platform:
        # env JAX_PLATFORMS loses to site hooks that pre-register the TPU
        # plugin (same pitfall as cli.py --platform); the config API wins
        jax.config.update("jax_platforms", args.platform)
    if args.x64:
        # the 1e-5 contract is defined against the float64 reference; x64
        # runs (XLA emulates f64 on TPU) prove the TPU *path* is correct,
        # while f32 runs measure the fast path's precision drift
        jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp

    T, N, P, Q, M = args.dates, args.stocks, args.industries, args.styles, args.sims
    K = 1 + P + Q
    dtype = jnp.float64 if args.x64 else jnp.float32

    if args.stage == "factors":
        # the OTHER half of the workload: the 16-factor pipeline + post
        # (rolling kernels, row-space packing, cross-sectional post ops)
        from mfm_tpu.config import FactorConfig
        from mfm_tpu.data.synthetic import (
            panel_to_engine_fields, synthetic_market_panel,
        )
        from mfm_tpu.factors.engine import FactorEngine

        data = synthetic_market_panel(T=T, N=N, n_industries=P, seed=0)
        fields = panel_to_engine_fields(data, dtype)
        eng = FactorEngine(fields, jnp.asarray(data["index_close"], dtype),
                           config=FactorConfig())
        out = eng.run()
        np.savez_compressed(
            args.out, platform=np.array(jax.devices()[0].platform),
            stage=np.array("factors"),
            **{k: np.asarray(v) for k, v in out.items()})
        print(json.dumps({"platform": str(jax.devices()[0].platform),
                          "stage": "factors", "out": args.out}))
        return

    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.eigen import simulated_eigen_covs
    from mfm_tpu.models.risk_model import RiskModel
    from __graft_entry__ import _synthetic_risk_inputs

    inputs = _synthetic_risk_inputs(T, N, P, Q, dtype=dtype, seed=0)
    cfg = RiskModelConfig(eigen_n_sims=M, eigen_sim_length=T)
    # identical draws on both backends: jax.random is backend-deterministic
    sim_covs = simulated_eigen_covs(jax.random.key(0), K, T, M, dtype)

    rm = RiskModel(*inputs, n_industries=P, config=cfg)
    # declaring sim_length runs the PRODUCTION eigen path (auto sweep cap,
    # unsorted Pallas sim eighs) rather than the conservative full-sweep
    # fallback — the gate must cover what ships (round-1 advisor finding)
    out = rm.run(sim_covs=sim_covs, sim_length=T)
    np.savez_compressed(
        args.out,
        platform=np.array(jax.devices()[0].platform),
        stage=np.array("risk"),
        factor_ret=np.asarray(out.factor_ret),
        r2=np.asarray(out.r2),
        nw_cov=np.asarray(out.nw_cov),
        nw_valid=np.asarray(out.nw_valid),
        eigen_cov=np.asarray(out.eigen_cov),
        eigen_valid=np.asarray(out.eigen_valid),
        vr_cov=np.asarray(out.vr_cov),
        lamb=np.asarray(out.lamb),
    )
    print(json.dumps({"platform": str(jax.devices()[0].platform),
                      "out": args.out}))


#: every capture kind's full stage checklist — a capture missing any of
#: these must fail loudly; a truncated pair agreeing with itself is not
#: parity (risk keys: RiskModelOutputs; factors keys: FactorEngine.run()
#: with the default FactorConfig)
REQUIRED_STAGES = {
    "risk": {"factor_ret", "r2", "nw_cov", "nw_valid", "eigen_cov",
             "eigen_valid", "vr_cov", "lamb"},
    "factors": {"SIZE", "BETA", "HSIGMA", "RSTR", "DASTD", "CMRA", "NLSIZE",
                "BP", "STOM", "STOQ", "STOA", "CETOP", "ETOP", "YOYProfit",
                "YOYSales", "MLEV", "DTOA", "BLEV", "volatility",
                "liquidity", "earnings", "growth", "leverage", "ret",
                "log_ret"},
}


def _load_budget(path, kind):
    """Per-stage drift budgets (``tools/parity_budget.json``): frozen from
    the measured f32 tails so kernel experiments cannot silently regress
    accuracy.  Each capture kind's section must carry a ``default`` entry —
    a budget file that silently skipped unknown stages would let a NEW
    stage regress ungated."""
    with open(path) as fh:
        all_budgets = json.load(fh)
    section = all_budgets.get(kind)
    if not isinstance(section, dict) or "default" not in section:
        raise SystemExit(
            f"budget file {path} has no '{kind}' section with a 'default' "
            "entry — nothing gated")
    return section


def _compare(args):
    a, b = np.load(args.a), np.load(args.b)

    def _kind(f):
        # pre-marker captures are risk-stage by construction
        return str(f["stage"]) if "stage" in f.files else "risk"

    def _data_files(f):
        # the marker is metadata, not a stage: a legacy capture (no marker)
        # must stay comparable against a fresh one of the same kind
        return sorted(k for k in f.files if k != "stage")

    if _kind(a) != _kind(b):
        raise SystemExit(f"incomparable captures: stage {_kind(a)} vs "
                         f"{_kind(b)}")
    if _data_files(a) != _data_files(b):
        raise SystemExit(f"incomparable captures: {_data_files(a)} vs "
                         f"{_data_files(b)}")
    kind = _kind(a)
    missing = REQUIRED_STAGES[kind] - set(a.files)
    if missing:
        # a gate over a truncated capture must not pass
        raise SystemExit(f"{kind} capture is missing stage(s) "
                         f"{sorted(missing)} — nothing gated")
    budget = _load_budget(args.budget, kind) if args.budget else None
    # stage-agnostic diff: every saved array is a stage (validity masks are
    # exact-matched below) — the same compare serves risk and factor runs
    stages = sorted(k for k in a.files
                    if k not in ("platform", "stage")
                    and not k.endswith("_valid"))
    failed = []
    for name in stages:
        x, y = a[name], b[name]
        m = np.isfinite(x) & np.isfinite(y)
        if not (np.isfinite(x) == np.isfinite(y)).all():
            failed.append(name + ":finiteness")
        # a stage can be all-invalid (short runs where no date is valid) —
        # emit n=0 rather than crashing on an empty reduction
        scale = max(np.abs(y[m]).max(), 1e-30) if m.any() else 1.0
        d = np.abs(x[m] - y[m]) / scale
        rec = {"stage": name, "n": int(m.sum()),
               "max_rel": float(d.max()) if d.size else 0.0,
               "median_rel": float(np.median(d)) if d.size else 0.0}
        if budget is not None:
            lim = budget.get(name, budget["default"])
            rec["budget"] = lim
            if rec["max_rel"] > lim["max_rel"]:
                failed.append(name + ":max_rel")
            if (lim.get("median_rel") is not None
                    and rec["median_rel"] > lim["median_rel"]):
                failed.append(name + ":median_rel")
        elif rec["max_rel"] > args.gate:
            failed.append(name)
        print(json.dumps(rec))
    for name in (k for k in a.files if k.endswith("_valid")):
        if not (a[name] == b[name]).all():
            failed.append(name)
    plats = [str(a["platform"]), str(b["platform"])]
    if plats[0] == plats[1]:
        # same backend twice proves determinism, not hardware parity
        failed.append("platforms:identical")
    verdict = {"parity": not failed, "failed": failed, "platforms": plats}
    if budget is not None:
        verdict["budget"] = args.budget
    else:
        verdict["gate"] = args.gate
    print(json.dumps(verdict))
    sys.exit(1 if failed else 0)


def main(argv=None):
    ap = argparse.ArgumentParser(prog="tpu_parity")
    sub = ap.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("run")
    r.add_argument("--out", required=True)
    r.add_argument("--dates", type=int, default=700)
    r.add_argument("--stocks", type=int, default=300)
    r.add_argument("--industries", type=int, default=31)
    r.add_argument("--styles", type=int, default=10)
    r.add_argument("--sims", type=int, default=40)
    r.add_argument("--stage", choices=["risk", "factors"], default="risk",
                   help="which half of the workload to capture: the risk "
                        "covariance stack (default) or the 16-factor "
                        "pipeline + post-processing")
    r.add_argument("--platform", default=None, metavar="cpu|tpu",
                   help="pin the JAX platform via the config API (the env "
                        "var loses to site hooks that pre-register a plugin)")
    r.add_argument("--x64", action="store_true",
                   help="run in float64 (the reference's precision; XLA "
                        "emulates f64 on TPU — slow but exact)")
    r.set_defaults(fn=_run)
    c = sub.add_parser("compare")
    c.add_argument("a")
    c.add_argument("b")
    c.add_argument("--gate", type=float, default=1e-5)
    c.add_argument("--budget", default=None, metavar="BUDGET_JSON",
                   help="per-stage drift budgets (tools/parity_budget.json) "
                        "instead of the flat --gate: each stage must meet "
                        "its own max_rel AND median_rel ceiling, so kernel "
                        "experiments cannot silently regress the f32 tails")
    c.set_defaults(fn=_compare)
    args = ap.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
