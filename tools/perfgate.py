#!/usr/bin/env python
"""Perf-regression sentinel over the BENCH_r*.json trajectory.

The driver records one bench JSON per round (``BENCH_r*.json`` at the repo
root, each a wrapper whose ``parsed`` field holds the record bench.py
printed).  This gate compares a freshly produced record against that
trajectory and exits non-zero when a key serving metric regressed past its
tolerance band — the teeth behind "don't ship a slower build".

Metrics and bands (overridable per metric with ``--tol``):

- lower-is-better: e2e wall (``value``), ``daily_update_latency_s``,
  ``guarded_update_latency_s``, the eigen-optimisation walls
  (``eigen_stage_wall_s`` — the unfused eigen stage;
  ``eigen_update_latency_s`` — the incremental single-date append at full
  Monte-Carlo fidelity), and the two overhead fractions
  (``telemetry_overhead_frac`` / ``tracing_overhead_frac``, which also get
  an absolute floor at the documented 1% budget — a 0.0002 -> 0.0004 jitter
  doubles the fraction without meaning anything).
- higher-is-better: ``portfolios_per_sec``, ``scenarios_per_sec``.

The baseline per metric is the BEST value in the trajectory under the
``(backend, universe_n)`` key (min for walls, max for throughputs) —
comparing a CPU-fallback run against a TPU round, or an N=5000 all-A wall
against N=300 CSI300 history, would only ever cry wolf, so records from a
different backend or universe are skipped.  Pre-PR-11 records carry no
``universe_n``; :func:`universe_n` backfills it from the metric name.
A record with no comparable baseline passes (you cannot regress from
nothing), but the report says so.

Used three ways: ``python bench.py --compare`` gates the record it just
produced; ``tools/bench_all.sh`` gates the riskmodel record of a full
sweep; ``python tools/perfgate.py RECORD.json`` gates any saved record.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: metric -> (direction, relative tolerance band, absolute floor|None).
#: A lower-is-better metric regresses when current > best * (1 + tol) AND
#: current > floor; higher-is-better when current < best * (1 - tol).
METRIC_SPECS = {
    "e2e_wall_s": ("lower", 0.25, None),
    "daily_update_latency_s": ("lower", 0.25, None),
    "guarded_update_latency_s": ("lower", 0.25, None),
    "eigen_stage_wall_s": ("lower", 0.25, None),
    "eigen_update_latency_s": ("lower", 0.25, None),
    "telemetry_overhead_frac": ("lower", 0.50, 0.01),
    "tracing_overhead_frac": ("lower", 0.50, 0.01),
    "portfolios_per_sec": ("higher", 0.20, None),
    "scenarios_per_sec": ("higher", 0.20, None),
    "sweep_scenarios_per_sec": ("higher", 0.20, None),
    "sweep_speedup_x": ("higher", 0.20, None),
    "minvol_portfolios_per_sec_b100": ("higher", 0.20, None),
    "minvol_portfolios_per_sec_b10000": ("higher", 0.20, None),
    "reverse_scenarios_per_sec": ("higher", 0.20, None),
    "fleet_qps": ("higher", 0.20, None),
    "fleet_p99_latency_s": ("lower", 0.30, 0.05),
    "fleet_mh_qps": ("higher", 0.20, None),
    "coalesce_batch_fill_frac": ("higher", 0.20, None),
    "cached_qps": ("higher", 0.20, None),
    "cache_hit_rate": ("higher", 0.05, None),
    "cache_p99_latency_s": ("lower", 0.30, 0.05),
}


def extract_metrics(rec) -> dict:
    """Flatten one bench record into the gate's metric namespace.  Unknown
    or failed records (value None) yield an empty/partial dict — the gate
    skips what it cannot read rather than failing the build on a malformed
    round."""
    out = {}
    if not isinstance(rec, dict):
        return out
    metric = rec.get("metric")
    if metric in ("csi300_riskmodel_e2e_wall", "riskmodel_e2e_wall",
                  "alla_full_pipeline_wall"):
        # the three riskmodel-wall families share one metric namespace;
        # universe_n() keeps their baselines apart
        out["e2e_wall_s"] = rec.get("value")
        for k in ("daily_update_latency_s", "guarded_update_latency_s",
                  "eigen_stage_wall_s", "eigen_update_latency_s",
                  "telemetry_overhead_frac", "tracing_overhead_frac"):
            out[k] = rec.get(k)
    elif metric == "portfolio_query_throughput":
        out["portfolios_per_sec"] = rec.get("value")
    elif metric == "scenario_throughput":
        out["scenarios_per_sec"] = rec.get("value")
    elif metric == "sweep_throughput":
        out["sweep_scenarios_per_sec"] = rec.get("value")
        out["sweep_speedup_x"] = rec.get("speedup_x")
    elif metric == "grad_throughput":
        for k in ("minvol_portfolios_per_sec_b100",
                  "minvol_portfolios_per_sec_b10000",
                  "reverse_scenarios_per_sec"):
            out[k] = rec.get(k)
    elif metric == "fleet_serving_throughput":
        for k in ("fleet_qps", "fleet_p99_latency_s",
                  "coalesce_batch_fill_frac"):
            out[k] = rec.get(k)
    elif metric == "fleet_mh_serving_throughput":
        # only gated when the kill drill survived — a QPS number from a
        # run whose fleet dropped requests is not evidence of anything
        if (rec.get("kill_drill") or {}).get("survived"):
            out["fleet_mh_qps"] = rec.get("fleet_mh_qps")
    elif metric == "cache_serving_throughput":
        for k in ("cached_qps", "cache_hit_rate",
                  "cache_p99_latency_s"):
            out[k] = rec.get(k)
    return {k: v for k, v in out.items()
            if isinstance(v, (int, float)) and v == v}


def universe_n(rec) -> int | None:
    """The stock-count key a record's baselines are bucketed under.

    Records written before PR 11 carry no ``universe_n``; every one of
    them was CSI300-shaped (N=300) except the alla pipeline record, which
    was N=5000 by construction — so absence backfills from the metric
    name.  Returns None for non-universe records (query/scenario
    throughputs), which gate across all universes as before."""
    if not isinstance(rec, dict):
        return None
    n = rec.get("universe_n")
    if isinstance(n, int):
        return n
    metric = rec.get("metric")
    if metric in ("csi300_riskmodel_e2e_wall", "riskmodel_e2e_wall"):
        return 300
    if metric == "alla_full_pipeline_wall":
        return 5000
    return None


def _unwrap(obj):
    """BENCH_r*.json files are driver wrappers ``{"n", "cmd", "rc",
    "parsed", "tail"}``; bare records (e.g. a saved ``bench.py`` line) are
    accepted as-is."""
    if isinstance(obj, dict) and "metric" in obj:
        return obj
    if isinstance(obj, dict):
        parsed = obj.get("parsed")
        if isinstance(parsed, dict) and "metric" in parsed:
            return parsed
    return None


def load_trajectory(root: str = REPO) -> list:
    """All readable BENCH_r*.json records under ``root``, oldest first.
    Unparseable files are skipped (a torn round must not wedge the gate)."""
    out = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path, encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        rec = _unwrap(obj)
        if rec is not None:
            out.append({"name": os.path.basename(path), "record": rec})
    return out


def gate_record(rec, trajectory, tolerances=None) -> dict:
    """Compare ``rec`` against the same-backend trajectory.  Returns a
    verdict dict: ``checks`` (every metric compared), ``regressions`` (the
    failing subset), ``skipped`` (metrics with no baseline or no current
    value), ``backend``, ``baseline_runs``."""
    tolerances = tolerances or {}
    backend = rec.get("backend") if isinstance(rec, dict) else None
    uni = universe_n(rec)
    current = extract_metrics(rec)

    # best value per metric under the (backend, universe_n) key (+ where
    # it came from) — an N=5000 wall must never be held to N=300 history
    best = {}
    runs = set()
    for entry in trajectory:
        base = entry["record"]
        if base.get("backend") != backend:
            continue
        if universe_n(base) != uni:
            continue
        for k, v in extract_metrics(base).items():
            direction = METRIC_SPECS[k][0]
            better = (v < best[k][0] if direction == "lower"
                      else v > best[k][0]) if k in best else True
            if better:
                best[k] = (v, entry["name"])
            runs.add(entry["name"])

    checks, skipped = [], []
    for name, (direction, tol, floor) in METRIC_SPECS.items():
        cur = current.get(name)
        if cur is None:
            skipped.append({"metric": name, "reason": "not in this record"})
            continue
        if name not in best:
            where = (f"no {backend or 'unknown'}-backend baseline in "
                     "trajectory")
            if uni is not None:
                where += f" at universe_n={uni}"
            skipped.append({"metric": name, "reason": where})
            continue
        base_v, base_run = best[name]
        tol = float(tolerances.get(name, tol))
        if direction == "lower":
            limit = base_v * (1.0 + tol)
            regressed = cur > limit and (floor is None or cur > floor)
        else:
            limit = base_v * (1.0 - tol)
            regressed = cur < limit
        checks.append({"metric": name, "direction": direction,
                       "current": cur, "baseline": base_v,
                       "baseline_run": base_run, "limit": round(limit, 6),
                       "tolerance": tol, "floor": floor,
                       "regressed": bool(regressed)})
    return {"backend": backend, "universe_n": uni, "checks": checks,
            "regressions": [c for c in checks if c["regressed"]],
            "skipped": skipped, "baseline_runs": sorted(runs)}


def format_report(verdict: dict) -> str:
    uni = verdict.get("universe_n")
    lines = [f"perfgate: backend={verdict['backend'] or 'unknown'} "
             + (f"universe_n={uni} " if uni is not None else "")
             + f"baselines={','.join(verdict['baseline_runs']) or 'none'}"]
    for c in verdict["checks"]:
        arrow = "<=" if c["direction"] == "lower" else ">="
        status = "REGRESSED" if c["regressed"] else "ok"
        lines.append(
            f"  [{status:9s}] {c['metric']}: {c['current']} "
            f"(want {arrow} {c['limit']}; best {c['baseline']} "
            f"from {c['baseline_run']}, tol {c['tolerance']:.0%})")
    for s in verdict["skipped"]:
        lines.append(f"  [skipped  ] {s['metric']}: {s['reason']}")
    n = len(verdict["regressions"])
    lines.append(f"perfgate: {'FAIL — %d regression(s)' % n if n else 'PASS'}"
                 f" ({len(verdict['checks'])} compared,"
                 f" {len(verdict['skipped'])} skipped)")
    return "\n".join(lines)


def _parse_tols(pairs) -> dict:
    out = {}
    for p in pairs or ():
        name, _, val = p.partition("=")
        if name not in METRIC_SPECS:
            raise SystemExit(f"perfgate: unknown metric {name!r} "
                             f"(known: {', '.join(sorted(METRIC_SPECS))})")
        try:
            out[name] = float(val)
        except ValueError:
            raise SystemExit(f"perfgate: bad tolerance {p!r} "
                             "(want metric=frac, e.g. e2e_wall_s=0.3)")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="gate a bench record against the BENCH_r*.json "
                    "trajectory; exit 1 on regression")
    ap.add_argument("record", help="path to a bench JSON record (bare or "
                                   "driver-wrapped), or '-' for stdin")
    ap.add_argument("--root", default=REPO, metavar="DIR",
                    help="directory holding BENCH_r*.json (default: repo "
                         "root)")
    ap.add_argument("--tol", action="append", metavar="METRIC=FRAC",
                    help="override one metric's relative tolerance band "
                         "(repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as JSON instead of the text "
                         "report")
    args = ap.parse_args(argv)

    if args.record == "-":
        obj = json.load(sys.stdin)
    else:
        with open(args.record, encoding="utf-8") as f:
            obj = json.load(f)
    rec = _unwrap(obj)
    if rec is None:
        print("perfgate: record has no 'metric' field (not a bench record)",
              file=sys.stderr)
        return 2

    verdict = gate_record(rec, load_trajectory(args.root),
                          tolerances=_parse_tols(args.tol))
    if args.json:
        print(json.dumps(verdict, sort_keys=True))
    else:
        print(format_report(verdict))
    return 1 if verdict["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
