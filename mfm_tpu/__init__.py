"""mfm_tpu — a TPU-native (JAX/XLA/pjit/Pallas) multi-factor equity risk-model framework.

A from-scratch re-design of the capabilities of the reference repo
``Izumighj/LLM-Driven-Multi-factor-Model`` (a serial pandas/statsmodels Barra
CNE/USE4-style pipeline): dense masked ``(dates, stocks)`` panels, vmapped
rolling-window and cross-sectional kernels, and the date/stock axes sharded
across a TPU mesh.

Layout
------
- :mod:`mfm_tpu.panel`     — the dense masked Panel abstraction (long <-> dense)
- :mod:`mfm_tpu.ops`       — masked cross-sectional / rolling / regression kernels
- :mod:`mfm_tpu.factors`   — the 16 Barra sub-factors + post-processing + FactorEngine
- :mod:`mfm_tpu.models`    — the risk model (cross-sectional WLS, Newey-West,
                             eigenfactor adjustment, vol-regime adjustment, bias stats)
- :mod:`mfm_tpu.parallel`  — mesh construction, sharding specs, multi-host helpers
- :mod:`mfm_tpu.data`      — host-side IO: CSV/parquet loaders, point-in-time joins,
                             synthetic data, incremental ETL, artifacts,
                             optional Tushare/Mongo adapters
- :mod:`mfm_tpu.alpha`     — alpha-expression DSL, batch evaluation, scoring,
                             correlation-capped selection
- :mod:`mfm_tpu.utils`     — observability, crosscheck, model-health report
"""

from mfm_tpu.config import (
    FactorConfig,
    RiskModelConfig,
    PipelineConfig,
)
from mfm_tpu.panel import Panel
from mfm_tpu.models.risk_model import RiskModel
from mfm_tpu.factors.engine import FactorEngine

__version__ = "0.1.0"

__all__ = [
    "Panel",
    "RiskModel",
    "FactorEngine",
    "FactorConfig",
    "RiskModelConfig",
    "PipelineConfig",
]
