"""Pallas TPU kernel: batched Jacobi eigh for small symmetric matrices.

The pure-JAX Brent-Luk version (:mod:`mfm_tpu.ops.eigh`) is HBM-bound: every
rotation round re-reads the whole (B, n, n) batch from HBM (~410 rounds x 2GB
for the CSI300 eigen stage).  This kernel keeps a block of matrices resident
in VMEM for the *entire* decomposition: layout (n, n, LANES) with the batch
in the lane dimension, so every rotation is dense (sublane, lane) VPU work.

Brent-Luk parallel ordering in its kernel-friendly fixed-permutation form:
matrices live in a permuted basis where every round rotates adjacent pairs
(2i, 2i+1) — pair quantities are *static* element picks, rotations are
reshape + elementwise, and the move to the next pairing is one constant
permutation applied as static row/column restacking.  No dynamic indexing,
no scatter, no MXU, no captured array constants; the fori body is a single
~200-op round shared by all sweeps.

Target workload: the eigenfactor adjustment's (date x sim) Monte-Carlo batch
(``mfm/utils.py:64-92``) — 139k 42x42 eighs for CSI300.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mfm_tpu.ops.eigh import _brent_luk_perms, _sweeps_for, canonicalize_signs

LANES = 128


def _make_kernel(n: int, sweeps: int, dtype):
    b0, pi = _brent_luk_perms(n)  # python int lists, n is static
    h = n // 2
    tiny = float(np.finfo(np.float32).tiny * 100)
    # pi has order n-1 (asserted in _brent_luk_perms' dev check), so after
    # sweeps*(n-1) rounds the basis is back to b0: slot j holds original
    # index b0[j] regardless of sweep count.  Outputs are emitted through
    # inv = argsort(b0) so slot i of w/V corresponds to ORIGINAL index i —
    # for near-diagonal input (the eigen Monte-Carlo's G, diagonal ~
    # ascending D0) the eigenvalue tracking direction i lands at slot i,
    # which the caller's per-slot statistics rely on (models/eigen.py pairs
    # slot i with D0[i]).
    inv = sorted(range(n), key=b0.__getitem__)

    def perm_rows(x, perm):
        return jnp.stack([x[i] for i in perm], axis=0)

    def perm_cols(x, perm):
        return jnp.stack([x[:, i] for i in perm], axis=1)

    def rotated(idx):
        return idx // 2, idx % 2 == 0

    def _angles(x):
        """Per-pair Jacobi angles (c, s) from the current adjacent pairs."""
        app = jnp.stack([x[2 * i, 2 * i] for i in range(h)])        # (h, L)
        apq = jnp.stack([x[2 * i, 2 * i + 1] for i in range(h)])
        aqq = jnp.stack([x[2 * i + 1, 2 * i + 1] for i in range(h)])

        small = jnp.abs(apq) <= tiny
        tau = (aqq - app) / jnp.where(small, 1.0, 2.0 * apq)
        t = jnp.sign(tau) / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        t = jnp.where(tau == 0, 1.0, t)
        t = jnp.where(small, 0.0, t)
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c
        return c, s

    # Rotation and the fixed basis permutation to the next pairing are fused:
    # each output row/column is the rotated row/column pi[.], written directly
    # into its permuted slot — one restack per array per round instead of a
    # rotation pass plus a permutation pass.

    def rot_rows(arrs, c, s):
        """perm_rows(J^T a, pi) for every array, one fused restack each."""
        outs = [[] for _ in arrs]
        for r in range(n):
            i, even = rotated(pi[r])
            for out, arr in zip(outs, arrs):
                a, b = arr[2 * i], arr[2 * i + 1]   # (n, L)
                out.append(c[i] * a - s[i] * b if even
                           else s[i] * a + c[i] * b)
        return [jnp.stack(out, axis=0) for out in outs]

    def rot_cols(arrs, c, s):
        """perm_cols(a J, pi) for every array (row perm commutes with the
        column rotation, so this composes with rot_rows either way)."""
        outs = [[] for _ in arrs]
        for q in range(n):
            i, even = rotated(pi[q])
            for out, arr in zip(outs, arrs):
                a, b = arr[:, 2 * i], arr[:, 2 * i + 1]
                out.append(c[i] * a - s[i] * b if even
                           else s[i] * a + c[i] * b)
        return [jnp.stack(out, axis=1) for out in outs]

    def one_round(_, carry):
        x, v = carry
        c, s = _angles(x)
        (y,) = rot_rows([x], c, s)
        x, v = rot_cols([y, v], c, s)
        return (x, v)

    def one_round_vt(_, carry):
        # Same rotation, but the eigenvector accumulator is stored TRANSPOSED
        # (vt[j, k] = V[k, j]): V <- V J becomes vt <- perm_rows(J' vt, pi) —
        # a rows pass with contiguous (n, L) tile-set slices, instead of the
        # strided column slices of one_round's fused v-cols update.  Purely an
        # internal layout choice of the weighted kernel (V never leaves VMEM
        # there); A/B-able on hardware via ``vt_rows``.
        x, vt = carry
        c, s = _angles(x)
        y, vt = rot_rows([x, vt], c, s)
        (x,) = rot_cols([y], c, s)
        return (x, vt)

    def compose2_rows(vt, c1, s1, c2, s2):
        """Two consecutive vt row passes fused into ONE restack.

        The vt update has no feedback into the angle computation, so two
        rounds' rotations compose: with P the fixed inter-round position
        permutation and J'_r the r-th paired rotation,
        ``vt2 = P(J2' P(J1' vt))`` — each output row is a 4-term
        combination of input rows at STATIC indices.  Same FLOPs as the
        two separate passes, one restack instead of two; an A/B candidate
        (``v_compose2``) for the pass-overhead share of the kernel.
        """
        def mid(q):
            # row q of perm_rows(J1' vt, pi) == (J1' vt)[pi[q]]
            j, even = rotated(pi[q])
            a, b = vt[2 * j], vt[2 * j + 1]     # (n, L) tile sets
            return c1[j] * a - s1[j] * b if even else s1[j] * a + c1[j] * b

        # each mid row feeds two output rows — compute once (unstacked:
        # these stay loose vregs, only the final result is restacked)
        mids = [mid(q) for q in range(n)]
        out = []
        for r in range(n):
            i2, even2 = rotated(pi[r])
            m1, m2 = mids[2 * i2], mids[2 * i2 + 1]
            out.append(c2[i2] * m1 - s2[i2] * m2 if even2
                       else s2[i2] * m1 + c2[i2] * m2)
        return jnp.stack(out, axis=0)

    def one_pair_vt(_, carry):
        # two rounds per iteration: A takes its usual per-round row+col
        # passes (angles feed back through A), vt takes one composed pass
        x, vt = carry
        c1, s1 = _angles(x)
        (y,) = rot_rows([x], c1, s1)
        (x,) = rot_cols([y], c1, s1)
        c2, s2 = _angles(x)
        (y,) = rot_rows([x], c2, s2)
        (x,) = rot_cols([y], c2, s2)
        return (x, compose2_rows(vt, c1, s1, c2, s2))

    def _decompose(a_ref, vt_rows=False, v_compose2=False):
        x = a_ref[0]                          # (n, n, L)
        i3 = jax.lax.broadcasted_iota(jnp.int32, (n, n, LANES), 0)
        j3 = jax.lax.broadcasted_iota(jnp.int32, (n, n, LANES), 1)
        v = jnp.where(i3 == j3, jnp.asarray(1.0, dtype), jnp.asarray(0.0, dtype))
        # move into the interleaved basis
        x = perm_cols(perm_rows(x, b0), b0)
        rounds = sweeps * (n - 1)
        if v_compose2:
            v = perm_rows(v, b0)  # identity' = identity: vt0 = (v0)'
            carry = jax.lax.fori_loop(0, rounds // 2, one_pair_vt, (x, v))
            if rounds % 2:
                carry = one_round_vt(0, carry)
            return carry
        if vt_rows:
            v = perm_rows(v, b0)
            step = one_round_vt
        else:
            v = perm_cols(v, b0)
            step = one_round
        return jax.lax.fori_loop(0, rounds, step, (x, v))

    def kernel(a_ref, w_ref, v_ref):
        x, v = _decompose(a_ref)
        # emit in original index order (see inv above)
        w_ref[0] = jnp.stack([x[inv[i], inv[i]] for i in range(n)])  # (n, L)
        v_ref[0] = jnp.stack([v[:, inv[i]] for i in range(n)], axis=1)

    def make_weighted_kernel(vt_rows, v_compose2=False):
        def weighted_kernel(a_ref, d_ref, w_ref, h_ref):
            # Same decomposition, but instead of writing the (n, n, L)
            # eigenvector block back to HBM, reduce it against the per-matrix
            # weight vector d in VMEM: h_i = sum_k V_ki^2 d_k.  The k axis
            # (original index order throughout — d is supplied in that order)
            # is v's row axis in the cols layout and vt's column axis in the
            # rows layout; slot j maps back through inv, exactly like w.
            x, v = _decompose(a_ref, vt_rows=vt_rows,
                              v_compose2=v_compose2)
            d = d_ref[0]                      # (n, L), original index order
            if vt_rows:
                hsum = jnp.sum(v * v * d[None, :, :], axis=1)
            else:
                hsum = jnp.sum(v * v * d[:, None, :], axis=0)
            w_ref[0] = jnp.stack([x[inv[i], inv[i]] for i in range(n)])
            h_ref[0] = jnp.stack([hsum[inv[i]] for i in range(n)])
        return weighted_kernel

    return kernel, make_weighted_kernel


def _pack_lanes(x: jax.Array):
    """(B, ...) -> ((nb, ..., LANES) with batch in the lane dim, nb)."""
    B = x.shape[0]
    nb = -(-B // LANES)
    xp = jnp.pad(x, ((0, nb * LANES - B),) + ((0, 0),) * (x.ndim - 1))
    xp = xp.reshape((nb, LANES) + x.shape[1:])
    return jnp.moveaxis(xp, 1, -1), nb


def _unpack_lanes(x: jax.Array, B: int):
    """Inverse of :func:`_pack_lanes` for a (nb, ..., LANES) output."""
    xp = jnp.moveaxis(x, -1, 1)
    return xp.reshape((-1,) + xp.shape[2:])[:B]


@functools.partial(jax.jit, static_argnames=("sweeps", "canonical_signs",
                                             "sort", "interpret"))
def jacobi_eigh_tpu(A: jax.Array, sweeps: int | None = None,
                    canonical_signs: bool = True, sort: bool = True,
                    interpret: bool = False):
    """Batched eigh of symmetric (B, n, n) via the Pallas kernel.

    Returns (w (B, n) ascending, V (B, n, n)) like ``np.linalg.eigh``.
    n must be even (the risk model's K = 1 + P + Q = 42 is); odd-n callers
    use :func:`mfm_tpu.ops.eigh.jacobi_eigh`.

    ``sort=False`` skips the eigenvalue ordering + eigenvector reordering and
    sign pass (a full extra HBM round trip of V).  Pairing of (w_i, v_i) is
    always consistent, and slots follow the matrix's ORIGINAL index order:
    for near-diagonal input, the eigenvalue tracking diagonal direction i is
    at slot i.  The eigenfactor Monte-Carlo (models/eigen.py) relies on this
    to pair slot i's bias ratio with F0's i-th eigenvalue; a basis-scrambled
    slot order would silently mispair the per-direction biases.
    """
    B, n, _ = A.shape
    if n % 2 != 0:
        raise ValueError(
            f"pallas path requires even n (Brent-Luk adjacent pairing), "
            f"got n={n}; odd-n callers use mfm_tpu.ops.eigh.jacobi_eigh")
    dtype = A.dtype
    if sweeps is None:
        sweeps = _sweeps_for(n, dtype)
    Ax, nb = _pack_lanes(A)  # (nb, n, n, L): batch into lanes

    kernel, _ = _make_kernel(n, sweeps, dtype)
    w, V = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[pl.BlockSpec((1, n, n, LANES), lambda b: (b, 0, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((1, n, LANES), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, n, LANES), lambda b: (b, 0, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, n, LANES), dtype),
            jax.ShapeDtypeStruct((nb, n, n, LANES), dtype),
        ],
        interpret=interpret,
    )(Ax)

    w = _unpack_lanes(w, B)
    V = _unpack_lanes(V, B)
    if sort:
        order = jnp.argsort(w, axis=-1)
        w = jnp.take_along_axis(w, order, axis=-1)
        V = jnp.take_along_axis(V, order[:, None, :], axis=-1)
    if canonical_signs:
        w, V = canonicalize_signs(w, V)
    return w, V


@functools.partial(jax.jit, static_argnames=("sweeps", "vt_rows",
                                             "v_compose2", "interpret"))
def jacobi_eigh_weighted_diag_tpu(A: jax.Array, d0: jax.Array,
                                  sweeps: int | None = None,
                                  vt_rows: bool = True,
                                  v_compose2: bool = False,
                                  interpret: bool = False):
    """Fused eigenvalues + weighted eigenvector diagonal: (w, h) with
    ``h_i = sum_k V_ki^2 d0_k`` for symmetric (B, n, n) ``A`` and per-matrix
    weights ``d0`` (B, n).

    This is the eigenfactor Monte-Carlo's consumer shape (models/eigen.py):
    the bias statistic needs only the simulated eigenvalues and the
    D0-weighted squared eigenvector columns (``D_hat = diag(U_m' F0 U_m)``,
    ``Barra-master/mfm/utils.py:83``), never the eigenvectors themselves.
    Reducing V against d0 inside the kernel skips the (B, n, n) eigenvector
    HBM writeout and the separate XLA einsum pass over it entirely.

    Slot order follows the matrix's ORIGINAL index order (same contract as
    ``jacobi_eigh_tpu(sort=False)``); (w_i, h_i) pairing is always
    consistent, so rank-based callers sort the two (B, n) outputs only.

    ``vt_rows`` picks the in-VMEM eigenvector-accumulator layout (identical
    outputs, layout only): True stores it transposed so the V-update is a
    rows pass over contiguous tile sets — measured 1.5x faster than the
    cols layout's strided column slices at the eigen MC's (139e3, 42, 42)
    shape on v5e (tools/kernel_ab.py), hence the default.  ``v_compose2``
    (vt layout only) fuses each two consecutive vt row passes into one
    4-term restack — algebraically identical (the vt update has no
    feedback into the angles), same FLOPs, one fewer stack
    materialization per round pair; an A/B candidate for the
    pass-overhead share of the kernel (``tools/kernel_ab.py``).
    """
    B, n, _ = A.shape
    if n % 2 != 0:
        raise ValueError(
            f"pallas path requires even n (Brent-Luk adjacent pairing), "
            f"got n={n}; odd-n callers use the XLA dispatch "
            f"(mfm_tpu.ops.eigh.batched_eigh_weighted_diag)")
    assert d0.shape == (B, n), (d0.shape, (B, n))  # one weight vector per matrix
    if v_compose2 and not vt_rows:
        # the composed update builds vt in the rows layout; reducing it with
        # the cols-layout formula would return a silently wrong h
        raise ValueError("v_compose2 requires vt_rows=True")
    dtype = A.dtype
    if sweeps is None:
        sweeps = _sweeps_for(n, dtype)
    Ax, nb = _pack_lanes(A)
    dx, _ = _pack_lanes(d0)                                 # (nb, n, L)

    _, make_weighted = _make_kernel(n, sweeps, dtype)
    kernel = make_weighted(vt_rows, v_compose2)
    w, h = pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((1, n, n, LANES), lambda b: (b, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, LANES), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, n, LANES), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, n, LANES), lambda b: (b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((nb, n, LANES), dtype),
            jax.ShapeDtypeStruct((nb, n, LANES), dtype),
        ],
        interpret=interpret,
    )(Ax, dx)

    return _unpack_lanes(w, B), _unpack_lanes(h, B)
