"""Batched constrained cross-sectional WLS — the risk-model kernel.

Re-design of the reference's per-date ``CrossSection.reg()``
(``Barra-master/mfm/CrossSection.py:57-108``) as a single masked, vmappable
function over static ``(N,)`` cross-sections:

- style standardization: cap-weighted mean, equal-weight population std
  (``CrossSection.py:12-20,46``)
- design X = [country=1 | industry one-hot | standardized styles]
  (``CrossSection.py:48,74``)
- WLS weights W = sqrt(cap)/sum(sqrt(cap))  (``CrossSection.py:50``)
- industry-neutrality constraint matrix R eliminating the LAST industry with
  cap-weight ratios (``CrossSection.py:66-71``)
- pure-factor-portfolio weights Omega = R pinv(Xr' W Xr) Xr' W
  (``CrossSection.py:74-76``)
- factor returns, specific returns, exposure check, R^2 = 1 - var(spec)/var(ret)
  (``CrossSection.py:101-106``)

Instead of one NumPy solve per date inside a Python loop (``mfm/MFM.py:57-68``),
the whole (T, N) panel is vmapped and the date axis shards over the device
mesh; with the stock axis sharded too, the normal-equation matmuls reduce over
stocks and XLA inserts psums over the 'stock' mesh axis.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mfm_tpu.ops.eigh import pinv_psd
from mfm_tpu.ops.masked import masked_var, zscore_cap_weighted

from mfm_tpu.utils.prec import highest_matmul_precision


class CrossSectionResult(NamedTuple):
    factor_ret: jax.Array  # (..., K) pure factor returns [country, P industries, Q styles]
    specific_ret: jax.Array  # (..., N) NaN outside the valid universe
    r2: jax.Array  # (...,)
    exposure: jax.Array | None = None  # (..., K, K) pure-factor portfolio exposures


def _constraint_matrix(ind_cap: jax.Array, Q: int) -> jax.Array:
    """Industry-neutrality constraint R of shape (K, K-1), K = 1 + P + Q.

    In the reduced basis the last industry's exposure is expressed through the
    other industries' cap weights: row ``P`` (the last industry) becomes
    ``-ind_cap_i / ind_cap_P`` over industry columns, and the last industry's
    own column is removed (``CrossSection.py:69-71``).
    """
    P = ind_cap.shape[0]
    K = 1 + P + Q
    R = jnp.eye(K, dtype=ind_cap.dtype)
    row = jnp.zeros((K,), ind_cap.dtype).at[1 : 1 + P].set(-ind_cap / ind_cap[-1])
    R = R.at[P].set(row)
    keep = jnp.concatenate([jnp.arange(P, dtype=jnp.int32),
                            jnp.arange(P + 1, K, dtype=jnp.int32)])
    return R[:, keep]  # static-shape column delete


@highest_matmul_precision
def regression_design(
    ret: jax.Array,
    cap: jax.Array,
    styles: jax.Array,
    industry: jax.Array,
    valid: jax.Array,
    *,
    n_industries: int,
    standardize_styles: bool = True,
):
    """One date's regression design in its exact estimation basis.

    Returns (X (N, K), valid (N,), capz (N,)): the masked country column,
    industry one-hot, cap-weighted-standardized styles — with the
    regression's own universe narrowing (finite ret/cap, industry in
    [0, P)).  Shared by :func:`cross_section_regress` and
    ``RiskPipelineResult.portfolio_risk`` so portfolio exposures are always
    computed in the basis the factor covariance was estimated in.
    """
    dtype = styles.dtype
    P = n_industries
    valid = valid & jnp.isfinite(ret) & jnp.isfinite(cap)
    if P:
        valid = valid & (industry >= 0) & (industry < P)
    vf = valid.astype(dtype)

    if standardize_styles:
        s = zscore_cap_weighted(styles, cap[:, None], valid[:, None], axis=0)
    else:
        s = styles
    s = jnp.where(valid[:, None], s, 0.0)
    capz = jnp.where(valid, cap, 0.0)
    country = vf[:, None]
    if P:
        ind_oh = (industry[:, None]
                  == jnp.arange(P, dtype=jnp.int32)[None, :]).astype(dtype) \
            * vf[:, None]
        X = jnp.concatenate([country, ind_oh, s], axis=1)  # (N, K)
    else:
        X = jnp.concatenate([country, s], axis=1)
    return X, valid, capz


@highest_matmul_precision
def cross_section_regress(
    ret: jax.Array,
    cap: jax.Array,
    styles: jax.Array,
    industry: jax.Array,
    valid: jax.Array,
    *,
    n_industries: int,
    standardize_styles: bool = True,
    return_exposure: bool = False,
) -> CrossSectionResult:
    """One date's constrained WLS pure-factor regression, masked.

    Args:
      ret:      (N,) next-period stock returns.
      cap:      (N,) market caps (the WLS/standardization weights).
      styles:   (N, Q) style exposures.
      industry: (N,) int industry codes in [0, P); anything outside is invalid.
      valid:    (N,) bool — the date's universe (rows the reference would keep
                after its drop-any-NaN filter, ``demo.py:25-27``).
      n_industries: P (static).  P=0 runs the no-industry branch
                (``CrossSection.py:95-98``).
    """
    normal = _normal_equations(
        ret, cap, styles, industry, valid, n_industries=n_industries,
        standardize_styles=standardize_styles,
    )
    Ginv = pinv_psd(normal.G)
    return _solve_from_normal(normal, Ginv, return_exposure=return_exposure)


class _NormalEq(NamedTuple):
    X: jax.Array        # (N, K) design in estimation basis
    retz: jax.Array     # (N,) returns, zeroed outside the universe
    valid: jax.Array    # (N,) the regression's own universe
    R: jax.Array | None # (K, K-1) constraint, None when P == 0
    XtW: jax.Array      # (K-1, N) (or (K, N) when P == 0)
    G: jax.Array        # (K-1, K-1) constrained normal matrix


@highest_matmul_precision
def _normal_equations(ret, cap, styles, industry, valid, *, n_industries,
                      standardize_styles) -> _NormalEq:
    """One date's design + constrained normal equations (everything before
    the pseudo-inverse).  Split out so :func:`regress_panel` can hoist the
    G pseudo-inverse out of the date vmap into ONE batched eigh — on TPU
    that rides the Pallas Jacobi kernel instead of T per-date XLA SVDs."""
    P = n_industries
    Q = styles.shape[-1]
    X, valid, capz = regression_design(
        ret, cap, styles, industry, valid, n_industries=P,
        standardize_styles=standardize_styles,
    )
    w = jnp.sqrt(capz)
    w = w / jnp.sum(w)

    if P:
        ind_oh = X[:, 1:1 + P]
        ind_cap = ind_oh.T @ capz  # (P,) per-industry total cap (CrossSection.py:66)
        R = _constraint_matrix(ind_cap, Q)  # (K, K-1)
        Xr = X @ R  # (N, K-1)
        XtW = Xr.T * w[None, :]
        G = XtW @ Xr  # (K-1, K-1)
    else:
        R = None
        XtW = X.T * w[None, :]
        G = XtW @ X
    return _NormalEq(X, jnp.where(valid, ret, 0.0), valid, R, XtW, G)


@highest_matmul_precision
def _solve_from_normal(normal: _NormalEq, Ginv: jax.Array, *,
                       return_exposure: bool) -> CrossSectionResult:
    """Second half of the regression given ``Ginv = pinv(G)``
    (``CrossSection.py:74-76,101-106``)."""
    X, retz, valid, R, XtW, _ = normal
    omega = Ginv @ XtW if R is None else R @ (Ginv @ XtW)  # (K, N)
    factor_ret = omega @ retz  # (K,)
    spec = retz - X @ factor_ret
    # equal-weight population variance over the date's universe (CrossSection.py:106)
    r2 = 1.0 - masked_var(spec, valid, axis=0, ddof=0) / masked_var(
        retz, valid, axis=0, ddof=0
    )
    spec = jnp.where(valid, spec, jnp.nan)
    exposure = (omega @ X) if return_exposure else None
    return CrossSectionResult(factor_ret, spec, r2, exposure)


@highest_matmul_precision
def regress_panel(
    ret: jax.Array,
    cap: jax.Array,
    styles: jax.Array,
    industry: jax.Array,
    valid: jax.Array,
    *,
    n_industries: int,
    standardize_styles: bool = True,
    return_exposure: bool = False,
) -> CrossSectionResult:
    """vmap of :func:`cross_section_regress` over the leading date axis.

    ret/cap: (T, N); styles: (T, N, Q); industry: (T, N) int; valid: (T, N).
    This replaces the reference's serial date loop (``mfm/MFM.py:57-68``).

    The per-date pseudo-inverse is hoisted out of the vmap: all T normal
    matrices decompose in ONE batched eigh (:func:`mfm_tpu.ops.eigh.pinv_psd`
    — the Pallas Jacobi kernel on TPU) instead of T small XLA SVDs.
    """
    phase1 = lambda r, c, s, i, v: _normal_equations(
        r, c, s, i, v,
        n_industries=n_industries,
        standardize_styles=standardize_styles,
    )
    normal = jax.vmap(phase1)(ret, cap, styles, industry, valid)
    Ginv = pinv_psd(normal.G)  # (T, K-1, K-1) in one batch
    phase2 = lambda ne, gi: _solve_from_normal(
        ne, gi, return_exposure=return_exposure)
    return jax.vmap(phase2)(normal, Ginv)
