"""Masked array kernels: cross-sectional ops, EWMA weights, rolling windows,
and the batched constrained WLS regression that is the heart of the risk model."""

from mfm_tpu.ops.masked import (
    masked_mean,
    masked_std,
    masked_var,
    masked_weighted_mean,
    winsorize_cs,
    zscore_cap_weighted,
    masked_ols_residuals,
)
from mfm_tpu.ops.xreg import cross_section_regress, CrossSectionResult
from mfm_tpu.ops.rolling import (
    ewma_tail_weights_from_mask,
    rolling_beta_hsigma,
    rolling_weighted_std,
    rolling_decay_weighted_mean,
    rolling_sum,
    rolling_cmra,
)

__all__ = [
    "masked_mean",
    "masked_std",
    "masked_var",
    "masked_weighted_mean",
    "winsorize_cs",
    "zscore_cap_weighted",
    "masked_ols_residuals",
    "cross_section_regress",
    "CrossSectionResult",
    "ewma_tail_weights_from_mask",
    "rolling_beta_hsigma",
    "rolling_weighted_std",
    "rolling_decay_weighted_mean",
    "rolling_sum",
    "rolling_cmra",
]
