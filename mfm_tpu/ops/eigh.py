"""Batched symmetric eigendecomposition for small matrices (parallel Jacobi).

Why this exists: the eigenfactor-adjustment stage decomposes ~T*(M+1) tiny
(KxK, K~42) symmetric matrices (``mfm/utils.py:64,79`` per date x sim).
XLA's TPU ``eigh`` (QDWH) costs ~100us per 42x42 matrix regardless of batch —
12+ seconds for the CSI300 workload, >95% of the whole pipeline.

This implements **Brent-Luk parallel-ordered cyclic Jacobi** in a fully
static form: matrices are kept in a permuted basis in which every round
rotates the adjacent pairs (2i, 2i+1) simultaneously — pair extraction and
rotation are strided reshapes + elementwise math, and the move to the next
round's pairing is one FIXED position permutation (a constant gather).  No
dynamic scatter/gather ever touches the batch, so the whole decomposition is
VPU-friendly elementwise work that batches perfectly.

Schedule construction: with the circle method, round r pairs are
(L_r[i], L_r[n-1-i]) where L_{r+1} = g(L_r) for a fixed rotation g.  Writing
f for the interleaving [L[0], L[n-1], L[1], L[n-2], ...] that makes pairs
adjacent, the basis change between consecutive rounds is pi = f^-1 . g . f —
the same permutation every round.

Returns eigenvalues ascending and eigenvectors in columns, like
``np.linalg.eigh``; optional deterministic sign canonicalization (largest-
magnitude component positive) makes results reproducible across backends.
"""

from __future__ import annotations

import math
import os

import jax
import jax.extend as jex
import jax.numpy as jnp
from jax.interpreters import mlir


def _brent_luk_perms(n: int):
    """(initial basis b0, per-round fixed permutation pi), both length-n
    python int lists.  The planner runs at trace time on the concrete
    static size, so it stays pure python: its indices become device
    constants only at the ``jnp.asarray(..., jnp.int32)`` boundary in the
    callers, never as platform-default-width host arrays."""
    assert n % 2 == 0
    # f: interleave so that circle-method pairs (i, n-1-i) become adjacent
    f = [0] * n
    f[0::2] = range(n // 2)
    f[1::2] = range(n - 1, n // 2 - 1, -1)
    # g: circle-method rotation L' = [L[0], L[-1], L[1], ..., L[-2]]
    g = [0, n - 1] + list(range(1, n - 1))
    f_inv = sorted(range(n), key=f.__getitem__)  # inverse permutation of f
    pi = [f_inv[g[fi]] for fi in f]  # position map of (f^-1 . g . f)
    return f, pi


def _check_perm_schedule(n):  # exercised by tests/test_eigh.py
    b0, pi = _brent_luk_perms(n)
    basis = list(b0)
    seen = set()
    for _ in range(n - 1):
        for i in range(n // 2):
            a, b = basis[2 * i], basis[2 * i + 1]
            seen.add((min(a, b), max(a, b)))
        basis = [basis[p] for p in pi]
    assert len(seen) == n * (n - 1) // 2, len(seen)
    # pi has order n-1: whole sweeps return the basis to b0 — the Pallas
    # kernel's output emission order (eigh_pallas._make_kernel) relies on it
    assert basis == b0


def _sweeps_for(n: int, dtype) -> int:
    base = 7 if dtype == jnp.float32 else 10
    return base + max(0, (n - 16) // 32)


def jacobi_eigh(A: jax.Array, sweeps: int | None = None,
                canonical_signs: bool = True):
    """Batched eigh of symmetric ``A`` (..., n, n) -> (w (..., n), V (..., n, n)).

    Eigenvalues ascending; ``V[..., :, i]`` is the i-th eigenvector.
    """
    n0 = A.shape[-1]
    dtype = A.dtype
    odd = n0 % 2 == 1
    if odd:
        # pad with an isolated dummy eigenvalue strictly below the spectrum
        # (Gershgorin bound); rotations against it are exact no-ops since its
        # off-diagonal entries stay zero
        d = jnp.diagonal(A, axis1=-2, axis2=-1)
        lb = jnp.min(d - (jnp.sum(jnp.abs(A), axis=-1) - jnp.abs(d)), axis=-1) - 1.0
        pad = jnp.zeros(A.shape[:-2] + (n0 + 1, n0 + 1), dtype)
        pad = pad.at[..., :n0, :n0].set(A)
        A = pad.at[..., n0, n0].set(lb)
    n = A.shape[-1]

    b0_list, pi_list = _brent_luk_perms(n)
    b0 = jnp.asarray(b0_list, jnp.int32)
    pi = jnp.asarray(pi_list, jnp.int32)
    if sweeps is None:
        sweeps = _sweeps_for(n, dtype)

    # move into the interleaved basis; B tracks basis columns (eigenvectors)
    A = jnp.take(jnp.take(A, b0, axis=-2), b0, axis=-1)
    V = jnp.broadcast_to(jnp.eye(n, dtype=dtype), A.shape)
    V = jnp.take(V, b0, axis=-1)

    batch = A.shape[:-2]
    h = n // 2

    def round_step(_, AV):
        A, V = AV
        # adjacent-pair quantities, all static strided views
        diag = jnp.diagonal(A, axis1=-2, axis2=-1)
        app = diag[..., 0::2]                       # (..., h)
        aqq = diag[..., 1::2]
        apq = jnp.diagonal(A[..., 0::2, 1::2], axis1=-2, axis2=-1)

        small = jnp.abs(apq) <= jnp.asarray(jnp.finfo(dtype).tiny * 100, dtype)
        tau = (aqq - app) / jnp.where(small, 1.0, 2.0 * apq)
        t = jnp.sign(tau) / (jnp.abs(tau) + jnp.sqrt(1.0 + tau * tau))
        t = jnp.where(tau == 0, 1.0, t)  # 45-degree rotation when app == aqq
        t = jnp.where(small, 0.0, t)
        c = 1.0 / jnp.sqrt(1.0 + t * t)
        s = t * c

        # rows: A <- J^T A
        Ar = A.reshape(batch + (h, 2, n))
        top, bot = Ar[..., 0, :], Ar[..., 1, :]
        cN, sN = c[..., :, None], s[..., :, None]
        Ar = jnp.stack([cN * top - sN * bot, sN * top + cN * bot], axis=-2)
        A = Ar.reshape(batch + (n, n))
        # cols: A <- A J
        Ac = A.reshape(batch + (n, h, 2))
        topc, botc = Ac[..., 0], Ac[..., 1]
        cM, sM = c[..., None, :], s[..., None, :]
        A = jnp.stack([cM * topc - sM * botc, sM * topc + cM * botc],
                      axis=-1).reshape(batch + (n, n))
        # eigenvector columns: V <- V J
        Vc = V.reshape(batch + (n, h, 2))
        topv, botv = Vc[..., 0], Vc[..., 1]
        V = jnp.stack([cM * topv - sM * botv, sM * topv + cM * botv],
                      axis=-1).reshape(batch + (n, n))

        # fixed basis permutation to the next round's pairing
        A = jnp.take(jnp.take(A, pi, axis=-2), pi, axis=-1)
        V = jnp.take(V, pi, axis=-1)
        return A, V

    # R2: explicit s32 bounds — python ints would canonicalize the loop
    # counter to s64 under x64 (same class as the vol_regime/newey_west fix)
    A, V = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(sweeps * (n - 1)), round_step, (A, V))

    w = jnp.diagonal(A, axis1=-2, axis2=-1)
    order = jnp.argsort(w, axis=-1)
    w = jnp.take_along_axis(w, order, axis=-1)
    V = jnp.take_along_axis(V, order[..., None, :], axis=-1)
    if odd:
        # dummy eigenvalue is strictly below the spectrum -> sorted first
        w = w[..., 1:]
        V = V[..., :n0, 1:]
    if canonical_signs:
        w, V = canonicalize_signs(w, V)
    return w, V


def canonicalize_signs(w, V):
    """Flip eigenvector signs so the largest-|.| component is positive."""
    idx = jnp.argmax(jnp.abs(V), axis=-2, keepdims=True)
    lead = jnp.take_along_axis(V, idx, axis=-2)
    sign = jnp.where(lead < 0, -1.0, 1.0)
    return w, V * sign


def eigh_small(A, *, use_jacobi: bool | None = None, canonical_signs=True):
    """eigh dispatcher: Jacobi for small n (the TPU fast path), XLA otherwise."""
    n = A.shape[-1]
    if use_jacobi is None:
        use_jacobi = n <= 128
    if use_jacobi:
        return jacobi_eigh(A, canonical_signs=canonical_signs)
    w, V = jnp.linalg.eigh(A)
    if canonical_signs:
        return canonicalize_signs(w, V)
    return w, V


def _pallas_eligible(A) -> bool:
    """Static (shape/dtype) eligibility for the Pallas Jacobi kernel.

    Mosaic has no 64-bit support, so f64 (x64 parity runs,
    ``tools/tpu_parity.py --x64``) always takes XLA's emulated-f64 eigh;
    the kernel itself handles even n <= 128 only.
    """
    n = A.shape[-1]
    return A.dtype != jnp.float64 and n % 2 == 0 and n <= 128


# --- lowering-time platform selection -------------------------------------
#
# ``lax.platform_dependent`` is the obvious tool, but on this JAX it stages
# every branch into a ``switch`` and lowers them ALL for the target platform
# before the constant platform index can prune anything — so the Pallas
# branch reaches pallas_call's CPU lowering rule and dies with "Only
# interpret mode is supported on CPU backend".  Instead the selection is a
# tiny primitive with per-platform lowering rules: the TPU rule lowers the
# Pallas branch, the default rule lowers the fallback, and a non-TPU program
# never contains the Pallas call at all.  No ``jax.devices()`` query happens
# at trace time (the dryrun_multichip gate relies on that), and AOT export
# for ("tpu",) from a CPU host still picks the Pallas rule.

_platform_select_p = jex.core.Primitive("mfm_eigh_platform_select")
_platform_select_p.multiple_results = True


def _psel_run(fn, treedef, flat):
    args = jax.tree_util.tree_unflatten(treedef, flat)
    return jax.tree_util.tree_leaves(fn(*args))


@_platform_select_p.def_impl
def _psel_impl(*flat, treedef, tpu_fn, default_fn):
    # eager execution: the computation runs on the process-default backend
    fn = tpu_fn if jax.default_backend() in ("tpu", "axon") else default_fn
    return _psel_run(fn, treedef, flat)


@_platform_select_p.def_abstract_eval
def _psel_abstract(*flat, treedef, tpu_fn, default_fn):
    import jax.core as jax_core

    args = jax.tree_util.tree_unflatten(treedef, flat)
    outs = jax.eval_shape(default_fn, *args)
    return [jax_core.ShapedArray(o.shape, o.dtype)
            for o in jax.tree_util.tree_leaves(outs)]


def _psel_lowering(which: str):
    def fn(*flat, treedef, tpu_fn, default_fn):
        return _psel_run(tpu_fn if which == "tpu" else default_fn,
                         treedef, flat)

    return mlir.lower_fun(fn, multiple_results=True)


mlir.register_lowering(_platform_select_p, _psel_lowering("default"))
for _plat in ("tpu", "axon"):
    # 'axon' mirrors the tunnelled-TPU plugin name: device.platform reports
    # 'tpu' there (PARITY_TPU.json), so 'tpu' is the rule that matches in
    # practice; the alias is insurance against the plugin ever surfacing its
    # own name as the lowering platform.
    try:
        mlir.register_lowering(_platform_select_p, _psel_lowering("tpu"),
                               platform=_plat)
    except Exception:
        pass


def _platform_select(operands: tuple, tpu_fn, default_fn):
    flat, treedef = jax.tree_util.tree_flatten(tuple(operands))
    outs = _platform_select_p.bind(*flat, treedef=treedef, tpu_fn=tpu_fn,
                                   default_fn=default_fn)
    out_tree = jax.tree_util.tree_structure(
        jax.eval_shape(default_fn, *operands))
    return jax.tree_util.tree_unflatten(out_tree, outs)


def cpu_jacobi_batch_threshold() -> int | None:
    """Batch size at which non-TPU backends route to the pure-JAX Jacobi.

    ``MFM_EIGH_CPU_JACOBI_BATCH=<int>`` opts in; unset/empty/non-positive
    means never.  The default is OFF because the A/B micro-bench
    (``tools/eigh_cpu_ab.py``) shows multithreaded LAPACK beating the
    vectorized Jacobi at every batch size on the dev host (K=42 f32:
    0.36s vs 4.4s at B=1024) — the switch exists for hosts where LAPACK
    dispatch overhead dominates, and it is the only CPU path that honors
    the ``sweeps`` cap.
    """
    raw = os.environ.get("MFM_EIGH_CPU_JACOBI_BATCH", "").strip()
    if not raw:
        return None
    thr = int(raw)
    return thr if thr > 0 else None


def _dispatch_eigh(operands: tuple, prefer_pallas, pallas_fn, xla_fn,
                   jacobi_fn=None, batch_hint: int | None = None,
                   cpu_jacobi: bool | None = None):
    """Shared backend dispatch for the batched eigh entry points.

    ``operands[0]`` is the matrix batch; extra operands ride along to the
    branch functions.  ``prefer_pallas=None`` resolves Pallas-vs-fallback at
    LOWERING time (see ``_platform_select_p``), never by querying
    ``jax.devices()`` at trace time — the trace-time query is wrong whenever
    the computation targets a different backend than the process default
    (the driver's ``dryrun_multichip`` gate jits onto a virtual CPU mesh
    from a TPU-attached process).

    The non-Pallas branch picks between XLA's eigh and the pure-JAX Jacobi
    (``jacobi_fn``) by static batch size: ``cpu_jacobi`` forces the choice,
    otherwise batches of at least :func:`cpu_jacobi_batch_threshold` take
    the Jacobi.  ``batch_hint`` overrides the batch size used for that
    decision — the chunked eigen Monte-Carlo passes its full-run batch so
    the solver choice (and thus the numbers) cannot depend on the chunk
    size.
    """
    if not _pallas_eligible(operands[0]):
        if prefer_pallas:
            A = operands[0]
            raise ValueError(
                "prefer_pallas=True but the Pallas Jacobi kernel cannot "
                f"handle dtype={A.dtype}, n={A.shape[-1]} (needs non-f64, "
                "even n <= 128) — an explicit pin must not silently "
                "measure the XLA fallback")
        prefer_pallas = False

    default_fn = xla_fn
    if jacobi_fn is not None:
        if cpu_jacobi is None:
            thr = cpu_jacobi_batch_threshold()
            # R1: math.prod on the static shape tuple, not np.prod — this
            # runs at trace time inside a traced dispatch path
            batch = batch_hint if batch_hint is not None else math.prod(
                operands[0].shape[:-2])
            cpu_jacobi = thr is not None and batch >= thr
        if cpu_jacobi:
            default_fn = jacobi_fn

    if prefer_pallas is None:
        return _platform_select(operands, pallas_fn, default_fn)
    return (pallas_fn if prefer_pallas else default_fn)(*operands)


def batched_eigh(A, *, prefer_pallas: bool | None = None,
                 canonical_signs: bool = True, sort: bool = True,
                 sweeps: int | None = None, batch_hint: int | None = None,
                 cpu_jacobi: bool | None = None):
    """Backend-aware batched eigh for (B, n, n) symmetric matrices.

    On TPU with even n <= 128 the VMEM-resident Pallas Jacobi kernel is ~8x
    XLA's QDWH eigh at the risk model's scale (139k 42x42 matrices: 1.77s
    measured vs 14.2s); elsewhere XLA/LAPACK eigh wins by default, with huge
    batches optionally routed to the pure-JAX Jacobi (``cpu_jacobi`` /
    ``MFM_EIGH_CPU_JACOBI_BATCH``, see :func:`cpu_jacobi_batch_threshold`).
    Signs are canonicalized either way so all paths produce identical
    decompositions (eigenvalues ascending, leading component positive).

    ``sweeps`` caps the Jacobi sweep count on the Pallas and pure-JAX Jacobi
    paths; the XLA/LAPACK fallback always solves to full precision and
    silently ignores it.
    """
    def _pallas(A):
        from mfm_tpu.ops.eigh_pallas import jacobi_eigh_tpu

        flat = A.reshape((-1,) + A.shape[-2:])
        w, V = jacobi_eigh_tpu(flat, sweeps=sweeps,
                               canonical_signs=canonical_signs, sort=sort)
        return (w.reshape(A.shape[:-1]), V.reshape(A.shape))

    def _xla(A):
        w, V = jnp.linalg.eigh(A)
        if canonical_signs:
            return canonicalize_signs(w, V)
        return w, V

    def _jacobi(A):
        return jacobi_eigh(A, sweeps=sweeps, canonical_signs=canonical_signs)

    return _dispatch_eigh((A,), prefer_pallas, _pallas, _xla,
                          jacobi_fn=_jacobi, batch_hint=batch_hint,
                          cpu_jacobi=cpu_jacobi)


def batched_eigh_weighted_diag(A, d0, *, prefer_pallas: bool | None = None,
                               sweeps: int | None = None,
                               batch_hint: int | None = None,
                               cpu_jacobi: bool | None = None):
    """Eigenvalues plus D0-weighted squared-eigenvector diagonal, batched.

    Returns ``(w, h)`` with ``h_i = sum_k V_ki^2 d0_k`` for symmetric
    ``A`` (..., n, n) and weights ``d0`` (..., n) — the eigenfactor
    Monte-Carlo's consumer shape (``D_hat = diag(U_m' F0 U_m)``,
    ``Barra-master/mfm/utils.py:83``, collapsed into the eigenbasis).

    On the TPU Pallas path the reduction is fused into the Jacobi kernel, so
    the (..., n, n) eigenvector batch never round-trips HBM; elsewhere it is
    ``eigh`` + einsum.  Slot order differs between the paths (original-index
    vs ascending) exactly as for ``batched_eigh(sort=False)`` — (w_i, h_i)
    pairing is consistent either way, and callers rank-pair by sorting the
    two small outputs.
    """
    n = A.shape[-1]
    d0b = jnp.broadcast_to(d0, A.shape[:-1])

    def _pallas(A, d0b):
        from mfm_tpu.ops.eigh_pallas import jacobi_eigh_weighted_diag_tpu

        flat = A.reshape((-1,) + A.shape[-2:])
        dflat = d0b.reshape(-1, n)
        # vt_rows: transposed eigenvector accumulator (rows-pass updates with
        # contiguous tile sets) — measured 1.5x faster than the cols layout at
        # the eigen MC's (139e3, 42, 42) shape on v5e (tools/kernel_ab.py).
        w, h = jacobi_eigh_weighted_diag_tpu(flat, dflat, sweeps=sweeps,
                                             vt_rows=True)
        return w.reshape(A.shape[:-1]), h.reshape(A.shape[:-1])

    def _xla(A, d0b):
        w, V = jnp.linalg.eigh(A)
        return w, jnp.einsum("...ki,...k->...i", V * V, d0b)

    def _jacobi(A, d0b):
        # honors the ``sweeps`` cap (sim matrices are near-diagonal, see
        # models/eigen.py::sim_sweeps_for) — the one thing the LAPACK
        # fallback cannot do
        w, V = jacobi_eigh(A, sweeps=sweeps, canonical_signs=False)
        return w, jnp.einsum("...ki,...k->...i", V * V, d0b)

    return _dispatch_eigh((A, d0b), prefer_pallas, _pallas, _xla,
                          jacobi_fn=_jacobi, batch_hint=batch_hint,
                          cpu_jacobi=cpu_jacobi)


def pinv_psd(G: jax.Array, *, rcond: float | None = None,
             prefer_pallas: bool | None = None) -> jax.Array:
    """Moore-Penrose pseudo-inverse of symmetric PSD-up-to-roundoff batches.

    For symmetric input, SVD-based ``pinv`` (the reference's
    ``np.linalg.pinv``, ``Barra-master/mfm/CrossSection.py:76``) equals the
    eigendecomposition form ``V diag(1/w where |w| > cut) V'`` with
    ``cut = rcond * max|w|`` — but the eigh rides the Pallas Jacobi kernel
    on TPU instead of XLA's iterative SVD.  ``rcond`` defaults to JAX's
    ``pinv`` default (``10 * n * eps``) so this is a drop-in replacement.

    Odd n is padded to even with an isolated diagonal entry c = trace/n:
    ``pinv(blockdiag(G, c)) = blockdiag(pinv(G), 1/c)`` exactly, and for PSD
    G, ``trace/n`` lies in ``[lambda_max/n, lambda_max]`` so it neither
    raises the cutoff nor gets discarded by it.
    """
    n = G.shape[-1]
    dtype = G.dtype
    if rcond is None:
        rcond = 10.0 * n * float(jnp.finfo(dtype).eps)
    pad = n % 2 == 1
    if pad:
        tr = jnp.trace(G, axis1=-2, axis2=-1) / n
        Gp = jnp.zeros(G.shape[:-2] + (n + 1, n + 1), dtype)
        Gp = Gp.at[..., :n, :n].set(G)
        G = Gp.at[..., n, n].set(tr)
    w, V = batched_eigh(G, prefer_pallas=prefer_pallas,
                        canonical_signs=False)
    cut = rcond * jnp.max(jnp.abs(w), axis=-1, keepdims=True)
    inv_w = jnp.where(jnp.abs(w) > cut, 1.0 / jnp.where(w == 0, 1.0, w), 0.0)
    out = jnp.einsum("...ik,...k,...jk->...ij", V, inv_w, V)
    if pad:
        out = out[..., :n, :n]
    return out
