"""Masked cross-sectional primitives.

Everything here operates on dense arrays where invalid entries are excluded
via a boolean mask (or NaN), reproducing the reference's drop-row semantics
(``demo.py:25-27``, per-date ``dropna`` in ``post_processing.py``) with static
shapes, so XLA can fuse and the date axis can shard over the mesh.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from mfm_tpu.utils.prec import highest_matmul_precision


def _as_mask(x: jax.Array, mask: jax.Array | None) -> jax.Array:
    if mask is None:
        return jnp.isfinite(x)
    return mask & jnp.isfinite(x)


def masked_mean(x, mask=None, axis=-1, keepdims: bool = False):
    """Mean over valid entries. Empty slice -> NaN (like pandas mean of none)."""
    m = _as_mask(x, mask)
    xz = jnp.where(m, x, 0.0)
    n = jnp.sum(m, axis=axis, keepdims=keepdims)
    s = jnp.sum(xz, axis=axis, keepdims=keepdims)
    return s / n


def masked_var(x, mask=None, axis=-1, ddof: int = 0, keepdims: bool = False):
    """Variance over valid entries (ddof=0 matches ``np.var``; ddof=1 matches
    pandas ``.std()**2`` as used in winsorization, ``post_processing.py:13-14``)."""
    m = _as_mask(x, mask)
    n = jnp.sum(m, axis=axis, keepdims=True)
    mu = jnp.sum(jnp.where(m, x, 0.0), axis=axis, keepdims=True) / n
    d2 = jnp.where(m, (x - mu) ** 2, 0.0)
    v = jnp.sum(d2, axis=axis, keepdims=True) / (n - ddof)
    if not keepdims:
        v = jnp.squeeze(v, axis=axis)
    return v


def masked_std(x, mask=None, axis=-1, ddof: int = 0, keepdims: bool = False):
    return jnp.sqrt(masked_var(x, mask, axis=axis, ddof=ddof, keepdims=keepdims))


def masked_weighted_mean(x, w, mask=None, axis=-1, keepdims: bool = False):
    """Weighted mean over valid entries; weights renormalized over the valid set
    (the reference's recurring pattern, e.g. ``factor_calculator.py:140-142``)."""
    m = _as_mask(x, mask)
    wz = jnp.where(m, w, 0.0)
    return jnp.sum(wz * jnp.where(m, x, 0.0), axis=axis, keepdims=keepdims) / jnp.sum(
        wz, axis=axis, keepdims=keepdims
    )


def winsorize_cs(x, n_std: float = 2.5, axis=-1):
    """Per-cross-section clip at mean +/- n_std * sample std (ddof=1).

    Contract: ``post_processing.py:12-15`` — pandas ``x.mean()/x.std()`` skip
    NaN and use ddof=1; ``clip`` leaves NaN in place.  A single-survivor
    section has NaN sample std, and pandas ``clip`` IGNORES NaN thresholds —
    the value passes through unclipped (jnp.clip would propagate the NaN;
    divergence found by tools/crosscheck_golden.py at the first date a
    factor's expanding window matures for exactly one stock).
    """
    m = jnp.isfinite(x)
    mu = masked_mean(x, m, axis=axis, keepdims=True)
    sd = masked_std(x, m, axis=axis, ddof=1, keepdims=True)
    lo, hi = mu - n_std * sd, mu + n_std * sd
    bounded = jnp.isfinite(lo) & jnp.isfinite(hi)
    return jnp.where(m & bounded, jnp.clip(x, lo, hi), x)


def zscore_cap_weighted(x, cap, mask=None, axis=-1):
    """Barra style standardization: cap-weighted mean, equal-weight std (ddof=0).

    Contract: ``mfm/CrossSection.py:12-20`` (DescrStatsW weighted mean;
    ``np.std`` population std).
    """
    m = _as_mask(x, mask)
    capm = jnp.where(m, cap, 0.0)
    wmu = jnp.sum(capm * jnp.where(m, x, 0.0), axis=axis, keepdims=True) / jnp.sum(
        capm, axis=axis, keepdims=True
    )
    sd = masked_std(x, m, axis=axis, ddof=0, keepdims=True)
    return jnp.where(m, (x - wmu) / sd, jnp.nan)


@highest_matmul_precision
def masked_ols_residuals(y, X, mask=None, *, min_valid: int | None = None):
    """Residuals of OLS y ~ [1, X] over the valid rows of one cross-section.

    y: (N,), X: (N, R).  Rows invalid in y or any column of X are excluded and
    get NaN residuals (contract: ``post_processing.py:52-61`` and the NLSIZE
    regression ``factor_calculator.py:252-275``).  If fewer than ``min_valid``
    valid rows (reference uses R+2 for ortho, 2 for NLSIZE), the whole section
    is NaN.  Solves via normal equations on the (R+1)x(R+1) system — tiny K,
    vmapped over dates.
    """
    y = jnp.asarray(y)
    X = jnp.asarray(X)
    if X.ndim == 1:
        X = X[:, None]
    N, R = X.shape
    m = jnp.isfinite(y) & jnp.all(jnp.isfinite(X), axis=-1)
    if mask is not None:
        m = m & mask
    n = jnp.sum(m)
    mf = m.astype(y.dtype)
    ones = jnp.ones((N, 1), dtype=y.dtype)
    A = jnp.concatenate([ones, jnp.where(m[:, None], X, 0.0)], axis=1)  # (N, R+1)
    A = A * mf[:, None]
    yz = jnp.where(m, y, 0.0)
    G = A.T @ A
    b = A.T @ yz
    # pinv-solve for rank-deficient safety on degenerate cross-sections
    coef = jnp.linalg.pinv(G) @ b
    resid = yz - A @ coef
    thresh = (R + 2) if min_valid is None else min_valid
    ok = n >= thresh
    return jnp.where(m & ok, resid, jnp.nan)
