"""Masked rolling-window kernels over (T, N) panels.

The reference computes every rolling factor with a per-stock Python loop of
per-window statsmodels/pandas fits (~400k WLS fits for BETA/HSIGMA alone,
``factor_calculator.py:106-122``).  Here each factor is one batched kernel:
windows are gathered in date *blocks* (bounded memory, ``lax.map`` over
blocks), reduced with closed-form masked math, and the stock axis shards over
the mesh.

Weight-alignment semantics (the 1e-5-parity-critical part):

- *Tail-aligned after dropna* (BETA ``factor_calculator.py:97``, DASTD
  ``:172``): the reference drops NaNs inside the window and gives the last n
  weights of the full decay vector to the n valid points in order.  Because
  the weights are geometric, the k-th most recent *valid* point gets
  ``decay**k`` — i.e. the weight of a point depends only on the number of
  valid points after it in the window.  That count is a reversed masked
  cumsum: no dropna needed.
- *Head-aligned by window position* (RSTR ``factor_calculator.py:137``):
  weight ``decay**p`` at window position p, renormalized over valid points.
  For short early windows the reference indexes weights from the series
  start; the geometric factor between the two alignments is constant within
  a window, so renormalization makes position-based weights exact.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp


def decay_rate(half_life: float, dtype=jnp.float64) -> jax.Array:
    """0.5 ** (1 / half_life) — the per-step decay (``factor_calculator.py:87``)."""
    return jnp.asarray(0.5, dtype) ** (1.0 / half_life)


def ewma_tail_weights_from_mask(valid: jax.Array, decay, axis: int = -2) -> jax.Array:
    """Unnormalized tail-aligned weights ``decay**(# valid after me)`` * valid.

    ``valid`` is a boolean window array; ``axis`` is the window axis.
    Reproduces ``weights_arr[-n:]`` applied to the post-dropna window
    (``factor_calculator.py:97``, ``:172``) without materializing ragged data.
    """
    v = valid.astype(jnp.float32)
    after = jnp.flip(jnp.cumsum(jnp.flip(v, axis), axis), axis) - v
    return jnp.where(valid, decay ** after, 0.0)


def auto_block(n_stocks: int, window: int = 504, budget_mb: int = 256,
               lo: int = 8, hi: int = 64, itemsize: int = 4) -> int:
    """Date-block size fitting the window buffer in a fixed HBM budget.

    Each rolling kernel materializes ``block * window * n_stocks`` elements
    per input (:func:`rolling_reduce`); this returns the largest power of
    two in [lo, hi] keeping that under ``budget_mb``.  The 504 default is
    the widest kernel's T = window + lag upper bound (RSTR rolls 483 dates
    after its 21-day skip, FactorConfig) — conservative by the lag.
    Reproduces the measured block sweep (BASELINE.md): 64 at CSI300's
    300 stocks, 16 at all-A's 5,000 (where 32/64 lose to VMEM pressure).
    """
    per_date = window * max(int(n_stocks), 1) * itemsize
    cap = max(lo, min(hi, budget_mb * 2**20 // per_date))
    b = lo
    while b * 2 <= cap:
        b *= 2
    return b


def rolling_reduce(
    inputs: Sequence[jax.Array],
    window: int,
    reducer: Callable[..., jax.Array | tuple],
    *,
    block: int = 64,
):
    """Map ``reducer`` over all length-``window`` trailing windows of (T, N) inputs.

    Windows end at each date t and cover [t-window+1, t]; positions before the
    series start are NaN-padded (invalid).  ``reducer`` receives one
    (B, window, N) array per input and returns (B, N) (or a tuple of them).
    Blocks of ``block`` dates are processed sequentially via ``lax.map`` to
    bound the materialized window memory at block*window*N.
    """
    T, N = inputs[0].shape
    dtype = inputs[0].dtype
    nb = -(-T // block)
    Tp = nb * block
    padded = [
        jnp.pad(
            x,
            ((window - 1, Tp - T), (0, 0)),
            constant_values=jnp.asarray(jnp.nan, dtype),
        )
        for x in inputs
    ]
    starts = jnp.arange(nb, dtype=jnp.int32) * block  # R2: explicit s32
    offs = (jnp.arange(block, dtype=jnp.int32)[:, None]
            + jnp.arange(window, dtype=jnp.int32)[None, :])  # (B, W)

    def one_block(t0):
        idx = t0 + offs  # (B, W) into padded rows; window ends at date t0+b
        wins = [jnp.take(p, idx, axis=0) for p in padded]  # (B, W, N)
        return reducer(*wins)

    out = jax.lax.map(one_block, starts)  # pytree of (nb, B, N)
    return jax.tree_util.tree_map(
        lambda o: o.reshape((Tp,) + o.shape[2:])[:T], out
    )


# ---------------------------------------------------------------------------
# two-level (chunked prefix/suffix) windowed reductions — O(T*N) scans
# ---------------------------------------------------------------------------
#
# The block path above materializes (block, window, N) gathers: O(T*W*N) work
# and HBM traffic.  Every kernel's reduction is an associative sum/max over a
# trailing window, optionally with geometric weights, so it has an exact
# O(T*N) form: split the date axis into chunks of C = window rows; a trailing
# window [t-W+1, t] then spans at most the chunk containing t and the one
# before it, and
#
#     S_t = prefix(chunk q, ..r)  +  suffix(chunk q-1, r+1..)
#
# — two in-chunk scans (cumsum/cummax) plus an elementwise combine.  Geometric
# weights stay exact because they are *separable*:
#
# - tail-aligned-after-dropna (BETA/DASTD): weight(j, t) = decay**(# valid in
#   (j, t]) = decay**(v_t - v_j) with v the running valid count — separable in
#   *event time*;
# - head-aligned (RSTR): weight(j, t) ∝ (1/decay)**(t - j) up to a constant
#   per-window factor that the renormalization cancels — separable in
#   *calendar time*.
#
# Exponents are rebased per chunk (rel = expo - expo[chunk start]), so every
# intermediate weight is bounded by decay**(-C): at the reference's
# window/half-life pairs (252/63, 252/42, 483/126) that is at most ~2**6 —
# no overflow, no catastrophic cancellation, and the accumulation spans at
# most 2*W terms, the same precision regime as the block path.


def _chunked(x: jax.Array, C: int):
    """Pad the date axis to a multiple of C and reshape to (nc, C, ...)."""
    T = x.shape[0]
    nc = -(-T // C)
    xp = jnp.pad(x, ((0, nc * C - T),) + ((0, 0),) * (x.ndim - 1))
    return xp.reshape((nc, C) + x.shape[1:]), nc


def _prev_chunk_suffix(B: jax.Array, fill=0.0):
    """Map in-chunk suffix scans B[q, s] = reduce(chunk q rows s..) to
    Bsh[q, r] = B[q-1, r+1] (the previous chunk's contribution to the window
    ending at row r of chunk q), with the reduction's identity element
    ``fill`` (0 for sums, -inf for max) at missing positions."""
    Bprev = jnp.concatenate([jnp.full_like(B[:1], fill), B[:-1]], axis=0)
    return jnp.concatenate(
        [Bprev[:, 1:], jnp.full_like(Bprev[:, :1], fill)], axis=1
    )


#: the rolling-kernel implementations — the single source for config
#: validation and CLI ``choices`` (scan = O(T*N) two-level chunked scans,
#: block = the windowed-gather reference formulation)
ROLLING_IMPLS = ("scan", "block")


def _check_impl(impl: str) -> bool:
    """Validate the rolling-kernel impl switch; True for the scan path."""
    if impl not in ROLLING_IMPLS:
        raise ValueError(f"impl must be one of {ROLLING_IMPLS}, got {impl!r}")
    return impl == "scan"


def windowed_sum_scan(term: jax.Array, window: int) -> jax.Array:
    """Trailing-window sums of ``term`` (T, N; invalid entries pre-zeroed) in
    O(T*N): exact two-level chunked prefix/suffix form."""
    T = term.shape[0]
    ch, _ = _chunked(term, window)
    A = jax.lax.cumsum(ch, axis=1)
    B = jax.lax.cumsum(ch, axis=1, reverse=True)
    out = A + _prev_chunk_suffix(B)
    return out.reshape((-1,) + term.shape[1:])[:T]


def decay_windowed_sums_scan(
    terms: Sequence[jax.Array],
    window: int,
    expo: jax.Array,
    decay,
) -> list[jax.Array]:
    """Trailing-window geometric-weighted sums, O(T*N) per term.

    Returns, for each (T, N) ``term`` (invalid entries pre-zeroed),
    ``S_t = sum_{j in [t-window+1, t]} decay**(expo_t - expo_j) * term_j``.

    ``expo`` is (T, N) or (T, 1), nondecreasing along the date axis: the
    running valid count for event-time (tail-aligned) weights, or
    ``arange(T)`` for calendar-time weights.  ``decay`` may exceed 1 (the
    head-aligned case uses 1/decay).  Exponents are rebased per chunk, so
    every power is bounded by the within-chunk expo range (<= window steps).
    """
    C = window
    T = terms[0].shape[0]
    dtype = terms[0].dtype
    lam = jnp.asarray(decay, dtype)
    # edge-pad expo (zero-padding would put huge rebased exponents in the
    # padded tail rows; they are never consumed, but inf*0 NaNs would ride
    # the reverse cumsum into real rows of the last chunk's suffix)
    nc = -(-T // C)
    ep = jnp.pad(expo.astype(dtype), ((0, nc * C - T),) + ((0, 0),) * (expo.ndim - 1),
                 mode="edge")
    ch_e = ep.reshape((nc, C) + expo.shape[1:])
    e0 = ch_e[:, :1]                               # chunk-start expo
    rel = ch_e - e0                                # >= 0, bounded by chunk range
    # next chunk's start expo; the last chunk's suffix is never consumed, any
    # finite value works there
    e0n = jnp.concatenate([e0[1:], ch_e[-1:, -1:]], axis=0)
    wdn = lam ** (-rel)                            # prefix weights
    wup = lam ** (e0n - ch_e)                      # suffix weights (to next e0)
    scale = lam ** rel
    outs = []
    for term in terms:
        ch, _ = _chunked(term, C)
        A = jax.lax.cumsum(wdn * ch, axis=1)
        B = jax.lax.cumsum(wup * ch, axis=1, reverse=True)
        S = scale * (A + _prev_chunk_suffix(B))
        outs.append(S.reshape((-1,) + term.shape[1:])[:T])
    return outs


def windowed_max_scan(x: jax.Array, window: int) -> jax.Array:
    """Trailing-window running max of ``x`` (T, N; invalid entries pre-set to
    -inf) in O(T*N), two-level chunked cummax."""
    T = x.shape[0]
    # zero-padded tail rows only reach sliced-off prefix positions and the
    # never-consumed last chunk's suffix, so they cannot win any real max
    ch, _ = _chunked(x, window)
    A = jax.lax.cummax(ch, axis=1)
    B = jax.lax.cummax(ch, axis=1, reverse=True)
    out = jnp.maximum(A, _prev_chunk_suffix(B, fill=-jnp.inf))
    return out.reshape((-1,) + x.shape[1:])[:T]


# ---------------------------------------------------------------------------
# factor kernels
# ---------------------------------------------------------------------------


def rolling_beta_hsigma(
    ret: jax.Array,
    market_ret: jax.Array,
    *,
    window: int = 252,
    half_life: int = 63,
    min_periods: int = 42,
    block: int = 64,
    impl: str = "scan",
):
    """Closed-form rolling WLS of stock returns on market returns.

    Replaces the reference's per-window ``sm.WLS(y, [1, x], weights).fit()``
    (``factor_calculator.py:90-122``).  BETA is the slope; HSIGMA is
    ``sqrt(model.scale)`` where statsmodels' scale = sum(w * e^2) / (n - 2)
    with the *unnormalized* tail-aligned weights (``factor_calculator.py:97-102``).

    ret: (T, N); market_ret: (T,) or (T, N).  Returns (beta, hsigma), (T, N).

    ``impl="scan"`` (default) computes the six weighted moments with the
    O(T*N) two-level event-time scans (weights are separable, module
    comment); ``"block"`` is the windowed-gather reference path.  HSIGMA's
    residual sum on the scan path uses the normal-equation identity
    ``ssr = syy - alpha*sy - beta*sxy`` (exact for the WLS solution) instead
    of materializing per-window residuals.  The identity cancels when
    R^2 -> 1: measured float32 drift vs the f64 reference (pinned by
    ``tests/test_rolling.py::test_scan_float32_drift``) is median ~3e-7 /
    max ~2e-4 for BETA and HSIGMA, the max occurring only on an
    index-tracker-like stock (R^2 ~ 0.999) whose HSIGMA is itself near
    zero; the block path's explicit residuals stay ~6e-7 there.  The f64
    parity contract is unaffected (both paths are ~1e-15 in f64).
    """
    T, N = ret.shape
    dtype = ret.dtype
    if market_ret.ndim == 1:
        market_ret = jnp.broadcast_to(market_ret[:, None], (T, N))
    lam = decay_rate(half_life, dtype)

    if _check_impl(impl):
        valid = jnp.isfinite(ret) & jnp.isfinite(market_ret)
        m = valid.astype(dtype)
        yz = jnp.where(valid, ret, 0.0)
        xz = jnp.where(valid, market_ret, 0.0)
        v = jnp.cumsum(m, axis=0)  # event-time: weight = lam**(v_t - v_j)
        sw, sx, sy, sxx, sxy, syy = decay_windowed_sums_scan(
            [m, xz * m, yz * m, xz * xz * m, xz * yz * m, yz * yz * m],
            window, v, lam,
        )
        n = windowed_sum_scan(m, window)
        denom = sw * sxx - sx * sx
        beta = (sw * sxy - sx * sy) / denom
        alpha = (sy - beta * sx) / sw
        ssr = syy - alpha * sy - beta * sxy
        scale = jnp.maximum(ssr, 0.0) / (n - 2)  # clamp moment-form rounding
        ok = n >= min_periods
        nan = jnp.asarray(jnp.nan, dtype)
        return jnp.where(ok, beta, nan), jnp.where(ok, jnp.sqrt(scale), nan)

    def reducer(y, x):
        valid = jnp.isfinite(y) & jnp.isfinite(x)
        u = ewma_tail_weights_from_mask(valid, lam, axis=1).astype(dtype)
        yz = jnp.where(valid, y, 0.0)
        xz = jnp.where(valid, x, 0.0)
        n = jnp.sum(valid, axis=1)
        sw = jnp.sum(u, axis=1)
        sx = jnp.sum(u * xz, axis=1)
        sy = jnp.sum(u * yz, axis=1)
        sxx = jnp.sum(u * xz * xz, axis=1)
        sxy = jnp.sum(u * xz * yz, axis=1)
        denom = sw * sxx - sx * sx
        beta = (sw * sxy - sx * sy) / denom
        alpha = (sy - beta * sx) / sw
        e = yz - alpha[:, None] - beta[:, None] * xz
        ssr = jnp.sum(u * e * e, axis=1)
        scale = ssr / (n - 2)
        ok = n >= min_periods
        nan = jnp.asarray(jnp.nan, dtype)
        return (
            jnp.where(ok, beta, nan),
            jnp.where(ok, jnp.sqrt(scale), nan),
        )

    return rolling_reduce([ret, market_ret], window, reducer, block=block)


def rolling_weighted_std(
    x: jax.Array,
    *,
    window: int = 252,
    half_life: int = 42,
    min_periods: int = 42,
    block: int = 64,
    impl: str = "scan",
):
    """DASTD kernel: exp-weighted std with tail-aligned renormalized weights
    (``factor_calculator.py:166-180``): weighted mean, then weighted central
    second moment, sqrt.

    The scan path uses the moment identity ``var = s2/sw - mu**2`` (the
    renormalization cancels, so unnormalized event-time sums suffice)."""
    dtype = x.dtype
    lam = decay_rate(half_life, dtype)

    if _check_impl(impl):
        valid = jnp.isfinite(x)
        m = valid.astype(dtype)
        xz = jnp.where(valid, x, 0.0)
        v = jnp.cumsum(m, axis=0)
        sw, s1, s2 = decay_windowed_sums_scan(
            [m, xz * m, xz * xz * m], window, v, lam)
        mu = s1 / sw
        var = jnp.maximum(s2 / sw - mu * mu, 0.0)
        n = windowed_sum_scan(m, window)
        return jnp.where(n >= min_periods, jnp.sqrt(var),
                         jnp.asarray(jnp.nan, dtype))

    def reducer(w):
        valid = jnp.isfinite(w)
        u = ewma_tail_weights_from_mask(valid, lam, axis=1).astype(dtype)
        u = u / jnp.sum(u, axis=1, keepdims=True)
        wz = jnp.where(valid, w, 0.0)
        mu = jnp.sum(u * wz, axis=1, keepdims=True)
        var = jnp.sum(u * jnp.where(valid, (w - mu) ** 2, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, jnp.sqrt(var), jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_decay_weighted_mean(
    x: jax.Array,
    *,
    window: int,
    half_life: int,
    min_periods: int,
    block: int = 64,
    impl: str = "scan",
):
    """RSTR kernel: sum of head-aligned decay weights (renormalized over valid)
    times the windowed series (``factor_calculator.py:136-142``).  Weight at
    window position p is ``decay**p`` — see module docstring for why this is
    exact for short early windows too.

    The scan path uses calendar-time weights ``(1/decay)**(t-j)``, which
    differ from position weights by a constant per-window factor that the
    renormalization cancels."""
    dtype = x.dtype
    lam = decay_rate(half_life, dtype)

    if _check_impl(impl):
        valid = jnp.isfinite(x)
        m = valid.astype(dtype)
        xz = jnp.where(valid, x, 0.0)
        t_idx = jnp.arange(x.shape[0], dtype=dtype)[:, None]
        num, den = decay_windowed_sums_scan(
            [xz * m, m], window, t_idx, 1.0 / lam)
        n = windowed_sum_scan(m, window)
        return jnp.where(n >= min_periods, num / den,
                         jnp.asarray(jnp.nan, dtype))

    wpos = lam ** jnp.arange(window, dtype=dtype)  # (W,) head-aligned

    def reducer(w):
        valid = jnp.isfinite(w)
        u = jnp.where(valid, wpos[None, :, None], 0.0).astype(dtype)
        u = u / jnp.sum(u, axis=1, keepdims=True)
        s = jnp.sum(u * jnp.where(valid, w, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, s, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_sum(
    x: jax.Array,
    *,
    window: int,
    min_periods: int,
    block: int = 64,
    impl: str = "scan",
):
    """NaN-skipping rolling sum with a min_periods gate — the liquidity base
    (``factor_calculator.py:346-350``)."""
    dtype = x.dtype

    if _check_impl(impl):
        valid = jnp.isfinite(x)
        m = valid.astype(dtype)
        s = windowed_sum_scan(jnp.where(valid, x, 0.0), window)
        n = windowed_sum_scan(m, window)
        return jnp.where(n >= min_periods, s, jnp.asarray(jnp.nan, dtype))

    def reducer(w):
        valid = jnp.isfinite(w)
        s = jnp.sum(jnp.where(valid, w, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, s, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_cmra(
    log_ret: jax.Array,
    *,
    window: int = 252,
    block: int = 64,
    impl: str = "scan",
):
    """CMRA kernel: log(1+max Z) - log(1+min Z) with Z the cumulative-return
    path over the window; requires a fully valid window
    (``factor_calculator.py:206-219`` — pandas only calls the reducer when all
    ``window`` observations are present).

    The scan path uses the algebraic collapse of the reference formula: with
    ``Z_j = exp(sum log_ret) - 1``, ``log1p(Z_j)`` IS the windowed cumulative
    log return, so CMRA = (windowed max - windowed min) of the global
    log-return prefix path — the window base and any shifts from dates
    outside the (fully valid) window cancel in the range."""
    dtype = log_ret.dtype

    if _check_impl(impl):
        valid = jnp.isfinite(log_ret)
        m = valid.astype(dtype)
        prefix = jnp.cumsum(jnp.where(valid, log_ret, 0.0), axis=0)
        big = jnp.where(valid, prefix, -jnp.inf)
        small = jnp.where(valid, -prefix, -jnp.inf)
        rng = windowed_max_scan(big, window) + windowed_max_scan(small, window)
        n = windowed_sum_scan(m, window)
        return jnp.where(n >= window, rng, jnp.asarray(jnp.nan, dtype))

    def reducer(w):
        valid = jnp.isfinite(w)
        n = jnp.sum(valid, axis=1)
        cum = jnp.cumsum(jnp.where(valid, w, 0.0), axis=1)
        z = jnp.exp(cum) - 1.0
        big = jnp.where(valid, z, -jnp.inf)
        small = jnp.where(valid, z, jnp.inf)
        rng = jnp.log1p(jnp.max(big, axis=1)) - jnp.log1p(jnp.min(small, axis=1))
        return jnp.where(n >= window, rng, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([log_ret], window, reducer, block=block)
