"""Masked rolling-window kernels over (T, N) panels.

The reference computes every rolling factor with a per-stock Python loop of
per-window statsmodels/pandas fits (~400k WLS fits for BETA/HSIGMA alone,
``factor_calculator.py:106-122``).  Here each factor is one batched kernel:
windows are gathered in date *blocks* (bounded memory, ``lax.map`` over
blocks), reduced with closed-form masked math, and the stock axis shards over
the mesh.

Weight-alignment semantics (the 1e-5-parity-critical part):

- *Tail-aligned after dropna* (BETA ``factor_calculator.py:97``, DASTD
  ``:172``): the reference drops NaNs inside the window and gives the last n
  weights of the full decay vector to the n valid points in order.  Because
  the weights are geometric, the k-th most recent *valid* point gets
  ``decay**k`` — i.e. the weight of a point depends only on the number of
  valid points after it in the window.  That count is a reversed masked
  cumsum: no dropna needed.
- *Head-aligned by window position* (RSTR ``factor_calculator.py:137``):
  weight ``decay**p`` at window position p, renormalized over valid points.
  For short early windows the reference indexes weights from the series
  start; the geometric factor between the two alignments is constant within
  a window, so renormalization makes position-based weights exact.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp


def decay_rate(half_life: float, dtype=jnp.float64) -> jax.Array:
    """0.5 ** (1 / half_life) — the per-step decay (``factor_calculator.py:87``)."""
    return jnp.asarray(0.5, dtype) ** (1.0 / half_life)


def ewma_tail_weights_from_mask(valid: jax.Array, decay, axis: int = -2) -> jax.Array:
    """Unnormalized tail-aligned weights ``decay**(# valid after me)`` * valid.

    ``valid`` is a boolean window array; ``axis`` is the window axis.
    Reproduces ``weights_arr[-n:]`` applied to the post-dropna window
    (``factor_calculator.py:97``, ``:172``) without materializing ragged data.
    """
    v = valid.astype(jnp.float32)
    after = jnp.flip(jnp.cumsum(jnp.flip(v, axis), axis), axis) - v
    return jnp.where(valid, decay ** after, 0.0)


def auto_block(n_stocks: int, window: int = 504, budget_mb: int = 256,
               lo: int = 8, hi: int = 64, itemsize: int = 4) -> int:
    """Date-block size fitting the window buffer in a fixed HBM budget.

    Each rolling kernel materializes ``block * window * n_stocks`` elements
    per input (:func:`rolling_reduce`); this returns the largest power of
    two in [lo, hi] keeping that under ``budget_mb``.  The 504 default is
    the widest kernel's T = window + lag upper bound (RSTR rolls 483 dates
    after its 21-day skip, FactorConfig) — conservative by the lag.
    Reproduces the measured block sweep (BASELINE.md): 64 at CSI300's
    300 stocks, 16 at all-A's 5,000 (where 32/64 lose to VMEM pressure).
    """
    per_date = window * max(int(n_stocks), 1) * itemsize
    cap = max(lo, min(hi, budget_mb * 2**20 // per_date))
    b = lo
    while b * 2 <= cap:
        b *= 2
    return b


def rolling_reduce(
    inputs: Sequence[jax.Array],
    window: int,
    reducer: Callable[..., jax.Array | tuple],
    *,
    block: int = 64,
):
    """Map ``reducer`` over all length-``window`` trailing windows of (T, N) inputs.

    Windows end at each date t and cover [t-window+1, t]; positions before the
    series start are NaN-padded (invalid).  ``reducer`` receives one
    (B, window, N) array per input and returns (B, N) (or a tuple of them).
    Blocks of ``block`` dates are processed sequentially via ``lax.map`` to
    bound the materialized window memory at block*window*N.
    """
    T, N = inputs[0].shape
    dtype = inputs[0].dtype
    nb = -(-T // block)
    Tp = nb * block
    padded = [
        jnp.pad(
            x,
            ((window - 1, Tp - T), (0, 0)),
            constant_values=jnp.asarray(jnp.nan, dtype),
        )
        for x in inputs
    ]
    starts = jnp.arange(nb) * block
    offs = jnp.arange(block)[:, None] + jnp.arange(window)[None, :]  # (B, W)

    def one_block(t0):
        idx = t0 + offs  # (B, W) into padded rows; window ends at date t0+b
        wins = [jnp.take(p, idx, axis=0) for p in padded]  # (B, W, N)
        return reducer(*wins)

    out = jax.lax.map(one_block, starts)  # pytree of (nb, B, N)
    return jax.tree_util.tree_map(
        lambda o: o.reshape((Tp,) + o.shape[2:])[:T], out
    )


# ---------------------------------------------------------------------------
# factor kernels
# ---------------------------------------------------------------------------


def rolling_beta_hsigma(
    ret: jax.Array,
    market_ret: jax.Array,
    *,
    window: int = 252,
    half_life: int = 63,
    min_periods: int = 42,
    block: int = 64,
):
    """Closed-form rolling WLS of stock returns on market returns.

    Replaces the reference's per-window ``sm.WLS(y, [1, x], weights).fit()``
    (``factor_calculator.py:90-122``).  BETA is the slope; HSIGMA is
    ``sqrt(model.scale)`` where statsmodels' scale = sum(w * e^2) / (n - 2)
    with the *unnormalized* tail-aligned weights (``factor_calculator.py:97-102``).

    ret: (T, N); market_ret: (T,) or (T, N).  Returns (beta, hsigma), (T, N).
    """
    T, N = ret.shape
    dtype = ret.dtype
    if market_ret.ndim == 1:
        market_ret = jnp.broadcast_to(market_ret[:, None], (T, N))
    lam = decay_rate(half_life, dtype)

    def reducer(y, x):
        valid = jnp.isfinite(y) & jnp.isfinite(x)
        u = ewma_tail_weights_from_mask(valid, lam, axis=1).astype(dtype)
        yz = jnp.where(valid, y, 0.0)
        xz = jnp.where(valid, x, 0.0)
        n = jnp.sum(valid, axis=1)
        sw = jnp.sum(u, axis=1)
        sx = jnp.sum(u * xz, axis=1)
        sy = jnp.sum(u * yz, axis=1)
        sxx = jnp.sum(u * xz * xz, axis=1)
        sxy = jnp.sum(u * xz * yz, axis=1)
        denom = sw * sxx - sx * sx
        beta = (sw * sxy - sx * sy) / denom
        alpha = (sy - beta * sx) / sw
        e = yz - alpha[:, None] - beta[:, None] * xz
        ssr = jnp.sum(u * e * e, axis=1)
        scale = ssr / (n - 2)
        ok = n >= min_periods
        nan = jnp.asarray(jnp.nan, dtype)
        return (
            jnp.where(ok, beta, nan),
            jnp.where(ok, jnp.sqrt(scale), nan),
        )

    return rolling_reduce([ret, market_ret], window, reducer, block=block)


def rolling_weighted_std(
    x: jax.Array,
    *,
    window: int = 252,
    half_life: int = 42,
    min_periods: int = 42,
    block: int = 64,
):
    """DASTD kernel: exp-weighted std with tail-aligned renormalized weights
    (``factor_calculator.py:166-180``): weighted mean, then weighted central
    second moment, sqrt."""
    dtype = x.dtype
    lam = decay_rate(half_life, dtype)

    def reducer(w):
        valid = jnp.isfinite(w)
        u = ewma_tail_weights_from_mask(valid, lam, axis=1).astype(dtype)
        u = u / jnp.sum(u, axis=1, keepdims=True)
        wz = jnp.where(valid, w, 0.0)
        mu = jnp.sum(u * wz, axis=1, keepdims=True)
        var = jnp.sum(u * jnp.where(valid, (w - mu) ** 2, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, jnp.sqrt(var), jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_decay_weighted_mean(
    x: jax.Array,
    *,
    window: int,
    half_life: int,
    min_periods: int,
    block: int = 64,
):
    """RSTR kernel: sum of head-aligned decay weights (renormalized over valid)
    times the windowed series (``factor_calculator.py:136-142``).  Weight at
    window position p is ``decay**p`` — see module docstring for why this is
    exact for short early windows too."""
    dtype = x.dtype
    lam = decay_rate(half_life, dtype)
    wpos = lam ** jnp.arange(window, dtype=dtype)  # (W,) head-aligned

    def reducer(w):
        valid = jnp.isfinite(w)
        u = jnp.where(valid, wpos[None, :, None], 0.0).astype(dtype)
        u = u / jnp.sum(u, axis=1, keepdims=True)
        s = jnp.sum(u * jnp.where(valid, w, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, s, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_sum(
    x: jax.Array,
    *,
    window: int,
    min_periods: int,
    block: int = 64,
):
    """NaN-skipping rolling sum with a min_periods gate — the liquidity base
    (``factor_calculator.py:346-350``)."""
    dtype = x.dtype

    def reducer(w):
        valid = jnp.isfinite(w)
        s = jnp.sum(jnp.where(valid, w, 0.0), axis=1)
        n = jnp.sum(valid, axis=1)
        return jnp.where(n >= min_periods, s, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([x], window, reducer, block=block)


def rolling_cmra(
    log_ret: jax.Array,
    *,
    window: int = 252,
    block: int = 64,
):
    """CMRA kernel: log(1+max Z) - log(1+min Z) with Z the cumulative-return
    path over the window; requires a fully valid window
    (``factor_calculator.py:206-219`` — pandas only calls the reducer when all
    ``window`` observations are present)."""
    dtype = log_ret.dtype

    def reducer(w):
        valid = jnp.isfinite(w)
        n = jnp.sum(valid, axis=1)
        cum = jnp.cumsum(jnp.where(valid, w, 0.0), axis=1)
        z = jnp.exp(cum) - 1.0
        big = jnp.where(valid, z, -jnp.inf)
        small = jnp.where(valid, z, jnp.inf)
        rng = jnp.log1p(jnp.max(big, axis=1)) - jnp.log1p(jnp.min(small, axis=1))
        return jnp.where(n >= window, rng, jnp.asarray(jnp.nan, dtype))

    return rolling_reduce([log_ret], window, reducer, block=block)
