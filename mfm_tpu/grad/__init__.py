"""Differentiable risk: the grad subsystem (docs/DIFFERENTIABLE.md).

Three consumer surfaces, all ``jax.grad``/``jax.vjp`` through the SAME
compiled composition the rest of the framework serves and audits —
``scenario/kernel.py``'s stressed covariance, the grad-safe PSD gate, and
``models/risk_model.py``'s pure portfolio vol:

- :mod:`mfm_tpu.grad.reverse` — reverse stress testing: per-portfolio
  projected gradient ascent over the ScenarioSpec shock space, "which
  admissible shock hurts THIS book most".
- :mod:`mfm_tpu.grad.construct` — gradient-based portfolio construction:
  min-vol / risk-parity / hedge-overlay solvers on the simplex, surfaced
  as ``construct`` request types on ``mfm-tpu serve``.
- :mod:`mfm_tpu.grad.sensitivity` — exact ∂vol/∂shock and ∂vol/∂exposure
  Jacobian rows (vjp, not finite differences), stamped into scenario
  manifests and the ``mfm-tpu grad`` CLI.

Device code lives in the three kernel modules (one donated jit each,
registered as audited cells in analysis/registry.py); host orchestration
and the atomic report writer live in :mod:`mfm_tpu.grad.engine` and
:mod:`mfm_tpu.grad.report` (mfmlint R7 host-only barriers).
"""

from mfm_tpu.grad.construct import hedge_batch, minvol_batch, riskparity_batch
from mfm_tpu.grad.engine import GradEngine, ShockBall
from mfm_tpu.grad.report import (GRAD_REPORT_NAME, read_grad_report,
                                 write_grad_report)
from mfm_tpu.grad.reverse import reverse_stress_batch
from mfm_tpu.grad.sensitivity import sensitivity_batch

__all__ = [
    "GradEngine",
    "ShockBall",
    "GRAD_REPORT_NAME",
    "read_grad_report",
    "write_grad_report",
    "reverse_stress_batch",
    "minvol_batch",
    "riskparity_batch",
    "hedge_batch",
    "sensitivity_batch",
]
