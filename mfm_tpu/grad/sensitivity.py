"""Exact sensitivity reports: one vjp, every Jacobian row at once.

For each scenario lane the report wants the gradient of the predicted
portfolio vol with respect to EVERY shock coordinate and every exposure —
∂vol/∂shift (K,), ∂vol/∂scale (K,), ∂vol/∂vol_mult, ∂vol/∂corr_beta, and
∂vol/∂x (K,).  vol is a scalar, so ONE reverse-mode pull-back through the
serving composition (``stress_cov`` -> grad-safe ``psd_project`` ->
``portfolio_vol``) yields all 3K + 2 numbers exactly — no finite
differences, no truncation error, no 3K+2 forward re-evaluations (the
host-side FD loop this subsystem replaces).

The derivative is evaluated AT the spec's shock point: an identity lane
reports the local gradient at the unshocked world ("which shock hurts
most from here"), the single most-asked sensitivity.  The bitwise
identity-passthrough discipline of ``scenario_batch`` is about served
COVARIANCE bytes and does not apply to derivatives, so this kernel has
no passthrough operand — rejected lanes are simply never stamped by the
host layer (grad/engine.py).

Non-finiteness: the eigh vjp divides by eigenvalue gaps, so a lane whose
stressed matrix is exactly degenerate (e.g. a full correlation melt-up
clipping many entries to +/-1) can report inf/NaN rows.  That is a true
mathematical statement — the vol there is not differentiable — and the
host layer records such rows as ``null`` with a ``nondifferentiable``
flag rather than laundering them into numbers (the parity taxonomy in
docs/DIFFERENTIABLE.md).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from mfm_tpu.models.risk_model import portfolio_vol
from mfm_tpu.scenario.kernel import psd_project, stress_cov


def _one_sens(cov, shift, scale, vol_mult, corr_beta, x):
    def vol_of(shift, scale, vol_mult, corr_beta, x):
        cov_s = stress_cov(cov, shift, scale, vol_mult, corr_beta)
        cov_p, _, _ = psd_project(cov_s)
        return portfolio_vol(cov_p, x)

    vol, pull = jax.vjp(vol_of, shift, scale, vol_mult, corr_beta, x)
    d_shift, d_scale, d_vm, d_cb, d_x = pull(jnp.ones((), vol.dtype))
    return vol, d_shift, d_scale, d_vm, d_cb, d_x


# shift/scale are donated: the engine densifies fresh (S, K) shock stacks
# per run (scenario/engine.py's _shock_vectors) and the d_shift/d_scale
# outputs alias them exactly.  base_cov is not — no (S, K, K) output
# exists to retire it into.
@partial(jax.jit, donate_argnums=(1, 2))
def sensitivity_batch(base_cov, shift, scale, vol_mult, corr_beta, x):
    """All sensitivity rows for S scenario lanes in one compiled program.

    Args:
      base_cov: (S, K, K) resolved base covariances per lane.
      shift, scale: (S, K) densified shock vectors (donated).
      vol_mult, corr_beta: (S,) scalar shocks per lane.
      x: (K,) the portfolio's factor exposures (shared across lanes).

    Returns ``(vol (S,), d_shift (S, K), d_scale (S, K), d_vol_mult (S,),
    d_corr_beta (S,), d_x (S, K))``.
    """
    return jax.vmap(_one_sens, in_axes=(0, 0, 0, 0, 0, None))(
        base_cov, shift, scale, vol_mult, corr_beta, x)
