"""Gradient-based portfolio construction against the served covariance.

Three solvers, each ONE donated jit vmapped over portfolios, all against
the checkpoint's ``last_good_cov`` (what serving answers queries from —
construction against any other matrix would optimize a world the desk
is not being quoted):

- :func:`minvol_batch` — minimum-vol long-only portfolio on the simplex
  with box constraints, by exponentiated gradient (multiplicative
  weights): ``x <- x * exp(-eta_i * g)`` renormalized.  The
  multiplicative form keeps iterates on the positive orthant for free,
  the clip applies the box, and the renormalization is the exact simplex
  projection for this geometry.  The step is *annealed*: constant over
  the first half of the run (travel), then geometrically decayed to
  ``eta * 1e-6`` (convergence).  A constant normalized step settles into
  a period-2 limit cycle on covariances with strongly negative
  correlations — the gradient never vanishes under max-normalization, so
  the iterate orbits the optimum at the step radius instead of reaching
  it (observed on a real fitted checkpoint: 44% excess vol, flagged by
  the KKT diagnostic).  The anneal drives the orbit radius to zero while
  the constant first half preserves total travel distance.
- :func:`riskparity_batch` — equal risk contributions, via the convex
  ERC formulation (minimize ``x'Fx/2 - c * sum(log x)``, whose unique
  positive minimizer has every ``rc_i = x_i (F x)_i`` equal to ``c``):
  each step applies the per-coordinate closed-form root
  ``x_i = (-B_i + sqrt(B_i^2 + 4 F_ii c)) / (2 F_ii)`` (``B_i`` the
  off-diagonal marginal) Jacobi-style with damping — positive iterates
  by construction even when risk contributions cross zero mid-path,
  where the naive multiplicative rescale oscillates forever.
- :func:`hedge_batch` — minimum-vol hedge overlay: projected gradient on
  a masked overlay ``h`` (only the hedgeable factors move) with a box
  ``|h| <= hmax``, minimizing the vol of ``x0 + mask * h`` while the base
  book ``x0`` stays untouched.

Solver knobs (``eta``, ``steps``) are traced scalars, not statics — the
jits key on the padded portfolio bucket only, so the steady-state serve
path with construction queries holds <= 1 compile per bucket (the
serve/query.py ladder discipline).

Pad-lane isolation: every update is multiplicative in the lane's own
weights or masked by its own gradient, and every normalizer carries a
``+ _TINY`` guard, so with the default ``lo = 0`` box an all-zero pad
lane stays EXACTLY zero through any number of iterations — and in every
case nothing contracts across the batch axis, so batch-of-B equals B
singles bitwise (the scenario kernel's correctness anchor, re-proven for
these solvers in tests/test_grad.py).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mfm_tpu.models.risk_model import portfolio_vol

#: denominator guard: bitwise-neutral next to any real weight sum or
#: gradient magnitude at f32, and 0 / _TINY == 0 keeps pad lanes frozen
_TINY = 1e-30

#: ln(1e-6): the annealed solvers decay their step by this factor over
#: the second half of the run (see module docstring)
_LOG_ANNEAL = -13.815510557964274


def _anneal(i, steps, eta, dtype):
    """Step size at iteration ``i``: ``eta`` for the first half, then a
    geometric decay to ``eta * 1e-6`` at the last iteration.  ``i`` and
    ``steps`` are traced, so the schedule adds no recompile keys."""
    fs = jnp.maximum(steps - 1, 1).astype(dtype)
    frac = jnp.maximum(2.0 * i.astype(dtype) / fs - 1.0, 0.0)
    return eta * jnp.exp(_LOG_ANNEAL * frac)


def _minvol_one(x0, cov, lo, hi, eta, steps):
    def body(i, x):
        g = cov @ x
        gn = g / (jnp.max(jnp.abs(g)) + _TINY)
        x = jnp.clip(x * jnp.exp(-_anneal(i, steps, eta, x0.dtype) * gn),
                     lo, hi)
        return x / (jnp.sum(x) + _TINY)

    x = lax.fori_loop(jnp.int32(0), steps, body, x0)
    var = x @ (cov @ x)
    # KKT stationarity at the solution: every coordinate strictly inside
    # the box must have marginal variance (F x)_i equal to the portfolio
    # variance x'Fx (the simplex multiplier); report the worst relative
    # violation over interior coordinates as the convergence diagnostic.
    # "Interior" means clear of the box by an absolute 1e-3 of weight:
    # the multiplicative update drives inactive coordinates toward the
    # boundary exponentially but never exactly onto it
    interior = (x > lo + 1e-3) & (x < hi - 1e-3)
    resid = jnp.abs(cov @ x - var) / (var + _TINY)
    kkt = jnp.max(jnp.where(interior, resid, jnp.zeros((), x.dtype)))
    return x, portfolio_vol(cov, x), kkt


@partial(jax.jit, donate_argnums=(0,))
def minvol_batch(xs0, cov, lo, hi, eta, steps):
    """Min-vol solve for B portfolios (warm starts ``xs0`` donated).

    Args:
      xs0: (B, K) start weights (any nonnegative warm start; pad lanes
        all-zero).  Donated — retired into the solved weights.
      cov: (K, K) served factor covariance.
      lo, hi: (K,) box constraints (``lo=0, hi=1`` recovers the plain
        long-only simplex).
      eta: scalar multiplicative-weights rate (peak of the annealed
        schedule — see the module docstring).
      steps: scalar i32 iteration count (traced).

    Returns ``(x (B, K), vol (B,), kkt_resid (B,))``.
    """
    return jax.vmap(_minvol_one, in_axes=(0, None, None, None, None, None))(
        xs0, cov, lo, hi, eta, steps)


def _riskparity_one(x0, cov, eta, steps):
    K = x0.shape[0]
    d = jnp.maximum(jnp.diagonal(cov), _TINY)
    # c sets the (arbitrary) scale of the unnormalized ERC fixed point;
    # the warm start's own variance keeps it commensurate with cov.  An
    # all-zero pad lane gives c = 0, whose root is x = 0 — frozen.
    c = (x0 @ (cov @ x0)) / K

    def body(_, x):
        off = cov @ x - d * x
        root = (-off + jnp.sqrt(off * off + 4 * d * c)) / (2 * d)
        return (1 - eta) * x + eta * root

    x = lax.fori_loop(jnp.int32(0), steps, body, x0)
    x = x / (jnp.sum(x) + _TINY)
    rc = x * (cov @ x)
    spread = (jnp.max(rc) - jnp.min(rc)) / (jnp.sum(rc) / K + _TINY)
    return x, portfolio_vol(cov, x), spread


@partial(jax.jit, donate_argnums=(0,))
def riskparity_batch(xs0, cov, eta, steps):
    """Risk-parity solve for B portfolios (``xs0`` donated).

    ``eta`` is the Jacobi damping in (0, 1] — 0.5 converges on every
    tested shape; undamped (1.0) can ring on strongly negative
    covariances.  Returns ``(x (B, K), vol (B,), rc_spread (B,))`` where
    ``rc_spread`` is (max - min) risk contribution over the mean risk
    contribution — 0 at exact parity.
    """
    return jax.vmap(_riskparity_one, in_axes=(0, None, None, None))(
        xs0, cov, eta, steps)


def _hedge_one(x0, h0, cov, mask, hmax, eta, steps):
    def body(i, h):
        g = mask * (cov @ (x0 + mask * h))
        gn = g / (jnp.max(jnp.abs(g)) + _TINY)
        # same annealed schedule as min-vol: the max-normalized gradient
        # never vanishes, so a constant step orbits the optimum at
        # radius ~eta * hmax instead of converging onto it
        return jnp.clip(h - _anneal(i, steps, eta, h.dtype) * hmax * gn,
                        -hmax, hmax)

    h = lax.fori_loop(jnp.int32(0), steps, body, h0)
    xt = x0 + mask * h
    return xt, h, portfolio_vol(cov, xt)


@partial(jax.jit, donate_argnums=(0, 1))
def hedge_batch(xs0, hs0, cov, mask, hmax, eta, steps):
    """Hedge-overlay solve for B books (``xs0``/``hs0`` donated).

    Args:
      xs0: (B, K) base books (held fixed; retired into the hedged books).
      hs0: (B, K) overlay starts (normally zeros; retired into ``h``).
      cov: (K, K) served factor covariance.
      mask: (B, K) 1.0 on the hedgeable factors, 0.0 elsewhere.
      hmax: scalar overlay box, ``|h_i| <= hmax``.
      eta: scalar step rate (peak fraction of ``hmax`` per iteration;
        annealed like min-vol).
      steps: scalar i32 iteration count (traced).

    Returns ``(x_hedged (B, K), h (B, K), vol (B,))``.
    """
    return jax.vmap(_hedge_one,
                    in_axes=(0, 0, None, 0, None, None, None))(
        xs0, hs0, cov, mask, hmax, eta, steps)
