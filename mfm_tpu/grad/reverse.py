"""Reverse stress testing: the worst admissible shock per portfolio.

Forward stress testing asks "what does scenario s do to my book"; reverse
stress testing asks the adjoint question — "which admissible scenario
hurts my book MOST".  Here the scenario space is the dense part of
:class:`mfm_tpu.scenario.spec.ScenarioSpec` (per-factor vol shift/scale,
the global vol-regime multiplier, the correlation stress beta) flattened
into one shock vector

    theta = [shift (K,) | scale (K,) | vol_mult | corr_beta]   # (2K + 2,)

and the search is projected gradient ASCENT of the predicted portfolio
vol through the real serving composition — ``stress_cov`` -> the
grad-safe PSD gate ``psd_project`` -> ``portfolio_vol`` — subject to an
admissibility box (the "shock ball", :class:`mfm_tpu.grad.engine.ShockBall`)
and, implicitly, the PSD cone (the shocked matrix a lane reports is the
POST-projection one, exactly what serving would use).

The inner step is ``jax.grad`` of that composition; the loop is a
fixed-iteration ``lax.fori_loop`` INSIDE one donated jit, vmapped over
portfolios, with ``steps``/``step`` as traced operands — so the jit keys
on the padded portfolio bucket only and the steady state holds <= 1
compile per bucket (the serve/query.py discipline; enforced by
``assert_max_compiles`` in bench and tests).

Per-coordinate scaling: theta's coordinates live on wildly different
scales (a vol shift of 0.01 is a big move, a vol_mult of 2.5 is routine),
so the ascent direction is the L2-normalized gradient scaled by each
coordinate's box width — a diagonal preconditioner that makes one step
move every coordinate a comparable fraction of its admissible range.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from mfm_tpu.models.risk_model import portfolio_vol
from mfm_tpu.scenario.kernel import psd_project, stress_cov

#: guard against 0/0 in the gradient normalization; bitwise-neutral next
#: to any real gradient norm at f32 and keeps all-zero (pad) lanes at 0
_TINY = 1e-30


def stressed_vol(theta, cov, x):
    """Predicted vol of exposure vector ``x`` under shock ``theta`` —
    the scalar the ascent differentiates.  ``cov`` is a constant (the
    base world); the PSD gate is the grad-safe form, so the value agrees
    with the serving kernel's projection and the vjp stays finite."""
    K = cov.shape[0]
    cov_s = stress_cov(cov, theta[:K], theta[K:2 * K],
                       theta[2 * K], theta[2 * K + 1])
    cov_p, _, _ = psd_project(cov_s)
    return portfolio_vol(cov_p, x)


def _reverse_one(cov, x, theta0, lo, hi, step, steps):
    """Projected gradient ascent for ONE portfolio; vmapped over the
    batch by :func:`reverse_stress_batch`."""
    width = hi - lo

    def body(_, theta):
        g = jax.grad(stressed_vol)(theta, cov, x)
        # the eigh vjp is genuinely non-differentiable at repeated
        # eigenvalues (heavily clipped correlations can reach them along
        # the ascent path); a non-finite component would poison theta
        # forever, so zero it — the projection step keeps us admissible
        # and the next iterate re-evaluates a clean gradient
        g = jnp.where(jnp.isfinite(g), g, jnp.zeros((), g.dtype))
        dirn = g / (jnp.sqrt(jnp.sum(g * g)) + _TINY)
        return jnp.clip(theta + step * width * dirn, lo, hi)

    theta = lax.fori_loop(jnp.int32(0), steps, body, theta0)
    vol0 = portfolio_vol(cov, x)
    return theta, stressed_vol(theta, cov, x), vol0


# theta0 is donated: the caller assembles a fresh (B, 2K+2) start per run
# and the ascent retires it into theta_star of the same shape/dtype.  cov
# / lo / hi are NOT donated — the host threads them unchanged into every
# next call (the sim_covs pattern of models/risk_model.py).
@partial(jax.jit, donate_argnums=(2,))
def reverse_stress_batch(cov, xs, theta0, lo, hi, step, steps):
    """Worst-case shock search for B portfolios in one compiled program.

    Args:
      cov: (K, K) base covariance (shared across lanes).
      xs: (B, K) factor-exposure vectors (pad lanes all-zero).
      theta0: (B, 2K+2) start shocks (the identity point, normally).
      lo, hi: (2K+2,) admissibility box (``ShockBall.bounds``).
      step: scalar ascent rate (fraction of box width per iteration).
      steps: scalar i32 iteration count (traced — NOT a static, so every
        bucket rung shares one cache entry per shape).

    Returns ``(theta_star (B, 2K+2), vol_star (B,), vol0 (B,))``.
    """
    return jax.vmap(_reverse_one,
                    in_axes=(None, 0, 0, None, None, None, None))(
        cov, xs, theta0, lo, hi, step, steps)
