"""Atomic grad reports: the differentiable-risk subsystem's evidence file.

A reverse-stress or sensitivity run is evidence in the same sense a
scenario batch is — "the worst admissible shock costs this book 2.4x its
vol" drives hedging decisions — so its results persist with the same
discipline as scenario manifests: ONE ``grad_report.json`` written
atomically (tmp -> fsync -> chaos point -> rename -> dir fsync).  The
chaos point (``grad_report.after_tmp``) is what the ``grad-kill-mid-solve``
fault plan SIGKILLs at, proving a crash mid-write never leaves a torn
report and never touches the checkpoint it was computed against.

This module is an mfmlint R7 host-only barrier (pure JSON/filesystem —
the device work happened upstream in grad/reverse.py et al.).
"""

from __future__ import annotations

import json
import os

from mfm_tpu.utils.chaos import chaos_point

GRAD_REPORT_SCHEMA_VERSION = 1
GRAD_REPORT_NAME = "grad_report.json"


class GradReportError(RuntimeError):
    """A grad report exists but is unreadable or schema-incompatible."""


def grad_report_path_for(artifact_dir: str) -> str:
    """The grad-report slot inside an artifact directory."""
    return os.path.join(artifact_dir, GRAD_REPORT_NAME)


def build_grad_report(kind: str, entries, *, stamp_json=None, backend=None,
                      staleness: int | None = None,
                      params: dict | None = None) -> dict:
    """Assemble the report dict (pure; :func:`write_grad_report` persists).

    ``kind``: ``"reverse_stress"`` | ``"sensitivity"`` | ``"construct"``;
    ``entries``: the per-portfolio / per-scenario result dicts the engine
    built; ``params``: the solver knobs that produced them (steps, step
    rate, ball bounds) so a report is replayable from its own bytes.
    """
    entries = list(entries)
    return {
        "schema_version": GRAD_REPORT_SCHEMA_VERSION,
        "kind": "grad_report",
        "grad_kind": str(kind),
        "config_stamp": stamp_json,
        "backend": backend,
        "staleness": staleness,
        "params": params or {},
        "n_entries": len(entries),
        "entries": entries,
    }


def write_grad_report(path: str, report: dict) -> str:
    """Atomic write (tmp -> fsync -> chaos point -> rename -> dir fsync);
    ``path`` may be the artifact directory.  Returns the final path."""
    if os.path.isdir(path):
        path = os.path.join(path, GRAD_REPORT_NAME)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(report, fh, indent=1, sort_keys=True, default=str)
            fh.write("\n")
            fh.flush()
            os.fsync(fh.fileno())
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    chaos_point("grad_report.after_tmp", path)
    os.replace(tmp, path)
    try:
        fd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - exotic filesystems
        pass
    return path


def read_grad_report(path: str) -> dict:
    """Load + schema-check a grad report (``path`` may be its directory).
    Raises :class:`GradReportError` on unreadable / torn JSON, wrong
    ``schema_version`` or ``kind``, or a missing ``entries`` list."""
    if os.path.isdir(path):
        path = os.path.join(path, GRAD_REPORT_NAME)
    try:
        with open(path, encoding="utf-8") as fh:
            r = json.load(fh)
    except OSError as e:
        raise GradReportError(f"{path}: unreadable grad report ({e})") from e
    except ValueError as e:
        raise GradReportError(
            f"{path}: grad report is not valid JSON ({e}) — torn write?"
        ) from e
    if not isinstance(r, dict):
        raise GradReportError(f"{path}: grad report is not a JSON object")
    if r.get("schema_version") != GRAD_REPORT_SCHEMA_VERSION:
        raise GradReportError(
            f"{path}: grad report schema_version "
            f"{r.get('schema_version')!r} unsupported (expected "
            f"{GRAD_REPORT_SCHEMA_VERSION})")
    if r.get("kind") != "grad_report":
        raise GradReportError(
            f"{path}: kind {r.get('kind')!r} is not a grad report")
    if not isinstance(r.get("entries"), list):
        raise GradReportError(f"{path}: grad report has no entries list")
    return r
