"""GradEngine — host orchestration for the differentiable-risk surfaces.

The engine owns everything that is NOT device math (this module is an
mfmlint R7 host-only barrier, like scenario/engine.py): admissibility
bounds, bucket padding, spec resolution (delegated to a composed
:class:`~mfm_tpu.scenario.engine.ScenarioEngine` so replay/counterfactual
worlds resolve identically), host-side verification of the worst-case
shocks the ascent returns, and the JSON-ready entry dicts the report
writer persists.  The device work happens in exactly three donated jits
(grad/reverse.py, grad/construct.py, grad/sensitivity.py), each called
at bucket-padded shapes so the steady state holds <= 1 compile per
bucket.

Sanitization doctrine: a non-finite sensitivity is a true statement (the
vol is not differentiable at that point — eigh's vjp at repeated
eigenvalues), so it is recorded as ``null`` + a ``nondifferentiable``
flag, never replaced by a plausible number (docs/DIFFERENTIABLE.md).
"""

from __future__ import annotations

import dataclasses

import numpy as np

import jax.numpy as jnp

from mfm_tpu.grad.construct import hedge_batch, minvol_batch, riskparity_batch
from mfm_tpu.grad.reverse import reverse_stress_batch
from mfm_tpu.grad.sensitivity import sensitivity_batch
from mfm_tpu.scenario.engine import ScenarioEngine
from mfm_tpu.scenario.spec import ScenarioSpec, validate_spec
from mfm_tpu.serve.query import bucket_for

#: default solver knobs — traced operands, so changing them never
#: recompiles; pinned here so serve, CLI and bench agree on one steady
#: state (docs/DIFFERENTIABLE.md's solver catalog cites these)
REVERSE_STEPS = 200
REVERSE_STEP = 0.1
MINVOL_STEPS = 2000
MINVOL_ETA = 0.15
RISKPARITY_STEPS = 2000
RISKPARITY_ETA = 0.5
HEDGE_STEPS = 200
HEDGE_ETA = 0.1

#: construct request vocabulary (serve/server.py admits exactly these)
SOLVERS = ("min_vol", "risk_parity", "hedge")


@dataclasses.dataclass(frozen=True)
class ShockBall:
    """The admissibility box of the reverse-stress search, in ScenarioSpec
    coordinates.  A box, not a sphere: each shock axis has its own
    physically-meaningful range, and the box is what ``jnp.clip``
    projects onto exactly.  The default ball CONTAINS the whole preset
    drill catalog (crash-2015-analog, covid-2020-analog, corr-meltup) —
    the worst admissible shock can never report less vol than a drill the
    desk already runs.

    Attributes:
      shift_max: |additive vol shift| cap per factor (vol units).
      scale_range: vol scale stays in [1 - r, 1 + r].
      vol_mult_lo/hi: global vol-regime multiplier range.
      corr_beta_lo/hi: correlation-stress range (hi must stay < 1/0.95 of
        the -1 pole validate_spec rejects; 0.95 keeps every spec the
        search can emit admissible by construction).
    """

    shift_max: float = 0.01
    scale_range: float = 0.5
    vol_mult_lo: float = 1.0
    vol_mult_hi: float = 3.5
    corr_beta_lo: float = 0.0
    corr_beta_hi: float = 0.95

    def bounds(self, K: int) -> tuple:
        """``(lo, hi)`` lists over the theta layout
        ``[shift (K,) | scale (K,) | vol_mult | corr_beta]``."""
        lo = ([-self.shift_max] * K + [1.0 - self.scale_range] * K
              + [self.vol_mult_lo, self.corr_beta_lo])
        hi = ([self.shift_max] * K + [1.0 + self.scale_range] * K
              + [self.vol_mult_hi, self.corr_beta_hi])
        return lo, hi

    def contains(self, theta, K: int, rtol: float = 1e-5) -> bool:
        """Host check that a returned shock vector sits inside the box
        (up to dtype round-off of the clip itself)."""
        lo, hi = self.bounds(K)
        lo = np.asarray(lo, np.float64)
        hi = np.asarray(hi, np.float64)
        t = np.asarray(theta, np.float64)
        slack = rtol * np.maximum(np.abs(lo), np.abs(hi))
        return bool(np.all(t >= lo - slack) and np.all(t <= hi + slack))

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class GradEngine:
    """Differentiable-risk runs against one served covariance.

    Mirrors :class:`~mfm_tpu.scenario.engine.ScenarioEngine`'s contract
    (same constructor surface, same ``from_risk_state`` guards — grad
    surfaces interrogate the GUARDED checkpoint's ``last_good_cov``, the
    matrix serving actually answers from).
    """

    def __init__(self, cov, *, factor_names=None, staleness: int = 0,
                 dtype=None, replay_lookup=None, counterfactual_fn=None):
        # compose a ScenarioEngine for validation + base-cov resolution:
        # grad lanes must resolve replay/counterfactual worlds EXACTLY the
        # way forward scenarios do, or the sensitivity a manifest stamps
        # would describe a different world than the entry it sits in
        self._scen = ScenarioEngine(
            cov, factor_names=factor_names, staleness=staleness,
            dtype=dtype, replay_lookup=replay_lookup,
            counterfactual_fn=counterfactual_fn)
        self.cov = self._scen.cov
        self.dtype = self._scen.dtype
        self.K = self._scen.K
        self.factor_names = self._scen.factor_names
        self.factor_index = self._scen.factor_index
        self.staleness = self._scen.staleness

    @classmethod
    def from_risk_state(cls, state, meta=None, dtype=None,
                        replay_lookup=None, counterfactual_fn=None):
        """Engine over a guarded ``RiskModelState`` checkpoint (refuses
        unguarded states, names off the checkpoint meta — the
        ``QueryEngine.from_risk_state`` contract)."""
        scen = ScenarioEngine.from_risk_state(
            state, meta=meta, dtype=dtype, replay_lookup=replay_lookup,
            counterfactual_fn=counterfactual_fn)
        return cls(scen.cov, factor_names=scen.factor_names,
                   staleness=scen.staleness, dtype=scen.dtype,
                   replay_lookup=replay_lookup,
                   counterfactual_fn=counterfactual_fn)

    # -- reverse stress testing ----------------------------------------------
    def reverse_stress(self, portfolios, *, ball: ShockBall | None = None,
                       steps: int = REVERSE_STEPS,
                       step: float = REVERSE_STEP,
                       bucket: int | None = None, labels=None) -> list:
        """Worst admissible shock per portfolio (ONE donated jit call).

        ``portfolios``: (P, K) factor-exposure rows.  Returns P entry
        dicts: the worst-case :class:`ScenarioSpec` (as a dict + hash),
        base/worst vol, the vol delta, and the host-verified
        ``admissible`` flag (inside the ball AND spec-valid AND the
        stressed covariance PSD at compute dtype).
        """
        ball = ball or ShockBall()
        W = np.atleast_2d(np.asarray(portfolios, self.dtype))
        if W.ndim != 2 or W.shape[1] != self.K:
            raise ValueError(f"portfolios must be (P, {self.K}), got "
                             f"{W.shape}")
        P = W.shape[0]
        B = bucket_for(P) if bucket is None else int(bucket)
        if B < P:
            raise ValueError(f"bucket {B} < batch size {P}")
        labels = ([f"p{i}" for i in range(P)] if labels is None
                  else [str(l) for l in labels])

        lo_l, hi_l = ball.bounds(self.K)
        lo = np.asarray(lo_l, self.dtype)
        hi = np.asarray(hi_l, self.dtype)
        xs = np.zeros((B, self.K), self.dtype)
        xs[:P] = W
        # start at the identity shock — theta0 is donated, rebuilt per run
        theta0 = np.zeros((B, 2 * self.K + 2), self.dtype)
        theta0[:, self.K:2 * self.K] = 1.0
        theta0[:, 2 * self.K] = 1.0
        # pad lanes (all-zero portfolios) hit the sqrt(0) gradient corner;
        # the kernel's isfinite guard pins them at the identity start and
        # the trim below discards them
        theta_star, vol_star, vol0 = reverse_stress_batch(
            jnp.array(self.cov), jnp.array(xs), jnp.array(theta0),
            jnp.array(lo), jnp.array(hi),
            jnp.asarray(step, self.dtype), jnp.int32(steps))
        theta_star = np.asarray(theta_star)[:P]
        vol_star = np.asarray(vol_star)[:P]
        vol0 = np.asarray(vol0)[:P]

        entries = []
        for i in range(P):
            spec = self._theta_spec(theta_star[i], f"reverse-{labels[i]}")
            admissible = (ball.contains(theta_star[i], self.K)
                          and not validate_spec(spec, self.factor_names)
                          and self._stressed_psd(theta_star[i]))
            entries.append({
                "label": labels[i],
                "spec": spec.to_dict(),
                "spec_hash": spec.spec_hash(),
                "vol_base": float(vol0[i]),
                "vol_worst": float(vol_star[i]),
                "vol_delta": float(vol_star[i] - vol0[i]),
                "admissible": bool(admissible),
            })
        return entries

    def _theta_spec(self, theta, name: str) -> ScenarioSpec:
        """A flat shock vector back to declarative ScenarioSpec form —
        the round trip that makes a reverse-stress answer REPLAYABLE as
        an ordinary forward scenario."""
        K = self.K
        return ScenarioSpec(
            name=name,
            shift=tuple((self.factor_names[j], float(theta[j]))
                        for j in range(K) if theta[j] != 0.0),
            scale=tuple((self.factor_names[j], float(theta[K + j]))
                        for j in range(K) if theta[K + j] != 1.0),
            vol_mult=float(theta[2 * K]),
            corr_beta=float(theta[2 * K + 1]),
        )

    def _stressed_psd(self, theta) -> bool:
        """Host check: the worst-case stressed covariance, through the
        REAL serving path (stress + gated projection), is PSD at compute
        dtype — min eigenvalue above the kernel's own reconstruction
        floor, -K * eps * lambda_max."""
        from mfm_tpu.scenario.kernel import psd_project, stress_cov
        K = self.K
        t = jnp.array(np.asarray(theta, self.dtype))
        cov_p, _, _ = psd_project(stress_cov(
            jnp.array(self.cov), t[:K], t[K:2 * K], t[2 * K], t[2 * K + 1]))
        lam = np.linalg.eigvalsh(np.asarray(cov_p, np.float64))
        eps = float(np.finfo(self.dtype).eps)
        return bool(lam[0] >= -K * eps * max(lam[-1], 0.0))

    # -- sensitivity reports -------------------------------------------------
    def sensitivities(self, specs, portfolio, *,
                      bucket: int | None = None) -> list:
        """Exact ∂vol/∂shock + ∂vol/∂exposure rows for each spec, for one
        portfolio (ONE donated jit call).

        Returns one entry dict per spec in input order: rejected specs
        carry ``status="rejected"`` + problems and no rows (the
        scenario-engine admission rules, applied identically); ok specs
        carry the vol at the shock point and the five Jacobian blocks,
        with non-finite rows recorded as ``null`` + ``nondifferentiable``.
        """
        specs = list(specs)
        S = len(specs)
        if S < 1:
            raise ValueError("need at least one scenario spec")
        x = np.asarray(portfolio, self.dtype).reshape(-1)
        if x.shape != (self.K,):
            raise ValueError(f"portfolio must be ({self.K},), got "
                             f"{x.shape}")
        B = bucket_for(S) if bucket is None else int(bucket)
        if B < S:
            raise ValueError(f"bucket {B} < batch size {S}")

        base = np.broadcast_to(self.cov, (B, self.K, self.K)).copy()
        shift = np.zeros((B, self.K), self.dtype)
        scale = np.ones((B, self.K), self.dtype)
        vol_mult = np.ones((B,), self.dtype)
        corr_beta = np.zeros((B,), self.dtype)
        lane_problems = []
        for i, spec in enumerate(specs):
            cov_i, problems = self._scen._resolve(spec)
            lane_problems.append(tuple(problems))
            if problems:
                continue   # rejected: the lane computes the identity point
            base[i] = cov_i
            shift[i], scale[i] = self._scen._shock_vectors(spec)
            vol_mult[i] = spec.vol_mult
            corr_beta[i] = spec.corr_beta

        vol, d_shift, d_scale, d_vm, d_cb, d_x = sensitivity_batch(
            jnp.array(base), jnp.array(shift), jnp.array(scale),
            jnp.array(vol_mult), jnp.array(corr_beta), jnp.array(x))
        vol = np.asarray(vol)
        d_shift = np.asarray(d_shift)
        d_scale = np.asarray(d_scale)
        d_vm = np.asarray(d_vm)
        d_cb = np.asarray(d_cb)
        d_x = np.asarray(d_x)

        entries = []
        for i, spec in enumerate(specs):
            e = {"name": spec.name, "status": "ok", "problems": []}
            if lane_problems[i]:
                e.update(status="rejected",
                         problems=list(lane_problems[i]))
                entries.append(e)
                continue
            rows = np.concatenate([d_shift[i], d_scale[i],
                                   [d_vm[i], d_cb[i]], d_x[i]])
            finite = bool(np.isfinite(rows).all() and np.isfinite(vol[i]))
            e.update({
                "vol": float(vol[i]) if np.isfinite(vol[i]) else None,
                "nondifferentiable": not finite,
                "d_vol_mult": _num(d_vm[i]),
                "d_corr_beta": _num(d_cb[i]),
                "d_shift": _rows(self.factor_names, d_shift[i]),
                "d_scale": _rows(self.factor_names, d_scale[i]),
                "d_exposure": _rows(self.factor_names, d_x[i]),
            })
            entries.append(e)
        return entries

    # -- portfolio construction ---------------------------------------------
    def construct_solve(self, solver: str, weights, *, lo=None, hi=None,
                        hedge_mask=None, hmax: float = 1.0,
                        eta: float | None = None, steps: int | None = None,
                        bucket: int | None = None) -> dict:
        """Run ONE construction solver over P request books (one donated
        jit call at the padded bucket).  ``weights``: (P, K) exposure
        rows — min-vol / risk-parity use them as warm starts, hedge as
        the fixed base books.  Returns ``{"weights", "vols", "diag"}``
        trimmed to P rows (``diag``: kkt residual / rc spread / overlay).
        """
        if solver not in SOLVERS:
            raise ValueError(f"unknown solver {solver!r}; have {SOLVERS}")
        W = np.atleast_2d(np.asarray(weights, self.dtype))
        if W.ndim != 2 or W.shape[1] != self.K:
            raise ValueError(f"weights must be (P, {self.K}), got {W.shape}")
        P = W.shape[0]
        B = bucket_for(P) if bucket is None else int(bucket)
        if B < P:
            raise ValueError(f"bucket {B} < batch size {P}")
        cov = jnp.array(self.cov)

        if solver == "hedge":
            xs0 = np.zeros((B, self.K), self.dtype)
            xs0[:P] = W
            hs0 = np.zeros((B, self.K), self.dtype)
            mask = np.zeros((B, self.K), self.dtype)
            if hedge_mask is None:
                mask[:P] = 1.0
            else:
                mask[:P] = np.asarray(hedge_mask, self.dtype)
            xt, h, vol = hedge_batch(
                jnp.array(xs0), jnp.array(hs0), cov, jnp.array(mask),
                jnp.asarray(hmax, self.dtype),
                jnp.asarray(HEDGE_ETA if eta is None else eta, self.dtype),
                jnp.int32(HEDGE_STEPS if steps is None else steps))
            return {"weights": np.asarray(xt)[:P],
                    "vols": np.asarray(vol)[:P],
                    "diag": np.asarray(h)[:P]}

        # simplex solvers: warm-start from the request book's positive
        # part, blended 10% toward uniform — the multiplicative min-vol
        # update can never resurrect a coordinate that starts at exactly
        # zero, so copying the book verbatim would silently restrict the
        # solve to the book's support (a one-factor book would come back
        # "solved" at its own vol).  An all-zero (or all-short) book
        # starts uniform outright; pad lanes stay exactly zero.
        xs0 = np.zeros((B, self.K), self.dtype)
        pos = np.maximum(W, 0)
        sums = pos.sum(axis=1, keepdims=True)
        uniform = np.full((1, self.K), 1.0 / self.K, self.dtype)
        xs0[:P] = np.where(sums > 0,
                           0.9 * pos / np.maximum(sums, 1e-300)
                           + 0.1 * uniform,
                           uniform)
        if solver == "min_vol":
            lo_v = (np.zeros(self.K, self.dtype) if lo is None
                    else np.asarray(lo, self.dtype))
            hi_v = (np.ones(self.K, self.dtype) if hi is None
                    else np.asarray(hi, self.dtype))
            x, vol, kkt = minvol_batch(
                jnp.array(xs0), cov, jnp.array(lo_v), jnp.array(hi_v),
                jnp.asarray(MINVOL_ETA if eta is None else eta, self.dtype),
                jnp.int32(MINVOL_STEPS if steps is None else steps))
            return {"weights": np.asarray(x)[:P],
                    "vols": np.asarray(vol)[:P],
                    "diag": np.asarray(kkt)[:P]}
        x, vol, spread = riskparity_batch(
            jnp.array(xs0), cov,
            jnp.asarray(RISKPARITY_ETA if eta is None else eta, self.dtype),
            jnp.int32(RISKPARITY_STEPS if steps is None else steps))
        return {"weights": np.asarray(x)[:P],
                "vols": np.asarray(vol)[:P],
                "diag": np.asarray(spread)[:P]}


def _num(v):
    return float(v) if np.isfinite(v) else None


def _rows(names, vals) -> dict:
    return {str(n): _num(v) for n, v in zip(names, vals)}
