"""Configuration for the whole pipeline as plain dataclasses.

The reference scatters its constants across ``Barra_factor_cal/config.py``
(factor list / composite weights / ortho rules / renames), hardcoded literals
(windows and half-lives inside ``factor_calculator.py``), and literal kwargs at
call sites (``Barra-master/demo.py:38-42``).  Here everything lives in one
typed config tree so a run is fully described by a single object.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class RollingSpec:
    """Window / half-life / min-periods triple for one rolling factor.

    Mirrors the literals in the reference, e.g. BETA's ``T, HALF_LIFE,
    MIN_PERIODS = 252, 63, 42`` (``factor_calculator.py:86``).
    """

    window: int
    half_life: int | None = None
    min_periods: int = 1


@dataclasses.dataclass(frozen=True)
class FactorConfig:
    """Every constant of the style-factor layer.

    Defaults reproduce the reference exactly:
    - BETA/HSIGMA: 252/63/42, tail-aligned exp weights
      (``factor_calculator.py:86-88``)
    - RSTR: T=504, lag L=21 => window 483, half-life 126, min 42,
      head-aligned weights renormalized over valid (``factor_calculator.py:130-142``)
    - DASTD: 252/42/42 tail-aligned renormalized (``factor_calculator.py:159-180``)
    - CMRA: 252, full window required (``factor_calculator.py:204-219``)
    - liquidity STOM/STOQ/STOA: 21/15, 63/42, 252/126 (``factor_calculator.py:346-350``)
    - composite weights / ortho rules (``Barra_factor_cal/config.py:23-50``)
    - winsorize at mean +/- 2.5 sample std (``post_processing.py:12-15``)
    """

    beta: RollingSpec = RollingSpec(window=252, half_life=63, min_periods=42)
    rstr_total: int = 504
    rstr_lag: int = 21
    rstr_half_life: int = 126
    rstr_min_periods: int = 42
    dastd: RollingSpec = RollingSpec(window=252, half_life=42, min_periods=42)
    cmra_window: int = 252
    stom: RollingSpec = RollingSpec(window=21, min_periods=15)
    stoq: RollingSpec = RollingSpec(window=63, min_periods=42)
    stoa: RollingSpec = RollingSpec(window=252, min_periods=126)

    winsorize_n_std: float = 2.5

    factors_to_run: Tuple[str, ...] = (
        "SIZE", "BETA", "RSTR", "DASTD", "CMRA", "NLSIZE", "BP",
        "LIQUIDITY", "EARNINGS", "GROWTH", "LEVERAGE",
    )

    # (name, components, weights) triples; missing components drop out with
    # weight renormalization (post_processing.py:35-43).  Tuples (not dicts)
    # keep the config hashable so it can be a jit static argument.
    composite: Tuple[Tuple[str, Tuple[str, ...], Tuple[float, ...]], ...] = (
        ("volatility", ("DASTD", "CMRA", "HSIGMA"), (0.7, 0.15, 0.15)),
        ("leverage", ("MLEV", "DTOA", "BLEV"), (1 / 3, 1 / 3, 1 / 3)),
        ("liquidity", ("STOM", "STOQ", "STOA"), (0.5, 0.25, 0.25)),
        ("earnings", ("CETOP", "ETOP"), (0.5, 0.5)),
        ("growth", ("YOYProfit", "YOYSales"), (0.5, 0.5)),
    )

    # (target, regressors) pairs; per-date OLS residualization
    # (post_processing.py:47-69)
    ortho_rules: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
        ("volatility", ("BETA", "SIZE")),
        ("liquidity", ("SIZE",)),
    )

    # final barra-style column names, in output order
    # (Barra_factor_cal/config.py:53-72)
    rename_map: Tuple[Tuple[str, str], ...] = (
        ("SIZE", "size"),
        ("BETA", "beta"),
        ("RSTR", "momentum"),
        ("volatility", "residual_volatility"),
        ("NLSIZE", "non_linear_size"),
        ("BP", "book_to_price_ratio"),
        ("liquidity", "liquidity"),
        ("earnings", "earnings_yield"),
        ("growth", "growth"),
        ("leverage", "leverage"),
    )
    output_styles: Tuple[str, ...] = (
        "size", "beta", "momentum", "residual_volatility", "non_linear_size",
        "book_to_price_ratio", "liquidity", "earnings_yield", "growth",
        "leverage",
    )


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """Input-guard thresholds for the daily serving loop (serve/guard.py).

    Disabled by default: the historical fit path trusts its inputs (they
    were assembled and validated upstream); the *serving* path — appended
    slabs arriving one date at a time from a live feed — is where a bad day
    must be caught before it poisons the Newey-West / vol-regime EWMA
    carries forever.  A date that trips any check is QUARANTINED: the model
    serves the last healthy covariance with a staleness counter and the
    recursive carries skip the date entirely, so the carry after
    (good, BAD, good) equals the carry after (good, good) bitwise.

    Thresholds are math identity: they decide which dates enter the EWMA
    sums, so they are stamped into checkpoints via
    :meth:`RiskModelConfig.identity`.
    """

    enabled: bool = False
    #: quarantine when the fraction of non-finite returns inside the
    #: universe exceeds this (a NaN-poisoned feed day)
    max_nan_frac: float = 0.05
    #: cross-sectional |ret - median| > mad_k * MAD marks an outlier cell;
    #: the date is quarantined when the outlier fraction exceeds
    #: ``max_outlier_frac`` (fat-fingered prices / split-adjustment bugs)
    mad_k: float = 10.0
    max_outlier_frac: float = 0.05
    #: quarantine when the universe (valid count) collapses below this
    #: fraction of the trailing-median universe over ``universe_window``
    #: healthy dates (half the market missing = upstream join broke)
    min_universe_frac: float = 0.5
    universe_window: int = 63

    def identity(self) -> tuple:
        return (self.enabled, self.max_nan_frac, self.mad_k,
                self.max_outlier_frac, self.min_universe_frac,
                self.universe_window)

    def __post_init__(self):
        if not (isinstance(self.universe_window, int)
                and not isinstance(self.universe_window, bool)
                and self.universe_window >= 1):
            raise ValueError(f"universe_window must be a positive int, "
                             f"got {self.universe_window!r}")
        for name in ("max_nan_frac", "max_outlier_frac", "min_universe_frac"):
            v = getattr(self, name)
            if not 0.0 <= float(v) <= 1.0:
                raise ValueError(f"{name} must be in [0, 1], got {v!r}")
        if float(self.mad_k) <= 0:
            raise ValueError(f"mad_k must be positive, got {self.mad_k!r}")


@dataclasses.dataclass(frozen=True)
class RiskModelConfig:
    """Hyper-parameters of the covariance stack.

    Defaults match ``Barra-master/demo.py:38-42``: Newey-West q=2 tau=252,
    eigenfactor adjustment M=100 scale=1.4, vol-regime tau=42 (note the
    method default in the reference is tau=84, ``mfm/MFM.py:130``; the demo
    overrides it to 42 — we default to the demo's value and document both).
    """

    nw_lags: int = 2
    nw_half_life: float = 252.0
    #: expanding Newey-West evaluation: "scan" (O(T) serial lax.scan, the
    #: single-chip default) or "associative" (lax.associative_scan — O(log T)
    #: depth, the date axis stays sharded; the sequence-parallel choice for
    #: long panels on a date-sharded mesh, models/newey_west.py:138-224)
    nw_method: str = "scan"
    eigen_n_sims: int = 100
    eigen_scale_coef: float = 1.4
    eigen_sim_length: int | None = None  # None => use panel length T (MFM.py:119)
    # Jacobi sweep cap for the (T, M) simulated eighs on the Pallas TPU path
    # ("auto" => models.eigen.sim_sweeps_for(K, dtype, sim_length), e.g. 5
    # at K=42 — measured bitwise-equal to the solver default there at ~30%
    # less eigen-stage wall-clock; the reduction and the unsorted fast path
    # only engage when the sims' near-diagonality premise holds, see
    # models/eigen.py; None => solver default; ignored where batched_eigh
    # falls back to XLA/LAPACK).  The F0 decomposition always runs at full
    # precision.
    eigen_sim_sweeps: int | str | None = "auto"
    #: date-chunk size for the eigen Monte-Carlo stream (models/eigen.py):
    #: the (T, M, K, K) simulated-covariance transient — the pipeline's
    #: largest allocation at production scale — is never materialized;
    #: lax.map runs the sim eighs over (chunk, M, K, K) slabs instead.
    #: "auto" (default) sizes the chunk from backend memory headroom at
    #: trace time and keeps the full batch when it fits
    #: (models.eigen.auto_eigen_chunk); None => always full batch; an
    #: int >= 1 pins the slab size.  Chunked and full-batch results are
    #: identical (same per-date op sequence, chunk-invariant solver
    #: dispatch).
    eigen_chunk: int | str | None = "auto"
    #: Monte-Carlo draw/assembly dtype for the eigen bias simulation
    #: (models/eigen.py).  None (default) keeps everything at the panel
    #: compute dtype — bitwise-unchanged.  "bfloat16" draws the sims and
    #: forms the scaled Gram matrices in bf16 with f32 accumulation
    #: (dot-general ``preferred_element_type``) and runs the eighs in f32;
    #: the result is NOT bitwise the f32 path but is gated by the
    #: eigenfactor-bias parity budget (tools/parity_budget.json
    #: ``eigen_mc_bf16``) instead.  Changes the numbers => part of
    #: ``identity()``.
    eigen_mc_dtype: str | None = None
    #: opt-in incremental eigen draws for the daily serving loop.  The
    #: default draw construction is ``normal(key, (M, K, T))`` whose values
    #: depend on the total length T, so a checkpoint's Monte-Carlo bias can
    #: only stay bitwise against a full-history rerun by pinning
    #: ``eigen_sim_length``.  With ``eigen_incremental=True`` the draws are
    #: instead generated once into a power-of-two padded bucket
    #: ``(M, K, Tpad)`` and the per-date sim covariances are re-estimated
    #: from the first ``T`` columns under a mask — a construction whose
    #: first-T values are INVARIANT as T grows, so the eigen bias tracks the
    #: growing history at full fidelity (sim_length == T, like a default
    #: full-history run) while each daily update stays O(new dates):
    #: bitwise-suffix-equal to a mode-on full-history rebuild
    #: (tests/test_risk_state.py).  Mutually exclusive with a pinned
    #: ``eigen_sim_length``.  Changes the draw values => part of
    #: ``identity()``.
    eigen_incremental: bool = False
    vol_regime_half_life: float = 42.0
    seed: int = 0
    #: serving-loop input guards + degraded mode (serve/guard.py); disabled
    #: by default so the historical fit path is untouched
    quarantine: QuarantinePolicy = dataclasses.field(
        default_factory=QuarantinePolicy)

    def identity(self) -> tuple:
        """The math identity of the covariance stack: every field that can
        change the numbers.  ``eigen_chunk`` is excluded — chunked and
        full-batch evaluation are bitwise identical (models/eigen.py), so it
        is an execution knob, not a model parameter.  The quarantine policy
        IS included: it decides which dates enter the EWMA sums.  Stamped
        into ``RiskModelState`` so a checkpoint refuses to resume under a
        config that would silently change the math mid-history.
        """
        return (
            self.nw_lags, self.nw_half_life, self.nw_method,
            self.eigen_n_sims, self.eigen_scale_coef, self.eigen_sim_length,
            self.eigen_sim_sweeps, self.eigen_mc_dtype,
            self.eigen_incremental, self.vol_regime_half_life, self.seed,
            self.quarantine.identity(),
        )

    def __post_init__(self):
        s = self.eigen_sim_sweeps
        ok = s is None or s == "auto" or (
            isinstance(s, int) and not isinstance(s, bool) and s >= 1
        )
        if not ok:
            raise ValueError(
                f"eigen_sim_sweeps must be an int >= 1, None, or 'auto'; "
                f"got {s!r}"
            )
        if self.nw_method not in ("scan", "associative"):
            raise ValueError(
                f"nw_method must be 'scan' or 'associative', "
                f"got {self.nw_method!r}"
            )
        c = self.eigen_chunk
        ok = c is None or c == "auto" or (
            isinstance(c, int) and not isinstance(c, bool) and c >= 1
        )
        if not ok:
            raise ValueError(
                f"eigen_chunk must be an int >= 1, None, or 'auto'; got {c!r}"
            )
        if self.eigen_mc_dtype not in (None, "bfloat16"):
            raise ValueError(
                f"eigen_mc_dtype must be None or 'bfloat16', "
                f"got {self.eigen_mc_dtype!r}"
            )
        if not isinstance(self.eigen_incremental, bool):
            raise ValueError(
                f"eigen_incremental must be a bool, "
                f"got {self.eigen_incremental!r}"
            )
        if self.eigen_incremental and self.eigen_sim_length is not None:
            raise ValueError(
                "eigen_incremental=True tracks the growing panel length "
                "(sim_length == T) by construction; a pinned "
                f"eigen_sim_length ({self.eigen_sim_length}) contradicts it "
                "— pick one"
            )


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh shape. axis 'date' shards the time axis (cross-sectional
    regressions, eigen MC), axis 'stock' shards the stock axis (rolling factor
    kernels, cross-sectional reductions become psums over 'stock')."""

    n_date_shards: int = 1
    n_stock_shards: int = 1


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    factors: FactorConfig = dataclasses.field(default_factory=FactorConfig)
    risk: RiskModelConfig = dataclasses.field(default_factory=RiskModelConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    dtype: str = "float32"  # compute dtype on TPU; tests use float64 on CPU
    #: rolling-kernel date-block size (memory = block x window x N elements
    #: per input, ops/rolling.py::rolling_reduce).  None (default) = derive
    #: from the panel width and dtype at run time (ops/rolling.py::auto_block:
    #: 64 at CSI300's 300 stocks, 16 at all-A's 5,000 per the BASELINE.md
    #: block sweep).
    block: int | None = None
    #: rolling-kernel implementation: "scan" (O(T*N) two-level chunked
    #: scans, the default) or "block" (the windowed-gather reference
    #: formulation; uses ``block``)
    rolling_impl: str = "scan"

    def __post_init__(self):
        from mfm_tpu.ops.rolling import ROLLING_IMPLS

        if self.rolling_impl not in ROLLING_IMPLS:
            raise ValueError(f"rolling_impl must be one of {ROLLING_IMPLS}, "
                             f"got {self.rolling_impl!r}")
        if self.block is None:
            return
        if not isinstance(self.block, int) or isinstance(self.block, bool) \
                or self.block < 1:
            raise ValueError(f"block must be a positive int or None, "
                             f"got {self.block!r}")
