"""mfmlint — the repo's JAX doctrine as a static gate.

Every rule here is a bug class this codebase has already paid for (the
incident record lives in docs/DOCTRINE.md):

  R1  no host-numpy compute inside traced code
  R2  integer dtypes in traced code must be explicit s32 (arange / iota /
      astype / fori_loop bounds) — the s64-under-SPMD class that broke
      tier-1 twice
  R3  jax.config.update / enable_compilation_cache only in designated
      entrypoint modules, and never the same key twice per module
  R4  no use of a donated argument after its donating call
  R5  perf_counter timing spans around async-dispatch (JAX) work must
      force results (block_until_ready) inside the span
  R6  PartitionSpec axis names must come from the mesh doctrine
      (parallel/mesh.py)
  R7  no metrics/logging (mfm_tpu.obs / utils.obs) reachable from traced
      code — telemetry is host-side only; record around the jit boundary

The analysis is a conservative intra-package call graph over the linted
files: functions reachable from ``jax.jit``/``pjit``/``vmap``/``lax.scan``/
``lax.fori_loop``/``lax.map``/... call sites count as *traced*; attribute
calls resolve by bare method name against every known def (over-approximate
on purpose — a missed edge hides a real s64, a spurious edge costs at most a
baseline entry).  ``pallas_call`` kernels are deliberately NOT traced roots:
Mosaic has no 64-bit types at all, so the s64 class cannot arise there and
the kernels' host-side planners are free to use numpy.

Intentional exceptions live in ``tools/mfmlint_baseline.json`` keyed by
(file, rule, function) — line-number free so refactors don't churn it.  The
default run exits non-zero only on NEW violations; ``--strict`` also fails
on stale baseline entries (grandfathered violations that no longer exist).

This module imports neither jax nor numpy: it is safe to run anywhere,
including as the first step of TPU capture scripts.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Iterable

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_TARGETS = ("mfm_tpu", "bench.py", "tools")
DEFAULT_BASELINE = os.path.join("tools", "mfmlint_baseline.json")

RULES = {
    "R1": "host-numpy compute inside traced code (host sync / tracer "
          "concretization; use jnp or hoist to the host path)",
    "R2": "integer dtype in traced code must be explicit s32 — unpinned "
          "arange/iota/astype/fori_loop bounds canonicalize to s64 under "
          "x64 and trip XLA's s32 SPMD shard-offset math",
    "R3": "jax.config.update / compilation-cache setup only in designated "
          "entrypoint modules, at most once per key per module",
    "R4": "donated argument used after its donating call (the buffer may "
          "already be retired into the callee's outputs)",
    "R5": "perf_counter span times async-dispatch JAX work without forcing "
          "it (block_until_ready) — the span measures dispatch, not compute",
    "R6": "PartitionSpec axis name outside the mesh doctrine "
          "(parallel/mesh.py defines the only legal mesh axes)",
    "R7": "metrics/logging call reachable from traced code — telemetry "
          "(mfm_tpu.obs, utils/obs.py) is host-side only; it syncs or "
          "concretizes under trace.  Record around the jit boundary",
}

# numpy attributes that are dtype/constant plumbing, not compute — legal
# anywhere, including traced code
_NP_ALLOWED = {
    "dtype", "float16", "float32", "float64", "int8", "int16", "int32",
    "int64", "uint8", "uint16", "uint32", "uint64", "bool_", "generic",
    "integer", "floating", "complexfloating", "number", "ndarray",
    "datetime64", "timedelta64", "finfo", "iinfo", "issubdtype",
    "result_type", "promote_types", "pi", "e", "inf", "nan", "newaxis",
    "errstate",
}

# modules (dotted) allowed to mutate process-global jax config.  tools/ are
# each their own CLI entrypoint; cli.py and utils/cache.py are the package's
# designated config owners; bench.py is a standalone entrypoint.
_R3_ALLOWED_MODULES = ("mfm_tpu.cli", "mfm_tpu.utils.cache", "bench")
_R3_ALLOWED_PREFIXES = ("tools.",)

# telemetry modules: host-side only, never reachable from traced code (R7).
# The mfm_tpu.obs prefix covers the whole subsystem — metrics, exporters,
# manifests, AND the tracing/profiling additions (obs/trace.py spans sync
# a monotonic clock per call; obs/profile.py triggers lowering/compiles) —
# so a span opened or a profile pulled inside a jitted function flags.
_R7_OBS_MODULES = ("mfm_tpu.utils.obs", "mfm_tpu.obs")

# serving-loop modules that are host-side BY DESIGN (breaker, admission
# queue, JSON decode, dead-letter IO): the traced-closure propagation
# treats them as barriers — it neither enters nor crosses them, so the
# conservative bare-name resolution can't drag the request loop (and,
# through it, the telemetry registry) into the traced set off a name
# collision like `run`/`query`/`identity`.  The scenario engine and its
# manifest writer join the list for the same reason: ScenarioEngine.run
# shares its bare name with the traced RiskModel.run, and both modules
# record obs metrics / do JSON+fsync IO that must stay host-side (the
# scenario DEVICE code lives alone in scenario/kernel.py, which stays
# fully lintable)
_R7_HOST_ONLY_MODULES = ("mfm_tpu.serve.server", "mfm_tpu.cli",
                         # the fleet layer is pure host plumbing: threads,
                         # sockets, subprocess pipes — no device code at all
                         "mfm_tpu.serve.coalesce",
                         "mfm_tpu.serve.frontend",
                         "mfm_tpu.serve.replica",
                         "mfm_tpu.serve.transport",
                         "mfm_tpu.scenario.engine",
                         "mfm_tpu.scenario.manifest",
                         # grad host orchestration + report writer (the
                         # grad DEVICE code lives in grad/reverse.py,
                         # grad/construct.py, grad/sensitivity.py — all
                         # fully lintable)
                         "mfm_tpu.grad.engine",
                         "mfm_tpu.grad.report",
                         # concurrency tooling: the AST lock-discipline
                         # pass and the deterministic scheduler are pure
                         # host code, and their stdlib-shaped method
                         # names (run/get/put/add/wait/value) collide
                         # with half the package under bare-name
                         # resolution
                         "mfm_tpu.analysis.sync",
                         "mfm_tpu.utils.sched")


def _is_obs_module(module: str) -> bool:
    return module in _R7_OBS_MODULES or module.startswith("mfm_tpu.obs.")


def _is_host_only_module(module: str) -> bool:
    return module in _R7_HOST_ONLY_MODULES

_TRACER_JIT = {"jit", "pjit", "vmap", "pmap", "checkpoint", "remat", "grad",
               "value_and_grad"}
_TRACER_LAX = {"scan", "fori_loop", "map", "while_loop", "cond", "switch",
               "associative_scan"}

# calls that force device work to completion on the host (R5)
_FORCING_NAMES = {"block_until_ready", "force", "_force", "asarray", "array",
                  "to_numpy", "item", "compile", "memory_analysis"}

_INT64_STRS = {"int64", "long", "i8"}


def _attr_chain(node) -> list[str] | None:
    """a.b.c -> ['a', 'b', 'c']; None when the root isn't a plain Name."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return None


def _const_str(node) -> str | None:
    return node.value if (isinstance(node, ast.Constant)
                          and isinstance(node.value, str)) else None


@dataclasses.dataclass
class Violation:
    file: str
    line: int
    rule: str
    qualname: str
    message: str

    def key(self) -> tuple:
        return (self.file, self.rule, self.qualname)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} [{self.qualname}] "
                f"{self.message}\n    doctrine: {RULES[self.rule]}")


@dataclasses.dataclass
class FuncInfo:
    qualname: str            # module:Outer.inner or module:<lambda@L..>
    module: str
    name: str                # bare name ('' for lambdas)
    node: object             # ast.FunctionDef | ast.Lambda
    parent: str | None       # enclosing function qualname
    file: str


@dataclasses.dataclass
class ModuleInfo:
    name: str                # dotted module name
    file: str                # path as given (repo-relative when possible)
    tree: object
    # import alias sets
    np_aliases: set = dataclasses.field(default_factory=set)
    jnp_aliases: set = dataclasses.field(default_factory=set)
    jax_aliases: set = dataclasses.field(default_factory=set)
    lax_aliases: set = dataclasses.field(default_factory=set)
    time_aliases: set = dataclasses.field(default_factory=set)
    functools_aliases: set = dataclasses.field(default_factory=set)
    partial_aliases: set = dataclasses.field(default_factory=set)
    pspec_aliases: set = dataclasses.field(default_factory=set)
    # local name -> (target module, attr) for from-imports
    from_imports: dict = dataclasses.field(default_factory=dict)
    # local alias -> dotted module for module imports
    module_imports: dict = dataclasses.field(default_factory=dict)
    # names imported directly from jax / jax.lax (e.g. `from jax import vmap`)
    jax_names: set = dataclasses.field(default_factory=set)
    lax_names: set = dataclasses.field(default_factory=set)
    # module-level defs: bare name -> qualname (methods as Class.meth)
    locals: dict = dataclasses.field(default_factory=dict)
    # local function names stored as values in module-level dict registries
    # (e.g. the alpha DSL's _OPS table) — dispatched via subscript calls that
    # name resolution cannot see
    registry_names: set = dataclasses.field(default_factory=set)
    # class name -> attrs assigned from EXTERNAL handle constructors
    # (subprocess.Popen, socket.socket, open, .makefile()): method calls
    # through such a receiver (`self.proc.poll()`) are OS-handle I/O and
    # must never resolve into package defs via the bare-name fallback —
    # otherwise the fleet's Popen.poll() aliases Coalescer.poll and drags
    # the whole transport layer into the jax_touch closure
    external_attrs: dict = dataclasses.field(default_factory=dict)
    # function qualname -> LOCAL names bound to external handles or builtin
    # containers (`fh = open(...)`, `with open(...) as fh`, `ev = {...}`):
    # the local form of the typed-receiver barrier — `fh.flush()` must not
    # alias Coalescer.flush, `ev.update(...)` must not alias
    # RiskModel.update
    external_fn_locals: dict = dataclasses.field(default_factory=dict)


class _Scanner(ast.NodeVisitor):
    """Collect imports + every function (incl. nested and lambdas)."""

    def __init__(self, mod: ModuleInfo, funcs: dict, bare_index: dict):
        self.mod = mod
        self.funcs = funcs
        self.bare_index = bare_index
        self.scope: list[str] = []      # class/function name stack
        self.class_stack: list[str] = []  # enclosing ClassDef names only

    # -- imports ------------------------------------------------------------
    def visit_Import(self, node):
        for a in node.names:
            alias = a.asname or a.name.split(".")[0]
            tgt = a.name
            if tgt == "numpy":
                self.mod.np_aliases.add(alias)
            elif tgt == "jax.numpy":
                self.mod.jnp_aliases.add(a.asname or "jax")
            elif tgt == "jax":
                self.mod.jax_aliases.add(alias)
            elif tgt == "jax.lax":
                self.mod.lax_aliases.add(a.asname or "jax")
            elif tgt == "time":
                self.mod.time_aliases.add(alias)
            elif tgt == "functools":
                self.mod.functools_aliases.add(alias)
            else:
                self.mod.module_imports[alias] = tgt
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        src = node.module or ""
        for a in node.names:
            local = a.asname or a.name
            if src == "jax" and a.name == "numpy":
                self.mod.jnp_aliases.add(local)
            elif src == "jax" and a.name == "lax":
                self.mod.lax_aliases.add(local)
            elif src == "jax":
                self.mod.jax_names.add(local)
            elif src in ("jax.lax",):
                self.mod.lax_names.add(local)
            elif src == "functools" and a.name == "partial":
                self.mod.partial_aliases.add(local)
            elif src == "time":
                self.mod.time_aliases.add(local)
            elif a.name == "PartitionSpec" or (
                    src.endswith("sharding") and a.name == "PartitionSpec"):
                self.mod.pspec_aliases.add(local)
            else:
                self.mod.from_imports[local] = (src, a.name)
        self.generic_visit(node)

    # -- defs ---------------------------------------------------------------
    def _register(self, name: str, node):
        qual = f"{self.mod.name}:{'.'.join(self.scope + [name]) or name}"
        parent = None
        # nearest enclosing *function* (skip class frames)
        for i in range(len(self.scope) - 1, -1, -1):
            cand = f"{self.mod.name}:{'.'.join(self.scope[: i + 1])}"
            if cand in self.funcs:
                parent = cand
                break
        self.funcs[qual] = FuncInfo(qual, self.mod.name, name, node, parent,
                                    self.mod.file)
        if name and not name.startswith("<"):
            self.bare_index.setdefault(name, []).append(qual)
        if len(self.scope) == 0 or all(
                f"{self.mod.name}:{'.'.join(self.scope[:i + 1])}"
                not in self.funcs for i in range(len(self.scope))):
            # module-level def or method of a module-level class
            self.mod.locals.setdefault(name, qual)
        return qual

    def _visit_func(self, node, name):
        self._register(name, node)
        self.scope.append(name)
        self.generic_visit(node)
        self.scope.pop()

    def visit_FunctionDef(self, node):
        self._visit_func(node, node.name)

    def visit_AsyncFunctionDef(self, node):
        self._visit_func(node, node.name)

    def visit_ClassDef(self, node):
        self.scope.append(node.name)
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()
        self.scope.pop()

    def visit_Lambda(self, node):
        self._visit_func(node, f"<lambda@L{node.lineno}>")

    def _is_external_handle_ctor(self, call: ast.Call) -> bool:
        """subprocess.Popen / socket.* / open / .makefile() — constructors
        of OS handles whose methods (poll/wait/kill/recv/...) share bare
        names with half the package but can never be package calls."""
        f = call.func
        if isinstance(f, ast.Name):
            if f.id == "open":
                return True
            src = self.mod.from_imports.get(f.id)
            return bool(src and src[0] in ("subprocess", "socket"))
        chain = _attr_chain(f)
        if not chain:
            return False
        root, attr = chain[0], chain[-1]
        if attr == "makefile":
            return True
        tgt = self.mod.module_imports.get(root)
        if tgt == "subprocess" and attr == "Popen":
            return True
        return tgt == "socket" and attr in ("socket", "create_connection",
                                            "socketpair")

    _CONTAINER_CTORS = ("dict", "list", "set", "frozenset", "bytearray")

    def _is_builtin_container(self, value) -> bool:
        """Dict/list/set displays, comprehensions, and calls to the builtin
        container constructors — receivers whose methods (update, append,
        flush-free but get/keys/add/...) can never be package calls."""
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                              ast.ListComp, ast.SetComp)):
            return True
        return (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in self._CONTAINER_CTORS
                and value.func.id not in self.mod.from_imports)

    def _current_func(self) -> str | None:
        for i in range(len(self.scope), 0, -1):
            q = f"{self.mod.name}:{'.'.join(self.scope[:i])}"
            if q in self.funcs:
                return q
        return None

    def _note_local_binding(self, name: str, external: bool) -> None:
        """Track (or, on rebind to anything else, untrack) a function-local
        name bound to an external handle / builtin container."""
        qual = self._current_func()
        if qual is None:
            return
        bound = self.mod.external_fn_locals.setdefault(qual, set())
        if external:
            bound.add(name)
        else:
            bound.discard(name)

    def visit_With(self, node):
        # `with open(tmp) as fh:` — the canonical atomic-writer idiom;
        # fh.flush()/fh.write() are OS-handle I/O, never package calls
        for item in node.items:
            if isinstance(item.optional_vars, ast.Name):
                self._note_local_binding(
                    item.optional_vars.id,
                    isinstance(item.context_expr, ast.Call)
                    and self._is_external_handle_ctor(item.context_expr))
        self.generic_visit(node)

    visit_AsyncWith = visit_With

    def visit_Assign(self, node):
        # `phase1 = lambda ...` binds a function to a name: register the
        # lambda under that name so `jax.vmap(phase1)` resolves to it
        if isinstance(node.value, ast.Lambda) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            self._visit_func(node.value, node.targets[0].id)
            return
        # module-level dict registries: `_OPS = {"delta": delta, ...}`
        if not self.scope and isinstance(node.value, ast.Dict):
            for v in node.value.values:
                if isinstance(v, ast.Name):
                    self.mod.registry_names.add(v.id)
        # `self.proc = subprocess.Popen(...)`: remember the attr as an
        # OS-handle receiver for the typed-receiver barrier in
        # _resolve_call (Popen.poll must not alias Coalescer.poll)
        if self.class_stack and isinstance(node.value, ast.Call) \
                and self._is_external_handle_ctor(node.value):
            for t in node.targets:
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    self.mod.external_attrs.setdefault(
                        self.class_stack[-1], set()).add(t.attr)
        # `fh = open(...)` / `ev = {...}`: the local form of the same
        # barrier (rebinding to anything else untracks the name)
        external = (isinstance(node.value, ast.Call)
                    and self._is_external_handle_ctor(node.value)) \
            or self._is_builtin_container(node.value)
        for t in node.targets:
            if isinstance(t, ast.Name):
                self._note_local_binding(t.id, external)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        # annotated registry: `_OPS: Dict[str, Callable] = {...}`
        if not self.scope and isinstance(node.value, ast.Dict):
            for v in node.value.values:
                if isinstance(v, ast.Name):
                    self.mod.registry_names.add(v.id)
        if node.value is not None and isinstance(node.target, ast.Name):
            self._note_local_binding(
                node.target.id,
                (isinstance(node.value, ast.Call)
                 and self._is_external_handle_ctor(node.value))
                or self._is_builtin_container(node.value))
        self.generic_visit(node)

    def visit_Call(self, node):
        # module-level `_OPS.update({"alias": fn, ...})`
        if not self.scope and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "update":
            for a in node.args:
                if isinstance(a, ast.Dict):
                    for v in a.values:
                        if isinstance(v, ast.Name):
                            self.mod.registry_names.add(v.id)
        self.generic_visit(node)


def _own_nodes(func_node) -> Iterable[ast.AST]:
    """Walk a function's body without descending into nested functions.

    Nested FunctionDef/Lambda nodes are yielded (so call sites can see them
    as arguments) but their bodies belong to their own FuncInfo.
    """
    if isinstance(func_node, ast.Lambda):
        roots = [func_node.body]
    else:
        roots = list(func_node.body)
    stack = list(roots)
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(n))


class Linter:
    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.funcs: dict[str, FuncInfo] = {}
        self.bare_index: dict[str, list[str]] = {}
        self.edges: dict[str, set[str]] = {}
        self.traced: set[str] = set()
        self.jax_touch: set[str] = set()
        self.donating: dict[str, tuple] = {}   # qualname -> donated positions
        #: qualnames that are jit/pjit COMPILATION UNITS (decorated defs or
        #: jit(fn) call-form targets) — a strict subset of the traced roots,
        #: which also include vmap/scan/... function arguments.  The audit
        #: registry's completeness test (mfm_tpu/analysis/registry.py) keys
        #: off this set: every jit root must be registered or allowlisted.
        self.jit_roots: set[str] = set()
        self.mesh_axes: set[str] = {"date", "stock"}
        self.violations: list[Violation] = []

    # -- loading ------------------------------------------------------------
    def add_file(self, path: str, relto: str | None = None):
        rel = os.path.relpath(path, relto or os.getcwd())
        modname = rel[:-3].replace(os.sep, ".").lstrip(".")
        while modname.startswith("."):
            modname = modname[1:]
        with open(path, encoding="utf-8") as f:
            src = f.read()
        try:
            tree = ast.parse(src, filename=path)
        except SyntaxError as e:
            self.violations.append(Violation(
                rel, e.lineno or 0, "R1", "<module>",
                f"syntax error prevents linting: {e.msg}"))
            return
        mod = ModuleInfo(modname, rel, tree)
        self.modules[modname] = mod
        _Scanner(mod, self.funcs, self.bare_index).visit(tree)

    # -- resolution ---------------------------------------------------------
    def _resolve_in_module(self, mod: ModuleInfo, name: str) -> list[str]:
        if name in mod.locals:
            return [mod.locals[name]]
        if name in mod.from_imports:
            src, attr = mod.from_imports[name]
            tgt = self.modules.get(src)
            if tgt and attr in tgt.locals:
                return [tgt.locals[attr]]
            # from-import of a submodule: `from mfm_tpu import pipeline`
            sub = self.modules.get(f"{src}.{attr}" if src else attr)
            if sub:
                return []  # module object, not a function
        return []

    def _resolve_call(self, caller: FuncInfo, func_node) -> list[str]:
        """Call target qualnames for a Call's func expression (conservative)."""
        mod = self.modules[caller.module]
        if isinstance(func_node, ast.Name):
            name = func_node.id
            # scope chain: nested defs of enclosing functions
            p = caller.qualname
            while p is not None:
                info = self.funcs.get(p)
                if info is None:
                    break
                prefix = p + "."  # children qualnames are parent.child
                cand = None
                for q in self.funcs:
                    if q.startswith(prefix) and q[len(prefix):] == name:
                        cand = q
                        break
                if cand:
                    return [cand]
                p = info.parent
            return self._resolve_in_module(mod, name)
        chain = _attr_chain(func_node)
        if not chain:
            return []
        root, attr = chain[0], chain[-1]
        # external API roots: never package calls
        if root in (mod.np_aliases | mod.jnp_aliases | mod.jax_aliases
                    | mod.lax_aliases | mod.time_aliases
                    | mod.functools_aliases):
            return []
        if root in mod.module_imports:
            tgt = self.modules.get(mod.module_imports[root])
            if tgt:
                return self._resolve_in_module(tgt, attr)
            return []
        if root in mod.from_imports:
            src, a = mod.from_imports[root]
            tgt = self.modules.get(f"{src}.{a}" if src else a)
            if tgt:
                return self._resolve_in_module(tgt, attr)
        # typed-receiver barrier: `self.proc.poll()` on a field assigned
        # from subprocess.Popen/socket/open is OS-handle I/O — resolving
        # `poll` through the bare index would alias Coalescer.poll and
        # mark the whole fleet transport as dispatching jax work
        if root == "self" and len(chain) >= 3:
            cls_name = caller.qualname.split(":", 1)[1].split(".", 1)[0]
            ext = mod.external_attrs.get(cls_name)
            if ext and chain[1] in ext:
                return []
        # ... and its local form: `fh.flush()` / `ev.update(...)` where the
        # receiver was bound in this function (or an enclosing one) to an
        # open()/Popen/socket handle or a builtin container literal
        p = caller.qualname
        while p is not None:
            if root in mod.external_fn_locals.get(p, ()):
                return []
            info = self.funcs.get(p)
            p = info.parent if info is not None else None
        # bare-name over-approximation: any def in the lint set with this name
        return list(self.bare_index.get(attr, []))

    # -- classification -----------------------------------------------------
    def _is_tracer_call(self, mod: ModuleInfo, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return (f.id in mod.jax_names and f.id in _TRACER_JIT) or \
                   (f.id in mod.lax_names and f.id in _TRACER_LAX)
        chain = _attr_chain(f)
        if not chain:
            return False
        root, attr = chain[0], chain[-1]
        if root in mod.jax_aliases:
            if "lax" in chain[:-1]:
                return attr in _TRACER_LAX
            return attr in _TRACER_JIT
        if root in mod.lax_aliases and "lax" in chain:
            return attr in _TRACER_LAX
        return False

    def _is_jit_expr(self, mod: ModuleInfo, node) -> bool:
        """jax.jit / jit / pjit as a plain expression (decorator or callee)."""
        if isinstance(node, ast.Name):
            return node.id in mod.jax_names and node.id in {"jit", "pjit"}
        chain = _attr_chain(node)
        return bool(chain) and chain[0] in mod.jax_aliases and \
            chain[-1] in {"jit", "pjit"}

    def _func_args_of_call(self, caller: FuncInfo, call: ast.Call):
        """Function-valued arguments of a tracer call -> qualnames."""
        out = []
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            if isinstance(arg, ast.Lambda):
                q = self._lambda_qual(caller, arg)
                if q:
                    out.append(q)
            elif isinstance(arg, (ast.Name, ast.Attribute)):
                out.extend(self._resolve_call(caller, arg))
        return out

    def _lambda_qual(self, caller: FuncInfo, node: ast.Lambda) -> str | None:
        name = f"<lambda@L{node.lineno}>"
        for q, info in self.funcs.items():
            if info.node is node:
                return q
        # fall back by position
        cand = f"{caller.qualname.split(':')[0]}:{name}"
        return cand if cand in self.funcs else None

    def _donate_positions(self, call: ast.Call) -> tuple:
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(e.value for e in v.elts
                                 if isinstance(e, ast.Constant)
                                 and isinstance(e.value, int))
        return ()

    # -- graph construction --------------------------------------------------
    def build(self):
        roots: set[str] = set()
        for qual, info in self.funcs.items():
            mod = self.modules[info.module]
            self.edges.setdefault(qual, set())
            # decorators: @jax.jit / @partial(jax.jit, ...) mark the def as a
            # traced root; donate_argnums there registers donation positions
            node = info.node
            for dec in getattr(node, "decorator_list", []):
                if self._is_jit_expr(mod, dec):
                    roots.add(qual)
                    self.jit_roots.add(qual)
                elif isinstance(dec, ast.Call):
                    dchain = _attr_chain(dec.func) or []
                    is_partial = (
                        (dchain and dchain[-1] == "partial"
                         and (dchain[0] in mod.functools_aliases
                              or dchain[0] in mod.partial_aliases))
                        or (isinstance(dec.func, ast.Name)
                            and dec.func.id in mod.partial_aliases))
                    if is_partial and dec.args and \
                            self._is_jit_expr(mod, dec.args[0]):
                        roots.add(qual)
                        self.jit_roots.add(qual)
                        pos = self._donate_positions(dec)
                        if pos:
                            self.donating[qual] = pos
                    elif self._is_jit_expr(mod, dec.func):
                        roots.add(qual)
                        self.jit_roots.add(qual)
                        pos = self._donate_positions(dec)
                        if pos:
                            self.donating[qual] = pos
            for n in _own_nodes(node):
                if not isinstance(n, ast.Call):
                    continue
                tgts = self._resolve_call(info, n.func)
                for tgt in tgts:
                    self.edges[qual].add(tgt)
                if tgts:
                    # higher-order flow: a function-valued argument passed
                    # to a package function may be called by it (e.g.
                    # _dispatch_eigh(jacobi_fn=_jacobi)) — assume it is
                    fargs = self._func_args_of_call(info, n)
                    if fargs:
                        for t in tgts:
                            self.edges.setdefault(t, set()).update(fargs)
                if self._is_tracer_call(mod, n):
                    roots.update(self._func_args_of_call(info, n))
                if self._is_jit_expr(mod, n.func) and n.args:
                    # jax.jit(fn, ...) call form
                    tgt_funcs = []
                    a0 = n.args[0]
                    if isinstance(a0, ast.Lambda):
                        q = self._lambda_qual(info, a0)
                        if q:
                            tgt_funcs.append(q)
                    elif isinstance(a0, (ast.Name, ast.Attribute)):
                        tgt_funcs = self._resolve_call(info, a0)
                    roots.update(tgt_funcs)
                    self.jit_roots.update(tgt_funcs)
                    pos = self._donate_positions(n)
                    for t in tgt_funcs:
                        if pos:
                            self.donating[t] = pos
            # direct jax/jnp/lax usage marks jax_touch seed
            for n in _own_nodes(node):
                if isinstance(n, ast.Call):
                    chain = _attr_chain(n.func)
                    if chain and chain[0] in (mod.jax_aliases
                                              | mod.jnp_aliases
                                              | mod.lax_aliases):
                        self.jax_touch.add(qual)
                        break
                    if isinstance(n.func, ast.Name) and (
                            n.func.id in mod.jax_names
                            or n.func.id in mod.lax_names):
                        self.jax_touch.add(qual)
                        break

        # module-level jit(fn) bindings (``guard_jit = jax.jit(guard, ...)``)
        # are compilation units too: the def carries no decorator, so the
        # call form at module scope is the only evidence
        for mod in self.modules.values():
            for n in _own_nodes(mod.tree):
                if not (isinstance(n, ast.Call)
                        and self._is_jit_expr(mod, n.func) and n.args):
                    continue
                a0 = n.args[0]
                if not isinstance(a0, ast.Name):
                    continue  # attribute/lambda at module scope: none yet
                tgts = self._resolve_in_module(mod, a0.id)
                roots.update(tgts)
                self.jit_roots.update(tgts)
                pos = self._donate_positions(n)
                for t in tgts:
                    if pos:
                        self.donating[t] = pos

        # traced: forward closure from roots over call edges.  Host-only
        # serving modules (breaker/admission-queue/IO — _R7_HOST_ONLY_MODULES)
        # are barriers: the conservative bare-name resolution would otherwise
        # drag e.g. QueryServer.run into the closure off any traced call to a
        # method NAMED run, and from there mark the whole telemetry registry
        # traced.  Their functions can never really be traced (they json/IO/
        # sync by design), so propagation neither enters nor crosses them.
        def propagate(seed):
            stack = list(seed)
            while stack:
                q = stack.pop()
                if q in self.traced or \
                        _is_host_only_module(q.split(":", 1)[0]):
                    continue
                self.traced.add(q)
                stack.extend(self.edges.get(q, ()))

        propagate(roots)

        # Indirect-dispatch closure, iterated to a fixpoint:
        #  (a) a traced function calling through a subscript
        #      (`_OPS[name](*args)`) can reach any function stored in that
        #      module's dict registries;
        #  (b) a traced function calling an unresolvable local variable
        #      (`e(p)` where e is a closure/callable object) can reach any
        #      __call__ method defined in the lint set.
        _BUILTIN_CALLS = {
            "len", "range", "print", "int", "float", "bool", "str", "tuple",
            "list", "dict", "set", "frozenset", "min", "max", "abs", "sum",
            "zip", "enumerate", "sorted", "reversed", "isinstance", "getattr",
            "setattr", "hasattr", "repr", "type", "id", "map", "filter",
            "any", "all", "round", "divmod", "slice", "iter", "next", "vars",
            "open", "format", "hash", "ValueError", "TypeError", "KeyError",
            "RuntimeError", "AssertionError", "NotImplementedError",
            "IndexError", "StopIteration", "Exception", "super", "object",
        }
        call_methods = {q for q, i in self.funcs.items()
                        if i.name == "__call__"}
        for _ in range(4):
            extra = set()
            for qual in list(self.traced):
                info = self.funcs.get(qual)
                if info is None:
                    continue
                mod = self.modules[info.module]
                for n in _own_nodes(info.node):
                    if not isinstance(n, ast.Call):
                        continue
                    if isinstance(n.func, ast.Subscript) and \
                            mod.registry_names:
                        for name in mod.registry_names:
                            tgt = mod.locals.get(name)
                            if tgt and tgt not in self.traced:
                                extra.add(tgt)
                    elif isinstance(n.func, ast.Name) and \
                            n.func.id not in _BUILTIN_CALLS and \
                            n.func.id not in (mod.jax_names | mod.lax_names
                                              | mod.partial_aliases) and \
                            not self._resolve_call(info, n.func):
                        extra.update(call_methods - self.traced)
            if not extra:
                break
            propagate(extra)

        # jax_touch: F touches jax if it calls a toucher (fixpoint)
        changed = True
        while changed:
            changed = False
            for q, outs in self.edges.items():
                if q not in self.jax_touch and outs & self.jax_touch:
                    self.jax_touch.add(q)
                    changed = True

        # mesh doctrine axes from parallel/mesh.py when present
        for mod in self.modules.values():
            if not mod.name.endswith("parallel.mesh"):
                continue
            for n in ast.walk(mod.tree):
                if isinstance(n, ast.Call):
                    chain = _attr_chain(n.func) or []
                    name = (n.func.id if isinstance(n.func, ast.Name)
                            else (chain[-1] if chain else ""))
                    if name == "Mesh" and len(n.args) >= 2 and \
                            isinstance(n.args[1], (ast.Tuple, ast.List)):
                        axes = {_const_str(e) for e in n.args[1].elts}
                        axes.discard(None)
                        if axes:
                            self.mesh_axes = axes

    # -- rules ---------------------------------------------------------------
    def _emit(self, info: FuncInfo, node, rule: str, msg: str):
        self.violations.append(Violation(
            info.file, getattr(node, "lineno", 0), rule,
            info.qualname.split(":", 1)[1], msg))

    def _int64_dtype_expr(self, mod: ModuleInfo, node) -> bool:
        if isinstance(node, ast.Name) and node.id == "int":
            return True
        s = _const_str(node)
        if s is not None:
            return s in _INT64_STRS
        chain = _attr_chain(node)
        return bool(chain) and chain[-1] in ("int64", "uint64")

    def _s32_pinned(self, mod: ModuleInfo, node) -> bool:
        """Expression explicitly pinned to a 32-bit integer."""
        if isinstance(node, ast.Call):
            chain = _attr_chain(node.func) or []
            if chain and chain[-1] in ("int32", "uint32"):
                return True
            if chain and chain[-1] == "astype" and node.args:
                a = node.args[0]
                achain = _attr_chain(a) or []
                if achain and achain[-1] in ("int32", "uint32"):
                    return True
                if _const_str(a) in ("int32", "uint32"):
                    return True
            if chain and chain[-1] in ("asarray", "array"):
                exprs = list(node.args[1:]) + [kw.value for kw in node.keywords
                                               if kw.arg == "dtype"]
                for e in exprs:
                    ec = _attr_chain(e) or []
                    if (ec and ec[-1] in ("int32", "uint32")) or \
                            _const_str(e) in ("int32", "uint32"):
                        return True
            return False
        # trusted: plain names / attributes (runtime values we can't see)
        return isinstance(node, (ast.Name, ast.Attribute))

    def _check_traced_function(self, info: FuncInfo):
        mod = self.modules[info.module]
        for n in _own_nodes(info.node):
            if not isinstance(n, ast.Call):
                continue
            chain = _attr_chain(n.func) or []
            # R1: numpy compute in traced code
            if chain and chain[0] in mod.np_aliases and len(chain) > 1:
                if chain[-1] not in _NP_ALLOWED and "linalg" not in chain:
                    self._emit(info, n, "R1",
                               f"np.{'.'.join(chain[1:])}(...) inside traced "
                               "code")
                elif "linalg" in chain:
                    self._emit(info, n, "R1",
                               f"np.{'.'.join(chain[1:])}(...) inside traced "
                               "code")
            if chain:
                attr = chain[-1]
            elif isinstance(n.func, ast.Attribute):
                attr = n.func.attr     # method on a non-Name root,
            elif isinstance(n.func, ast.Name):  # e.g. x[i].astype(...)
                attr = n.func.id
            else:
                attr = ""
            is_jnp = bool(chain) and chain[0] in mod.jnp_aliases
            # R2: arange
            if attr == "arange" and is_jnp:
                dt = next((kw.value for kw in n.keywords
                           if kw.arg == "dtype"), None)
                if dt is None and len(n.args) >= 4:
                    dt = n.args[3]
                if dt is None:
                    if not any(isinstance(a, ast.Constant)
                               and isinstance(a.value, float)
                               for a in n.args):
                        self._emit(info, n, "R2",
                                   "integer arange without an explicit "
                                   "dtype (s64 under x64) — pin "
                                   "dtype=jnp.int32")
                elif self._int64_dtype_expr(mod, dt):
                    self._emit(info, n, "R2",
                               "arange pinned to a 64-bit integer dtype")
            # R2: iota
            if attr in ("iota", "broadcasted_iota") and chain:
                for a in list(n.args) + [kw.value for kw in n.keywords]:
                    if self._int64_dtype_expr(mod, a):
                        self._emit(info, n, "R2",
                                   f"{attr} with a 64-bit integer dtype")
            # R2: astype
            if attr == "astype" and isinstance(n.func, ast.Attribute) \
                    and n.args and self._int64_dtype_expr(mod, n.args[0]):
                self._emit(info, n, "R2",
                           "astype to a 64-bit integer in traced code — "
                           "use jnp.int32")
            # R2: fori_loop bounds
            if attr == "fori_loop" and len(n.args) >= 2:
                for i, bound in enumerate(n.args[:2]):
                    if not self._s32_pinned(mod, bound):
                        which = "lower" if i == 0 else "upper"
                        self._emit(info, n, "R2",
                                   f"fori_loop {which} bound is not "
                                   "explicitly s32 (python ints/expressions "
                                   "canonicalize the counter to s64 under "
                                   "x64) — wrap with jnp.int32(...)")
            # R7: telemetry reachable from traced code
            obs_tgts = [t for t in self._resolve_call(info, n.func)
                        if _is_obs_module(t.split(":", 1)[0])]
            if obs_tgts:
                self._emit(info, n, "R7",
                           f"call resolves into {obs_tgts[0].split(':', 1)[0]}"
                           " from traced code — record metrics/events around "
                           "the jit boundary, never inside it")

    def _check_r3(self, mod: ModuleInfo):
        allowed = (mod.name in _R3_ALLOWED_MODULES
                   or mod.name.startswith(_R3_ALLOWED_PREFIXES)
                   or mod.name.split(".")[-1] == "conftest")
        seen_keys: dict[str, int] = {}
        cache_calls = 0
        for n in ast.walk(mod.tree):
            if not isinstance(n, ast.Call):
                continue
            chain = _attr_chain(n.func) or []
            name = (n.func.id if isinstance(n.func, ast.Name)
                    else (chain[-1] if chain else ""))
            is_config_update = (len(chain) >= 3 and chain[-2] == "config"
                                and chain[-1] == "update"
                                and chain[0] in mod.jax_aliases)
            is_cache_enable = name in ("enable_compilation_cache",
                                       "enable_persistent_compilation_cache",
                                       "initialize_cache")
            if not (is_config_update or is_cache_enable):
                continue
            qual = self._enclosing_qual(mod, n)
            if not allowed:
                what = ("jax.config.update" if is_config_update
                        else name)
                self.violations.append(Violation(
                    mod.file, n.lineno, "R3", qual,
                    f"{what}(...) outside designated entrypoint modules "
                    f"({', '.join(_R3_ALLOWED_MODULES)}, tools/*)"))
                continue
            if is_cache_enable:
                cache_calls += 1
                if cache_calls > 1:
                    self.violations.append(Violation(
                        mod.file, n.lineno, "R3", qual,
                        f"duplicate {name}(...) in one module — the second "
                        "call is dead weight or a conflicting cache dir"))
            if is_config_update and n.args:
                key = _const_str(n.args[0])
                if key is not None:
                    seen_keys[key] = seen_keys.get(key, 0) + 1
                    if seen_keys[key] > 1:
                        self.violations.append(Violation(
                            mod.file, n.lineno, "R3", qual,
                            f"jax.config.update({key!r}, ...) repeated in "
                            "one module — one process path must set a key "
                            "at most once"))

    def _enclosing_qual(self, mod: ModuleInfo, node) -> str:
        best, best_span = "<module>", None
        for q, info in self.funcs.items():
            if info.module != mod.name:
                continue
            fn = info.node
            end = getattr(fn, "end_lineno", fn.lineno)
            if fn.lineno <= node.lineno <= end:
                span = end - fn.lineno
                if best_span is None or span < best_span:
                    best, best_span = q.split(":", 1)[1], span
        return best

    def _check_r4(self, info: FuncInfo):
        mod = self.modules[info.module]
        # donating targets callable by bare name from this function
        for n in _own_nodes(info.node):
            if not isinstance(n, ast.Call):
                continue
            targets = self._resolve_call(info, n.func)
            donated_pos: tuple = ()
            for t in targets:
                if t in self.donating:
                    donated_pos = self.donating[t]
                    break
            if not donated_pos:
                continue
            tainted = {n.args[p].id for p in donated_pos
                       if p < len(n.args) and isinstance(n.args[p], ast.Name)}
            if not tainted:
                continue
            call_line = n.lineno
            # loads within the call's own (possibly multi-line) span ARE
            # the donation, not a post-donation use
            call_end = getattr(n, "end_lineno", n.lineno)
            for m in _own_nodes(info.node):
                if isinstance(m, ast.Name) and m.id in tainted:
                    if isinstance(m.ctx, ast.Store) and \
                            m.lineno >= call_line:
                        tainted.discard(m.id)  # rebound: taint cleared
            for m in _own_nodes(info.node):
                if isinstance(m, ast.Name) and m.id in tainted and \
                        isinstance(m.ctx, ast.Load) and m.lineno > call_end:
                    self._emit(info, m, "R4",
                               f"'{m.id}' used after being donated at line "
                               f"{call_line} — its buffer may already be "
                               "retired into the callee's outputs")
                    tainted.discard(m.id)

    def _check_r5(self, info: FuncInfo):
        mod = self.modules[info.module]
        if not (mod.name == "bench" or mod.name.startswith("tools.")):
            return
        pcs, forcing, jaxish = [], [], []
        for n in _own_nodes(info.node):
            if not isinstance(n, ast.Call):
                continue
            chain = _attr_chain(n.func) or []
            if isinstance(n.func, ast.Name):
                name = n.func.id
            elif isinstance(n.func, ast.Attribute):
                name = n.func.attr   # covers jnp.sum(x).block_until_ready()
            else:
                name = ""
            if name == "perf_counter" and (not chain
                                           or chain[0] in mod.time_aliases):
                pcs.append(n.lineno)
            elif name in _FORCING_NAMES:
                forcing.append(n.lineno)
            else:
                if chain and chain[0] in (mod.jnp_aliases | mod.jax_aliases
                                          | mod.lax_aliases):
                    jaxish.append(n.lineno)
                else:
                    for t in self._resolve_call(info, n.func):
                        if t in self.jax_touch:
                            jaxish.append(n.lineno)
                            break
        if len(pcs) < 2:
            return
        lo, hi = min(pcs), max(pcs)
        spans_jax = [ln for ln in jaxish if lo <= ln <= hi]
        if spans_jax and not any(lo <= ln <= hi for ln in forcing):
            self._emit(info, info.node, "R5",
                       f"perf_counter span (lines {lo}-{hi}) contains JAX "
                       "dispatch without a block_until_ready/force inside "
                       "the span")

    def _check_r6(self, mod: ModuleInfo):
        if not mod.pspec_aliases:
            return
        for n in ast.walk(mod.tree):
            if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Name)
                    and n.func.id in mod.pspec_aliases):
                continue
            for a in n.args:
                elts = a.elts if isinstance(a, (ast.Tuple, ast.List)) else [a]
                for e in elts:
                    s = _const_str(e)
                    if s is not None and s not in self.mesh_axes:
                        self.violations.append(Violation(
                            mod.file, n.lineno, "R6",
                            self._enclosing_qual(mod, n),
                            f"PartitionSpec axis {s!r} is not a doctrine "
                            f"mesh axis {sorted(self.mesh_axes)}"))

    def run_rules(self):
        for info in self.funcs.values():
            if info.qualname in self.traced:
                self._check_traced_function(info)
            self._check_r4(info)
            self._check_r5(info)
        for mod in self.modules.values():
            self._check_r3(mod)
            self._check_r6(mod)
        self.violations.sort(key=lambda v: (v.file, v.line, v.rule))


# -- baseline + driver -------------------------------------------------------

def load_baseline(path: str | None) -> list[dict]:
    if not path or not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return json.load(f)


@dataclasses.dataclass
class LintResult:
    new: list[Violation]
    baselined: list[Violation]
    stale: list[dict]

    @property
    def ok(self) -> bool:
        return not self.new


def collect_files(paths: Iterable[str], root: str) -> list[str]:
    out = []
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if os.path.isdir(full):
            for dirpath, dirnames, filenames in os.walk(full):
                dirnames[:] = [d for d in dirnames
                               if d not in ("__pycache__", ".git")]
                out.extend(os.path.join(dirpath, f)
                           for f in sorted(filenames) if f.endswith(".py"))
        elif full.endswith(".py"):
            out.append(full)
    return out


def run_lint(paths: Iterable[str], baseline: list[dict] | None = None,
             root: str | None = None) -> LintResult:
    """Lint ``paths`` (files or directories) against the doctrine rules.

    ``root`` anchors module-name derivation (defaults to the repo root);
    ``baseline`` entries are dicts with file/rule/qualname keys.
    """
    root = root or REPO_ROOT
    lint = Linter()
    for f in collect_files(paths, root):
        lint.add_file(f, relto=root)
    lint.build()
    lint.run_rules()
    baseline = baseline or []
    bl_keys = {(b["file"], b["rule"], b["qualname"]) for b in baseline}
    new = [v for v in lint.violations if v.key() not in bl_keys]
    old = [v for v in lint.violations if v.key() in bl_keys]
    hit = {v.key() for v in old}
    stale = [b for b in baseline
             if (b["file"], b["rule"], b["qualname"]) not in hit]
    return LintResult(new, old, stale)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mfmlint",
        description="AST lint for the repo's JAX doctrine (R1-R7; see "
                    "docs/DOCTRINE.md)")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_TARGETS),
                    help="files/dirs to lint (default: mfm_tpu bench.py "
                         "tools)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of grandfathered violations "
                         "('none' disables)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="root for module-name derivation (default: repo)")
    args = ap.parse_args(argv)

    bl_path = None if args.baseline.lower() == "none" else (
        args.baseline if os.path.isabs(args.baseline)
        else os.path.join(args.root, args.baseline))
    res = run_lint(args.paths, load_baseline(bl_path), root=args.root)

    if args.as_json:
        print(json.dumps({
            "new": [dataclasses.asdict(v) for v in res.new],
            "baselined": [dataclasses.asdict(v) for v in res.baselined],
            "stale": res.stale,
        }, indent=1))
    else:
        for v in res.new:
            print(v.render())
        for b in res.stale:
            print(f"STALE baseline entry: {b['file']} {b['rule']} "
                  f"[{b['qualname']}] — the violation no longer exists; "
                  "remove it")
        print(f"mfmlint: {len(res.new)} new violation(s), "
              f"{len(res.baselined)} baselined, {len(res.stale)} stale "
              "baseline entr(ies)")
    if res.new:
        return 1
    if args.strict and res.stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
