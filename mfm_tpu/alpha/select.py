"""Alpha selection: greedy top-k under a pairwise-correlation cap.

The reference's title promises LLM-*driven* factor generation but ships no
selection machinery (SURVEY.md: no LLM code exists in the repo).  This is
the missing half of that loop: after :func:`compile_alpha_batch` scores a
candidate batch (LLM-generated or otherwise), pick the k best expressions
whose strategy PnL is not just a re-discovery of one another — the standard
industrial acceptance rule ("PnL correlation with existing alphas < 0.7").

Correlation is measured between per-date signal series (the top-minus-
bottom quantile spread — a long-short PnL — or the IC series), with
pairwise-valid date masks, exactly matching ``pandas.DataFrame.corr`` with
``min_periods``.  Everything pairwise is (E, T) matmuls — cheap on the MXU
for thousands of candidates; the greedy pass itself is tiny and host-side.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mfm_tpu.alpha.metrics import (
    information_coefficient, quantile_spread,
)
from mfm_tpu.utils.prec import highest_matmul_precision


def signal_series(alphas: jax.Array, fwd_ret: jax.Array,
                  kind: str = "spread", q: float = 0.2) -> jax.Array:
    """Per-expression per-date signal series, shape (E, T).

    ``kind="spread"``: top-minus-bottom ``q``-quantile forward return (a
    daily long-short PnL — the series whose correlation defines alpha
    redundancy).  ``kind="ic"``: the per-date information coefficient.
    """
    if kind == "spread":
        return quantile_spread(alphas, fwd_ret, q)
    if kind == "ic":
        return information_coefficient(alphas, fwd_ret)
    raise ValueError(f"unknown signal kind {kind!r} (want 'spread' or 'ic')")


@highest_matmul_precision
def series_correlation_matrix(series: jax.Array,
                              min_periods: int = 3) -> jax.Array:
    """Pairwise Pearson correlation of (E, T) series with NaN handling.

    Entry (i, j) is the correlation over the dates where BOTH series are
    finite (``pandas.DataFrame.corr(min_periods=...)`` semantics — the
    pairwise means/variances are computed over the joint-valid dates, not
    each series' own).  Pairs with fewer than ``min_periods`` joint dates
    are NaN.  All pairwise sums are (E, T) @ (T, E) matmuls.
    """
    m = jnp.isfinite(series)
    x = jnp.where(m, series, 0.0)
    mf = m.astype(x.dtype)
    n = mf @ mf.T                      # joint-valid date counts
    sxy = x @ x.T                      # Σ x_i x_j over joint dates
    sx = x @ mf.T                      # Σ x_i over dates where j also valid
    sxx = (x * x) @ mf.T               # Σ x_i² over joint dates
    nn = jnp.where(n > 0, n, 1.0)
    cov = sxy - sx * sx.T / nn
    var_i = sxx - sx * sx / nn
    var_j = var_i.T
    corr = cov / jnp.sqrt(var_i * var_j)
    return jnp.where(n >= min_periods, corr, jnp.nan)


def greedy_select(scores: np.ndarray, corr: np.ndarray, k: int,
                  max_corr: float = 0.7, min_score: float = 0.0) -> dict:
    """Greedy pick of ``k`` indices by descending score under the cap.

    Walks candidates from best score down; a candidate joins the selection
    iff its |corr| to every already-selected index is ≤ ``max_corr``.  An
    undefined correlation (NaN — too few joint-valid dates) does not block:
    there is no evidence of redundancy.  Candidates with NaN score or score
    < ``min_score`` never join.  Returns ``indices`` (selection order),
    ``scores`` and ``max_corr_to_selected`` aligned to it, and ``rejected``
    — {index: blocking index} for candidates that hit the cap.
    """
    scores = np.asarray(scores, np.float64)
    corr = np.asarray(corr, np.float64)
    order = np.argsort(-np.where(np.isfinite(scores), scores, -np.inf),
                       kind="stable")
    chosen: list[int] = []
    max_c: list[float] = []
    rejected: dict[int, int] = {}
    for i in order:
        i = int(i)
        if len(chosen) >= k:
            break
        if not np.isfinite(scores[i]) or scores[i] < min_score:
            continue
        cs = np.abs(corr[i, chosen]) if chosen else np.empty(0)
        over = np.nonzero(np.isfinite(cs) & (cs > max_corr))[0]
        if over.size:
            rejected[i] = chosen[int(over[0])]
            continue
        finite = cs[np.isfinite(cs)]
        max_c.append(float(finite.max()) if finite.size else np.nan)
        chosen.append(i)
    return {"indices": chosen,
            "scores": [float(scores[i]) for i in chosen],
            "max_corr_to_selected": max_c,
            "rejected": rejected}


def select_alphas(alphas: jax.Array, fwd_ret: jax.Array, k: int,
                  max_corr: float = 0.7, scores=None, min_score: float = 0.0,
                  kind: str = "spread", q: float = 0.2,
                  min_periods: int = 3) -> dict:
    """Score → correlate → greedily select from an (E, T, N) alpha batch.

    ``scores``: per-expression ranking (default |mean IC| — candidates are
    sign-ambiguous, so magnitude ranks; pass your own, e.g. ``ic_ir`` from
    :func:`mfm_tpu.alpha.metrics.alpha_summary`, to rank differently).
    Returns the :func:`greedy_select` dict plus the (E, E) ``corr`` matrix
    (host numpy) for reporting.
    """
    series = signal_series(alphas, fwd_ret, kind=kind, q=q)
    if scores is None:
        ic = information_coefficient(alphas, fwd_ret)
        m = jnp.isfinite(ic)
        scores = jnp.abs(
            jnp.sum(jnp.where(m, ic, 0.0), axis=-1) / jnp.sum(m, axis=-1))
    corr = np.asarray(series_correlation_matrix(series, min_periods))
    out = greedy_select(np.asarray(scores), corr, k,
                        max_corr=max_corr, min_score=min_score)
    out["corr"] = corr
    return out
