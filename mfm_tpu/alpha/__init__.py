"""Batch alpha-expression evaluation over dense panels.

The reference's title promises LLM-driven factors but contains none
(SURVEY.md preamble); ``BASELINE.json`` config 5 makes batch evaluation of
LLM-generated alpha expressions an explicit workload: parse candidate
expressions into panel ops, evaluate them fused under one jit over the
(T, N) panel, score them (IC / rank-IC / turnover / quantile spread)
against forward returns, and greedily select the top-k under a pairwise
long-short-PnL correlation cap (:mod:`mfm_tpu.alpha.select`).
"""

from mfm_tpu.alpha.dsl import (
    AlphaExpr,
    compile_alpha,
    compile_alpha_batch,
    evaluate_alphas,
)
from mfm_tpu.alpha.llm import extract_expressions
from mfm_tpu.alpha.metrics import (
    alpha_summary,
    information_coefficient,
    quantile_spread,
    rank_ic,
    rank_turnover,
)
from mfm_tpu.alpha.select import (
    greedy_select,
    select_alphas,
    series_correlation_matrix,
    signal_series,
)

__all__ = [
    "AlphaExpr",
    "compile_alpha",
    "compile_alpha_batch",
    "evaluate_alphas",
    "extract_expressions",
    "information_coefficient",
    "rank_ic",
    "rank_turnover",
    "quantile_spread",
    "alpha_summary",
    "signal_series",
    "series_correlation_matrix",
    "greedy_select",
    "select_alphas",
]
