"""Alpha scoring: per-date information coefficients and summaries."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.alpha.dsl import cs_rank


def information_coefficient(alpha: jax.Array, fwd_ret: jax.Array) -> jax.Array:
    """Per-date Pearson correlation of alpha vs forward returns.

    alpha: (..., T, N); fwd_ret: (T, N).  Returns (..., T).
    """
    m = jnp.isfinite(alpha) & jnp.isfinite(fwd_ret)
    n = jnp.sum(m, axis=-1)
    az = jnp.where(m, alpha, 0.0)
    rz = jnp.where(m, fwd_ret, 0.0)
    ma = jnp.sum(az, axis=-1) / n
    mr = jnp.sum(rz, axis=-1) / n
    da = jnp.where(m, alpha - ma[..., None], 0.0)
    dr = jnp.where(m, fwd_ret - mr[..., None], 0.0)
    cov = jnp.sum(da * dr, axis=-1)
    ic = cov / jnp.sqrt(jnp.sum(da * da, axis=-1) * jnp.sum(dr * dr, axis=-1))
    return jnp.where(n >= 3, ic, jnp.nan)


def rank_ic(alpha: jax.Array, fwd_ret: jax.Array) -> jax.Array:
    """Spearman: Pearson IC of the cross-sectional ranks."""
    ra = cs_rank(alpha)
    rr = cs_rank(jnp.broadcast_to(fwd_ret, alpha.shape))
    return information_coefficient(ra, rr)


def _turnover_from_ranks(r: jax.Array) -> jax.Array:
    prev = jnp.concatenate(
        [jnp.full_like(r[..., :1, :], jnp.nan), r[..., :-1, :]], axis=-2)
    m = jnp.isfinite(r) & jnp.isfinite(prev)
    n = jnp.sum(m, axis=-1)
    d = jnp.sum(jnp.where(m, jnp.abs(r - prev), 0.0), axis=-1)
    return jnp.where(n >= 1, d / n, jnp.nan)


def rank_turnover(alpha: jax.Array) -> jax.Array:
    """Per-date mean |Δ cross-sectional rank| between consecutive dates.

    alpha: (..., T, N).  Returns (..., T); date 0 and dates where a stock is
    valid on only one of the two days contribute through the stocks valid on
    BOTH.  0 = identical ordering day-over-day, →0.5 = full reshuffle (the
    expectation for independent uniform ranks is 1/3).
    """
    return _turnover_from_ranks(cs_rank(alpha))


def _spread_from_ranks(r: jax.Array, fwd_ret: jax.Array,
                       q: float) -> jax.Array:
    f = jnp.broadcast_to(fwd_ret, r.shape)
    m = jnp.isfinite(r) & jnp.isfinite(f)
    top = m & (r > 1.0 - q)
    bot = m & (r <= q)
    n_top = jnp.sum(top, axis=-1)
    n_bot = jnp.sum(bot, axis=-1)
    mu_top = jnp.sum(jnp.where(top, f, 0.0), axis=-1) / n_top
    mu_bot = jnp.sum(jnp.where(bot, f, 0.0), axis=-1) / n_bot
    return jnp.where((n_top >= 1) & (n_bot >= 1), mu_top - mu_bot, jnp.nan)


def quantile_spread(alpha: jax.Array, fwd_ret: jax.Array,
                    q: float = 0.2) -> jax.Array:
    """Per-date top-minus-bottom quantile forward return.

    Mean forward return of the top-``q`` fraction of stocks by alpha minus
    the bottom-``q`` fraction (by fractional cross-sectional rank).
    alpha: (..., T, N); fwd_ret: (T, N).  Returns (..., T).
    """
    return _spread_from_ranks(cs_rank(alpha), fwd_ret, q)


def _nanmean_last(x):
    m = jnp.isfinite(x)
    return jnp.sum(jnp.where(m, x, 0.0), axis=-1) / jnp.sum(m, axis=-1)


def alpha_summary(alphas: jax.Array, fwd_ret: jax.Array,
                  spread_q: float = 0.2) -> dict:
    """Batch scorecard for (E, T, N) alpha values.

    Returns per-expression arrays: mean IC, IC information ratio
    (mean/std over dates), mean rank-IC, coverage (mean valid fraction),
    mean day-over-day rank turnover, and the mean top-minus-bottom
    ``spread_q``-quantile forward return.
    """
    ic = information_coefficient(alphas, fwd_ret)  # (E, T)
    # one double-argsort over (E, T, N) shared by rank-IC, turnover, spread
    r = cs_rank(alphas)
    ric = information_coefficient(r, cs_rank(fwd_ret))
    m = jnp.isfinite(ic)
    n = jnp.sum(m, axis=-1)
    mean_ic = jnp.sum(jnp.where(m, ic, 0.0), axis=-1) / n
    var_ic = jnp.sum(jnp.where(m, (ic - mean_ic[:, None]) ** 2, 0.0), axis=-1) / n
    coverage = jnp.mean(jnp.isfinite(alphas), axis=(-2, -1))
    return {
        "mean_ic": mean_ic,
        "ic_ir": mean_ic / jnp.sqrt(var_ic),
        "mean_rank_ic": _nanmean_last(ric),
        "coverage": coverage,
        "mean_turnover": _nanmean_last(_turnover_from_ranks(r)),
        "mean_spread": _nanmean_last(_spread_from_ranks(r, fwd_ret,
                                                        spread_q)),
    }
