"""Alpha scoring: per-date information coefficients and summaries."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.alpha.dsl import cs_rank


def information_coefficient(alpha: jax.Array, fwd_ret: jax.Array) -> jax.Array:
    """Per-date Pearson correlation of alpha vs forward returns.

    alpha: (..., T, N); fwd_ret: (T, N).  Returns (..., T).
    """
    m = jnp.isfinite(alpha) & jnp.isfinite(fwd_ret)
    n = jnp.sum(m, axis=-1)
    az = jnp.where(m, alpha, 0.0)
    rz = jnp.where(m, fwd_ret, 0.0)
    ma = jnp.sum(az, axis=-1) / n
    mr = jnp.sum(rz, axis=-1) / n
    da = jnp.where(m, alpha - ma[..., None], 0.0)
    dr = jnp.where(m, fwd_ret - mr[..., None], 0.0)
    cov = jnp.sum(da * dr, axis=-1)
    ic = cov / jnp.sqrt(jnp.sum(da * da, axis=-1) * jnp.sum(dr * dr, axis=-1))
    return jnp.where(n >= 3, ic, jnp.nan)


def rank_ic(alpha: jax.Array, fwd_ret: jax.Array) -> jax.Array:
    """Spearman: Pearson IC of the cross-sectional ranks."""
    ra = cs_rank(alpha)
    rr = cs_rank(jnp.broadcast_to(fwd_ret, alpha.shape))
    return information_coefficient(ra, rr)


def alpha_summary(alphas: jax.Array, fwd_ret: jax.Array) -> dict:
    """Batch scorecard for (E, T, N) alpha values.

    Returns per-expression arrays: mean IC, IC information ratio
    (mean/std over dates), mean rank-IC, coverage (mean valid fraction).
    """
    ic = information_coefficient(alphas, fwd_ret)  # (E, T)
    ric = rank_ic(alphas, fwd_ret)
    m = jnp.isfinite(ic)
    n = jnp.sum(m, axis=-1)
    mean_ic = jnp.sum(jnp.where(m, ic, 0.0), axis=-1) / n
    var_ic = jnp.sum(jnp.where(m, (ic - mean_ic[:, None]) ** 2, 0.0), axis=-1) / n
    mr = jnp.isfinite(ric)
    mean_ric = jnp.sum(jnp.where(mr, ric, 0.0), axis=-1) / jnp.sum(mr, axis=-1)
    coverage = jnp.mean(jnp.isfinite(alphas), axis=(-2, -1))
    return {
        "mean_ic": mean_ic,
        "ic_ir": mean_ic / jnp.sqrt(var_ic),
        "mean_rank_ic": mean_ric,
        "coverage": coverage,
    }
