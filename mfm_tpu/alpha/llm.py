"""Tolerant extraction of DSL expressions from raw LLM output.

The strict readers (``cli._read_alpha_sources``, ``alpha --exprs``) demand
one clean expression per line and fail fast on anything else — right for
curated files, wrong for the actual output of an LLM asked to "propose 50
alpha factors", which arrives wrapped in markdown fences, numbered lists,
inline backticks, ``alpha_3 = ...`` assignments, and prose paragraphs.
This module pulls every *valid* DSL expression out of such text and reports
what it rejected and why, so the title's loop

    LLM chat dump -> extract -> validate -> dedup -> evaluate/select
        -> style factors of the risk model (``pipeline --alphas``)

needs no hand-cleaning step.  The validator is the DSL compiler itself
(:func:`mfm_tpu.alpha.dsl.compile_alpha` — same vocabulary, same rejection
of non-DSL syntax); extraction only normalizes the surrounding chrome:

- markdown code fences are unwrapped (their language tag line dropped);
- list markers (``1.``, ``-``, ``*``, ``•``) and inline backticks strip.
  NOTE the convention this fixes: a leading ``- `` (dash, space) is read as
  a LIST BULLET, never as negation — a negated alpha must be written
  ``-expr`` with no space (how LLMs overwhelmingly format it).  The report
  counts dash-bullet strips (``n_dash_bullets_stripped``) so a surprising
  sign is traceable;
- ``name = expr`` / ``name: expr`` keeps the right-hand side when the left
  is a bare identifier (the LLM's label, not a DSL field);
- trailing ``,`` / ``;`` strip;
- prose lines simply fail to compile and land in the rejection report; a
  bare identifier or constant (``momentum``, ``42``) — valid DSL but never
  a useful alpha, and exactly what stray prose words look like — is
  rejected as ``trivial`` unless it came from inside backticks/a fence;
- duplicates (structural: same AST after whitespace/parens/label chrome)
  are dropped, first occurrence wins.

``known_fields`` (e.g. the panel's columns) tightens validation: candidates
referencing other names are rejected as ``unknown-field`` instead of
crashing the evaluator later.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable

from mfm_tpu.alpha.dsl import _ALIASES, compile_alpha

_FENCE = re.compile(r"^\s*```")
_INLINE_FENCE = re.compile(r"^\s*```(.*?)```\s*$")
_LIST_MARKER = re.compile(r"^\s*(?:[-*•]|\d+[.)])\s+")
_LABEL = re.compile(r"^\s*[A-Za-z_]\w*\s*[=:]\s*(?![=])")
_TRAILING = re.compile(r"[,;\s]+$")


def _candidates(text: str) -> Iterable[tuple[int, str, bool, bool]]:
    """Yield (lineno, cleaned-candidate, was_code_marked, was_dash_bullet)
    per non-blank line (one per inline-backtick span on span lines)."""
    fenced = False
    for no, raw in enumerate(text.splitlines(), 1):
        m = _INLINE_FENCE.match(raw)
        if m:  # ```expr``` opens AND closes on one line: inline code,
            sp = m.group(1).strip()  # not a fence toggle
            sp = _TRAILING.sub("", _LABEL.sub("", sp))
            if sp:
                yield no, sp, True, False
            continue
        if _FENCE.match(raw):
            fenced = not fenced
            continue
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        # inline backticks: EVERY span is its own candidate (a line may
        # offer several alternatives); the surrounding prose is chrome
        spans = re.findall(r"`([^`]+)`", line)
        if spans:
            for sp in spans:
                sp = _TRAILING.sub("", _LABEL.sub("", sp.strip()))
                if sp:
                    yield no, sp, True, False
            continue
        code_marked = fenced
        dash_bullet = line.startswith("- ")
        line = _LIST_MARKER.sub("", line)
        # the DSL grammar contains no ':' anywhere, so a colon whose prefix
        # holds no expression syntax is label chrome ("**Mean reversion**:")
        head, sep, tail = line.partition(":")
        if sep and not any(c in head for c in "(`="):
            line = tail.strip()
        line = _LABEL.sub("", line)
        line = _TRAILING.sub("", line)
        if line:
            yield no, line, code_marked, dash_bullet


def _canonical_key(body: ast.AST) -> str:
    """Structural dedup key, alias-insensitive: ``rank(close)`` and
    ``cs_rank(close)`` are the same factor (LLM output mixes the 101-Alphas
    and DSL vocabularies — the whole reason the aliases exist)."""
    import copy

    b = copy.deepcopy(body)
    for n in ast.walk(b):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Name):
            n.func.id = _ALIASES.get(n.func.id, n.func.id)
    return ast.dump(b)


def extract_expressions(text: str, known_fields=None):
    """Extract valid DSL expressions from raw LLM output.

    Returns ``(exprs, report)``: ``exprs`` is the deduplicated list of
    expression sources in first-seen order; ``report`` holds
    ``n_candidates`` / ``n_extracted`` / ``n_duplicates`` and ``rejected``
    as a list of ``(lineno, candidate, reason)`` — surfaced by the CLI so a
    silently-dropped factor is visible, not mysterious.
    """
    known = set(known_fields) if known_fields is not None else None
    exprs: list[str] = []
    seen: set[str] = set()
    rejected: list[tuple[int, str, str]] = []
    n_cand = n_dup = n_dash = 0
    def _trunc(s: str, cap: int = 200) -> str:
        return s if len(s) <= cap else s[:cap] + "..."

    def reject(no, cand, reason):
        # truncate BOTH fields: monster candidates, and reasons that embed
        # candidate text (compile_alpha's messages quote the offender)
        rejected.append((no, _trunc(cand), _trunc(reason)))

    for no, cand, code_marked, dash_bullet in _candidates(text):
        n_cand += 1
        try:
            e = compile_alpha(cand)
        except (ValueError, SyntaxError) as err:
            # compile_alpha guarantees this catch suffices: degenerate
            # sampling-loop lines (over-long, parser-overflowing, or
            # depth-capped) all surface as ValueError
            reject(no, cand, f"not DSL: {err}")
            continue
        body = e.tree.body
        if not e.fields:
            # no panel dependency -> a constant signal ('-0.03', '5'),
            # never a factor; also crashes batch stacking downstream
            reject(no, cand, "trivial: no panel fields")
            continue
        if not code_marked and isinstance(body, ast.Name):
            reject(no, cand, "trivial: bare name outside code markup")
            continue
        if known is not None:
            missing = [f for f in e.fields if f not in known]
            if missing:
                reject(no, cand, f"unknown-field: {missing}")
                continue
        key = _canonical_key(body)
        if key in seen:
            n_dup += 1
            continue
        seen.add(key)
        if dash_bullet:
            n_dash += 1
        exprs.append(cand)
    report = {
        "n_candidates": n_cand,
        "n_extracted": len(exprs),
        "n_duplicates": n_dup,
        # dash-space reads as a bullet, never negation (module docstring) —
        # count the strips so a surprising sign is traceable
        "n_dash_bullets_stripped": n_dash,
        "rejected": rejected,
    }
    return exprs, report
