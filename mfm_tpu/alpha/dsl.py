"""A small, safe alpha-expression DSL compiled to masked panel ops.

Grammar: Python expression syntax (parsed with ``ast``, no eval) over panel
field names, numeric literals, arithmetic/comparison operators, and a fixed
op vocabulary in the WorldQuant-alpha style:

  elementwise: abs, log, sign, sqrt, where(cond, a, b), min, max, power,
      signed_power(x, a)
  cross-sectional (per date over valid stocks):
      cs_rank, cs_zscore, cs_demean, cs_scale (unit L1 norm),
      cs_winsorize(x, k), cs_neutralize(x, group_field)
  time-series (per stock, trailing window):
      delay(x, d), delta(x, d), ts_mean(x, w), ts_std(x, w), ts_sum(x, w),
      ts_product(x, w), ts_min(x, w), ts_max(x, w), ts_rank(x, w),
      ts_corr(x, y, w), ts_cov(x, y, w), ts_argmax(x, w), ts_argmin(x, w),
      decay_linear(x, w)

All ops are NaN-masked (missing stays missing; windows require full validity
for corr/rank, count>=1 elsewhere), static-shaped, and jit/vmap-friendly —
an arbitrary batch of expressions evaluates as one fused XLA program.
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
from typing import Callable, Dict, Mapping, Sequence

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# masked panel primitives
# ---------------------------------------------------------------------------

def _nan(dtype):
    return jnp.asarray(jnp.nan, dtype)


def cs_rank(x):
    """Per-date fractional rank in (0, 1] over valid stocks (ties broken by
    position, pandas method='first'); works for any leading batch dims."""
    m = jnp.isfinite(x)
    big = jnp.where(m, x, jnp.inf)
    order = jnp.argsort(big, axis=-1)
    rank0 = jnp.argsort(order, axis=-1).astype(x.dtype)  # 0-based sort position
    n = jnp.sum(m, axis=-1, keepdims=True)
    return jnp.where(m, (rank0 + 1.0) / n, _nan(x.dtype))


def cs_zscore(x):
    m = jnp.isfinite(x)
    n = jnp.sum(m, axis=-1, keepdims=True)
    mu = jnp.sum(jnp.where(m, x, 0.0), axis=-1, keepdims=True) / n
    sd = jnp.sqrt(jnp.sum(jnp.where(m, (x - mu) ** 2, 0.0), axis=-1, keepdims=True) / n)
    return jnp.where(m, (x - mu) / sd, _nan(x.dtype))


def cs_demean(x):
    m = jnp.isfinite(x)
    n = jnp.sum(m, axis=-1, keepdims=True)
    mu = jnp.sum(jnp.where(m, x, 0.0), axis=-1, keepdims=True) / n
    return jnp.where(m, x - mu, _nan(x.dtype))


def cs_scale(x):
    m = jnp.isfinite(x)
    denom = jnp.sum(jnp.where(m, jnp.abs(x), 0.0), axis=-1, keepdims=True)
    return jnp.where(m, x / denom, _nan(x.dtype))


def delay(x, d: int):
    d = int(d)
    if d == 0:
        return x
    if d >= x.shape[0]:
        # lag past the series start: every cell is pre-history.  Without
        # this branch the concat below would emit shape (d, N), not (T, N)
        return jnp.full_like(x, jnp.nan)
    pad = jnp.full((d,) + x.shape[1:], jnp.nan, x.dtype)
    return jnp.concatenate([pad, x[:-d]], axis=0)


def delta(x, d: int):
    return x - delay(x, d)


def _windows(x, w: int):
    """(T, W, N) trailing windows, NaN-padded before the series start."""
    w = int(w)
    T = x.shape[0]
    pad = jnp.full((w - 1,) + x.shape[1:], jnp.nan, x.dtype)
    xp = jnp.concatenate([pad, x], axis=0)
    idx = (jnp.arange(T, dtype=jnp.int32)[:, None]
           + jnp.arange(w, dtype=jnp.int32)[None, :])  # R2: explicit s32
    return jnp.take(xp, idx, axis=0)


def _ts_reduce(x, w, reducer, min_count=1):
    win = _windows(x, w)
    m = jnp.isfinite(win)
    n = jnp.sum(m, axis=1)
    out = reducer(win, m)
    return jnp.where(n >= min_count, out, _nan(x.dtype))


def _winsum(x, w: int):
    """Trailing-window sum via cumsum difference — O(T), no window
    materialization (this is what makes 1000-expression batches cheap)."""
    cs = jnp.cumsum(x, axis=0)
    return cs - jnp.concatenate(
        [jnp.zeros((int(w),) + x.shape[1:], x.dtype), cs[:-int(w)]], axis=0
    )[: x.shape[0]]


def _moments(x, w, min_count):
    m = jnp.isfinite(x)
    n = _winsum(m.astype(x.dtype), w)
    s = _winsum(jnp.where(m, x, 0.0), w)
    return m, n, s, jnp.where(n >= min_count, 1.0, jnp.nan)


def ts_sum(x, w):
    m, n, s, gate = _moments(x, w, 1)
    return s * gate


def ts_mean(x, w):
    m, n, s, gate = _moments(x, w, 1)
    return s / n * gate


def ts_std(x, w):
    m, n, s, gate = _moments(x, w, 2)
    ss = _winsum(jnp.where(m, x * x, 0.0), w)
    var = (ss - s * s / n) / (n - 1.0)
    return jnp.sqrt(jnp.maximum(var, 0.0)) * gate


def ts_min(x, w):
    return _ts_reduce(x, w, lambda win, m: jnp.min(jnp.where(m, win, jnp.inf), axis=1))


def ts_max(x, w):
    return _ts_reduce(x, w, lambda win, m: jnp.max(jnp.where(m, win, -jnp.inf), axis=1))


def ts_product(x, w):
    """Trailing-window product over valid entries (count >= 1 like ts_sum;
    a cumprod-ratio formulation would 0/0 on zero values, so the window is
    materialized like ts_min/ts_max)."""
    return _ts_reduce(x, w, lambda win, m: jnp.prod(jnp.where(m, win, 1.0), axis=1))


def ts_rank(x, w):
    """Fractional rank of today's value within its trailing window."""
    def red(win, m):
        cur = win[:, -1]
        less = jnp.sum(jnp.where(m, (win <= cur[:, None]), False), axis=1)
        n = jnp.sum(m, axis=1)
        return less.astype(x.dtype) / n

    return _ts_reduce(x, w, red)


def ts_corr(x, y, w):
    m = jnp.isfinite(x) & jnp.isfinite(y)
    xz = jnp.where(m, x, 0.0)
    yz = jnp.where(m, y, 0.0)
    n = _winsum(m.astype(x.dtype), w)
    sx = _winsum(xz, w)
    sy = _winsum(yz, w)
    sxy = _winsum(xz * yz, w)
    sxx = _winsum(xz * xz, w)
    syy = _winsum(yz * yz, w)
    cov = sxy - sx * sy / n
    vx = sxx - sx * sx / n
    vy = syy - sy * sy / n
    out = cov / jnp.sqrt(vx * vy)
    return jnp.where(n >= 2, out, _nan(x.dtype))


def decay_linear(x, w):
    """Linearly-decaying weighted mean: weight (p+1) at window position p,
    renormalized over valid points.  Position weights are an affine function
    of the date index, so two cumsum-window sums suffice: with weight
    i - (t - w) for series index i, the weighted sum is
    [sum i*x]_win - (t-w) [sum x]_win."""
    w = int(w)
    m = jnp.isfinite(x)
    t_idx = jnp.arange(x.shape[0], dtype=x.dtype).reshape(
        (-1,) + (1,) * (x.ndim - 1)
    )
    xz = jnp.where(m, x, 0.0)
    mz = m.astype(x.dtype)
    s_ix = _winsum(t_idx * xz, w)
    s_x = _winsum(xz, w)
    s_im = _winsum(t_idx * mz, w)
    s_m = _winsum(mz, w)
    base = t_idx - w  # weight of series index i in the window ending t: i-(t-w)
    num = s_ix - base * s_x
    den = s_im - base * s_m
    return jnp.where(s_m >= 1, num / den, _nan(x.dtype))


def ts_cov(x, y, w):
    """Trailing sample covariance (pandas ``rolling.cov`` ddof=1)."""
    m = jnp.isfinite(x) & jnp.isfinite(y)
    xz = jnp.where(m, x, 0.0)
    yz = jnp.where(m, y, 0.0)
    n = _winsum(m.astype(x.dtype), w)
    cov = (_winsum(xz * yz, w) - _winsum(xz, w) * _winsum(yz, w) / n)
    return jnp.where(n >= 2, cov / (n - 1.0), _nan(x.dtype))


def ts_argmax(x, w):
    """Days since the trailing-window maximum (0 = today is the max; ties
    resolve to the most recent occurrence)."""
    def red(win, m):
        rev = jnp.where(m, win, -jnp.inf)[:, ::-1]  # position 0 = today
        return jnp.argmax(rev, axis=1).astype(x.dtype)

    return _ts_reduce(x, w, red)


def ts_argmin(x, w):
    """Days since the trailing-window minimum (0 = today; most recent tie)."""
    def red(win, m):
        rev = jnp.where(m, win, jnp.inf)[:, ::-1]
        return jnp.argmin(rev, axis=1).astype(x.dtype)

    return _ts_reduce(x, w, red)


def signed_power(x, a):
    """sign(x) * |x|**a — the WorldQuant convention for fractional powers
    of signed signals."""
    return jnp.sign(x) * jnp.abs(x) ** a


def cs_winsorize(x, k=2.5):
    """Per-date clip at mean ± k·std over valid stocks — the factor
    pipeline's own winsorization (one implementation:
    :func:`mfm_tpu.ops.masked.winsorize_cs`, ``post_processing.py:12-15``),
    with the DSL's NaN-stays-NaN convention."""
    from mfm_tpu.ops.masked import winsorize_cs

    out = winsorize_cs(x, n_std=k)
    return jnp.where(jnp.isfinite(x), out, _nan(x.dtype))


def cs_neutralize(x, g, num_groups: int = 64):
    """Subtract the per-(date, group) mean — industry/sector neutralization.

    ``g`` is a (T, N) panel field of small integer group codes in
    [0, num_groups) (float-encoded is fine).  Cells where x or g is missing
    — or where the code is OUT OF RANGE (e.g. raw 801010-style SW codes
    passed without ordinal-encoding first) — come back NaN rather than
    silently aliasing into a wrong group.  Scatter-add into a
    (T, num_groups) table keeps this O(T·N), no one-hot materialization.
    """
    m = (jnp.isfinite(x) & jnp.isfinite(g)
         & (g >= 0) & (g < num_groups))
    gi = jnp.where(m, g, 0).astype(jnp.int32)
    T = x.shape[0]
    rows = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[:, None], x.shape)
    sums = jnp.zeros((T, num_groups), x.dtype).at[rows, gi].add(
        jnp.where(m, x, 0.0))
    cnts = jnp.zeros((T, num_groups), x.dtype).at[rows, gi].add(
        m.astype(x.dtype))
    mu = sums / jnp.maximum(cnts, 1.0)
    return jnp.where(m, x - mu[rows, gi], _nan(x.dtype))


_ELEMENTWISE = {
    "abs": jnp.abs,
    "log": lambda x: jnp.log(jnp.where(x > 0, x, jnp.nan)),
    "sign": jnp.sign,
    "sqrt": lambda x: jnp.sqrt(jnp.where(x >= 0, x, jnp.nan)),
    # explicit-arity wrappers: the raw jnp callables under-constrain
    # ``inspect.signature`` — jnp.where defaults x/y to None (1- and 2-arg
    # calls bind, then crash inside the jit batch) and the minimum/maximum
    # ufunc wrappers report zero required positionals — so _check_arity
    # could not reject ``where(cond)`` / ``min(x)`` at compile time.
    # power is wrapped too, pre-emptively: its jnp signature is exact in
    # the installed JAX, but a ufunc conversion upstream (exactly what
    # happened to minimum/maximum) would silently void the compile-time
    # guarantee with no test tripping
    "power": lambda x, y: jnp.power(x, y),
    "min": lambda x, y: jnp.minimum(x, y),
    "max": lambda x, y: jnp.maximum(x, y),
    "where": lambda cond, x, y: jnp.where(cond, x, y),
}

_OPS: Dict[str, Callable] = {
    **_ELEMENTWISE,
    "cs_rank": cs_rank,
    "cs_zscore": cs_zscore,
    "cs_demean": cs_demean,
    "cs_scale": cs_scale,
    "delay": delay,
    "delta": delta,
    "ts_mean": ts_mean,
    "ts_std": ts_std,
    "ts_sum": ts_sum,
    "ts_min": ts_min,
    "ts_max": ts_max,
    "ts_product": ts_product,
    "ts_rank": ts_rank,
    "ts_corr": ts_corr,
    "ts_cov": ts_cov,
    "ts_argmax": ts_argmax,
    "ts_argmin": ts_argmin,
    "decay_linear": decay_linear,
    "signed_power": signed_power,
    "cs_winsorize": cs_winsorize,
    "cs_neutralize": cs_neutralize,
}

# WorldQuant "101 Formulaic Alphas" vocabulary aliases: LLMs prompted for
# alpha factors overwhelmingly emit these operator names (the 101-alphas
# paper is in their training data), so the DSL accepts them directly —
# each maps onto the op of matching semantics (cross-sectional rank/scale,
# trailing-window reductions).  delay / delta / decay_linear / ts_rank /
# ts_argmax / ts_argmin / log / sign / abs already share names.  min/max
# deliberately stay ELEMENTWISE (NumPy semantics) — the 101 paper reads
# min(x, d) as ts_min; the validator rejects the ambiguous integer form
# rather than silently picking a meaning.  Op names (incl. aliases) are
# reserved words: a panel field may not use one.
_ALIASES = {
    "rank": "cs_rank",
    "stddev": "ts_std",
    "correlation": "ts_corr",
    "covariance": "ts_cov",
    "sum": "ts_sum",
    "product": "ts_product",
    "signedpower": "signed_power",
    "indneutralize": "cs_neutralize",
    "scale": "cs_scale",
}
_OPS.update({alias: _OPS[target] for alias, target in _ALIASES.items()})

_BINOPS = {
    ast.Add: jnp.add,
    ast.Sub: jnp.subtract,
    ast.Mult: jnp.multiply,
    ast.Div: jnp.divide,
    ast.Pow: jnp.power,
    ast.Mod: jnp.mod,
}
_CMPOPS = {
    ast.Gt: jnp.greater,
    ast.GtE: jnp.greater_equal,
    ast.Lt: jnp.less,
    ast.LtE: jnp.less_equal,
    ast.Eq: jnp.equal,
    ast.NotEq: jnp.not_equal,
}

# exactly the node types _eval_node can evaluate (plus the operator classes
# it maps and the Load context every Name carries): validation is a
# WHITELIST, so anything newer/fancier — lists, ternaries, boolean ops,
# f-strings, walrus, ast.keyword, FloorDiv/BitXor/... — fails compile with
# a per-line ValueError instead of surfacing mid-batch as _eval_node's
# "unsupported node" / a _BINOPS KeyError inside the shared jit trace.
_ALLOWED_NODES = (ast.Expression, ast.Constant, ast.Name, ast.Load,
                  ast.BinOp, ast.UnaryOp, ast.Compare, ast.Call,
                  ast.USub, ast.UAdd) + tuple(_BINOPS) + tuple(_CMPOPS)

# longest source worth parsing: the 101-alphas corpus tops out around 200
# chars; 4096 leaves room for legitimately-deep composites while keeping
# CPython's parser clear of the stack overflows that degenerate
# sampling-loop lines ('-'*20000 + 'close') trigger
_MAX_SOURCE_CHARS = 4096


@dataclasses.dataclass
class AlphaExpr:
    """A parsed, validated alpha expression."""

    source: str
    tree: ast.expression
    fields: tuple

    def __call__(self, panel: Mapping[str, jax.Array]) -> jax.Array:
        return _eval_node(self.tree.body, panel)


def _collect_fields(node, fields):
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id not in _OPS:
            fields.add(n.id)


# positional args that must be INTEGER CONSTANTS within a per-op range
# (windows, lags, group counts): {canonical op: {arg index: (lo, hi)}}.
# They parameterize static shapes, so a non-constant there
# (``delay(close, volume)``) or a non-int (``ts_mean(close, 5.5)``,
# ``cs_neutralize(x, g, 32.5)``) either crashes the shared jit batch at
# trace time — aborting every expression in the chunk — or, worse, traces
# "fine" with silently truncated semantics (arange(5.5) -> window 6).
# Checked at compile so bad lines land in the tolerant-mode per-line
# rejection report instead.  Windows need >= 1 and are capped at 2048: the
# window-materializing reductions (_windows) build a (T, w, N) tensor, so
# an LLM-emitted ``ts_rank(close, 50000)`` would OOM the whole chunk, while
# every real trading window is <= 504 (RSTR) and 2048 is ~8 years of
# trading days.  delay/delta lags support 0 (identity / zero) under the
# same cap; num_groups is capped at 4096 — the op scatter-adds into a
# (T, num_groups) table (SW L1 has 31 industries, so 4096 is generous).
# Float-valued constants like cs_winsorize's k or exponents are
# deliberately absent.
_W = (1, 2048)
_STATIC_INT_ARGS = {
    "delay": {1: (0, 2048)}, "delta": {1: (0, 2048)},
    "ts_mean": {1: _W}, "ts_std": {1: _W}, "ts_sum": {1: _W},
    "ts_min": {1: _W}, "ts_max": {1: _W}, "ts_product": {1: _W},
    "ts_rank": {1: _W}, "ts_argmax": {1: _W}, "ts_argmin": {1: _W},
    "decay_linear": {1: _W},
    "ts_corr": {2: _W}, "ts_cov": {2: _W},
    "cs_neutralize": {2: (1, 4096)},
}


def _check_static_int_args(node: ast.Call):
    canon = _ALIASES.get(node.func.id, node.func.id)
    for idx, (lo, hi) in _STATIC_INT_ARGS.get(canon, {}).items():
        if idx >= len(node.args):
            continue  # optional (cs_neutralize's num_groups); arity is
            # checked separately
        a = node.args[idx]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, int)
                and not isinstance(a.value, bool) and lo <= a.value <= hi):
            got = ast.unparse(a)
            raise ValueError(
                f"{node.func.id} argument {idx + 1} must be an integer "
                f"constant in [{lo}, {hi}] (a window/lag/group count), "
                f"got {got!r}")


# deepest expression tree accepted: real alphas nest < 20 levels; beyond
# ~1000 the recursive _eval_node would hit Python's recursion limit at
# evaluation time, INSIDE the shared jit batch.  Computed iteratively so
# the check itself cannot overflow.
_MAX_AST_DEPTH = 100


def _ast_depth(tree) -> int:
    depth = 0
    stack = [(tree, 1)]
    while stack:
        node, d = stack.pop()
        depth = max(depth, d)
        stack.extend((child, d + 1) for child in ast.iter_child_nodes(node))
    return depth


def _check_arity(name: str, nargs: int):
    """Reject calls whose argument count the op cannot bind — at COMPILE
    time, so a 101-paper signature mismatch (``scale(x, 2)``,
    ``sum(x)`` without the window) surfaces as a reportable ValueError
    instead of a TypeError mid-evaluation inside the jit batch."""
    try:
        sig = inspect.signature(_OPS[name])
    except (TypeError, ValueError):  # some jnp callables hide theirs
        return
    try:
        sig.bind(*([None] * nargs))
    except TypeError:
        raise ValueError(f"{name} does not take {nargs} argument(s)") from None


def compile_alpha(source: str) -> AlphaExpr:
    """Parse an expression string into a callable panel op.

    Raises ValueError on any syntax outside what :func:`_eval_node` can
    evaluate (the whitelist below — attribute access, subscripts, lambdas,
    comprehensions, lists/tuples/dicts, ternaries, boolean operators,
    f-strings, ``//``/bitwise operators, ... are all rejected), on a call
    with unbindable arity, on an op name used as a value (op names are
    reserved words — evaluation would mistake one for a panel field), and
    on the 101-ambiguous ``min(x, d)``/``max(x, d)`` integer form (the
    paper reads it as ts_min/ts_max; this DSL's min/max are elementwise).
    Everything is checked HERE so that nothing that compiles can later
    fail inside the shared jit batch, where one bad expression would abort
    the whole chunk: parser blowups on degenerate sampling-loop lines
    become ValueError, and the node/operator/constant whitelists are
    exactly ``_eval_node``'s capabilities.
    """
    if len(source) > _MAX_SOURCE_CHARS:
        raise ValueError(
            f"expression too long: {len(source)} chars (max "
            f"{_MAX_SOURCE_CHARS}) — degenerate sampling-loop line?")
    try:
        tree = ast.parse(source, mode="eval")
    except (RecursionError, MemoryError):
        # CPython's parser overflows its stack on deep token runs
        # ('-'*3000 + 'close') — per-line handlers expect ValueError
        raise ValueError("expression too deeply nested to parse") from None
    depth = _ast_depth(tree)
    if depth > _MAX_AST_DEPTH:
        raise ValueError(
            f"expression nests {depth} levels deep (max {_MAX_AST_DEPTH}) — "
            "evaluation would overflow the recursion limit mid-batch")
    callees = {id(n.func) for n in ast.walk(tree) if isinstance(n, ast.Call)}
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(f"disallowed syntax in alpha: "
                             f"{type(node).__name__} in {ast.dump(node)[:60]}")
        if isinstance(node, ast.Constant) and (
                isinstance(node.value, bool)
                or not isinstance(node.value, (int, float))):
            # strings/None/bytes/complex would reach jnp ops and die
            # there; bools are not part of the DSL grammar either
            raise ValueError(
                f"non-numeric constant {str(node.value)[:40]!r} in alpha")
        if isinstance(node, ast.Compare) and len(node.ops) != 1:
            raise ValueError("chained comparisons unsupported in alpha")
        if isinstance(node, ast.Call):
            if not isinstance(node.func, ast.Name) or node.func.id not in _OPS:
                raise ValueError(f"unknown function in alpha: {ast.dump(node.func)[:60]}")
            _check_arity(node.func.id, len(node.args))
            _check_static_int_args(node)
            if (node.func.id in ("min", "max") and len(node.args) == 2
                    and isinstance(node.args[1], ast.Constant)
                    and isinstance(node.args[1].value, int)):
                raise ValueError(
                    f"ambiguous {node.func.id}(x, {node.args[1].value}): the "
                    "101-alphas paper reads this as the windowed "
                    f"ts_{node.func.id}; write ts_{node.func.id}(x, d) for "
                    "the window or use a float (e.g. "
                    f"{node.args[1].value}.0) for an elementwise clamp")
        elif isinstance(node, ast.Name) and node.id in _OPS \
                and id(node) not in callees:
            raise ValueError(f"op name {node.id!r} used as a value "
                             "(op names are reserved words)")
    fields: set = set()
    _collect_fields(tree, fields)
    return AlphaExpr(source=source, tree=tree, fields=tuple(sorted(fields)))


def _eval_node(node, panel):
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        return panel[node.id]
    if isinstance(node, ast.BinOp):
        return _BINOPS[type(node.op)](_eval_node(node.left, panel),
                                      _eval_node(node.right, panel))
    if isinstance(node, ast.UnaryOp):
        v = _eval_node(node.operand, panel)
        if isinstance(node.op, ast.USub):
            return -v
        if isinstance(node.op, ast.UAdd):
            return v
        raise ValueError("unsupported unary op")
    if isinstance(node, ast.Compare):
        if len(node.ops) != 1:
            raise ValueError("chained comparisons unsupported")
        return _CMPOPS[type(node.ops[0])](_eval_node(node.left, panel),
                                          _eval_node(node.comparators[0], panel))
    if isinstance(node, ast.Call):
        args = [_eval_node(a, panel) for a in node.args]
        return _OPS[node.func.id](*args)
    raise ValueError(f"unsupported node {type(node).__name__}")


def compile_alpha_batch(sources: Sequence[str], chunk: int = 1000) -> Callable:
    """Compile a batch of expressions into a panel -> (E, T, N) callable.

    Batches beyond ``chunk`` expressions compile as separate sub-jits
    (VERDICT r3 weak #6): total compile then grows linearly in E instead of
    whatever one unbounded program costs.  The default keeps the BASELINE
    1,000-expression config in ONE program, which measures *fastest* on TPU
    — per-program overhead dominates below that size (measured 2026-07-29,
    1,000 exprs, compile+first-exec: chunk=100 -> 89 s, 250 -> 48 s,
    500 -> 50 s, single jit -> 33 s) — while still bounding the 10k+ regime.
    Within a chunk XLA CSEs shared subexpressions.  Reuse the returned
    callable to amortize compilation over repeated panels.

    Do NOT wrap the returned callable in an outer ``jax.jit`` when chunking
    matters — tracing would inline every chunk back into one program.
    ``chunk=None`` forces the single-jit behavior regardless of size.
    """
    if not sources:
        # fail at compile time with a real message — an empty list would
        # otherwise surface as chunk=0 slicing or an IndexError below
        raise ValueError("no sources")
    exprs = [compile_alpha(s) for s in sources]
    chunk = len(exprs) if not chunk else chunk
    groups = [exprs[i:i + chunk] for i in range(0, len(exprs), chunk)]

    def make_run(es):
        @jax.jit
        def run(p):
            return jnp.stack([e(p) for e in es], axis=0)
        return run

    runs = [make_run(es) for es in groups]
    if len(runs) == 1:
        return runs[0]

    def run_all(p):
        return jnp.concatenate([r(p) for r in runs], axis=0)

    return run_all


def compile_alpha_scores(sources: Sequence[str], chunk: int = 50) -> Callable:
    """Compile expressions into a fused ``(panel, fwd_ret) -> summary``
    callable that never materializes the full (E, T, N) alpha tensor.

    The all-A memory plan (BASELINE config 5 at 2500 x 5000): one alpha
    panel is T*N*4 = 50 MB, so 1,000 stacked alphas are 50 GB — far past a
    single chip's HBM — and the window ops (``_windows``: ts_rank/ts_min/
    ts_corr materialize (T, W, N)) add up to W x 50 MB of transient per
    expression.  Scoring INSIDE each chunk's jit reduces every alpha to its
    (E,)-shaped summary stats before the next chunk runs, so live HBM is
    ``chunk`` panels + one window buffer: chunk=50 keeps it ~2.5 GB + the
    largest (T, W, N) transient.  Returns a dict of (E,) arrays in source
    order (:func:`mfm_tpu.alpha.metrics.alpha_summary` keys).

    Like :func:`compile_alpha_batch`: do NOT wrap the result in an outer
    ``jax.jit`` — tracing would inline every chunk into one program.
    """
    from mfm_tpu.alpha.metrics import alpha_summary

    if not sources:
        raise ValueError("no sources")
    exprs = [compile_alpha(s) for s in sources]
    chunk = len(exprs) if not chunk else chunk
    groups = [exprs[i:i + chunk] for i in range(0, len(exprs), chunk)]

    def make_run(es):
        @jax.jit
        def run(p, fwd):
            return alpha_summary(jnp.stack([e(p) for e in es], axis=0), fwd)
        return run

    runs = [make_run(es) for es in groups]
    if len(runs) == 1:
        return runs[0]

    def run_all(p, fwd):
        outs = [r(p, fwd) for r in runs]
        return {k: jnp.concatenate([o[k] for o in outs], axis=0)
                for k in outs[0]}

    return run_all


def evaluate_alphas(
    sources: Sequence[str],
    panel: Mapping[str, jax.Array],
    jit: bool = True,
) -> jax.Array:
    """One-shot batch evaluation -> (E, T, N) (BASELINE.json config 5).

    For repeated evaluation compile once with :func:`compile_alpha_batch`.
    """
    if jit:
        return compile_alpha_batch(sources)(dict(panel))
    exprs = [compile_alpha(s) for s in sources]
    return jnp.stack([e(dict(panel)) for e in exprs], axis=0)
