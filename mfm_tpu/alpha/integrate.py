"""Alpha -> risk-model integration: the title's full loop.

The reference promises an "LLM-Driven Multi-factor Model" but contains no
LLM-factor code at all (SURVEY.md intro); this module closes the loop the
title describes: a batch of (LLM-)generated alpha expressions is evaluated
over the raw market panel, scored against forward returns, greedily
de-correlated (:mod:`mfm_tpu.alpha.select`), and the survivors become extra
*style columns* of the barra table — so the constrained cross-sectional
regression prices them alongside the classic styles and the covariance
stack forecasts their risk.

Exposure convention: each selected alpha is per-date z-scored over its
valid cross-section and missing values become 0 (= mean exposure), so the
reference's drop-any-NaN row filter (``demo.py:25-27``) never loses rows to
alpha warm-up windows; the regression's own cap-weighted standardization
(``CrossSection.py:12-20``) then rescales like any other style.  On dates
where an alpha is entirely invalid the column is all-zero and the
constrained solve's pseudo-inverse (the reference's own degeneracy policy,
``CrossSection.py:76``) prices it at ~0.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from mfm_tpu.alpha.dsl import compile_alpha, cs_zscore, evaluate_alphas
from mfm_tpu.alpha.metrics import information_coefficient
from mfm_tpu.alpha.select import select_alphas


def alpha_style_columns(
    sources: Sequence[str],
    fields: Mapping[str, jax.Array],
    fwd_ret: jax.Array,
    k: int,
    max_corr: float = 0.7,
) -> tuple[list[str], np.ndarray, dict]:
    """Evaluate, select, and standardize alphas into style-column form.

    Args:
      sources: candidate expressions (validated against ``fields``).
      fields: (T, N) panel fields the expressions reference.
      fwd_ret: (T, N) next-period returns (the barra table's ``ret``).
      k / max_corr: selection budget and pairwise PnL-correlation cap
        (:func:`mfm_tpu.alpha.select.select_alphas`).

    Returns ``(names, exposures (T, N, k'), report)`` with k' <= k selected
    columns named ``alpha_01``.. in selection order, exposures z-scored per
    date with NaN -> 0, and a JSON-ready report mapping each name to its
    expression and mean IC.
    """
    if not sources:
        raise ValueError("no alpha expressions given")
    for i, src in enumerate(sources, 1):
        expr = compile_alpha(src)  # raises on bad syntax/vocabulary
        missing = [f for f in expr.fields if f not in fields]
        if missing:
            raise ValueError(f"expression {i} references unknown panel "
                             f"field(s) {missing}: {src!r}")
    alphas = evaluate_alphas(sources, fields)          # (E, T, N)
    # one IC pass serves both the selection scores (select_alphas' default
    # is exactly |mean IC| — passing it avoids recomputing the full
    # (E, T, N) reduction) and the report
    ic = information_coefficient(alphas, fwd_ret)      # (E, T)
    m = jnp.isfinite(ic)
    mean_ic = jnp.sum(jnp.where(m, ic, 0.0), axis=-1) / jnp.maximum(
        jnp.sum(m, axis=-1), 1)
    sel = select_alphas(alphas, fwd_ret, k, max_corr=max_corr,
                        scores=jnp.abs(mean_ic))
    chosen = sel["indices"]                            # selection order
    if not len(chosen):
        raise ValueError("alpha selection kept no expressions (all scores "
                         "below the floor or pairwise-correlated away)")

    z = cs_zscore(alphas[jnp.asarray(chosen)])         # (k', T, N)
    z = jnp.where(jnp.isfinite(z), z, 0.0)
    exposures = np.moveaxis(np.asarray(z, np.float32), 0, -1)  # (T, N, k')

    names = [f"alpha_{i + 1:02d}" for i in range(len(chosen))]
    report = {
        name: {
            "expression": sources[int(e)],
            "mean_ic": float(mean_ic[int(e)]),
            # sel["scores"] is aligned to the selection order, not to the
            # expression index
            "score": float(sel["scores"][pos]),
        }
        for pos, (name, e) in enumerate(zip(names, chosen))
    }
    return names, exposures, report
