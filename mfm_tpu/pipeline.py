"""End-to-end pipelines: raw panel -> factor table -> barra assembly -> risk model.

The TPU-native equivalents of the reference's two drivers:

- :func:`assemble_barra_table` + :func:`run_factor_pipeline` ≈
  ``Barra_factor_cal/main.py`` (factor production: compute, post-process,
  merge industry, shift returns to t+1, rename to barra schema,
  ``main.py:42-159``)
- :func:`run_risk_pipeline` ≈ ``Barra-master/demo.py`` (risk model over a
  barra table, saving factor returns / specific returns / R2 / covariances /
  lambda, ``demo.py:22-94``)
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Dict, Mapping

import numpy as np

import jax
import jax.numpy as jnp

from mfm_tpu.config import PipelineConfig
from mfm_tpu.obs import instrument as _telemetry
from mfm_tpu.data.barra import BarraArrays, barra_frame_to_arrays
from mfm_tpu.factors.engine import FactorEngine, rowspace_index, gather_rows, scatter_rows
from mfm_tpu.models.risk_model import RiskModel, RiskModelOutputs, RiskModelState

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


#: composite -> barra output name (Barra_factor_cal/config.py:53-72)
BARRA_OUTPUT_STYLES = (
    ("SIZE", "size"),
    ("BETA", "beta"),
    ("RSTR", "momentum"),
    ("volatility", "residual_volatility"),
    ("NLSIZE", "non_linear_size"),
    ("BP", "book_to_price_ratio"),
    ("liquidity", "liquidity"),
    ("earnings", "earnings_yield"),
    ("growth", "growth"),
    ("leverage", "leverage"),
)


def shift_ret_next_period(ret, observed):
    """t+1 return label: each (stock, day) row gets the stock's *next traded
    day* return (``main.py:99``: groupby shift(-1) on the long frame)."""
    idx = rowspace_index(jnp.asarray(observed))
    rs = gather_rows(jnp.asarray(ret), idx)
    shifted = jnp.concatenate(
        [rs[1:], jnp.full((1, rs.shape[1]), jnp.nan, rs.dtype)], axis=0
    )
    return np.asarray(scatter_rows(shifted, idx))


def assemble_barra_table(
    factors: Mapping[str, np.ndarray],
    dates,
    stocks,
    industry_l1,
    circ_mv,
    observed,
):
    """Long barra-format DataFrame in the reference's output schema.

    factors: dict of (T, N) arrays containing at least the composite names in
    BARRA_OUTPUT_STYLES plus 'ret'.  industry_l1: (N,) per-stock SW L1 codes.
    Rows = observed (stock, day) cells; 'ret' is shifted to the next traded
    day.  Column names/order: ``config.BARRA_OUTPUT_COLUMNS``.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    observed = np.asarray(observed, bool)
    ti, si = np.nonzero(observed)
    next_ret = shift_ret_next_period(np.asarray(factors["ret"]), observed)
    data = {
        "date": np.asarray(dates)[ti],
        "stocknames": np.asarray(stocks)[si],
        "capital": np.asarray(circ_mv)[ti, si],
        "ret": next_ret[ti, si],
        "industry": np.asarray(industry_l1)[si],
    }
    for src, dst in BARRA_OUTPUT_STYLES:
        data[dst] = np.asarray(factors[src])[ti, si]
    return pd.DataFrame(data)


def run_factor_pipeline(
    fields: Dict,
    index_close,
    industry_l1,
    dates,
    stocks,
    config: PipelineConfig | None = None,
):
    """Raw dense panel -> (barra long table, factor dict).

    ``fields`` must include everything :class:`FactorEngine` needs, plus
    ``circ_mv``.  This is the whole ``Barra_factor_cal/main.py`` path.
    ``config.block`` sizes the rolling kernels' date blocks.
    """
    config = config or PipelineConfig()
    dtype = jnp.float64 if config.dtype == "float64" else jnp.float32
    jfields = {
        k: (jnp.asarray(v, dtype) if k != "end_date_code" else jnp.asarray(v))
        for k, v in fields.items()
    }
    eng = FactorEngine(jfields, jnp.asarray(index_close, dtype),
                       config=config.factors, block=config.block,
                       rolling_impl=config.rolling_impl)
    factors = {k: np.asarray(v) for k, v in eng.run().items()}
    observed = np.isfinite(np.asarray(fields["close"], np.float64))
    barra = assemble_barra_table(
        factors, dates, stocks, industry_l1, fields["circ_mv"], observed
    )
    return barra, factors


@dataclasses.dataclass
class RiskPipelineResult:
    outputs: RiskModelOutputs
    arrays: BarraArrays
    #: the fitted model, when this result came from a live run; None when
    #: rehydrated from artifacts (:func:`load_risk_pipeline_result`) — every
    #: result method works off outputs+arrays alone
    model: RiskModel | None = None
    #: the resumable scan state after the last date, when the run was asked
    #: for one (``run_risk_pipeline(with_state=True)`` or
    #: :func:`append_risk_pipeline`); persist with
    #: :func:`save_pipeline_state` to serve future dates in O(1) each
    state: RiskModelState | None = None
    #: per-date guard verdicts over the appended slab
    #: (:class:`mfm_tpu.serve.guard.GuardReport`) when the append ran with
    #: quarantine enabled; ``report.served_cov`` is the degraded-mode
    #: covariance series a reader should be handed
    report: object | None = None
    #: (half_life, ngroup, q, min_periods) -> (T, N) shrunk specific vol
    _spec_cache: dict = dataclasses.field(default_factory=dict, repr=False)

    # -- demo.py:60-94 result tables --------------------------------------
    def factor_returns(self):
        return pd.DataFrame(np.asarray(self.outputs.factor_ret),
                            index=self.arrays.dates,
                            columns=self.arrays.factor_names())

    def r_squared(self):
        return pd.DataFrame(np.asarray(self.outputs.r2),
                            index=self.arrays.dates, columns=["R2"])

    def specific_returns(self):
        return pd.DataFrame(np.asarray(self.outputs.specific_ret),
                            index=self.arrays.dates, columns=self.arrays.stocks)

    def final_covariance(self):
        """Last date's fully-adjusted covariance (annualizable), like
        ``demo.py:84-88``."""
        return pd.DataFrame(np.asarray(self.outputs.vr_cov[-1]),
                            index=self.arrays.factor_names(),
                            columns=self.arrays.factor_names())

    def lambda_series(self):
        return pd.DataFrame(np.asarray(self.outputs.lamb),
                            index=self.arrays.dates, columns=["lambda"])

    # -- portfolio-level combination (the model's end use; the reference
    # -- stops at the covariance CSVs, demo.py:60-94) -----------------------
    def specific_risk(self, half_life: float = 42.0, ngroup: int = 10,
                      q: float = 1.0, min_periods: int = 10):
        """(raw, shrunk) per-stock specific-vol DataFrames (T x N):
        EWMA specific volatility Bayes-shrunk toward cap-group means
        (``utils.py:133-168``, the stage the reference defines but never
        wires)."""
        raw, shrunk = self._specific_panels(half_life, ngroup, q, min_periods)
        f = lambda x: pd.DataFrame(x, index=self.arrays.dates,
                                   columns=self.arrays.stocks)
        return f(raw), f(shrunk)

    def _specific_panels(self, half_life, ngroup, q, min_periods):
        """Cached (raw, shrunk) (T, N) specific-vol panels per parameter
        set — one EWMA scan + shrinkage shared by :meth:`specific_risk` and
        :meth:`portfolio_risk`."""
        from mfm_tpu.models.specific import specific_risk_by_time

        key = (half_life, ngroup, q, min_periods)
        if key not in self._spec_cache:
            raw, shrunk = specific_risk_by_time(
                self.outputs.specific_ret, jnp.asarray(self.arrays.cap),
                half_life=half_life, ngroup=ngroup, q=q,
                min_periods=min_periods)
            self._spec_cache[key] = (np.asarray(raw), np.asarray(shrunk))
        return self._spec_cache[key]

    def portfolio_bias(self, n_portfolios: int = 100, seed: int = 0,
                       burn_in: int = 252, half_life: float = 42.0,
                       ngroup: int = 10, q: float = 1.0,
                       min_periods: int = 10) -> dict:
        """Random-portfolio bias statistics — the USE4 acceptance test the
        reference only runs on eigenfactor portfolios.  ``n_portfolios``
        random long-only base portfolios (|N(0,1)| weights over all stocks,
        restricted per date to the regression universe with a specific-vol
        estimate and renormalized); predicted vol from the adjusted factor
        covariance + shrunk specific risk; realized from the t+1-labelled
        returns.  Returns a JSON-ready dict with the per-portfolio bias
        list and aggregates, full-sample and burn-in-excluded
        (:func:`mfm_tpu.models.bias.portfolio_bias_stat`)."""
        from mfm_tpu.models.bias import bias_std, portfolio_bias_stat
        from mfm_tpu.ops.xreg import regression_design

        a = self.arrays
        T = a.ret.shape[0]
        X, dval, _ = jax.vmap(
            lambda r, c, s, i, v: regression_design(
                r, c, s, i, v, n_industries=a.n_industries)
        )(jnp.asarray(a.ret), jnp.asarray(a.cap), jnp.asarray(a.styles),
          jnp.asarray(a.industry), jnp.asarray(a.valid))
        spec = jnp.asarray(
            self._specific_panels(half_life, ngroup, q, min_periods)[1])
        rng = np.random.default_rng(seed)
        weights = jnp.asarray(
            np.abs(rng.standard_normal((n_portfolios, a.ret.shape[1]))),
            X.dtype)
        # vr_cov's validity is the eigen stage's (the vol-regime stage only
        # scales it by lambda^2)
        z, ok = portfolio_bias_stat(
            X, dval, jnp.asarray(self.outputs.vr_cov),
            jnp.asarray(self.outputs.eigen_valid), spec,
            jnp.asarray(a.ret), weights)

        def agg(mask):
            b = np.asarray(bias_std(z, mask))
            fin = b[np.isfinite(b)]
            dev = np.abs(fin - 1.0)
            r = lambda x: round(float(x), 4)
            return {
                "bias": [r(v) if np.isfinite(v) else None for v in b],
                "mean": r(fin.mean()) if fin.size else None,
                "median": r(np.median(fin)) if fin.size else None,
                "mean_abs_dev_from_1": r(dev.mean()) if fin.size else None,
                "max_abs_dev_from_1": r(dev.max()) if fin.size else None,
            }

        out = {"n_portfolios": int(n_portfolios), "seed": int(seed),
               "all_valid_dates": agg(ok)}
        t_ok = jnp.arange(T - 1) >= burn_in
        if bool(np.asarray(ok & t_ok[None, :]).any()):
            out[f"after_burn_in_{burn_in}"] = agg(ok & t_ok[None, :])
        return out

    def portfolio_risk(self, weights, t: int = -1, specific_vol=None,
                       half_life: float = 42.0, ngroup: int = 10,
                       q: float = 1.0, min_periods: int = 10) -> dict:
        """Predicted portfolio risk at date ``t``:
        ``sigma_p^2 = x'Fx + sum_i w_i^2 sigma_i^2`` with x = X_t' w.

        ``weights``: (N,) finite, aligned to ``arrays.stocks``; weight on
        stocks outside date t's regression universe must be 0 (raises).
        X_t is the regression's own design (shared builder
        :func:`mfm_tpu.ops.xreg.regression_design`), so F (the
        vol-regime-adjusted covariance) applies to x in the exact basis it
        was estimated in.  ``specific_vol``: (N,) per-stock vol at date t;
        defaults to the shrunk EWMA specific risk with the given
        ``half_life``/``ngroup``/``q``/``min_periods`` (same defaults as
        :meth:`specific_risk`; the panel is computed once and cached per
        parameter set).  Held stocks with no vol estimate raise rather than
        silently dropping their idiosyncratic variance.
        """
        from mfm_tpu.ops.xreg import regression_design

        a = self.arrays
        T = a.ret.shape[0]
        t = int(t)
        if not -T <= t < T:
            # no silent modulo wrap: t = T (the classic len(dates)
            # off-by-one) must not quietly report date-0 risk
            raise IndexError(f"date index {t} out of range for T={T}")
        t %= T
        w = np.asarray(weights, np.float64)
        if not np.isfinite(w).all():
            raise ValueError("weights must be finite (reindex fills of NaN "
                             "on out-of-universe stocks must be 0)")
        X, valid, _ = regression_design(
            jnp.asarray(a.ret[t]), jnp.asarray(a.cap[t]),
            jnp.asarray(a.styles[t]), jnp.asarray(a.industry[t]),
            jnp.asarray(a.valid[t]), n_industries=a.n_industries)
        X, valid = np.asarray(X, np.float64), np.asarray(valid)
        if np.abs(w[~valid]).sum() > 0:
            raise ValueError("nonzero weight on stocks outside the date-t "
                             "regression universe")
        F = np.asarray(self.outputs.vr_cov[t], np.float64)
        if not np.isfinite(F).all():
            raise ValueError(f"no valid adjusted covariance at date index {t}")
        x = X.T @ w
        Fx = F @ x
        factor_var = float(x @ Fx)
        if specific_vol is None:
            specific_vol = self._specific_panels(
                half_life, ngroup, q, min_periods)[1][t]
        sv = np.asarray(specific_vol, np.float64)
        held = np.abs(w) > 0
        if np.isnan(sv[held]).any():
            n_bad = int(np.isnan(sv[held]).sum())
            raise ValueError(
                f"{n_bad} held stock(s) have no specific-vol estimate at "
                f"date index {t} (fewer than min_periods={min_periods} "
                "observations); pass specific_vol= explicitly or zero their "
                "weight")
        spec_var = float(np.sum((w[held] ** 2) * (sv[held] ** 2)))
        # Euler decomposition of the factor variance: contribution_i =
        # x_i (F x)_i, summing exactly to x'Fx — the per-factor risk
        # attribution a Barra covariance exists to provide
        contrib = x * Fx
        return {
            "date": a.dates[t],
            "factor_var": factor_var,
            "specific_var": spec_var,
            "total_vol": float(np.sqrt(factor_var + spec_var)),
            "factor_exposures": pd.Series(x, index=a.factor_names()),
            "factor_risk_contribution": pd.Series(contrib,
                                                  index=a.factor_names()),
        }

    def query_engine(self, t: int = -1, benchmarks=None,
                     half_life: float = 42.0, ngroup: int = 10,
                     q: float = 1.0, min_periods: int = 10):
        """Build the batched :class:`mfm_tpu.serve.query.QueryEngine` for
        date ``t`` — the serving-side counterpart of
        :meth:`portfolio_risk`: same X_t basis, same covariance, same
        shrunk specific vols, but answering B portfolios per call in one
        vmapped jit instead of one python dict each.

        When the run was guarded (quarantine enabled), the engine serves
        the guard report's ``served_cov[t]`` — the degraded-serving
        contract — and carries its staleness stamp; otherwise the raw
        adjusted covariance with staleness 0.  Out-of-universe stocks get
        zeroed exposure/specific-var rows: the REQUEST guard layer, not
        the engine math, is where invalid weight gets rejected.

        ``benchmarks``: optional ``{name: (N,) stock weights}`` served for
        active-risk/beta queries.
        """
        from mfm_tpu.ops.xreg import regression_design
        from mfm_tpu.serve.query import QueryEngine

        a = self.arrays
        T = a.ret.shape[0]
        t = int(t)
        if not -T <= t < T:
            raise IndexError(f"date index {t} out of range for T={T}")
        t %= T
        X, valid, _ = regression_design(
            jnp.asarray(a.ret[t]), jnp.asarray(a.cap[t]),
            jnp.asarray(a.styles[t]), jnp.asarray(a.industry[t]),
            jnp.asarray(a.valid[t]), n_industries=a.n_industries)
        X, valid = np.asarray(X), np.asarray(valid)
        X = np.where(valid[:, None], X, 0.0)
        if self.report is not None:
            F = np.asarray(self.report.served_cov[t])
            staleness = int(np.asarray(self.report.staleness[t]))
        else:
            F = np.asarray(self.outputs.vr_cov[t])
            staleness = 0
        sv = self._specific_panels(half_life, ngroup, q, min_periods)[1][t]
        svar = np.where(valid & np.isfinite(sv), sv, 0.0) ** 2
        return QueryEngine(
            F, factor_names=a.factor_names(), exposures=X,
            specific_var=svar, stocks=list(map(str, a.stocks)),
            benchmarks=benchmarks, staleness=staleness)


class LazyBarraArrays:
    """:class:`BarraArrays` facade over a :class:`BarraCOO`.

    Metadata (dates/stocks/codes/names) is immediate; the first access to a
    dense panel attribute densifies once and caches.  The sharded pipeline
    path returns this so the RUN never builds a host-side dense panel —
    only post-hoc analytics that genuinely need one (``portfolio_bias``,
    the specific-risk cap groups) pay the densification, lazily.
    """

    _PANELS = ("ret", "cap", "styles", "industry", "valid")

    def __init__(self, coo, dtype=np.float64):
        self._coo, self._dtype, self._dense = coo, dtype, None
        self.dates = coo.dates
        self.stocks = coo.stocks
        self.industry_codes = coo.industry_codes
        self.style_names = list(coo.style_names)

    @property
    def n_industries(self) -> int:
        return len(self.industry_codes)

    def factor_names(self) -> list:
        return self._coo.factor_names()

    def __getattr__(self, name):
        if name in LazyBarraArrays._PANELS:
            if self._dense is None:
                self._dense = self._coo.to_arrays(self._dtype)
            return getattr(self._dense, name)
        raise AttributeError(name)


def _sharded_risk_panels(coo, mesh, dtype):
    """Materialize the five risk-model panels DIRECTLY in their sharded
    mesh layout: ``jax.make_array_from_callback`` asks for each device's
    ``(date, stock)`` rectangle and :meth:`BarraCOO.block` densifies only
    those rows — the host never holds a full (T, N) dense panel.

    Global shapes are pre-padded to mesh-divisible sizes (the
    ``pad_to_mesh`` doctrine); a padding cell is simply a rectangle no
    table row falls in, so it densifies to missing data (NaN / valid
    False) — inert by the model's masked design, no separate fill step.
    Returns ``(panels, (T, N))`` with T, N the real (unpadded) extents.
    """
    from jax.sharding import NamedSharding

    from mfm_tpu.parallel.mesh import PIPELINE_SPECS

    T, N, Q = len(coo.dates), len(coo.stocks), len(coo.style_names)
    nd, ns = mesh.shape["date"], mesh.shape["stock"]
    Tp, Np = T + (-T) % nd, N + (-N) % ns
    np_dtype = np.dtype(dtype)
    cache = {}

    def _block(t0, t1, s0, s1):
        key = (t0, t1, s0, s1)
        if key not in cache:
            cache[key] = coo.block(t0, t1, s0, s1, dtype=np_dtype)
        return cache[key]

    def make(name, shape):
        sharding = NamedSharding(mesh, PIPELINE_SPECS[name])

        def cb(index):
            t0, t1, _ = index[0].indices(shape[0])
            s0, s1, _ = index[1].indices(shape[1])
            return _block(t0, t1, s0, s1)[name]

        return jax.make_array_from_callback(shape, sharding, cb)

    panels = (make("ret", (Tp, Np)), make("cap", (Tp, Np)),
              make("styles", (Tp, Np, Q)), make("industry", (Tp, Np)),
              make("valid", (Tp, Np)))
    return panels, (T, N)


def _crop_outputs(out: RiskModelOutputs, T: int, N: int) -> RiskModelOutputs:
    """Crop mesh-padded outputs back to the real (T, N) extents."""
    return RiskModelOutputs(
        factor_ret=out.factor_ret[:T], specific_ret=out.specific_ret[:T, :N],
        r2=out.r2[:T], nw_cov=out.nw_cov[:T], nw_valid=out.nw_valid[:T],
        eigen_cov=out.eigen_cov[:T], eigen_valid=out.eigen_valid[:T],
        vr_cov=out.vr_cov[:T], lamb=out.lamb[:T])


def run_risk_pipeline(
    barra_df=None,
    arrays: BarraArrays | None = None,
    config: PipelineConfig | None = None,
    industry_codes=None,
    sim_covs=None,
    sim_length: int | None = None,
    fused: bool = True,
    with_state: bool = False,
    mesh=None,
) -> RiskPipelineResult:
    """Barra table -> full risk model (the ``demo.py`` path).

    ``sim_length`` declares the draw count behind injected ``sim_covs``,
    engaging the production eigen auto-sweep path; omitting it with
    ``sim_covs`` set falls back to the conservative full-sweep count.
    (Without ``sim_covs`` the draws are generated internally and
    ``config.risk.eigen_sim_length`` already declares their count.)

    ``fused`` (default) runs all four stages as one jitted program with
    donated panel inputs (:meth:`RiskModel.run_fused`); the panels here are
    fresh per-call copies, so donation costs callers nothing.  ``False``
    keeps the stage-by-stage dispatch (e.g. to inspect intermediates under
    a debugger).

    ``with_state`` runs :meth:`RiskModel.init_state` instead (same fused
    math, also returns the final scan carries) and sets ``result.state`` —
    the checkpoint :func:`append_risk_pipeline` serves new dates from.

    ``mesh`` (a ``('date','stock')`` mesh, :func:`mfm_tpu.parallel.mesh.
    make_mesh`) runs the risk stack SHARDED: panel construction is
    shard-local (each device densifies only its own block straight from
    the long table's row space — no host-side full-panel densify) and the
    fused program executes under the mesh with the bitwise stock-gather
    doctrine.  Outputs are cropped back to the real (T, N); a state run
    requires T divisible by the mesh date axis and N by its stock axis
    (time/stock padding must never enter the resumable carries).
    """
    config = config or PipelineConfig()
    dtype = jnp.float64 if config.dtype == "float64" else jnp.float32
    if mesh is not None:
        return _run_risk_pipeline_sharded(
            barra_df, arrays, config, industry_codes, sim_covs, sim_length,
            fused, with_state, mesh, dtype)
    if arrays is None:
        arrays = barra_frame_to_arrays(barra_df, industry_codes=industry_codes)
    # jnp.array (copying), not asarray: the panels are donated by the fused
    # init/update jits, and on CPU asarray can zero-copy alias the numpy
    # buffers — donating memory JAX does not own corrupts outputs.
    rm = RiskModel(
        jnp.array(arrays.ret, dtype), jnp.array(arrays.cap, dtype),
        jnp.array(arrays.styles, dtype), jnp.array(arrays.industry),
        jnp.array(arrays.valid), n_industries=arrays.n_industries,
        config=config.risk, factor_names=arrays.factor_names(),
    )
    if with_state:
        out, state = rm.init_state(
            sim_covs=sim_covs, sim_length=sim_length,
            last_date=date_stamp(arrays.dates[-1]))
        return RiskPipelineResult(outputs=out, arrays=arrays, model=rm,
                                  state=state)
    run = rm.run_fused if fused else rm.run
    out = run(sim_covs=sim_covs, sim_length=sim_length)
    return RiskPipelineResult(outputs=out, arrays=arrays, model=rm)


def _run_risk_pipeline_sharded(barra_df, arrays, config, industry_codes,
                               sim_covs, sim_length, fused, with_state,
                               mesh, dtype):
    """The ``mesh=`` body of :func:`run_risk_pipeline` (see its docstring).

    The long table goes to row space (:func:`barra_frame_to_coo`) and each
    device materializes its own panel block; a caller handing pre-densified
    ``arrays`` still gets mesh execution (the panels are re-laid-out
    per-shard), just not the ingest saving.
    """
    from mfm_tpu.data.barra import barra_frame_to_coo
    from mfm_tpu.parallel.mesh import use_mesh

    if arrays is None:
        coo = barra_frame_to_coo(barra_df, industry_codes=industry_codes)
        result_arrays = LazyBarraArrays(coo, np.dtype(dtype))
    else:
        # dense arrays already exist — wrap them in the same block protocol
        # so one code path builds the sharded panels
        coo = _DenseBlocks(arrays)
        result_arrays = arrays

    nd, ns = mesh.shape["date"], mesh.shape["stock"]
    T, N = len(coo.dates), len(coo.stocks)
    if with_state and (T % nd or N % ns):
        raise ValueError(
            f"a state (resumable-carry) run cannot be mesh-padded: T={T} "
            f"must divide the date axis ({nd}) and N={N} the stock axis "
            f"({ns}) — pick a compatible mesh (make_mesh(n_date=...)) or "
            "run unsharded")
    panels, (T, N) = _sharded_risk_panels(coo, mesh, dtype)
    with use_mesh(mesh):
        rm = RiskModel(
            *panels, n_industries=coo.n_industries,
            config=config.risk, factor_names=coo.factor_names(),
        )
        if with_state:
            out, state = rm.init_state(
                sim_covs=sim_covs, sim_length=sim_length,
                last_date=date_stamp(coo.dates[-1]))
            return RiskPipelineResult(outputs=_crop_outputs(out, T, N),
                                      arrays=result_arrays, model=rm,
                                      state=state)
        run = rm.run_fused if fused else rm.run
        out = run(sim_covs=sim_covs, sim_length=sim_length)
    return RiskPipelineResult(outputs=_crop_outputs(out, T, N),
                              arrays=result_arrays, model=rm)


class _DenseBlocks:
    """Adapter giving pre-densified :class:`BarraArrays` the
    :meth:`BarraCOO.block` protocol (slice instead of densify), so
    :func:`_sharded_risk_panels` serves both ingest forms."""

    def __init__(self, arrays):
        self._a = arrays
        self.dates, self.stocks = arrays.dates, arrays.stocks
        self.industry_codes = arrays.industry_codes
        self.style_names = list(arrays.style_names)

    @property
    def n_industries(self):
        return len(self.industry_codes)

    def factor_names(self):
        return self._a.factor_names()

    def block(self, t0, t1, s0, s1, dtype=np.float64):
        a = self._a
        T, N = a.ret.shape
        out = {}
        for name, fill in (("ret", np.nan), ("cap", np.nan),
                           ("styles", np.nan), ("industry", -1),
                           ("valid", False)):
            src = getattr(a, name)
            shape = (t1 - t0, s1 - s0) + src.shape[2:]
            dt = (dtype if src.dtype.kind == "f" else src.dtype)
            blk = np.full(shape, fill, dt)
            tt, ss = min(t1, T), min(s1, N)
            if tt > t0 and ss > s0:
                blk[:tt - t0, :ss - s0] = src[t0:tt, s0:ss]
            out[name] = blk
        return out


def save_pipeline_state(path: str, result: RiskPipelineResult):
    """Persist ``result.state`` with the alignment metadata an append in a
    later process needs: the stock axis, style order, industry code list and
    dtype the checkpoint's arrays were built against.  The append path pins
    its slab densification to these, so row/column alignment is identical
    to the run that produced the checkpoint."""
    from mfm_tpu.data.artifacts import save_risk_state

    if result.state is None:
        raise ValueError("result has no state — run the pipeline with "
                         "with_state=True (or append_risk_pipeline)")
    a = result.arrays
    save_risk_state(path, result.state, meta={
        "stocks": np.asarray(a.stocks).astype(str).tolist(),
        "style_names": list(map(str, a.style_names)),
        "industry_codes": np.asarray(a.industry_codes).tolist(),
        "dtype": str(np.asarray(result.outputs.factor_ret).dtype),
        "n_dates": int(len(a.dates)),
        "first_date": date_stamp(a.dates[0]),
    })


def append_risk_pipeline(
    state_path: str,
    barra_df,
    config: PipelineConfig | None = None,
    force: bool = False,
    mesh=None,
) -> RiskPipelineResult:
    """Serve the new date(s) of a barra table from a saved checkpoint.

    Rehydrates the :func:`save_pipeline_state` artifact, selects the rows of
    ``barra_df`` strictly after the checkpoint's last date, densifies them
    pinned to the checkpoint's stock/style/industry axes, and runs ONE
    O(slab) :meth:`RiskModel.update` step — no recompute of the history.
    Returns a result covering only the appended dates, with ``result.state``
    advanced past them (save it back with :func:`save_pipeline_state` to
    continue tomorrow).  Outputs are bitwise what a full-history rerun would
    produce for those dates.  Raises when the table holds no new dates.

    With ``config.risk.quarantine.enabled`` (and a checkpoint initialized
    under it), the update runs GUARDED (:meth:`RiskModel.update_guarded`):
    slab dates are health-checked, quarantined dates are excised from the
    carries and served the last healthy covariance, and ``result.report``
    carries the verdicts.  ``force`` overrides the checkpoint generation
    fencing (:func:`mfm_tpu.data.artifacts.load_risk_state`).

    With ``mesh`` (a ``make_mesh`` ('date','stock') mesh), the slab panels
    are sharded and the state replicated so the ONE update step computes on
    the mesh — bitwise the single-device update (the cross-section is
    gathered once per stage, so per-date math is identical).  The slab must
    divide the mesh exactly: the update folds every row into the carries,
    so a padded slab would corrupt them.
    """
    from mfm_tpu.data.artifacts import load_risk_state
    from mfm_tpu.serve.guard import host_date_reasons

    config = config or PipelineConfig()
    state, meta = load_risk_state(state_path, force=force)
    arrays = barra_frame_to_arrays(
        barra_df,
        industry_codes=np.asarray(meta["industry_codes"]),
        style_names=list(meta["style_names"]),
        stocks=np.asarray(meta["stocks"]),
    )
    last = state.last_date
    keep = np.array([last is None or date_stamp(d) > last
                     for d in arrays.dates], bool)
    if not keep.any():
        raise ValueError(
            f"{state_path}: checkpoint already covers every date in the "
            f"table (last_date={last!r})")
    sl = arrays
    slab = BarraArrays(
        dates=sl.dates[keep], stocks=sl.stocks,
        ret=sl.ret[keep], cap=sl.cap[keep], styles=sl.styles[keep],
        industry=sl.industry[keep], valid=sl.valid[keep],
        industry_codes=sl.industry_codes, style_names=sl.style_names,
    )
    dtype = jnp.float64 if config.dtype == "float64" else jnp.float32
    # copying conversion — the slab panels are donated (see run_risk_pipeline)
    panels = (
        jnp.array(slab.ret, dtype), jnp.array(slab.cap, dtype),
        jnp.array(slab.styles, dtype), jnp.array(slab.industry),
        jnp.array(slab.valid),
    )
    mesh_ctx = contextlib.nullcontext()
    if mesh is not None:
        from mfm_tpu.parallel.mesh import replicated, shard_panel, use_mesh

        nd, ns = int(mesh.shape["date"]), int(mesh.shape["stock"])
        Ts, Ns = len(slab.dates), len(slab.stocks)
        if Ts % nd or Ns % ns:
            raise ValueError(
                f"sharded append: slab (T={Ts}, N={Ns}) must divide the "
                f"({nd} date x {ns} stock) mesh exactly — the update folds "
                "every row into the carries, so a padded slab would corrupt "
                "them")
        panels = shard_panel(panels, mesh)
        state = jax.device_put(state, replicated(mesh))
        mesh_ctx = use_mesh(mesh)
    with mesh_ctx:
        return _append_update_step(panels, slab, state, config, last)


def _append_update_step(panels, slab, state, config, last):
    from mfm_tpu.serve.guard import host_date_reasons

    rm = RiskModel(
        *panels, n_industries=slab.n_industries,
        config=config.risk, factor_names=slab.factor_names(),
    )
    if config.risk.quarantine.enabled:
        # the host-side date-order pre-check feeds the traced guards; a
        # disordered date is quarantined, not folded into the carries
        pre = host_date_reasons(
            [date_stamp(d) for d in slab.dates], last_date=last)
        t0 = time.perf_counter()
        outputs, report, new_state = rm.update_guarded(
            state, last_date=date_stamp(slab.dates[-1]), pre_reasons=pre)
        # host-side telemetry off the materialized report (mfmlint R7:
        # recording happens around the fused jit step, never inside it)
        _telemetry.record_guard_report(report)
        _telemetry.record_update_latency(time.perf_counter() - t0)
        return RiskPipelineResult(outputs=outputs, arrays=slab, model=rm,
                                  state=new_state, report=report)
    t0 = time.perf_counter()
    outputs, new_state = rm.update(state,
                                   last_date=date_stamp(slab.dates[-1]))
    _telemetry.record_update_latency(time.perf_counter() - t0)
    return RiskPipelineResult(outputs=outputs, arrays=slab, model=rm,
                              state=new_state)


def date_stamp(d) -> str:
    """Calendar-day form of a date value, for artifact identity stamps
    (normalizes datetime64-precision / CSV-string representation drift)."""
    try:
        return str(pd.Timestamp(d).date())
    except (ValueError, TypeError):
        return str(d)


def load_risk_pipeline_result(out_dir: str,
                              barra_csv: str = "barra_data.csv",
                              npz: str = "risk_outputs.npz",
                              industry_info: str = "industry_info.csv"):
    """Rehydrate a finished ``pipeline`` output directory.

    Reads the stage artifacts the ``pipeline`` subcommand writes (the barra
    table, the one-hot code list, and the ``risk_outputs.npz``) back into a
    :class:`RiskPipelineResult`, so post-hoc analytics — result tables,
    :meth:`~RiskPipelineResult.specific_risk`,
    :meth:`~RiskPipelineResult.portfolio_risk`,
    :meth:`~RiskPipelineResult.portfolio_bias` — run without recomputing
    the model (the reference's analogue is re-reading its result CSVs).
    ``model`` is None on a rehydrated result.
    """
    import os

    from mfm_tpu.data.artifacts import load_risk_outputs
    from mfm_tpu.data.barra import load_barra_csv

    outputs, meta = load_risk_outputs(os.path.join(out_dir, npz))
    info_path = os.path.join(out_dir, industry_info)
    arrays = load_barra_csv(
        os.path.join(out_dir, barra_csv),
        info_path if os.path.exists(info_path) else None)
    if arrays.ret.shape != np.asarray(outputs.specific_ret).shape:
        raise ValueError(
            f"{out_dir}: barra table shape {arrays.ret.shape} does not match "
            f"the artifact's {np.asarray(outputs.specific_ret).shape} — "
            "mixed outputs from different runs?")
    if np.asarray(outputs.factor_ret).shape[1] != len(arrays.factor_names()):
        raise ValueError(
            f"{out_dir}: the barra table implies "
            f"{len(arrays.factor_names())} factors but the artifact holds "
            f"{np.asarray(outputs.factor_ret).shape[1]} — industry_info.csv "
            "missing or from a different run?")
    # exact-identity stamp when the artifact carries one (cli.py writes
    # first/last dates) — catches same-shape mixes the heuristics can't
    stamp = meta.get("dates")
    if stamp is not None:
        have = [date_stamp(arrays.dates[0]), date_stamp(arrays.dates[-1])]
        if have != [date_stamp(s) for s in stamp]:
            raise ValueError(f"{out_dir}: barra table covers {have} but the "
                             f"artifact was saved for {stamp}")
    return RiskPipelineResult(outputs=outputs, arrays=arrays)
