"""Command-line drivers (the reference has none — plain scripts only,
SURVEY.md §1 L7).

  python -m mfm_tpu.cli risk --barra barra_data.csv --out results/
  python -m mfm_tpu.cli factors --prepared prepared/ --out results/
  python -m mfm_tpu.cli demo --out results/          # synthetic end-to-end
  python -m mfm_tpu.cli pipeline --store data/ --out results/  # store -> risk
  python -m mfm_tpu.cli alpha --exprs alphas.txt --panel panel.csv
  python -m mfm_tpu.cli crosscheck --ours a.csv --external b.csv
  python -m mfm_tpu.cli report --results results/ --plot report.png
  python -m mfm_tpu.cli etl-update --store data/ --start 20200101
  python -m mfm_tpu.cli etl-verify --store data/     # verify_data.py path
  python -m mfm_tpu.cli etl-missing --store data/    # fill_missing_data.py path
"""

from __future__ import annotations

import argparse
import json
import os
import time


def _profile_ctx(profile_dir):
    """jax.profiler capture context, or a no-op when no dir was asked for
    (shared by every --profile flag; SURVEY §5's tracing subsystem)."""
    import contextlib

    if not profile_dir:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(profile_dir)


def _maybe_portfolio_bias(res, args) -> None:
    """Run the USE4 random-portfolio acceptance test and write
    ``OUT/portfolio_bias.json`` when ``--portfolio-bias Q`` was given
    (shared by the ``risk`` and ``pipeline`` subcommands)."""
    if not args.portfolio_bias:
        return
    rep = res.portfolio_bias(n_portfolios=args.portfolio_bias,
                             burn_in=args.bias_burn_in)
    with open(os.path.join(args.out, "portfolio_bias.json"), "w") as fh:
        json.dump(rep, fh, indent=1)


def _maybe_portfolio_risk(res, args) -> None:
    """Predicted portfolio risk + per-factor Euler attribution for a
    ``ts_code,weight`` CSV, written to ``OUT/portfolio_risk.json`` when
    ``--portfolio FILE`` was given (shared by ``risk`` and ``pipeline``).

    Unknown ts_codes in the file are an error (a silent drop would change
    the portfolio); universe stocks absent from the file get weight 0."""
    if not args.portfolio:
        return
    import numpy as np
    import pandas as pd

    pf = pd.read_csv(args.portfolio)
    missing = {"ts_code", "weight"} - set(pf.columns)
    if missing:
        raise SystemExit(f"--portfolio file lacks columns {sorted(missing)}")
    stocks = list(res.arrays.stocks)
    unknown = sorted(set(pf["ts_code"]) - set(stocks))
    if unknown:
        raise SystemExit(f"--portfolio has ts_codes outside the panel: "
                         f"{unknown[:5]}{'...' if len(unknown) > 5 else ''}")
    dup = pf["ts_code"][pf["ts_code"].duplicated()]
    if len(dup):
        # label assignment below is last-wins; silently collapsing repeated
        # rows would compute risk for a different portfolio
        raise SystemExit(f"--portfolio lists ts_codes more than once: "
                         f"{sorted(set(dup))[:5]}")
    w = pd.Series(0.0, index=stocks)
    w[pf["ts_code"].to_numpy()] = pf["weight"].to_numpy(float)
    rep = res.portfolio_risk(w.to_numpy(), t=args.portfolio_date)
    rep = {k: (v.to_dict() if isinstance(v, pd.Series)
               else v if not isinstance(v, np.generic) else v.item())
           for k, v in rep.items()}
    rep["date"] = str(rep["date"])
    with open(os.path.join(args.out, "portfolio_risk.json"), "w") as fh:
        json.dump(rep, fh, indent=1)


def _save_outputs_npz(res, out: str, source) -> None:
    """Persist every stage output (incl. the full covariance series) as one
    identity-stamped artifact — one schema shared by ``risk`` and
    ``pipeline`` so the stamp keys never drift between the two.  Load with
    ``load_risk_outputs``; the full-result rehydration
    (``load_risk_pipeline_result``) additionally needs the barra-table
    stage artifacts only the ``pipeline`` subcommand writes."""
    from mfm_tpu.data.artifacts import save_risk_outputs
    from mfm_tpu.pipeline import date_stamp

    save_risk_outputs(
        os.path.join(out, "risk_outputs.npz"), res.outputs,
        meta={"source": source,
              "dates": [date_stamp(res.arrays.dates[0]),
                        date_stamp(res.arrays.dates[-1])],
              "n_stocks": int(res.arrays.ret.shape[1])})


def _write_result_tables(res, out: str, specific_risk: bool) -> None:
    """The five demo.py result tables (``demo.py:60-94``) plus, beyond the
    reference, the USE4 specific-risk panel (EWMA vol, Bayes-shrunk;
    models/specific.py) when asked."""
    os.makedirs(out, exist_ok=True)
    res.factor_returns().to_csv(os.path.join(out, "factor_returns.csv"))
    res.r_squared().to_csv(os.path.join(out, "r_squared.csv"))
    res.specific_returns().to_csv(os.path.join(out, "specific_returns.csv"))
    res.final_covariance().to_csv(os.path.join(out, "final_covariance.csv"))
    res.lambda_series().to_csv(os.path.join(out, "lambda.csv"))
    if specific_risk:
        _, shrunk = res.specific_risk()
        shrunk.to_csv(os.path.join(out, "specific_risk.csv"))


def _report_json(res) -> dict:
    """JSON-ready quarantine summary of an append result's GuardReport."""
    import numpy as np
    from mfm_tpu.pipeline import date_stamp
    from mfm_tpu.serve.guard import reason_names

    rep = res.report
    q = np.asarray(rep.quarantined, bool)
    reasons = np.asarray(rep.reasons)
    stale = np.asarray(rep.staleness)
    # the report covers the appended slab; on the `pipeline --append` path
    # res.arrays is the full concatenated history, so align from the tail
    dates = [date_stamp(d) for d in res.arrays.dates[-len(q):]]
    return {
        "quarantined": [
            {"date": dates[i], "reasons": reason_names(int(reasons[i])),
             "staleness": int(stale[i])}
            for i in np.nonzero(q)[0]
        ],
        "quarantine_count_total": int(np.asarray(
            res.state.quarantine_count)) if res.state is not None else None,
    }


def _metrics_init(args) -> None:
    """``--metrics-dir``: route the JSONL event stream there and start the
    compile-event counter (telemetry records regardless; this adds sinks)."""
    mdir = getattr(args, "metrics_dir", None)
    if not mdir:
        return
    from mfm_tpu.obs.exporters import emit_event, route_events_to
    from mfm_tpu.obs.instrument import watch_compiles

    os.makedirs(mdir, exist_ok=True)
    route_events_to(os.path.join(mdir, "events.jsonl"))
    watch_compiles()
    emit_event("info", "run_start", cmd=args.cmd)


def _metrics_flush(args) -> None:
    """``--metrics-dir``: write the Prometheus textfile + snapshot JSON +
    the run's Chrome trace (atomic, Perfetto-loadable)."""
    mdir = getattr(args, "metrics_dir", None)
    if not mdir:
        return
    from mfm_tpu.obs.exporters import emit_event, write_prometheus_textfile
    from mfm_tpu.obs.metrics import snapshot_json
    from mfm_tpu.obs.trace import spans, write_chrome_trace

    write_prometheus_textfile(os.path.join(mdir, "metrics.prom"))
    with open(os.path.join(mdir, "metrics.json"), "w") as fh:
        fh.write(snapshot_json() + "\n")
    if spans():
        write_chrome_trace(os.path.join(mdir, "trace.json"))
    emit_event("info", "run_end", cmd=args.cmd)


def _root_span(args):
    """Open the per-run root span; its trace_id lands in the run manifest
    so ``doctor`` can join manifests to traces.  Explicit start/end (not a
    context manager) so command bodies stay flat."""
    from mfm_tpu.obs.trace import new_trace_id, start_span

    return start_span(f"cli.{args.cmd}", trace_id=new_trace_id())


def _write_manifest_beside(state_path: str, res, trace_id=None) -> dict:
    """After a checkpoint save: run-manifest next to it (atomic), carrying
    the checkpoint's identity stamp, the guard verdict summary, the live
    metrics snapshot, and the model-health verdict.  Returns the health
    dict.  This is the CLI layer on purpose: the health monitors compile
    their own small programs, which must never ride the ≤1-compile
    steady-state update path."""
    import jax

    from mfm_tpu.data.artifacts import _stamp_to_json
    from mfm_tpu.obs.health import evaluate_health
    from mfm_tpu.obs.instrument import guard_summary_from_registry
    from mfm_tpu.obs.manifest import (
        build_run_manifest, manifest_path_for, write_run_manifest,
    )
    from mfm_tpu.obs.metrics import REGISTRY

    guard = guard_summary_from_registry()
    health = evaluate_health(res.outputs, guard_summary=guard)
    manifest = build_run_manifest(
        stamp_json=(_stamp_to_json(res.state.stamp)
                    if res.state is not None else None),
        checkpoint=state_path,
        backend=jax.devices()[0].platform,
        metrics_snapshot=REGISTRY.snapshot(),
        guard_summary=guard,
        health=health,
        extra=({"trace_id": trace_id} if trace_id else None),
    )
    write_run_manifest(manifest_path_for(state_path), manifest)
    return health


def _parse_mesh_arg(spec):
    """``--mesh DxS`` -> a ('date','stock') device mesh over the first
    D*S devices, or None.  The risk paths then compute sharded: panels
    shard-local, state replicated (PR 11's scaling knob)."""
    if not spec:
        return None
    import jax

    from mfm_tpu.parallel.mesh import make_mesh

    d, _, s = str(spec).lower().partition("x")
    try:
        nd, ns = int(d), int(s) if s else 1
    except ValueError:
        raise SystemExit(f"--mesh: want DATExSTOCK (e.g. 2x4), got {spec!r}")
    need = nd * ns
    if need > jax.device_count():
        raise SystemExit(
            f"--mesh {spec}: needs {need} devices but only "
            f"{jax.device_count()} are up — on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} before launch")
    return make_mesh(nd, ns, devices=jax.devices()[:need])


def _risk(args):
    import numpy as np
    import pandas as pd
    from mfm_tpu.config import (
        PipelineConfig, QuarantinePolicy, RiskModelConfig,
    )
    from mfm_tpu.data.barra import barra_frame_to_arrays
    from mfm_tpu.pipeline import run_risk_pipeline

    if args.bias_plot:
        _require_matplotlib("--bias-plot")  # before the pipeline runs
    if args.update and args.save_state:
        raise SystemExit("--update advances its checkpoint FILE in place; "
                         "drop --save-state")
    if (args.update or args.save_state) and args.nw_method != "scan":
        raise SystemExit("the resumable state is the serial scan's carry; "
                         "--save-state/--update need --nw-method scan")
    if args.update and (args.bias_plot or args.portfolio_bias):
        # bias statistics need history; an appended slab has none
        raise SystemExit("--update serves new dates only — run the bias "
                         "acceptance tests on a full-history run instead")
    _metrics_init(args)
    from mfm_tpu.obs.trace import end_span

    root = _root_span(args)

    cfg = PipelineConfig(
        risk=RiskModelConfig(
            nw_lags=args.nw_lags, nw_half_life=args.nw_half_life,
            nw_method=args.nw_method,
            eigen_n_sims=args.eigen_sims, eigen_scale_coef=args.eigen_scale,
            eigen_chunk=args.eigen_chunk,
            eigen_sim_length=args.eigen_sim_length,
            eigen_mc_dtype=args.eigen_mc_dtype,
            eigen_incremental=args.eigen_incremental,
            vol_regime_half_life=args.vr_half_life, seed=args.seed,
            quarantine=QuarantinePolicy(enabled=args.quarantine),
        ),
        dtype=args.dtype,
    )
    if args.barra_store:
        # the demo.ipynb variant: barra table from the store's
        # ``barra_factors`` collection (written by ``pipeline --to-store``,
        # the reference's main.py:144-155 Mongo save) instead of a CSV
        from mfm_tpu.data.etl import PanelStore

        st = PanelStore(args.barra_store)
        df = st.read("barra_factors")
        if not len(df):
            raise SystemExit(f"{args.barra_store}: no barra_factors "
                             "collection (run `pipeline --to-store` first)")
        if args.industry_info:
            # an explicit file wins over the store's own collection (same
            # role as on the CSV path: fix the one-hot code order)
            codes = pd.read_csv(args.industry_info)["code"].to_numpy()
        else:
            info = st.read("sw_industry_info_for_factors")
            if not len(info):
                # as strict as the barra_factors check: a data-derived
                # one-hot order would silently diverge from the pipeline's
                raise SystemExit(
                    f"{args.barra_store}: no sw_industry_info_for_factors "
                    "collection — rerun `pipeline --to-store`, or pass the "
                    "code list explicitly with --industry-info")
            codes = info["code"].to_numpy()
    else:
        df = pd.read_csv(args.barra)
        codes = (pd.read_csv(args.industry_info)["code"].to_numpy()
                 if args.industry_info else None)

    if args.update:
        # incremental serving: one O(slab) update step from the checkpoint
        # instead of the O(T) full-history rebuild — same outputs, bitwise
        from mfm_tpu.pipeline import (
            append_risk_pipeline, date_stamp, save_pipeline_state,
        )

        from mfm_tpu.data.artifacts import (
            ArtifactCorruptError, ArtifactStaleError,
        )

        t0 = time.perf_counter()
        with _profile_ctx(args.profile or args.jax_profile):
            try:
                res = append_risk_pipeline(args.update, df, config=cfg,
                                           force=args.force,
                                           mesh=_parse_mesh_arg(args.mesh))
            except (ValueError, ArtifactCorruptError,
                    ArtifactStaleError) as err:
                raise SystemExit(f"--update: {err}") from err
        _write_result_tables(res, args.out, args.specific_risk)
        save_pipeline_state(args.update, res)  # advance the checkpoint
        wall = time.perf_counter() - t0
        from mfm_tpu.obs.instrument import record_stage_seconds

        record_stage_seconds("update_total", wall)
        health = _write_manifest_beside(args.update, res,
                                        trace_id=root.trace_id)
        if args.save_outputs:
            _save_outputs_npz(res, args.out,
                              args.barra or args.barra_store)
        _maybe_portfolio_risk(res, args)
        rec = {
            "appended_dates": [date_stamp(d) for d in res.arrays.dates],
            "stocks": int(res.arrays.ret.shape[1]),
            "factors": len(res.arrays.factor_names()),
            "update_wall_s": round(wall, 3),
            "mean_r2": float(np.nanmean(np.asarray(res.outputs.r2))),
            "state": args.update,
            "health": health["status"],
            "trace_id": root.trace_id,
        }
        if res.report is not None:
            rec.update(_report_json(res))
        end_span(root, wall_s=round(wall, 3))
        _metrics_flush(args)
        print(json.dumps(rec))
        return

    arrays = barra_frame_to_arrays(df, industry_codes=codes)
    t0 = time.perf_counter()
    # the reported wall_s includes the profiler overhead when --profile is on
    with _profile_ctx(args.profile or args.jax_profile):
        res = run_risk_pipeline(arrays=arrays, config=cfg,
                                with_state=bool(args.save_state),
                                mesh=_parse_mesh_arg(args.mesh))
    _write_result_tables(res, args.out, args.specific_risk)
    wall = time.perf_counter() - t0
    from mfm_tpu.obs.instrument import record_stage_seconds

    record_stage_seconds("risk_full", wall)
    if args.save_state:
        # checkpoint the resumable scan state (outside the timed region,
        # like the artifact/plot writes below); `risk --update FILE` serves
        # the next dates from it in O(1) each
        from mfm_tpu.pipeline import save_pipeline_state

        save_pipeline_state(args.save_state, res)
        _write_manifest_beside(args.save_state, res, trace_id=root.trace_id)
    if args.save_outputs:
        # the full (T, K, K) covariance series + every stage output as one
        # artifact (the CSV tables only carry the last date's covariance,
        # demo.py:84-88) — same format the pipeline subcommand writes.
        # Outside the timed region, like the plotting below
        _save_outputs_npz(res, args.out, args.barra or args.barra_store)
    # plotting stays outside the timed region (matplotlib import + render
    # would otherwise pollute the reported pipeline wall-clock)
    if args.bias_plot:
        import jax
        from mfm_tpu.models.bias import bias_stats_summary, plot_bias_stats

        # the USE4 acceptance numbers + picture (utils.py:97-117): the eigen
        # adjustment must pull the bias statistic toward 1, most visibly at
        # the extreme eigenfactor ranks
        o = res.outputs
        summary = bias_stats_summary(o.nw_cov, o.nw_valid, o.eigen_cov,
                                     o.eigen_valid, o.factor_ret,
                                     burn_in=args.bias_burn_in)
        plot_bias_stats(
            {label: np.array([np.nan if v is None else v for v in d["bias"]])
             for label, d in summary["all_valid_dates"].items()},
            os.path.join(args.out, args.bias_plot),
        )
        summary["backend"] = jax.devices()[0].platform
        with open(os.path.join(args.out, "bias_stats.json"), "w") as fh:
            json.dump(summary, fh, indent=1)
    # USE4's headline acceptance test (random test portfolios) — the
    # reference only runs the eigen-portfolio variant
    _maybe_portfolio_bias(res, args)
    _maybe_portfolio_risk(res, args)
    end_span(root, wall_s=round(wall, 3))
    _metrics_flush(args)
    print(json.dumps({
        "dates": int(arrays.ret.shape[0]), "stocks": int(arrays.ret.shape[1]),
        "factors": len(arrays.factor_names()), "wall_s": round(wall, 3),
        "mean_r2": float(np.nanmean(np.asarray(res.outputs.r2))),
        "trace_id": root.trace_id,
    }))


#: the three artifacts `prepare` writes and `factors --prepared` consumes
PREPARED_PANEL = "panel.parquet"
PREPARED_INDEX = "index_prices.csv"
PREPARED_INDUSTRY = "industry_map.csv"


def _read_long_table(path):
    """csv/parquet long table with a parsed trade_date column."""
    import pandas as pd

    return (pd.read_parquet(path) if path.endswith(".parquet")
            else pd.read_csv(path, parse_dates=["trade_date"]))


def _factors(args):
    import numpy as np
    import pandas as pd
    from mfm_tpu.config import PipelineConfig
    from mfm_tpu.panel import Panel
    from mfm_tpu.pipeline import run_factor_pipeline

    if args.prepared:
        # consume a `prepare` output directory directly (its three
        # artifacts have fixed names — no need to spell them out)
        for flag, val in (("--panel", args.panel), ("--index", args.index),
                          ("--industry", args.industry)):
            if val:
                raise SystemExit(f"--prepared already provides {flag}; "
                                 "drop one of the two")
        args.panel = os.path.join(args.prepared, PREPARED_PANEL)
        args.index = os.path.join(args.prepared, PREPARED_INDEX)
        args.industry = os.path.join(args.prepared, PREPARED_INDUSTRY)
        missing = [p for p in (args.panel, args.index, args.industry)
                   if not os.path.exists(p)]
        if missing:
            raise SystemExit(f"--prepared {args.prepared}: missing "
                             f"artifact(s) {missing} (run `prepare` first)")
    elif not (args.panel and args.index and args.industry):
        raise SystemExit("pass either --prepared DIR or all of "
                         "--panel/--index/--industry")
    panel_df = _read_long_table(args.panel)
    index_df = _read_long_table(args.index)
    ind_df = pd.read_csv(args.industry)

    p = Panel.from_long(panel_df)
    idx_close = (
        index_df.set_index("trade_date")["close"].reindex(pd.Index(p.dates)).to_numpy()
    )
    l1 = (
        ind_df.drop_duplicates("ts_code").set_index("ts_code")["l1_code"]
        .reindex(p.stocks).to_numpy()
    )
    # report id for TTM: rank-encode end_date per cell if provided
    if "end_date" in p.fields:
        ed = np.asarray(p.fields["end_date"])
        ok = np.isfinite(ed)
        codes = np.unique(ed[ok])
        rid = np.full(ed.shape, -1, np.int32)
        rid[ok] = np.searchsorted(codes, ed[ok]).astype(np.int32)
        p.fields["end_date_code"] = rid
        del p.fields["end_date"]
    barra, _ = run_factor_pipeline(
        p.fields, idx_close, l1, p.dates, p.stocks,
        PipelineConfig(dtype=args.dtype, block=args.block,
                       rolling_impl=args.rolling_impl),
    )
    os.makedirs(args.out, exist_ok=True)
    out_path = os.path.join(args.out, "barra_data.csv")
    barra.to_csv(out_path, index=False)
    print(json.dumps({"rows": len(barra), "out": out_path}))


def _demo(args):
    from mfm_tpu.config import PipelineConfig, RiskModelConfig
    from mfm_tpu.data.synthetic import synthetic_barra_table
    from mfm_tpu.pipeline import run_risk_pipeline

    df, _ = synthetic_barra_table(T=args.dates, N=args.stocks, P=args.industries,
                                  Q=args.styles, seed=0)
    cfg = PipelineConfig(risk=RiskModelConfig(eigen_n_sims=args.eigen_sims),
                         dtype=args.dtype)
    t0 = time.perf_counter()
    res = run_risk_pipeline(barra_df=df, config=cfg)
    # all five demo.py result tables, like the risk/pipeline subcommands
    _write_result_tables(res, args.out, specific_risk=False)
    rec = {"wall_s": round(time.perf_counter() - t0, 3), "out": args.out}
    if args.check_determinism:
        # the framework's sanitizer (SURVEY §5's race-detector analogue):
        # same seed, same inputs -> bitwise-equal outputs, twice over
        from mfm_tpu.utils.obs import determinism_check

        rec["deterministic"] = determinism_check(
            lambda: run_risk_pipeline(barra_df=df, config=cfg).outputs)
    print(json.dumps(rec))


def _prepare(args):
    """Store -> factor-input artifacts (the ``load_and_prepare_data`` path,
    ``load_data.py:66-418``): a long master panel parquet + index prices +
    per-stock industry map, consumable by the ``factors`` subcommand."""
    import pandas as pd
    from mfm_tpu.data.etl import PanelStore
    from mfm_tpu.data.prepare import load_and_prepare_data, sw_l1_map

    store = PanelStore(args.store)
    master, index_px, sw = load_and_prepare_data(
        store, index_code=args.index_code, start_date=args.start,
        end_date=args.end, fin_start_date=args.fin_start)
    os.makedirs(args.out, exist_ok=True)
    out = master.copy()
    # encode report/announcement dates as yyyymmdd floats (NaN = none): the
    # factors path re-ranks end_date into the TTM report id
    for c in ("balance_sheet_f_ann_date", "financial_indicators_ann_date",
              "cashflow_f_ann_date", "end_date"):
        if c in out.columns:
            dtc = pd.to_datetime(out[c])
            out[c] = pd.to_numeric(dtc.dt.strftime("%Y%m%d"), errors="coerce")
    panel_path = os.path.join(args.out, PREPARED_PANEL)
    index_path = os.path.join(args.out, PREPARED_INDEX)
    industry_path = os.path.join(args.out, PREPARED_INDUSTRY)
    out.to_parquet(panel_path, index=False)
    index_px.to_csv(index_path, index=False)
    stocks = sorted(out["ts_code"].unique())
    pd.DataFrame({"ts_code": stocks,
                  "l1_code": sw_l1_map(sw, stocks)}).to_csv(
        industry_path, index=False)
    print(json.dumps({"rows": len(out), "stocks": len(stocks),
                      "panel": panel_path, "index": index_path,
                      "industry": industry_path}))


def _extract_llm_sources(text, path, known_fields=None):
    """Shared ``--llm`` ingestion (``alpha --llm`` and ``pipeline
    --alphas-llm``): tolerant extraction with per-line rejection reasons on
    stderr — stdout stays each command's single JSON line.  Returns
    ``(sources, count-only report)``."""
    import sys

    from mfm_tpu.alpha.llm import extract_expressions

    sources, rep = extract_expressions(text, known_fields=known_fields)
    for no, cand, reason in rep.pop("rejected"):
        print(f"{path}:{no}: skipped: {reason}", file=sys.stderr)
    return sources, rep


def _read_alpha_sources(path, llm=False):
    """Read + syntax-validate an ``--alphas`` expression file, fail-fast
    (before any expensive pipeline stage runs) with file:line context —
    same policy as the ``alpha`` subcommand's reader.  ``llm=True`` switches
    to tolerant extraction from raw LLM output (``alpha/llm.py``) instead of
    one-clean-expression-per-line."""
    from mfm_tpu.alpha.dsl import compile_alpha

    try:
        fh = open(path)
    except OSError as err:
        raise SystemExit(f"--alphas: {err}") from err
    sources = []
    with fh:
        if llm:
            sources, _ = _extract_llm_sources(fh.read(), path)
        else:
            for i, line in enumerate(fh, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    compile_alpha(line)
                except (ValueError, SyntaxError) as err:
                    raise SystemExit(f"{path}:{i}: {err}") from err
                sources.append(line)
    if not sources:
        raise SystemExit(f"--alphas: {path} has no expressions")
    return sources


def _append_alpha_styles(args, sources, barra, prep):
    """Evaluate/select the ``--alphas`` expressions on the prepared raw
    panel and append the survivors as style columns of the barra table (in
    memory only — the resumable stage artifact stays the classic factor
    table; selection is cheap and deterministic, so it recomputes per run)."""
    import numpy as np
    import pandas as pd
    import jax.numpy as jnp
    from mfm_tpu.alpha.integrate import alpha_style_columns

    fields = {k: jnp.asarray(np.asarray(v, np.float32))
              for k, v in prep.fields.items()}

    if getattr(args, "alphas_llm", False):
        # llm mode is tolerant end to end: an extracted expression whose
        # fields the prepared panel lacks (a hallucinated name) is dropped
        # with a report, not a pipeline abort — the field set only becomes
        # known here, after prepare ran
        import sys

        from mfm_tpu.alpha.dsl import compile_alpha

        kept = []
        for s in sources:
            missing = [f for f in compile_alpha(s).fields if f not in fields]
            if missing:
                print(f"--alphas (llm): dropped {s!r}: unknown panel "
                      f"field(s) {missing}", file=sys.stderr)
            else:
                kept.append(s)
        if not kept:
            raise SystemExit("--alphas: no extracted expression references "
                             f"known panel fields (have: {sorted(fields)})")
        sources = kept

    # forward returns = the barra table's own t+1 ``ret`` column, densified
    # on the prepared (dates x stocks) grid
    t_idx = {d: i for i, d in enumerate(pd.to_datetime(prep.dates))}
    s_idx = {s: j for j, s in enumerate(prep.stocks)}
    bdates = pd.to_datetime(barra["date"])
    ti = bdates.map(t_idx).to_numpy()
    si = barra["stocknames"].map(s_idx).to_numpy()
    if np.isnan(ti.astype(float)).any() or np.isnan(si.astype(float)).any():
        raise SystemExit("--alphas: the resumed barra table's dates/stocks "
                         "do not match the store's prepared panel — rerun "
                         "without --resume")
    T, N = len(prep.dates), len(prep.stocks)
    fwd = np.full((T, N), np.nan, np.float32)
    fwd[ti, si] = barra["ret"].to_numpy(np.float32)

    try:
        names, expo, report = alpha_style_columns(
            sources, fields, jnp.asarray(fwd),
            k=args.alpha_top, max_corr=args.alpha_max_corr)
    except ValueError as err:
        raise SystemExit(f"--alphas: {err}") from err

    barra = barra.drop(columns=[c for c in barra.columns
                                if c.startswith("alpha_")])
    for j, name in enumerate(names):
        barra[name] = expo[ti, si, j]
    return barra, report


def _check_append_prefix_unrevised(prev_barra, barra, last_date, dtype):
    """Refuse an append whose refreshed factor table rewrote history.

    Compares the rows at or before the checkpoint's last date between the
    table the prior run persisted and the refreshed one, on the columns
    both have, AT THE RISK COMPUTE DTYPE: the factor stage's f64
    intermediates jitter in the last ulp when the history length changes
    (XLA re-tiles the reductions), but only what survives the cast to
    ``dtype`` ever reached the checkpointed scan.  Dates normalize to the
    checkpoint's 'YYYY-MM-DD' stamps so string ordering is chronological."""
    import numpy as np
    import pandas as pd

    fdtype = np.dtype(dtype)

    if prev_barra is None:
        raise SystemExit("--append: the prior run's barra_data.csv is "
                         "missing — run the pipeline once without --append "
                         "first")

    def norm(df):
        df = df.copy()
        df["date"] = pd.to_datetime(df["date"]).dt.strftime("%Y-%m-%d")
        df = df[df["date"] <= last_date]
        return df.sort_values(["date", "stocknames"]).reset_index(drop=True)

    old, new = norm(prev_barra), norm(barra)
    cols = [c for c in old.columns if c in set(new.columns)]
    bad = None
    if len(old) != len(new) or \
            not old["date"].equals(new["date"]) or \
            not old["stocknames"].astype(str).equals(
                new["stocknames"].astype(str)):
        bad = "row set"
    else:
        for c in cols:
            oc, nc = old[c].to_numpy(), new[c].to_numpy()
            if oc.dtype.kind == "f" and nc.dtype.kind == "f":
                same = np.array_equal(oc.astype(fdtype), nc.astype(fdtype),
                                      equal_nan=True)
            else:
                same = bool((old[c].astype(str) == new[c].astype(str)).all())
            if not same:
                bad = f"column {c!r}"
                break
    if bad is not None:
        raise SystemExit(
            f"--append: the refreshed factor table revised history at or "
            f"before the checkpoint (last_date={last_date}, {bad} changed) "
            "— typically a next-traded-day return label filling in across "
            "a suspension gap.  The incremental path cannot reproduce a "
            "revised prefix; rerun without --append")


def _pipeline_append_stage(args, barra, cfg, prev_barra):
    """``--append``'s risk stage: prior outputs artifact + checkpoint + ONE
    :meth:`RiskModel.update` step over the dates past the checkpoint ->
    a full-history result, bitwise what a from-scratch rerun would produce
    for the risk stage.

    The factor stage's rolling windows are causal, so style/cap rows at or
    before the checkpoint cannot change — but the t+1 return label is NOT:
    ``shift_ret_next_period`` gives each row the stock's *next traded day*
    return, so a suspension gap straddling the checkpoint fills a prefix
    label in once the stock trades again.  A from-scratch rerun would see
    that revised history; the checkpoint didn't.  ``prev_barra`` (the table
    the prior run wrote) lets us detect the revision and refuse rather than
    silently diverge."""
    import numpy as np
    from mfm_tpu.data.artifacts import load_artifact, load_risk_outputs
    from mfm_tpu.data.barra import barra_frame_to_arrays
    from mfm_tpu.models.risk_model import RiskModelOutputs
    from mfm_tpu.pipeline import (
        RiskPipelineResult, append_risk_pipeline, date_stamp,
    )

    state_path = os.path.join(args.out, "risk_state.npz")
    prev_path = os.path.join(args.out, "risk_outputs.npz")
    for p in (state_path, prev_path):
        if not os.path.exists(p):
            raise SystemExit(f"--append: {p} not found — run the pipeline "
                             "once without --append first")
    prev, _ = load_risk_outputs(prev_path)
    _, smeta = load_artifact(state_path)
    _check_append_prefix_unrevised(prev_barra, barra, smeta["last_date"],
                                   cfg.dtype)
    from mfm_tpu.data.artifacts import (
        ArtifactCorruptError, ArtifactStaleError,
    )

    t0 = time.perf_counter()
    try:
        app = append_risk_pipeline(state_path, barra, config=cfg,
                                   force=args.force,
                                   mesh=_parse_mesh_arg(
                                       getattr(args, "mesh", None)))
    except (ValueError, ArtifactCorruptError, ArtifactStaleError) as err:
        raise SystemExit(f"--append: {err}") from err
    update_wall = time.perf_counter() - t0
    # full-history arrays pinned to the checkpoint's axes, so the
    # concatenated outputs' rows/columns line up with the new table exactly
    full = barra_frame_to_arrays(
        barra, industry_codes=app.arrays.industry_codes,
        style_names=app.arrays.style_names, stocks=app.arrays.stocks)
    T_prev = int(np.asarray(prev.r2).shape[0])
    if T_prev + len(app.arrays.dates) != len(full.dates):
        raise SystemExit(
            f"--append: {prev_path} covers {T_prev} dates but the refreshed "
            f"table has {len(full.dates)} with {len(app.arrays.dates)} new "
            "— the history itself changed; rerun without --append")
    cat = RiskModelOutputs(*[
        np.concatenate([np.asarray(p), np.asarray(n)], axis=0)
        for p, n in zip(prev, app.outputs)])
    res = RiskPipelineResult(outputs=cat, arrays=full, state=app.state,
                             report=app.report)
    return res, [date_stamp(d) for d in app.arrays.dates], update_wall


def _pipeline(args):
    """One-command end-to-end: raw store -> master panel -> factor table ->
    risk outputs (the reference's ``main.py`` + ``demo.py`` chain), with a
    stage artifact between the factor and risk stages for resume, and a
    risk-state checkpoint (``OUT/risk_state.npz``) for ``--append``'s
    O(new-dates) daily refresh."""
    import numpy as np
    import pandas as pd
    from mfm_tpu.config import (
        PipelineConfig, QuarantinePolicy, RiskModelConfig,
    )
    from mfm_tpu.data.etl import PanelStore
    from mfm_tpu.data.prepare import prepare_factor_inputs
    from mfm_tpu.pipeline import run_factor_pipeline, run_risk_pipeline

    if args.append and args.resume:
        raise SystemExit("--append re-runs the factor stage over the "
                         "refreshed store; drop --resume")
    if args.append and args.nw_method != "scan":
        raise SystemExit("the resumable state is the serial scan's carry; "
                         "--append needs --nw-method scan")
    _metrics_init(args)
    from mfm_tpu.obs.trace import end_span

    root = _root_span(args)
    cfg = PipelineConfig(
        risk=RiskModelConfig(
            nw_lags=args.nw_lags, nw_half_life=args.nw_half_life,
            nw_method=args.nw_method,
            eigen_n_sims=args.eigen_sims, eigen_scale_coef=args.eigen_scale,
            eigen_chunk=args.eigen_chunk,
            eigen_sim_length=args.eigen_sim_length,
            eigen_mc_dtype=args.eigen_mc_dtype,
            eigen_incremental=args.eigen_incremental,
            vol_regime_half_life=args.vr_half_life, seed=args.seed,
            quarantine=QuarantinePolicy(enabled=args.quarantine),
        ),
        dtype=args.dtype,
        block=args.block,
        rolling_impl=args.rolling_impl,
    )
    os.makedirs(args.out, exist_ok=True)
    barra_path = os.path.join(args.out, "barra_data.csv")
    industry_info_path = os.path.join(args.out, "industry_info.csv")
    t0 = time.perf_counter()

    # profiler capture spans both compute stages (factors + risk, plus the
    # stage-artifact pandas IO between them); the result-table writes after
    # the block stay out, and an exception inside still stops the trace
    # (no half-open profiler session)
    # fail-fast on a bad --alphas path/expression BEFORE the factor stage
    alpha_sources = (_read_alpha_sources(args.alphas, llm=args.alphas_llm)
                     if args.alphas else None)
    prep = None
    # the factor stage below overwrites barra_data.csv; --append's history-
    # revision check needs the prior run's table, so read it first
    prev_barra = (pd.read_csv(barra_path)
                  if args.append and os.path.exists(barra_path) else None)
    with _profile_ctx(args.profile or args.jax_profile):
        if args.resume and os.path.exists(barra_path) \
                and os.path.exists(industry_info_path):
            barra = pd.read_csv(barra_path)
        else:
            store = PanelStore(args.store)
            prep = prepare_factor_inputs(
                store, index_code=args.index_code, start_date=args.start,
                end_date=args.end, fin_start_date=args.fin_start)
            barra, _ = run_factor_pipeline(
                prep.fields, prep.index_close, prep.industry_l1,
                prep.dates, prep.stocks, cfg)
            barra.to_csv(barra_path, index=False)  # stage artifact (main.py:144)
            # industry_info: code list fixing the one-hot order (main.py:137-143)
            sw = store.read("sw_industries")
            info = (sw.drop_duplicates(subset=["l1_code"])
                    if len(sw) else pd.DataFrame({"l1_code": []}))
            info = info[info["l1_code"].isin(set(barra["industry"].dropna()))]
            pd.DataFrame({
                "code": info["l1_code"],
                "industry_names": info.get("l1_name", info["l1_code"]),
            }).sort_values("code").to_csv(industry_info_path, index=False)
        factor_wall = time.perf_counter() - t0

        info_df = pd.read_csv(industry_info_path)
        if args.to_store:
            # the reference persists the factor table to Mongo collections
            # ``barra_factors`` + ``sw_industry_info_for_factors``
            # (main.py:144-155, full refresh); same here against a
            # PanelStore, consumable by `risk --barra-store`
            out_store = PanelStore(args.to_store)
            out_store.replace("barra_factors", barra)
            out_store.replace("sw_industry_info_for_factors", info_df)

        n_alpha_styles = 0
        if args.alphas:
            # the title's full loop: (LLM-)generated alpha expressions ->
            # evaluate on the raw panel -> IC-score + de-correlate -> the
            # survivors join the barra table as extra style columns, priced
            # by the constrained regression and forecast by the covariance
            # stack (mfm_tpu/alpha/integrate.py)
            if prep is None:  # --resume skipped the prepare stage
                prep = prepare_factor_inputs(
                    PanelStore(args.store), index_code=args.index_code,
                    start_date=args.start, end_date=args.end,
                    fin_start_date=args.fin_start)
            barra, report = _append_alpha_styles(args, alpha_sources,
                                                 barra, prep)
            n_alpha_styles = len(report)
            with open(os.path.join(args.out, "alpha_styles.json"), "w") as fh:
                json.dump(report, fh, indent=1)

        codes = info_df["code"].to_numpy()
        appended = update_wall = None
        if args.append:
            res, appended, update_wall = _pipeline_append_stage(
                args, barra, cfg, prev_barra)
        else:
            # capture the resumable scan state alongside the outputs (same
            # fused math; the associative NW method has no serial carry to
            # checkpoint, so no state there)
            res = run_risk_pipeline(barra_df=barra, config=cfg,
                                    industry_codes=codes,
                                    with_state=cfg.risk.nw_method == "scan")
    _write_result_tables(res, args.out, args.specific_risk)
    wall = time.perf_counter() - t0
    from mfm_tpu.obs.instrument import record_stage_seconds

    record_stage_seconds("factor", factor_wall)
    record_stage_seconds("pipeline_total", wall)
    _save_outputs_npz(res, args.out, args.store)  # outside the timed region
    health = None
    if res.state is not None:
        # the daily-serving checkpoint `pipeline --append` resumes from
        from mfm_tpu.pipeline import save_pipeline_state

        state_path = os.path.join(args.out, "risk_state.npz")
        save_pipeline_state(state_path, res)
        health = _write_manifest_beside(state_path, res,
                                        trace_id=root.trace_id)
    # acceptance-test compute stays OUT of the reported wall (same policy
    # as _risk's bias block)
    _maybe_portfolio_bias(res, args)
    _maybe_portfolio_risk(res, args)
    rec = {
        "rows": int(len(barra)),
        "dates": int(res.arrays.ret.shape[0]),
        "stocks": int(res.arrays.ret.shape[1]),
        "factors": len(res.arrays.factor_names()),
        "factor_stage_wall_s": round(factor_wall, 3),
        "wall_s": round(wall, 3),
        "mean_r2": float(np.nanmean(np.asarray(res.outputs.r2))),
        "alpha_styles": n_alpha_styles,
        "out": args.out,
        "trace_id": root.trace_id,
    }
    if health is not None:
        rec["health"] = health["status"]
    if appended is not None:
        rec["appended_dates"] = appended
        rec["update_wall_s"] = round(update_wall, 3)
    if res.report is not None:
        rec.update(_report_json(res))
    end_span(root, wall_s=round(wall, 3))
    _metrics_flush(args)
    print(json.dumps(rec))


def _alpha(args):
    """Batch alpha evaluation + scorecard over a long panel (the BASELINE
    config-5 workload as a driver): expressions from a text file, one per
    line, scored against next-traded-day returns."""
    import numpy as np
    import pandas as pd
    import jax.numpy as jnp
    from mfm_tpu.alpha.dsl import compile_alpha, compile_alpha_batch
    from mfm_tpu.alpha.metrics import alpha_summary
    from mfm_tpu.panel import Panel
    from mfm_tpu.pipeline import shift_ret_next_period

    p = Panel.from_long(_read_long_table(args.panel))
    fields = {k: jnp.asarray(v, jnp.float32) for k, v in p.fields.items()}
    if args.fwd_field not in fields:
        raise SystemExit(f"panel has no field {args.fwd_field!r} "
                         f"(have: {sorted(fields)})")

    import contextlib
    import sys

    exprs = []
    llm_report = None
    # `--exprs -` reads stdin: the LLM-pipe workflow the title promises
    # (generator | mfm-tpu alpha --exprs - --panel ...)
    src = (contextlib.nullcontext(sys.stdin) if args.exprs == "-"
           else open(args.exprs))
    with src as fh:
        if args.llm:
            # raw chat output: tolerant extraction instead of fail-fast
            exprs, llm_report = _extract_llm_sources(
                fh.read(), args.exprs, known_fields=fields)
        else:
            for i, line in enumerate(fh, 1):
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                try:
                    # surface syntax/vocabulary errors with a file:line; ast
                    # raises SyntaxError, the validator ValueError
                    e = compile_alpha(line)
                except (ValueError, SyntaxError) as err:
                    raise SystemExit(f"{args.exprs}:{i}: {err}") from err
                missing = [f for f in e.fields if f not in fields]
                if missing:
                    raise SystemExit(
                        f"{args.exprs}:{i}: panel has no field(s) {missing} "
                        f"(have: {sorted(fields)})")
                exprs.append(line)
    if not exprs:
        raise SystemExit(f"{args.exprs}: no expressions")
    observed = np.isfinite(np.asarray(p.fields[args.fwd_field]))
    fwd = jnp.asarray(shift_ret_next_period(
        np.asarray(p.fields[args.fwd_field]), observed), jnp.float32)

    t0 = time.perf_counter()
    batch = compile_alpha_batch(exprs, chunk=args.chunk)
    values = batch(fields)
    summary = alpha_summary(values, fwd, spread_q=args.spread_q)
    score = pd.DataFrame(
        {k: np.asarray(v) for k, v in summary.items()},
        index=pd.Index(exprs, name="expression"),
    )
    report: dict = {
        "n_exprs": len(exprs),
        "dates": int(values.shape[1]), "stocks": int(values.shape[2]),
    }
    if llm_report is not None:
        report["llm_extraction"] = llm_report
    if args.select is not None:
        # greedy top-k under the PnL-correlation cap (alpha/select.py) —
        # ranked by |mean IC| (reusing the scorecard's own, not recomputing
        # the (E,T,N) IC); the scorecard gains selection columns and the
        # chosen expressions land in --select-out, one per line
        from mfm_tpu.alpha.select import select_alphas

        sel = select_alphas(values, fwd, args.select,
                            max_corr=args.max_corr, min_score=args.min_ic,
                            q=args.spread_q,
                            scores=np.abs(np.asarray(summary["mean_ic"])))
        score["selected"] = False
        score["select_rank"] = -1
        score["select_max_corr"] = np.nan
        for rank, (i, c) in enumerate(
                zip(sel["indices"], sel["max_corr_to_selected"])):
            score.iloc[i, score.columns.get_loc("selected")] = True
            score.iloc[i, score.columns.get_loc("select_rank")] = rank
            score.iloc[i, score.columns.get_loc("select_max_corr")] = c
        if args.select_out:
            with open(args.select_out, "w") as fh:
                fh.writelines(exprs[i] + "\n" for i in sel["indices"])
            report["select_out"] = args.select_out
        report["n_selected"] = len(sel["indices"])
        report["n_rejected_by_corr"] = len(sel["rejected"])
    if args.values_out:
        # evaluated alpha panels as a long table (trade_date, ts_code,
        # one column per expression) — the bridge back into the factor
        # pipeline: a selected alpha becomes a custom style factor.
        # Restricted to the selection when --select ran (1,000 full panels
        # would be E*T*N cells); all expressions otherwise.
        keep = (list(sel["indices"]) if args.select is not None
                else list(range(len(exprs))))
        # gather the kept slices on device BEFORE the host transfer — with
        # --select this moves k panels, not the full (E, T, N) batch
        vals = np.asarray(values[jnp.asarray(keep)]) if keep \
            else np.empty((0,) + values.shape[1:], np.float32)
        out_panel = Panel(
            dates=p.dates, stocks=p.stocks,
            fields={f"alpha_{i:04d}": vals[j] for j, i in enumerate(keep)})
        out_panel.to_long(dropna=False).to_parquet(args.values_out,
                                                   index=False)
        with open(args.values_out + ".exprs.txt", "w") as fh:
            fh.writelines(f"alpha_{i:04d}\t{exprs[i]}\n" for i in keep)
        report["values_out"] = args.values_out
    wall = time.perf_counter() - t0
    score.to_csv(args.out)
    report.update({
        "wall_s": round(wall, 3), "out": args.out,
        "best_mean_ic": float(np.nanmax(np.asarray(summary["mean_ic"]))),
    })
    print(json.dumps(report))


def _crosscheck(args):
    import pandas as pd
    from mfm_tpu.utils.crosscheck import crosscheck_factors

    def read(p):
        df = (pd.read_parquet(p) if p.endswith(".parquet")
              else pd.read_csv(p))
        # normalize the merge key regardless of the stored dtype so a CSV
        # side and a parquet side still align.  Go through str for non-
        # datetime columns: pd.to_datetime on int64 yyyymmdd (this repo's
        # native trade_date format) would read them as epoch nanoseconds.
        col = df[args.date_col]
        if not pd.api.types.is_datetime64_any_dtype(col):
            col = pd.to_datetime(col.astype(str))
        df[args.date_col] = col
        return df

    rep = crosscheck_factors(
        read(args.ours), read(args.external),
        factors=([f.strip() for f in args.factors.split(",")]
                 if args.factors else None),
        date_col=args.date_col, code_col=args.code_col,
    )
    if args.out:
        rep.to_csv(args.out)
    print(rep.to_json(orient="index"))
    if args.gate is not None:
        # CI-style agreement gate: any factor whose max |diff| over the
        # overlap exceeds the gate (or that has NO overlap at all) fails
        # the run with a named verdict on stderr.  An EMPTY comparison
        # (no shared numeric factor columns) is also a failure — a gate
        # that compared nothing must not pass
        import sys

        if not len(rep):
            print("GATE FAIL: no shared numeric factor columns to compare",
                  file=sys.stderr)
            raise SystemExit(1)
        bad = rep[(rep["n_overlap"] == 0)
                  | ~(rep["max_abs_diff"] <= args.gate)]
        if len(bad):
            for name, row in bad.iterrows():
                print(f"GATE FAIL {name}: n_overlap={int(row.n_overlap)} "
                      f"max_abs_diff={row.max_abs_diff!r} > {args.gate}",
                      file=sys.stderr)
            raise SystemExit(1)


def _require_matplotlib(flag: str):
    """Fail fast with the install hint instead of an ImportError traceback
    (shared by every plotting flag)."""
    try:
        import matplotlib  # noqa: F401
    except ImportError as err:
        raise SystemExit(f"{flag} needs matplotlib "
                         "(pip install 'mfm-tpu[plot]')") from err


def _report(args):
    """Model-health report over a risk-run results directory — the
    reference's notebook eyeballing (factor paths, R², λ, bias pictures;
    SURVEY §4) as one driver.  Writes a JSON summary and, with --plot, a
    small-multiples PNG."""
    from mfm_tpu.utils.report import (
        load_results, model_health_summary, plot_model_health,
    )

    if args.plot:
        _require_matplotlib("--plot")  # before any loading/summary work
    res = load_results(args.results)
    summary = model_health_summary(args.results, roll_window=args.roll_window,
                                   res=res)
    if args.plot:
        plot_model_health(args.results, os.path.join(args.results, args.plot),
                          top_k=args.top_k, roll_window=args.roll_window,
                          res=res)
        summary["plot"] = os.path.join(args.results, args.plot)
    if args.json:
        with open(os.path.join(args.results, args.json), "w") as fh:
            json.dump(summary, fh, indent=1)
    print(json.dumps(summary))


def _etl_xlsx(args):
    """Static-workbook ingestion: the reference ships data/index_list.xlsx
    (tushare index_basic export) and data/industry_index_data.xlsx (Wind
    EDB export of CITIC/SW L1 industry index closes) as pipeline inputs
    (SURVEY.md "Static data"); this loads them into store collections with
    the same idempotent-insert discipline as the API collections."""
    from mfm_tpu.data.etl import PanelStore
    from mfm_tpu.data.xlsx import ingest_workbooks

    counts = ingest_workbooks(
        PanelStore(args.store), index_list=args.index_list,
        industry_index=args.industry_index,
        industry_sheets=tuple(int(s) for s in args.sheets.split(",")),
    )
    print(json.dumps(counts))


def _etl_update(args):
    """Calendar-driven refresh of every collection — the reference's
    ``update_mongo_db.py:__main__`` chain (``:579-614``), against the
    parquet PanelStore with the same watermark/rate-limit/retry behavior."""
    from mfm_tpu.data.etl import IncrementalUpdater, PanelStore, RateLimiter

    if args.dry_run:
        # pre-flight plan from the store's watermarks alone: no token, no
        # API call, no rate-limit budget spent
        from mfm_tpu.data.etl import plan_update

        print(json.dumps(plan_update(
            PanelStore(args.store), args.start,
            args.end or time.strftime("%Y%m%d"),
            index_codes=[s.strip() for s in args.index_codes.split(",")],
            statements=([s.strip() for s in args.statements.split(",")]
                        if args.statements else ()),
            components_date=args.components_date,
            sw=not args.no_sw)))
        return
    from mfm_tpu.data.tushare_source import TushareSource

    up = IncrementalUpdater(
        store=PanelStore(args.store),
        source=TushareSource(token=args.token),
        limiter=RateLimiter(args.calls_per_min),
    )
    summary = up.run_all(
        args.start,
        args.end or time.strftime("%Y%m%d"),
        index_codes=[s.strip() for s in args.index_codes.split(",")],
        statements=([s.strip() for s in args.statements.split(",")]
                    if args.statements else ()),
        components_date=args.components_date,
        sw=not args.no_sw,
        sw_csv=args.sw_csv,
    )
    print(json.dumps(summary))


def _etl_verify(args):
    from mfm_tpu.data.etl import PanelStore, verify_store

    store = PanelStore(args.store)
    if args.diagnose:
        # per-stock statement QC (the reference's notebook bisection hunt
        # for bad merge groups, try_1017.ipynb cells 9-12, vectorized)
        from mfm_tpu.data.pit import diagnose_statements

        try:
            rep = diagnose_statements(store.read(args.name),
                                      by=args.code_col,
                                      ann_col=args.ann_col,
                                      end_col=args.end_col)
        except ValueError as err:
            # wrong-schema / empty / typo'd collection: a clean error, not a
            # KeyError traceback (--name defaults to daily_prices, which has
            # no announcement columns)
            raise SystemExit(f"--diagnose {args.name}: {err}") from err
        rep["collection"] = args.name
        print(json.dumps(rep))
        return
    print(json.dumps(verify_store(store, name=args.name,
                                  code_col=args.code_col,
                                  date_col=args.date_col)))


def _etl_missing(args):
    from mfm_tpu.data.etl import (
        IncrementalUpdater, PanelStore, RateLimiter, find_missing_stocks,
    )

    store = PanelStore(args.store)
    if args.fix:
        # detect AND refetch (fill_missing_data.py:16-64).  The refill
        # fetches daily_basic rows, so it only makes sense for the default
        # price collection — custom --name/--code-col would insert
        # wrong-schema rows
        if args.name != "daily_prices" or args.code_col != "ts_code":
            raise SystemExit("--fix only repairs the daily_prices "
                             "collection (it refetches daily_basic rows); "
                             "drop --name/--code-col")
        from mfm_tpu.data.tushare_source import TushareSource

        up = IncrementalUpdater(
            store=store, source=TushareSource(token=args.token),
            limiter=RateLimiter(args.calls_per_min))
        rep = up.repair_missing_stocks(
            args.start, args.end or time.strftime("%Y%m%d"),
            universe_name=args.universe)
        print(json.dumps({"n_missing": len(rep["missing"]),
                          "missing": rep["missing"],
                          "rows_inserted": rep["rows_inserted"]}))
        return
    missing = find_missing_stocks(store, universe_name=args.universe,
                                  data_name=args.name,
                                  code_col=args.code_col)
    print(json.dumps({"n_missing": len(missing), "missing": missing}))


def _doctor(args):
    """Audit a serving state directory (or one artifact): payload
    checksums, fencing generation vs ``latest.json``, and the risk-state
    field/stamp schema.  Prints one JSON record per artifact and exits
    non-zero when anything is corrupt, stale, or schema-broken — the
    pre-flight check for `risk --update` / `pipeline --append` after a
    crash or restore (docs/SERVING.md)."""
    import glob

    from mfm_tpu.data.artifacts import (
        _NW_SCALARS, _NW_STACKED, ArtifactCorruptError, ArtifactStaleError,
        _file_sha256, _stamp_from_json, load_artifact, read_pointer,
    )

    if args.path is None:
        if not (getattr(args, "audit", None)
                or getattr(args, "sync", None)):
            raise SystemExit("doctor: PATH is required unless --audit or "
                             "--sync is given (the static checks need no "
                             "serving artifacts)")
        paths = []
    elif os.path.isdir(args.path):
        paths = sorted(glob.glob(os.path.join(args.path, "*.npz")))
        if not paths:
            raise SystemExit(f"{args.path}: no .npz artifacts to audit")
    elif os.path.exists(args.path):
        paths = [args.path]
    else:
        raise SystemExit(f"{args.path}: not found")

    records, unhealthy, metas = [], 0, {}
    for p in paths:
        rec = {"file": p, "status": "ok", "problems": [], "warnings": []}
        records.append(rec)
        try:
            arrays, meta = load_artifact(p, fenced=True, force=args.force)
        except ArtifactStaleError as err:
            rec["status"] = "stale"
            rec["problems"].append(str(err))
            continue
        except ArtifactCorruptError as err:
            rec["status"] = "corrupt"
            rec["problems"].append(str(err))
            continue
        metas[os.path.basename(p)] = meta
        rec["kind"] = meta.get("kind", "raw")
        rec["arrays"] = len(arrays)
        if meta.get("sha256") is None:
            # loadable, but silent corruption would pass undetected —
            # re-running the producing stage upgrades it in place
            rec["warnings"].append("no payload checksum (legacy artifact)")
        gen = meta.get("generation")
        entry = read_pointer(p)
        if gen is not None:
            rec["generation"] = gen
        if entry is not None:
            ptr_gen = entry.get("generation")
            rec["pointer_generation"] = ptr_gen
            if isinstance(gen, int) and isinstance(ptr_gen, int) \
                    and gen < ptr_gen:
                # only reachable under --force (the fenced load refuses
                # otherwise); keep it visible
                rec["warnings"].append(
                    f"generation {gen} older than the pointer ({ptr_gen}) "
                    "— audited past the fence via --force")
            if gen == ptr_gen and isinstance(entry.get("sha256"), str) \
                    and _file_sha256(p) != entry["sha256"]:
                rec["problems"].append(
                    "file hash differs from the latest.json pointer's — "
                    "the live file changed after its pointer swap")
        if meta.get("kind") == "risk_state":
            required = (set(_NW_SCALARS) | set(_NW_STACKED)
                        | {"vr_num", "vr_den"})
            # the eigen stage's resumable form: frozen sim covariances, or
            # (eigen_incremental) the draw stream + prefix-moment carry
            if "eig_draws" in arrays:
                required |= {"eig_draws", "eig_R", "eig_p", "eig_n"}
            else:
                required |= {"sim_covs"}
            missing = sorted(required - set(arrays))
            if missing:
                rec["problems"].append(
                    f"missing state field(s) {missing}")
            guard_keys = sorted(k for k in arrays if k.startswith("guard_"))
            rec["guarded"] = len(guard_keys) == 5
            if guard_keys and len(guard_keys) != 5:
                rec["problems"].append(
                    f"partial guard state {guard_keys} — expected all "
                    "five guard_* leaves or none")
            try:
                stamp = _stamp_from_json(meta["stamp"])
                if not isinstance(stamp, tuple):
                    raise ValueError("stamp is not a tuple")
            except (KeyError, TypeError, ValueError) as err:
                rec["problems"].append(f"unusable config stamp ({err}) — "
                                       "updates would be refused")
            rec["last_date"] = meta.get("last_date")
        if rec["problems"]:
            rec["status"] = "unhealthy" if rec["status"] == "ok" \
                else rec["status"]

    # the newest run manifest, when one sits beside the artifacts: schema,
    # health field, and stamp-vs-checkpoint identity (a mismatch means the
    # directory mixes artifacts from different runs)
    man_dir = None
    if args.path is not None:
        man_dir = (args.path if os.path.isdir(args.path)
                   else os.path.dirname(args.path) or ".")
    mpath = os.path.join(man_dir, "run_manifest.json") if man_dir else ""
    if man_dir is not None and os.path.exists(mpath):
        from mfm_tpu.obs.manifest import ManifestError, read_run_manifest

        rec = {"file": mpath, "kind": "run_manifest", "status": "ok",
               "problems": [], "warnings": []}
        records.append(rec)
        try:
            man = read_run_manifest(mpath)
        except ManifestError as err:
            rec["status"] = "corrupt"
            rec["problems"].append(str(err))
        else:
            rec["health"] = man["health"].get("status")
            ckpt = man.get("checkpoint")
            meta = metas.get(ckpt)
            if ckpt and meta is None:
                rec["problems"].append(
                    f"manifest names checkpoint {ckpt!r}, which is missing "
                    "or failed its own audit")
            elif meta is not None \
                    and man.get("config_stamp") != meta.get("stamp"):
                rec["problems"].append(
                    "manifest config_stamp does not match the checkpoint's "
                    "identity stamp — artifacts from different runs in one "
                    "directory")
            if rec["health"] == "degraded":
                rec["warnings"].append(
                    "model health was degraded at manifest write time "
                    "(see manifest health.checks)")
            if rec["problems"]:
                rec["status"] = "unhealthy"

    # --serve: audit the newest serve manifest's breaker/shed counters —
    # a breaker left open at shutdown means the query service exited
    # while rejecting traffic, which is a failed serve run even if every
    # request got a well-formed response
    if getattr(args, "serve", False) and man_dir is not None:
        from mfm_tpu.obs.manifest import ManifestError, read_run_manifest

        from mfm_tpu.serve.replica import FLEET_MANIFEST_NAME

        spath = os.path.join(man_dir, SERVE_MANIFEST_NAME)
        rec = {"file": spath, "kind": "serve_manifest", "status": "ok",
               "problems": [], "warnings": []}
        records.append(rec)
        fpath = os.path.join(man_dir, FLEET_MANIFEST_NAME)
        if not os.path.exists(spath) and os.path.exists(fpath):
            # a fleet run writes ONE merged manifest — the front end's
            # serve summary lives there, not in serve_manifest.json
            spath = fpath
            rec["file"] = fpath
        if not os.path.exists(spath):
            rec["status"] = "missing"
            rec["problems"].append(
                "no serve_manifest.json beside the artifacts — has "
                "`mfm-tpu serve` run against this checkpoint dir?")
        else:
            try:
                man = read_run_manifest(spath)
            except ManifestError as err:
                rec["status"] = "corrupt"
                rec["problems"].append(str(err))
            else:
                serve = man.get("serve")
                if not isinstance(serve, dict):
                    rec["problems"].append(
                        "serve manifest has no 'serve' summary block")
                else:
                    for k in ("breaker_state", "breaker_open_total",
                              "shed_total", "shed_rate", "requests_total"):
                        rec[k] = serve.get(k)
                    if serve.get("breaker_state") == "open":
                        rec["problems"].append(
                            "circuit breaker was OPEN at shutdown — the "
                            "service exited rejecting traffic (see "
                            "serve.requests outcomes in the manifest)")
                    if serve.get("shed_rate") or serve.get("shed_total"):
                        rec["warnings"].append(
                            f"load shedding occurred (shed_total="
                            f"{serve.get('shed_total')}, shed_rate="
                            f"{serve.get('shed_rate')})")
                    cb = serve.get("cache")
                    if isinstance(cb, dict) and cb.get("delivered_total"):
                        # delivery audit: every delivered response was
                        # either computed (a recorded outcome) or a
                        # cache hit — anything else means responses
                        # were fabricated or lost around the cache
                        rec["cache_hits_total"] = cb.get("hits_total")
                        rec["cache_hit_rate"] = cb.get("hit_rate")
                        rec["cache_delivered_total"] = cb.get(
                            "delivered_total")
                        computed = serve.get("requests_total") or 0
                        expect = computed + (cb.get("hits_total") or 0)
                        if cb["delivered_total"] != expect:
                            rec["warnings"].append(
                                "response-cache delivery audit is off: "
                                f"delivered {cb['delivered_total']} != "
                                f"computed {computed} + cache hits "
                                f"{cb.get('hits_total')} — responses "
                                "bypassed or double-counted the cache "
                                "seat")
                    ckpt = man.get("checkpoint")
                    if ckpt and ckpt not in metas:
                        rec["warnings"].append(
                            f"serve manifest names checkpoint {ckpt!r}, "
                            "which is not among the audited artifacts")
                if man.get("health", {}).get("status") == "degraded":
                    rec["warnings"].append(
                        "query service ran with degraded model health "
                        "(responses were stamped degraded)")
                if not man.get("trace_id"):
                    rec["warnings"].append(
                        "serve manifest carries no root trace_id — this "
                        "run cannot be joined to its trace (pre-tracing "
                        "build, or tracing disabled)")
                # SLO burn audit: a fast-burning objective at shutdown is
                # a page-now condition doctor FAILS on; slow burn warns
                slo = (serve.get("slo") if isinstance(serve, dict)
                       else None)
                if isinstance(slo, dict):
                    rec["slo_worst_state"] = slo.get("worst_state")
                    for s in slo.get("slos", []):
                        if s.get("state") == "fast_burn":
                            rec["problems"].append(
                                f"SLO {s.get('name')!r} was FAST-BURNING "
                                f"(burn {s.get('burn_fast')} over the "
                                f"{slo.get('window_fast_s')}s window, "
                                "threshold "
                                f"{slo.get('fast_burn_threshold')}) — "
                                "the error budget was being spent at "
                                "page-now rate")
                        elif s.get("state") == "slow_burn":
                            rec["warnings"].append(
                                f"SLO {s.get('name')!r} was slow-burning "
                                f"(burn {s.get('burn_slow')} over the "
                                f"{slo.get('window_slow_s')}s window)")
                if rec["problems"]:
                    rec["status"] = "unhealthy"

        # fleet audit: when a merged fleet manifest sits beside the
        # artifacts, the per-replica delivered outcome counts plus the
        # front end's locally-answered ledger must sum to the accepted
        # count — a mismatch means responses were lost between dispatch
        # and delivery (a replica death the re-dispatch failed to cover),
        # which no per-process manifest can see on its own
        from mfm_tpu.serve.replica import FLEET_MANIFEST_NAME
        fpath = os.path.join(man_dir, FLEET_MANIFEST_NAME)
        if os.path.exists(fpath):
            frec = {"file": fpath, "kind": "fleet_manifest",
                    "status": "ok", "problems": [], "warnings": []}
            records.append(frec)
            try:
                fman = read_run_manifest(fpath)
            except ManifestError as err:
                frec["status"] = "corrupt"
                frec["problems"].append(str(err))
            else:
                fm = fman.get("fleet")
                if not isinstance(fm, dict):
                    frec["problems"].append(
                        "fleet manifest has no 'fleet' merge block")
                else:
                    audit = fm.get("audit", {})
                    frec["accepted_total"] = audit.get("accepted_total")
                    frec["replica_outcomes_sum"] = audit.get(
                        "replica_outcomes_sum")
                    frec["frontend_local_total"] = audit.get(
                        "frontend_local_total")
                    delivered = audit.get(
                        "delivered_total",
                        audit.get("replica_outcomes_sum"))
                    frec["delivered_total"] = delivered
                    if not audit.get("consistent"):
                        frec["problems"].append(
                            "delivered outcome counts (replicas + "
                            f"frontend-local = {delivered}) do not sum "
                            "to the front end's accepted count "
                            f"({audit.get('accepted_total')}) — "
                            "responses were lost between dispatch and "
                            "delivery")
                    redisp = 0
                    for rep in fm.get("replicas", []):
                        if rep.get("lost"):
                            frec["warnings"].append(
                                f"replica {rep.get('replica')} was lost "
                                f"(exit {rep.get('exit_code')}) — its "
                                "in-flight batch re-dispatched to "
                                "survivors")
                        if rep.get("wedged"):
                            frec["warnings"].append(
                                f"replica {rep.get('replica')} wedged "
                                "(deadline/heartbeat expiry with the "
                                "process still alive) — quarantined and "
                                "its in-flight batch re-dispatched")
                        elif rep.get("quarantined"):
                            frec["warnings"].append(
                                f"replica {rep.get('replica')} was "
                                "quarantined after failing its fence "
                                "audit")
                        tp = rep.get("transport")
                        if isinstance(tp, dict):
                            redisp += int(tp.get("redispatches", 0) or 0)
                            neg = sorted(
                                k for k, v in tp.items()
                                if isinstance(v, int) and v < 0)
                            if neg:
                                frec["problems"].append(
                                    f"replica {rep.get('replica')} "
                                    "transport counters went negative "
                                    f"({', '.join(neg)}) — the counter "
                                    "plumbing is corrupt")
                            if tp.get("heartbeat_misses"):
                                frec["warnings"].append(
                                    f"replica {rep.get('replica')} "
                                    f"missed {tp['heartbeat_misses']} "
                                    "heartbeat(s)")
                    tr = fm.get("transport")
                    if isinstance(tr, dict):
                        # the transport totals are part of the delivery
                        # story: every re-dispatched request must still
                        # appear in exactly one ledger (checked by
                        # `consistent` above); here the merged totals
                        # must agree with the per-replica counters
                        frec["transport"] = tr
                        if int(tr.get("redispatches", 0) or 0) != redisp:
                            frec["problems"].append(
                                "fleet transport totals disagree with "
                                f"per-replica counters (redispatches "
                                f"{tr.get('redispatches')} != "
                                f"{redisp})")
                if frec["problems"]:
                    frec["status"] = "unhealthy"

        # flight-recorder dumps: a dump beside the artifacts means the
        # run hit a postmortem trigger (breaker open, wedge quarantine,
        # fence-audit failure, SIGTERM) — doctor validates the bundle
        # parses (the faultinject kill-mid-dump plan drives the torn-file
        # case) and surfaces the trigger + triggering trace id
        import glob as _glob

        from mfm_tpu.obs.flightrec import read_flightrec
        for fr_path in sorted(_glob.glob(
                os.path.join(man_dir, "flightrec*.json"))):
            fr_rec = {"file": fr_path, "kind": "flightrec",
                      "status": "ok", "problems": [], "warnings": []}
            records.append(fr_rec)
            try:
                dump = read_flightrec(fr_path)
            except (ValueError, OSError) as err:
                fr_rec["status"] = "corrupt"
                fr_rec["problems"].append(str(err))
            else:
                fr_rec["trigger"] = dump.get("trigger")
                fr_rec["trace_id"] = dump.get("trace_id")
                fr_rec["events"] = len(dump.get("events", []))
                fr_rec["spans"] = len(dump.get("spans", []))
                fr_rec["warnings"].append(
                    "flight-recorder dump present (trigger="
                    f"{dump.get('trigger')!r}, trace_id="
                    f"{dump.get('trace_id')!r}) — the serve run hit a "
                    "postmortem trigger; inspect the bundled "
                    "events/spans/metrics")

    # --scenarios: audit the scenario manifest beside the artifacts — a
    # torn write, an embedded spec whose recomputed hash disagrees with
    # the recorded one, or inconsistent counts all mean the last stress
    # run cannot be trusted (tools/faultinject.py's scenario plans drive
    # this exact check after a mid-write SIGKILL)
    if getattr(args, "scenarios", False) and man_dir is not None:
        from mfm_tpu.scenario.manifest import (
            ScenarioManifestError, audit_scenario_manifest,
            scenario_manifest_path_for,
        )

        from mfm_tpu.scenario.sweep import (
            SweepManifestError, audit_sweep_manifest, read_sweep_manifest,
            sweep_manifest_path_for,
        )

        scpath = scenario_manifest_path_for(man_dir)
        swpath = sweep_manifest_path_for(man_dir)
        rec = {"file": scpath, "kind": "scenario_manifest", "status": "ok",
               "problems": [], "warnings": []}
        records.append(rec)
        if not os.path.exists(scpath):
            if os.path.exists(swpath):
                # a sweep ran here but no preset drill did — fine for the
                # artifacts, just worth flagging
                rec["warnings"].append(
                    "no scenario_manifest.json beside the artifacts "
                    "(only a sweep manifest) — run `mfm-tpu scenario "
                    "run` for the preset drill record")
            else:
                rec["status"] = "missing"
                rec["problems"].append(
                    "no scenario_manifest.json beside the artifacts — has "
                    "`mfm-tpu scenario run` run against this checkpoint "
                    "dir?")
        else:
            try:
                problems, warnings = audit_scenario_manifest(scpath)
            except ScenarioManifestError as err:
                rec["status"] = "corrupt"
                rec["problems"].append(str(err))
            else:
                rec["problems"].extend(problems)
                rec["warnings"].extend(warnings)
                from mfm_tpu.scenario.manifest import read_scenario_manifest

                summary = read_scenario_manifest(scpath).get("summary") or {}
                if not summary.get("trace_id"):
                    rec["warnings"].append(
                        "scenario manifest carries no root trace_id — "
                        "this run cannot be joined to its trace "
                        "(pre-tracing build, or tracing disabled)")
                if rec["problems"]:
                    rec["status"] = "unhealthy"
        # sweep manifests are optional — audit one only when present, so
        # checkpoints that never ran a sweep stay green
        if os.path.exists(swpath):
            swrec = {"file": swpath, "kind": "sweep_manifest",
                     "status": "ok", "problems": [], "warnings": []}
            records.append(swrec)
            try:
                problems, warnings = audit_sweep_manifest(swpath)
            except SweepManifestError as err:
                swrec["status"] = "corrupt"
                swrec["problems"].append(str(err))
            else:
                swrec["problems"].extend(problems)
                swrec["warnings"].extend(warnings)
                summary = read_sweep_manifest(swpath).get("summary") or {}
                if not summary.get("trace_id"):
                    swrec["warnings"].append(
                        "sweep manifest carries no root trace_id — "
                        "this run cannot be joined to its trace "
                        "(pre-tracing build, or tracing disabled)")
                if swrec["problems"]:
                    swrec["status"] = "unhealthy"
    # --audit: verify the committed static-audit snapshot (AUDIT_r*.json)
    # — torn writes, broken seals, non-clean runs, and staleness against
    # the live registry/budget file all fail, same contract as the
    # artifact records above
    if getattr(args, "audit", None):
        from mfm_tpu.analysis.run import latest_snapshot_path, verify_snapshot

        apath = args.audit
        if apath == "latest":
            apath = latest_snapshot_path()
        rec = {"file": apath, "kind": "audit_snapshot", "status": "ok",
               "problems": [], "warnings": []}
        records.append(rec)
        if apath is None:
            rec["file"] = "AUDIT_r*.json"
            rec["status"] = "missing"
            rec["problems"].append(
                "no committed AUDIT_r*.json snapshot — run "
                "`mfm-tpu audit --json AUDIT_r01.json` and commit it")
        else:
            problems, warns, doc = verify_snapshot(apath)
            rec["problems"].extend(problems)
            rec["warnings"].extend(warns)
            if doc is None:
                rec["status"] = "corrupt"
            else:
                if isinstance(doc, dict):
                    rec["strict_clean"] = doc.get("strict_clean")
                    rec["summary"] = doc.get("summary")
                if rec["problems"]:
                    rec["status"] = "unhealthy"
    # --sync: run the lock-discipline pass strict against its committed
    # baseline — new findings are problems, a stale baseline is a
    # problem too (the justified exception no longer exists), baselined
    # findings are warnings so the operator sees what is being excused
    if getattr(args, "sync", None):
        from mfm_tpu.analysis.sync import (
            DEFAULT_BASELINE as _SYNC_BASELINE, REPO_ROOT as _SYNC_ROOT,
            load_baseline as _load_sync_baseline, run_sync,
        )

        bpath = os.path.join(_SYNC_ROOT, _SYNC_BASELINE)
        rec = {"file": bpath, "kind": "sync_analysis", "status": "ok",
               "problems": [], "warnings": []}
        records.append(rec)
        res = run_sync(baseline=_load_sync_baseline(bpath))
        for v in res.new:
            rec["problems"].append(
                f"{v.file}:{v.line}: {v.rule} [{v.qualname}] {v.message}")
        for b in res.stale:
            rec["problems"].append(
                f"stale baseline entry: {b['file']} {b['rule']} "
                f"[{b['qualname']}] — the finding no longer exists")
        for v in res.baselined:
            rec["warnings"].append(
                f"baselined: {v.file} {v.rule} [{v.qualname}]")
        rec["baselined"] = len(res.baselined)
        if rec["problems"]:
            rec["status"] = "unhealthy"
    unhealthy = sum(r["status"] != "ok" for r in records)
    print(json.dumps({"audited": len(records), "unhealthy": unhealthy,
                      "records": records}, indent=1))
    raise SystemExit(1 if unhealthy else 0)


SERVE_MANIFEST_NAME = "serve_manifest.json"


def _serve(args):
    """Batched portfolio-query service over a guarded risk-state checkpoint:
    JSONL requests in (stdin or --input), JSONL responses out, with request
    guards + dead-letter quarantine, bounded-queue admission control with
    oldest-first load shedding, per-request deadlines, degraded-serving
    stamps (staleness + health verdict), and a circuit breaker
    (docs/SERVING.md §"Query service").  At shutdown the serve summary
    (QPS/latency/shed/breaker counters) is written to a
    ``serve_manifest.json`` beside the checkpoint, which
    ``mfm-tpu doctor --serve`` audits."""
    import sys

    from mfm_tpu.data.artifacts import (
        ArtifactCorruptError, ArtifactStaleError, load_risk_state,
        read_pointer,
    )
    from mfm_tpu.data.etl import with_retry
    from mfm_tpu.obs.instrument import guard_summary_from_registry
    from mfm_tpu.obs.manifest import (
        ManifestError, build_run_manifest, manifest_path_for,
        read_run_manifest, write_run_manifest,
    )
    from mfm_tpu.obs.metrics import REGISTRY
    from mfm_tpu.obs.trace import end_span
    from mfm_tpu.serve.cache import ResponseCache, WarmStartIndex
    from mfm_tpu.serve.query import QueryEngine
    from mfm_tpu.serve.server import QueryServer, ServePolicy

    _metrics_init(args)
    root = _root_span(args)
    state_path = args.state

    def _dead_letter_startup(rec: dict) -> None:
        if not args.dead_letter:
            return
        rec = dict(rec)
        rec.setdefault("kind", "startup_failure")
        with open(args.dead_letter, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(rec, sort_keys=True) + "\n")

    try:
        state, meta = with_retry(lambda: load_risk_state(state_path),
                                 attempts=args.load_attempts,
                                 backoff_s=args.load_backoff_s,
                                 retryable=(OSError,))
    except (ArtifactCorruptError, ArtifactStaleError) as e:
        # fence audit failed before the loop even started: nothing to
        # serve degraded FROM, so refuse outright (post-crash triage is
        # `mfm-tpu doctor`)
        raise SystemExit(f"serve: checkpoint failed its fence audit: {e}")
    except OSError as e:
        # the retry history rides into the dead letter so the operator can
        # tell "failed instantly" from "fought the outage"
        _dead_letter_startup({
            "path": state_path, "error": str(e),
            "attempts": getattr(e, "attempts", 1),
            "total_backoff_s": round(getattr(e, "total_backoff_s", 0.0), 3)})
        raise SystemExit(f"serve: cannot load {state_path}: {e}")

    benchmarks = None
    if args.benchmarks:
        with open(args.benchmarks, encoding="utf-8") as fh:
            benchmarks = {str(k): v for k, v in json.load(fh).items()}

    def _health_beside() -> str:
        mpath = manifest_path_for(state_path)
        if not os.path.exists(mpath):
            return "unknown"
        try:
            return read_run_manifest(mpath)["health"].get("status",
                                                          "unknown")
        except ManifestError:
            return "unknown"

    try:
        engine = QueryEngine.from_risk_state(state, meta,
                                             benchmarks=benchmarks)
    except ValueError as e:
        raise SystemExit(f"serve: {e}")

    policy = ServePolicy(
        queue_max=args.queue_max, batch_max=args.batch_max,
        default_deadline_s=args.deadline_s,
        breaker_failures=args.breaker_failures,
        breaker_cooldown_s=args.breaker_cooldown_s,
        weight_mad_k=args.weight_mad_k,
        fsync_emits=args.fsync_emits)

    def _scenario_hashes_beside() -> dict | None:
        # the cache fences scenario-tagged requests on the served spec
        # hash; absent manifest -> name-keyed fallback inside the cache
        from mfm_tpu.scenario.manifest import (
            ScenarioManifestError, read_scenario_manifest,
            scenario_manifest_path_for,
        )
        try:
            m = read_scenario_manifest(scenario_manifest_path_for(
                os.path.dirname(state_path) or "."))
        except (ScenarioManifestError, OSError):
            return None
        return {str(e.get("name")): str(e.get("spec_hash"))
                for e in m.get("scenarios", []) if e.get("spec_hash")}

    cache = None
    if not (args.no_cache or getattr(args, "worker", False)):
        cache = ResponseCache(
            args.cache_entries, args.cache_bytes,
            generation=int((meta or {}).get("generation") or 0),
            scenario_hashes=_scenario_hashes_beside())
    warm_index = (WarmStartIndex(tol=args.warm_tol)
                  if args.warm_tol > 0 else None)

    reload_fn = None
    if args.watch or args.rollout or args.hold_fence:
        # --rollout implies watching: the frontend needs the reload hook
        # to move its admission engine + cache fence once the fleet
        # agrees.  --hold-fence implies it too — that flag's one job is
        # "re-fence on the frontend's reload frame", which is this hook;
        # without it a TCP worker would answer every reload frame with
        # its startup generation and the fleet could never agree
        seen = {"gen": (read_pointer(state_path) or {}).get("generation")}

        def reload_fn():
            gen = (read_pointer(state_path) or {}).get("generation")
            if gen == seen["gen"]:
                return None
            # fence-audit failures propagate (the server force-opens the
            # breaker); transient IO keeps the old engine serving
            try:
                st, mt = with_retry(lambda: load_risk_state(state_path),
                                    attempts=2, backoff_s=0.05,
                                    retryable=(OSError,))
            except OSError as e:
                print(f"serve: reload failed after "
                      f"{getattr(e, 'attempts', 1)} attempts "
                      f"({getattr(e, 'total_backoff_s', 0.0):.3f}s backoff)"
                      f": {e} — still serving the previous engine",
                      file=sys.stderr)
                return None
            seen["gen"] = gen
            if cache is not None:
                # bump the fence BEFORE the engine swap lands: stale
                # entries become unreachable, no sweep needed
                cache.set_fence(
                    generation=int(gen or 0),
                    scenario_hashes=_scenario_hashes_beside())
            return {"engine": QueryEngine.from_risk_state(
                        st, mt, benchmarks=benchmarks),
                    "health": _health_beside(),
                    "generation": int(gen or 0)}

    server = QueryServer(engine, policy, health=_health_beside(),
                         dead_letter_path=args.dead_letter,
                         reload_fn=reload_fn, warm_index=warm_index)
    # generation stamp for the rolling-rollout agreement protocol (a
    # worker reports it in its "reloaded" frame)
    server.generation = int((meta or {}).get("generation") or 0)
    man_dir = os.path.dirname(state_path) or "."

    # SLO engine + flight recorder: every serve process evaluates its own
    # burn rates at scrape time (the block rides serve summaries into
    # /healthz, the manifests and doctor --serve), and triggered
    # postmortem dumps land beside the checkpoint — workers get
    # per-replica shard names so a fleet on one host never races the
    # frontend's dump
    from mfm_tpu.obs import flightrec as _frec
    from mfm_tpu.obs import slo as _slo
    _slo.install(_slo.SloEngine())
    frec_name = (f"flightrec.r{args.worker_id}.json" if args.worker
                 else _frec.FLIGHTREC_NAME)
    _frec.arm(os.path.join(man_dir, frec_name))

    def _finish(summary: dict, manifest_name: str, extra: dict) -> None:
        manifest = build_run_manifest(
            stamp_json=meta.get("stamp"),
            checkpoint=state_path,
            backend=jax_backend_name(),
            metrics_snapshot=REGISTRY.snapshot(),
            guard_summary=guard_summary_from_registry(),
            health={"status": server.health, "checks": {}},
            extra=dict(extra, serve=summary, trace_id=root.trace_id),
        )
        spath = os.path.join(man_dir, manifest_name)
        write_run_manifest(spath, manifest)
        end_span(root)
        _metrics_flush(args)
        print(json.dumps({"serve": summary, "manifest": spath,
                          "trace_id": root.trace_id},
                         indent=1), file=sys.stderr)

    if args.worker:
        # fleet worker: admitted lines in, seq envelopes out (the wire
        # protocol in serve/replica.py); manifest shard beside the
        # checkpoint for the front end's merge.  With --listen the same
        # loop runs over ONE accepted TCP connection instead of stdin —
        # the multi-host worker a remote frontend attaches to with
        # --workers host:port (docs/SERVING.md §10)
        from mfm_tpu.serve.replica import WORKER_MANIFEST_FMT, run_worker

        if args.listen:
            from mfm_tpu.serve.transport import serve_worker_socket

            host, _, port = args.listen.rpartition(":")

            def announce(addr):
                print(json.dumps({
                    "worker_listening": f"{addr[0]}:{addr[1]}",
                    "worker_id": args.worker_id}),
                    file=sys.stderr, flush=True)

            summary = serve_worker_socket(
                server, host or "127.0.0.1", int(port or 0),
                announce=announce, poll_on_flush=not args.hold_fence)
        else:
            summary = run_worker(server, sys.stdin, sys.stdout,
                                 poll_on_flush=not args.hold_fence)
        _finish(summary, WORKER_MANIFEST_FMT.format(idx=args.worker_id),
                {"worker_id": args.worker_id})
        return

    if args.replicas or args.listen or args.workers:
        _serve_fleet(args, server, state_path, man_dir, _finish,
                     cache=cache)
        return

    in_fp = (sys.stdin if args.input in (None, "-")
             else open(args.input, encoding="utf-8"))
    out_fp = (sys.stdout if args.output in (None, "-")
              else open(args.output, "w", encoding="utf-8"))
    try:
        summary = server.run(in_fp, out_fp, gulp=args.gulp, cache=cache)
    finally:
        if in_fp is not sys.stdin:
            in_fp.close()
        if out_fp is not sys.stdout:
            out_fp.close()
    _finish(summary, SERVE_MANIFEST_NAME, {})


def _serve_fleet(args, server, state_path, man_dir, _finish,
                 cache=None) -> None:
    """The fleet/coalescing serve paths: ``--replicas N`` dispatches
    batches to spawned worker subprocesses, ``--workers host:port,...``
    attaches to already-running TCP workers on any host (both may mix),
    ``--listen`` accepts concurrent socket (or ``--http``) connections;
    each alone also works — ``--replicas`` over stdin is the
    deterministic drill mode, and ``--listen`` without workers coalesces
    into the local engine."""
    import signal
    import sys

    from mfm_tpu.data.artifacts import read_pointer
    from mfm_tpu.obs.instrument import fleet_summary_from_registry
    from mfm_tpu.serve.coalesce import Coalescer
    from mfm_tpu.serve.frontend import SocketFrontend
    from mfm_tpu.serve.replica import (
        FLEET_MANIFEST_NAME, FleetServer, Replica, build_fleet_manifest,
        replica_env, worker_cmd,
    )

    fleet = None
    replicas = []
    if args.replicas:
        policy_args = [
            "--queue-max", str(args.queue_max),
            "--batch-max", str(args.batch_max),
            "--deadline-s", str(args.deadline_s),
            "--breaker-failures", str(args.breaker_failures),
            "--breaker-cooldown-s", str(args.breaker_cooldown_s),
            "--weight-mad-k", str(args.weight_mad_k),
            "--warm-tol", str(args.warm_tol)]
        if args.benchmarks:
            policy_args += ["--benchmarks", args.benchmarks]
        if args.watch or args.rollout:
            policy_args += ["--watch"]
        if args.rollout:
            # rollout workers must NOT self-poll: generations move one
            # worker at a time on the frontend's reload frames
            policy_args += ["--hold-fence"]
        if args.fsync_emits:
            policy_args += ["--fsync-emits"]
        replicas = [
            Replica(i, worker_cmd(state_path, worker_id=i,
                                  policy_args=policy_args),
                    env=replica_env(i),
                    io_timeout_s=args.worker_timeout_s)
            for i in range(args.replicas)]
    if args.workers:
        base = len(replicas)
        for j, spec in enumerate(p.strip() for p in args.workers.split(",")
                                 if p.strip()):
            whost, _, wport = spec.rpartition(":")
            try:
                replicas.append(Replica.connect(
                    base + j, (whost or "127.0.0.1", int(wport)),
                    io_timeout_s=args.worker_timeout_s,
                    attempts=args.connect_attempts,
                    backoff_s=args.connect_backoff_s))
            except OSError as e:
                raise SystemExit(
                    f"serve: cannot attach worker {spec}: {e} "
                    f"(phase={getattr(e, 'phase', 'connect')}, "
                    f"attempts={getattr(e, 'attempts', 1)}, "
                    f"backoff={getattr(e, 'total_backoff_s', 0.0):.3f}s)")

    def make_backend(deliver=None):
        if replicas:
            rollout_check = None
            if args.rollout:
                def rollout_check():
                    return (read_pointer(state_path)
                            or {}).get("generation")
            return FleetServer(server, replicas, linger_s=args.linger_s,
                               deliver=deliver, cache=cache,
                               heartbeat_s=args.heartbeat_s,
                               heartbeat_timeout_s=args.heartbeat_timeout_s,
                               rollout_check=rollout_check)
        return Coalescer(server, linger_s=args.linger_s, deliver=deliver,
                         cache=cache)

    if args.listen:
        host, _, port = args.listen.rpartition(":")
        fe = SocketFrontend(host or "127.0.0.1", int(port or 0),
                            http=args.http)
        backend = make_backend(deliver=fe.deliver)
        fe.backend = backend
        fleet = backend if replicas else None
        addr = fe.listen()
        print(json.dumps({"listening": f"{addr[0]}:{addr[1]}",
                          "replicas": len(replicas),
                          "http": bool(args.http)}),
              file=sys.stderr, flush=True)
        def _on_term(*_):
            # the operator's kill is a postmortem trigger too: dump the
            # flight recorder BEFORE the drain so the bundle shows what
            # was in flight when the signal landed
            from mfm_tpu.obs import flightrec as _frec
            state = (backend._flightrec_state()
                     if hasattr(backend, "_flightrec_state") else None)
            _frec.trigger_dump("sigterm", state=state)
            fe.stop()

        for sig in (signal.SIGINT, signal.SIGTERM):
            signal.signal(sig, _on_term)
        fe.serve(backend)   # blocks until stop(); drains the backend
    else:
        backend = make_backend()
        fleet = backend if replicas else None
        in_fp = (sys.stdin if args.input in (None, "-")
                 else open(args.input, encoding="utf-8"))
        out_fp = (sys.stdout if args.output in (None, "-")
                  else open(args.output, "w", encoding="utf-8"))

        def emit(pairs):
            for _origin, resp in pairs:
                out_fp.write(json.dumps(resp, sort_keys=True) + "\n")
            if pairs:
                out_fp.flush()
                if server.policy.fsync_emits:
                    try:
                        os.fsync(out_fp.fileno())
                    except (OSError, ValueError):
                        pass
        try:
            for line in in_fp:
                line = line.strip()
                if not line:
                    continue
                emit(backend.submit(line))
            emit(backend.stop())
        finally:
            if in_fp is not sys.stdin:
                in_fp.close()
            if out_fp is not sys.stdout:
                out_fp.close()

    summary = fleet_summary_from_registry()
    if fleet is not None:
        fleet.close_replicas()
        fm = build_fleet_manifest(summary, fleet, man_dir)
        _finish(summary, FLEET_MANIFEST_NAME, {"fleet": fm})
    else:
        _finish(summary, SERVE_MANIFEST_NAME, {})


def _scenario(args):
    """Batched stress tests over a guarded risk-state checkpoint: factor
    shocks, vol-regime multipliers, correlation stress, historical replay
    and quarantine counterfactuals, all padded into ONE donated jit per
    S-bucket (docs/SCENARIOS.md).  ``run`` writes an atomic
    ``scenario_manifest.json`` beside the checkpoint, which
    ``mfm-tpu doctor --scenarios`` audits; ``list`` prints the preset
    catalog."""
    import sys

    from mfm_tpu.scenario import (
        PRESET_NOTES, PRESETS, ScenarioEngine, ScenarioSpec,
        build_scenario_manifest, preset, write_scenario_manifest,
    )

    if args.scmd == "list":
        catalog = [{"name": n, "note": PRESET_NOTES.get(n, ""),
                    "kinds": list(PRESETS[n].kinds),
                    "spec": PRESETS[n].to_dict()}
                   for n in sorted(PRESETS)]
        print(json.dumps({"presets": catalog}, indent=1))
        return
    if args.scmd == "sweep":
        _scenario_sweep(args)
        return

    from mfm_tpu.data.artifacts import (
        ArtifactCorruptError, ArtifactStaleError, load_risk_state,
    )
    from mfm_tpu.obs.instrument import scenario_summary_from_registry
    from mfm_tpu.obs.trace import end_span

    _metrics_init(args)
    root = _root_span(args)
    try:
        state, meta = load_risk_state(args.state)
    except (ArtifactCorruptError, ArtifactStaleError) as e:
        # same refusal as `serve`: a checkpoint past its fence audit is
        # not a world worth stressing (post-crash triage is `doctor`)
        raise SystemExit(f"scenario: checkpoint failed its fence audit: {e}")
    except OSError as e:
        raise SystemExit(f"scenario: cannot load {args.state}: {e}")

    specs = []
    try:
        for name in args.preset:
            specs.append(preset(name))
    except KeyError as e:
        raise SystemExit(f"scenario: {e.args[0]}")
    for path in args.spec:
        try:
            with open(path, encoding="utf-8") as fh:
                obj = json.load(fh)
        except (OSError, ValueError) as e:
            raise SystemExit(f"scenario: cannot read spec file {path}: {e}")
        try:
            for d in (obj if isinstance(obj, list) else [obj]):
                specs.append(ScenarioSpec.from_dict(d))
        except (TypeError, ValueError, KeyError) as e:
            raise SystemExit(f"scenario: bad spec in {path}: {e}")
    if not specs:
        raise SystemExit("scenario run: no scenarios given — use --preset "
                         "and/or --spec (`mfm-tpu scenario list` shows the "
                         "catalog)")

    try:
        engine = ScenarioEngine.from_risk_state(state, meta)
        results = engine.run(specs, bucket=args.bucket)
    except ValueError as e:
        raise SystemExit(f"scenario: {e}")

    out_dir = args.out or (os.path.dirname(args.state) or ".")
    # a fresh --out must exist as a DIRECTORY before the manifest write:
    # write_scenario_manifest treats a non-dir path as the file itself
    os.makedirs(out_dir, exist_ok=True)
    # the root trace id rides in the summary block — the ONE volatile
    # manifest field — so the bitwise-replay contract
    # (faultinject's _manifest_modulo_summary) is untouched
    summary = scenario_summary_from_registry()
    summary["trace_id"] = root.trace_id
    manifest = build_scenario_manifest(
        results, engine.factor_names, stamp_json=meta.get("stamp"),
        backend=jax_backend_name(),
        summary=summary,
        staleness=engine.staleness)
    mpath = write_scenario_manifest(out_dir, manifest)
    for r in results:
        line = {"scenario": r.spec.name, "status": r.status,
                "problems": list(r.problems),
                "psd_projected": bool(r.psd_projected)}
        if r.ok:
            line["min_eig_stressed"] = float(r.min_eig_stressed)
        print(json.dumps(line, sort_keys=True))
    end_span(root)
    _metrics_flush(args)
    print(json.dumps({"manifest": mpath, "n_scenarios": len(results),
                      "n_ok": manifest["n_ok"],
                      "n_rejected": manifest["n_rejected"],
                      "n_psd_projected": manifest["n_psd_projected"],
                      "trace_id": root.trace_id},
                     indent=1), file=sys.stderr)
    if manifest["n_ok"] == 0:
        raise SystemExit(1)


def _scenario_sweep(args):
    """Streaming million-scenario sweep over a guarded checkpoint
    (scenario/sweep.py): a sampler generates shock lanes host-side,
    chunks stream through the donated aggregate carry, the coarse top-k
    seeds a reverse-stress refinement, and the fixed-size answer lands
    in an atomic ``sweep_manifest.json`` audited by ``doctor
    --scenarios``."""
    import sys

    import numpy as np

    from mfm_tpu.data.artifacts import (
        ArtifactCorruptError, ArtifactStaleError, load_risk_state,
    )
    from mfm_tpu.grad.engine import ShockBall
    from mfm_tpu.obs.instrument import sweep_summary_from_registry
    from mfm_tpu.obs.trace import end_span
    from mfm_tpu.scenario import (
        GridSampler, SobolSampler, SweepEngine, UniformSampler,
        build_sweep_manifest, write_sweep_manifest,
    )

    _metrics_init(args)
    root = _root_span(args)
    try:
        state, meta = load_risk_state(args.state)
    except (ArtifactCorruptError, ArtifactStaleError) as e:
        # same refusal as `serve` / `scenario run`: a checkpoint past its
        # fence audit is not a world worth sweeping
        raise SystemExit(f"scenario: checkpoint failed its fence audit: {e}")
    except OSError as e:
        raise SystemExit(f"scenario: cannot load {args.state}: {e}")

    try:
        engine = SweepEngine.from_risk_state(state, meta)
    except ValueError as e:
        raise SystemExit(f"scenario: {e}")
    W = _grad_portfolios(args, engine)

    if not (args.n >= 1):
        raise SystemExit("scenario sweep: --n must be >= 1")
    ball = ShockBall(shift_max=args.shift_max,
                     scale_range=args.scale_range,
                     vol_mult_hi=args.vol_mult_max,
                     corr_beta_hi=args.corr_beta_max)
    try:
        if args.sampler == "grid":
            side = max(int(np.sqrt(args.n)), 1)
            sampler = GridSampler(ball, engine.K, n_vol=side, n_corr=side)
        elif args.sampler == "sobol":
            sampler = SobolSampler(ball, engine.K, args.n, seed=args.seed)
        else:
            sampler = UniformSampler(ball, engine.K, args.n,
                                     seed=args.seed)
        refine = None if args.no_refine else {"seed": args.seed}
        result = engine.sweep(W, sampler, chunk=args.chunk,
                              top_k=args.top_k, bins=args.bins,
                              hist_span=args.hist_span, ball=ball,
                              refine=refine)
    except ValueError as e:
        raise SystemExit(f"scenario sweep: {e}")
    dominance = engine.preset_dominance(result, W)

    out_dir = args.out or (os.path.dirname(args.state) or ".")
    os.makedirs(out_dir, exist_ok=True)
    # trace id rides in the summary block — the ONE volatile manifest
    # field — so seeded re-runs stay byte-equal modulo summary (the
    # sweep-kill-mid-stream replay contract)
    summary = sweep_summary_from_registry()
    summary["trace_id"] = root.trace_id
    manifest = build_sweep_manifest(
        result, stamp_json=meta.get("stamp"), backend=jax_backend_name(),
        staleness=engine.staleness, dominance=dominance, summary=summary)
    mpath = write_sweep_manifest(out_dir, manifest)
    for book, dom in zip(result.books, dominance):
        top = book["top"][0] if book["top"] else None
        line = {"book": book["label"], "vol_base": book["vol_base"],
                "vol_worst": top["vol"] if top else None,
                "worst_spec_hash": top["spec_hash"] if top else None,
                "dominates_presets": dom["dominates_all"]}
        print(json.dumps(line, sort_keys=True))
    end_span(root)
    _metrics_flush(args)
    print(json.dumps({"manifest": mpath, "counts": result.counts,
                      "sampler": result.sampler,
                      "trace_id": root.trace_id},
                     indent=1), file=sys.stderr)
    if result.counts["n_ok"] == 0:
        raise SystemExit(1)


def _grad_portfolios(args, engine):
    """(P, K) portfolio rows off ``--portfolio`` JSON — one K-vector, a
    list of them, or factor-name-keyed dicts; default is ONE equal-weight
    portfolio over the engine's factors."""
    import numpy as np

    path = getattr(args, "portfolio", None)
    if path is None:
        return np.full((1, engine.K), 1.0 / engine.K)
    try:
        with open(path, encoding="utf-8") as fh:
            obj = json.load(fh)
    except (OSError, ValueError) as e:
        raise SystemExit(f"grad: cannot read portfolio file {path}: {e}")
    rows = obj if isinstance(obj, list) else [obj]
    if rows and isinstance(rows[0], (int, float)):
        rows = [rows]
    W = np.zeros((len(rows), engine.K))
    for i, row in enumerate(rows):
        if isinstance(row, dict):
            for k, v in row.items():
                if str(k) not in engine.factor_index:
                    raise SystemExit(f"grad: portfolio row {i} names "
                                     f"unknown factor {k!r}")
                try:
                    W[i, engine.factor_index[str(k)]] = float(v)
                except (TypeError, ValueError) as e:
                    raise SystemExit(f"grad: bad weight in row {i}: {e}")
        else:
            try:
                r = np.asarray(row, np.float64)
            except (TypeError, ValueError) as e:
                raise SystemExit(f"grad: bad portfolio row {i}: {e}")
            if r.shape != (engine.K,):
                raise SystemExit(f"grad: portfolio row {i} is {r.shape}, "
                                 f"need ({engine.K},)")
            W[i] = r
    if not np.isfinite(W).all():
        raise SystemExit(f"grad: non-finite weights in {path}")
    return W


def _grad_specs(args):
    """Scenario specs for `grad sensitivity` off --preset/--spec (the
    `scenario run` assembly); default: identity + the preset catalog."""
    from mfm_tpu.scenario import PRESETS, ScenarioSpec, preset

    specs = []
    try:
        for name in args.preset:
            specs.append(preset(name))
    except KeyError as e:
        raise SystemExit(f"grad: {e.args[0]}")
    for path in args.spec:
        try:
            with open(path, encoding="utf-8") as fh:
                obj = json.load(fh)
        except (OSError, ValueError) as e:
            raise SystemExit(f"grad: cannot read spec file {path}: {e}")
        try:
            for d in (obj if isinstance(obj, list) else [obj]):
                specs.append(ScenarioSpec.from_dict(d))
        except (TypeError, ValueError, KeyError) as e:
            raise SystemExit(f"grad: bad spec in {path}: {e}")
    if not specs:
        specs = [ScenarioSpec.identity()] + [PRESETS[n]
                                             for n in sorted(PRESETS)]
    return specs


def _grad(args):
    """Differentiable risk over a guarded checkpoint
    (docs/DIFFERENTIABLE.md): ``reverse`` finds the worst admissible
    shock per portfolio, ``sensitivity`` stamps exact ∂vol/∂shock +
    ∂vol/∂exposure rows into the scenario manifest, ``construct`` runs
    the min-vol / risk-parity / hedge solvers.  Every subcommand writes
    an atomic ``grad_report.json`` beside the checkpoint."""
    import sys

    import numpy as np

    from mfm_tpu.data.artifacts import (
        ArtifactCorruptError, ArtifactStaleError, load_risk_state,
    )
    from mfm_tpu.grad import GradEngine, ShockBall, write_grad_report
    from mfm_tpu.grad.report import build_grad_report
    from mfm_tpu.obs.trace import end_span

    _metrics_init(args)
    root = _root_span(args)
    try:
        state, meta = load_risk_state(args.state)
    except (ArtifactCorruptError, ArtifactStaleError) as e:
        # same refusal as `serve` / `scenario`: a checkpoint past its
        # fence audit is not a world worth differentiating
        raise SystemExit(f"grad: checkpoint failed its fence audit: {e}")
    except OSError as e:
        raise SystemExit(f"grad: cannot load {args.state}: {e}")
    try:
        engine = GradEngine.from_risk_state(state, meta)
    except ValueError as e:
        raise SystemExit(f"grad: {e}")
    W = _grad_portfolios(args, engine)
    out_dir = args.out or (os.path.dirname(args.state) or ".")
    os.makedirs(out_dir, exist_ok=True)

    if args.gcmd == "reverse":
        from mfm_tpu.grad.engine import REVERSE_STEPS

        ball = ShockBall(vol_mult_hi=args.vol_mult_max,
                         corr_beta_hi=args.corr_beta_max)
        steps = REVERSE_STEPS if args.steps is None else args.steps
        try:
            entries = engine.reverse_stress(W, ball=ball, steps=steps,
                                            bucket=args.bucket)
        except ValueError as e:
            raise SystemExit(f"grad: {e}")
        kind = "reverse_stress"
        params = {"ball": ball.to_dict(), "steps": int(steps)}
        failed = sum(1 for e in entries if not e["admissible"])
    elif args.gcmd == "sensitivity":
        specs = _grad_specs(args)
        try:
            entries = engine.sensitivities(specs, W[0], bucket=args.bucket)
        except ValueError as e:
            raise SystemExit(f"grad: {e}")
        # stamp the rows into the scenario manifest too: the forward
        # batch runs first (same specs, same bucket discipline) and each
        # ok entry gains a "sensitivity" block — one file answers both
        # "what happened" and "how fast it changes"
        from mfm_tpu.obs.instrument import scenario_summary_from_registry
        from mfm_tpu.scenario import (
            ScenarioEngine, build_scenario_manifest, write_scenario_manifest,
        )
        scen = ScenarioEngine.from_risk_state(state, meta)
        try:
            results = scen.run(specs, bucket=args.bucket)
        except ValueError as e:
            raise SystemExit(f"grad: {e}")
        summary = scenario_summary_from_registry()
        summary["trace_id"] = root.trace_id
        manifest = build_scenario_manifest(
            results, scen.factor_names, stamp_json=meta.get("stamp"),
            backend=jax_backend_name(), summary=summary,
            staleness=scen.staleness,
            sensitivities={e["name"]: e for e in entries})
        write_scenario_manifest(out_dir, manifest)
        kind = "sensitivity"
        params = {"portfolio": np.asarray(W[0], np.float64).tolist()}
        failed = sum(1 for e in entries if e["status"] != "ok")
    else:
        try:
            res = engine.construct_solve(args.solver, W,
                                         bucket=args.bucket)
        except ValueError as e:
            raise SystemExit(f"grad: {e}")
        entries = []
        for i in range(W.shape[0]):
            diag = np.asarray(res["diag"][i])
            entries.append({
                "label": f"p{i}",
                "solver": args.solver,
                "weights": {str(n): float(v) for n, v in
                            zip(engine.factor_names, res["weights"][i])},
                "total_vol": float(res["vols"][i]),
                "diag": diag.tolist() if diag.ndim else float(diag),
            })
        kind = "construct"
        params = {"solver": args.solver}
        failed = 0

    report = build_grad_report(kind, entries, stamp_json=meta.get("stamp"),
                               backend=jax_backend_name(),
                               staleness=engine.staleness, params=params)
    rpath = write_grad_report(out_dir, report)
    for e in entries:
        print(json.dumps(e, sort_keys=True, default=str))
    end_span(root)
    _metrics_flush(args)
    print(json.dumps({"report": rpath, "grad_kind": kind,
                      "n_entries": len(entries), "n_failed": failed,
                      "trace_id": root.trace_id},
                     indent=1), file=sys.stderr)
    if entries and failed == len(entries):
        raise SystemExit(1)


def jax_backend_name() -> str:
    import jax

    return jax.devices()[0].platform


def _metrics_paths(path: str, filename: str) -> str:
    """Resolve a metrics artifact: PATH itself when it's a file, else
    PATH/<filename>."""
    p = os.path.join(path, filename) if os.path.isdir(path) else path
    if not os.path.exists(p):
        raise SystemExit(f"{p}: not found — run with --metrics-dir first")
    return p


def _load_metrics_snapshot(path: str) -> dict:
    p = _metrics_paths(path, "metrics.json")
    try:
        with open(p, encoding="utf-8") as fh:
            snap = json.load(fh)
    except ValueError as err:
        raise SystemExit(f"{p}: not valid JSON ({err})") from err
    if not isinstance(snap, dict) or snap.get("schema") != 1 \
            or not isinstance(snap.get("metrics"), dict):
        raise SystemExit(f"{p}: not a metrics snapshot (schema 1)")
    return snap


def _snapshot_scalars(snap: dict) -> dict:
    """Flatten a snapshot to {series key -> value} for diffing: counters/
    gauges by value, histograms by their _count and _sum."""
    out = {}
    for name, m in snap["metrics"].items():
        for s in m.get("series", []):
            lbl = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
            key = f"{name}{{{lbl}}}" if lbl else name
            if m.get("type") == "histogram":
                out[key + ":count"] = s.get("count", 0)
                out[key + ":sum"] = s.get("sum", 0.0)
            else:
                out[key] = s.get("value")
    return out


def _fleet_manifest_scalars(man: dict) -> dict | None:
    """Flatten a merged fleet manifest (or the run manifest embedding
    one) into diffable series keys, or None when ``man`` is not one.
    The frontend's own metrics snapshot flattens normally; each replica
    shard contributes ``r{idx}:``-prefixed series (delivered outcomes +
    transport counters), so a diff of two fleet runs shows per-worker
    drift, not just the merged totals."""
    fm = man.get("fleet")
    if not isinstance(fm, dict):
        fm = man if {"replicas", "audit"} <= set(man) else None
    if fm is None:
        return None
    out = {}
    snap = man.get("metrics")
    if isinstance(snap, dict) and snap.get("schema") == 1 \
            and isinstance(snap.get("metrics"), dict):
        out.update(_snapshot_scalars(snap))
    out["fleet:accepted_total"] = fm.get("accepted_total")
    for k, v in (fm.get("transport") or {}).items():
        out[f"fleet:transport:{k}"] = v
    for k, v in ((fm.get("frontend_local") or {}).get("outcomes")
                 or {}).items():
        out[f"fleet:frontend_local:{k}"] = v
    for rep in fm.get("replicas") or []:
        i = rep.get("replica")
        out[f"r{i}:outcomes_total"] = rep.get("outcomes_total")
        for k, v in (rep.get("outcomes") or {}).items():
            out[f"r{i}:outcomes:{k}"] = v
        tp = rep.get("transport")
        if isinstance(tp, dict):
            for k, v in sorted(tp.items()):
                if isinstance(v, (int, float)):
                    out[f"r{i}:transport:{k}"] = v
    return out


def _metrics_diff_side(path: str) -> dict:
    """One side of ``metrics diff``: a metrics snapshot (file or
    --metrics-dir) or a merged ``fleet_manifest.json`` — the fleet form
    diffs the frontend snapshot plus per-replica shard series."""
    p = os.path.join(path, "metrics.json") if os.path.isdir(path) else path
    if not os.path.exists(p):
        raise SystemExit(f"{p}: not found — run with --metrics-dir first")
    try:
        with open(p, encoding="utf-8") as fh:
            obj = json.load(fh)
    except ValueError as err:
        raise SystemExit(f"{p}: not valid JSON ({err})") from err
    if isinstance(obj, dict):
        fleet = _fleet_manifest_scalars(obj)
        if fleet is not None:
            return fleet
        if obj.get("schema") == 1 and isinstance(obj.get("metrics"), dict):
            return _snapshot_scalars(obj)
    raise SystemExit(f"{p}: neither a metrics snapshot (schema 1) nor a "
                     "merged fleet manifest")


def _metrics(args):
    """dump: print + parse-validate the Prometheus textfile; snapshot:
    print the validated snapshot JSON; diff: per-series deltas between two
    snapshots (counters/gauges by value, histograms by count/sum) —
    either side may also be a merged fleet manifest, whose replica shards
    diff as ``r{idx}:``-prefixed series."""
    from mfm_tpu.obs.exporters import parse_prometheus

    if args.action == "dump":
        p = _metrics_paths(args.path, "metrics.prom")
        with open(p, encoding="utf-8") as fh:
            text = fh.read()
        parse_prometheus(text)  # malformed exposition exits via ValueError
        print(text, end="")
        return
    if args.action == "snapshot":
        print(json.dumps(_load_metrics_snapshot(args.path), indent=1,
                         sort_keys=True))
        return
    # diff
    a = _metrics_diff_side(args.a)
    b = _metrics_diff_side(args.b)
    delta = {}
    for key in sorted(set(a) | set(b)):
        va, vb = a.get(key), b.get(key)
        if va != vb:
            delta[key] = {"a": va, "b": vb,
                          "delta": (None if va is None or vb is None
                                    else round(vb - va, 9))}
    print(json.dumps({"changed": len(delta), "series": delta}, indent=1))


def _lint_cmd(args):
    # pure-AST pass (mfm_tpu/lint.py): no backend, no numpy — safe to run
    # anywhere, including a box with a dead TPU tunnel
    from mfm_tpu.lint import main as lint_main

    lint_argv = list(args.paths)
    if args.baseline:
        lint_argv += ["--baseline", args.baseline]
    if args.strict:
        lint_argv.append("--strict")
    if args.json:
        lint_argv.append("--json")
    raise SystemExit(lint_main(lint_argv))


def _sync_cmd(args):
    # stdlib-only AST pass (mfm_tpu/analysis/sync.py): lock discipline and
    # shared-state analysis for the serving fleet — no backend, no numpy
    from mfm_tpu.analysis.sync import main as sync_main

    sync_argv = list(args.paths)
    if args.baseline:
        sync_argv += ["--baseline", args.baseline]
    if args.strict:
        sync_argv.append("--strict")
    if args.json:
        sync_argv.append("--json")
    raise SystemExit(sync_main(sync_argv))


def _audit_cmd(args):
    # device-free IR audit (mfm_tpu/analysis/): lowers and compiles every
    # registered entrypoint on whatever backend is pinned, executes
    # nothing.  Mesh cells need 8 devices; on a smaller host they skip
    # with a warning — `python tools/mfmaudit.py` pins the 8-way virtual
    # CPU split before jax loads and is the form CI gates on.
    from mfm_tpu.analysis.run import main as audit_main

    audit_argv = []
    if args.passes:
        audit_argv += ["--passes", args.passes]
    if args.baseline:
        audit_argv += ["--baseline", args.baseline]
    if args.budgets:
        audit_argv += ["--budgets", args.budgets]
    if args.write_budgets:
        audit_argv.append("--write-budgets")
    if args.json:
        audit_argv += ["--json", args.json]
    if args.strict:
        audit_argv.append("--strict")
    raise SystemExit(audit_main(audit_argv))


def main(argv=None):
    # safe pre-pinning: importing the module only loads jax, it does not
    # initialize a backend (the --platform pin below still wins)
    from mfm_tpu.ops.rolling import ROLLING_IMPLS

    ap = argparse.ArgumentParser(prog="mfm_tpu")
    ap.add_argument("--platform", default=None, metavar="cpu|tpu",
                    help="pin the JAX platform via the config API (env "
                         "JAX_PLATFORMS loses to site hooks that pre-register "
                         "a TPU plugin; this flag wins)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    r = sub.add_parser("risk", help="risk model over a barra-format CSV (demo.py path)")
    rsrc = r.add_mutually_exclusive_group(required=True)
    rsrc.add_argument("--barra", help="barra-format CSV (demo.py:22)")
    rsrc.add_argument("--barra-store", metavar="STORE",
                      help="read the barra_factors collection from this "
                           "PanelStore instead (demo.ipynb's Mongo-sourced "
                           "variant; written by `pipeline --to-store`)")
    r.add_argument("--industry-info", default=None)
    r.add_argument("--out", default="results")
    r.add_argument("--nw-lags", type=int, default=2)
    r.add_argument("--nw-half-life", type=float, default=252.0)
    r.add_argument("--nw-method", choices=["scan", "associative"],
                   default="scan",
                   help="expanding Newey-West evaluation: serial lax.scan "
                        "(single-chip default) or associative_scan (O(log T) "
                        "depth; keeps the date axis sharded on a mesh)")
    r.add_argument("--eigen-sims", type=int, default=100)
    r.add_argument("--eigen-scale", type=float, default=1.4)
    r.add_argument("--vr-half-life", type=float, default=42.0)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--dtype", default="float32")
    r.add_argument("--bias-plot", default=None, metavar="FILE.png",
                   help="also render the USE4 bias-statistic plot into OUT "
                        "(needs matplotlib: pip install 'mfm-tpu[plot]') and "
                        "write the numbers to OUT/bias_stats.json")
    r.add_argument("--bias-burn-in", type=int, default=252,
                   help="dates excluded from the burn-in-free bias variant")
    r.add_argument("--specific-risk", action="store_true",
                   help="also write specific_risk.csv (shrunk EWMA "
                        "specific vol per stock x date)")
    r.add_argument("--profile", default=None, metavar="DIR",
                   help="capture a jax.profiler trace of the pipeline run "
                        "into DIR (TensorBoard/Perfetto-viewable)")
    r.add_argument("--jax-profile", default=None, metavar="DIR",
                   help="synonym of --profile (the device-profiling flag "
                        "shared with bench.py): gate jax.profiler.trace "
                        "around the hot region, output into DIR")
    def _positive_int(v):
        iv = int(v)
        if iv < 1:
            raise argparse.ArgumentTypeError(f"must be >= 1, got {v}")
        return iv

    def _eigen_chunk(v):
        if v == "auto":
            return "auto"
        if v in ("none", "full"):
            return None
        return _positive_int(v)

    _eigen_chunk_help = (
        "date-chunk size for the eigen Monte-Carlo stream (bounds its "
        "(chunk, M, K, K) transient); 'auto' (default) sizes it from live "
        "memory headroom, 'none' forces the single full batch, an int "
        "pins it.  Results are identical either way")
    r.add_argument("--eigen-chunk", type=_eigen_chunk, default="auto",
                   metavar="N|auto|none", help=_eigen_chunk_help)
    _eigen_sim_length_help = (
        "draw length behind each simulated covariance (default: the panel "
        "length T).  Pin it when serving incrementally: a checkpoint "
        "freezes its Monte-Carlo draws, and only a pinned length keeps a "
        "from-scratch rerun on the same draws (bitwise comparability)")
    r.add_argument("--eigen-sim-length", type=_positive_int, default=None,
                   metavar="L", help=_eigen_sim_length_help)
    _eigen_mc_dtype_help = (
        "storage dtype for the eigen Monte-Carlo draws/scaled-cov assembly "
        "(eigh and accumulation stay f32).  'bfloat16' halves the stage's "
        "memory traffic; outputs change within the documented eigenfactor-"
        "bias parity budget (tools/parity_budget.json: eigen_mc_bf16), NOT "
        "bitwise — leave unset for the bitwise default path")
    r.add_argument("--eigen-mc-dtype", choices=["bfloat16"], default=None,
                   help=_eigen_mc_dtype_help)
    _eigen_incremental_help = (
        "causal incremental eigen: each date's Monte-Carlo bias uses "
        "exactly the draw prefix available at that date, and the raw draw "
        "moments ride the checkpoint as a carry — `--update` then appends "
        "a date in O(1) eigen work (one simulated eigh batch) instead of "
        "recomputing the whole history's bias, bitwise-equal to the "
        "corresponding full-history rerun under this same flag.  "
        "Incompatible with --eigen-sim-length (the draw count is the "
        "date count by construction)")
    r.add_argument("--eigen-incremental", action="store_true",
                   help=_eigen_incremental_help)

    r.add_argument("--save-state", default=None, metavar="FILE.npz",
                   help="also checkpoint the resumable scan state (NW/vol-"
                        "regime carries + frozen eigen draws) after the last "
                        "date; `risk --update FILE.npz` then serves each new "
                        "date in O(1) instead of an O(T) rebuild")
    r.add_argument("--update", default=None, metavar="FILE.npz",
                   help="incremental serve: load this checkpoint, run ONE "
                        "update step over the barra table's dates after the "
                        "checkpoint's last date, write tables for those "
                        "dates only, and advance FILE in place.  Outputs "
                        "are bitwise the full-history run's")
    r.add_argument("--save-outputs", action="store_true",
                   help="also write OUT/risk_outputs.npz (every stage "
                        "output incl. the full covariance series — the "
                        "CSVs carry only the last date's)")
    r.add_argument("--portfolio-bias", type=_positive_int, default=None,
                   metavar="Q",
                   help="also run the USE4 random-portfolio bias acceptance "
                        "test with Q portfolios and write "
                        "OUT/portfolio_bias.json")
    r.add_argument("--portfolio", default=None, metavar="CSV",
                   help="ts_code,weight table: write predicted portfolio "
                        "risk + per-factor Euler attribution to "
                        "OUT/portfolio_risk.json")
    r.add_argument("--portfolio-date", type=int, default=-1,
                   help="date index for --portfolio (default: last)")
    r.add_argument("--mesh", default=None, metavar="DxS",
                   help="compute on a DATExSTOCK device mesh (e.g. 2x4): "
                        "panels are built shard-local and the risk stack "
                        "runs pjit-sharded; with --update the slab must "
                        "divide the mesh exactly.  On CPU bring up virtual "
                        "devices with XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N")
    r.add_argument("--quarantine", action="store_true",
                   help="guard appended dates (NaN density, universe "
                        "collapse, MAD outliers, bad caps, date order) and "
                        "serve quarantined dates in degraded mode: last "
                        "healthy covariance + staleness, carries frozen.  "
                        "See docs/SERVING.md")
    r.add_argument("--force", action="store_true",
                   help="with --update: accept a checkpoint whose "
                        "generation is older than the latest.json pointer "
                        "(deliberate rollback; never bypasses the checksum)")
    _metrics_dir_help = (
        "write telemetry here: events.jsonl (structured event stream), "
        "metrics.prom (Prometheus textfile exposition) and metrics.json "
        "(snapshot, diffable with `mfm-tpu metrics diff`).  The run "
        "manifest is independent of this flag — it always lands beside "
        "the checkpoint.  docs/OBSERVABILITY.md")
    r.add_argument("--metrics-dir", default=None, metavar="DIR",
                   help=_metrics_dir_help)
    r.set_defaults(fn=_risk)

    f = sub.add_parser("factors", help="style-factor production (main.py path)")
    f.add_argument("--prepared", default=None, metavar="DIR",
                   help="a `prepare` output directory (provides --panel/"
                        "--index/--industry in one flag)")
    f.add_argument("--panel", default=None, help="long csv/parquet of raw fields")
    f.add_argument("--index", default=None, help="index daily prices csv/parquet")
    f.add_argument("--industry", default=None, help="ts_code -> l1_code csv")
    f.add_argument("--out", default="results")
    f.add_argument("--dtype", default="float32")
    f.add_argument("--block", type=int, default=None,
                   help="rolling-kernel date-block size (memory = block x "
                        "window x stocks floats per input); default: auto "
                        "from the panel width (64 at CSI300, 16 at all-A)")
    f.add_argument("--rolling-impl", choices=ROLLING_IMPLS,
                   default="scan",
                   help="rolling-kernel implementation: O(T*N) two-level "
                        "scans (default) or the windowed-gather form")
    f.set_defaults(fn=_factors)

    d = sub.add_parser("demo", help="synthetic end-to-end risk model")
    d.add_argument("--dates", type=int, default=120)
    d.add_argument("--stocks", type=int, default=60)
    d.add_argument("--industries", type=int, default=6)
    d.add_argument("--styles", type=int, default=4)
    d.add_argument("--eigen-sims", type=int, default=16)
    d.add_argument("--out", default="results")
    d.add_argument("--dtype", default="float32")
    d.add_argument("--check-determinism", action="store_true",
                   help="run the pipeline twice more and report whether "
                        "outputs are bitwise identical (the same-seed "
                        "sanitizer)")
    d.set_defaults(fn=_demo)

    pp = sub.add_parser("prepare",
                        help="store -> master-panel artifacts "
                             "(load_and_prepare_data path)")
    pp.add_argument("--store", required=True)
    pp.add_argument("--out", default="prepared")
    pp.add_argument("--index-code", default="000300.SH")
    pp.add_argument("--start", default="20200101")
    pp.add_argument("--end", default=None)
    pp.add_argument("--fin-start", default="20190101")
    pp.set_defaults(fn=_prepare)

    pl = sub.add_parser("pipeline",
                        help="one command: raw store -> factors -> risk "
                             "outputs (main.py + demo.py chain)")
    pl.add_argument("--store", required=True)
    pl.add_argument("--out", default="results")
    pl.add_argument("--index-code", default="000300.SH")
    pl.add_argument("--start", default="20200101")
    pl.add_argument("--end", default=None)
    pl.add_argument("--fin-start", default="20190101")
    pl.add_argument("--resume", action="store_true",
                    help="reuse the barra_data.csv stage artifact if present")
    pl.add_argument("--append", action="store_true",
                    help="daily refresh: re-run the factor stage over the "
                         "(updated) store, then serve only the dates past "
                         "OUT/risk_state.npz's checkpoint with ONE update "
                         "step and splice them onto OUT's artifacts — OUT "
                         "ends up bitwise identical to a from-scratch risk "
                         "stage, in O(new dates) instead of O(history)")
    pl.add_argument("--to-store", default=None, metavar="STORE",
                    help="also save barra_factors + "
                         "sw_industry_info_for_factors collections into this "
                         "PanelStore (main.py:144-155's Mongo save), "
                         "readable by `risk --barra-store`")
    pl.add_argument("--mesh", default=None, metavar="DxS",
                    help="run the --append update step on a DATExSTOCK "
                         "device mesh (slab sharded, state replicated; "
                         "bitwise the single-device update)")
    pl.add_argument("--nw-lags", type=int, default=2)
    pl.add_argument("--nw-half-life", type=float, default=252.0)
    pl.add_argument("--nw-method", choices=["scan", "associative"],
                    default="scan",
                    help="expanding Newey-West evaluation: serial lax.scan "
                         "(single-chip default) or associative_scan "
                         "(O(log T) depth; keeps the date axis sharded)")
    pl.add_argument("--eigen-sims", type=int, default=100)
    pl.add_argument("--eigen-scale", type=float, default=1.4)
    pl.add_argument("--eigen-chunk", type=_eigen_chunk, default="auto",
                    metavar="N|auto|none", help=_eigen_chunk_help)
    pl.add_argument("--eigen-sim-length", type=_positive_int, default=None,
                    metavar="L", help=_eigen_sim_length_help)
    pl.add_argument("--eigen-mc-dtype", choices=["bfloat16"], default=None,
                    help=_eigen_mc_dtype_help)
    pl.add_argument("--eigen-incremental", action="store_true",
                    help=_eigen_incremental_help)
    pl.add_argument("--vr-half-life", type=float, default=42.0)
    pl.add_argument("--seed", type=int, default=0)
    pl.add_argument("--dtype", default="float32")
    pl.add_argument("--block", type=int, default=None,
                    help="rolling-kernel date-block size; default: auto "
                         "from the panel width (64 at CSI300, 16 at all-A)")
    pl.add_argument("--rolling-impl", choices=ROLLING_IMPLS,
                    default="scan",
                    help="rolling-kernel implementation: O(T*N) two-level "
                         "scans (default) or the windowed-gather form")
    pl.add_argument("--specific-risk", action="store_true",
                    help="also write specific_risk.csv (shrunk EWMA "
                         "specific vol per stock x date)")
    pl.add_argument("--profile", default=None, metavar="DIR",
                    help="capture a jax.profiler trace spanning the factor "
                         "and risk stages into DIR")
    pl.add_argument("--jax-profile", default=None, metavar="DIR",
                    help="synonym of --profile (the device-profiling flag "
                         "shared with bench.py): gate jax.profiler.trace "
                         "around the hot region, output into DIR")
    pl.add_argument("--portfolio-bias", type=_positive_int, default=None,
                    metavar="Q",
                    help="also run the USE4 random-portfolio bias acceptance "
                         "test with Q portfolios and write "
                         "OUT/portfolio_bias.json")
    pl.add_argument("--bias-burn-in", type=int, default=252,
                    help="dates excluded from the burn-in-free bias scope")
    pl.add_argument("--portfolio", default=None, metavar="CSV",
                    help="ts_code,weight table: write predicted portfolio "
                         "risk + per-factor Euler attribution to "
                         "OUT/portfolio_risk.json")
    pl.add_argument("--portfolio-date", type=int, default=-1,
                    help="date index for --portfolio (default: last)")
    pl.add_argument("--alphas", default=None, metavar="FILE",
                    help="alpha-DSL expressions (one per line): evaluate on "
                         "the raw panel, select the best de-correlated "
                         "--alpha-top, and price them as extra style "
                         "factors (report: OUT/alpha_styles.json)")
    pl.add_argument("--alphas-llm", action="store_true",
                    help="treat --alphas as raw LLM output (tolerant "
                         "extraction instead of one-expression-per-line)")
    pl.add_argument("--alpha-top", type=_positive_int, default=5,
                    help="max alpha styles to keep (default 5)")
    pl.add_argument("--alpha-max-corr", type=float, default=0.7,
                    help="pairwise PnL-correlation cap for alpha selection")
    pl.add_argument("--quarantine", action="store_true",
                    help="guard appended dates and serve quarantined ones "
                         "in degraded mode (last healthy covariance + "
                         "staleness, carries frozen).  See docs/SERVING.md")
    pl.add_argument("--force", action="store_true",
                    help="with --append: accept a checkpoint whose "
                         "generation is older than the latest.json pointer "
                         "(deliberate rollback; never bypasses the checksum)")
    pl.add_argument("--metrics-dir", default=None, metavar="DIR",
                    help=_metrics_dir_help)
    pl.set_defaults(fn=_pipeline)

    al = sub.add_parser("alpha",
                        help="batch alpha-expression evaluation + scorecard "
                             "(BASELINE config 5)")
    al.add_argument("--exprs", required=True,
                    help="text file, one expression per line (# = comment); "
                         "'-' reads stdin (pipe an LLM's output straight in)")
    al.add_argument("--panel", required=True,
                    help="long csv/parquet with ts_code/trade_date + fields")
    al.add_argument("--out", default="alpha_scores.csv")
    al.add_argument("--fwd-field", default="ret",
                    help="field whose next-traded-day value is the target")
    al.add_argument("--spread-q", type=float, default=0.2)
    al.add_argument("--chunk", type=int, default=1000,
                    help="expressions per compiled sub-batch")
    al.add_argument("--select", type=_positive_int, default=None, metavar="K",
                    help="greedily pick the K best expressions (by |mean "
                         "IC|) whose pairwise long-short-PnL correlation "
                         "stays under --max-corr")
    al.add_argument("--max-corr", type=float, default=0.7,
                    help="redundancy cap for --select")
    al.add_argument("--min-ic", type=float, default=0.0,
                    help="--select floor: candidates with |mean IC| below "
                         "this never join, even under k")
    al.add_argument("--select-out", default=None, metavar="FILE.txt",
                    help="write the selected expressions here, one per line")
    al.add_argument("--values-out", default=None, metavar="FILE.parquet",
                    help="write the evaluated alpha panels as a long table "
                         "(selected expressions when --select ran, else "
                         "all) + a FILE.exprs.txt column map — feedable "
                         "back into the factors pipeline as custom styles")
    al.add_argument("--llm", action="store_true",
                    help="treat --exprs as RAW LLM output (markdown fences, "
                         "numbered lists, `name = expr` labels, prose): "
                         "extract every valid DSL expression, dedup, and "
                         "report what was rejected instead of failing fast")
    al.set_defaults(fn=_alpha)

    c = sub.add_parser("crosscheck",
                       help="compare factor tables vs an external source "
                            "(beta.ipynb jqdatasdk check, generalized)")
    c.add_argument("--ours", required=True)
    c.add_argument("--external", required=True)
    c.add_argument("--factors", default=None, help="comma list; default: "
                   "all shared numeric columns")
    c.add_argument("--date-col", default="trade_date")
    c.add_argument("--code-col", default="ts_code")
    c.add_argument("--out", default=None, help="write report CSV here")
    c.add_argument("--gate", type=float, default=None, metavar="TOL",
                   help="exit 1 if any factor's max |diff| over the overlap "
                        "exceeds TOL or has no overlap (CI parity gate)")
    c.set_defaults(fn=_crosscheck)

    rp = sub.add_parser("report",
                        help="model-health summary + plots over a risk-run "
                             "results dir (the notebooks' QC eyeballing, "
                             "as a driver)")
    rp.add_argument("--results", required=True,
                    help="directory a risk/pipeline run wrote its tables to")
    rp.add_argument("--plot", default=None, metavar="FILE.png",
                    help="render the 2x2 health plot into RESULTS "
                         "(needs matplotlib)")
    rp.add_argument("--json", default=None, metavar="FILE.json",
                    help="also write the summary JSON into RESULTS")
    rp.add_argument("--top-k", type=int, default=6,
                    help="factors direct-labelled in the cumulative panel")
    rp.add_argument("--roll-window", type=int, default=63,
                    help="rolling window (days) for the R² mean")
    rp.set_defaults(fn=_report)

    ex = sub.add_parser("etl-xlsx",
                        help="ingest the shipped static workbooks "
                             "(index_list.xlsx / industry_index_data.xlsx "
                             "Wind EDB export) into store collections")
    ex.add_argument("--store", required=True)
    ex.add_argument("--index-list", default=None, metavar="XLSX")
    ex.add_argument("--industry-index", default=None, metavar="XLSX")
    ex.add_argument("--sheets", default="0,1",
                    help="industry workbook sheet indices (default: CITIC "
                         "and SW L1)")
    ex.set_defaults(fn=_etl_xlsx)

    eu = sub.add_parser("etl-update",
                        help="calendar-driven refresh of all collections "
                             "(update_mongo_db.py __main__ path)")
    eu.add_argument("--store", required=True)
    eu.add_argument("--start", required=True, help="yyyymmdd")
    eu.add_argument("--end", default=None, help="yyyymmdd (default: today)")
    eu.add_argument("--index-codes",
                    default="000300.SH,000016.SH,000903.SH",
                    help="comma list (reference __main__: CSI300/SSE50/CSI100)")
    eu.add_argument("--statements",
                    default="balancesheet,cashflow,income,"
                            "financial_indicators",
                    help="comma list of statement kinds; empty to skip")
    eu.add_argument("--components-date", default=None,
                    help="refresh index components at this yyyymmdd date")
    eu.add_argument("--no-sw", action="store_true",
                    help="skip the SW industry refresh")
    eu.add_argument("--sw-csv", default=None,
                    help="load SW industries from this CSV instead of the "
                         "per-stock API (the reference's CSV path)")
    eu.add_argument("--calls-per-min", type=int, default=480)
    eu.add_argument("--token", default=None,
                    help="tushare token (default: TUSHARE_TOKEN env)")
    eu.add_argument("--dry-run", action="store_true",
                    help="print the per-collection fetch plan (watermarks, "
                         "ranges, call counts) without touching the API")
    eu.set_defaults(fn=_etl_update)

    ev = sub.add_parser("etl-verify",
                        help="store sanity counters (verify_data.py path)")
    ev.add_argument("--store", required=True)
    ev.add_argument("--name", default="daily_prices")
    ev.add_argument("--code-col", default="ts_code")
    ev.add_argument("--date-col", default="trade_date")
    ev.add_argument("--diagnose", action="store_true",
                    help="per-stock statement QC on --name (missing/dup "
                         "announcement keys, ann-before-period-end) — the "
                         "notebooks' bad-group bisection, vectorized")
    ev.add_argument("--ann-col", default="f_ann_date")
    ev.add_argument("--end-col", default="end_date")
    ev.set_defaults(fn=_etl_verify)

    em = sub.add_parser("etl-missing",
                        help="stocks in the universe with no price rows "
                             "(fill_missing_data.py path)")
    em.add_argument("--store", required=True)
    em.add_argument("--universe", default="stock_info")
    em.add_argument("--name", default="daily_prices")
    em.add_argument("--code-col", default="ts_code")
    em.add_argument("--fix", action="store_true",
                    help="refetch the missing stocks' prices "
                         "(fill_missing_data.py's repair step)")
    em.add_argument("--start", default="20200101", help="repair range start")
    em.add_argument("--end", default=None, help="repair range end (today)")
    em.add_argument("--calls-per-min", type=int, default=480)
    em.add_argument("--token", default=None)
    em.set_defaults(fn=_etl_missing)

    mt = sub.add_parser(
        "metrics",
        help="inspect telemetry artifacts a --metrics-dir run wrote "
             "(docs/OBSERVABILITY.md)")
    mts = mt.add_subparsers(dest="action", required=True)
    md = mts.add_parser("dump",
                        help="print a metrics.prom textfile after "
                             "parse-validating the exposition format")
    md.add_argument("path", help="metrics dir or .prom file")
    msn = mts.add_parser("snapshot",
                         help="print a validated metrics.json snapshot")
    msn.add_argument("path", help="metrics dir or metrics.json file")
    mdf = mts.add_parser("diff",
                         help="per-series deltas between two snapshots "
                              "(counters/gauges by value, histograms by "
                              "count/sum)")
    mdf.add_argument("a", help="older metrics dir or metrics.json")
    mdf.add_argument("b", help="newer metrics dir or metrics.json")
    mt.set_defaults(fn=_metrics)

    ln = sub.add_parser(
        "lint",
        help="the JAX-doctrine linter (rules R1-R7, docs/DOCTRINE.md) over "
             "mfm_tpu/, bench.py and tools/")
    ln.add_argument("paths", nargs="*",
                    help="files/dirs to lint (default: mfm_tpu bench.py "
                         "tools)")
    ln.add_argument("--baseline", default=None,
                    help="baseline JSON ('none' disables; default: "
                         "tools/mfmlint_baseline.json)")
    ln.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ln.add_argument("--json", action="store_true",
                    help="machine-readable output")
    ln.set_defaults(fn=_lint_cmd)

    sy = sub.add_parser(
        "sync",
        help="lock-discipline & shared-state static analysis for the "
             "serving fleet (rules S1-S3: guarded-field accesses, "
             "lock-order cycles, blocking under a lock; docs/DOCTRINE.md "
             "§Concurrency doctrine)")
    sy.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: mfm_tpu)")
    sy.add_argument("--baseline", default=None,
                    help="baseline JSON ('none' disables; default: "
                         "tools/mfmsync_baseline.json)")
    sy.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    sy.add_argument("--json", action="store_true",
                    help="machine-readable output")
    sy.set_defaults(fn=_sync_cmd)

    au = sub.add_parser(
        "audit",
        help="IR-level static audit of every jit entrypoint: donation-"
             "aliasing proof, wide-dtype/callback scan, collective audit, "
             "recompile-surface enumeration, and static memory budgets "
             "(passes A1-A5, docs/AUDIT.md); device-free — nothing runs")
    au.add_argument("--passes", default=None,
                    help="comma-separated subset of A1,A2,A3,A4,A5 "
                         "(default: all)")
    au.add_argument("--baseline", default=None,
                    help="baseline JSON of suppressed findings ('none' "
                         "disables; default: tools/mfmaudit_baseline.json)")
    au.add_argument("--budgets", default=None,
                    help="A5 budget file (default: "
                         "tools/audit_budgets.json)")
    au.add_argument("--write-budgets", action="store_true",
                    help="freeze the measured memory numbers as the new "
                         "budget file instead of gating against them")
    au.add_argument("--json", default=None, metavar="FILE",
                    help="write the sealed report JSON to FILE "
                         "('-' for stdout)")
    au.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    au.set_defaults(fn=_audit_cmd)

    dr = sub.add_parser(
        "doctor",
        help="audit serving artifacts: payload checksums, fencing "
             "generations vs latest.json, risk-state schema/stamp, and "
             "the run manifest beside them (schema/stamp-match/health; "
             "exit 1 on any problem; docs/SERVING.md)")
    dr.add_argument("path", nargs="?", default=None,
                    help=".npz artifact or a directory of them (e.g. a "
                         "pipeline OUT dir or checkpoint dir); optional "
                         "when only --audit is asked for")
    dr.add_argument("--force", action="store_true",
                    help="audit past a stale-generation refusal (reported "
                         "as a warning instead of a failure)")
    dr.add_argument("--serve", action="store_true",
                    help="also audit the serve_manifest.json beside the "
                         "artifacts: exit non-zero if the query service's "
                         "circuit breaker was open at shutdown; warn on "
                         "load shedding / degraded health")
    dr.add_argument("--scenarios", action="store_true",
                    help="also audit the scenario_manifest.json beside the "
                         "artifacts: exit non-zero on a torn manifest, a "
                         "spec-hash mismatch, or inconsistent counts; warn "
                         "on rejected scenarios")
    dr.add_argument("--audit", nargs="?", const="latest", default=None,
                    metavar="FILE",
                    help="also verify the committed static-audit snapshot "
                         "(newest AUDIT_r*.json, or FILE): schema, seal "
                         "digest (tamper detection), strict-cleanliness, "
                         "and staleness vs the live registry and budget "
                         "file; exit non-zero on a torn or tampered "
                         "snapshot")
    dr.add_argument("--sync", action="store_true",
                    help="also run the lock-discipline pass (mfm-tpu "
                         "sync --strict) against its committed baseline: "
                         "exit non-zero on new S1-S3 findings or stale "
                         "baseline entries; baselined findings surface "
                         "as warnings")
    dr.set_defaults(fn=_doctor)

    sv = sub.add_parser(
        "serve",
        help="batched portfolio-query service over a guarded risk-state "
             "checkpoint: JSONL requests in, JSONL responses out, with "
             "request guards + dead-letter quarantine, bounded-queue "
             "admission control, deadlines, load shedding, and a circuit "
             "breaker (docs/SERVING.md §Query service)")
    sv.add_argument("state", help="risk-state .npz saved with quarantine "
                                  "enabled (serves its last_good_cov)")
    sv.add_argument("--input", default="-",
                    help="JSONL request file ('-' = stdin)")
    sv.add_argument("--output", default="-",
                    help="JSONL response file ('-' = stdout)")
    sv.add_argument("--dead-letter", default=None,
                    help="JSONL file collecting guarded-out requests "
                         "(default: discard)")
    sv.add_argument("--benchmarks", default=None,
                    help="JSON file {name: [factor exposures]} of served "
                         "benchmarks for active-risk/beta queries")
    sv.add_argument("--queue-max", type=int, default=4096,
                    help="admission bound; overflow sheds the OLDEST "
                         "queued request (default 4096)")
    sv.add_argument("--batch-max", type=int, default=1024,
                    help="max requests per device batch (default 1024)")
    sv.add_argument("--deadline-s", type=float, default=1.0,
                    help="default per-request deadline budget (default 1.0)")
    sv.add_argument("--breaker-failures", type=int, default=3,
                    help="consecutive batch failures that open the "
                         "circuit breaker (default 3)")
    sv.add_argument("--breaker-cooldown-s", type=float, default=5.0,
                    help="breaker open->half-open cooldown, also the "
                         "retry_after_s on rejections (default 5.0)")
    sv.add_argument("--weight-mad-k", type=float, default=0.0,
                    help="reject requests with a weight beyond K MADs of "
                         "the request's own median (0 = off)")
    sv.add_argument("--gulp", action="store_true",
                    help="read ALL input before the first drain — "
                         "deterministic overload mode (shedding depends "
                         "only on the input, not drain timing)")
    sv.add_argument("--watch", action="store_true",
                    help="poll latest.json between batches and hot-swap "
                         "the engine when the checkpoint generation moves; "
                         "a failed fence audit opens the breaker")
    sv.add_argument("--fsync-emits", action="store_true",
                    help="fsync the response stream after every emitted "
                         "batch — responses survive SIGKILL through the "
                         "OS page cache, not just the Python buffer")
    sv.add_argument("--replicas", type=int, default=0,
                    help="run N worker replica processes behind a "
                         "coalescing front end sharing the fenced "
                         "checkpoint store (0 = serve in-process); "
                         "writes fleet_manifest.json beside the "
                         "checkpoint (docs/SERVING.md §Fleet)")
    sv.add_argument("--listen", default=None, metavar="HOST:PORT",
                    help="accept concurrent socket connections (JSONL "
                         "per connection) instead of reading --input; "
                         "port 0 binds ephemerally and the bound address "
                         "is printed to stderr")
    sv.add_argument("--http", action="store_true",
                    help="speak HTTP/1.1 on the --listen socket (POST / "
                         "with a JSONL body; GET /healthz, /metrics)")
    sv.add_argument("--linger-s", type=float, default=0.01,
                    help="coalescer max-linger budget: the oldest "
                         "admitted request flushes after this wait even "
                         "if its bucket has not filled (default 0.01)")
    sv.add_argument("--cache-entries", type=int, default=4096,
                    help="response-cache entry bound: repeated request "
                         "bodies answer from a content-addressed cache "
                         "fenced on checkpoint generation + scenario "
                         "spec hash (default 4096; docs/SERVING.md §9)")
    sv.add_argument("--cache-bytes", type=int, default=64 << 20,
                    help="response-cache resident-byte bound "
                         "(default 64 MiB; LRU evicts past either bound)")
    sv.add_argument("--no-cache", action="store_true",
                    help="kill switch: disable the response cache "
                         "entirely (every request computes)")
    sv.add_argument("--warm-tol", type=float, default=0.0,
                    help="construct warm-start tolerance: relative-L2 "
                         "exposure distance under which a solved book "
                         "seeds the next solve's warm-start blend "
                         "(0 = off; warmed responses carry a "
                         "warm_start parity stanza)")
    sv.add_argument("--workers", default=None, metavar="HOST:PORT,...",
                    help="attach to already-running TCP workers, each "
                         "started elsewhere with `serve STATE --worker "
                         "--listen HOST:PORT` against the same fenced "
                         "checkpoint; mixes with --replicas; dialing "
                         "retries with exponential backoff "
                         "(docs/SERVING.md §10 Multi-host fleets)")
    sv.add_argument("--rollout", action="store_true",
                    help="rolling zero-downtime reload: when the "
                         "checkpoint generation moves, drain + re-fence "
                         "ONE worker at a time; the admission engine and "
                         "response-cache fence move only after the whole "
                         "fleet agrees (spawned workers run with "
                         "--hold-fence; TCP workers should be started "
                         "with it)")
    sv.add_argument("--hold-fence", action="store_true",
                    help="worker mode: do not self-poll the checkpoint "
                         "pointer between batches; re-fence only on the "
                         "frontend's __fleet__ reload frame (the rolling "
                         "rollout protocol)")
    sv.add_argument("--worker-timeout-s", type=float, default=30.0,
                    help="per-I/O deadline on every worker read/write; "
                         "silence past this quarantines the worker as "
                         "wedged and re-dispatches its in-flight batch "
                         "(default 30)")
    sv.add_argument("--heartbeat-s", type=float, default=5.0,
                    help="ping a worker idle this long before handing it "
                         "a batch; a missed pong quarantines it "
                         "(default 5, 0 = off)")
    sv.add_argument("--heartbeat-timeout-s", type=float, default=2.0,
                    help="deadline on a heartbeat pong / live metrics "
                         "scrape (default 2)")
    sv.add_argument("--connect-attempts", type=int, default=5,
                    help="--workers dial attempts per worker, with "
                         "exponential backoff (default 5)")
    sv.add_argument("--connect-backoff-s", type=float, default=0.05,
                    help="base backoff between --workers dial attempts "
                         "(default 0.05, doubles per retry)")
    sv.add_argument("--worker", action="store_true",
                    help=argparse.SUPPRESS)   # internal: fleet replica
    sv.add_argument("--worker-id", type=int, default=0,
                    help=argparse.SUPPRESS)   # internal: replica index
    sv.add_argument("--load-attempts", type=int, default=3,
                    help="startup checkpoint-load retries (default 3)")
    sv.add_argument("--load-backoff-s", type=float, default=0.1,
                    help="backoff between startup load retries "
                         "(default 0.1)")
    sv.add_argument("--metrics-dir", default=None, help=_metrics_dir_help)
    sv.set_defaults(fn=_serve)

    sc = sub.add_parser(
        "scenario",
        help="batched stress tests over a guarded risk-state checkpoint: "
             "factor shocks, vol regimes, correlation stress, historical "
             "replay, quarantine counterfactuals — one donated jit per "
             "S-bucket, atomic scenario_manifest.json beside the "
             "checkpoint (docs/SCENARIOS.md)")
    scs = sc.add_subparsers(dest="scmd", required=True)
    scs.add_parser("list", help="print the preset scenario catalog")
    scr = scs.add_parser(
        "run", help="run scenarios against a checkpoint and write "
                    "scenario_manifest.json beside it")
    scr.add_argument("state", help="risk-state .npz saved with quarantine "
                                   "enabled (scenarios shock its "
                                   "last_good_cov)")
    scr.add_argument("--preset", action="append", default=[],
                     help="preset scenario name, repeatable "
                          "(`mfm-tpu scenario list` shows the catalog)")
    scr.add_argument("--spec", action="append", default=[],
                     help="JSON ScenarioSpec file — one spec object or a "
                          "list of them (repeatable)")
    scr.add_argument("--out", default=None,
                     help="directory for scenario_manifest.json (default: "
                          "beside the checkpoint)")
    scr.add_argument("--bucket", type=int, default=None,
                     help="explicit pad bucket >= the number of scenarios "
                          "(default: the geometric bucket for S)")
    scr.add_argument("--metrics-dir", default=None, help=_metrics_dir_help)
    scw = scs.add_parser(
        "sweep", help="stream a sampler-generated scenario sweep through "
                      "fixed-size aggregates (top-k worst, quantile "
                      "sketch), refine with reverse-stress gradients, "
                      "write sweep_manifest.json beside the checkpoint")
    scw.add_argument("state", help="risk-state .npz saved with quarantine "
                                   "enabled (the sweep stresses its "
                                   "last_good_cov)")
    scw.add_argument("--sampler", choices=("uniform", "sobol", "grid"),
                     default="uniform",
                     help="spec generator over the shock ball "
                          "(default: uniform)")
    scw.add_argument("--n", type=int, default=65536,
                     help="scenarios to stream (default: 65536; grid "
                          "rounds to a square)")
    scw.add_argument("--seed", type=int, default=0,
                     help="sampler + refinement seed (default: 0)")
    scw.add_argument("--chunk", type=int, default=8192,
                     help="scenarios per donated jit call (default: 8192)")
    scw.add_argument("--top-k", type=int, default=16,
                     help="worst entries kept per book (default: 16)")
    scw.add_argument("--bins", type=int, default=64,
                     help="quantile-sketch histogram bins (default: 64)")
    scw.add_argument("--hist-span", type=float, default=8.0,
                     help="sketch upper edge as a multiple of each "
                          "book's base vol (default: 8.0)")
    scw.add_argument("--portfolio", default=None,
                     help="JSON portfolio file: one K-vector, a list of "
                          "them, or factor-name-keyed dicts (default: "
                          "one equal-weight portfolio)")
    scw.add_argument("--shift-max", type=float, default=0.01,
                     help="shock-ball |vol shift| cap (default: 0.01)")
    scw.add_argument("--scale-range", type=float, default=0.5,
                     help="shock-ball vol-scale half-range "
                          "(default: 0.5)")
    scw.add_argument("--vol-mult-max", type=float, default=3.5,
                     help="shock-ball vol_mult ceiling (default: 3.5)")
    scw.add_argument("--corr-beta-max", type=float, default=0.95,
                     help="shock-ball corr_beta ceiling (default: 0.95)")
    scw.add_argument("--no-refine", action="store_true",
                     help="skip the reverse-stress refinement loop")
    scw.add_argument("--out", default=None,
                     help="directory for sweep_manifest.json (default: "
                          "beside the checkpoint)")
    scw.add_argument("--metrics-dir", default=None, help=_metrics_dir_help)
    sc.set_defaults(fn=_scenario)

    gr = sub.add_parser(
        "grad",
        help="differentiable risk over a guarded checkpoint: reverse "
             "stress (worst admissible shock per portfolio), exact "
             "d vol/d shock sensitivity reports stamped into the scenario "
             "manifest, and gradient-based construction solvers — atomic "
             "grad_report.json beside the checkpoint "
             "(docs/DIFFERENTIABLE.md)")
    grs = gr.add_subparsers(dest="gcmd", required=True)

    def _grad_common(p):
        p.add_argument("state", help="risk-state .npz saved with "
                                     "quarantine enabled (grad runs "
                                     "against its last_good_cov)")
        p.add_argument("--portfolio", default=None,
                       help="JSON portfolio file: one K-vector of factor "
                            "weights, a list of them, or factor-name-"
                            "keyed dicts (default: one equal-weight "
                            "portfolio)")
        p.add_argument("--out", default=None,
                       help="directory for grad_report.json (default: "
                            "beside the checkpoint)")
        p.add_argument("--bucket", type=int, default=None,
                       help="explicit pad bucket >= the batch size "
                            "(default: the geometric bucket)")
        p.add_argument("--metrics-dir", default=None,
                       help=_metrics_dir_help)

    grr = grs.add_parser(
        "reverse", help="projected gradient ascent over the admissible "
                        "shock ball: the worst-case ScenarioSpec per "
                        "portfolio")
    _grad_common(grr)
    grr.add_argument("--steps", type=int, default=None,
                     help="ascent iterations (default: 200)")
    grr.add_argument("--vol-mult-max", type=float, default=3.5,
                     help="shock-ball vol_mult ceiling (default: 3.5)")
    grr.add_argument("--corr-beta-max", type=float, default=0.95,
                     help="shock-ball corr_beta ceiling (default: 0.95)")

    grn = grs.add_parser(
        "sensitivity", help="exact d vol/d shock and d vol/d exposure "
                            "rows per scenario, stamped into the "
                            "scenario manifest")
    _grad_common(grn)
    grn.add_argument("--preset", action="append", default=[],
                     help="preset scenario name, repeatable (default: "
                          "identity + the whole preset catalog)")
    grn.add_argument("--spec", action="append", default=[],
                     help="JSON ScenarioSpec file — one spec object or a "
                          "list of them (repeatable)")

    grc = grs.add_parser(
        "construct", help="gradient-based portfolio construction "
                          "against the served covariance")
    _grad_common(grc)
    grc.add_argument("solver", choices=("min_vol", "risk_parity", "hedge"),
                     help="which solver to run over the portfolio rows")
    gr.set_defaults(fn=_grad)

    args = ap.parse_args(argv)
    if getattr(args, "select_out", None) and args.select is None:
        ap.error("--select-out requires --select")
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    # pay pipeline-scale XLA compiles (the 32.5 s config-5 alpha batch,
    # the risk step) once per MACHINE, not once per process
    # (MFM_COMPILATION_CACHE=off disables, =DIR relocates).  Only for the
    # subcommands that actually jit: the data-only paths (etl-*, report,
    # crosscheck) must not pay the jax import or touch the cache dir.
    if args.cmd in ("risk", "factors", "demo", "prepare", "pipeline",
                    "alpha", "serve", "grad") \
            or (args.cmd == "scenario"
                and getattr(args, "scmd", None) in ("run", "sweep")):
        from mfm_tpu.utils.cache import enable_persistent_compilation_cache

        enable_persistent_compilation_cache()
    args.fn(args)


if __name__ == "__main__":
    main()
