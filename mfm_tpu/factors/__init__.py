"""The 16 Barra sub-factors, post-processing, and the FactorEngine driver."""

from mfm_tpu.factors.style import (
    compute_size,
    compute_beta_hsigma,
    compute_rstr,
    compute_dastd,
    compute_cmra,
    compute_nlsize,
    compute_bp,
    compute_liquidity,
    compute_earnings_yield,
    compute_growth,
    compute_leverage,
)
from mfm_tpu.factors.post import (
    winsorize_panel,
    composite_factor,
    orthogonalize,
)
from mfm_tpu.factors.engine import FactorEngine

__all__ = [
    "compute_size",
    "compute_beta_hsigma",
    "compute_rstr",
    "compute_dastd",
    "compute_cmra",
    "compute_nlsize",
    "compute_bp",
    "compute_liquidity",
    "compute_earnings_yield",
    "compute_growth",
    "compute_leverage",
    "winsorize_panel",
    "composite_factor",
    "orthogonalize",
    "FactorEngine",
]
