"""Factor post-processing: winsorize, composite aggregation, orthogonalization.

Contracts: ``Barra_factor_cal/post_processing.py`` (see SURVEY.md §1 L4).
All ops are per-date cross-sections batched over the (T, N) panel.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp

from mfm_tpu.ops.masked import masked_ols_residuals, winsorize_cs


def winsorize_panel(x: jax.Array, n_std: float = 2.5) -> jax.Array:
    """Per-date clip at mean +/- n_std * sample std (ddof=1), NaN passthrough
    (``post_processing.py:7-24``). x: (T, N)."""
    return winsorize_cs(x, n_std=n_std, axis=-1)


def composite_factor(
    components: Sequence[jax.Array], weights: Sequence[float]
) -> jax.Array:
    """Missing-aware weighted average: weights renormalize over the non-missing
    components per cell; all-missing -> NaN (``post_processing.py:26-45``)."""
    num = jnp.zeros_like(components[0])
    den = jnp.zeros_like(components[0])
    for comp, w in zip(components, weights):
        ok = jnp.isfinite(comp)
        num = num + jnp.where(ok, comp, 0.0) * w
        den = den + ok * w
    return num / den


def orthogonalize(
    target: jax.Array, regressors: Sequence[jax.Array]
) -> jax.Array:
    """Per-date OLS residual of target on [1, regressors...]; sections with
    fewer than len(regressors)+2 valid rows are all-NaN
    (``post_processing.py:47-69``). Arrays are (T, N)."""
    X = jnp.stack(regressors, axis=-1)  # (T, N, R)

    def one(y, Xd):
        return masked_ols_residuals(y, Xd, min_valid=Xd.shape[-1] + 2)

    return jax.vmap(one)(target, X)


def apply_post_processing(
    factors: dict,
    composite_config: Sequence[tuple],
    ortho_rules: Sequence[tuple],
    n_std: float = 2.5,
    winsorize_cols: Sequence[str] | None = None,
) -> dict:
    """The full L4 stage: winsorize every sub-factor, build composites, then
    orthogonalize (order per ``Barra_factor_cal/main.py:72-86``).

    ``composite_config``: (name, components, weights) triples;
    ``ortho_rules``: (target, regressors) pairs — the shapes used by
    :class:`mfm_tpu.config.FactorConfig`.
    """
    out = dict(factors)
    cols = winsorize_cols if winsorize_cols is not None else list(out)
    for name in cols:
        out[name] = winsorize_panel(out[name], n_std=n_std)
    for new_name, comps, weights in composite_config:
        present = [(c, w) for c, w in zip(comps, weights) if c in out]
        out[new_name] = composite_factor(
            [out[c] for c, _ in present], [w for _, w in present]
        )
    for target, regs in ortho_rules:
        out[target] = orthogonalize(out[target], [out[r] for r in regs])
    return out
