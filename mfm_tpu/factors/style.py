"""The 16 Barra sub-factor kernels.

Each function is a pure array op over dense panels; the FactorEngine prepares
inputs (including *row-space* packing: the reference's long frame has rows
only for days a stock actually traded, so its per-stock rolling windows span
the stock's own trading days — the engine compresses each stock's observed
days to the front of the array, runs the rolling kernels there, and scatters
back; see :mod:`mfm_tpu.factors.engine`).

Exact contracts per sub-factor: SURVEY.md §2.3, citing
``Barra_factor_cal/factor_calculator.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from mfm_tpu.config import FactorConfig
from mfm_tpu.ops.masked import masked_ols_residuals
from mfm_tpu.ops.rolling import (
    rolling_beta_hsigma,
    rolling_cmra,
    rolling_decay_weighted_mean,
    rolling_sum,
    rolling_weighted_std,
)


def compute_size(total_mv: jax.Array) -> jax.Array:
    """SIZE = ln(total market value) (``factor_calculator.py:68-77``)."""
    return jnp.log(total_mv)


def compute_beta_hsigma(ret, market_ret, cfg: FactorConfig = FactorConfig(), *,
                        block=64, impl="scan"):
    """BETA/HSIGMA: rolling WLS slope + residual std
    (``factor_calculator.py:79-125``)."""
    s = cfg.beta
    return rolling_beta_hsigma(
        ret, market_ret,
        window=s.window, half_life=s.half_life, min_periods=s.min_periods,
        block=block, impl=impl,
    )


def compute_rstr(log_ret, cfg: FactorConfig = FactorConfig(), *,
                 block=64, impl="scan"):
    """RSTR momentum: lagged, head-aligned decay-weighted mean of log returns
    (``factor_calculator.py:127-153``).  The L-day skip is a shift along the
    stock's own row sequence (``x.shift(L)``)."""
    L = cfg.rstr_lag
    window = cfg.rstr_total - L
    shifted = jnp.concatenate(
        [jnp.full((L,) + log_ret.shape[1:], jnp.nan, log_ret.dtype), log_ret[:-L]],
        axis=0,
    )
    return rolling_decay_weighted_mean(
        shifted,
        window=window, half_life=cfg.rstr_half_life,
        min_periods=cfg.rstr_min_periods, block=block, impl=impl,
    )


def compute_dastd(ret, market_ret, cfg: FactorConfig = FactorConfig(), *,
                  block=64, impl="scan"):
    """DASTD: exp-weighted std of excess returns
    (``factor_calculator.py:155-196``)."""
    if market_ret.ndim == 1:
        market_ret = market_ret[:, None]
    s = cfg.dastd
    return rolling_weighted_std(
        ret - market_ret,
        window=s.window, half_life=s.half_life, min_periods=s.min_periods,
        block=block, impl=impl,
    )


def compute_cmra(log_ret, cfg: FactorConfig = FactorConfig(), *,
                 block=64, impl="scan"):
    """CMRA: cumulative-return range over a fully-observed window
    (``factor_calculator.py:199-234``)."""
    return rolling_cmra(log_ret, window=cfg.cmra_window, block=block,
                        impl=impl)


def compute_nlsize(size: jax.Array, valid=None) -> jax.Array:
    """NLSIZE: minus the residual of the per-date cross-sectional OLS of
    SIZE^3 on SIZE (``factor_calculator.py:237-293``); needs >= 2 valid.

    Computed in the centered basis: with m the cross-sectional mean and
    z = SIZE - m, the 3*m^2*z + m^3 part of SIZE^3 lies in span{1, SIZE},
    so resid(SIZE^3) = resid(z^3 + 3*m*z^2) — algebraically identical
    (the golden parity test pins it), but the regressed magnitudes drop
    from O(m^3) ~ 1e3 to O(1), which removes the catastrophic f32
    cancellation of the raw form (measured ~0.19 absolute TPU-vs-CPU
    drift on a 16-stock cross-section; centered ~1e-5).
    """
    def one(s, v):
        n = jnp.sum(v)
        m = jnp.sum(jnp.where(v, s, 0.0)) / jnp.maximum(n, 1)
        z = jnp.where(v, s - m, 0.0)
        y = z**3 + 3.0 * m * z**2
        return -masked_ols_residuals(y, z[:, None], v, min_valid=2)

    # intersect with finiteness so a caller mask that marks a NaN size as
    # valid drops that row (as the raw form's internal isfinite did) rather
    # than NaN-poisoning the whole date through the mean
    valid = (jnp.isfinite(size) if valid is None
             else valid & jnp.isfinite(size))
    return jax.vmap(one)(size, valid)


def compute_bp(pb: jax.Array) -> jax.Array:
    """BP = 1/pb where pb > 0 (``factor_calculator.py:295-321``)."""
    return jnp.where(pb > 0, 1.0 / pb, jnp.nan)


def compute_liquidity(turnover_rate, cfg: FactorConfig = FactorConfig(), *,
                      block=64, impl="scan"):
    """STOM/STOQ/STOA: log rolling sums of daily turnover (percent/100),
    zero sums -> NaN before the log (``factor_calculator.py:324-367``)."""
    dtv = turnover_rate / 100.0
    out = {}
    for name, spec in (("STOM", cfg.stom), ("STOQ", cfg.stoq), ("STOA", cfg.stoa)):
        base = rolling_sum(
            dtv, window=spec.window, min_periods=spec.min_periods,
            block=block, impl=impl,
        )
        out[name] = jnp.log(jnp.where(base == 0.0, jnp.nan, base))
    return out


def ttm_rolling4(values: jax.Array, report_id: jax.Array):
    """Trailing-twelve-month values: rolling 4-quarter sum over each stock's
    sequence of *distinct* reports, mapped back to days.

    Contract (``factor_calculator.py:392-412``): unique (stock, report) rows,
    sorted by report date, ``rolling(4, min_periods=4).sum()`` (so all 4 of
    the last 4 reports must be present and non-NaN), joined back to days by
    report id.  ``report_id`` is any int that changes when the report changes
    (< 0 = no report that day).

    One lax.scan over time, vmapped across stocks via lane-wise ops.
    """
    T, N = values.shape
    dtype = values.dtype

    def step(carry, inp):
        prev_id, ring = carry  # ring: (4, N) most recent last
        v, rid = inp
        push = (rid != prev_id) & (rid >= 0)
        new_ring = jnp.concatenate([ring[1:], v[None, :]], axis=0)
        ring = jnp.where(push[None, :], new_ring, ring)
        ttm = jnp.sum(ring, axis=0)
        ok = (rid >= 0) & jnp.all(jnp.isfinite(ring), axis=0)
        out = jnp.where(ok, ttm, jnp.nan)
        prev_id = jnp.where(rid >= 0, rid, prev_id)
        return (prev_id, ring), out

    init = (
        jnp.full((N,), -2, report_id.dtype),
        jnp.full((4, N), jnp.nan, dtype),
    )
    _, ttm = jax.lax.scan(step, init, (values, report_id))
    return ttm


def compute_earnings_yield(cashflow_ttm, total_mv, pe_ttm):
    """CETOP = TTM operating cashflow / total_mv (both must be > 0);
    ETOP = 1/pe_ttm where pe_ttm > 0 (``factor_calculator.py:371-434``)."""
    cetop = jnp.where(
        (total_mv > 0) & (cashflow_ttm > 0), cashflow_ttm / total_mv, jnp.nan
    )
    etop = jnp.where(pe_ttm > 0, 1.0 / pe_ttm, jnp.nan)
    return cetop, etop


def compute_growth(q_profit_yoy, q_sales_yoy):
    """YOYProfit/YOYSales: percent -> ratio passthrough
    (``factor_calculator.py:436-462``)."""
    return q_profit_yoy / 100.0, q_sales_yoy / 100.0


def compute_leverage(total_mv, total_ncl, book_value, debt_to_assets):
    """MLEV/DTOA/BLEV (``factor_calculator.py:464-509``): MLEV maps +-inf
    (zero market cap) to NaN; BLEV requires positive book value."""
    mlev = (total_mv + total_ncl) / total_mv
    mlev = jnp.where(jnp.isinf(mlev), jnp.nan, mlev)
    dtoa = debt_to_assets
    blev = jnp.where(book_value > 0, (book_value + total_ncl) / book_value, jnp.nan)
    return mlev, dtoa, blev
