"""FactorEngine — the batched equivalent of the reference's
``FactorCalculator.run`` (``Barra_factor_cal/factor_calculator.py:515-577``).

Row-space semantics
-------------------
The reference's master frame has one row per (stock, traded day): a stock's
rolling windows span *its own* trading days, skipping suspensions entirely
(``groupby('ts_code').rolling(...)``).  To reproduce that with dense (T, N)
arrays, the engine packs each stock's observed days to the front of the time
axis ("row space"), runs every rolling kernel there, and scatters results
back to calendar positions.  Cross-sectional factors (NLSIZE) and all
post-processing run in calendar space.  Returns are computed in row space
(close-over-previous-traded-close, like pandas ``pct_change`` within the
group, ``factor_calculator.py:50-51``).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from mfm_tpu.config import FactorConfig
from mfm_tpu.factors import style
from mfm_tpu.ops.rolling import auto_block
from mfm_tpu.factors.post import apply_post_processing


# ---------------------------------------------------------------------------
# row-space packing
# ---------------------------------------------------------------------------

def rowspace_index(observed: jax.Array) -> jax.Array:
    """(T, N) bool -> (T, N) int32: row r of stock n holds the calendar index
    of its r-th observed day, or -1 past the end."""
    T = observed.shape[0]
    t = jnp.arange(T, dtype=jnp.int32)[:, None]
    key = jnp.where(observed, t, T + t)  # observed days sort first, in order
    order = jnp.argsort(key, axis=0).astype(jnp.int32)
    nobs = jnp.sum(observed, axis=0)
    return jnp.where(t < nobs[None, :], order, -1)


def gather_rows(x: jax.Array, idx: jax.Array) -> jax.Array:
    """Pack calendar-space (T, ...) data into row space via idx."""
    safe = jnp.maximum(idx, 0)
    if x.ndim == 1:  # per-date data (e.g. market return): broadcast per stock
        g = x[safe]
    else:
        g = jnp.take_along_axis(x, safe, axis=0)
    return jnp.where(idx >= 0, g, jnp.nan)


def scatter_rows(f: jax.Array, idx: jax.Array) -> jax.Array:
    """Unpack row-space results back to calendar positions (inverse gather)."""
    T, N = f.shape
    safe = jnp.where(idx >= 0, idx, T)
    out = jnp.full((T + 1, N), jnp.nan, f.dtype)
    out = out.at[safe, jnp.arange(N, dtype=jnp.int32)[None, :]].set(
        jnp.where(idx >= 0, f, jnp.nan))
    return out[:T]


@dataclasses.dataclass
class FactorEngine:
    """Compute the 16 sub-factors + composites over a dense panel.

    Required fields (dict of (T, N) float arrays, NaN = missing; names follow
    the tushare columns the reference joins, SURVEY.md §2.3):
      close, total_mv, circ_mv, turnover_rate, pb, pe_ttm, n_cashflow_act,
      end_date_code (int report id, -1 = none), q_profit_yoy, q_sales_yoy,
      total_ncl, total_hldr_eqy_inc_min_int, debt_to_assets
    plus index_close: (T,) market index closes.
    """

    fields: Dict[str, jax.Array]
    index_close: jax.Array
    config: FactorConfig = dataclasses.field(default_factory=FactorConfig)
    #: rolling date-block size; None = auto from the panel width
    #: (ops/rolling.py::auto_block).  Only used by the "block" impl.
    block: int | None = None
    #: rolling-kernel implementation: "scan" (O(T*N) two-level chunked
    #: scans, the default) or "block" (windowed gathers, the reference
    #: formulation; memory bounded by ``block``)
    rolling_impl: str = "scan"

    def __post_init__(self):
        if self.block is None:
            close = self.fields["close"]
            cfg = self.config
            # budget for THIS config's widest rolling kernel, not the
            # default windows (rstr_total = window + lag, the upper bound)
            widest = max(cfg.beta.window, cfg.rstr_total, cfg.dastd.window,
                         cfg.cmra_window, cfg.stoa.window)
            self.block = auto_block(close.shape[1], window=widest,
                                    itemsize=close.dtype.itemsize)

    def run(self, factors=None, post_process: bool = True) -> Dict[str, jax.Array]:
        factors = tuple(factors or self.config.factors_to_run)
        fn = partial(
            _run_jit, config=self.config, block=self.block,
            impl=self.rolling_impl, factors=factors,
            post_process=post_process,
        )
        return fn(self.fields, self.index_close)


@partial(jax.jit, static_argnames=("config", "block", "impl", "factors",
                                   "post_process"))
def _run_jit(fields, index_close, *, config, block, impl, factors, post_process):
    f = fields
    cfg = config
    close = f["close"]
    observed = jnp.isfinite(close)
    idx = rowspace_index(observed)

    # returns in row space: previous traded day, like groupby pct_change
    rs_close = gather_rows(close, idx)
    rs_ret = rs_close / jnp.concatenate(
        [jnp.full((1, close.shape[1]), jnp.nan, close.dtype), rs_close[:-1]], axis=0
    ) - 1.0
    rs_logret = jnp.log1p(rs_ret)
    market_ret = index_close / jnp.concatenate(
        [jnp.full((1,), jnp.nan, index_close.dtype), index_close[:-1]]
    ) - 1.0
    rs_market = gather_rows(market_ret, idx)

    out: Dict[str, jax.Array] = {
        "ret": scatter_rows(rs_ret, idx),
        "log_ret": scatter_rows(rs_logret, idx),
    }

    for name in factors:
        name = name.upper()
        if name == "SIZE":
            out["SIZE"] = style.compute_size(f["total_mv"])
        elif name == "BETA":
            beta, hsigma = style.compute_beta_hsigma(
                rs_ret, rs_market, cfg, block=block, impl=impl
            )
            out["BETA"] = scatter_rows(beta, idx)
            out["HSIGMA"] = scatter_rows(hsigma, idx)
        elif name == "RSTR":
            out["RSTR"] = scatter_rows(
                style.compute_rstr(rs_logret, cfg, block=block, impl=impl), idx
            )
        elif name == "DASTD":
            out["DASTD"] = scatter_rows(
                style.compute_dastd(rs_ret, rs_market, cfg, block=block,
                                    impl=impl), idx
            )
        elif name == "CMRA":
            out["CMRA"] = scatter_rows(
                style.compute_cmra(rs_logret, cfg, block=block, impl=impl), idx
            )
        elif name == "NLSIZE":
            out["NLSIZE"] = style.compute_nlsize(jnp.log(f["total_mv"]))
        elif name == "BP":
            out["BP"] = style.compute_bp(f["pb"])
        elif name == "LIQUIDITY":
            rs_turn = gather_rows(f["turnover_rate"], idx)
            for k, v in style.compute_liquidity(rs_turn, cfg, block=block,
                                                 impl=impl).items():
                out[k] = scatter_rows(v, idx)
        elif name == "EARNINGS":
            rs_cash = gather_rows(f["n_cashflow_act"], idx)
            rs_rid = jnp.where(
                idx >= 0,
                jnp.take_along_axis(f["end_date_code"], jnp.maximum(idx, 0), axis=0),
                -1,
            )
            ttm = style.ttm_rolling4(rs_cash, rs_rid)
            cetop, etop = style.compute_earnings_yield(
                scatter_rows(ttm, idx), f["total_mv"], f["pe_ttm"]
            )
            out["CETOP"] = cetop
            out["ETOP"] = etop
        elif name == "GROWTH":
            out["YOYProfit"], out["YOYSales"] = style.compute_growth(
                f["q_profit_yoy"], f["q_sales_yoy"]
            )
        elif name == "LEVERAGE":
            mlev, dtoa, blev = style.compute_leverage(
                f["total_mv"], f["total_ncl"],
                f["total_hldr_eqy_inc_min_int"], f["debt_to_assets"],
            )
            out["MLEV"], out["DTOA"], out["BLEV"] = mlev, dtoa, blev
        else:
            raise ValueError(f"unknown factor {name!r}")

    if post_process:
        sub = {k: v for k, v in out.items() if k not in ("ret", "log_ret")}
        processed = apply_post_processing(
            sub, cfg.composite, cfg.ortho_rules, n_std=cfg.winsorize_n_std
        )
        out.update(processed)
    return out
