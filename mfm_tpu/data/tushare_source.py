"""Tushare Pro adapter: the L0 fetch layer.

Wraps the same 10 endpoints the reference wraps
(``Barra_database/database/tushare_fetcher.py``), but with the token taken
from the environment (the reference hardcodes it in four files,
``tushare_fetcher.py:7``) and the client injectable for tests.  The field
lists are the tushare API column enumerations the pipeline consumes
(``tushare_fetcher.py:49-83,100-139,155-186,203-228``).

tushare is not installed in this image; constructing :class:`TushareSource`
without a client raises a clear error, and everything downstream
(:mod:`mfm_tpu.data.etl`) accepts any object with the same fetch methods.
"""

from __future__ import annotations

import os

BALANCESHEET_FIELDS = (
    "ts_code,ann_date,f_ann_date,end_date,report_type,comp_type,"
    "total_share,cap_rese,undistr_porfit,surplus_rese,special_rese,"
    "money_cap,total_assets,total_liab,total_hldr_eqy_inc_min_int,"
    "total_ncl,total_cur_liab"
)
CASHFLOW_FIELDS = (
    "ts_code,ann_date,f_ann_date,end_date,comp_type,report_type,"
    "net_profit,finan_exp,c_fr_sale_sg,c_inf_fr_operate_a,"
    "n_cashflow_act,n_cashflow_inv_act,n_cash_flows_fnc_act"
)
INCOME_FIELDS = (
    "ts_code,ann_date,f_ann_date,end_date,report_type,comp_type,"
    "basic_eps,diluted_eps,total_revenue,revenue,operate_profit,"
    "total_profit,n_income,n_income_attr_p"
)
FINA_INDICATOR_FIELDS = (
    "ts_code,ann_date,end_date,eps,dt_eps,total_revenue_ps,revenue_ps,"
    "bps,roe,roa,npta,debt_to_assets,q_profit_yoy,q_sales_yoy,"
    "q_op_yoy,ocf_yoy,roe_yoy"
)
DAILY_BASIC_FIELDS = (
    "ts_code,trade_date,close,turnover_rate,turnover_rate_f,volume_ratio,"
    "pe,pe_ttm,pb,ps,ps_ttm,dv_ratio,dv_ttm,total_share,float_share,"
    "free_share,total_mv,circ_mv"
)


class TushareSource:
    """Fetch methods named to match :class:`mfm_tpu.data.etl.IncrementalUpdater`."""

    def __init__(self, client=None, token: str | None = None):
        if client is None:
            try:
                import tushare as ts
            except ImportError as e:  # pragma: no cover
                raise ImportError(
                    "tushare is not installed; pass an explicit client or use "
                    "a fake source"
                ) from e
            token = token or os.environ.get("TUSHARE_TOKEN")
            if not token:
                raise ValueError("set TUSHARE_TOKEN or pass token=")
            ts.set_token(token)
            client = ts.pro_api()
        self.pro = client

    # --- market data -----------------------------------------------------
    def fetch_stock_info(self):
        return self.pro.stock_basic(exchange="", list_status="L",
                                    fields="ts_code,symbol,name,area,industry,list_date")

    def fetch_daily_prices(self, trade_date):
        return self.pro.daily_basic(trade_date=trade_date,
                                    fields=DAILY_BASIC_FIELDS)

    def fetch_daily_prices_by_stock(self, ts_code, start_date=None,
                                    end_date=None):
        # the repair tool's per-stock variant (fill_missing_data.py:58)
        return self.pro.daily_basic(ts_code=ts_code, start_date=start_date,
                                    end_date=end_date,
                                    fields=DAILY_BASIC_FIELDS)

    def fetch_trade_calendar(self, start_date, end_date):
        cal = self.pro.trade_cal(exchange="SSE", start_date=start_date,
                                 end_date=end_date, is_open="1")
        return list(cal["cal_date"])

    # --- statements (per stock) -----------------------------------------
    def fetch_balancesheet_by_stock(self, ts_code, start_date=None, end_date=None):
        return self.pro.balancesheet(ts_code=ts_code, start_date=start_date,
                                     end_date=end_date, fields=BALANCESHEET_FIELDS)

    def fetch_cashflow_by_stock(self, ts_code, start_date=None, end_date=None):
        return self.pro.cashflow(ts_code=ts_code, start_date=start_date,
                                 end_date=end_date, fields=CASHFLOW_FIELDS)

    def fetch_income_by_stock(self, ts_code, start_date=None, end_date=None):
        return self.pro.income(ts_code=ts_code, start_date=start_date,
                               end_date=end_date, fields=INCOME_FIELDS)

    def fetch_financial_indicators_by_stock(self, ts_code, start_date=None,
                                            end_date=None):
        return self.pro.fina_indicator(ts_code=ts_code, start_date=start_date,
                                       end_date=end_date,
                                       fields=FINA_INDICATOR_FIELDS)

    # --- indices ---------------------------------------------------------
    def fetch_index_info(self):
        return self.pro.index_basic(market="SSE")

    def fetch_daily_index_prices(self, ts_code, start_date=None, end_date=None):
        return self.pro.index_daily(ts_code=ts_code, start_date=start_date,
                                    end_date=end_date)

    def fetch_index_components(self, index_code, trade_date):
        return self.pro.index_weight(index_code=index_code,
                                     trade_date=trade_date)

    def fetch_sw_industries(self, ts_code):
        return self.pro.index_member_all(ts_code=ts_code)
