"""Barra-format table <-> dense risk-model arrays.

The reference's risk model eats a long CSV with columns
``date, stocknames, capital, ret, industry, <10 styles>``
(``result/barra_data_csi.csv``, consumed at ``Barra-master/demo.py:22-38``),
drops any row containing any NaN (``demo.py:25-27``) and one-hot encodes the
industry column against an ``industry_info.csv`` code list (``demo.py:32-35``).

Here the same table densifies into (T, N) arrays + a validity mask; the
drop-any-NaN rule becomes the mask.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


@dataclasses.dataclass
class BarraArrays:
    """Dense inputs of :class:`mfm_tpu.models.RiskModel` plus metadata."""

    dates: np.ndarray       # (T,) as given (string/datetime), sorted ascending
    stocks: np.ndarray      # (N,) sorted ascending (MFM sorts by stockname, MFM.py:59)
    ret: np.ndarray         # (T, N)
    cap: np.ndarray         # (T, N)
    styles: np.ndarray      # (T, N, Q)
    industry: np.ndarray    # (T, N) int in [0, P), -1 where missing
    valid: np.ndarray       # (T, N) bool
    industry_codes: np.ndarray  # (P,) the code list (one-hot column order)
    style_names: list

    @property
    def n_industries(self) -> int:
        return len(self.industry_codes)

    def factor_names(self) -> list:
        return ["country"] + list(map(str, self.industry_codes)) + list(self.style_names)


def barra_frame_to_arrays(
    df,
    industry_codes: Sequence | None = None,
    style_names: Sequence[str] | None = None,
    drop_any_nan: bool = True,
    dtype=np.float64,
    stocks: Sequence | None = None,
) -> BarraArrays:
    """Densify a barra-format long DataFrame.

    ``industry_codes`` fixes the one-hot column order (the reference reads it
    from ``industry_info.csv``, ``demo.py:32-35``); default: sorted unique
    codes present.  ``drop_any_nan`` applies the reference's row filter
    (``demo.py:25-27``).  ``stocks`` pins the stock axis to a given ordered
    list (the incremental append path aligns a new-date slab to the
    checkpoint's stock universe this way): stocks absent from ``df`` become
    all-invalid columns, and stocknames outside the list raise — a new
    listing silently dropped from a resumed history would desync every
    column after it.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    base_cols = ["date", "stocknames", "capital", "ret", "industry"]
    if style_names is None:
        style_names = [c for c in df.columns if c not in base_cols]
    if drop_any_nan:
        df = df.dropna(how="any")
    if not len(df):
        # fail with the cause, not a downstream IndexError from empty axes —
        # the usual culprit is a slab cut entirely inside the factor-warmup
        # region, where every style column is still NaN
        raise ValueError(
            "no rows survive the NaN row filter (drop_any_nan): every row "
            "has at least one missing field — check that the slab's dates "
            "lie beyond the style-factor warmup region")
    dates = np.sort(df["date"].unique())
    if stocks is None:
        stocks = np.sort(df["stocknames"].unique())
    else:
        stocks = np.asarray(stocks)
        unknown = np.setdiff1d(df["stocknames"].unique(), stocks)
        if unknown.size:
            raise ValueError(
                f"stocknames not in the pinned stock axis: "
                f"{list(unknown[:5])}{'...' if unknown.size > 5 else ''} — "
                "a pinned (checkpoint-aligned) densification cannot admit "
                "new stocks")
    if industry_codes is None:
        industry_codes = np.sort(df["industry"].unique())
    industry_codes = np.asarray(industry_codes)

    t_idx = {d: i for i, d in enumerate(dates)}
    s_idx = {s: j for j, s in enumerate(stocks)}
    code_idx = {c: p for p, c in enumerate(industry_codes)}
    T, N, Q = len(dates), len(stocks), len(style_names)

    ti = df["date"].map(t_idx).to_numpy()
    si = df["stocknames"].map(s_idx).to_numpy()

    ret = np.full((T, N), np.nan, dtype)
    cap = np.full((T, N), np.nan, dtype)
    styles = np.full((T, N, Q), np.nan, dtype)
    industry = np.full((T, N), -1, np.int32)
    valid = np.zeros((T, N), bool)

    ret[ti, si] = df["ret"].to_numpy(dtype)
    cap[ti, si] = df["capital"].to_numpy(dtype)
    for q, name in enumerate(style_names):
        styles[ti, si, q] = df[name].to_numpy(dtype)
    industry[ti, si] = df["industry"].map(code_idx).fillna(-1).to_numpy(np.int32)
    valid[ti, si] = True
    # rows whose industry code is not in the code list are invalid (the
    # reference's one-hot against industry_info simply yields all-zero dummies
    # there; we exclude them outright and document the difference)
    valid &= industry >= 0

    return BarraArrays(
        dates=dates, stocks=stocks, ret=ret, cap=cap, styles=styles,
        industry=industry, valid=valid,
        industry_codes=industry_codes, style_names=list(style_names),
    )


def load_barra_csv(path, industry_info_path=None, **kw) -> BarraArrays:
    """Load the reference's CSV schema directly (``demo.py:22-35``)."""
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    df = pd.read_csv(path)
    codes = None
    if industry_info_path is not None:
        codes = pd.read_csv(industry_info_path)["code"].to_numpy()
    return barra_frame_to_arrays(df, industry_codes=codes, **kw)
