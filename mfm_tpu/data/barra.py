"""Barra-format table <-> dense risk-model arrays.

The reference's risk model eats a long CSV with columns
``date, stocknames, capital, ret, industry, <10 styles>``
(``result/barra_data_csi.csv``, consumed at ``Barra-master/demo.py:22-38``),
drops any row containing any NaN (``demo.py:25-27``) and one-hot encodes the
industry column against an ``industry_info.csv`` code list (``demo.py:32-35``).

Here the same table densifies into (T, N) arrays + a validity mask; the
drop-any-NaN rule becomes the mask.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


@dataclasses.dataclass
class BarraArrays:
    """Dense inputs of :class:`mfm_tpu.models.RiskModel` plus metadata."""

    dates: np.ndarray       # (T,) as given (string/datetime), sorted ascending
    stocks: np.ndarray      # (N,) sorted ascending (MFM sorts by stockname, MFM.py:59)
    ret: np.ndarray         # (T, N)
    cap: np.ndarray         # (T, N)
    styles: np.ndarray      # (T, N, Q)
    industry: np.ndarray    # (T, N) int in [0, P), -1 where missing
    valid: np.ndarray       # (T, N) bool
    industry_codes: np.ndarray  # (P,) the code list (one-hot column order)
    style_names: list

    @property
    def n_industries(self) -> int:
        return len(self.industry_codes)

    def factor_names(self) -> list:
        return ["country"] + list(map(str, self.industry_codes)) + list(self.style_names)


@dataclasses.dataclass
class BarraCOO:
    """Row-space (COO) form of a barra long table: the axes plus one entry
    per surviving table row, WITHOUT the dense (T, N) panels.

    This is the shard-local ingest representation: ``block`` densifies any
    (date, stock) rectangle on demand, so a mesh run materializes each
    device's block via ``jax.make_array_from_callback`` and the host never
    allocates a full dense panel (at all-A scale the five f64 panels are
    ~1.5 GB — the ingest cost the ISSUE-11 refactor removes).  Cells not
    covered by any row — including mesh-padding cells past the real (T, N)
    extent — densify to missing data (NaN / industry -1 / valid False),
    which the model's masked design already treats as inert.
    """

    dates: np.ndarray           # (T,) sorted ascending
    stocks: np.ndarray          # (N,) sorted ascending
    industry_codes: np.ndarray  # (P,)
    style_names: list
    ti: np.ndarray              # (R,) int  date index per row
    si: np.ndarray              # (R,) int  stock index per row
    ret_v: np.ndarray           # (R,)
    cap_v: np.ndarray           # (R,)
    styles_v: np.ndarray        # (R, Q)
    industry_v: np.ndarray      # (R,) int in [0, P), -1 for unknown codes

    @property
    def n_industries(self) -> int:
        return len(self.industry_codes)

    def factor_names(self) -> list:
        return (["country"] + list(map(str, self.industry_codes))
                + list(self.style_names))

    def block(self, t0: int, t1: int, s0: int, s1: int,
              dtype=np.float64) -> dict:
        """Densify rows falling in ``[t0, t1) x [s0, s1)`` into local
        ``(t1-t0, s1-s0)`` panels (keys: ret/cap/styles/industry/valid).
        The rectangle may extend past (T, N) — the overhang is padding and
        densifies to missing data."""
        keep = (self.ti >= t0) & (self.ti < t1) \
            & (self.si >= s0) & (self.si < s1)
        ti, si = self.ti[keep] - t0, self.si[keep] - s0
        t, n, q = t1 - t0, s1 - s0, len(self.style_names)
        ret = np.full((t, n), np.nan, dtype)
        cap = np.full((t, n), np.nan, dtype)
        styles = np.full((t, n, q), np.nan, dtype)
        industry = np.full((t, n), -1, np.int32)
        valid = np.zeros((t, n), bool)
        ret[ti, si] = self.ret_v[keep].astype(dtype)
        cap[ti, si] = self.cap_v[keep].astype(dtype)
        styles[ti, si] = self.styles_v[keep].astype(dtype)
        industry[ti, si] = self.industry_v[keep]
        valid[ti, si] = True
        valid &= industry >= 0
        return {"ret": ret, "cap": cap, "styles": styles,
                "industry": industry, "valid": valid}

    def to_arrays(self, dtype=np.float64) -> BarraArrays:
        """The classic full densification (one block covering everything)."""
        b = self.block(0, len(self.dates), 0, len(self.stocks), dtype)
        return BarraArrays(
            dates=self.dates, stocks=self.stocks, ret=b["ret"], cap=b["cap"],
            styles=b["styles"], industry=b["industry"], valid=b["valid"],
            industry_codes=self.industry_codes,
            style_names=list(self.style_names),
        )


def barra_frame_to_coo(
    df,
    industry_codes: Sequence | None = None,
    style_names: Sequence[str] | None = None,
    drop_any_nan: bool = True,
    stocks: Sequence | None = None,
) -> BarraCOO:
    """Long DataFrame -> :class:`BarraCOO` (row space, no dense panels).

    ``industry_codes`` fixes the one-hot column order (the reference reads it
    from ``industry_info.csv``, ``demo.py:32-35``); default: sorted unique
    codes present.  ``drop_any_nan`` applies the reference's row filter
    (``demo.py:25-27``).  ``stocks`` pins the stock axis to a given ordered
    list (the incremental append path aligns a new-date slab to the
    checkpoint's stock universe this way): stocks absent from ``df`` become
    all-invalid columns, and stocknames outside the list raise — a new
    listing silently dropped from a resumed history would desync every
    column after it.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    base_cols = ["date", "stocknames", "capital", "ret", "industry"]
    if style_names is None:
        style_names = [c for c in df.columns if c not in base_cols]
    if drop_any_nan:
        df = df.dropna(how="any")
    if not len(df):
        # fail with the cause, not a downstream IndexError from empty axes —
        # the usual culprit is a slab cut entirely inside the factor-warmup
        # region, where every style column is still NaN
        raise ValueError(
            "no rows survive the NaN row filter (drop_any_nan): every row "
            "has at least one missing field — check that the slab's dates "
            "lie beyond the style-factor warmup region")
    dates = np.sort(df["date"].unique())
    if stocks is None:
        stocks = np.sort(df["stocknames"].unique())
    else:
        stocks = np.asarray(stocks)
        unknown = np.setdiff1d(df["stocknames"].unique(), stocks)
        if unknown.size:
            raise ValueError(
                f"stocknames not in the pinned stock axis: "
                f"{list(unknown[:5])}{'...' if unknown.size > 5 else ''} — "
                "a pinned (checkpoint-aligned) densification cannot admit "
                "new stocks")
    if industry_codes is None:
        industry_codes = np.sort(df["industry"].unique())
    industry_codes = np.asarray(industry_codes)

    t_idx = {d: i for i, d in enumerate(dates)}
    s_idx = {s: j for j, s in enumerate(stocks)}
    code_idx = {c: p for p, c in enumerate(industry_codes)}

    return BarraCOO(
        dates=dates, stocks=stocks, industry_codes=industry_codes,
        style_names=list(style_names),
        ti=df["date"].map(t_idx).to_numpy(),
        si=df["stocknames"].map(s_idx).to_numpy(),
        ret_v=df["ret"].to_numpy(np.float64),
        cap_v=df["capital"].to_numpy(np.float64),
        styles_v=np.stack([df[n].to_numpy(np.float64)
                           for n in style_names], axis=-1)
        if style_names else np.zeros((len(df), 0)),
        industry_v=df["industry"].map(code_idx).fillna(-1)
        .to_numpy(np.int32),
    )


def barra_frame_to_arrays(
    df,
    industry_codes: Sequence | None = None,
    style_names: Sequence[str] | None = None,
    drop_any_nan: bool = True,
    dtype=np.float64,
    stocks: Sequence | None = None,
) -> BarraArrays:
    """Densify a barra-format long DataFrame (single-host dense path).

    The row-space step and the filling rules live in
    :func:`barra_frame_to_coo` / :meth:`BarraCOO.block`, shared with the
    shard-local mesh ingest — the two paths cannot drift.
    """
    return barra_frame_to_coo(
        df, industry_codes=industry_codes, style_names=style_names,
        drop_any_nan=drop_any_nan, stocks=stocks,
    ).to_arrays(dtype)


def load_barra_csv(path, industry_info_path=None, **kw) -> BarraArrays:
    """Load the reference's CSV schema directly (``demo.py:22-35``)."""
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    df = pd.read_csv(path)
    codes = None
    if industry_info_path is not None:
        codes = pd.read_csv(industry_info_path)["code"].to_numpy()
    return barra_frame_to_arrays(df, industry_codes=codes, **kw)
