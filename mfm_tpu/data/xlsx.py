"""Static-workbook ingestion: a dependency-free xlsx reader + loaders for
the reference's two shipped workbooks.

The reference ships ``data/index_list.xlsx`` (a tushare ``index_basic``
export: ts_code/name/market/publisher/..., inline-string cells) and
``data/industry_index_data.xlsx`` (a Wind EDB export of CITIC 中信 and
SW 申万 L1 industry index daily closes: a banner row, a header row of
series names, two meta rows (frequency/unit), then rows of
[excel-date-serial, close...]) as pipeline inputs (SURVEY.md §2.1 "Static
data").  This image carries no openpyxl/xlrd, and the files are plain
zip+XML — so the reader below implements exactly the subset those
workbooks use: shared strings, inline strings, cached formula strings,
numbers, and sheet resolution by name or index.

    from mfm_tpu.data.xlsx import read_xlsx, ingest_workbooks
    ingest_workbooks(store, index_list="data/index_list.xlsx",
                     industry_index="data/industry_index_data.xlsx")

storing ``index_list`` (one row per index) and ``industry_index_prices``
(long (index_name, trade_date, close) rows, yyyymmdd dates — the same
storage format as every other collection).
"""

from __future__ import annotations

import datetime
import re
import zipfile
from typing import Dict, List
from xml.etree import ElementTree

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None

_NS = "{http://schemas.openxmlformats.org/spreadsheetml/2006/main}"
_REL_NS = ("{http://schemas.openxmlformats.org/officeDocument/2006/"
           "relationships}")

#: Excel's day-serial epoch (the 1900 system, with its phantom 1900-02-29
#: already absorbed — serial 1 = 1900-01-01, so the base is 1899-12-30)
_EPOCH = datetime.date(1899, 12, 30)


def excel_serial_to_date(serial: float) -> datetime.date:
    return _EPOCH + datetime.timedelta(days=int(serial))


def _col_index(ref: str) -> int:
    """'A1' -> 0, 'AB17' -> 27."""
    n = 0
    for ch in ref:
        if not ch.isalpha():
            break
        n = n * 26 + (ord(ch.upper()) - ord("A") + 1)
    return n - 1


def _sheet_path(z: zipfile.ZipFile, sheet) -> str:
    """Resolve a sheet name or 0-based index to its archive member via
    workbook.xml + its rels (sheet order need not match file numbering)."""
    wb = ElementTree.fromstring(z.read("xl/workbook.xml"))
    rels = ElementTree.fromstring(z.read("xl/_rels/workbook.xml.rels"))
    rel_to_target = {
        r.get("Id"): r.get("Target")
        for r in rels.iter(f"{{http://schemas.openxmlformats.org/package/"
                           f"2006/relationships}}Relationship")
    }
    sheets = wb.find(f"{_NS}sheets")
    entries = [(s.get("name"), rel_to_target[s.get(f"{_REL_NS}id")])
               for s in sheets]
    if isinstance(sheet, int):
        if not 0 <= sheet < len(entries):
            raise ValueError(f"sheet index {sheet} out of range "
                             f"({len(entries)} sheets)")
        target = entries[sheet][1]
    else:
        matches = [t for n, t in entries if n == sheet]
        if not matches:
            raise ValueError(f"no sheet named {sheet!r}; have "
                             f"{[n for n, _ in entries]}")
        target = matches[0]
    target = target.lstrip("/")
    return target if target.startswith("xl/") else "xl/" + target


def _shared_strings(z: zipfile.ZipFile) -> List[str]:
    if "xl/sharedStrings.xml" not in z.namelist():
        return []
    root = ElementTree.fromstring(z.read("xl/sharedStrings.xml"))
    out = []
    for si in root.iter(f"{_NS}si"):
        # rich-text runs split one string across <r><t> chunks
        out.append("".join(t.text or "" for t in si.iter(f"{_NS}t")))
    return out


def read_xlsx(path: str, sheet=0) -> List[List[object]]:
    """Read one worksheet into a dense list-of-rows grid.

    Cells come back as str (shared/inline/formula strings), float
    (numbers), bool, or None (absent).  Rows are padded to the widest row.
    The caller interprets headers/dates — this is deliberately a GRID
    reader, not a table reader, because the Wind EDB export's meaning
    lives in its banner/meta rows.
    """
    with zipfile.ZipFile(path) as z:
        strings = _shared_strings(z)
        root = ElementTree.fromstring(z.read(_sheet_path(z, sheet)))
        rows: Dict[int, Dict[int, object]] = {}
        for row in root.iter(f"{_NS}row"):
            rr = row.get("r")
            if rr is None:
                raise ValueError(f"{path}: <row> without an r attribute — "
                                 "implied-position rows are not supported")
            r = int(rr) - 1
            cells: Dict[int, object] = {}
            for c in row.iter(f"{_NS}c"):
                ref = c.get("r")
                if not ref:
                    # spec-legal implied positions (some writers omit r on
                    # re-save) would land at index -1 and silently vanish
                    # from the grid — refuse loudly instead
                    raise ValueError(f"{path}: <c> without an r attribute "
                                     f"in row {r + 1} — implied-position "
                                     "cells are not supported")
                ci = _col_index(ref)
                t = c.get("t", "n")
                if t == "inlineStr":
                    is_el = c.find(f"{_NS}is")
                    val = "".join(tt.text or "" for tt in
                                  is_el.iter(f"{_NS}t")) if is_el is not None \
                        else None
                else:
                    v = c.find(f"{_NS}v")
                    if v is None or v.text is None:
                        val = None
                    elif t == "s":
                        val = strings[int(v.text)]
                    elif t == "str":  # cached formula result
                        val = v.text
                    elif t == "b":
                        val = v.text == "1"
                    else:
                        val = float(v.text)
                if val is not None:
                    cells[ci] = val
            if cells:
                rows[r] = cells
    if not rows:
        return []
    width = max(max(cs) for cs in rows.values()) + 1
    height = max(rows) + 1
    return [[rows.get(r, {}).get(c) for c in range(width)]
            for r in range(height)]


def read_index_list(path: str):
    """``index_list.xlsx`` -> DataFrame (header row 1: ts_code, name, ...)."""
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    grid = read_xlsx(path, sheet=0)
    if not grid:
        raise ValueError(f"{path}: sheet 0 is empty — no header row to read")
    header = [str(h) for h in grid[0]]
    return pd.DataFrame(grid[1:], columns=header)


def read_industry_index_prices(path: str, sheet=0):
    """One Wind EDB sheet -> long (index_name, trade_date, close) frame.

    Layout (verified against the shipped workbook): optional banner row(s),
    one header row whose first cell is ``指标名称`` (series names follow),
    meta rows (frequency/unit — string-valued), then data rows whose first
    cell is an Excel date serial.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    grid = read_xlsx(path, sheet=sheet)
    header = None
    records = []
    for row in grid:
        first = row[0] if row else None
        if header is None:
            if isinstance(first, str) and first.strip() == "指标名称":
                header = [str(h) if h is not None else "" for h in row[1:]]
            continue
        if isinstance(first, bool) or not isinstance(first, (int, float)):
            # meta rows (frequency/unit) between header and data; bool is
            # an int subclass, and a stray TRUE cell is not a date serial
            continue
        date = excel_serial_to_date(first).strftime("%Y%m%d")
        for name, val in zip(header, row[1:]):
            if name and isinstance(val, (int, float)) \
                    and not isinstance(val, bool):
                records.append({"index_name": name, "trade_date": date,
                                "close": float(val)})
    if header is None:
        raise ValueError(f"{path}: no 指标名称 header row — not a Wind EDB "
                         "export sheet")
    return pd.DataFrame.from_records(records)


def ingest_workbooks(store, index_list: str | None = None,
                     industry_index: str | None = None,
                     industry_sheets=(0, 1)) -> Dict[str, int]:
    """Load the static workbooks into PanelStore collections.

    ``index_list`` -> full-refresh ``index_list`` collection;
    ``industry_index`` sheets -> duplicate-tolerant inserts into
    ``industry_index_prices`` keyed (index_name, trade_date) — re-ingesting
    an updated workbook only adds the new rows (the same idempotent-load
    discipline as the API collections).
    """
    counts: Dict[str, int] = {}
    if index_list:
        df = read_index_list(index_list)
        store.replace("index_list", df)
        counts["index_list"] = len(df)
    if industry_index:
        n = 0
        for sh in industry_sheets:
            df = read_industry_index_prices(industry_index, sheet=sh)
            n += store.insert("industry_index_prices", df,
                              unique=("index_name", "trade_date"))
        counts["industry_index_prices"] = n
    return counts
