"""Incremental ETL: the framework's equivalent of the reference's
``Barra_database/database`` layer (tushare fetch + MongoDB upsert).

Reference mechanisms reproduced (SURVEY.md §2 / §5):

- last-date **watermark** resume per collection (``update_mongo_db.py:19-30``)
- trade-calendar-driven per-day incremental fetch (``update_mongo_db.py:87-116``)
- **rate limiting** to N calls/min (480 or 190 in the reference,
  ``update_mongo_db.py:151-162,410-427``)
- **retry** with fixed backoff, 3 attempts (``update_mongo_db.py:164-184``)
- duplicate-tolerant idempotent inserts (unique index +
  ``insert_many(ordered=False)``, ``update_mongo_db.py:118-128``)
- delete-then-insert refresh for index components (``update_mongo_db.py:514-521``)
- verification tools: universe count checks (``verify_data.py``) and
  missing-stock set-difference repair (``fill_missing_data.py``)

Storage is a parquet-per-collection :class:`PanelStore` (MongoDB is not part
of this image; an adapter with the same interface can wrap pymongo where it
exists).  All transports (the tushare client, the clock, the sleeper) are
injectable so the logic is testable hermetically.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Iterable, Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


class RateLimiter:
    """Sliding-window limiter: at most ``calls_per_min`` calls in 60s."""

    def __init__(self, calls_per_min: int, clock=time.monotonic, sleep=time.sleep):
        self.calls_per_min = calls_per_min
        self._clock = clock
        self._sleep = sleep
        self._stamps: list[float] = []

    def wait(self):
        now = self._clock()
        self._stamps = [t for t in self._stamps if now - t < 60.0]
        if len(self._stamps) >= self.calls_per_min:
            delay = 60.0 - (now - self._stamps[0])
            if delay > 0:
                self._sleep(delay)
        self._stamps.append(self._clock())


def with_retry(fn: Callable, attempts: int = 3, backoff_s: float = 5.0,
               sleep=time.sleep):
    """Call ``fn``; on exception retry up to ``attempts`` times with a fixed
    backoff (the reference's pattern, ``update_mongo_db.py:164-184``)."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — mirror the reference's broad catch
            last = e
            if i < attempts - 1:
                sleep(backoff_s)
    raise last


class PanelStore:
    """Parquet-per-collection store with unique-key dedup and watermarks."""

    def __init__(self, root: str):
        if pd is None:  # pragma: no cover
            raise ImportError("pandas required")
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.parquet")

    def read(self, name: str):
        p = self._path(name)
        if not os.path.exists(p):
            return pd.DataFrame()
        return pd.read_parquet(p)

    def insert(self, name: str, df, unique: Sequence[str] | None = None):
        """Append rows; rows whose ``unique`` key already exists are dropped
        (the unique-index + ordered=False insert semantics)."""
        if df is None or len(df) == 0:
            return 0
        cur = self.read(name)
        if len(cur) and unique:
            merged = pd.concat([cur, df], ignore_index=True)
            merged = merged.drop_duplicates(subset=list(unique), keep="first")
            added = len(merged) - len(cur)
            merged.to_parquet(self._path(name), index=False)
            return added
        out = pd.concat([cur, df], ignore_index=True) if len(cur) else df
        out.to_parquet(self._path(name), index=False)
        return len(df)

    def replace_where(self, name: str, mask_fn, df):
        """Delete rows matching ``mask_fn`` then insert ``df`` (the index-
        components refresh pattern)."""
        cur = self.read(name)
        if len(cur):
            cur = cur[~mask_fn(cur)]
        out = pd.concat([cur, df], ignore_index=True) if len(cur) else df
        out.to_parquet(self._path(name), index=False)

    def last_date(self, name: str, date_col: str = "trade_date"):
        """Watermark: newest date present (``update_mongo_db.py:19-30``)."""
        cur = self.read(name)
        if not len(cur) or date_col not in cur.columns:
            return None
        return cur[date_col].max()

    def distinct_count(self, name: str, col: str) -> int:
        cur = self.read(name)
        return 0 if not len(cur) else cur[col].nunique()


@dataclasses.dataclass
class IncrementalUpdater:
    """Watermark-driven incremental collection updates.

    ``source`` is any object with fetch methods returning DataFrames (the
    tushare adapter in production, a fake in tests).
    """

    store: PanelStore
    source: object
    limiter: RateLimiter | None = None
    attempts: int = 3
    backoff_s: float = 5.0
    sleep: Callable = time.sleep

    def _call(self, fn, *a, **k):
        if self.limiter is not None:
            self.limiter.wait()
        return with_retry(lambda: fn(*a, **k), self.attempts, self.backoff_s,
                          sleep=self.sleep)

    def update_daily_prices(self, trade_calendar: Iterable, name="daily_prices"):
        """Per-trade-day fetch of everything after the watermark
        (``update_mongo_db.py:59-128``)."""
        wm = self.store.last_date(name)
        n = 0
        for day in trade_calendar:
            if wm is not None and day <= wm:
                continue
            df = self._call(self.source.fetch_daily_prices, trade_date=day)
            n += self.store.insert(name, df, unique=("ts_code", "trade_date"))
        return n

    def update_statements(self, ts_codes: Sequence[str], kind: str,
                          start_date=None, end_date=None):
        """Per-stock statement fetch (balancesheet/cashflow/income/
        fina_indicator), the reference's hours-long hot loop
        (``update_mongo_db.py:134-342``)."""
        fetch = getattr(self.source, f"fetch_{kind}_by_stock")
        unique_key = ("ts_code", "end_date",
                      "ann_date" if kind == "financial_indicators" else "f_ann_date")
        n = 0
        for code in ts_codes:
            df = self._call(fetch, ts_code=code, start_date=start_date,
                            end_date=end_date)
            n += self.store.insert(kind, df, unique=unique_key)
        return n

    def update_index_components(self, index_codes: Sequence[str], trade_date,
                                name="index_components"):
        """Delete-then-insert per (index, date) (``update_mongo_db.py:459-534``)."""
        for idx in index_codes:
            df = self._call(self.source.fetch_index_components,
                            index_code=idx, trade_date=trade_date)
            self.store.replace_where(
                name,
                lambda c, idx=idx: (c["index_code"] == idx)
                & (c["trade_date"] == trade_date),
                df,
            )


def find_missing_stocks(store: PanelStore, universe_name="stock_info",
                        data_name="daily_prices", code_col="ts_code"):
    """Set-difference repair detection (``fill_missing_data.py:16-46``)."""
    uni = store.read(universe_name)
    dat = store.read(data_name)
    have = set() if not len(dat) else set(dat[code_col].unique())
    want = set() if not len(uni) else set(uni[code_col].unique())
    return sorted(want - have)


def verify_store(store: PanelStore, name="daily_prices", code_col="ts_code",
                 date_col="trade_date"):
    """Sanity counters (``verify_data.py:8-29``)."""
    df = store.read(name)
    return {
        "rows": int(len(df)),
        "stocks": 0 if not len(df) else int(df[code_col].nunique()),
        "first_date": None if not len(df) else str(df[date_col].min()),
        "last_date": None if not len(df) else str(df[date_col].max()),
    }
