"""Incremental ETL: the framework's equivalent of the reference's
``Barra_database/database`` layer (tushare fetch + MongoDB upsert).

Reference mechanisms reproduced (SURVEY.md §2 / §5):

- last-date **watermark** resume per collection (``update_mongo_db.py:19-30``)
- trade-calendar-driven per-day incremental fetch (``update_mongo_db.py:87-116``)
- **rate limiting** to N calls/min (480 or 190 in the reference,
  ``update_mongo_db.py:151-162,410-427``)
- **retry** with fixed backoff, 3 attempts (``update_mongo_db.py:164-184``)
- duplicate-tolerant idempotent inserts (unique index +
  ``insert_many(ordered=False)``, ``update_mongo_db.py:118-128``)
- delete-then-insert refresh for index components (``update_mongo_db.py:514-521``)
- verification tools: universe count checks (``verify_data.py``) and
  missing-stock set-difference repair (``fill_missing_data.py``)

Storage is a parquet-per-collection :class:`PanelStore` (MongoDB is not part
of this image; an adapter with the same interface can wrap pymongo where it
exists).  All transports (the tushare client, the clock, the sleeper) are
injectable so the logic is testable hermetically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Callable, Iterable, Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None



class RateLimiter:
    """Sliding-window limiter: at most ``calls_per_min`` calls in 60s."""

    def __init__(self, calls_per_min: int, clock=time.monotonic, sleep=time.sleep):
        self.calls_per_min = calls_per_min
        self._clock = clock
        self._sleep = sleep
        self._stamps: list[float] = []

    def wait(self):
        now = self._clock()
        self._stamps = [t for t in self._stamps if now - t < 60.0]
        if len(self._stamps) >= self.calls_per_min:
            delay = 60.0 - (now - self._stamps[0])
            if delay > 0:
                self._sleep(delay)
        self._stamps.append(self._clock())


def with_retry(fn: Callable, attempts: int = 3, backoff_s: float = 5.0,
               sleep=time.sleep, *, exponential: bool = False,
               max_backoff_s: float = 60.0, jitter: float = 0.0,
               seed: int = 0, retryable: tuple = (Exception,),
               phase: str | None = None):
    """Call ``fn``; on a retryable exception retry up to ``attempts`` times.

    Defaults reproduce the reference's fixed 5 s backoff, broad catch
    (``update_mongo_db.py:164-184``) exactly.  Production knobs:

    - ``exponential``: back off ``backoff_s * 2**i`` capped at
      ``max_backoff_s`` — repeated transient failures stop hammering a
      recovering upstream.
    - ``jitter``: multiply each delay by a seeded uniform draw from
      ``[1 - jitter, 1 + jitter]`` (decorrelates a fleet of daily jobs all
      retrying the same outage on the same schedule).  Seeded so a replay
      sleeps the same schedule.
    - ``retryable``: exception classes worth retrying; anything else
      (a programming error, an auth failure) re-raises IMMEDIATELY — two
      more identical attempts cannot fix a TypeError.

    On exhaustion the raised exception carries its retry history:
    ``e.attempts`` (calls made) and ``e.total_backoff_s`` (seconds slept
    between them) — dead-letter records and reload failure logs in the
    query loop stamp these so an operator can tell "failed instantly"
    from "fought the outage for a minute".  ``phase`` additionally
    stamps ``e.phase`` so a caller several frames up can tell WHICH
    retried operation died — the fleet transport uses it to separate
    "never connected" (``phase="connect"``) from "connection lost
    mid-batch" (``phase="batch"``) in its manifest counters.
    """
    import random

    from mfm_tpu.obs import instrument as _telemetry

    rng = random.Random(seed)
    last = None
    total_backoff = 0.0
    for i in range(attempts):
        try:
            result = fn()
            _telemetry.RETRY_ATTEMPTS_TOTAL.inc(
                outcome="ok" if i == 0 else "retried")
            return result
        except retryable as e:
            last = e
            if i < attempts - 1:
                delay = (min(backoff_s * (2.0 ** i), max_backoff_s)
                         if exponential else backoff_s)
                if jitter:
                    delay *= 1.0 + jitter * (2.0 * rng.random() - 1.0)
                _telemetry.RETRY_BACKOFF_SECONDS.observe(delay)
                total_backoff += delay
                sleep(delay)
    _telemetry.RETRY_ATTEMPTS_TOTAL.inc(outcome="exhausted")
    last.attempts = attempts
    last.total_backoff_s = total_backoff
    if phase is not None:
        last.phase = phase
    raise last


class PanelStore:
    """Partitioned-parquet-per-collection store with unique-key dedup and
    watermarks.

    Each collection is a directory of append-only part files: an insert
    writes ONE new part instead of rewriting the whole collection (the
    round-1 O(total^2) IO finding; the reference's Mongo insert is likewise
    incremental, ``update_mongo_db.py:118-128``).  Unique-key enforcement
    uses a per-process key-set cache, loaded once per collection via a
    key-columns-only scan, then maintained incrementally — so N inserts cost
    O(rows inserted), not O(total stored) each.  Legacy single-file
    ``<name>.parquet`` stores are read transparently.
    """

    def __init__(self, root: str):
        if pd is None:  # pragma: no cover
            raise ImportError("pandas required")
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._keys: dict = {}   # (name, unique cols) -> set of key tuples

    def _legacy_path(self, name: str) -> str:
        return os.path.join(self.root, f"{name}.parquet")

    def _dir(self, name: str) -> str:
        return os.path.join(self.root, name)

    def _marker_path(self, name: str) -> str:
        return os.path.join(self._dir(name), "_compact.json")

    def _heal(self, name: str) -> None:
        """Roll an interrupted _rewrite forward (idempotent).

        The marker is written *after* the merged part and *before* any
        deletion, so its presence means the merged data is complete: finish
        the rename, drop the obsolete parts, drop the marker.  A ``.pending``
        file with no marker is an aborted write — discard it."""
        d = self._dir(name)
        if not os.path.isdir(d):
            return
        marker = self._marker_path(name)
        if os.path.exists(marker):
            with open(marker) as f:
                m = json.load(f)
            pending = os.path.join(d, m["pending"])
            final = os.path.join(d, m["final"])
            if os.path.exists(pending) and not os.path.exists(final):
                os.replace(pending, final)
            for rel in m["obsolete"]:
                p = os.path.join(self.root, rel)
                if os.path.exists(p):
                    os.remove(p)
            os.remove(marker)
            self._keys = {k: v for k, v in self._keys.items() if k[0] != name}
        for f in os.listdir(d):
            if f.endswith(".pending"):
                os.remove(os.path.join(d, f))

    def _parts(self, name: str) -> list:
        self._heal(name)
        parts = []
        if os.path.exists(self._legacy_path(name)):
            parts.append(self._legacy_path(name))
        d = self._dir(name)
        if os.path.isdir(d):
            parts += sorted(
                os.path.join(d, f) for f in os.listdir(d)
                if f.endswith(".parquet")
            )
        return parts

    def read(self, name: str, columns: Sequence[str] | None = None):
        parts = self._parts(name)
        if not parts:
            return pd.DataFrame()
        cols = list(columns) if columns is not None else None
        dfs = [pd.read_parquet(p, columns=cols) for p in parts]
        if len(dfs) == 1:
            return dfs[0]
        return pd.concat(dfs, ignore_index=True)

    @staticmethod
    def _hash_keys(df, cols: tuple) -> np.ndarray:
        """64-bit key hashes with normalized nulls.

        NaN/None/NaT all normalize to one sentinel per column before hashing
        so null-keyed rows dedup like ``drop_duplicates`` treats them (NaN !=
        NaN under tuple equality would re-admit them forever).  Hashing keeps
        the cache at 8-ish bytes/key instead of a tuple per row — the
        all-A-share scale (~13.5M daily keys) stays well under a GB.  A
        64-bit collision silently drops one row with probability ~n^2/2^64
        (~5e-6 at that scale); the reference's Mongo unique index is exact,
        so is the on-disk state here — only the admission check is hashed.
        """
        kdf = df[list(cols)].copy()
        for c in kdf.columns:
            if kdf[c].dtype == object:
                kdf[c] = kdf[c].where(pd.notna(kdf[c]), None)
        return pd.util.hash_pandas_object(kdf, index=False).to_numpy(np.uint64)

    def _key_set(self, name: str, cols: tuple) -> set:
        """Unique-key cache for one collection, kept in sync with the part
        files on disk: parts written by OTHER store instances since the last
        look are key-scanned incrementally, and any *deletion* of a seen part
        (another instance's replace_where/compact) invalidates the cache
        entirely.  Concurrent writers racing on the same collection still
        need external locking — the reference's arbiter there is Mongo's
        unique index."""
        cache_key = (name, cols)
        keys, seen_parts = self._keys.get(cache_key, (set(), set()))
        current = set(self._parts(name))
        if seen_parts - current:  # a seen part vanished: cache is stale
            keys, seen_parts = set(), set()
        for p in sorted(current - seen_parts):
            cur = pd.read_parquet(p, columns=list(cols))
            keys.update(self._hash_keys(cur, cols).tolist())
            seen_parts.add(p)
        self._keys[cache_key] = (keys, seen_parts)
        return keys

    def _next_part_index(self, d: str) -> int:
        idx = -1
        for f in os.listdir(d):
            if f.startswith("part-") and f.endswith(".parquet"):
                try:
                    idx = max(idx, int(f.split("-")[1]))
                except ValueError:
                    continue
        return idx + 1

    def _write_part(self, name: str, df) -> str:
        d = self._dir(name)
        os.makedirs(d, exist_ok=True)
        # max-existing-index + 1, NOT the file count: after a rewrite removes
        # parts, a count-based name would collide with (and os.replace would
        # clobber) a live part
        n = self._next_part_index(d)
        path = os.path.join(d, f"part-{n:06d}-{os.getpid()}.parquet")
        tmp = path + ".tmp"
        df.to_parquet(tmp, index=False)
        os.replace(tmp, path)
        return path

    def insert(self, name: str, df, unique: Sequence[str] | None = None):
        """Append rows as one new part; rows whose ``unique`` key already
        exists are dropped (the unique-index + ordered=False semantics)."""
        if df is None or len(df) == 0:
            return 0
        if unique:
            cols = tuple(unique)
            have = self._key_set(name, cols)
            incoming = self._hash_keys(df, cols).tolist()
            seen: set = set()
            keep = np.empty(len(incoming), bool)
            for i, k in enumerate(incoming):
                fresh = k not in have and k not in seen
                keep[i] = fresh
                if fresh:
                    seen.add(k)
            df = df[keep]
            if not len(df):
                return 0
            have.update(seen)
        else:
            # un-keyed insert: existing key caches for this collection are
            # no longer complete
            self._keys = {k: v for k, v in self._keys.items() if k[0] != name}
        path = self._write_part(name, df.reset_index(drop=True))
        if unique:
            # our own part is already reflected in the key set
            self._keys[(name, tuple(unique))][1].add(path)
        return len(df)

    def replace_where(self, name: str, mask_fn, df):
        """Delete rows matching ``mask_fn`` then insert ``df`` (the index-
        components refresh pattern) — compacts the collection."""
        cur = self.read(name)
        if len(cur):
            cur = cur[~mask_fn(cur)]
        out = pd.concat([cur, df], ignore_index=True) if len(cur) else df
        self._rewrite(name, out)

    def replace(self, name: str, df):
        """Full refresh: the collection's contents become exactly ``df``
        (the reference's drop + ``insert_many`` pattern,
        ``update_mongo_db.py:32-57``) — unlike an all-True ``replace_where``
        this never reads the rows being discarded.  ``None`` wipes the
        collection (the Mongo adapter's behavior — shared contract)."""
        self._rewrite(name, df if df is not None else pd.DataFrame())

    def compact(self, name: str):
        """Merge all parts into one (maintenance; reads stay correct
        either way)."""
        cur = self.read(name)
        if len(cur):
            self._rewrite(name, cur)

    def _rewrite(self, name: str, df) -> None:
        """Replace the collection's contents atomically w.r.t. crashes:
        merged part first, then a marker, then deletions (see _heal)."""
        old = self._parts(name)
        d = self._dir(name)
        os.makedirs(d, exist_ok=True)
        final = f"part-{self._next_part_index(d):06d}-{os.getpid()}.parquet"
        pending = final + ".pending"
        df.reset_index(drop=True).to_parquet(os.path.join(d, pending),
                                             index=False)
        marker = {
            "pending": pending, "final": final,
            "obsolete": [os.path.relpath(p, self.root) for p in old],
        }
        tmp = self._marker_path(name) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(marker, f)
        os.replace(tmp, self._marker_path(name))
        os.replace(os.path.join(d, pending), os.path.join(d, final))
        for p in old:
            os.remove(p)
        os.remove(self._marker_path(name))
        self._keys = {k: v for k, v in self._keys.items() if k[0] != name}

    def last_date(self, name: str, date_col: str = "trade_date"):
        """Watermark: newest date present (``update_mongo_db.py:19-30``)."""
        parts = self._parts(name)
        if not parts:
            return None
        import pyarrow.parquet as pq

        # a missing date column is a clean None; IO/corruption errors from
        # the schema read or data read propagate — they must not silently
        # reset the watermark and trigger a full refetch
        if date_col not in pq.read_schema(parts[0]).names:
            return None
        cur = self.read(name, columns=[date_col])
        return cur[date_col].max() if len(cur) else None

    def distinct_count(self, name: str, col: str) -> int:
        parts = self._parts(name)
        if not parts:
            return 0
        cur = self.read(name, columns=[col])
        return 0 if not len(cur) else cur[col].nunique()


@dataclasses.dataclass
class IncrementalUpdater:
    """Watermark-driven incremental collection updates.

    ``source`` is any object with fetch methods returning DataFrames (the
    tushare adapter in production, a fake in tests).
    """

    store: PanelStore
    source: object
    limiter: RateLimiter | None = None
    attempts: int = 3
    backoff_s: float = 5.0
    sleep: Callable = time.sleep

    def _call(self, fn, *a, **k):
        if self.limiter is not None:
            self.limiter.wait()
        return with_retry(lambda: fn(*a, **k), self.attempts, self.backoff_s,
                          sleep=self.sleep)

    def update_daily_prices(self, trade_calendar: Iterable, name="daily_prices"):
        """Per-trade-day fetch of everything after the watermark
        (``update_mongo_db.py:59-128``)."""
        wm = self.store.last_date(name)
        n = 0
        for day in trade_calendar:
            if wm is not None and day <= wm:
                continue
            df = self._call(self.source.fetch_daily_prices, trade_date=day)
            n += self.store.insert(name, df, unique=("ts_code", "trade_date"))
        return n

    def update_statements(self, ts_codes: Sequence[str], kind: str,
                          start_date=None, end_date=None):
        """Per-stock statement fetch (balancesheet/cashflow/income/
        fina_indicator), the reference's hours-long hot loop
        (``update_mongo_db.py:134-342``)."""
        fetch = getattr(self.source, f"fetch_{kind}_by_stock")
        unique_key = ("ts_code", "end_date",
                      "ann_date" if kind == "financial_indicators" else "f_ann_date")
        n = 0
        for code in ts_codes:
            df = self._call(fetch, ts_code=code, start_date=start_date,
                            end_date=end_date)
            n += self.store.insert(kind, df, unique=unique_key)
        return n

    def update_index_components(self, index_codes: Sequence[str], trade_date,
                                name="index_components"):
        """Delete-then-insert per (index, date) (``update_mongo_db.py:459-534``)."""
        for idx in index_codes:
            df = self._call(self.source.fetch_index_components,
                            index_code=idx, trade_date=trade_date)
            self.store.replace_where(
                name,
                lambda c, idx=idx: (c["index_code"] == idx)
                & (c["trade_date"] == trade_date),
                df,
            )

    def update_stock_info(self, name="stock_info") -> list:
        """Full refresh of the live A-share list; every run replaces the old
        collection and returns the ts_code universe the statement updaters
        iterate (``update_mongo_db.py:32-57``: drop + insert_many)."""
        df = self._call(self.source.fetch_stock_info)
        if df is None or not len(df):
            return []
        self.store.replace(name, df)
        return list(df["ts_code"])

    @staticmethod
    def _next_day(date_str) -> str:
        d = pd.to_datetime(str(date_str), format="%Y%m%d")
        return (d + pd.Timedelta(days=1)).strftime("%Y%m%d")

    def update_daily_index_prices(self, index_codes: Sequence[str],
                                  end_date=None,
                                  name="index_daily_prices") -> int:
        """Watermarked ranged fetch per index (``update_mongo_db.py:387-454``:
        start = watermark + 1 day, rate limited, retried, duplicate-tolerant
        insert).  Documented deviation: the reference keeps ONE watermark for
        the whole collection (``:398``), so an index code added to the list
        after the first run would silently get no history; here the
        watermark is per index, and a first-seen code is fetched in full."""
        have = self.store.read(name, columns=["ts_code", "trade_date"])
        # one pass for all per-index maxima, not a filter per code
        wms = (have.groupby("ts_code")["trade_date"].max().to_dict()
               if len(have) else {})
        n = 0
        for code in index_codes:
            wm = wms.get(code)
            start = self._next_day(wm) if wm is not None else None
            if start is not None and end_date is not None \
                    and str(start) > str(end_date):
                continue  # this index is up to date (update_mongo_db.py:401-403)
            df = self._call(self.source.fetch_daily_index_prices,
                            ts_code=code, start_date=start, end_date=end_date)
            n += self.store.insert(name, df, unique=("ts_code", "trade_date"))
        return n

    def update_sw_industries(self, ts_codes: Sequence[str] | None = None,
                             csv_path: str | None = None,
                             name="sw_industries") -> int:
        """Full refresh of the SW industry classification
        (``update_mongo_db.py:536-576``: drop + insert_many from a CSV).
        Either path works: ``csv_path`` mirrors the reference; ``ts_codes``
        fetches per stock through the source's ``index_member_all`` wrapper
        instead (the notebook path, ``industry_data.ipynb`` cell 3)."""
        if csv_path is not None:
            df = pd.read_csv(csv_path)
        elif ts_codes is not None:
            frames = [self._call(self.source.fetch_sw_industries, ts_code=c)
                      for c in ts_codes]
            frames = [f for f in frames if f is not None and len(f)]
            df = pd.concat(frames, ignore_index=True) if frames \
                else pd.DataFrame()
        else:
            raise ValueError("pass ts_codes or csv_path")
        if not len(df):
            return 0
        self.store.replace(name, df)
        return len(df)

    def repair_missing_stocks(self, start_date, end_date,
                              universe_name="stock_info") -> dict:
        """Detect AND refetch stocks present in the universe but absent from
        ``daily_prices`` (``fill_missing_data.py:16-64``: set difference,
        then a per-stock ranged ``daily_basic`` fetch, duplicate-tolerant
        insert).  The refill is daily-prices-specific by construction (it
        fetches ``daily_basic`` rows), so collection/key are not
        parameters — detection over other collections stays with
        :func:`find_missing_stocks`."""
        missing = find_missing_stocks(self.store, universe_name=universe_name,
                                      data_name="daily_prices",
                                      code_col="ts_code")
        n = 0
        for code in missing:
            df = self._call(self.source.fetch_daily_prices_by_stock,
                            ts_code=code, start_date=start_date,
                            end_date=end_date)
            n += self.store.insert("daily_prices", df,
                                   unique=("ts_code", "trade_date"))
        return {"missing": missing, "rows_inserted": n}

    def run_all(self, start_date, end_date,
                index_codes: Sequence[str] = ("000300.SH", "000016.SH",
                                              "000903.SH"),
                statements: Sequence[str] = ("balancesheet", "cashflow",
                                             "income", "financial_indicators"),
                components_date=None, sw: bool = True,
                sw_csv: str | None = None) -> dict:
        """Calendar-driven refresh of every collection, in the reference's
        ``__main__`` order (``update_mongo_db.py:579-614``): stock_info ->
        daily_prices over the trade calendar -> statements per stock ->
        index daily prices -> index components -> SW industries.  The steps
        the reference ships commented out ("run manually", ``:590-614``) are
        on by default here and individually disableable."""
        codes = self.update_stock_info()
        cal = self._call(self.source.fetch_trade_calendar,
                         start_date=start_date, end_date=end_date)
        summary = {
            "stock_info": len(codes),
            "daily_prices": self.update_daily_prices(cal),
            "statements": {
                k: self.update_statements(codes, k, start_date, end_date)
                for k in statements
            },
            "index_daily_prices": self.update_daily_index_prices(
                index_codes, end_date=end_date),
        }
        if components_date is not None:
            self.update_index_components(index_codes, components_date)
            summary["index_components_date"] = str(components_date)
        if sw:
            summary["sw_industries"] = self.update_sw_industries(
                ts_codes=codes, csv_path=sw_csv)
        return summary


def plan_update(store: PanelStore, start_date, end_date,
                index_codes: Sequence[str] = ("000300.SH", "000016.SH",
                                              "000903.SH"),
                statements: Sequence[str] = ("balancesheet", "cashflow",
                                             "income",
                                             "financial_indicators"),
                components_date=None, sw: bool = True) -> dict:
    """Dry-run of :meth:`IncrementalUpdater.run_all`: what each step WOULD
    fetch, derived from the store's watermarks alone — zero API calls.

    The reference's updater spends a hard budget (480 calls/min, hours of
    wall clock for a statement backfill, ``update_mongo_db.py:151-184``);
    this is the pre-flight check before committing to it.  Mirrors
    ``run_all``'s own step toggles (``components_date``/``sw``) so the plan
    previews exactly the command it is a dry run of.  Returns, per
    collection: the current watermark (or row count for full-refresh
    collections), the planned fetch range, and whether it is already up to
    date.
    """
    _next = IncrementalUpdater._next_day
    start_s, end_s = str(start_date), str(end_date)

    wm = store.last_date("daily_prices")
    # run_all only walks the [start, end] trade calendar, so an old
    # watermark never implies a pre-start backfill: clamp to start
    daily_start = max(_next(wm), start_s) if wm is not None else start_s
    n_codes = store.distinct_count("stock_info", "ts_code")
    plan: dict = {
        "range": [start_s, end_s],
        "stock_info": {"rows": int(n_codes), "action": "full refresh"},
        "daily_prices": {
            "watermark": None if wm is None else str(wm),
            "fetch_from": daily_start,
            "up_to_date": daily_start > end_s,
        },
    }
    plan["statements"] = {
        k: {
            # run_all refreshes stock_info FIRST, so an empty store means
            # the universe (and the real call count) is unknown here, not 0
            "per_stock_calls": int(n_codes) if n_codes else None,
            **({} if n_codes else
               {"note": "universe unknown until stock_info refreshes"}),
            "range": [start_s, end_s],
        }
        for k in statements
    }
    have = store.read("index_daily_prices", columns=["ts_code", "trade_date"])
    wms = (have.groupby("ts_code")["trade_date"].max().to_dict()
           if len(have) else {})
    idx = {}
    for code in index_codes:
        w = wms.get(code)
        frm = _next(w) if w is not None else None
        idx[code] = {"watermark": None if w is None else str(w),
                     "fetch_from": frm,
                     "up_to_date": frm is not None and str(frm) > end_s}
    plan["index_daily_prices"] = idx
    if components_date is not None:
        plan["index_components"] = {
            "date": str(components_date), "indexes": list(index_codes),
            "action": "delete-then-insert refresh"}
    if sw:
        plan["sw_industries"] = {
            "rows": int(store.distinct_count("sw_industries", "ts_code")),
            "action": "full refresh"}
    return plan


def find_missing_stocks(store: PanelStore, universe_name="stock_info",
                        data_name="daily_prices", code_col="ts_code"):
    """Set-difference repair detection (``fill_missing_data.py:16-46``)."""
    uni = store.read(universe_name)
    dat = store.read(data_name)
    have = set() if not len(dat) else set(dat[code_col].unique())
    want = set() if not len(uni) else set(uni[code_col].unique())
    return sorted(want - have)


def verify_store(store: PanelStore, name="daily_prices", code_col="ts_code",
                 date_col="trade_date"):
    """Sanity counters (``verify_data.py:8-29``)."""
    df = store.read(name)
    return {
        "rows": int(len(df)),
        "stocks": 0 if not len(df) else int(df[code_col].nunique()),
        "first_date": None if not len(df) else str(df[date_col].min()),
        "last_date": None if not len(df) else str(df[date_col].max()),
    }
