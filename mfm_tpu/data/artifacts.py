"""Stage-artifact checkpointing.

The reference checkpoints *data* between stages everywhere (Mongo collections
with watermarks, intermediate CSVs — SURVEY.md §5 "Checkpoint / resume").
Here every stage boundary can persist its arrays to an .npz artifact with a
schema stamp, and jitted executables persist via JAX's compilation cache
(``mfm_tpu.utils.cache.enable_persistent_compilation_cache``).
"""

from __future__ import annotations

import json
import os
from typing import Mapping

import numpy as np

FORMAT_VERSION = 1


def save_artifact(path: str, arrays: Mapping[str, object], meta: dict | None = None):
    """Persist a flat dict of arrays (+ JSON-able metadata) atomically."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps({"format": FORMAT_VERSION, **(meta or {})}).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp.npz"  # savez appends .npz unless already present
    try:
        np.savez_compressed(tmp, **payload)
    except BaseException:
        # a failed write must not leave a half-written temp behind — the
        # next save would os.replace over it, but stray .tmp.npz files in
        # artifact dirs confuse globbing consumers and retention scripts
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    os.replace(tmp, path)


def load_artifact(path: str):
    """Returns (arrays dict, meta dict)."""
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode()) if "__meta__" in z.files else {}
    return arrays, meta


def save_risk_outputs(path: str, outputs, meta: dict | None = None):
    """Persist a RiskModelOutputs tuple (stage-6 artifact)."""
    arrays = {f: np.asarray(getattr(outputs, f)) for f in outputs._fields}
    save_artifact(path, arrays, meta)


def load_risk_outputs(path: str):
    """Rehydrate a :func:`save_risk_outputs` artifact.

    Returns ``(RiskModelOutputs, meta)`` — the inverse, so post-hoc
    analytics (specific risk, portfolio risk, bias acceptance tests) can
    run off a finished pipeline's artifact without recomputing the model.
    """
    from mfm_tpu.models.risk_model import RiskModelOutputs

    arrays, meta = load_artifact(path)
    missing = set(RiskModelOutputs._fields) - set(arrays)
    if missing:
        raise ValueError(f"{path}: not a risk-outputs artifact — missing "
                         f"field(s) {sorted(missing)}")
    return RiskModelOutputs(**{f: arrays[f]
                               for f in RiskModelOutputs._fields}), meta


# -- risk-model state (the incremental daily-update checkpoint) --------------

_NW_SCALARS = ("nw_t", "nw_S", "nw_A", "nw_Z")
_NW_STACKED = ("nw_Ps", "nw_hs", "nw_gs", "nw_Slags", "nw_xlags")


def save_risk_state(path: str, state, meta: dict | None = None):
    """Persist a :class:`mfm_tpu.models.risk_model.RiskModelState`.

    The Newey-West carry's per-lag tuples stack into ``(q, ...)`` arrays;
    everything identity-like (static aux + caller alignment metadata such
    as stocks / style names / last date) rides in the JSON ``__meta__``
    buffer.  npz round-trips every dtype bit-exactly, so a rehydrated
    state resumes the scans bitwise.
    """
    t, S, A, Z, Ps, hs, gs, Slags, xlags = state.nw_carry
    arrays = {
        "nw_t": np.asarray(t),
        "nw_S": np.asarray(S),
        "nw_A": np.asarray(A),
        "nw_Z": np.asarray(Z),
        "nw_Ps": np.stack([np.asarray(p) for p in Ps]) if Ps
                 else np.zeros((0,) + np.asarray(A).shape, np.asarray(A).dtype),
        "nw_hs": np.stack([np.asarray(h) for h in hs]) if hs
                 else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "nw_gs": np.stack([np.asarray(g) for g in gs]) if gs
                 else np.zeros((0,), np.asarray(Z).dtype),
        "nw_Slags": np.stack([np.asarray(s) for s in Slags]) if Slags
                    else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "nw_xlags": np.stack([np.asarray(x) for x in xlags]) if xlags
                    else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "vr_num": np.asarray(state.vr_num),
        "vr_den": np.asarray(state.vr_den),
        "sim_covs": np.asarray(state.sim_covs),
    }
    state_meta = {
        "kind": "risk_state",
        "nw_q": len(Ps),
        "sim_length": state.sim_length,
        "eigen_batch_hint": state.eigen_batch_hint,
        "stamp": _stamp_to_json(state.stamp),
        "last_date": state.last_date,
    }
    save_artifact(path, arrays, {**state_meta, **(meta or {})})


def load_risk_state(path: str):
    """Rehydrate a :func:`save_risk_state` artifact.

    Returns ``(RiskModelState, meta)``; arrays come back as jax arrays with
    their exact saved dtypes, so ``RiskModel.update`` from the loaded state
    is bitwise the run that would have continued in-process.
    """
    import jax.numpy as jnp

    from mfm_tpu.models.risk_model import RiskModelState

    arrays, meta = load_artifact(path)
    missing = (set(_NW_SCALARS) | set(_NW_STACKED)
               | {"vr_num", "vr_den", "sim_covs"}) - set(arrays)
    if meta.get("kind") != "risk_state" or missing:
        raise ValueError(f"{path}: not a risk-state artifact"
                         + (f" — missing field(s) {sorted(missing)}"
                            if missing else ""))
    q = int(meta["nw_q"])
    unstack = lambda name: tuple(jnp.asarray(arrays[name][i]) for i in range(q))
    nw_carry = (
        jnp.asarray(arrays["nw_t"]),
        jnp.asarray(arrays["nw_S"]),
        jnp.asarray(arrays["nw_A"]),
        jnp.asarray(arrays["nw_Z"]),
        unstack("nw_Ps"), unstack("nw_hs"), unstack("nw_gs"),
        unstack("nw_Slags"), unstack("nw_xlags"),
    )
    state = RiskModelState(
        nw_carry,
        jnp.asarray(arrays["vr_num"]),
        jnp.asarray(arrays["vr_den"]),
        jnp.asarray(arrays["sim_covs"]),
        sim_length=meta["sim_length"],
        eigen_batch_hint=int(meta["eigen_batch_hint"]),
        stamp=_stamp_from_json(meta["stamp"]),
        last_date=meta.get("last_date"),
    )
    return state, meta


def _stamp_to_json(obj):
    """Nested tuples -> nested lists with a tag, reversibly (JSON has no
    tuple; the stamp is compared with ``==`` against a live model's tuple
    stamp, so the round trip must restore tuple-ness exactly)."""
    if isinstance(obj, tuple):
        return {"__tuple__": [_stamp_to_json(x) for x in obj]}
    return obj


def _stamp_from_json(obj):
    if isinstance(obj, dict) and "__tuple__" in obj:
        return tuple(_stamp_from_json(x) for x in obj["__tuple__"])
    return obj
