"""Stage-artifact checkpointing.

The reference checkpoints *data* between stages everywhere (Mongo collections
with watermarks, intermediate CSVs — SURVEY.md §5 "Checkpoint / resume").
Here every stage boundary can persist its arrays to an .npz artifact with a
schema stamp, and jitted executables persist via JAX's compilation cache
(``mfm_tpu.utils.cache.enable_persistent_compilation_cache``).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import zipfile
import zlib
from typing import Mapping

import numpy as np

from mfm_tpu.obs import instrument as _telemetry
from mfm_tpu.utils.chaos import chaos_point

FORMAT_VERSION = 1

#: per-directory fencing pointer: ``{basename: {"generation": g,
#: "sha256": file-digest}}`` — swapped atomically AFTER the artifact rename,
#: so it always names a fully-written file
POINTER_NAME = "latest.json"


class ArtifactCorruptError(RuntimeError):
    """An artifact file exists but cannot be trusted: truncated or corrupt
    npz (suspected torn write) or a checksum mismatch."""


class ArtifactStaleError(RuntimeError):
    """Fencing refusal: the artifact's generation is older than the
    directory's ``latest.json`` pointer — a restored backup or a file from
    a superseded writer.  Load with ``force=True`` to accept it anyway."""


def _payload_sha256(payload: Mapping[str, np.ndarray]) -> str:
    """Canonical digest of the array payload (name/dtype/shape/bytes, name
    order).  Lives INSIDE the npz meta — an end-to-end content check the
    zip CRCs don't give us across numpy/zlib versions — while the pointer
    carries the whole-file digest for the doctor audit."""
    h = hashlib.sha256()
    for k in sorted(payload):
        a = np.ascontiguousarray(payload[k])
        h.update(k.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _fsync_dir(dirname: str) -> None:
    """Durably record a rename: fsync of the file alone does not persist
    the directory entry pointing at it."""
    try:
        fd = os.open(dirname or ".", os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _pointer_path(path: str) -> str:
    return os.path.join(os.path.dirname(path) or ".", POINTER_NAME)


def read_pointer(path: str) -> dict | None:
    """The ``latest.json`` entry for ``path`` (None when absent/unreadable —
    a torn pointer write cannot exist by protocol, but an unreadable pointer
    must not brick loading: the artifact's own checksum still protects it)."""
    try:
        with open(_pointer_path(path)) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return None
    entry = table.get(os.path.basename(path))
    return entry if isinstance(entry, dict) else None


def _swap_pointer(path: str, generation: int, sha256: str) -> None:
    """Atomically advance the fencing pointer for ``path``: read-modify-
    write of the whole table through a tmp + fsync + rename."""
    ptr = _pointer_path(path)
    try:
        with open(ptr) as f:
            table = json.load(f)
        if not isinstance(table, dict):
            table = {}
    except (OSError, ValueError):
        table = {}
    table[os.path.basename(path)] = {
        "generation": int(generation), "sha256": sha256,
    }
    tmp = ptr + ".tmp"
    with open(tmp, "w") as f:
        json.dump(table, f, indent=0, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, ptr)
    _fsync_dir(os.path.dirname(ptr))


def save_artifact(path: str, arrays: Mapping[str, object],
                  meta: dict | None = None, *, fenced: bool = False):
    """Persist a flat dict of arrays (+ JSON-able metadata) atomically.

    Always: payload sha256 into ``__meta__``, tmp write + fsync + rename +
    directory fsync — a kill at any byte leaves either the old file or the
    new file, never neither.  ``fenced`` additionally stamps a monotonically
    increasing ``generation`` (pointer + 1) into the meta and swaps the
    directory's ``latest.json`` pointer after the rename; loaders then
    refuse generations older than the pointer (:func:`load_artifact`).
    """
    t0 = time.perf_counter()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    meta = dict(meta or {})
    meta["sha256"] = _payload_sha256(payload)
    generation = None
    if fenced:
        entry = read_pointer(path)
        generation = (int(entry["generation"]) if entry
                      and isinstance(entry.get("generation"), int) else 0) + 1
        meta["generation"] = generation
    payload["__meta__"] = np.frombuffer(
        json.dumps({"format": FORMAT_VERSION, **meta}).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp.npz"  # savez appends .npz unless already present
    try:
        np.savez_compressed(tmp, **payload)
        with open(tmp, "rb+") as f:
            os.fsync(f.fileno())
    except BaseException:
        # a failed write must not leave a half-written temp behind — the
        # next save would os.replace over it, but stray .tmp.npz files in
        # artifact dirs confuse globbing consumers and retention scripts
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    file_sha = _file_sha256(tmp)
    chaos_point("save_artifact.after_tmp", path)
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    chaos_point("save_artifact.after_rename", path)
    if fenced:
        _swap_pointer(path, generation, file_sha)
        _telemetry.CHECKPOINT_GENERATION.set_value(generation)
    _telemetry.CHECKPOINT_SAVES_TOTAL.inc()
    _telemetry.CHECKPOINT_SAVE_SECONDS.observe(time.perf_counter() - t0)


def load_artifact(path: str, *, fenced: bool = False, force: bool = False):
    """Returns (arrays dict, meta dict).

    A truncated or corrupt npz (the torn-write signature) raises
    :class:`ArtifactCorruptError` naming the path instead of surfacing a
    raw ``zipfile.BadZipFile``; a payload-checksum mismatch likewise.  With
    ``fenced``, the artifact's ``generation`` is checked against the
    directory's ``latest.json``: older than the pointer raises
    :class:`ArtifactStaleError` (``force=True`` overrides); exactly one
    NEWER than the pointer means the writer died between the rename and the
    pointer swap — the file is complete (it passed its checksum), so the
    pointer is healed forward and the load succeeds.
    """
    t0 = time.perf_counter()
    try:
        with np.load(path, allow_pickle=False) as z:
            arrays = {k: z[k] for k in z.files if k != "__meta__"}
            meta = (json.loads(bytes(z["__meta__"]).decode())
                    if "__meta__" in z.files else {})
    except (zipfile.BadZipFile, zlib.error, EOFError) as e:
        _telemetry.CHECKPOINT_CORRUPT_TOTAL.inc()
        raise ArtifactCorruptError(
            f"{path}: truncated or corrupt npz ({e}) — suspected torn "
            f"write; recover from the previous generation or re-run the "
            f"producing stage (docs/SERVING.md)") from e
    except ValueError as e:
        # np.load raises bare ValueError on non-zip magic / header damage
        _telemetry.CHECKPOINT_CORRUPT_TOTAL.inc()
        raise ArtifactCorruptError(
            f"{path}: unreadable artifact ({e}) — suspected torn write or "
            f"foreign file; recover per docs/SERVING.md") from e
    # force bypasses FENCING only — a corrupt payload is corrupt under any
    # flag (rebuild it; don't serve garbage covariances)
    want = meta.get("sha256")
    if want is not None:
        got = _payload_sha256(arrays)
        if got != want:
            _telemetry.CHECKPOINT_CORRUPT_TOTAL.inc()
            raise ArtifactCorruptError(
                f"{path}: payload sha256 mismatch (stored {want[:12]}…, "
                f"recomputed {got[:12]}…) — corrupt or tampered artifact")
    if fenced and not force:
        entry = read_pointer(path)
        gen = meta.get("generation")
        if entry is not None and isinstance(gen, int):
            ptr_gen = entry.get("generation")
            if isinstance(ptr_gen, int):
                if gen < ptr_gen:
                    _telemetry.CHECKPOINT_STALE_TOTAL.inc()
                    raise ArtifactStaleError(
                        f"{path}: generation {gen} is older than the "
                        f"latest.json pointer ({ptr_gen}) — stale state "
                        f"(restored backup / superseded writer); pass "
                        f"force to load anyway")
                if gen > ptr_gen:
                    # crash between rename and pointer swap: heal forward
                    _swap_pointer(path, gen, _file_sha256(path))
                    _telemetry.CHECKPOINT_HEAL_FORWARD_TOTAL.inc()
    if fenced and isinstance(meta.get("generation"), int):
        _telemetry.CHECKPOINT_GENERATION.set_value(meta["generation"])
    _telemetry.CHECKPOINT_LOADS_TOTAL.inc()
    _telemetry.CHECKPOINT_LOAD_SECONDS.observe(time.perf_counter() - t0)
    return arrays, meta


def save_risk_outputs(path: str, outputs, meta: dict | None = None):
    """Persist a RiskModelOutputs tuple (stage-6 artifact)."""
    arrays = {f: np.asarray(getattr(outputs, f)) for f in outputs._fields}
    save_artifact(path, arrays, meta)


def load_risk_outputs(path: str):
    """Rehydrate a :func:`save_risk_outputs` artifact.

    Returns ``(RiskModelOutputs, meta)`` — the inverse, so post-hoc
    analytics (specific risk, portfolio risk, bias acceptance tests) can
    run off a finished pipeline's artifact without recomputing the model.
    """
    from mfm_tpu.models.risk_model import RiskModelOutputs

    arrays, meta = load_artifact(path)
    missing = set(RiskModelOutputs._fields) - set(arrays)
    if missing:
        raise ValueError(f"{path}: not a risk-outputs artifact — missing "
                         f"field(s) {sorted(missing)}")
    return RiskModelOutputs(**{f: arrays[f]
                               for f in RiskModelOutputs._fields}), meta


# -- risk-model state (the incremental daily-update checkpoint) --------------

_NW_SCALARS = ("nw_t", "nw_S", "nw_A", "nw_Z")
_NW_STACKED = ("nw_Ps", "nw_hs", "nw_gs", "nw_Slags", "nw_xlags")


def save_risk_state(path: str, state, meta: dict | None = None):
    """Persist a :class:`mfm_tpu.models.risk_model.RiskModelState`.

    The Newey-West carry's per-lag tuples stack into ``(q, ...)`` arrays;
    everything identity-like (static aux + caller alignment metadata such
    as stocks / style names / last date) rides in the JSON ``__meta__``
    buffer.  npz round-trips every dtype bit-exactly, so a rehydrated
    state resumes the scans bitwise.
    """
    t, S, A, Z, Ps, hs, gs, Slags, xlags = state.nw_carry
    arrays = {
        "nw_t": np.asarray(t),
        "nw_S": np.asarray(S),
        "nw_A": np.asarray(A),
        "nw_Z": np.asarray(Z),
        "nw_Ps": np.stack([np.asarray(p) for p in Ps]) if Ps
                 else np.zeros((0,) + np.asarray(A).shape, np.asarray(A).dtype),
        "nw_hs": np.stack([np.asarray(h) for h in hs]) if hs
                 else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "nw_gs": np.stack([np.asarray(g) for g in gs]) if gs
                 else np.zeros((0,), np.asarray(Z).dtype),
        "nw_Slags": np.stack([np.asarray(s) for s in Slags]) if Slags
                    else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "nw_xlags": np.stack([np.asarray(x) for x in xlags]) if xlags
                    else np.zeros((0,) + np.asarray(S).shape, np.asarray(S).dtype),
        "vr_num": np.asarray(state.vr_num),
        "vr_den": np.asarray(state.vr_den),
    }
    # exactly one of the two eigen representations is present: the frozen
    # simulated covariances (default mode) or the draw tensor + raw prefix
    # moments (config.eigen_incremental)
    if state.sim_covs is not None:
        arrays["sim_covs"] = np.asarray(state.sim_covs)
    eig_draws_dtype = None
    if state.eig_draws is not None:
        d = np.asarray(state.eig_draws)
        if d.dtype.kind not in "fiub":
            # extension dtypes (bf16 under eigen_mc_dtype) do not survive
            # npz: np.load hands back raw void bytes, breaking both the
            # payload digest and the consumer.  Store the bit pattern as a
            # same-width unsigned view and record the real dtype in meta.
            eig_draws_dtype = str(d.dtype)
            d = d.view(np.dtype(f"u{d.dtype.itemsize}"))
        arrays["eig_draws"] = d
        arrays["eig_R"] = np.asarray(state.eig_R)
        arrays["eig_p"] = np.asarray(state.eig_p)
        arrays["eig_n"] = np.asarray(state.eig_n)
    if state.guarded:
        arrays["guard_last_good_cov"] = np.asarray(state.last_good_cov)
        arrays["guard_staleness"] = np.asarray(state.staleness)
        arrays["guard_quarantine_count"] = np.asarray(state.quarantine_count)
        arrays["guard_ring"] = np.asarray(state.guard_ring)
        arrays["guard_ring_pos"] = np.asarray(state.guard_ring_pos)
    state_meta = {
        "kind": "risk_state",
        "nw_q": len(Ps),
        "sim_length": state.sim_length,
        "eigen_batch_hint": state.eigen_batch_hint,
        "stamp": _stamp_to_json(state.stamp),
        "last_date": state.last_date,
    }
    if eig_draws_dtype is not None:
        state_meta["eig_draws_dtype"] = eig_draws_dtype
    save_artifact(path, arrays, {**state_meta, **(meta or {})}, fenced=True)


def load_risk_state(path: str, force: bool = False):
    """Rehydrate a :func:`save_risk_state` artifact.

    Returns ``(RiskModelState, meta)``; arrays come back as jax arrays with
    their exact saved dtypes, so ``RiskModel.update`` from the loaded state
    is bitwise the run that would have continued in-process.  Checkpoint
    loads are FENCED: a generation older than the directory's
    ``latest.json`` pointer is refused (:class:`ArtifactStaleError`) unless
    ``force`` — serving yesterday's carries as today's silently forks the
    history.
    """
    import jax.numpy as jnp

    from mfm_tpu.models.risk_model import RiskModelState

    arrays, meta = load_artifact(path, fenced=True, force=force)
    missing = (set(_NW_SCALARS) | set(_NW_STACKED)
               | {"vr_num", "vr_den"}) - set(arrays)
    # the eigen stage is either the frozen sim_covs (default) or the
    # incremental draws+moments quartet — a checkpoint must carry one
    incremental = "eig_draws" in arrays
    if incremental:
        missing |= {"eig_R", "eig_p", "eig_n"} - set(arrays)
    elif "sim_covs" not in arrays:
        missing.add("sim_covs")
    if meta.get("kind") != "risk_state" or missing:
        raise ValueError(f"{path}: not a risk-state artifact"
                         + (f" — missing field(s) {sorted(missing)}"
                            if missing else ""))
    q = int(meta["nw_q"])
    # jnp.array, NOT jnp.asarray: every leaf built here is later DONATED to
    # the fused update jits (donate_argnums).  On CPU, asarray zero-copies
    # the npz-loaded numpy buffer whenever its alignment permits (most of
    # the time, empirically), and donating a buffer JAX does not own lets
    # XLA scribble over host memory — nondeterministic garbage in the very
    # outputs the bitwise-resume contract promises.  jnp.array always copies.
    own = lambda name: jnp.array(arrays[name])
    unstack = lambda name: tuple(jnp.array(arrays[name][i]) for i in range(q))
    nw_carry = (
        own("nw_t"), own("nw_S"), own("nw_A"), own("nw_Z"),
        unstack("nw_Ps"), unstack("nw_hs"), unstack("nw_gs"),
        unstack("nw_Slags"), unstack("nw_xlags"),
    )
    guard = {}
    if "guard_last_good_cov" in arrays:
        guard = dict(
            last_good_cov=own("guard_last_good_cov"),
            staleness=own("guard_staleness"),
            quarantine_count=own("guard_quarantine_count"),
            guard_ring=own("guard_ring"),
            guard_ring_pos=own("guard_ring_pos"),
        )
    eig = {}
    if incremental:
        draws = arrays["eig_draws"]
        if meta.get("eig_draws_dtype"):
            # reverse the save-side unsigned bit-pattern view (bf16 etc.)
            draws = draws.view(np.dtype(meta["eig_draws_dtype"]))
        eig = dict(eig_draws=jnp.array(draws), eig_R=own("eig_R"),
                   eig_p=own("eig_p"), eig_n=own("eig_n"))
    state = RiskModelState(
        nw_carry,
        own("vr_num"),
        own("vr_den"),
        own("sim_covs") if "sim_covs" in arrays else None,
        sim_length=meta["sim_length"],
        eigen_batch_hint=int(meta["eigen_batch_hint"]),
        stamp=_stamp_from_json(meta["stamp"]),
        last_date=meta.get("last_date"),
        **guard,
        **eig,
    )
    return state, meta


def _stamp_to_json(obj):
    """Nested tuples -> nested lists with a tag, reversibly (JSON has no
    tuple; the stamp is compared with ``==`` against a live model's tuple
    stamp, so the round trip must restore tuple-ness exactly)."""
    if isinstance(obj, tuple):
        return {"__tuple__": [_stamp_to_json(x) for x in obj]}
    return obj


def _stamp_from_json(obj):
    if isinstance(obj, dict) and "__tuple__" in obj:
        return tuple(_stamp_from_json(x) for x in obj["__tuple__"])
    return obj
