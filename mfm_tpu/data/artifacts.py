"""Stage-artifact checkpointing.

The reference checkpoints *data* between stages everywhere (Mongo collections
with watermarks, intermediate CSVs — SURVEY.md §5 "Checkpoint / resume").
Here every stage boundary can persist its arrays to an .npz artifact with a
schema stamp, and jitted executables persist via JAX's compilation cache.
"""

from __future__ import annotations

import json
import os
from typing import Mapping

import numpy as np

import jax

FORMAT_VERSION = 1


def save_artifact(path: str, arrays: Mapping[str, object], meta: dict | None = None):
    """Persist a flat dict of arrays (+ JSON-able metadata) atomically."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    payload = {k: np.asarray(v) for k, v in arrays.items()}
    payload["__meta__"] = np.frombuffer(
        json.dumps({"format": FORMAT_VERSION, **(meta or {})}).encode(), dtype=np.uint8
    )
    tmp = path + ".tmp.npz"  # savez appends .npz unless already present
    np.savez_compressed(tmp, **payload)
    os.replace(tmp, path)


def load_artifact(path: str):
    """Returns (arrays dict, meta dict)."""
    with np.load(path, allow_pickle=False) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode()) if "__meta__" in z.files else {}
    return arrays, meta


def save_risk_outputs(path: str, outputs, meta: dict | None = None):
    """Persist a RiskModelOutputs tuple (stage-6 artifact)."""
    arrays = {f: np.asarray(getattr(outputs, f)) for f in outputs._fields}
    save_artifact(path, arrays, meta)


def load_risk_outputs(path: str):
    """Rehydrate a :func:`save_risk_outputs` artifact.

    Returns ``(RiskModelOutputs, meta)`` — the inverse, so post-hoc
    analytics (specific risk, portfolio risk, bias acceptance tests) can
    run off a finished pipeline's artifact without recomputing the model.
    """
    from mfm_tpu.models.risk_model import RiskModelOutputs

    arrays, meta = load_artifact(path)
    missing = set(RiskModelOutputs._fields) - set(arrays)
    if missing:
        raise ValueError(f"{path}: not a risk-outputs artifact — missing "
                         f"field(s) {sorted(missing)}")
    return RiskModelOutputs(**{f: arrays[f]
                               for f in RiskModelOutputs._fields}), meta


def enable_compilation_cache(cache_dir: str | None = None):
    """Persist jitted executables across processes (the reference's analogue
    is nothing — every run recompiles pandas ops; here a second run of the
    same pipeline skips XLA compilation entirely)."""
    cache_dir = cache_dir or os.environ.get(
        "MFM_COMPILE_CACHE", os.path.expanduser("~/.cache/mfm_tpu_xla")
    )
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    return cache_dir
