"""MongoDB adapter for the :class:`mfm_tpu.data.etl.PanelStore` interface.

The reference's actual storage layer is MongoDB (``Barra_database/database/
update_mongo_db.py:579-614``: database ``barra_financial_data``, one
collection per dataset, unique indexes + ``insert_many(ordered=False)`` for
duplicate-tolerant idempotent loads).  This adapter exposes that backend
through the same methods the parquet :class:`PanelStore` offers —
``insert`` / ``read`` / ``replace_where`` / ``replace`` / ``last_date`` /
``distinct_count`` — so :class:`mfm_tpu.data.etl.IncrementalUpdater`,
:func:`mfm_tpu.data.prepare.prepare_factor_inputs`, and the CLI drivers run
unchanged against either.

pymongo is not part of this image; the import is guarded and the class
raises a clear error when constructed without it.  The shared contract test
(``tests/test_store_contract.py``) runs against the parquet store
unconditionally and against this adapter ALWAYS — on a real localhost
server when one is reachable, else on the in-memory pymongo stand-in
(``tests/mongofake.py``), so every code path here (null-key dedup
admission, BulkWriteError triage, the last_date index fallback) executes
hermetically in CI.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None

try:  # pragma: no cover - exercised only where pymongo exists
    import pymongo
    from pymongo.errors import BulkWriteError
except Exception:  # pragma: no cover
    pymongo = None
    BulkWriteError = None


def _transient_mongo_errors() -> tuple:
    """The pymongo exception classes that mean "try again later", resolved
    lazily so the monkeypatched stand-in (tests/mongofake.py, which defines
    only the errors the adapter's logic needs) works too.  The builtin
    connection/timeout errors ride along — drivers and injected transports
    surface raw socket failures as those.
    """
    names = ("AutoReconnect", "NetworkTimeout", "ConnectionFailure",
             "ServerSelectionTimeoutError", "ExecutionTimeout",
             "WTimeoutError")
    errs = tuple(
        t for n in names
        if isinstance(t := getattr(pymongo.errors, n, None), type)
    )
    return errs + (ConnectionError, TimeoutError)


class MongoPanelStore:
    """PanelStore-compatible wrapper over a ``pymongo.database.Database``.

    Unique-key enforcement is Mongo's own unique index (exact, server-side
    — the arbiter the parquet store's hashed key cache approximates), with
    ``insert_many(ordered=False)`` continuing past duplicate-key errors
    (``update_mongo_db.py:118-128``).
    """

    def __init__(self, database):
        if pymongo is None:  # pragma: no cover
            raise ImportError("pymongo is required for MongoPanelStore")
        if pd is None:  # pragma: no cover
            raise ImportError("pandas required")
        self.db = database
        self._indexed: set = set()  # (name, unique cols) already ensured

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _records(df):
        return df.reset_index(drop=True).to_dict("records")

    def _frame(self, cursor, columns=None):
        rows = list(cursor)
        df = pd.DataFrame(rows)
        if "_id" in df.columns:
            df = df.drop(columns=["_id"])
        if columns is not None:
            df = df[list(columns)] if len(df) else pd.DataFrame(
                columns=list(columns))
        return df

    # -- PanelStore interface ---------------------------------------------
    def insert(self, name: str, df, unique: Sequence[str] | None = None) -> int:
        """Append rows; rows whose ``unique`` key already exists are dropped
        (unique index + ``ordered=False``)."""
        if df is None or len(df) == 0:
            return 0
        coll = self.db[name]
        if unique:
            key = (name, tuple(unique))
            if key not in self._indexed:
                # once per (collection, key) per store instance — the ETL
                # statement loop calls insert ~20k times per run_all and
                # must not pay a createIndexes round-trip each time
                coll.create_index([(c, 1) for c in unique], unique=True)
                self._indexed.add(key)
        # ordered=False also for un-keyed inserts: if the collection carries
        # a unique index from an earlier keyed insert, duplicates are
        # skipped (count returned) instead of raising mid-batch.  This is
        # the one divergence from the parquet store, whose un-keyed insert
        # appends duplicates — parquet has no index to enforce.
        try:
            res = coll.insert_many(self._records(df), ordered=False)
            return len(res.inserted_ids)
        except BulkWriteError as e:
            # only duplicate keys (11000) are tolerable; anything else
            # (oversized doc, validation, shard key) must surface — the
            # caller would otherwise advance its watermark past a silent gap
            errs = e.details.get("writeErrors", [])
            if any(we.get("code") != 11000 for we in errs):
                raise
            return e.details.get("nInserted", 0)

    def read(self, name: str, columns: Sequence[str] | None = None):
        proj = {"_id": 0}
        if columns is not None:
            proj.update({c: 1 for c in columns})
        return self._frame(self.db[name].find({}, proj), columns)

    def replace_where(self, name: str, mask_fn, df) -> None:
        """Delete rows matching ``mask_fn`` then insert ``df``.

        ``mask_fn`` is a DataFrame predicate (the parquet store's contract),
        so matching happens client-side: read, evaluate, delete by ``_id``.
        """
        coll = self.db[name]
        rows = list(coll.find({}))
        if rows:
            cur = pd.DataFrame(rows)
            # np.asarray: callers pass either a pandas Series predicate or a
            # bare ndarray (etl.py's all-True full-refresh masks)
            mask = np.asarray(mask_fn(cur.drop(columns=["_id"])))
            if mask.all():
                # full refresh (update_stock_info / update_sw_industries):
                # one server-side wipe, no id round-trip
                coll.delete_many({})
            else:
                ids = cur.loc[mask, "_id"]
                if len(ids):
                    coll.delete_many({"_id": {"$in": list(ids)}})
        if df is not None and len(df):
            # through insert() for ordered=False duplicate tolerance — a
            # unique index from an earlier keyed insert must not abort the
            # refresh mid-batch
            self.insert(name, df)

    def replace(self, name: str, df) -> None:
        """Full refresh: one server-side wipe then insert (the reference's
        drop + ``insert_many``, ``update_mongo_db.py:32-57``)."""
        self.db[name].delete_many({})
        if df is not None and len(df):
            self.insert(name, df)

    def compact(self, name: str) -> None:
        """No-op: Mongo has no parts to merge."""

    def last_date(self, name: str, date_col: str = "trade_date"):
        key = (name, ("__date__", date_col))
        if key not in self._indexed:
            # the compound unique key (ts_code, trade_date) cannot serve a
            # sort on trade_date alone — without this, every watermark read
            # is a full collection scan.  Best-effort: a read-only role
            # (monitoring/report clients) may not createIndexes; the
            # find_one below still answers, just unindexed.  Only an
            # authorization failure is cached as don't-retry — a transient
            # error (stepdown, timeout) must not permanently degrade reads.
            try:
                self.db[name].create_index([(date_col, pymongo.DESCENDING)])
                self._indexed.add(key)
            except pymongo.errors.OperationFailure:
                self._indexed.add(key)
            except _transient_mongo_errors():
                # don't-cache-transient-failures (stated above): a stepdown
                # or timeout must NOT mark the key done — the next call
                # retries and builds the index.  Narrowed from a bare
                # ``except Exception``: a programming error in the index
                # spec must surface, not be swallowed as "transient".
                pass
        doc = self.db[name].find_one(
            {date_col: {"$exists": True}}, {date_col: 1, "_id": 0},
            sort=[(date_col, pymongo.DESCENDING)],
        )
        return None if doc is None else doc[date_col]

    def distinct_count(self, name: str, col: str) -> int:
        return len(self.db[name].distinct(col))
