"""Synthetic data with the reference workload's shape.

Two generators:

- :func:`synthetic_market_panel` — raw daily market + financial data in the
  shape the factor engine consumes (close/turnover/total_mv/pb/pe_ttm/
  financial statement fields), with per-stock listing windows and missing
  data, mirroring the master panel of ``Barra_factor_cal/load_data.py``.
- :func:`synthetic_barra_table` — a finished barra-format long table (the
  ``result/barra_data_csi.csv`` schema: date, stocknames, capital, ret,
  industry, Q styles) for exercising the risk model alone, like
  ``Barra-master/demo.py:22-38``.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


def _dates(T: int, start: str = "2020-01-02") -> np.ndarray:
    """T business days."""
    if pd is not None:
        return pd.bdate_range(start, periods=T).values.astype("datetime64[D]")
    d0 = np.datetime64(start, "D")
    out, d = [], d0
    while len(out) < T:
        if np.is_busday(d):
            out.append(d)
        d += 1
    return np.array(out)


def synthetic_market_panel(
    T: int = 300,
    N: int = 50,
    n_industries: int = 8,
    seed: int = 0,
    missing: float = 0.02,
    listing_gap: float = 0.3,
) -> Dict[str, np.ndarray]:
    """Dense (T, N) market/financial arrays + metadata.

    Fields follow the tushare names the reference joins into its master panel
    (close, turnover_rate, total_mv, circ_mv, pb, pe_ttm, n_cashflow_act,
    q_profit_yoy, q_sales_yoy, total_ncl, total_hldr_eqy_inc_min_int,
    debt_to_assets — see SURVEY.md §2.3 for which factor eats which).
    ``listing_gap`` fraction of stocks list mid-sample (leading NaNs), which
    exercises the ragged-universe masking.
    """
    rng = np.random.default_rng(seed)
    dates = _dates(T)
    stocks = np.array([f"{600000 + i}.SH" for i in range(N)])
    industry = rng.integers(0, n_industries, size=N)

    # market factor + idiosyncratic returns
    mkt = 0.0003 + 0.01 * rng.standard_normal(T)
    beta = 0.5 + rng.random(N)
    idio = 0.015 * rng.standard_normal((T, N)) * (0.5 + rng.random(N))
    ret = beta[None, :] * mkt[:, None] + idio
    close0 = np.exp(2.0 + rng.standard_normal(N))
    close = close0[None, :] * np.cumprod(1.0 + ret, axis=0)
    index_close = 3000.0 * np.cumprod(1.0 + mkt)

    total_mv = np.exp(rng.normal(11.0, 1.2, size=N))[None, :] * np.cumprod(
        1.0 + ret, axis=0
    )
    circ_mv = total_mv * (0.4 + 0.5 * rng.random(N))[None, :]
    turnover = np.exp(rng.normal(0.0, 0.8, size=(T, N)))  # percent units
    pb = np.exp(rng.normal(0.8, 0.5, size=(T, N)))
    pb[rng.random((T, N)) < 0.01] *= -1  # a few nonpositive pb -> NaN BP
    pe = np.exp(rng.normal(3.0, 0.7, size=(T, N)))
    pe[rng.random((T, N)) < 0.02] *= -1

    # quarterly report fields, forward-filled daily like the PIT join output
    n_q = T // 63 + 2
    q_cash = rng.normal(1e5, 5e4, size=(n_q, N))
    q_profit = rng.normal(10.0, 20.0, size=(n_q, N))
    q_sales = rng.normal(8.0, 15.0, size=(n_q, N))
    q_idx = np.minimum(np.arange(T) // 63, n_q - 1)
    end_date_code = q_idx[:, None] * np.ones((1, N), dtype=int)
    n_cashflow_act = q_cash[q_idx]
    q_profit_yoy = q_profit[q_idx]
    q_sales_yoy = q_sales[q_idx]

    total_ncl = np.exp(rng.normal(10.0, 1.0, size=(T, N)))
    book = np.exp(rng.normal(10.5, 1.0, size=(T, N)))
    book[rng.random((T, N)) < 0.01] *= -1
    dtoa = 100.0 * rng.random((T, N)) * 0.8

    fields = {
        "close": close,
        "total_mv": total_mv,
        "circ_mv": circ_mv,
        "turnover_rate": turnover,
        "pb": pb,
        "pe_ttm": pe,
        "n_cashflow_act": n_cashflow_act,
        "q_profit_yoy": q_profit_yoy,
        "q_sales_yoy": q_sales_yoy,
        "total_ncl": total_ncl,
        "total_hldr_eqy_inc_min_int": book,
        "debt_to_assets": dtoa,
    }

    # listing gaps: leading NaNs per stock; plus sparse random missingness
    start_idx = np.zeros(N, dtype=int)
    late = rng.random(N) < listing_gap
    start_idx[late] = rng.integers(1, max(2, T // 2), size=late.sum())
    row = np.arange(T)[:, None]
    alive = row >= start_idx[None, :]
    holes = rng.random((T, N)) >= missing
    obs = alive & holes
    for k, v in fields.items():
        v = v.astype(np.float64)
        v[~obs] = np.nan
        fields[k] = v
    fields["end_date_code"] = np.where(obs, end_date_code, -1)

    return {
        "dates": dates,
        "stocks": stocks,
        "industry": industry,
        "index_close": index_close,
        "observed": obs,
        **fields,
    }


#: non-field metadata keys in a :func:`synthetic_market_panel` result
PANEL_META_KEYS = ("dates", "stocks", "industry", "index_close", "observed",
                   "end_date_code")


class Universe:
    """A named (T, N, P, Q) workload shape — the ``--universe`` knob.

    ``csi300`` is the flagship CSI300-shaped panel every BENCH_r* record
    before r06 was measured on; ``alla`` is the full A-share universe of
    PAPER.md's Barra/USE4 pipeline (~5,000 names).  An integer spec gives
    an N-stock universe with the CSI300 history length and the same USE4
    factor structure (P=31 industries + Q=10 styles) so walls stay
    comparable along the N axis alone.
    """

    __slots__ = ("name", "T", "N", "P", "Q")

    def __init__(self, name, T, N, P, Q):
        self.name, self.T, self.N, self.P, self.Q = name, T, N, P, Q

    def __repr__(self):
        return (f"Universe({self.name!r}, T={self.T}, N={self.N}, "
                f"P={self.P}, Q={self.Q})")


#: the named universes (T, N, P, Q).  csi300 matches bench.py's historical
#: config-1 shapes; alla matches config-4 (bench_alla).
UNIVERSES = {
    "csi300": (1390, 300, 31, 10),
    "alla": (2500, 5000, 31, 10),
}


def resolve_universe(spec, T: int | None = None) -> Universe:
    """``'csi300' | 'alla' | N`` (int-like) -> :class:`Universe`.

    ``T`` overrides the history length (e.g. a bounded smoke run at
    N=5000); the override is recorded in the universe's name so a record
    produced from it can never masquerade as the full-length workload.
    """
    if isinstance(spec, str) and spec in UNIVERSES:
        t0, n, p, q = UNIVERSES[spec]
        name = spec
    else:
        try:
            n = int(spec)
        except (TypeError, ValueError):
            raise ValueError(
                f"unknown universe {spec!r}: expected "
                f"{sorted(UNIVERSES)} or an integer stock count") from None
        if n <= 0:
            raise ValueError(f"universe N must be positive, got {n}")
        t0, (_, p, q) = UNIVERSES["csi300"][0], UNIVERSES["csi300"][1:]
        name = f"n{n}"
    t = t0 if T is None else int(T)
    if t != t0:
        name = f"{name}_t{t}"
    return Universe(name, t, n, p, q)


def panel_to_engine_fields(data: Dict, dtype) -> Dict:
    """The :class:`mfm_tpu.factors.engine.FactorEngine` field dict for a
    :func:`synthetic_market_panel` result: float fields cast to ``dtype``,
    the integer report id passed through untouched (one shared builder —
    bench, the parity tool, and the tests must not each hand-maintain the
    metadata exclusion list)."""
    import jax.numpy as jnp

    fields = {k: jnp.asarray(v, dtype) for k, v in data.items()
              if k not in PANEL_META_KEYS}
    fields["end_date_code"] = jnp.asarray(data["end_date_code"])
    return fields


def synthetic_collections(
    store,
    T: int = 120,
    N: int = 20,
    n_industries: int = 5,
    index_code: str = "000300.SH",
    seed: int = 0,
    start: str = "2020-01-02",
    missing: float = 0.02,
    listing_gap: float = 0.2,
    revision_rate: float = 0.3,
):
    """Fill a :class:`mfm_tpu.data.etl.PanelStore` with raw tushare-shaped
    collections (yyyymmdd string dates, the storage format of the reference's
    Mongo collections, ``update_mongo_db.py:59-342``).

    Produces the six collections ``load_and_prepare_data`` consumes plus
    ``stock_info``: daily_prices, balancesheet, cashflow,
    financial_indicators, index_daily_prices, index_components,
    sw_industries.  ``revision_rate`` of statements get a second announcement
    (same end_date, later f_ann_date, revised values) to exercise the
    two-pass dedup; one extra stock exists outside the index to exercise
    universe selection.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    rng = np.random.default_rng(seed)
    dates = pd.bdate_range(start, periods=T)
    date_strs = dates.strftime("%Y%m%d")
    # N constituents + 1 non-member (must be excluded by universe selection)
    all_stocks = [f"{600000 + i}.SH" for i in range(N + 1)]
    members, outsider = all_stocks[:N], all_stocks[N]
    l1_codes = [f"801{(i % n_industries):02d}0.SI" for i in range(N + 1)]

    store.insert("stock_info", pd.DataFrame({
        "ts_code": all_stocks,
        "name": [f"stk{i}" for i in range(N + 1)],
        "list_date": ["20100101"] * (N + 1),
    }), unique=("ts_code",))

    # --- daily prices (with listing gaps + random holes) -------------------
    mkt = 0.0003 + 0.01 * rng.standard_normal(T)
    rows = []
    for j, code in enumerate(all_stocks):
        beta = 0.5 + rng.random()
        ret = beta * mkt + 0.015 * rng.standard_normal(T)
        close = np.exp(2.0 + rng.standard_normal()) * np.cumprod(1 + ret)
        mv0 = np.exp(rng.normal(11.0, 1.0))
        start_i = (rng.integers(1, max(2, T // 3))
                   if rng.random() < listing_gap else 0)
        for t in range(start_i, T):
            if rng.random() < missing:
                continue
            rows.append({
                "ts_code": code, "trade_date": date_strs[t],
                "close": close[t], "total_mv": mv0 * close[t] / close[0],
                "circ_mv": 0.7 * mv0 * close[t] / close[0],
                "pb": np.exp(rng.normal(0.8, 0.3)),
                "turnover_rate": np.exp(rng.normal(0.0, 0.6)),
                "pe_ttm": np.exp(rng.normal(3.0, 0.5)),
            })
    store.insert("daily_prices", pd.DataFrame(rows),
                 unique=("ts_code", "trade_date"))

    # --- quarterly statements (with revisions) -----------------------------
    q_ends = pd.date_range(
        pd.Timestamp(start) - pd.offsets.QuarterEnd() * 6,
        dates[-1], freq="QE")
    bal_rows, cf_rows, fi_rows = [], [], []
    for code in all_stocks:
        for qe in q_ends:
            ann = qe + pd.Timedelta(days=int(rng.integers(20, 80)))
            rec = {
                "ts_code": code,
                "end_date": qe.strftime("%Y%m%d"),
                "f_ann_date": ann.strftime("%Y%m%d"),
            }
            bal_rows.append({**rec,
                             "total_ncl": np.exp(rng.normal(10.0, 0.5)),
                             "total_hldr_eqy_inc_min_int":
                                 np.exp(rng.normal(10.5, 0.5))})
            cf_rows.append({**rec,
                            "n_cashflow_act": rng.normal(1e5, 5e4)})
            fi_rows.append({"ts_code": code,
                            "end_date": rec["end_date"],
                            "ann_date": rec["f_ann_date"],
                            "q_profit_yoy": rng.normal(10, 15),
                            "q_sales_yoy": rng.normal(8, 12),
                            "debt_to_assets": 80 * rng.random()})
            if rng.random() < revision_rate:  # revised announcement
                ann2 = ann + pd.Timedelta(days=int(rng.integers(5, 40)))
                bal_rows.append({**rec,
                                 "f_ann_date": ann2.strftime("%Y%m%d"),
                                 "total_ncl": np.exp(rng.normal(10.0, 0.5)),
                                 "total_hldr_eqy_inc_min_int":
                                     np.exp(rng.normal(10.5, 0.5))})
                cf_rows.append({**rec,
                                "f_ann_date": ann2.strftime("%Y%m%d"),
                                "n_cashflow_act": rng.normal(1e5, 5e4)})
    store.insert("balancesheet", pd.DataFrame(bal_rows),
                 unique=("ts_code", "end_date", "f_ann_date"))
    store.insert("cashflow", pd.DataFrame(cf_rows),
                 unique=("ts_code", "end_date", "f_ann_date"))
    store.insert("financial_indicators", pd.DataFrame(fi_rows),
                 unique=("ts_code", "end_date", "ann_date"))

    # --- index prices + components + SW industries -------------------------
    store.insert("index_daily_prices", pd.DataFrame({
        "ts_code": index_code, "trade_date": date_strs,
        "close": 3000.0 * np.cumprod(1 + mkt),
    }), unique=("ts_code", "trade_date"))
    # two snapshots; universe selection must use the latest one only
    old_members = members[: max(1, N - 2)] + [outsider]
    comp = pd.concat([
        pd.DataFrame({"index_code": index_code, "trade_date": date_strs[0],
                      "con_code": old_members}),
        pd.DataFrame({"index_code": index_code, "trade_date": date_strs[-1],
                      "con_code": members}),
    ])
    store.insert("index_components", comp,
                 unique=("index_code", "trade_date", "con_code"))
    sw = pd.DataFrame({
        "ts_code": all_stocks, "l1_code": l1_codes,
        "l1_name": [f"ind_{c[3:5]}" for c in l1_codes],
        "in_date": "20100101", "out_date": None, "is_new": "Y",
    })
    # a stale membership row that must lose to is_new == 'Y'
    stale = sw.iloc[:2].copy()
    stale["l1_code"] = "801990.SI"
    stale["is_new"] = "N"
    store.insert("sw_industries", pd.concat([stale, sw]))
    return {"dates": date_strs, "stocks": members, "index_code": index_code}


def synthetic_barra_table(
    T: int = 120,
    N: int = 60,
    P: int = 6,
    Q: int = 4,
    seed: int = 0,
    missing: float = 0.05,
):
    """A long barra-format DataFrame like ``result/barra_data_csi.csv``.

    Returns (df, style_names).  Industry codes are strings like the SW L1
    codes; returns are generated from a true factor structure so the WLS
    stage has signal to find.  ``missing`` drops whole stock-date rows
    (ragged universes); every industry is guaranteed at least one member per
    date so the constraint matrix stays finite (the reference divides by the
    last industry's cap, ``CrossSection.py:70``).
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    rng = np.random.default_rng(seed)
    dates = _dates(T)
    stocks = np.array([f"{600000 + i}.SH" for i in range(N)])
    # ensure every industry has >= ceil(N/P) members; keep >= 3 per industry
    industry = np.arange(N) % P
    rng.shuffle(industry)
    styles = rng.standard_normal((T, N, Q))
    f_style = 0.002 * rng.standard_normal((T, Q))
    f_ind = 0.003 * rng.standard_normal((T, P))
    f_cty = 0.0005 * rng.standard_normal(T)
    ind_oh = np.eye(P)[industry]  # (N, P)
    ret = (
        f_cty[:, None]
        + (ind_oh @ f_ind.T).T
        + np.einsum("tnq,tq->tn", styles, f_style)
        + 0.01 * rng.standard_normal((T, N))
    )
    cap = np.exp(rng.normal(11.0, 1.0, size=N))[None, :] * np.ones((T, 1))

    keep = rng.random((T, N)) >= missing
    # guarantee every industry present each date: always keep the first
    # member of each industry
    first_member = np.array([np.argmax(industry == p) for p in range(P)])
    keep[:, first_member] = True

    ti, si = np.nonzero(keep)
    style_names = [f"style_{q}" for q in range(Q)]
    df = pd.DataFrame(
        {
            "date": np.asarray(dates)[ti].astype("datetime64[D]").astype(str),
            "stocknames": stocks[si],
            "capital": cap[ti, si],
            "ret": ret[ti, si],
            "industry": np.array([f"sw{p:02d}" for p in industry])[si],
        }
    )
    for q, name in enumerate(style_names):
        df[name] = styles[ti, si, q]
    return df, style_names
