"""Master-panel orchestration: PanelStore -> FactorEngine inputs.

The framework's equivalent of the reference's ``load_and_prepare_data()``
(``Barra_factor_cal/load_data.py:66-418``) plus the SW-industry merge of
``Barra_factor_cal/main.py:98``:

1. universe = latest index constituents (``load_data.py:92-123``)
2. load the six collections, projected to the pipeline's columns
   (``load_data.py:130-257``)
3. statement dedup — two-pass for balancesheet/cashflow
   (``load_data.py:268-298``), single-pass for financial indicators
   (``load_data.py:305-309``)
4. chained point-in-time as-of joins on announcement dates
   (``load_data.py:329-378``)
5. missing-value policy: per-stock ffill then 0 (``load_data.py:390-418``;
   the reference's trailing per-date median fill runs after ``fillna(0)``
   and is therefore dead code — see :func:`mfm_tpu.data.pit.fill_missing`)
6. densify the long master frame into the (T, N) field dict
   :class:`mfm_tpu.factors.engine.FactorEngine` consumes, plus the aligned
   index close series and per-stock SW L1 industry codes.

Documented deviations from the reference (quirks, not omissions):

- ``load_data.py:83`` hardcodes ``index_code="000016.SH"`` (SSE 50) inside
  the nominally-CSI300 pipeline; here the index is a parameter defaulting to
  CSI300 (``000300.SH``), the universe the rest of the reference uses.
- The reference ffills *and zero-fills* the announcement/report **date**
  columns (``load_data.py:396-407``), so pre-first-report rows carry epoch
  dates.  Here date columns are ffilled but never zero-filled; rows with no
  report yet get ``end_date_code = -1`` (no-report sentinel), which the TTM
  kernel treats as missing.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import numpy as np

from mfm_tpu.data.pit import asof_join, dedup_statements, fill_missing
from mfm_tpu.panel import Panel

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None

#: projections per collection (load_data.py:134-257)
DAILY_FIELDS = ("close", "total_mv", "circ_mv", "pb", "turnover_rate", "pe_ttm")
BALANCE_FIELDS = ("total_ncl", "total_hldr_eqy_inc_min_int")
INDICATOR_FIELDS = ("q_profit_yoy", "q_sales_yoy", "debt_to_assets")
CASHFLOW_FIELDS = ("n_cashflow_act",)

#: the master-frame fill set (load_data.py:393-402), numeric part
FILL_FIELDS = ("pe_ttm", "pb", "total_ncl", "total_hldr_eqy_inc_min_int",
               "debt_to_assets", "q_sales_yoy", "q_profit_yoy",
               "n_cashflow_act")
#: announcement/report date columns, ffilled but never zero-filled (see
#: module docstring)
FILL_DATE_COLS = ("balance_sheet_f_ann_date", "financial_indicators_ann_date",
                  "cashflow_f_ann_date", "end_date")


def _to_dt(s, col):
    out = s.copy()
    if not pd.api.types.is_datetime64_any_dtype(out[col]):
        out[col] = pd.to_datetime(out[col].astype(str), format="%Y%m%d")
    return out


def latest_index_constituents(store, index_code: str) -> list:
    """Universe selection: constituents at the newest trade_date recorded for
    ``index_code`` (``load_data.py:92-123``)."""
    comp = store.read("index_components")
    if not len(comp):
        raise ValueError("index_components collection is empty")
    comp = comp[comp["index_code"] == index_code]
    if not len(comp):
        raise ValueError(f"no index_components rows for {index_code!r}")
    latest = comp["trade_date"].max()
    return sorted(comp.loc[comp["trade_date"] == latest, "con_code"].unique())


def dedup_indicators(df, by="ts_code", ann_col="ann_date", end_col="end_date"):
    """The financial-indicator dedup is SINGLE-pass in the reference
    (``load_data.py:305-309``): keep the latest report period per (stock,
    announcement date) — unlike the two-pass statement dedup."""
    df = df.sort_values([by, ann_col, end_col], ascending=[True, True, False])
    return df.drop_duplicates(subset=[by, ann_col], keep="first")


def load_and_prepare_data(
    store,
    index_code: str = "000300.SH",
    start_date: str | None = "20200101",
    end_date: str | None = None,
    fin_start_date: str | None = "20190101",
    median_fill: bool = False,
):
    """Store -> (master long frame, index prices frame, sw industry frame).

    Mirrors ``load_and_prepare_data`` end to end (``load_data.py:66-418``).
    Returns pandas objects; :func:`prepare_factor_inputs` densifies them.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")

    universe = latest_index_constituents(store, index_code)

    def _window(df, col, lo, hi):
        if lo is not None:
            df = df[df[col] >= lo]
        if hi is not None:
            df = df[df[col] <= hi]
        return df

    daily = store.read("daily_prices")
    daily = daily[daily["ts_code"].isin(universe)]
    daily = _window(daily, "trade_date", start_date, end_date)
    daily = _to_dt(daily[["ts_code", "trade_date", *DAILY_FIELDS]], "trade_date")

    def _stmt(name, ann_col, cols):
        df = store.read(name)
        if not len(df):
            raise ValueError(f"collection {name!r} is empty")
        df = df[df["ts_code"].isin(universe)]
        df = _window(df, "end_date", fin_start_date, end_date)
        df = df[["ts_code", ann_col, "end_date", *cols]]
        return _to_dt(_to_dt(df, ann_col), "end_date")

    balance = dedup_statements(
        _stmt("balancesheet", "f_ann_date", BALANCE_FIELDS))
    cashflow = dedup_statements(
        _stmt("cashflow", "f_ann_date", CASHFLOW_FIELDS))
    indicators = dedup_indicators(
        _stmt("financial_indicators", "ann_date", INDICATOR_FIELDS))

    index_px = store.read("index_daily_prices")
    index_px = index_px[index_px["ts_code"] == index_code]
    index_px = _window(index_px, "trade_date", start_date, end_date)
    index_px = _to_dt(index_px[["ts_code", "trade_date", "close"]], "trade_date")

    sw = store.read("sw_industries")
    keep = [c for c in ("ts_code", "l1_code", "l1_name", "in_date",
                        "out_date", "is_new") if c in sw.columns]
    sw = sw[keep] if len(sw) else sw

    # --- PIT join chain (load_data.py:329-378) -------------------------------
    # rename announcement columns up front (the reference renames after each
    # merge); drop the balancesheet/indicator report periods (end_date_x/_y,
    # load_data.py:383) so the surviving end_date is the cashflow's
    balance = balance.rename(columns={"f_ann_date": "balance_sheet_f_ann_date"})
    balance = balance.drop(columns=["end_date"])
    indicators = indicators.rename(
        columns={"ann_date": "financial_indicators_ann_date"})
    indicators = indicators.drop(columns=["end_date"])
    cashflow = cashflow.rename(columns={"f_ann_date": "cashflow_f_ann_date"})

    master = asof_join(daily, balance, left_on="trade_date",
                       right_on="balance_sheet_f_ann_date")
    master = asof_join(master, indicators, left_on="trade_date",
                       right_on="financial_indicators_ann_date")
    master = asof_join(master, cashflow, left_on="trade_date",
                       right_on="cashflow_f_ann_date")

    # --- fill policy (load_data.py:390-418) ---------------------------------
    master = fill_missing(master, FILL_FIELDS, median_fill=median_fill)
    date_cols = [c for c in FILL_DATE_COLS if c in master.columns]
    master[date_cols] = master.groupby("ts_code", observed=True)[date_cols].ffill()
    return master, index_px, sw


def sw_l1_map(sw, stocks: Sequence) -> np.ndarray:
    """Per-stock SW L1 code, aligned to ``stocks``.

    The reference merges ``sw_industry_data[['ts_code','l1_code']]`` straight
    onto the factor frame (``main.py:98``), which silently duplicates rows
    when a stock has several classification records; here current membership
    wins (``is_new == 'Y'`` where the column exists, else the last record).
    """
    df = sw
    if len(df) and "is_new" in df.columns:
        cur = df[df["is_new"] == "Y"]
        df = cur if len(cur) else df
    ser = (df.drop_duplicates("ts_code", keep="last")
           .set_index("ts_code")["l1_code"])
    return ser.reindex(stocks).to_numpy()


@dataclasses.dataclass
class PreparedData:
    """Dense FactorEngine inputs + metadata, ready for
    :func:`mfm_tpu.pipeline.run_factor_pipeline`."""

    fields: Dict[str, np.ndarray]   # (T, N) float arrays + int end_date_code
    index_close: np.ndarray         # (T,)
    industry_l1: np.ndarray         # (N,) SW L1 codes
    dates: np.ndarray               # (T,) datetime64[D]
    stocks: np.ndarray              # (N,)


def prepare_factor_inputs(
    store,
    index_code: str = "000300.SH",
    start_date: str | None = "20200101",
    end_date: str | None = None,
    fin_start_date: str | None = "20190101",
    median_fill: bool = False,
) -> PreparedData:
    """The full store -> FactorEngine-fields path (missing piece #1 of
    VERDICT round 1): universe, collections, dedup, PIT joins, fill,
    densify."""
    master, index_px, sw = load_and_prepare_data(
        store, index_code, start_date, end_date, fin_start_date, median_fill)

    value_cols = list(dict.fromkeys(DAILY_FIELDS + FILL_FIELDS))
    p = Panel.from_long(master, value_cols=value_cols)

    # report id for the TTM kernel: rank-encode the (ffilled, never
    # zero-filled) cashflow end_date; NaT -> -1
    ed = master["end_date"]
    codes = np.sort(ed.dropna().unique())
    rid_long = np.where(ed.isna(), -1, np.searchsorted(codes, ed.to_numpy()))
    t_idx = {d: i for i, d in enumerate(p.dates)}
    s_idx = {s: j for j, s in enumerate(p.stocks)}
    rid = np.full((p.T, p.N), -1, np.int32)
    rid[master["trade_date"].map(t_idx).to_numpy(),
        master["ts_code"].map(s_idx).to_numpy()] = rid_long
    fields = dict(p.fields)
    fields["end_date_code"] = rid

    index_close = (index_px.set_index("trade_date")["close"]
                   .reindex(pd.Index(p.dates)).to_numpy(np.float64))
    return PreparedData(
        fields=fields,
        index_close=index_close,
        industry_l1=sw_l1_map(sw, p.stocks),
        dates=np.asarray(p.dates),
        stocks=np.asarray(p.stocks),
    )
