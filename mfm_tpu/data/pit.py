"""Point-in-time alignment: statement dedup, as-of joins, fill policy.

Contracts from ``Barra_factor_cal/load_data.py``:

- :func:`dedup_statements` — two-pass dedup (``load_data.py:264-310``): keep
  the latest announcement per (stock, report period), then the latest report
  period per (stock, announcement).
- :func:`asof_join` — for each (stock, trade day), the row of the statement
  table with the newest announcement date <= trade day
  (``load_data.py:324-378``).  The reference loops Python over stocks and
  calls ``pd.merge_asof`` per chunk (``load_data.py:41-62``); here one
  vectorized ``searchsorted`` over the whole sorted table does all stocks at
  once.
- :func:`fill_missing` — per-stock ffill then fill 0 (``load_data.py:390-408``).
  NOTE (reference quirk, SURVEY.md §7.3): the reference *also* has a per-date
  cross-sectional median fill (``load_data.py:409-418``) but runs it after
  ``fillna(0)`` has already removed every NaN — it is dead code.  We default
  to the effective behavior (ffill -> 0) and expose the evidently intended
  order (ffill -> daily median -> 0) behind ``median_fill=True``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


def dedup_statements(df, by: str = "ts_code", ann_col: str = "f_ann_date",
                     end_col: str = "end_date"):
    """Two-pass statement dedup (``load_data.py:268-278``)."""
    df = df.sort_values([by, end_col, ann_col], ascending=[True, True, False])
    df = df.drop_duplicates(subset=[by, end_col], keep="first")
    df = df.sort_values([by, ann_col, end_col], ascending=[True, True, False])
    df = df.drop_duplicates(subset=[by, ann_col], keep="first")
    return df


def asof_join(left, right, *, left_on: str, right_on: str, by: str = "ts_code",
              suffix: str = "_stmt"):
    """Backward as-of join of ``right`` onto ``left`` per ``by`` group.

    Equivalent to the reference's per-stock ``pd.merge_asof(...,
    direction='backward')`` loop, implemented as one searchsorted over a
    (group, time) composite key — O((L+R) log R) total, no Python loop.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    left = left.sort_values([by, left_on], kind="mergesort").reset_index(drop=True)
    right = right.sort_values([by, right_on], kind="mergesort").reset_index(drop=True)

    # composite integer keys: group id * big + time rank
    keys = pd.unique(pd.concat([left[by], right[by]], ignore_index=True))
    gid = {k: i for i, k in enumerate(keys)}
    lg = left[by].map(gid).to_numpy(np.int64)
    rg = right[by].map(gid).to_numpy(np.int64)
    lt = left[left_on].to_numpy().astype("datetime64[ns]").astype(np.int64)
    rt = right[right_on].to_numpy().astype("datetime64[ns]").astype(np.int64)

    # rank-compress times so (group, time) packs into one int64 key
    uniq = np.unique(np.concatenate([lt, rt]))
    ltr = np.searchsorted(uniq, lt)
    rtr = np.searchsorted(uniq, rt)
    stride = np.int64(len(uniq) + 1)
    lkey = lg * stride + ltr
    rkey = rg * stride + rtr
    pos = np.searchsorted(rkey, lkey, side="right") - 1
    ok = pos >= 0
    ok &= np.where(ok, rg[np.maximum(pos, 0)] == lg, False)

    out = left.copy()
    rcols = [c for c in right.columns if c != by]
    for c in rcols:
        vals = right[c].to_numpy()
        take = np.where(ok, np.maximum(pos, 0), 0)
        col = vals[take]
        col = pd.Series(col).where(ok, other=pd.NA)
        name = c if c not in out.columns else c + suffix
        out[name] = col.to_numpy()
    return out


def diagnose_statements(df, by: str = "ts_code", ann_col: str = "f_ann_date",
                        end_col: str = "end_date") -> dict:
    """Per-stock statement-table QC — the reference's bad-group hunt.

    The reference debugged broken ``merge_asof`` groups by bisecting the
    stock list in a notebook until the offending frames surfaced
    (``try_1017.ipynb`` cells 9-12: null/dtype checks, monotonic-sort
    assertions, per-stock isolation).  This does the whole hunt in one
    vectorized pass and names the offenders directly.  Issues per stock:

    - ``missing_ann`` / ``missing_end`` — NaT/NaN key dates (these rows
      silently vanish from a PIT join's keying);
    - ``dup_ann`` / ``dup_end`` — duplicate (stock, announcement) or
      (stock, period-end) keys surviving in the input, counting EVERY row
      in a duplicate group (each group of g rows contributes g, of which
      :func:`dedup_statements` would keep one);
    - ``ann_before_end`` — announcement dated before its own period end
      (a statement cannot be public before the period closes; almost
      always a data-entry error that shifts the PIT availability early).

    Returns ``{"n_rows", "n_stocks", "issue_counts": {issue: row count},
    "stocks": {ts_code: [issues]}}`` — clean input gives empty dicts.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    missing_cols = [c for c in (by, ann_col, end_col) if c not in df.columns]
    if missing_cols:
        raise ValueError(
            f"not a statement table: missing column(s) {missing_cols} "
            f"(have: {sorted(df.columns)})")
    ann = pd.to_datetime(df[ann_col], errors="coerce")
    end = pd.to_datetime(df[end_col], errors="coerce")
    flags = {
        "missing_ann": ann.isna(),
        "missing_end": end.isna(),
        "dup_ann": df.duplicated([by, ann_col], keep=False) & ann.notna(),
        "dup_end": df.duplicated([by, end_col], keep=False) & end.notna(),
        "ann_before_end": ann.notna() & end.notna() & (ann < end),
    }
    stocks: dict[str, list[str]] = {}
    counts: dict[str, int] = {}
    for issue, mask in flags.items():
        n = int(mask.sum())
        if not n:
            continue
        counts[issue] = n
        for code in df.loc[mask, by].unique():
            stocks.setdefault(code, []).append(issue)
    return {"n_rows": int(len(df)),
            "n_stocks": int(df[by].nunique()),
            "issue_counts": counts,
            "stocks": {k: stocks[k] for k in sorted(stocks)}}


def fill_missing(df, cols: Sequence[str], by: str = "ts_code",
                 date_col: str = "trade_date", median_fill: bool = False):
    """Missing-value policy over the merged master frame
    (``load_data.py:390-418``)."""
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    df = df.sort_values([by, date_col]).reset_index(drop=True)
    df[list(cols)] = df.groupby(by, observed=True)[list(cols)].ffill()
    if median_fill:
        for c in cols:
            med = df.groupby(date_col)[c].transform("median")
            df[c] = df[c].fillna(med)
    df[list(cols)] = df[list(cols)].fillna(0)
    return df
