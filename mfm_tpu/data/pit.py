"""Point-in-time alignment: statement dedup, as-of joins, fill policy.

Contracts from ``Barra_factor_cal/load_data.py``:

- :func:`dedup_statements` — two-pass dedup (``load_data.py:264-310``): keep
  the latest announcement per (stock, report period), then the latest report
  period per (stock, announcement).
- :func:`asof_join` — for each (stock, trade day), the row of the statement
  table with the newest announcement date <= trade day
  (``load_data.py:324-378``).  The reference loops Python over stocks and
  calls ``pd.merge_asof`` per chunk (``load_data.py:41-62``); here one
  vectorized ``searchsorted`` over the whole sorted table does all stocks at
  once.
- :func:`fill_missing` — per-stock ffill then fill 0 (``load_data.py:390-408``).
  NOTE (reference quirk, SURVEY.md §7.3): the reference *also* has a per-date
  cross-sectional median fill (``load_data.py:409-418``) but runs it after
  ``fillna(0)`` has already removed every NaN — it is dead code.  We default
  to the effective behavior (ffill -> 0) and expose the evidently intended
  order (ffill -> daily median -> 0) behind ``median_fill=True``.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

try:
    import pandas as pd
except Exception:  # pragma: no cover
    pd = None


def dedup_statements(df, by: str = "ts_code", ann_col: str = "f_ann_date",
                     end_col: str = "end_date"):
    """Two-pass statement dedup (``load_data.py:268-278``)."""
    df = df.sort_values([by, end_col, ann_col], ascending=[True, True, False])
    df = df.drop_duplicates(subset=[by, end_col], keep="first")
    df = df.sort_values([by, ann_col, end_col], ascending=[True, True, False])
    df = df.drop_duplicates(subset=[by, ann_col], keep="first")
    return df


def asof_join(left, right, *, left_on: str, right_on: str, by: str = "ts_code",
              suffix: str = "_stmt"):
    """Backward as-of join of ``right`` onto ``left`` per ``by`` group.

    Equivalent to the reference's per-stock ``pd.merge_asof(...,
    direction='backward')`` loop, implemented as one searchsorted over a
    (group, time) composite key — O((L+R) log R) total, no Python loop.
    """
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    left = left.sort_values([by, left_on], kind="mergesort").reset_index(drop=True)
    right = right.sort_values([by, right_on], kind="mergesort").reset_index(drop=True)

    # composite integer keys: group id * big + time rank
    keys = pd.unique(pd.concat([left[by], right[by]], ignore_index=True))
    gid = {k: i for i, k in enumerate(keys)}
    lg = left[by].map(gid).to_numpy(np.int64)
    rg = right[by].map(gid).to_numpy(np.int64)
    lt = left[left_on].to_numpy().astype("datetime64[ns]").astype(np.int64)
    rt = right[right_on].to_numpy().astype("datetime64[ns]").astype(np.int64)

    # rank-compress times so (group, time) packs into one int64 key
    uniq = np.unique(np.concatenate([lt, rt]))
    ltr = np.searchsorted(uniq, lt)
    rtr = np.searchsorted(uniq, rt)
    stride = np.int64(len(uniq) + 1)
    lkey = lg * stride + ltr
    rkey = rg * stride + rtr
    pos = np.searchsorted(rkey, lkey, side="right") - 1
    ok = pos >= 0
    ok &= np.where(ok, rg[np.maximum(pos, 0)] == lg, False)

    out = left.copy()
    rcols = [c for c in right.columns if c != by]
    for c in rcols:
        vals = right[c].to_numpy()
        take = np.where(ok, np.maximum(pos, 0), 0)
        col = vals[take]
        col = pd.Series(col).where(ok, other=pd.NA)
        name = c if c not in out.columns else c + suffix
        out[name] = col.to_numpy()
    return out


def fill_missing(df, cols: Sequence[str], by: str = "ts_code",
                 date_col: str = "trade_date", median_fill: bool = False):
    """Missing-value policy over the merged master frame
    (``load_data.py:390-418``)."""
    if pd is None:  # pragma: no cover
        raise ImportError("pandas required")
    df = df.sort_values([by, date_col]).reset_index(drop=True)
    df[list(cols)] = df.groupby(by, observed=True)[list(cols)].ffill()
    if median_fill:
        for c in cols:
            med = df.groupby(date_col)[c].transform("median")
            df[c] = df[c].fillna(med)
    df[list(cols)] = df[list(cols)].fillna(0)
    return df
