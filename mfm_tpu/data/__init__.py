"""Host-side data layer: no JAX in the hot path here.

- :mod:`mfm_tpu.data.synthetic` — realistic synthetic market/financial panels
  (the reference's CSI300 CSVs are git-lfs-filtered out of the repo, so tests
  and benches generate data with the same shape/missingness instead).
- :mod:`mfm_tpu.data.barra` — load/save the reference's barra-format table
  (``result/barra_data_csi.csv`` schema) into dense risk-model arrays.
- :mod:`mfm_tpu.data.pit` — statement dedup + point-in-time as-of joins +
  per-stock statement QC (``Barra_factor_cal/load_data.py`` contracts).
- :mod:`mfm_tpu.data.etl` — partitioned-parquet ``PanelStore`` + the
  incremental updater surface (watermarks, rate limits, retries, plans).
- :mod:`mfm_tpu.data.prepare` — store -> master factor-input panel
  (``load_and_prepare_data`` path).
- :mod:`mfm_tpu.data.artifacts` — stage-artifact checkpointing (npz +
  schema stamp), including the resumable risk-model state.
- :mod:`mfm_tpu.data.mongo_store` — pymongo adapter with the PanelStore
  interface (import-guarded).
- :mod:`mfm_tpu.data.tushare_source` — the Tushare Pro fetcher surface
  (same 10 endpoints as the reference, token from env, injectable client).
"""

from mfm_tpu.data.synthetic import synthetic_market_panel, synthetic_barra_table
from mfm_tpu.data.barra import (
    barra_frame_to_arrays,
    load_barra_csv,
    BarraArrays,
)
