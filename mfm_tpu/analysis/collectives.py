"""A3 — the per-entrypoint collective/resharding audit (and the home of
the mesh-doctrine report that used to live in tools/collective_audit.py).

The mesh layout doctrine (``mfm_tpu/parallel/mesh.py``) makes concrete,
checkable claims: the cross-sectional regression's stock-axis reductions
become all-reduces (riding ICI), rolling kernels' stock-only layout needs
NO communication, and no stage ever moves a full (T, N) panel between
devices — with ONE explicit carve-out (XLA's eigh is not
batch-partitionable, so the hoisted batched decompositions all-gather
their tiny K^2-sized, doctrine-replicated matrix batches).  The ROADMAP's
N≈5000 A-share scale-up makes this a merge gate, not documentation: an
implicit all-gather of a (T, 5000) panel is a correctness-of-scale bug we
catch by lowering, never by waiting for a TPU.

Two layers here:

- the **audit pass** (:func:`run_pass`): every registered mesh cell is
  compiled under its declared device mesh and its optimized HLO is swept
  for collectives; any KIND outside the entrypoint's allowlist, any
  collective at full-panel size, and any non-reduce collective beyond the
  eigh carve-out budget is an error.  Primary (unsharded) cells assert
  ZERO collectives — nothing in this package may smuggle in a shard_map.

- the **legacy report** (:func:`build_report` + :func:`check_invariants`):
  the stage-level mesh-doctrine evidence tools/collective_audit.py used to
  print; kept verbatim-compatible (tests/test_collective_audit.py drives
  it through the deprecation shim that now lives at the old path).
"""

from __future__ import annotations

import re

import numpy as np

from mfm_tpu.analysis.registry import AUDIT_MATRIX, Finding, _K

# optimized-HLO collective ops and their result types — plain or variadic:
#   %all-reduce.3 = f32[8,42]{1,0} all-reduce(...)
#   %all-reduce.9 = (f32[16,5]{1,0}, f32[16,3]{1,0}) all-reduce(...)
_COLLECTIVE = re.compile(
    r"=\s*(\([^)]*\)|[a-z0-9]+\[[0-9,]*\]\S*)\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"(-start|-done)?\("
)
_SHAPE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_DTYPE_BYTES = {"f64": 8, "f32": 4, "s64": 8, "u64": 8, "s32": 4, "u32": 4,
                "pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2}


def _type_bytes(type_str: str) -> int:
    """Total bytes across every array in a (possibly tuple) HLO result type."""
    total = 0
    for dtype, dims in _SHAPE.findall(type_str):
        n = int(np.prod([int(d) for d in dims.split(",")])) if dims else 1
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def audit_hlo(text: str) -> dict:
    """Count collectives in optimized HLO and size their results."""
    found = []
    for type_str, kind, suffix in _COLLECTIVE.findall(text):
        if suffix == "-done":  # async pair: count the -start only
            continue
        found.append({"kind": kind, "bytes": _type_bytes(type_str)})
    by_kind: dict = {}
    for f in found:
        by_kind[f["kind"]] = by_kind.get(f["kind"], 0) + 1
    reduces = ("all-reduce", "reduce-scatter")
    return {
        "total": len(found),
        "by_kind": by_kind,
        "largest_bytes": max((f["bytes"] for f in found), default=0),
        "largest_non_reduce_bytes": max(
            (f["bytes"] for f in found if f["kind"] not in reduces),
            default=0),
        "non_reduce_kinds": sorted({f["kind"] for f in found
                                    if f["kind"] not in reduces}),
    }


def eigh_gather_budget(T: int, K: int) -> int:
    """The one structural carve-out, as a byte bound: XLA's eigh (QDWH) is
    not batch-partitionable on this jaxlib, so the batched decompositions
    all-gather their (T, K, K) normal/covariance batches plus QDWH's
    (2K, 2K) workspace — doctrine-replicated SMALL matrices, never panel
    movement.  f64 upper bound, same formula the legacy report used."""
    return T * (2 * K) * (2 * K) * 8


def check_collectives(ep_name: str, cell_name: str, summary: dict, *,
                      allow: frozenset, panel_bytes: int,
                      gather_budget: int) -> list:
    """The pure A3 verdicts for one compiled mesh cell."""
    findings = []
    bad_kinds = sorted(set(summary["by_kind"]) - set(allow))
    if bad_kinds:
        findings.append(Finding(
            "A3", "error", ep_name, cell_name, "collective-kind",
            f"collectives {bad_kinds} outside the entrypoint allowlist "
            f"{sorted(allow)} (counts: {summary['by_kind']})"))
    ceiling = max(panel_bytes, gather_budget)
    if summary["largest_bytes"] >= ceiling:
        findings.append(Finding(
            "A3", "error", ep_name, cell_name, "full-panel-collective",
            f"largest collective moves {summary['largest_bytes']} bytes "
            f">= the full-panel/carve-out ceiling {ceiling} — the "
            f"N=5000 scale-up killer"))
    if summary["largest_non_reduce_bytes"] > gather_budget:
        findings.append(Finding(
            "A3", "error", ep_name, cell_name, "gather-over-budget",
            f"non-reduce collective moves "
            f"{summary['largest_non_reduce_bytes']} bytes > the eigh "
            f"carve-out budget {gather_budget}"))
    return findings


def run_pass(artifacts: dict) -> list:
    """A3 over the artifact cache: mesh cells against their allowlists,
    primary cells against zero-collective."""
    T, N = AUDIT_MATRIX["T"], AUDIT_MATRIX["N"]
    panel_bytes = T * N * 4
    budget = eigh_gather_budget(T, _K)
    findings = []
    for (ep, cell), art in artifacts.items():
        if "compiled_text" not in art:
            if cell.role == "mesh":
                findings.append(Finding(
                    "A3", "warn", ep.name, cell.name, "mesh-skipped",
                    f"mesh {cell.mesh} needs {cell.mesh[0] * cell.mesh[1]} "
                    f"devices — run under XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 to audit"))
            continue
        summary = audit_hlo(art["compiled_text"])
        art["collectives"] = summary
        if cell.role == "mesh":
            findings.extend(check_collectives(
                ep.name, cell.name, summary, allow=ep.collectives_allow,
                panel_bytes=panel_bytes, gather_budget=budget))
        elif summary["total"]:
            findings.append(Finding(
                "A3", "error", ep.name, cell.name, "unsharded-collective",
                f"single-device lowering contains collectives "
                f"{summary['by_kind']} — an embedded shard_map or mesh "
                f"context leaked into the entrypoint"))
    return findings


# -- the legacy stage-level doctrine report ---------------------------------
# (moved intact from tools/collective_audit.py; that path is now a shim)

def check_invariants(regression: dict, full_pipeline: dict,
                     rolling_beta: dict, *, panel_bytes: int,
                     eigh_gather_budget: int) -> dict:
    """Evaluate the mesh-layout doctrine on audited stage HLO.

    Takes the :func:`audit_hlo` summaries of the three compiled stages and
    returns the named structural invariants plus an overall ``ok``.  Pure
    and importable: tests assert the doctrine in-process on whatever HLO
    they compiled, no subprocess and no report plumbing.

    One structural exception is carved out explicitly rather than hidden:
    XLA's eigh (QDWH) is not batch-partitionable on this jaxlib, so the
    hoisted batched pseudo-inverse/eigen decompositions gather their tiny
    (T, K, K) matrix batches (plus QDWH's (2K, 2K) workspace) onto every
    device.  That is a K^2-sized gather of replicated-by-doctrine small
    matrices, NOT (T, N) panel movement — bound it by ``eigh_gather_budget``
    and reject anything larger.
    """
    inv = {
        "rolling_is_communication_free": rolling_beta["total"] == 0,
        "no_full_panel_collective": all(
            e["largest_bytes"] < max(panel_bytes, eigh_gather_budget)
            for e in (regression, full_pipeline)),
        # the regression stage communicates through reductions only, except
        # the bounded all-gather feeding the batched eigh
        "regression_is_reduce_only": (
            set(regression["non_reduce_kinds"]) <= {"all-gather"}
            and regression["largest_non_reduce_bytes"] <= eigh_gather_budget),
    }
    inv["ok"] = all(inv.values())
    return inv


def compiled_text(fn, mesh, arg_specs, *args) -> str:
    import jax

    shardings = [jax.NamedSharding(mesh, s) for s in arg_specs]
    placed = [jax.device_put(a, s) for a, s in zip(args, shardings)]
    return jax.jit(fn).lower(*placed).compile().as_text()


def build_report(T=192, N=96, P=8, Q=4, meshes=((8, 1), (4, 2), (2, 4))):
    # the audit is a structural check of the f32 production fast path; x64
    # (the test suite's golden-parity mode) changes GSPMD's decisions —
    # f64 batches are Pallas-ineligible and the partitioner inserts extra
    # gathers — so pin it off for the duration of the build
    from jax.experimental import disable_x64

    with disable_x64():
        return _build_report(T, N, P, Q, meshes)


def _build_report(T, N, P, Q, meshes):
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as Sp

    from mfm_tpu.config import RiskModelConfig
    from mfm_tpu.models.risk_model import RiskModel
    from mfm_tpu.ops.rolling import rolling_beta_hsigma
    from mfm_tpu.parallel.mesh import (
        PIPELINE_SPECS,
        make_mesh,
        panel_sharding,
    )

    rng = np.random.default_rng(0)
    ret = jnp.asarray(rng.normal(0, 0.02, (T, N)))
    cap = jnp.asarray(rng.lognormal(10, 1, (T, N)))
    styles = jnp.asarray(rng.normal(0, 1, (T, N, Q)))
    industry = jnp.asarray(rng.integers(0, P, (T, N)))
    valid = jnp.asarray(rng.random((T, N)) > 0.05)
    mkt = jnp.asarray(rng.normal(0, 0.01, T))
    cfg = RiskModelConfig(eigen_n_sims=4, eigen_sim_length=64)
    K = 1 + P + Q
    sim = jnp.asarray(rng.normal(size=(4, K, 64)))
    d = sim - sim.mean(axis=-1, keepdims=True)
    sim_covs = jnp.einsum("mkt,mlt->mkl", d, d) / 63.0

    def regression(ret, cap, styles, industry, valid):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=P, config=cfg)
        return m.reg_by_time()[:2]

    def full(ret, cap, styles, industry, valid, sim_covs):
        m = RiskModel(ret, cap, styles, industry, valid,
                      n_industries=P, config=cfg)
        return m.run(sim_covs=sim_covs)

    def rolling(ret, mkt):
        return rolling_beta_hsigma(ret, mkt, window=64, half_life=16,
                                   min_periods=8)

    panel_bytes = int(ret.size * ret.dtype.itemsize)
    report = {"shape": {"T": T, "N": N, "K": K},
              "panel_bytes": panel_bytes, "meshes": {}}
    ok = True
    # the canonical cross-sectional layouts, by argument name (mesh.py)
    xsec_specs = [PIPELINE_SPECS[k]
                  for k in ("ret", "cap", "styles", "industry", "valid")]
    for nd, ns in meshes:
        mesh = make_mesh(nd, ns)
        entry = {}
        entry["regression"] = audit_hlo(compiled_text(
            regression, mesh, xsec_specs,
            ret, cap, styles, industry, valid))
        entry["full_pipeline"] = audit_hlo(compiled_text(
            full, mesh, xsec_specs + [PIPELINE_SPECS["sim_covs"]],
            ret, cap, styles, industry, valid, sim_covs))
        roll_spec = panel_sharding(mesh, rolling=True).spec
        entry["rolling_beta"] = audit_hlo(compiled_text(
            rolling, mesh, [roll_spec, Sp()], ret, mkt))

        # doctrine invariants (see check_invariants for the eigh carve-out)
        budget = T * (2 * K) * (2 * K) * 8  # f64 upper bound
        entry["eigh_gather_budget_bytes"] = budget
        inv = check_invariants(
            entry["regression"], entry["full_pipeline"],
            entry["rolling_beta"], panel_bytes=panel_bytes,
            eigh_gather_budget=budget)
        entry.update((k, v) for k, v in inv.items() if k != "ok")
        ok &= inv["ok"]
        report["meshes"][f"{nd}x{ns}"] = entry
    report["invariants_hold"] = ok
    return report
