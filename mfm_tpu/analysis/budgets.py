"""A5 — static memory budgets per entrypoint x config cell.

``compiled.memory_analysis()`` prices a program without running it:
argument/output/alias/temp bytes and the generated-code size.  Freezing
those numbers per registered cell in ``tools/audit_budgets.json`` turns
"this change doubled the guarded step's temp memory" from a TPU-day
surprise into a pre-merge diff — the perfgate idea (tolerance bands over a
committed trajectory) applied to STATIC cost instead of measured wall
clock, and the mfmlint-baseline workflow (committed JSON, stale-entry
detection, an explicit regeneration flow) applied to its lifecycle.

Gate semantics per cell:

- ``temp_bytes`` and ``workspace_bytes`` (argument + output + temp -
  alias) regress when they exceed budget * (1 + tolerance) — an absolute
  floor (:data:`FLOOR_BYTES`) keeps KB-scale cells from crying wolf over
  allocator jitter;
- a measurement WAY below budget (< budget * (1 - tolerance), beyond the
  floor) is a *warn*: the budget is stale and should be re-frozen so the
  next regression is measured from the real baseline, not a forgotten one
  (``mfm-tpu audit --write-budgets``);
- a registered cell with no budget entry is an error pointing at the
  regeneration flow; a budget entry with no registered cell is a STALE
  error (same contract as mfmlint's stale baseline entries).

Budget identity: the numbers measure the AUDIT_MATRIX shapes on the pinned
jaxlib — regenerate when either moves, never to paper over a regression.
"""

from __future__ import annotations

import json
import os

from mfm_tpu.analysis.registry import Finding

BUDGETS_SCHEMA = "mfmaudit-budgets/1"
DEFAULT_TOLERANCE = 0.25
#: differences under this many bytes never gate — sub-64KiB cells (the
#: query/guard kernels) see allocator-granularity jitter across jaxlib
#: builds that is not a regression signal
FLOOR_BYTES = 64 * 1024

#: the measured metrics a budget freezes, in gate order
METRICS = ("temp_bytes", "workspace_bytes")

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BUDGETS_PATH = os.path.join(_REPO, "tools", "audit_budgets.json")


def measure_cell(mem: dict) -> dict:
    """Reduce obs.profile.compiled_memory_of output to the budgeted
    metrics.  ``workspace_bytes`` is the executable's whole static
    footprint net of donation reuse — the number that decides whether a
    cell fits on a core."""
    temp = int(mem.get("temp_bytes") or 0)
    work = (int(mem.get("argument_bytes") or 0)
            + int(mem.get("output_bytes") or 0) + temp
            - int(mem.get("alias_bytes") or 0))
    return {"temp_bytes": temp, "workspace_bytes": work}


def load_budgets(path: str = DEFAULT_BUDGETS_PATH) -> dict:
    """The committed budget file, or an empty skeleton when absent (every
    registered cell then reports ``unbudgeted``)."""
    if not os.path.exists(path):
        return {"schema": BUDGETS_SCHEMA, "tolerance": DEFAULT_TOLERANCE,
                "cells": {}}
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("schema") != BUDGETS_SCHEMA:
        raise ValueError(f"unsupported budget schema {doc.get('schema')!r} "
                         f"in {path} (want {BUDGETS_SCHEMA})")
    return doc


def write_budgets(measured: dict, path: str = DEFAULT_BUDGETS_PATH,
                  tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Freeze ``measured`` (cell key -> metric dict) as the new budget
    file.  Atomic tmp -> fsync -> rename, same as every other committed
    snapshot in this repo — a SIGKILL mid-write must not tear the gate."""
    doc = {"schema": BUDGETS_SCHEMA, "tolerance": tolerance,
           "cells": {k: dict(v) for k, v in sorted(measured.items())}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(path) or ".", os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return doc


def check_budgets(measured: dict, budgets: dict) -> list:
    """The pure A5 verdicts: ``measured`` maps ``"ep/cell"`` ->
    metric dict, ``budgets`` is the loaded budget doc."""
    tol = float(budgets.get("tolerance", DEFAULT_TOLERANCE))
    cells = budgets.get("cells", {})
    findings = []
    for key in sorted(measured):
        ep_name, _, cell_name = key.partition("/")
        got = measured[key]
        want = cells.get(key)
        if want is None:
            findings.append(Finding(
                "A5", "error", ep_name, cell_name, "unbudgeted",
                f"no committed budget for {key} — freeze one with "
                f"`mfm-tpu audit --write-budgets` and commit "
                f"tools/audit_budgets.json"))
            continue
        for metric in METRICS:
            cur = int(got.get(metric) or 0)
            ref = int(want.get(metric) or 0)
            if cur > ref * (1 + tol) and cur - ref > FLOOR_BYTES:
                findings.append(Finding(
                    "A5", "error", ep_name, cell_name, f"over-{metric}",
                    f"{key} {metric} {cur} exceeds budget {ref} by "
                    f"{cur - ref} bytes (> {tol:.0%} band) — a static "
                    f"memory regression"))
            elif ref * (1 - tol) > cur and ref - cur > FLOOR_BYTES:
                findings.append(Finding(
                    "A5", "warn", ep_name, cell_name, f"stale-{metric}",
                    f"{key} {metric} {cur} is far under budget {ref} — "
                    f"re-freeze so the band measures from reality"))
    for key in sorted(set(cells) - set(measured)):
        ep_name, _, cell_name = key.partition("/")
        findings.append(Finding(
            "A5", "error", ep_name, cell_name, "stale-budget",
            f"budget entry {key} matches no registered cell — remove it "
            f"or restore the registration (same contract as mfmlint's "
            f"stale baseline entries)"))
    return findings


def run_pass(artifacts: dict, budgets_path: str = DEFAULT_BUDGETS_PATH):
    """A5 over every compiled primary AND mesh cell.  Mesh cells carry
    budgets too (PR 11): a sharded program whose per-device argument or
    temp bytes balloon would silently erase the memory win that motivates
    sharding at all.  Ladder cells stay excluded — their contract is
    arity (A4), and three near-identical bucket budgets would only add
    noise.  Returns ``(findings, measured)`` — the measurements ride into
    the audit report and the ``--write-budgets`` flow."""
    measured = {}
    for (ep, cell), art in artifacts.items():
        if cell.role not in ("primary", "mesh") or "memory" not in art:
            continue
        measured[f"{ep.name}/{cell.name}"] = measure_cell(art["memory"])
    findings = check_budgets(measured, load_budgets(budgets_path))
    return findings, measured
