"""mfm_tpu.analysis — IR-level static analysis of the jit entrypoints.

mfmlint (``mfm_tpu/lint.py``) enforces the JAX doctrine at the *source*
level; this package enforces it one layer down, where the two worst bugs
this repo has shipped actually lived: the donated-aliased-buffer corruption
PR 4 caught at runtime, and the s64-under-SPMD retraces PR 1 fixed.  Both
are visible statically in the lowered StableHLO / compiled executable —
``mfm-tpu audit`` lowers every registered entrypoint across a small config
matrix (CPU backend, lowering + compilation only, nothing ever executes)
and runs five passes over the artifacts:

- A1 ``aliasing``    donation-aliasing proof (registry vs lowered donation
                     flags; compiled alias map must be donated-only)
- A2 ``ir``          dtype-leak audit (f64/i64 tensor types, host callbacks)
- A3 ``collectives`` per-entrypoint collective audit under the doctrine mesh
- A4 ``surface``     recompile-surface enumeration (bucket-ladder cache keys)
- A5 ``budgets``     static memory budgets vs tools/audit_budgets.json

Entry points: :data:`mfm_tpu.analysis.registry.REGISTRY` (the declarative
inventory), :func:`mfm_tpu.analysis.run.run_audit` (the in-process API used
by tests and the CLI), ``tools/mfmaudit.py`` / ``mfm-tpu audit`` (the
gates).  See docs/AUDIT.md for the pass catalog and workflows.
"""

from mfm_tpu.analysis.registry import (  # noqa: F401
    AUDIT_MATRIX,
    Cell,
    Entrypoint,
    Finding,
    NON_ENTRYPOINT_JITS,
    REGISTRY,
    registry_by_name,
)
from mfm_tpu.analysis.run import run_audit  # noqa: F401
