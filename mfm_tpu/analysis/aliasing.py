"""A1 — the donation-aliasing proof.

PR 4's worst bug class: a donated operand whose buffer XLA retires into an
output while the host still holds (and later reads) the reference — or the
dual, an operand the caller contract says is host-retained that the
compiled executable aliases anyway.  Both were caught at RUNTIME by guards;
this pass catches them at lowering time, pre-merge:

1. **Contract vs jit** — the registry's ``donate=`` tuple (the caller
   contract serving code is written against) must match the jit's own
   donation set as the lowering reports it (``lowered.args_info``).  A
   mismatch in either direction is an error: a contract that promises
   donation the jit doesn't declare re-creates the PR 4 setup (the host
   thinks the buffer is gone, the jit thinks it's shared — or vice versa).

2. **Alias map ⊆ donated** — every entry of the compiled executable's
   ``input_output_alias`` map must point at a donated operand.  XLA only
   aliases declared donors, so a violation here means the artifact and the
   declaration disagree — tampering, a miscompile, or a registry rot; all
   of them gate.

3. **Donated-but-unaliased** operands are *info*, not errors: donation is
   an upper bound, and a donated operand with no same-shape/dtype output
   (the bool ``valid`` panel, the s32 ``industry`` panel) legitimately
   donates nothing.  The finding keeps the evidence trail so a donation
   that silently STOPS aliasing (a layout regression that doubles peak
   memory) is visible in the committed snapshot diff.

The checks are pure functions over (declared set, lowered flags, parsed
alias map) so the PR 4 reconstruction fixtures in tests/test_audit.py can
drive them with synthetic inputs — no compile needed to prove the pass
fails when it must.
"""

from __future__ import annotations

import re

import jax

from mfm_tpu.analysis.registry import Finding, flat_donated

#: one entry of the compiled-HLO header's alias map:
#:   input_output_alias={ {1}: (0, {}, may-alias), {13}: (6, {}, must-alias) }
#: output index tuple (possibly nested, e.g. {1, 0}) -> (param, param_index,
#: kind).  We key on the PARAM number — which operand's buffer is reused.
_ALIAS_ENTRY = re.compile(
    r"\{\s*([0-9,\s]*)\}\s*:\s*\(\s*(\d+)\s*,\s*\{[0-9,\s]*\}\s*,\s*"
    r"(may-alias|must-alias)\s*\)")


def parse_input_output_alias(compiled_text: str) -> list:
    """Extract ``input_output_alias`` entries from compiled-HLO text.

    Returns ``[{"output": "1", "param": 0, "kind": "may-alias"}, ...]``;
    an executable with no alias map yields ``[]``.  Pure text -> data, so
    fixtures can feed synthetic headers.
    """
    start = compiled_text.find("input_output_alias={")
    if start < 0:
        return []
    # the map nests braces ({output_index}: (param, {param_index}, kind)),
    # so walk a brace counter instead of trusting a non-greedy regex
    i = start + len("input_output_alias=")
    depth = 0
    end = i
    for end in range(i, min(len(compiled_text), i + 100_000)):
        c = compiled_text[end]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if depth == 0:
                break
    body = compiled_text[i + 1:end]
    out = []
    for out_idx, param, kind in _ALIAS_ENTRY.findall(body):
        out.append({"output": out_idx.replace(" ", ""),
                    "param": int(param), "kind": kind})
    return out


def donated_operand_flags(lowered) -> list:
    """Per-FLATTENED-operand donation flags, in compiled parameter order,
    straight from the lowering (``args_info`` reflects the jit's declared
    ``donate_argnums`` after static-arg binding — the ground truth the
    registry contract is checked against)."""
    args, kwargs = lowered.args_info
    leaves = (jax.tree_util.tree_leaves(args)
              + jax.tree_util.tree_leaves(kwargs))
    return [bool(a.donated) for a in leaves]


def check_aliasing(ep_name: str, cell_name: str, declared: set,
                   lowered_flags: list, alias_entries: list) -> list:
    """The pure A1 verdicts for one cell.

    Args:
      declared: flattened operand indices the REGISTRY says are donated
        (:func:`mfm_tpu.analysis.registry.flat_donated`).
      lowered_flags: per-operand donation booleans from the lowering
        (:func:`donated_operand_flags`).
      alias_entries: parsed compiled alias map
        (:func:`parse_input_output_alias`).
    """
    findings = []
    actual = {i for i, d in enumerate(lowered_flags) if d}
    if declared != actual:
        over = sorted(declared - actual)
        under = sorted(actual - declared)
        detail = []
        if over:
            detail.append(f"contract donates operands {over} the jit does "
                          f"not (host will drop buffers the program shares)")
        if under:
            detail.append(f"jit donates operands {under} the contract "
                          f"retains (host reads a retired buffer — the "
                          f"PR 4 corruption class)")
        findings.append(Finding(
            "A1", "error", ep_name, cell_name, "donation-contract-mismatch",
            "; ".join(detail)))
    aliased = set()
    for e in alias_entries:
        aliased.add(e["param"])
        if e["param"] >= len(lowered_flags) or not lowered_flags[e["param"]]:
            findings.append(Finding(
                "A1", "error", ep_name, cell_name, "nondonated-alias",
                f"compiled alias map reuses operand {e['param']} "
                f"(output {{{e['output']}}}, {e['kind']}) which is NOT "
                f"donated — executable and declaration disagree"))
    unaliased = sorted(actual - aliased)
    if unaliased:
        findings.append(Finding(
            "A1", "info", ep_name, cell_name, "donated-unaliased",
            f"donated operands {unaliased} established no alias (no "
            f"compatible output buffer) — donation is inert there"))
    return findings


def run_pass(artifacts: dict) -> list:
    """A1 over every compiled primary cell.

    ``artifacts`` maps ``(ep, cell) -> {"lowered", "compiled_text", ...}``
    (built once by :mod:`mfm_tpu.analysis.run` and shared across passes).
    """
    findings = []
    for (ep, cell), art in artifacts.items():
        if cell.role != "primary" or "compiled_text" not in art:
            continue
        findings.extend(check_aliasing(
            ep.name, cell.name,
            flat_donated(ep, cell),
            donated_operand_flags(art["lowered"]),
            parse_input_output_alias(art["compiled_text"])))
    return findings
