"""A2 — the dtype-leak audit over lowered StableHLO.

PR 1's s64-under-SPMD retraces and the x64-vs-production split
(tests run golden parity in f64; serving runs f32) make "no 64-bit tensor
reaches a lowered serving program" a doctrine claim.  mfmlint approximates
it at the source level (R2's np scalars, R6's bare ints); this pass proves
it on the artifact: the audit lowers every registered cell under
``jax.experimental.disable_x64`` (the production numerics mode) and walks
the StableHLO module's TENSOR TYPES — ``tensor<64x48xf64>``,
``tensor<i64>``, ``tensor<3xui64>`` — which is the only honest place to
look, because the raw text is full of harmless ``: i64`` ATTRIBUTE types
(dimension numbers, iota dims) that a naive grep would flag.

Also flagged: host callbacks (``stablehlo.custom_call`` targeting the
python callback trampolines).  A host round-trip inside a serving
entrypoint breaks both the latency contract and AOT portability — nothing
in the registry is allowed one.

Pure text -> findings, so fixtures drive it with synthetic modules.
"""

from __future__ import annotations

import re

from mfm_tpu.analysis.registry import Finding

#: every tensor type in a StableHLO module, e.g. tensor<64x48xf32>,
#: tensor<i1>, tensor<4x9x9xf64>; dynamic dims (?) and scalars included
_TENSOR = re.compile(r"tensor<([0-9x?]*)((?:[a-z][a-z0-9]*)|)>")

#: 64-bit element types that must never appear in a production lowering
_WIDE = {"f64", "i64", "ui64", "si64", "c128"}

#: custom_call targets that are host round-trips (python callbacks in
#: their jaxlib spellings), matched as substrings of the target name
_CALLBACK_MARKERS = ("python_cpu_callback", "python_gpu_callback",
                     "xla_python_callback", "CallbackTrampoline")


def module_tensor_dtypes(stablehlo_text: str) -> set:
    """Element dtypes of every ``tensor<...>`` type in the module text."""
    out = set()
    for _dims, elt in _TENSOR.findall(stablehlo_text):
        if elt:
            out.add(elt)
    # complex element types nest (<tensor<2xcomplex<f64>>) past the regex
    if "complex<f64>" in stablehlo_text:
        out.add("c128")
    return out


def host_callbacks(stablehlo_text: str) -> list:
    """call_target_name values of host-callback custom_calls."""
    targets = re.findall(r'call_target_name\s*=\s*"([^"]+)"', stablehlo_text)
    return [t for t in targets
            if any(m in t for m in _CALLBACK_MARKERS)]


def scan_module(ep_name: str, cell_name: str, stablehlo_text: str) -> list:
    """The pure A2 verdicts for one lowered module."""
    findings = []
    wide = sorted(module_tensor_dtypes(stablehlo_text) & _WIDE)
    if wide:
        findings.append(Finding(
            "A2", "error", ep_name, cell_name, "wide-dtype",
            f"lowered module contains {wide} tensor types under the "
            f"production f32 mode — a 64-bit leak (PR 1's retrace class "
            f"when it is an index dtype, a 2x memory bill when it is data)"))
    cbs = host_callbacks(stablehlo_text)
    if cbs:
        findings.append(Finding(
            "A2", "error", ep_name, cell_name, "host-callback",
            f"lowered module calls back into the host ({sorted(set(cbs))}) "
            f"— serving entrypoints must be AOT-pure"))
    return findings


def run_pass(artifacts: dict) -> list:
    """A2 over every lowered cell (primary AND mesh — a leak the
    partitioner introduces only under SPMD still gates)."""
    findings = []
    for (ep, cell), art in artifacts.items():
        if "stablehlo" not in art:
            continue
        findings.extend(scan_module(ep.name, cell.name, art["stablehlo"]))
    return findings
