"""The declarative entrypoint registry behind ``mfm-tpu audit``.

Every PUBLIC jit compilation unit in the package is declared here as an
:class:`Entrypoint`: which callable, which operand shapes (abstract
``jax.ShapeDtypeStruct`` avals — nothing is ever executed), which operands
the caller contract says are donated, which mesh layouts it must tolerate,
and which shape-bucket ladder its steady-state ``<= 1 compile per bucket``
claim is made over.  The audit passes (aliasing / ir / collectives /
surface / budgets) consume these declarations; the registry-completeness
test (tests/test_audit.py) walks the package with mfmlint's call graph and
fails if a jit root is neither registered nor allowlisted in
:data:`NON_ENTRYPOINT_JITS` — a new entrypoint cannot silently dodge the
audit.

Two sources of truth are deliberately kept independent and cross-checked:
the ``donate=`` tuple here is the *caller contract* (what serving code is
allowed to assume about buffer ownership), while the jit's own
``donate_argnums`` reaches the audit through ``lowered.args_info`` — the
aliasing pass fails when they disagree in either direction (the static
form of the PR 4 donated-alias corruption).

The config matrix (:data:`AUDIT_MATRIX`) is intentionally SMALL: the audit
is a structural check of the lowered program, not a performance run, and
its properties (donation marks, tensor dtypes, collective kinds, cache-key
arity) are shape-generic.  Keeping T/N tiny is what lets the whole matrix
lower + compile device-free in well under the 120 s tier-1 budget.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Mapping

import jax
import jax.numpy as jnp

#: the audit's config matrix — one small, fixed shape cell per axis.  The
#: values are part of the budget identity (tools/audit_budgets.json keys
#: measure THESE shapes); change them only together with a budget
#: regeneration (``mfm-tpu audit --write-budgets``).
AUDIT_MATRIX = {
    "T": 64,    # dates per slab
    "N": 48,    # stocks
    "P": 5,     # industries
    "Q": 3,     # style factors
    "M": 4,     # eigen Monte-Carlo sims
    "SIM_LEN": 48,   # pinned eigen_sim_length of the non-incremental cells
}
_K = 1 + AUDIT_MATRIX["P"] + AUDIT_MATRIX["Q"]   # country + P + Q


@dataclasses.dataclass(frozen=True)
class Finding:
    """One audit verdict.  ``severity`` is ``error`` (fails ``--strict``),
    ``warn`` (reported, non-fatal) or ``info`` (evidence trail).  ``code``
    is the stable machine id the baseline file keys on."""

    pass_id: str        # "A1".."A5"
    severity: str       # "error" | "warn" | "info"
    entrypoint: str     # registry name, or "-" for registry-level findings
    cell: str           # cell name, or "-"
    code: str           # e.g. "nondonated-alias"
    message: str

    def key(self) -> str:
        return f"{self.pass_id}:{self.entrypoint}:{self.cell}:{self.code}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True, eq=False)   # identity hash: cells key
class Cell:                                      # the artifact cache
    """One (shapes, statics) point of an entrypoint's config matrix.

    ``role`` drives what the audit does with it: ``primary`` cells are
    lowered AND compiled (aliasing / ir / budget passes), ``mesh`` cells
    are lowered + compiled under a device mesh (collective pass), and
    ``ladder`` cells are never lowered at all — the surface pass only
    computes their jit cache keys.
    """

    name: str
    args: tuple
    kwargs: Mapping
    role: str = "primary"        # "primary" | "mesh" | "ladder"
    mesh: tuple | None = None    # (n_date, n_stock) for role == "mesh"
    bucket: int | None = None    # declared bucket for role == "ladder"
    #: positional args that are STATIC (jit static_argnums) — they carry a
    #: plain Python value in ``args`` and produce no lowered operands
    static_argnums: tuple = ()


@dataclasses.dataclass(frozen=True, eq=False)   # identity hash, like Cell
class Entrypoint:
    """One registered public jit entrypoint."""

    name: str                    # audit id, e.g. "risk.update_guarded"
    qualname: str                # lint-style "module:func" qualname
    fn: Callable                 # the jitted callable (AOT .lower works)
    donate: tuple                # caller-contract donated POSITIONAL args
    build_cells: Callable[[], "list[Cell]"]
    collectives_allow: frozenset = frozenset()   # kinds allowed on a mesh
    ladder: str | None = None    # "query" | "scenario" | "eigen" | None
    notes: str = ""

    def cells(self) -> "list[Cell]":
        return self.build_cells()


# -- aval builders -----------------------------------------------------------

def _sds(shape, dtype):
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _panel_avals():
    """The five (T, N)-family panel operands every risk step starts with:
    ret, cap, styles, industry, valid — dtypes pinned to the production
    f32 path (the audit runs under ``disable_x64``)."""
    T, N, Q = (AUDIT_MATRIX[k] for k in ("T", "N", "Q"))
    return (
        _sds((T, N), jnp.float32),        # ret
        _sds((T, N), jnp.float32),        # cap
        _sds((T, N, Q), jnp.float32),     # styles
        _sds((T, N), jnp.int32),          # industry
        _sds((T, N), jnp.bool_),          # valid
    )


def _base_config():
    from mfm_tpu.config import RiskModelConfig

    return RiskModelConfig(eigen_n_sims=AUDIT_MATRIX["M"],
                           eigen_sim_length=AUDIT_MATRIX["SIM_LEN"])


def _guarded_config():
    from mfm_tpu.config import QuarantinePolicy, RiskModelConfig

    return RiskModelConfig(eigen_n_sims=AUDIT_MATRIX["M"],
                           eigen_sim_length=AUDIT_MATRIX["SIM_LEN"],
                           quarantine=QuarantinePolicy(enabled=True))


def _incremental_config():
    from mfm_tpu.config import RiskModelConfig

    return RiskModelConfig(eigen_n_sims=AUDIT_MATRIX["M"],
                           eigen_incremental=True)


def _sim_covs_aval():
    return _sds((AUDIT_MATRIX["M"], _K, _K), jnp.float32)


def _eigen_seed_avals():
    """(eig_draws, eig_R, eig_p, eig_n) avals of the incremental-eigen
    cells, derived abstractly from the production constructors (no concrete
    arrays)."""
    from mfm_tpu.models.eigen import draw_bucket, eigen_carry_init

    T, M = AUDIT_MATRIX["T"], AUDIT_MATRIX["M"]
    draws = _sds((M, _K, draw_bucket(T)), jnp.float32)
    carry = jax.eval_shape(lambda: eigen_carry_init(M, _K, jnp.float32))
    return (draws,) + tuple(carry)


def _eigen_sweeps():
    """The static Jacobi sweep cap the incremental serving loop resolves
    host-side (risk_model._eigen_sweeps with the default "auto" policy) —
    mirrored here so the audited static set matches production's."""
    from mfm_tpu.models.eigen import sim_sweeps_for

    return sim_sweeps_for(_K, jnp.float32, AUDIT_MATRIX["T"])


@functools.lru_cache(maxsize=None)
def _init_carries(mode: str):
    """(nw_carry, vr_num, vr_den, eig_carry) avals for the update cells,
    derived via ``eval_shape`` of the INIT entrypoint — the same abstract
    plumbing production uses, so a carry-layout change here is caught as a
    shape mismatch rather than silently audited against stale shapes."""
    from mfm_tpu.models.risk_model import _fused_init_step

    T, M = AUDIT_MATRIX["T"], AUDIT_MATRIX["M"]
    if mode == "incremental":
        cfg = _incremental_config()
        draws, eig_r, eig_p, eig_n = _eigen_seed_avals()
        _, nw, (vr_num, vr_den), eig = _fused_init_step.eval_shape(
            *_panel_avals(), None, draws, eig_r, eig_p, eig_n,
            n_industries=AUDIT_MATRIX["P"], config=cfg, sim_length=None,
            eigen_batch_hint=T * M, eigen_sweeps=_eigen_sweeps())
    else:
        cfg = _guarded_config() if mode == "guarded" else _base_config()
        _, nw, (vr_num, vr_den), eig = _fused_init_step.eval_shape(
            *_panel_avals(), _sim_covs_aval(), None, None, None, None,
            n_industries=AUDIT_MATRIX["P"], config=cfg,
            sim_length=AUDIT_MATRIX["SIM_LEN"],
            eigen_batch_hint=T * M, eigen_sweeps=None)
    return nw, vr_num, vr_den, eig


def _guard_leaf_avals(policy):
    """(last_good, staleness, q_count, ring, ring_pos) avals matching
    RiskModel._seed_guard_state's layout."""
    return (
        _sds((_K, _K), jnp.float32),                      # last_good_cov
        _sds((), jnp.int32),                              # staleness
        _sds((), jnp.int32),                              # quarantine_count
        _sds((policy.universe_window,), jnp.float32),     # guard_ring
        _sds((), jnp.int32),                              # guard_ring_pos
    )


# -- cell builders per entrypoint -------------------------------------------

def _risk_fused_cells():
    from mfm_tpu.parallel.mesh import PIPELINE_SPECS, make_mesh
    from jax.sharding import NamedSharding

    P, SIM_LEN = AUDIT_MATRIX["P"], AUDIT_MATRIX["SIM_LEN"]
    cfg = _base_config()
    statics = dict(n_industries=P, config=cfg, sim_length=SIM_LEN)
    args = _panel_avals() + (_sim_covs_aval(),)
    cells = [Cell("base", args, statics)]
    # the doctrine-mesh cells: panels laid out by PIPELINE_SPECS, sim_covs
    # replicated — skipped (with a warn finding) when the process has too
    # few devices for the mesh
    names = ("ret", "cap", "styles", "industry", "valid", "sim_covs")
    for nd, ns in ((4, 2), (2, 4)):
        if jax.device_count() < nd * ns:
            cells.append(Cell(f"mesh{nd}x{ns}", (), statics, role="mesh",
                              mesh=(nd, ns)))
            continue
        mesh = make_mesh(nd, ns)
        sh_args = tuple(
            _sds(a.shape, a.dtype) if n is None else jax.ShapeDtypeStruct(
                a.shape, a.dtype,
                sharding=NamedSharding(mesh, PIPELINE_SPECS[n]))
            for a, n in zip(args, names))
        cells.append(Cell(f"mesh{nd}x{ns}", sh_args, statics, role="mesh",
                          mesh=(nd, ns)))
    return cells


#: positional panel names for the doctrine layout (PIPELINE_SPECS); any
#: other argument of a mesh cell replicates
_PANEL_NAMES = ("ret", "cap", "styles", "industry", "valid", "sim_covs")


def _mesh_cells(args, statics, meshes=((2, 4),)):
    """role='mesh' cells for a sharded entrypoint: the five panels (+
    sim_covs) laid out by PIPELINE_SPECS, every other operand (carries,
    guard leaves, host pre-verdicts) replicated — the layout the sharded
    pipeline/serve paths put on the wire.  Skipped with a warn finding
    when the process has too few devices (matches _risk_fused_cells)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from mfm_tpu.parallel.mesh import PIPELINE_SPECS, make_mesh

    cells = []
    for nd, ns in meshes:
        if jax.device_count() < nd * ns:
            cells.append(Cell(f"mesh{nd}x{ns}", (), statics, role="mesh",
                              mesh=(nd, ns)))
            continue
        mesh = make_mesh(nd, ns)

        def shard(a, name):
            if a is None:
                return None
            spec = PIPELINE_SPECS.get(name, PartitionSpec())
            # carries are aval PYTREES (eval_shape output); leaves share
            # the argument's layout (panels sharded, carries replicated)
            return jax.tree_util.tree_map(
                lambda leaf: jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype,
                    sharding=NamedSharding(mesh, spec)),
                a)

        names = _PANEL_NAMES + (None,) * (len(args) - len(_PANEL_NAMES))
        cells.append(Cell(
            f"mesh{nd}x{ns}",
            tuple(shard(a, n) for a, n in zip(args, names)),
            statics, role="mesh", mesh=(nd, ns)))
    return cells


def _risk_init_cells():
    T, P, M, SIM_LEN = (AUDIT_MATRIX[k] for k in ("T", "P", "M", "SIM_LEN"))
    statics = dict(n_industries=P, config=_base_config(), sim_length=SIM_LEN,
                   eigen_batch_hint=T * M, eigen_sweeps=None)
    args = _panel_avals() + (_sim_covs_aval(), None, None, None, None)
    base = Cell("base", args, statics)
    draws, eig_r, eig_p, eig_n = _eigen_seed_avals()
    incr = Cell(
        "eigen-incremental",
        _panel_avals() + (None, draws, eig_r, eig_p, eig_n),
        dict(n_industries=P, config=_incremental_config(), sim_length=None,
             eigen_batch_hint=T * M, eigen_sweeps=_eigen_sweeps()))
    # PR 11: the sharded-pipeline init (panels shard-local, carries born
    # replicated) — the state path never pads, so the audit mesh divides
    # the (T, N) matrix exactly
    return [base, incr] + _mesh_cells(args, statics)


def _risk_update_cells():
    from mfm_tpu.models.eigen import draw_bucket

    T, P, M, SIM_LEN = (AUDIT_MATRIX[k] for k in ("T", "P", "M", "SIM_LEN"))
    t_count = _sds((), jnp.int32)
    nw, vr_num, vr_den, _ = _init_carries("base")
    base = Cell(
        "base",
        _panel_avals() + (_sim_covs_aval(), nw, vr_num, vr_den, t_count,
                          None, None, None, None),
        dict(n_industries=P, config=_base_config(), sim_length=SIM_LEN,
             eigen_batch_hint=T * M, eigen_sweeps=None))

    nw_i, vrn_i, vrd_i, eig_i = _init_carries("incremental")
    eig_r, eig_p, eig_n = eig_i
    incr_statics = dict(n_industries=P, config=_incremental_config(),
                        sim_length=None, eigen_batch_hint=T * M,
                        eigen_sweeps=_eigen_sweeps())

    def incr_cell(name, bucket, role):
        draws = _sds((M, _K, bucket), jnp.float32)
        return Cell(
            name,
            _panel_avals() + (None, nw_i, vrn_i, vrd_i, t_count,
                              draws, eig_r, eig_p, eig_n),
            incr_statics, role=role, bucket=bucket)

    cells = [base, incr_cell("eigen-incremental", draw_bucket(T), "primary")]
    # the declared draw-bucket ladder (pow2 >= 64): the growing history
    # retraces ONLY at bucket rollovers — the surface pass proves the cells
    # produce exactly one cache key per declared bucket
    for b in (64, 128, 256):
        assert draw_bucket(b) == b, "declared eigen bucket not a fixed point"
        cells.append(incr_cell(f"bucket{b}", b, "ladder"))
    return cells


def _risk_update_guarded_cells():
    T, P, M, SIM_LEN = (AUDIT_MATRIX[k] for k in ("T", "P", "M", "SIM_LEN"))
    cfg = _guarded_config()
    nw, vr_num, vr_den, _ = _init_carries("guarded")
    guard = _guard_leaf_avals(cfg.quarantine)
    pre = _sds((T,), jnp.uint32)
    heal = _sds((T,), jnp.bool_)
    t_count = _sds((), jnp.int32)
    args = (_panel_avals() + (_sim_covs_aval(), nw, vr_num, vr_den)
            + guard + (pre, heal, t_count, None, None, None, None))
    statics = dict(n_industries=P, config=cfg, sim_length=SIM_LEN,
                   eigen_batch_hint=T * M, eigen_sweeps=None)
    # PR 11: the sharded guarded append (slab sharded, state replicated —
    # append_risk_pipeline(mesh=...)'s exact wire layout)
    return [Cell("base", args, statics)] + _mesh_cells(args, statics)


_QUERY_BUCKETS = (8, 32, 128)    # bucket_for's 8 * 4^i ladder, first rungs
_N_BENCH = 3                     # benchmark table rows (2 benchmarks + zero)


def _query_factor_cells():
    from mfm_tpu.serve.query import bucket_for

    cov = _sds((_K, _K), jnp.float32)
    bx = _sds((_N_BENCH, _K), jnp.float32)

    def cell(b, role):
        # pad_batch's documented operand dtypes: f32 weights, i32 indices
        return Cell(f"bucket{b}",
                    (_sds((b, _K), jnp.float32), _sds((b,), jnp.int32),
                     cov, bx),
                    {}, role=role, bucket=b)

    cells = [cell(_QUERY_BUCKETS[0], "primary")]
    for b in _QUERY_BUCKETS:
        assert bucket_for(b) == b, "declared query bucket not a fixed point"
        cells.append(cell(b, "ladder"))
    return cells


def _query_stock_cells():
    N = AUDIT_MATRIX["N"]
    b = _QUERY_BUCKETS[0]
    args = (
        _sds((b, N), jnp.float32),          # w
        _sds((b,), jnp.int32),              # bidx
        _sds((_K, _K), jnp.float32),        # cov
        _sds((N, _K), jnp.float32),         # X
        _sds((N,), jnp.float32),            # svar
        _sds((_N_BENCH, _K), jnp.float32),  # bx
        _sds((_N_BENCH, N), jnp.float32),   # bw
    )
    return [Cell(f"bucket{b}", args, {}, bucket=b)]


def _scenario_cells():
    from mfm_tpu.serve.query import bucket_for

    def cell(s, role):
        args = (
            _sds((s, _K, _K), jnp.float32),   # base_cov
            _sds((s, _K), jnp.float32),       # shift
            _sds((s, _K), jnp.float32),       # scale
            _sds((s,), jnp.float32),          # vol_mult
            _sds((s,), jnp.float32),          # corr_beta
            _sds((s,), jnp.bool_),            # passthrough
        )
        return Cell(f"bucket{s}", args, {}, role=role, bucket=s)

    cells = [cell(_QUERY_BUCKETS[0], "primary")]
    for s in _QUERY_BUCKETS:
        assert bucket_for(s) == s
        cells.append(cell(s, "ladder"))
    return cells


#: sweep-cell fixed sizes: the carry is shape-static in (books, top_k,
#: bins) — the audit declares one representative configuration (the
#: bench's own), the ladder varies only the chunk axis
_SWEEP_B, _SWEEP_TOPK, _SWEEP_BINS, _SWEEP_LIB = 2, 16, 64, 2


def _sweep_carry_avals():
    th = 2 * _K + 2
    return (
        _sds((_SWEEP_B, _SWEEP_TOPK), jnp.float32),        # top_vol
        _sds((_SWEEP_B, _SWEEP_TOPK, th), jnp.float32),    # top_theta
        _sds((_SWEEP_B, _SWEEP_TOPK), jnp.int32),          # top_src
        _sds((_SWEEP_B, _SWEEP_TOPK), jnp.int32),          # top_base
        _sds((_SWEEP_B, _SWEEP_BINS), jnp.int32),          # hist
        _sds((3,), jnp.int32),                             # counts
    )


def _sweep_mesh_cells(make_args, meshes=((2, 4),)):
    """role='mesh' cells for the sweep jits: every operand replicated
    (the chunk axis is placed by the engine's NamedSharding at run time;
    the audit proves the replicated lowering stays collective-clean —
    same skip-with-warn contract as _replicated_mesh_cells, which this
    mirrors because the sweep carry is a nested tuple its flat ``for a
    in args`` cannot walk)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from mfm_tpu.parallel.mesh import make_mesh

    cells = []
    for nd, ns in meshes:
        if jax.device_count() < nd * ns:
            cells.append(Cell(f"mesh{nd}x{ns}", (), {}, role="mesh",
                              mesh=(nd, ns)))
            continue
        mesh = make_mesh(nd, ns)
        rep = NamedSharding(mesh, PartitionSpec())
        args = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep),
            make_args())
        cells.append(Cell(f"mesh{nd}x{ns}", args, {}, role="mesh",
                          mesh=(nd, ns)))
    return cells


def _sweep_chunk_cells():
    from mfm_tpu.serve.query import bucket_for

    th = 2 * _K + 2

    def make(c):
        return (
            _sweep_carry_avals(),                          # carry (donated)
            _sds((_SWEEP_LIB, _K, _K), jnp.float32),       # base_lib
            _sds((_SWEEP_B, _K), jnp.float32),             # xs
            _sds((c, th), jnp.float32),                    # thetas
            _sds((c,), jnp.int32),                         # base_idx
            _sds((c,), jnp.int32),                         # src
            _sds((c,), jnp.bool_),                         # take
            _sds((c,), jnp.bool_),                         # reject
            _sds((c,), jnp.bool_),                         # passthrough
            _sds((_SWEEP_B,), jnp.float32),                # lo
            _sds((_SWEEP_B,), jnp.float32),                # width
        )

    c0 = _QUERY_BUCKETS[0]
    cells = [Cell(f"bucket{c0}", make(c0), {}, bucket=c0)]
    for c in _QUERY_BUCKETS:
        assert bucket_for(c) == c
        cells.append(Cell(f"bucket{c}", make(c), {}, role="ladder",
                          bucket=c))
    return cells + _sweep_mesh_cells(lambda: make(c0))


def _sweep_merge_cells():
    from mfm_tpu.serve.query import bucket_for

    th = 2 * _K + 2

    def make(m):
        return (
            _sweep_carry_avals(),                          # carry (donated)
            _sds((m, _K, _K), jnp.float32),                # covs (exact path)
            _sds((_SWEEP_B, _K), jnp.float32),             # xs
            _sds((m, th), jnp.float32),                    # thetas
            _sds((m,), jnp.int32),                         # src
            _sds((m,), jnp.int32),                         # base_idx
            _sds((m,), jnp.bool_),                         # take
            _sds((m,), jnp.bool_),                         # projected
            _sds((_SWEEP_B,), jnp.float32),                # lo
            _sds((_SWEEP_B,), jnp.float32),                # width
        )

    m0 = _QUERY_BUCKETS[0]
    cells = [Cell(f"bucket{m0}", make(m0), {}, bucket=m0)]
    for m in _QUERY_BUCKETS:
        assert bucket_for(m) == m
        cells.append(Cell(f"bucket{m}", make(m), {}, role="ladder",
                          bucket=m))
    return cells + _sweep_mesh_cells(lambda: make(m0))


def _replicated_mesh_cells(args, meshes=((2, 4),)):
    """role='mesh' cells with EVERY operand replicated — the grad
    entrypoints' wire layout: their batches are portfolio/scenario lanes
    (no ('date','stock') panel axes to lay out), so under a mesh the whole
    program replicates and the collective pass proves it stays
    collective-free.  Skipped with a warn finding when the process has too
    few devices (matches _risk_fused_cells)."""
    from jax.sharding import NamedSharding, PartitionSpec
    from mfm_tpu.parallel.mesh import make_mesh

    cells = []
    for nd, ns in meshes:
        if jax.device_count() < nd * ns:
            cells.append(Cell(f"mesh{nd}x{ns}", (), {}, role="mesh",
                              mesh=(nd, ns)))
            continue
        mesh = make_mesh(nd, ns)
        rep = NamedSharding(mesh, PartitionSpec())
        cells.append(Cell(
            f"mesh{nd}x{ns}",
            tuple(jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=rep)
                  for a in args),
            {}, role="mesh", mesh=(nd, ns)))
    return cells


def _grad_ladder_cells(make_args):
    """primary + ladder + replicated-mesh cells over the query bucket
    ladder for one grad jit; ``make_args(b)`` builds the aval tuple at
    batch ``b``.  Solver knobs (eta/step/steps) are TRACED scalar
    operands, so every rung shares one static signature — the surface
    pass proves exactly one cache key per bucket."""
    from mfm_tpu.serve.query import bucket_for

    b0 = _QUERY_BUCKETS[0]
    cells = [Cell(f"bucket{b0}", make_args(b0), {}, bucket=b0)]
    for b in _QUERY_BUCKETS:
        assert bucket_for(b) == b, "declared grad bucket not a fixed point"
        cells.append(Cell(f"bucket{b}", make_args(b), {}, role="ladder",
                          bucket=b))
    return cells + _replicated_mesh_cells(make_args(b0))


def _grad_reverse_cells():
    th = 2 * _K + 2        # theta layout: shift | scale | vol_mult | corr

    def make(b):
        return (
            _sds((_K, _K), jnp.float32),      # cov
            _sds((b, _K), jnp.float32),       # xs
            _sds((b, th), jnp.float32),       # theta0 (donated)
            _sds((th,), jnp.float32),         # lo
            _sds((th,), jnp.float32),         # hi
            _sds((), jnp.float32),            # step (traced)
            _sds((), jnp.int32),              # steps (traced)
        )
    return _grad_ladder_cells(make)


def _grad_minvol_cells():
    def make(b):
        return (
            _sds((b, _K), jnp.float32),       # xs0 (donated)
            _sds((_K, _K), jnp.float32),      # cov
            _sds((_K,), jnp.float32),         # lo
            _sds((_K,), jnp.float32),         # hi
            _sds((), jnp.float32),            # eta (traced)
            _sds((), jnp.int32),              # steps (traced)
        )
    return _grad_ladder_cells(make)


def _grad_riskparity_cells():
    def make(b):
        return (
            _sds((b, _K), jnp.float32),       # xs0 (donated)
            _sds((_K, _K), jnp.float32),      # cov
            _sds((), jnp.float32),            # eta (traced)
            _sds((), jnp.int32),              # steps (traced)
        )
    return _grad_ladder_cells(make)


def _grad_hedge_cells():
    def make(b):
        return (
            _sds((b, _K), jnp.float32),       # xs0 (donated)
            _sds((b, _K), jnp.float32),       # hs0 (donated)
            _sds((_K, _K), jnp.float32),      # cov
            _sds((b, _K), jnp.float32),       # mask
            _sds((), jnp.float32),            # hmax (traced)
            _sds((), jnp.float32),            # eta (traced)
            _sds((), jnp.int32),              # steps (traced)
        )
    return _grad_ladder_cells(make)


def _grad_sensitivity_cells():
    def make(b):
        return (
            _sds((b, _K, _K), jnp.float32),   # base_cov
            _sds((b, _K), jnp.float32),       # shift (donated)
            _sds((b, _K), jnp.float32),       # scale (donated)
            _sds((b,), jnp.float32),          # vol_mult
            _sds((b,), jnp.float32),          # corr_beta
            _sds((_K,), jnp.float32),         # x
        )
    return _grad_ladder_cells(make)


def _guard_step_cells():
    T, N = AUDIT_MATRIX["T"], AUDIT_MATRIX["N"]
    policy = _guarded_config().quarantine
    args = (
        _sds((T, N), jnp.float32),                     # ret
        _sds((T, N), jnp.float32),                     # cap
        _sds((T, N), jnp.bool_),                       # valid
        _sds((policy.universe_window,), jnp.float32),  # ring
        _sds((), jnp.int32),                           # ring_pos
        policy,                                        # static (argnum 5)
        _sds((T,), jnp.uint32),                        # pre_reasons
        _sds((T,), jnp.bool_),                         # heal_mask
    )
    return [Cell("base", args, {}, static_argnums=(5,))]


# -- the registry ------------------------------------------------------------

def _build_registry() -> tuple:
    from mfm_tpu.grad import construct as _gc
    from mfm_tpu.grad import reverse as _gr
    from mfm_tpu.grad import sensitivity as _gs
    from mfm_tpu.models import risk_model as _rm
    from mfm_tpu.scenario import kernel as _sk
    from mfm_tpu.serve import guard as _guard
    from mfm_tpu.serve import query as _q

    return (
        Entrypoint(
            name="risk.fused",
            qualname="mfm_tpu.models.risk_model:_fused_risk_step",
            fn=_rm._fused_risk_step,
            donate=(0, 1, 2, 3, 4),
            build_cells=_risk_fused_cells,
            collectives_allow=frozenset({"all-reduce", "all-gather"}),
            notes="full-history fit, one fused XLA program"),
        Entrypoint(
            name="risk.init",
            qualname="mfm_tpu.models.risk_model:_fused_init_step",
            fn=_rm._fused_init_step,
            donate=(0, 1, 2, 3, 4, 7, 8, 9),
            build_cells=_risk_init_cells,
            collectives_allow=frozenset({"all-reduce", "all-gather"}),
            notes="fit + resumable carry (plain and incremental-eigen)"),
        Entrypoint(
            name="risk.update",
            qualname="mfm_tpu.models.risk_model:_fused_update_step",
            fn=_rm._fused_update_step,
            donate=(0, 1, 2, 3, 4, 6, 7, 8, 11, 12, 13),
            build_cells=_risk_update_cells,
            ladder="eigen",
            notes="daily append; eigen draw buckets are the retrace ladder"),
        Entrypoint(
            name="risk.update_guarded",
            qualname="mfm_tpu.models.risk_model:_fused_update_guarded_step",
            fn=_rm._fused_update_guarded_step,
            donate=(0, 1, 2, 3, 4, 6, 7, 8, 9, 10, 11, 12, 13, 18, 19, 20),
            build_cells=_risk_update_guarded_cells,
            collectives_allow=frozenset({"all-reduce", "all-gather"}),
            notes="guards + carried stages + degraded serving, one program"),
        Entrypoint(
            name="query.factor",
            qualname="mfm_tpu.serve.query:_batch_factor",
            fn=_q._batch_factor,
            donate=(0, 1),
            build_cells=_query_factor_cells,
            ladder="query",
            notes="factor-space portfolio queries, geometric 8*4^i buckets"),
        Entrypoint(
            name="query.stock",
            qualname="mfm_tpu.serve.query:_batch_stock",
            fn=_q._batch_stock,
            donate=(0, 1),
            build_cells=_query_stock_cells,
            notes="stock-space portfolio queries (same bucket discipline)"),
        Entrypoint(
            name="scenario.batch",
            qualname="mfm_tpu.scenario.kernel:scenario_batch",
            fn=_sk.scenario_batch,
            donate=(0, 3),
            build_cells=_scenario_cells,
            ladder="scenario",
            notes="S-lane covariance shocks, query-engine bucket ladder"),
        Entrypoint(
            name="scenario.sweep_chunk",
            qualname="mfm_tpu.scenario.kernel:sweep_chunk",
            fn=_sk.sweep_chunk,
            donate=(0,),
            build_cells=_sweep_chunk_cells,
            ladder="scenario",
            notes="streaming sweep fold: C certified lanes -> donated "
                  "top-k/histogram carry, in-jit sub-chunk scan"),
        Entrypoint(
            name="scenario.sweep_merge",
            qualname="mfm_tpu.scenario.kernel:sweep_merge",
            fn=_sk.sweep_merge,
            donate=(0,),
            build_cells=_sweep_merge_cells,
            ladder="scenario",
            notes="offender-lane merge: exact-path covariances folded "
                  "into the same donated sweep carry"),
        Entrypoint(
            name="grad.reverse",
            qualname="mfm_tpu.grad.reverse:reverse_stress_batch",
            fn=_gr.reverse_stress_batch,
            donate=(2,),
            build_cells=_grad_reverse_cells,
            ladder="query",
            notes="reverse stress: projected ascent over the shock ball, "
                  "differentiating through the gated PSD projection"),
        Entrypoint(
            name="grad.minvol",
            qualname="mfm_tpu.grad.construct:minvol_batch",
            fn=_gc.minvol_batch,
            donate=(0,),
            build_cells=_grad_minvol_cells,
            ladder="query",
            notes="min-vol construction (exponentiated gradient on the "
                  "boxed simplex), query bucket ladder"),
        Entrypoint(
            name="grad.riskparity",
            qualname="mfm_tpu.grad.construct:riskparity_batch",
            fn=_gc.riskparity_batch,
            donate=(0,),
            build_cells=_grad_riskparity_cells,
            ladder="query",
            notes="equal-risk-contribution construction (damped Jacobi on "
                  "the convex ERC root)"),
        Entrypoint(
            name="grad.hedge",
            qualname="mfm_tpu.grad.construct:hedge_batch",
            fn=_gc.hedge_batch,
            donate=(0, 1),
            build_cells=_grad_hedge_cells,
            ladder="query",
            notes="masked hedge-overlay construction (projected gradient "
                  "in the |h| <= hmax box)"),
        Entrypoint(
            name="grad.sensitivity",
            qualname="mfm_tpu.grad.sensitivity:sensitivity_batch",
            fn=_gs.sensitivity_batch,
            donate=(1, 2),
            build_cells=_grad_sensitivity_cells,
            ladder="query",
            notes="exact d vol/d shock + d vol/d exposure rows per "
                  "scenario lane (vjp, never finite differences)"),
        Entrypoint(
            name="guard.step",
            # the TRACED function's qualname (what mfmlint's call graph
            # reports for the jit(fn) call form binding guard_slab_jit)
            qualname="mfm_tpu.serve.guard:guard_slab",
            fn=_guard.guard_slab_jit,
            donate=(3, 4),
            build_cells=_guard_step_cells,
            notes="standalone slab health screen (ring donated through)"),
    )


#: jit roots that are deliberately NOT audit entrypoints — each with the
#: reason.  The registry-completeness test fails on any package jit root
#: missing from both REGISTRY and this map, so additions here are reviewed
#: justifications, not silent exemptions.
NON_ENTRYPOINT_JITS = {
    "mfm_tpu.factors.engine:_run_jit":
        "factor-stage program over the prepared field-panel dict; its "
        "operand set tracks the store schema, not a fixed shape matrix — "
        "covered by the crosscheck parity gates and its own steady-state "
        "compile tests",
    "mfm_tpu.ops.eigh_pallas:jacobi_eigh_tpu":
        "inner kernel dispatch; reached only through the fused risk steps, "
        "which the registry lowers end to end",
    "mfm_tpu.ops.eigh_pallas:jacobi_eigh_weighted_diag_tpu":
        "inner kernel dispatch (weighted-diagonal variant); same coverage "
        "as jacobi_eigh_tpu",
    "mfm_tpu.alpha.dsl:compile_alpha_batch.make_run.run":
        "per-expression-batch closure jit; shapes/statics are user-program "
        "dependent, no declarable config matrix (alpha DSL tests own it)",
    "mfm_tpu.alpha.dsl:compile_alpha_scores.make_run.run":
        "per-expression-batch closure jit (scored variant); same story",
}


@functools.lru_cache(maxsize=1)
def _registry_cached() -> tuple:
    return _build_registry()


def registry() -> tuple:
    """The registered entrypoints (built lazily — importing this module
    stays cheap; building touches serve/scenario/model modules)."""
    return _registry_cached()


def registry_by_name(name: str) -> Entrypoint:
    for ep in registry():
        if ep.name == name:
            return ep
    raise KeyError(f"no audit entrypoint named {name!r}")


class _LazyRegistry:
    """Tuple-like view over :func:`registry` that defers the build to first
    iteration, so ``from mfm_tpu.analysis import REGISTRY`` has no import
    side effects."""

    def __iter__(self):
        return iter(registry())

    def __len__(self):
        return len(registry())

    def __getitem__(self, i):
        return registry()[i]


REGISTRY = _LazyRegistry()


def flat_donated(ep: Entrypoint, cell: Cell) -> set:
    """Expand the entrypoint's POSITIONAL donate contract to FLATTENED
    operand indices of the lowered module for ``cell`` (None subtrees
    flatten to zero leaves, exactly as jit drops them)."""
    donated = set()
    idx = 0
    for pos, arg in enumerate(cell.args):
        if pos in cell.static_argnums:
            continue   # static: a Python value, no lowered operand
        leaves = jax.tree_util.tree_leaves(arg)
        if pos in ep.donate:
            donated.update(range(idx, idx + len(leaves)))
        idx += len(leaves)
    return donated
