"""The audit driver: build cells, lower/compile once, run A1–A5.

Everything here is device-FREE: entrypoints are lowered from abstract
``ShapeDtypeStruct`` avals and compiled for the CPU backend; nothing is
ever executed and no operand buffer is ever materialized.  The whole
matrix fits comfortably inside tier-1's 120 s budget because the
AUDIT_MATRIX shapes are tiny and each (entrypoint, cell) is lowered and
compiled exactly once, with every pass reading from the shared artifact
cache.

Numerics mode: the audit runs under ``jax.experimental.disable_x64`` —
the production f32 serving mode — regardless of the caller's global x64
setting (the test suite runs golden parity in x64; auditing THAT mode
would flag every program as a 64-bit leak and measure the wrong budgets).

Baseline contract (same as mfmlint): a committed JSON list of
``{"key", "note"}`` suppresses known findings by exact key; suppressed
keys that no longer fire are STALE and fail ``--strict`` so the baseline
can only shrink.
"""

from __future__ import annotations

import argparse
import dataclasses
import hashlib
import json
import os
import sys
import time

from mfm_tpu.analysis import aliasing, budgets, collectives, ir, surface
from mfm_tpu.analysis.registry import AUDIT_MATRIX, Finding, registry

AUDIT_SCHEMA = "mfmaudit/1"

PASS_IDS = ("A1", "A2", "A3", "A4", "A5")


@dataclasses.dataclass
class AuditReport:
    findings: list
    baselined: list
    stale_baseline: list
    measured: dict            # "ep/cell" -> budget metrics
    cells: dict               # "ep/cell" -> cell evidence
    matrix: dict
    passes: tuple
    wall_s: float

    @property
    def errors(self) -> list:
        return [f for f in self.findings if f.severity == "error"]

    @property
    def strict_clean(self) -> bool:
        return not self.errors and not self.stale_baseline

    def to_dict(self) -> dict:
        import jax

        return {
            "schema": AUDIT_SCHEMA,
            "jax": jax.__version__,
            "matrix": dict(self.matrix),
            "passes": list(self.passes),
            "cells": self.cells,
            "measured": {k: dict(v) for k, v in sorted(self.measured.items())},
            "findings": [f.to_dict() for f in self.findings],
            "baselined": [f.to_dict() for f in self.baselined],
            "stale_baseline": list(self.stale_baseline),
            "summary": {
                "errors": len(self.errors),
                "warnings": sum(1 for f in self.findings
                                if f.severity == "warn"),
                "info": sum(1 for f in self.findings
                            if f.severity == "info"),
                "cells": len(self.cells),
            },
            "strict_clean": self.strict_clean,
            "wall_s": round(self.wall_s, 2),
        }


def _build_artifacts(entrypoints, cells_by_ep, compile_cells: bool) -> dict:
    """Lower (and for primary/mesh cells compile) every cell once.  The
    artifact dict is the single shared evidence store every pass reads."""
    from mfm_tpu.obs.profile import compiled_memory_of

    artifacts = {}
    for ep in entrypoints:
        for cell in cells_by_ep[ep]:
            if cell.role == "ladder":
                continue   # surface pass works on avals alone
            art = {}
            artifacts[(ep, cell)] = art
            if cell.role == "mesh" and not cell.args:
                continue   # declared but unbuildable (too few devices)
            lowered = ep.fn.lower(*cell.args, **cell.kwargs)
            art["lowered"] = lowered
            art["stablehlo"] = lowered.as_text()
            if compile_cells:
                compiled = lowered.compile()
                art["compiled"] = compiled
                art["compiled_text"] = compiled.as_text()
                if cell.role in ("primary", "mesh"):
                    # mesh cells are budgeted too (A5): the per-device
                    # footprint is the number sharding exists to shrink
                    art["memory"] = compiled_memory_of(compiled)
    return artifacts


def report_digest(doc: dict) -> str:
    """Content hash of a report payload, excluding the embedded hash
    itself.  ``mfm-tpu doctor --audit`` recomputes this over the committed
    AUDIT_r*.json — a hand-edited snapshot (strict_clean flipped to true,
    findings deleted) no longer matches and the doctor refuses it."""
    body = {k: v for k, v in doc.items() if k != "sha256"}
    blob = json.dumps(body, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def write_report(doc: dict, path: str) -> dict:
    """Seal ``doc`` with its digest and write it atomically (tmp -> fsync
    -> rename -> dir fsync), same as every committed snapshot here — a
    SIGKILL mid-write must not tear the artifact the doctor verifies."""
    doc = dict(doc)
    doc["sha256"] = report_digest(doc)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                    os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)
    return doc


def load_audit_baseline(path: str | None) -> list:
    if not path:
        return []
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as fh:
        entries = json.load(fh)
    for e in entries:
        if "key" not in e or "note" not in e:
            raise ValueError(f"audit baseline entry missing key/note: {e}")
    return entries


def run_audit(passes=PASS_IDS, baseline: list | None = None,
              budgets_path: str | None = None) -> AuditReport:
    """Lower + inspect the whole registry.  Pure analysis: no entrypoint
    executes, no file is written (the CLI owns report/budget IO)."""
    import warnings

    from jax.experimental import disable_x64

    passes = tuple(passes)
    unknown = set(passes) - set(PASS_IDS)
    if unknown:
        raise ValueError(f"unknown audit passes {sorted(unknown)}")
    t0 = time.perf_counter()
    findings: list = []
    with disable_x64(), warnings.catch_warnings():
        # the lowering emits "Some donated buffers were not usable" for
        # legitimately inert donations — that is exactly what A1 reports
        # as structured `donated-unaliased` findings instead
        warnings.filterwarnings("ignore", message=".*[Dd]onat.*",
                                category=UserWarning)
        entrypoints = registry()
        cells_by_ep = {ep: ep.cells() for ep in entrypoints}
        need_compile = bool({"A1", "A3", "A5"} & set(passes))
        artifacts = _build_artifacts(entrypoints, cells_by_ep, need_compile)

        if "A1" in passes:
            findings += aliasing.run_pass(artifacts)
        if "A2" in passes:
            findings += ir.run_pass(artifacts)
        if "A3" in passes:
            findings += collectives.run_pass(artifacts)
        if "A4" in passes:
            findings += surface.run_pass(entrypoints, cells_by_ep)
        measured: dict = {}
        if "A5" in passes:
            b_findings, measured = budgets.run_pass(
                artifacts, budgets_path or budgets.DEFAULT_BUDGETS_PATH)
            findings += b_findings

    # evidence summary per cell (rides into AUDIT_r*.json)
    cells = {}
    for (ep, cell), art in artifacts.items():
        entry = {"role": cell.role, "lowered": "stablehlo" in art,
                 "compiled": "compiled_text" in art}
        if cell.mesh:
            entry["mesh"] = list(cell.mesh)
        if "collectives" in art:
            entry["collectives"] = art["collectives"]
        if "stablehlo" in art:
            entry["tensor_dtypes"] = sorted(
                ir.module_tensor_dtypes(art["stablehlo"]))
        cells[f"{ep.name}/{cell.name}"] = entry

    baseline = baseline or []
    keys = {e["key"] for e in baseline}
    fired = {f.key() for f in findings if f.key() in keys}
    kept = [f for f in findings if f.key() not in keys]
    suppressed = [f for f in findings if f.key() in keys]
    stale = sorted(keys - fired)
    return AuditReport(
        findings=kept, baselined=suppressed, stale_baseline=stale,
        measured=measured, cells=cells, matrix=AUDIT_MATRIX, passes=passes,
        wall_s=time.perf_counter() - t0)


_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(_REPO, "tools", "mfmaudit_baseline.json")


def latest_snapshot_path(root: str = _REPO) -> str | None:
    """The newest committed ``AUDIT_r*.json`` (same naming ladder as the
    perfgate's ``BENCH_r*.json`` trajectory), or None."""
    import glob

    found = sorted(glob.glob(os.path.join(root, "AUDIT_r*.json")))
    return found[-1] if found else None


def verify_snapshot(path: str, budgets_path: str | None = None):
    """`mfm-tpu doctor --audit`: is the committed audit snapshot intact,
    strict-clean, and still describing THIS tree?

    Returns ``(problems, warnings, doc)``; ``doc`` is None when the file
    is torn/unreadable.  Checks, in order: parseability (a torn write is
    a problem, not a crash), schema, the seal digest (hand-editing the
    snapshot — flipping ``strict_clean``, deleting findings — breaks it),
    strict-cleanliness of the recorded run, measurement agreement with
    the committed budget file, and cell-coverage agreement with the LIVE
    registry (an entrypoint added since the snapshot means the snapshot
    vouches for a tree that no longer exists).
    """
    problems: list = []
    warns: list = []
    try:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
    except OSError as err:
        return [f"snapshot unreadable: {err}"], warns, None
    except json.JSONDecodeError as err:
        return [f"snapshot torn or not JSON: {err}"], warns, None
    if not isinstance(doc, dict) or doc.get("schema") != AUDIT_SCHEMA:
        got = doc.get("schema") if isinstance(doc, dict) else type(doc)
        problems.append(f"unsupported snapshot schema {got!r} "
                        f"(want {AUDIT_SCHEMA})")
        return problems, warns, doc
    sha = doc.get("sha256")
    if not isinstance(sha, str):
        problems.append("snapshot carries no seal (sha256) — regenerate "
                        "with `mfm-tpu audit --json`")
    elif report_digest(doc) != sha:
        problems.append("seal digest mismatch — the snapshot was edited "
                        "after it was sealed (or the write tore)")
    if not doc.get("strict_clean", False):
        problems.append("snapshot records a run that was NOT strict-clean "
                        "— the audited tree had gating findings")

    from mfm_tpu.analysis import budgets as budgets_mod

    budgets = budgets_mod.load_budgets(
        budgets_path or budgets_mod.DEFAULT_BUDGETS_PATH)
    snap = {k: {m: int(v) for m, v in d.items()}
            for k, d in (doc.get("measured") or {}).items()}
    live = {k: {m: int(v) for m, v in d.items()}
            for k, d in (budgets.get("cells") or {}).items()}
    if snap != live:
        drift = sorted(k for k in set(snap) | set(live)
                       if snap.get(k) != live.get(k))
        problems.append(
            f"snapshot measurements disagree with tools/audit_budgets.json "
            f"at {drift} — one of the two is stale; re-run "
            f"`mfm-tpu audit --write-budgets --json AUDIT_r*.json`")

    try:
        from mfm_tpu.analysis.registry import registry

        expected = {f"{ep.name}/{cell.name}"
                    for ep in registry() for cell in ep.cells()
                    if cell.role != "ladder"}
    except Exception as err:   # registry must never crash the doctor
        warns.append(f"could not rebuild the live registry for the "
                     f"drift check: {err}")
    else:
        got = set(doc.get("cells") or {})
        if got != expected:
            problems.append(
                f"snapshot covers cells {sorted(got ^ expected)} "
                f"differently than the live registry — the snapshot "
                f"vouches for a different tree; regenerate it")

    import jax

    if doc.get("jax") != jax.__version__:
        warns.append(f"snapshot was sealed under jax {doc.get('jax')}, "
                     f"running {jax.__version__} — re-audit before "
                     f"trusting the budget numbers")
    return problems, warns, doc


def main(argv=None) -> int:
    """Shared CLI body behind ``python tools/mfmaudit.py`` and
    ``mfm-tpu audit`` (the tools shim additionally pins the CPU backend
    and the 8-way virtual device split before jax loads)."""
    from mfm_tpu.analysis import budgets as budgets_mod

    ap = argparse.ArgumentParser(
        prog="mfmaudit",
        description="IR-level static analysis of every jit entrypoint "
                    "(passes A1-A5; see docs/AUDIT.md)")
    ap.add_argument("--passes", default=",".join(PASS_IDS),
                    help="comma-separated subset of passes to run")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of suppressed findings "
                         "('none' disables)")
    ap.add_argument("--budgets", default=None,
                    help="budget file for A5 (default: "
                         "tools/audit_budgets.json)")
    ap.add_argument("--write-budgets", action="store_true",
                    help="freeze the measured A5 numbers as the new "
                         "budget file instead of gating against them")
    ap.add_argument("--json", dest="json_out", default=None, metavar="FILE",
                    help="write the sealed report JSON to FILE "
                         "('-' for stdout)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    args = ap.parse_args(argv)

    passes = tuple(p.strip() for p in args.passes.split(",") if p.strip())
    bl_path = None if args.baseline.lower() == "none" else (
        args.baseline if os.path.isabs(args.baseline)
        else os.path.join(_REPO, args.baseline))
    budgets_path = args.budgets or budgets_mod.DEFAULT_BUDGETS_PATH

    rep = run_audit(passes=passes, baseline=load_audit_baseline(bl_path),
                    budgets_path=budgets_path)

    if args.write_budgets:
        if not rep.measured:
            print("mfmaudit: --write-budgets needs pass A5 in --passes",
                  file=sys.stderr)
            return 2
        budgets_mod.write_budgets(rep.measured, budgets_path)
        # re-gate A5 against the file just frozen: the pre-freeze
        # unbudgeted/over findings are the reason the user regenerated
        rep.findings = (
            [f for f in rep.findings if f.pass_id != "A5"]
            + budgets_mod.check_budgets(
                rep.measured, budgets_mod.load_budgets(budgets_path)))
        print(f"mfmaudit: froze {len(rep.measured)} cell budget(s) -> "
              f"{budgets_path}")

    doc = rep.to_dict()
    if args.json_out == "-":
        doc["sha256"] = report_digest(doc)
        print(json.dumps(doc, indent=1, sort_keys=True))
    else:
        if args.json_out:
            write_report(doc, args.json_out)
            print(f"mfmaudit: wrote sealed report -> {args.json_out}")
        for f in rep.findings:
            print(f"{f.pass_id} {f.severity:5s} {f.entrypoint}/{f.cell} "
                  f"[{f.code}] {f.message}")
        for key in rep.stale_baseline:
            print(f"STALE baseline entry: {key} — the finding no longer "
                  f"fires; remove it")
        s = doc["summary"]
        print(f"mfmaudit: {s['errors']} error(s), {s['warnings']} "
              f"warning(s), {s['info']} info over {s['cells']} cell(s), "
              f"{len(rep.baselined)} baselined, "
              f"{len(rep.stale_baseline)} stale baseline entr(ies) "
              f"[{doc['wall_s']:.1f}s]")
    if rep.errors:
        return 1
    if args.strict and rep.stale_baseline:
        return 1
    return 0
