"""mfmsync — lock-discipline & shared-state static analysis for the fleet.

PRs 13-17 made serving genuinely concurrent: coalescer flusher thread,
per-connection writer threads, replica pipe pumps, the breaker, the LRU
response cache and the obs registry all share mutable state under
``threading.Lock/RLock/Condition``.  mfmlint sees none of that — a lock
inversion or an unguarded field write is invisible to the JAX doctrine —
and the bitwise-parity contracts the repo is built on (coalesced ==
sequential per id, cache hit == cold bytes) are exactly what a silent
race corrupts nondeterministically.  This pass closes the gap in the
mfmlint mold: stdlib-only AST analysis, a ``(file, rule, qualname)``
keyed baseline with stale detection, and a ``--strict`` gate.

Rules:

  S1  unguarded access to a guarded field.  Per class, a field counts as
      *guarded* when at least one write to it outside ``__init__``
      happens while the class's lock is held; every other read or write
      of it outside ``__init__`` must then also hold the lock.
  S2  lock-order hazard: a cycle in the lock-acquisition order graph
      (potential deadlock), or re-acquiring a non-reentrant
      ``threading.Lock`` already held.  ``threading.Condition(lock)``
      aliases to its underlying lock, so waiting or re-locking through
      the condition is ordered against the same node.
  S3  blocking while holding a lock: socket/pipe I/O (accept/recv/
      sendall/connect/readline), ``subprocess`` spawns, ``time.sleep``,
      argument-less ``.join()``/``.get()``, waiting on a *foreign*
      condition or event, or a call that (transitively) dispatches jax
      work — the PR 13 slow-socket lesson generalized.  ``cond.wait()``
      on the lock currently held is the one legal blocking call (the
      wait releases it).

Held-region inference, all conventions documented in docs/DOCTRINE.md
("Concurrency doctrine"):

- ``with self._lock:`` blocks (and ``with <module lock>:`` for
  module-level locks) establish held regions syntactically.
- A method whose name ends in ``_locked`` is entered with its class's
  (or module's) lock held — the repo-wide naming convention.
- A private method (``_name``) is entered with the *intersection* of
  the held sets at its intra-class call sites (fixed point), which is
  how ``CircuitBreaker._to`` or ``FleetServer._dispatch`` inherit their
  callers' locks without annotations.
- ``threading.Thread`` targets are entry points: entered lock-free.
- Lock identity canonicalizes through inheritance (``FleetServer``'s
  ``self._lock`` *is* ``Coalescer._lock``) and condition aliasing.

Known blind spots (conservative on purpose, like mfmlint): fields
reached through another object (``conn.outstanding``, ``fleet.
accepted_total``), callback fields invoked under a lock
(``self._deliver(...)``), module-global state outside classes, and
blocking I/O more than one call level below a held region.  The
deterministic-interleaving harness (``mfm_tpu/utils/sched.py`` + the
``sync-schedule-*`` faultinject plans) exists to make the top findings
confirmable at runtime rather than merely plausible.

Like mfmlint, this module imports neither jax nor numpy.
"""

from __future__ import annotations

import argparse
import ast
import dataclasses
import json
import os
import sys
from typing import Iterable

from mfm_tpu.lint import (Linter, _attr_chain, collect_files, load_baseline,
                          REPO_ROOT)

#: sync analyzes the package only: tools/ are single-threaded CLI
#: entrypoints, and tests drive the package's primitives directly.
DEFAULT_TARGETS = ("mfm_tpu",)
DEFAULT_BASELINE = os.path.join("tools", "mfmsync_baseline.json")

SYNC_RULES = {
    "S1": "unguarded access to a guarded field — some writes hold the "
          "class lock, this access does not; a concurrent interleaving "
          "can lose updates or observe torn state",
    "S2": "lock-order hazard — a cycle in the lock-acquisition order "
          "graph (potential deadlock) or re-acquiring a non-reentrant "
          "Lock already held",
    "S3": "blocking operation reachable while a lock is held — socket/"
          "pipe I/O, subprocess, time.sleep, bare join()/get(), a "
          "foreign wait(), or a jit dispatch; every other thread "
          "contending for the lock stalls behind it",
}

#: threading constructors that create a lock-like primitive
_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition",
               "Semaphore": "Lock", "BoundedSemaphore": "Lock"}

#: queue constructors: queue-typed fields are internally synchronized,
#: so they are exempt from S1 and they mark a class as analyzed for the
#: thread-target coverage check
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue", "SimpleQueue"}

#: method calls on a self-attribute that mutate it (S1 write detection)
_MUTATORS = {"add", "discard", "remove", "append", "appendleft", "extend",
             "insert", "pop", "popleft", "popitem", "clear", "update",
             "setdefault", "put", "put_nowait", "move_to_end"}

#: attribute calls that block on I/O regardless of receiver type
_BLOCKING_ATTRS = {"accept", "recv", "recv_into", "recvfrom", "sendall",
                   "connect", "readline", "readlines"}

_SUBPROCESS_CALLS = {"run", "Popen", "call", "check_call", "check_output",
                     "communicate"}

#: attribute names so generic (container / threading protocol) that a
#: bare-name match is noise: ``self._done.add(tid)`` must not resolve to
#: some class's unrelated ``add`` method and manufacture lock edges out
#: of thin air.  Confident self/cls MRO resolutions are unaffected, so
#: ``self.put(...)`` inside the owning class still counts.
_GENERIC_ATTRS = _MUTATORS | {
    "wait", "notify", "notify_all", "acquire", "release", "join", "get",
    "close", "items", "keys", "values", "copy", "sort", "index", "count",
    "split", "strip", "encode", "decode", "read", "write",
}


@dataclasses.dataclass
class SyncViolation:
    file: str
    line: int
    rule: str
    qualname: str
    message: str

    def key(self) -> tuple:
        return (self.file, self.rule, self.qualname)

    def render(self) -> str:
        return (f"{self.file}:{self.line}: {self.rule} [{self.qualname}] "
                f"{self.message}\n    doctrine: {SYNC_RULES[self.rule]}")


@dataclasses.dataclass
class ClassInfo:
    qualname: str                 # module:Class
    name: str
    module: str
    file: str
    node: object
    base_names: list = dataclasses.field(default_factory=list)
    #: lock attr -> kind ("Lock" | "RLock" | "Condition" | "unknown")
    lock_attrs: dict = dataclasses.field(default_factory=dict)
    #: condition attr -> underlying lock attr (Condition(self._lock))
    lock_alias: dict = dataclasses.field(default_factory=dict)
    queue_attrs: set = dataclasses.field(default_factory=set)
    #: attrs assigned via plain `self.X = ...` anywhere in the class
    stores: set = dataclasses.field(default_factory=set)
    methods: dict = dataclasses.field(default_factory=dict)  # name -> qual


class _FuncScan(ast.NodeVisitor):
    """One pass over a function body: self-attribute accesses, lock
    acquisitions and call sites, each annotated with the locally-held
    lock set (entry-held context unions in later)."""

    def __init__(self, analyzer, info, cls):
        self.an = analyzer
        self.info = info
        self.cls = cls
        self.local: list = []
        self.accesses: list = []   # (attr, is_write, frozenset, lineno)
        self.acquires: list = []   # (frozenset-before, node, kind, lineno)
        self.calls: list = []      # (ast.Call, frozenset, lineno)

    # nested defs are separate FuncInfos with their own scans
    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _access(self, attr, write, lineno):
        self.accesses.append((attr, write, frozenset(self.local), lineno))

    def _with(self, node):
        acquired = []
        for item in node.items:
            self.visit(item.context_expr)
            lk = self.an._lock_node_of(self.cls, self.info,
                                       item.context_expr)
            if lk is not None:
                self.acquires.append((frozenset(self.local), lk[0], lk[1],
                                      item.context_expr.lineno))
                acquired.append(lk[0])
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        self.local.extend(acquired)
        for s in node.body:
            self.visit(s)
        if acquired:
            del self.local[-len(acquired):]

    visit_With = _with
    visit_AsyncWith = _with

    def visit_Call(self, node):
        self.calls.append((node, frozenset(self.local), node.lineno))
        f = node.func
        # mutator write: self.X.append(...) / .add / .put_nowait / ...
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS and \
                isinstance(f.value, ast.Attribute) and \
                isinstance(f.value.value, ast.Name) and \
                f.value.value.id == "self":
            self._access(f.value.attr, True, node.lineno)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._access(node.attr, write, node.lineno)
        self.generic_visit(node)

    def _subscript_write(self, tgt, lineno):
        # self.X[k] = v mutates X even though the AST loads the attribute
        while isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if isinstance(tgt, ast.Attribute) and \
                isinstance(tgt.value, ast.Name) and tgt.value.id == "self":
            self._access(tgt.attr, True, lineno)

    def visit_Assign(self, node):
        for t in node.targets:
            self._subscript_write(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._subscript_write(node.target, node.lineno)
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            self._subscript_write(t, node.lineno)
        self.generic_visit(node)


class SyncAnalyzer:
    """The pass.  Feed it a built :class:`~mfm_tpu.lint.Linter` (call
    graph, imports, jax-touch closure) and call :meth:`run`."""

    def __init__(self, linter: Linter):
        self.lint = linter
        self.classes: dict[str, ClassInfo] = {}      # module:Class -> info
        self.module_locks: dict[str, dict] = {}      # module -> {name: kind}
        self.method_class: dict[str, ClassInfo] = {} # func qual -> class
        self.scans: dict[str, _FuncScan] = {}
        self.entry: dict[str, object] = {}           # qual -> frozenset|None
        self.thread_targets: list = []               # (qual|None, repr, file, line)
        self.lock_kinds: dict[str, str] = {}         # node id -> kind
        self.violations: list[SyncViolation] = []

    # -- discovery ------------------------------------------------------------
    def _ctor_of(self, mod, call) -> tuple | None:
        """('threading'|'queue', ctor-name) for a constructor call."""
        if not isinstance(call, ast.Call):
            return None
        f = call.func
        if isinstance(f, ast.Name):
            src = mod.from_imports.get(f.id)
            if src:
                return (src[0], src[1])
            return None
        chain = _attr_chain(f)
        if not chain or len(chain) < 2:
            return None
        root = mod.module_imports.get(chain[0])
        if root in ("threading", "queue"):
            return (root, chain[-1])
        return None

    def _collect_classes(self):
        for mod in self.lint.modules.values():
            # module-level locks (obs/trace.py style)
            locks = {}
            for stmt in mod.tree.body:
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    ctor = self._ctor_of(mod, stmt.value)
                    if ctor and ctor[0] == "threading" and \
                            ctor[1] in _LOCK_CTORS:
                        locks[stmt.targets[0].id] = _LOCK_CTORS[ctor[1]]
            if locks:
                self.module_locks[mod.name] = locks
            for stmt in mod.tree.body:
                if not isinstance(stmt, ast.ClassDef):
                    continue
                ci = ClassInfo(f"{mod.name}:{stmt.name}", stmt.name,
                               mod.name, mod.file, stmt)
                for b in stmt.bases:
                    if isinstance(b, ast.Name):
                        ci.base_names.append(b.id)
                    else:
                        chain = _attr_chain(b)
                        if chain:
                            ci.base_names.append(chain[-1])
                self._scan_class_body(mod, ci)
                self.classes[ci.qualname] = ci
        # map methods to classes
        for qual, info in self.lint.funcs.items():
            local = qual.split(":", 1)[1]
            if "." in local:
                clsname = local.rsplit(".", 1)[0]
                ci = self.classes.get(f"{info.module}:{clsname}")
                if ci is not None:
                    self.method_class[qual] = ci
                    ci.methods.setdefault(local.rsplit(".", 1)[1], qual)

    def _scan_class_body(self, mod, ci: ClassInfo):
        for n in ast.walk(ci.node):
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) \
                    else [n.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        ci.stores.add(t.attr)
                        ctor = self._ctor_of(mod, n.value)
                        if ctor is None:
                            continue
                        src, name = ctor
                        if src == "threading" and name in _LOCK_CTORS:
                            ci.lock_attrs[t.attr] = _LOCK_CTORS[name]
                            if name == "Condition" and n.value.args:
                                a0 = n.value.args[0]
                                if isinstance(a0, ast.Attribute) and \
                                        isinstance(a0.value, ast.Name) and \
                                        a0.value.id == "self":
                                    ci.lock_alias[t.attr] = a0.attr
                        elif src == "queue" and name in _QUEUE_CTORS:
                            ci.queue_attrs.add(t.attr)
            elif isinstance(n, (ast.AugAssign,)):
                t = n.target
                if isinstance(t, ast.Attribute) and \
                        isinstance(t.value, ast.Name) and t.value.id == "self":
                    ci.stores.add(t.attr)
            elif isinstance(n, (ast.With, ast.AsyncWith)):
                for item in n.items:
                    e = item.context_expr
                    if isinstance(e, ast.Attribute) and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self":
                        ci.lock_attrs.setdefault(e.attr, "unknown")

    # -- lock identity --------------------------------------------------------
    def _mro(self, ci: ClassInfo) -> list:
        out, seen, stack = [], set(), [ci]
        while stack:
            c = stack.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            out.append(c)
            for b in c.base_names:
                # same module first, then any analyzed module
                cand = self.classes.get(f"{c.module}:{b}")
                if cand is None:
                    for q, other in self.classes.items():
                        if other.name == b:
                            cand = other
                            break
                if cand is not None:
                    stack.append(cand)
        return out

    def _canon_lock(self, ci: ClassInfo, attr: str) -> tuple | None:
        """(node id, kind) for a class lock attr, alias- and
        inheritance-resolved; None when the attr is no known lock."""
        mro = self._mro(ci)
        seen = set()
        while attr not in seen:
            seen.add(attr)
            nxt = None
            for c in mro:
                if attr in c.lock_alias:
                    nxt = c.lock_alias[attr]
                    break
            if nxt is None:
                break
            attr = nxt
        kind = None
        for c in mro:
            k = c.lock_attrs.get(attr)
            if k and k != "unknown":
                kind = k
                break
            if k and kind is None:
                kind = k
        if kind is None:
            return None
        owner = ci
        for c in reversed(mro):      # most basal class that assigns it
            if attr in c.stores or attr in c.lock_attrs:
                owner = c
                break
        node = f"{owner.qualname}.{attr}"
        self.lock_kinds.setdefault(node, kind)
        return node, kind

    def _class_lock_nodes(self, ci: ClassInfo) -> frozenset:
        out = set()
        for c in self._mro(ci):
            for attr in c.lock_attrs:
                lk = self._canon_lock(ci, attr)
                if lk:
                    out.add(lk[0])
        return frozenset(out)

    def _module_lock_nodes(self, module: str) -> frozenset:
        locks = self.module_locks.get(module, {})
        out = set()
        for name, kind in locks.items():
            node = f"{module}:<module>.{name}"
            self.lock_kinds.setdefault(node, kind)
            out.add(node)
        return frozenset(out)

    def _lock_node_of(self, cls, info, expr) -> tuple | None:
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            if cls is None:
                return None
            return self._canon_lock(cls, expr.attr)
        if isinstance(expr, ast.Name):
            locks = self.module_locks.get(info.module, {})
            if expr.id in locks:
                node = f"{info.module}:<module>.{expr.id}"
                self.lock_kinds.setdefault(node, locks[expr.id])
                return node, locks[expr.id]
        return None

    # -- thread targets -------------------------------------------------------
    def _is_thread_ctor(self, mod, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Name):
            return mod.from_imports.get(f.id) == ("threading", "Thread")
        chain = _attr_chain(f)
        return bool(chain) and len(chain) >= 2 and \
            mod.module_imports.get(chain[0]) == "threading" and \
            chain[-1] == "Thread"

    def _collect_thread_targets(self):
        for qual, info in self.lint.funcs.items():
            mod = self.lint.modules[info.module]
            cls = self.method_class.get(qual)
            for n in ast.walk(info.node):
                if not (isinstance(n, ast.Call)
                        and self._is_thread_ctor(mod, n)):
                    continue
                tgt_expr = None
                for kw in n.keywords:
                    if kw.arg == "target":
                        tgt_expr = kw.value
                if tgt_expr is None and n.args:
                    tgt_expr = n.args[0]
                if tgt_expr is None:
                    continue
                # the target may be conditional (http vs jsonl reader):
                # resolve every self-attr / bare name inside the expr
                found = False
                for e in ast.walk(tgt_expr):
                    tq = None
                    if isinstance(e, ast.Attribute) and \
                            isinstance(e.value, ast.Name) and \
                            e.value.id == "self" and cls is not None:
                        for c in self._mro(cls):
                            if e.attr in c.methods:
                                tq = c.methods[e.attr]
                                break
                    elif isinstance(e, ast.Name):
                        hits = self.lint._resolve_in_module(mod, e.id)
                        tq = hits[0] if hits else None
                    if tq is not None:
                        self.thread_targets.append(
                            (tq, ast.dump(tgt_expr)[:60], info.file,
                             n.lineno))
                        found = True
                if not found:
                    self.thread_targets.append(
                        (None, ast.dump(tgt_expr)[:60], info.file, n.lineno))

    def analyzed_classes(self) -> set:
        """Class qualnames owning a lock or a queue field (directly or by
        inheritance) — the shared-state surface this pass reasons about."""
        out = set()
        for q, ci in self.classes.items():
            for c in self._mro(ci):
                if c.lock_attrs or c.queue_attrs:
                    out.add(q)
                    break
        return out

    def thread_target_coverage(self) -> tuple[list, list]:
        """(covered, uncovered) thread-target records; uncovered targets
        need an S4 justification entry in the baseline."""
        analyzed = self.analyzed_classes()
        covered, uncovered = [], []
        for tq, rep, file, line in self.thread_targets:
            rec = {"target": tq, "expr": rep, "file": file, "line": line}
            cls = self.method_class.get(tq) if tq else None
            if cls is not None and cls.qualname in analyzed:
                covered.append(rec)
            else:
                uncovered.append(rec)
        return covered, uncovered

    # -- entry-held fixpoint --------------------------------------------------
    def _confident_target(self, qual, func_expr) -> str | None:
        info = self.lint.funcs[qual]
        mod = self.lint.modules[info.module]
        if isinstance(func_expr, ast.Name):
            hits = self.lint._resolve_in_module(mod, func_expr.id)
            return hits[0] if len(hits) == 1 else None
        if isinstance(func_expr, ast.Attribute) and \
                isinstance(func_expr.value, ast.Name) and \
                func_expr.value.id in ("self", "cls"):
            cls = self.method_class.get(qual)
            if cls is not None:
                for c in self._mro(cls):
                    if func_expr.attr in c.methods:
                        return c.methods[func_expr.attr]
        return None

    def _init_entry(self):
        forced = {tq for tq, _r, _f, _l in self.thread_targets if tq}
        for qual, info in self.lint.funcs.items():
            cls = self.method_class.get(qual)
            name = info.name
            if cls is not None:
                if name == "__init__" or qual in forced:
                    self.entry[qual] = frozenset()
                elif name.endswith("_locked"):
                    self.entry[qual] = self._class_lock_nodes(cls)
                elif name.startswith("_") and not name.startswith("__"):
                    self.entry[qual] = None       # TOP: narrowed by fixpoint
                else:
                    self.entry[qual] = frozenset()
            else:
                if name.endswith("_locked"):
                    self.entry[qual] = self._module_lock_nodes(info.module)
                elif name.startswith("_") and not name.startswith("__") \
                        and not name.startswith("<"):
                    self.entry[qual] = None
                else:
                    self.entry[qual] = frozenset()

    def _fixpoint_entry(self):
        fix_vars = {q for q, v in self.entry.items() if v is None}
        for _ in range(10):
            sites: dict[str, list] = {}
            for qual, scan in self.scans.items():
                base = self.entry.get(qual)
                for cnode, local, _line in scan.calls:
                    tgt = self._confident_target(qual, cnode.func)
                    if tgt is None or tgt not in fix_vars:
                        continue
                    held = None if base is None else frozenset(base | local)
                    sites.setdefault(tgt, []).append(held)
            changed = False
            for tgt in fix_vars:
                known = [h for h in sites.get(tgt, []) if h is not None]
                if not known:
                    continue
                new = frozenset.intersection(*known)
                if self.entry[tgt] is None or self.entry[tgt] != new:
                    self.entry[tgt] = new
                    changed = True
            if not changed:
                break
        for q in fix_vars:                # never called confidently: entry
            if self.entry[q] is None:     # points are conservative
                self.entry[q] = frozenset()

    # -- shared call-resolution helpers --------------------------------------
    def _full_targets(self, qual, cnode) -> list:
        f = cnode.func
        if isinstance(f, ast.Attribute) and f.attr in _GENERIC_ATTRS:
            t = self._confident_target(qual, f)
            return [t] if t else []
        return self.lint._resolve_call(self.lint.funcs[qual], cnode.func)

    def _restricted_targets(self, qual, cnode) -> list:
        """Targets resolved confidently or by a UNIQUE bare name — the
        only resolutions trusted for transitive reasoning (jit-dispatch,
        may-acquire closure); ambiguous bare names stay one-level."""
        tgts = self._full_targets(qual, cnode)
        if len(tgts) == 1:
            return tgts
        t = self._confident_target(qual, cnode.func)
        return [t] if t else []

    def _is_intraclass(self, qual, cnode) -> bool:
        f = cnode.func
        if not (isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name)
                and f.value.id in ("self", "cls")):
            return False
        cls = self.method_class.get(qual)
        if cls is None:
            return False
        return any(f.attr in c.methods for c in self._mro(cls))

    # -- blocking classification (S3) ----------------------------------------
    def _blocking_desc(self, qual, cnode, held) -> str | None:
        info = self.lint.funcs[qual]
        mod = self.lint.modules[info.module]
        f = cnode.func
        if isinstance(f, ast.Name):
            if f.id == "sleep" and f.id in mod.time_aliases:
                return "time.sleep()"
            src = mod.from_imports.get(f.id)
            if src and src[0] == "subprocess" and \
                    src[1] in _SUBPROCESS_CALLS:
                return f"subprocess.{src[1]}()"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        chain = _attr_chain(f) or []
        attr = f.attr
        if chain and chain[0] in mod.time_aliases and attr == "sleep":
            return "time.sleep()"
        if chain and mod.module_imports.get(chain[0]) == "subprocess" and \
                attr in _SUBPROCESS_CALLS:
            return f"subprocess.{attr}()"
        if chain and chain[0] in (mod.jnp_aliases | mod.jax_aliases
                                  | mod.lax_aliases):
            return f"direct jax dispatch ({'.'.join(chain)})"
        if attr in _BLOCKING_ATTRS:
            return f"socket/pipe I/O (.{attr}())"
        if attr == "join" and not cnode.args:
            return "blocking .join()"
        if attr == "get" and not cnode.args:
            return "blocking queue .get()"
        if attr == "wait":
            obj = f.value
            if isinstance(obj, ast.Attribute) and \
                    isinstance(obj.value, ast.Name) and \
                    obj.value.id == "self":
                cls = self.method_class.get(qual)
                lk = self._canon_lock(cls, obj.attr) if cls else None
                if lk is not None and lk[0] in held:
                    return None       # waiting on the held condition: legal
            if isinstance(obj, ast.Name):
                locks = self.module_locks.get(info.module, {})
                if obj.id in locks and \
                        f"{info.module}:<module>.{obj.id}" in held:
                    return None
            return "wait() on a foreign condition/event"
        return None

    # -- rule passes ----------------------------------------------------------
    def run(self):
        self._collect_classes()
        self._collect_thread_targets()
        for qual, info in self.lint.funcs.items():
            scan = _FuncScan(self, info, self.method_class.get(qual))
            body = (info.node.body if not isinstance(info.node, ast.Lambda)
                    else [info.node.body])
            for s in body:
                scan.visit(s)
            self.scans[qual] = scan
        self._init_entry()
        self._fixpoint_entry()
        self._rule_s1()
        self._rule_s2()
        self._rule_s3()
        self.violations.sort(key=lambda v: (v.file, v.line, v.rule))

    def _emit(self, qual, line, rule, msg):
        info = self.lint.funcs[qual]
        self.violations.append(SyncViolation(
            info.file, line, rule, qual.split(":", 1)[1], msg))

    def _held_at(self, qual, local) -> frozenset:
        base = self.entry.get(qual) or frozenset()
        return frozenset(base | local)

    def _rule_s1(self):
        field_acc: dict[tuple, list] = {}
        for qual, scan in self.scans.items():
            cls = self.method_class.get(qual)
            if cls is None:
                continue
            locks = self._class_lock_nodes(cls)
            if not locks:
                continue
            mro = self._mro(cls)
            excl = set()
            for c in mro:
                excl |= set(c.lock_attrs) | set(c.lock_alias) | c.queue_attrs
            info = self.lint.funcs[qual]
            in_init = info.name == "__init__"
            for attr, write, local, line in scan.accesses:
                if attr in excl:
                    continue
                owner = cls
                for c in reversed(mro):
                    if attr in c.stores:
                        owner = c
                        break
                held = self._held_at(qual, local)
                field_acc.setdefault((owner.qualname, attr), []).append(
                    (write, bool(held & locks), in_init, qual, line))
        for (_owner, attr), accs in sorted(field_acc.items()):
            guarded_by = [a for a in accs if a[0] and a[1] and not a[2]]
            if not guarded_by:
                continue
            seen = set()
            for write, protected, in_init, qual, line in accs:
                if in_init or protected:
                    continue
                info = self.lint.funcs[qual]
                k = (info.file, line, attr)
                if k in seen:
                    continue
                seen.add(k)
                self._emit(qual, line, "S1",
                           f"unguarded {'write to' if write else 'read of'} "
                           f"guarded field 'self.{attr}' — "
                           f"{len(guarded_by)} other write(s) hold the "
                           "class lock; this access does not")

    def _direct_acquires(self, qual) -> set:
        return {node for _h, node, _k, _l in self.scans[qual].acquires}

    def _may_acquire(self) -> dict:
        """Transitive lock-acquisition closure over confidently / uniquely
        resolved calls (ambiguous bare names are excluded: a spurious
        deep edge is how over-approximation manufactures fake cycles)."""
        ma = {q: set(self._direct_acquires(q)) for q in self.scans}
        rtgts = {}
        for qual, scan in self.scans.items():
            outs = set()
            for cnode, _local, _line in scan.calls:
                outs.update(self._restricted_targets(qual, cnode))
            rtgts[qual] = outs
        changed = True
        while changed:
            changed = False
            for q, outs in rtgts.items():
                for t in outs:
                    if t in ma and not ma[t] <= ma[q]:
                        ma[q] |= ma[t]
                        changed = True
        return ma

    def _rule_s2(self):
        edges: dict[tuple, tuple] = {}   # (a, b) -> (qual, line, via)

        def add_edge(a, b, qual, line, via):
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (qual, line, via)

        ma = self._may_acquire()
        for qual, scan in self.scans.items():
            for local_before, node, kind, line in scan.acquires:
                held = self._held_at(qual, local_before)
                if node in held and kind == "Lock":
                    self._emit(qual, line, "S2",
                               f"re-acquiring non-reentrant lock {node} "
                               "already held — self-deadlock")
                for h in sorted(held):
                    add_edge(h, node, qual, line, "direct")
            for cnode, local, line in scan.calls:
                held = self._held_at(qual, local)
                if not held:
                    continue
                reach = set()
                for t in self._full_targets(qual, cnode):
                    if t in self.scans:
                        reach |= self._direct_acquires(t)
                for t in self._restricted_targets(qual, cnode):
                    reach |= ma.get(t, set())
                for h in sorted(held):
                    for a in sorted(reach):
                        add_edge(h, a, qual, line, "via call")
        # cycle detection (iterative DFS, deterministic order)
        graph: dict[str, list] = {}
        for a, b in edges:
            graph.setdefault(a, []).append(b)
        for outs in graph.values():
            outs.sort()
        state: dict[str, int] = {}
        reported = set()

        def dfs(start):
            stack = [(start, iter(graph.get(start, ())))]
            path = [start]
            state[start] = 1
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if state.get(nxt, 0) == 1:
                        cyc = path[path.index(nxt):] + [nxt]
                        key = frozenset(cyc)
                        if key not in reported:
                            reported.add(key)
                            q, ln, _via = edges[(node, nxt)]
                            pretty = " -> ".join(
                                c.split(":", 1)[1] for c in cyc)
                            self._emit(q, ln, "S2",
                                       f"lock-order cycle: {pretty} — two "
                                       "threads taking these locks in "
                                       "opposite orders deadlock")
                        continue
                    if state.get(nxt, 0) == 0:
                        state[nxt] = 1
                        path.append(nxt)
                        stack.append((nxt, iter(graph.get(nxt, ()))))
                        advanced = True
                        break
                if not advanced:
                    state[node] = 2
                    stack.pop()
                    if path and path[-1] == node:
                        path.pop()

        for n in sorted(graph):
            if state.get(n, 0) == 0:
                dfs(n)

    def _rule_s3(self):
        # per-function direct blocking ops (held or not) for the
        # one-level transitive check at held call sites
        direct_block: dict[str, list] = {}
        for qual, scan in self.scans.items():
            out = []
            for cnode, local, line in scan.calls:
                held = self._held_at(qual, local)
                d = self._blocking_desc(qual, cnode, held)
                if d:
                    out.append((d, line))
            direct_block[qual] = out
        for qual, scan in self.scans.items():
            seen = set()
            for cnode, local, line in scan.calls:
                held = self._held_at(qual, local)
                if not held:
                    continue
                lock = sorted(held)[0].split(":", 1)[1]
                d = self._blocking_desc(qual, cnode, held)
                if d and ("direct", d) not in seen:
                    seen.add(("direct", d))
                    self._emit(qual, line, "S3",
                               f"{d} while holding {lock}")
                if self._is_intraclass(qual, cnode):
                    continue    # the callee is analyzed with inherited held
                for t in self._restricted_targets(qual, cnode):
                    if t in self.lint.jax_touch and ("jit", t) not in seen:
                        seen.add(("jit", t))
                        self._emit(qual, line, "S3",
                                   f"call into {t.split(':', 1)[1]} "
                                   f"dispatches jax work while holding "
                                   f"{lock}")
                for t in self._full_targets(qual, cnode):
                    for d2, _l2 in direct_block.get(t, ()):
                        if ("lvl1", t, d2) in seen:
                            continue
                        seen.add(("lvl1", t, d2))
                        self._emit(qual, line, "S3",
                                   f"call into {t.split(':', 1)[1]} "
                                   f"performs {d2} while holding {lock}")


# -- baseline + driver --------------------------------------------------------

@dataclasses.dataclass
class SyncResult:
    new: list
    baselined: list
    stale: list
    analyzer: SyncAnalyzer | None = None

    @property
    def ok(self) -> bool:
        return not self.new


def run_sync(paths: Iterable[str] | None = None,
             baseline: list | None = None,
             root: str | None = None) -> SyncResult:
    """Run the sync pass over ``paths`` (default: the package) against a
    baseline of justified exceptions (dicts with file/rule/qualname)."""
    root = root or REPO_ROOT
    lint = Linter()
    for f in collect_files(paths or DEFAULT_TARGETS, root):
        lint.add_file(f, relto=root)
    syntax_errors = [SyncViolation(v.file, v.line, "S1", v.qualname,
                                   v.message)
                     for v in lint.violations]   # add_file syntax errors
    lint.violations = []
    lint.build()
    an = SyncAnalyzer(lint)
    an.run()
    an.violations = syntax_errors + an.violations
    baseline = baseline or []
    bl_keys = {(b["file"], b["rule"], b["qualname"]) for b in baseline}
    new = [v for v in an.violations if v.key() not in bl_keys]
    old = [v for v in an.violations if v.key() in bl_keys]
    hit = {v.key() for v in old}
    stale = [b for b in baseline
             if b["rule"] != "S4" and
             (b["file"], b["rule"], b["qualname"]) not in hit]
    return SyncResult(new, old, stale, an)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="mfmsync",
        description="lock-discipline & shared-state static analysis "
                    "(S1-S3; see docs/DOCTRINE.md, 'Concurrency "
                    "doctrine')")
    ap.add_argument("paths", nargs="*", default=list(DEFAULT_TARGETS),
                    help="files/dirs to analyze (default: mfm_tpu)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON of justified findings "
                         "('none' disables)")
    ap.add_argument("--strict", action="store_true",
                    help="also fail on stale baseline entries")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--root", default=REPO_ROOT,
                    help="root for module-name derivation (default: repo)")
    args = ap.parse_args(argv)

    bl_path = None if args.baseline.lower() == "none" else (
        args.baseline if os.path.isabs(args.baseline)
        else os.path.join(args.root, args.baseline))
    res = run_sync(args.paths, load_baseline(bl_path), root=args.root)

    if args.as_json:
        print(json.dumps({
            "new": [dataclasses.asdict(v) for v in res.new],
            "baselined": [dataclasses.asdict(v) for v in res.baselined],
            "stale": res.stale,
        }, indent=1))
    else:
        for v in res.new:
            print(v.render())
        for b in res.stale:
            print(f"STALE baseline entry: {b['file']} {b['rule']} "
                  f"[{b['qualname']}] — the finding no longer exists; "
                  "remove it")
        print(f"mfmsync: {len(res.new)} new finding(s), "
              f"{len(res.baselined)} baselined, {len(res.stale)} stale "
              "baseline entr(ies)")
    if res.new:
        return 1
    if args.strict and res.stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
