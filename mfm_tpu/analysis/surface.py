"""A4 — recompile-surface enumeration over the declared bucket ladders.

The serving subsystems all make the same steady-state promise: host-side
padding quantizes batch shapes onto a SMALL declared ladder (query/scenario
geometric 8*4^i buckets, eigen power-of-two >= 64 draw buckets), so the jit
cache holds exactly one entry per bucket and the hot loop never retraces.
Until now that was a runtime counter assertion (assert_max_compiles); this
pass makes it a provable static property:

- every registered ladder cell's **jit cache key** — the flattened
  (shape, dtype) signature of its operands plus the repr of its static
  arguments — is computed WITHOUT lowering anything;
- the number of DISTINCT keys must equal the number of declared buckets:
  fewer means two rungs collide (the ladder lies about its arity), more
  means something besides the bucketed axis moved — the classic instance
  being an index operand whose dtype drifts (np.arange's platform-default
  i64 against the pad path's pinned i32), PR 1's s64 retrace trap, which
  now fails here before it can ship;
- within a ladder, every cell must agree on the **dtype signature** and on
  the **static signature** — only shapes may move between rungs;
- every declared bucket must be a fixed point of the PRODUCTION bucket
  function (``bucket_for(b) == b``, ``draw_bucket(b) == b``), so the
  registry cannot drift from the code it vouches for (the registry
  builders assert this at declaration time; the pass re-checks it here so
  a hand-built fixture cannot dodge it).

Everything is pure over avals — the cheapest pass in the audit.
"""

from __future__ import annotations

import jax

from mfm_tpu.analysis.registry import Cell, Finding

#: ladder name -> the production bucket function it must agree with
def _ladder_fn(name: str):
    if name == "eigen":
        from mfm_tpu.models.eigen import draw_bucket

        return draw_bucket
    if name in ("query", "scenario"):
        # the scenario engine reuses serve.query's geometric ladder
        from mfm_tpu.serve.query import bucket_for

        return bucket_for
    return None


def cache_key(cell: Cell) -> tuple:
    """The audit's model of the jit cache key for one cell: flattened
    operand (shape, dtype) pairs + the static signature.  Two cells with
    equal keys hit the same compiled executable; anything that makes the
    keys differ is a retrace."""
    shapes = []
    for pos, arg in enumerate(cell.args):
        if pos in cell.static_argnums:
            continue
        for leaf in jax.tree_util.tree_leaves(arg):
            shapes.append((tuple(leaf.shape), str(leaf.dtype)))
    statics = tuple(sorted((k, repr(v)) for k, v in cell.kwargs.items()))
    statics += tuple(repr(cell.args[p]) for p in cell.static_argnums)
    return (tuple(shapes), statics)


def dtype_signature(cell: Cell) -> tuple:
    """The shape-free half of the key: operand dtypes in order."""
    shapes, _ = cache_key(cell)
    return tuple(dt for _shape, dt in shapes)


def check_ladder(ep_name: str, ladder: str, cells: list) -> list:
    """The pure A4 verdicts for one entrypoint's ladder cells."""
    findings = []
    declared = [c.bucket for c in cells]
    if len(set(declared)) != len(declared):
        findings.append(Finding(
            "A4", "error", ep_name, ladder, "duplicate-bucket",
            f"declared ladder repeats buckets: {declared}"))
    keys = {}
    for c in cells:
        keys.setdefault(cache_key(c), []).append(c.name)
    if len(keys) != len(cells):
        collided = [names for names in keys.values() if len(names) > 1]
        findings.append(Finding(
            "A4", "error", ep_name, ladder, "bucket-key-collision",
            f"{len(cells)} declared buckets produce only {len(keys)} "
            f"distinct jit cache keys — colliding rungs: {collided}"))
    sigs = {}
    for c in cells:
        sigs.setdefault(dtype_signature(c), []).append(c.name)
    if len(sigs) > 1:
        findings.append(Finding(
            "A4", "error", ep_name, ladder, "ladder-dtype-drift",
            f"operand dtypes differ across ladder rungs "
            f"{ {str(k): v for k, v in sigs.items()} } — only shapes may "
            f"move between buckets (an i64 index rung is PR 1's s64 "
            f"retrace trap)"))
    statics = {cache_key(c)[1] for c in cells}
    if len(statics) > 1:
        findings.append(Finding(
            "A4", "error", ep_name, ladder, "ladder-static-drift",
            f"static arguments differ across ladder rungs — each change "
            f"is a whole extra compile per bucket ({len(statics)} static "
            f"signatures over {len(cells)} rungs)"))
    fn = _ladder_fn(ladder)
    if fn is not None:
        broken = [b for b in declared if b is None or fn(b) != b]
        if broken:
            findings.append(Finding(
                "A4", "error", ep_name, ladder, "bucket-not-fixed-point",
                f"declared buckets {broken} are not fixed points of the "
                f"production ladder function — the registry has drifted "
                f"from the code"))
    return findings


def run_pass(entrypoints, cells_by_ep: dict) -> list:
    """A4 over every registered ladder.  ``cells_by_ep`` maps Entrypoint ->
    its built cells (shared with the other passes so the builders run
    once)."""
    findings = []
    for ep in entrypoints:
        if ep.ladder is None:
            continue
        ladder_cells = [c for c in cells_by_ep[ep] if c.role == "ladder"]
        if not ladder_cells:
            findings.append(Finding(
                "A4", "error", ep.name, ep.ladder, "empty-ladder",
                "entrypoint declares a bucket ladder but registers no "
                "ladder cells"))
            continue
        findings.extend(check_ladder(ep.name, ep.ladder, ladder_cells))
    return findings
