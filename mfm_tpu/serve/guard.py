"""Per-date input guards for the daily serving loop.

A production daily-batch risk model's real failure mode is bad *days*, not
bad math (ISSUE/PAPER: USE4-style systems): a NaN-poisoned slab, a feed
that silently lost half the universe, a split-adjustment bug spraying 10-MAD
returns.  One such date entering the Newey-West / vol-regime EWMA carries
corrupts every later covariance — the carries are exact cumulative sums
with no forgetting beyond the half-life decay.

:func:`guard_slab` runs INSIDE the jitted update step (no host round-trips
in the hot loop): for each appended date it computes a reason bitmask over
the traced checks and a quarantine verdict, and maintains a ring buffer of
healthy-universe sizes so the collapse check compares against a trailing
median.  Dates are processed in order — a quarantined date does not enter
the ring, so a collapse cannot drag its own reference down.

The one check that cannot be traced — non-monotone / duplicate dates — runs
host-side (:func:`host_date_reasons`) and feeds in through ``pre_reasons``.

All thresholds come from :class:`mfm_tpu.config.QuarantinePolicy`, a frozen
(hashable) dataclass that rides in the jit-static config, so the compiled
step is specialized to the policy and re-tuning recompiles exactly once.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mfm_tpu.serve._checks import (
    combine_reason_bits,
    mad_outlier_cells,
    names_of_mask,
)

# reason bitmask: a date may trip several checks at once; the report keeps
# all of them (uint32 leaves room to grow)
REASON_NAN_DENSITY = 1        # non-finite ret fraction inside the universe
REASON_UNIVERSE_COLLAPSE = 2  # valid count << trailing-median universe
REASON_RET_OUTLIER = 4        # too many |ret - median| > mad_k * MAD cells
REASON_CAP_NONPOS = 8         # non-positive / non-finite cap in universe
REASON_DATE_ORDER = 16        # host-side: non-monotone or duplicate date
REASON_FORCED = 32            # host-side: verdict forced by a counterfactual
                              # (mfm_tpu.scenario) — not a data problem

_REASON_NAMES = (
    (REASON_NAN_DENSITY, "nan_density"),
    (REASON_UNIVERSE_COLLAPSE, "universe_collapse"),
    (REASON_RET_OUTLIER, "ret_outlier"),
    (REASON_CAP_NONPOS, "cap_nonpos"),
    (REASON_DATE_ORDER, "date_order"),
    (REASON_FORCED, "forced"),
)


def reason_names(mask: int) -> list[str]:
    """Human-readable names of the bits set in a reason mask."""
    return names_of_mask(mask, _REASON_NAMES)


class GuardReport(NamedTuple):
    """Per-date verdicts of one guarded update step.

    ``served_cov[t]`` is the covariance the serving layer should hand out
    at date t: ``vr_cov[t]`` bitwise-untouched for healthy dates, the last
    healthy covariance (``staleness[t]`` dates old) for quarantined ones.
    """

    quarantined: jax.Array   # (T,) bool
    reasons: jax.Array       # (T,) uint32 bitmask
    staleness: jax.Array     # (T,) int32: dates since the served cov was fit
    served_cov: jax.Array    # (T, K, K)


def guard_ring_init(window: int, dtype) -> tuple[jax.Array, jax.Array]:
    """Empty trailing-universe ring: NaN slots are "no observation yet"
    (the collapse check disables itself until the ring holds data)."""
    return (jnp.full((window,), jnp.nan, dtype),
            jnp.asarray(0, jnp.int32))


def guard_slab(ret, cap, valid, ring, ring_pos, policy, pre_reasons=None,
               heal_mask=None):
    """Health-check every date of an appended slab, in order.

    Args:
      ret, cap: (T, N) slab panels (compute dtype).
      valid: (T, N) bool universe mask.
      ring: (W,) trailing healthy-universe sizes (NaN = empty slot).
      ring_pos: s32 next write slot.
      policy: :class:`QuarantinePolicy` (trace-time constants).
      pre_reasons: optional (T,) uint32 host-computed reasons
        (:func:`host_date_reasons`) OR-ed into the verdicts.
      heal_mask: optional (T,) bool forcing the verdict HEALTHY at the
        marked dates regardless of what tripped — the quarantine
        counterfactual of :mod:`mfm_tpu.scenario` ("what if date t had not
        been quarantined?").  A healed date feeds the trailing-universe
        ring like any healthy one; its ``reasons`` bits are kept in the
        report so the counterfactual stays auditable.  ``None`` (the
        default) is the production path and is bitwise-identical to the
        pre-heal-mask behaviour.

    Returns ``(quarantined (T,) bool, reasons (T,) uint32, ring, ring_pos)``.
    Traced; call from inside the jitted update step.
    """
    T, _ = ret.shape
    dtype = ret.dtype
    one = jnp.asarray(1.0, dtype)
    if pre_reasons is None:
        pre_reasons = jnp.zeros((T,), jnp.uint32)
    if heal_mask is None:
        heal_mask = jnp.zeros((T,), bool)

    def body(i, state):
        ring, pos, reasons_acc = state
        rett = jax.lax.dynamic_index_in_dim(ret, i, 0, keepdims=False)
        capt = jax.lax.dynamic_index_in_dim(cap, i, 0, keepdims=False)
        vt = jax.lax.dynamic_index_in_dim(valid, i, 0, keepdims=False)
        pre = jax.lax.dynamic_index_in_dim(pre_reasons, i, 0, keepdims=False)
        heal = jax.lax.dynamic_index_in_dim(heal_mask, i, 0, keepdims=False)

        n_valid = jnp.sum(vt.astype(dtype))
        denom = jnp.maximum(n_valid, one)

        # 1. NaN/Inf density over the universe
        bad_ret = vt & ~jnp.isfinite(rett)
        nan_frac = jnp.sum(bad_ret.astype(dtype)) / denom
        r_nan = nan_frac > policy.max_nan_frac

        # 2. universe collapse vs the trailing median of HEALTHY dates.
        # An empty ring yields a NaN reference -> check disabled (isfinite).
        ref = jnp.nanmedian(ring)
        r_uni = jnp.isfinite(ref) & (n_valid < policy.min_universe_frac * ref)

        # 3. cross-sectional return outliers: |r - med| > mad_k * MAD
        # (serve/_checks.py owns the formula, shared with the request
        # guards; a degenerate MAD disables the check, NaN never flags)
        r_use = jnp.where(vt & jnp.isfinite(rett), rett, jnp.nan)
        out_cells = mad_outlier_cells(r_use, policy.mad_k, jnp)
        out_frac = jnp.sum(out_cells.astype(dtype)) / denom
        r_out = out_frac > policy.max_outlier_frac

        # 4. cap positivity: the regression weights are cap-derived; a
        # non-positive or non-finite cap inside the universe is corrupt
        r_cap = jnp.any(vt & (~jnp.isfinite(capt) | (capt <= 0)))

        reasons = pre | combine_reason_bits((
            (r_nan, REASON_NAN_DENSITY),
            (r_uni, REASON_UNIVERSE_COLLAPSE),
            (r_out, REASON_RET_OUTLIER),
            (r_cap, REASON_CAP_NONPOS),
        ), jnp)
        q_t = (reasons != 0) & ~heal

        # only healthy dates feed the trailing-universe reference
        ring_upd = jax.lax.dynamic_update_index_in_dim(
            ring, n_valid.astype(ring.dtype), pos, 0)
        ring = jnp.where(q_t, ring, ring_upd)
        pos = jnp.where(q_t, pos,
                        (pos + jnp.int32(1)) % jnp.int32(ring.shape[0]))
        reasons_acc = jax.lax.dynamic_update_index_in_dim(
            reasons_acc, reasons, i, 0)
        return ring, pos, reasons_acc

    ring, ring_pos, reasons = jax.lax.fori_loop(
        jnp.int32(0), jnp.int32(T), body,
        (ring, ring_pos.astype(jnp.int32), jnp.zeros((T,), jnp.uint32)),
    )
    return (reasons != 0) & ~heal_mask, reasons, ring, ring_pos


#: the standalone guard step: the same traced :func:`guard_slab`, compiled
#: on its own — ops tooling pre-screens a slab (is this feed day servable?)
#: without paying for a model update.  ``policy`` is jit-static exactly as
#: in the fused guarded step; the ring state is donated through (argnums
#: 3-4) because the screen advances it the same way the update does.  This
#: is a registered audit entrypoint (mfm_tpu/analysis/registry.py
#: "guard.step") — its donation/dtype/recompile contracts are proven
#: statically by ``mfm-tpu audit``.
guard_slab_jit = jax.jit(guard_slab, static_argnums=(5,),
                         donate_argnums=(3, 4))


def host_date_reasons(dates, last_date=None) -> "object":
    """Host-side pre-check: flag non-monotone / duplicate dates.

    ``dates`` is the appended slab's date axis (any orderable values, e.g.
    the normalized strings of :func:`mfm_tpu.pipeline.date_stamp`);
    ``last_date`` the checkpoint's last served date.  Returns a (T,) uint32
    numpy array with :data:`REASON_DATE_ORDER` set on every date that is
    <= its predecessor (or <= ``last_date``) — those dates are quarantined
    rather than folded into the carries, so one miswired feed day cannot
    corrupt the time axis.  Host-side by design: string/object dates never
    enter the traced step.
    """
    import numpy as np

    out = np.zeros(len(dates), np.uint32)
    prev = last_date
    for i, d in enumerate(dates):
        if prev is not None and not (d > prev):
            out[i] = REASON_DATE_ORDER
        else:
            prev = d
    return out
