"""Deadline-bearing worker transports: the fleet's machine boundary.

PR 13's fleet talked to its workers through blocking pipe file objects —
``Replica.run_batch`` sat in ``proc.stdout.readline()`` with no deadline,
so a worker that was alive-but-wedged (SIGSTOP, a hung device call, a
full pipe) froze every flush forever.  This module is the fix AND the
multi-host door: every worker read and write goes through a transport
whose single I/O primitive carries a deadline, and the same JSONL wire
protocol runs over either

- :class:`PipeTransport` — the stdin/stdout pipe pair of a spawned
  ``serve --worker`` subprocess (same-host fleet, the PR 13 shape), with
  the pipe fds switched to non-blocking so writes against a full pipe
  time out instead of wedging the flush loop; or
- :class:`TcpTransport` — a socket to a worker started elsewhere with
  ``serve --worker --listen HOST:PORT`` (multi-host fleet).  Connection
  establishment reuses :func:`mfm_tpu.data.etl.with_retry` exponential
  backoff, and the raised exception is stamped ``phase="connect"`` so a
  "never connected" failure reads differently from a mid-batch loss
  (``phase="batch"``) in the fleet manifest's transport counters.

Failure taxonomy (what :class:`~mfm_tpu.serve.replica.FleetServer` keys
its quarantine/re-dispatch decisions on):

- :class:`TransportClosed` — the peer is GONE: EOF, broken pipe,
  connection reset.  The worker is dead; its in-flight batch
  re-dispatches to a survivor.
- :class:`TransportTimeout` — the peer is WEDGED: the deadline expired
  with the worker still nominally alive.  Treated exactly like a death
  (quarantine + re-dispatch) because a frozen worker holding a batch
  hostage is indistinguishable from a dead one to the client — except
  that the process may need killing at shutdown, which ``Replica.close``
  handles.

Both carry a ``phase`` attribute ("connect" or "batch") and feed the
``mfm_fleet_transport_*`` counters.  Deadlines are per-I/O, not
per-batch: a worker legitimately crunching a large batch keeps the read
alive by emitting envelopes as sub-batches drain, while a wedged one
produces silence and trips the timeout within one ``io_timeout_s``.

The transports are NOT internally locked: the fleet serializes all
worker I/O under the coalescer's admission lock (the mfmsync-baselined
dispatch discipline), and the worker side of a socket is owned by one
``run_worker`` loop.  Keeping them lock-free keeps mfmsync's S1/S2
surface unchanged.
"""

from __future__ import annotations

import json
import os
import select
import socket
import time

#: default per-I/O deadline — generous against real batch walls (BENCH
#: figures put p99 batch wall well under a second), tight enough that a
#: wedged worker cannot stall a flush for long
DEFAULT_IO_TIMEOUT_S = 30.0


class TransportError(RuntimeError):
    """Base worker-transport failure; ``phase`` says when it happened."""

    phase = "batch"

    def __init__(self, msg: str, *, phase: str = "batch"):
        super().__init__(msg)
        self.phase = phase


class TransportClosed(TransportError):
    """Peer gone: EOF, broken pipe, connection reset."""


class TransportTimeout(TransportError):
    """Deadline expired with the peer still nominally alive (wedged)."""


def _new_counters() -> dict:
    return {
        "frames_sent": 0,
        "frames_recv": 0,
        "send_timeouts": 0,
        "recv_timeouts": 0,
        "connect_attempts": 0,
        "reconnects": 0,
        "failure_phases": {},   # phase -> count, off raised errors
    }


class LineTransport:
    """Deadline-bearing JSONL framing over a byte stream.

    Subclasses supply four primitives — readable/writable fds and
    non-blocking chunk read/write — and this base runs the framed
    ``send_lines`` / ``recv_line`` loops with one deadline per I/O wait.
    A ``None`` from :meth:`recv_line` means clean EOF (the worker drained
    and exited); torn/blocked I/O raises the taxonomy above.
    """

    def __init__(self, io_timeout_s: float = DEFAULT_IO_TIMEOUT_S):
        self.io_timeout_s = float(io_timeout_s)
        self.closed = False
        self.counters = _new_counters()
        self._rbuf = bytearray()

    # -- subclass surface ----------------------------------------------------
    def _recv_fd(self) -> int:
        raise NotImplementedError

    def _send_fd(self) -> int:
        raise NotImplementedError

    def _read_chunk(self, n: int) -> bytes:
        """Non-blocking read after readability; b'' = EOF."""
        raise NotImplementedError

    def _write_chunk(self, data: bytes) -> int:
        """Non-blocking write after writability; returns bytes written."""
        raise NotImplementedError

    def close(self) -> None:
        self.closed = True

    # -- deadline plumbing ---------------------------------------------------
    def _fail(self, exc: TransportError) -> TransportError:
        ph = exc.phase
        self.counters["failure_phases"][ph] = \
            self.counters["failure_phases"].get(ph, 0) + 1
        return exc

    def _await(self, fd: int, deadline: float, *, read: bool) -> None:
        remaining = deadline - time.monotonic()
        if remaining > 0:
            try:
                r, w, _ = select.select([fd] if read else [],
                                        [] if read else [fd], [],
                                        remaining)
            except (OSError, ValueError) as e:
                raise self._fail(TransportClosed(
                    f"transport fd gone: {e}")) from e
            if r or w:
                return
        op = "recv" if read else "send"
        self.counters[f"{op}_timeouts"] += 1
        raise self._fail(TransportTimeout(
            f"worker {op} exceeded {self.io_timeout_s:.3f}s deadline "
            "(peer wedged?)"))

    # -- framing -------------------------------------------------------------
    def send_lines(self, lines) -> None:
        """Write each line + newline, one deadline per I/O wait."""
        data = memoryview(("".join(ln + "\n" for ln in lines))
                          .encode("utf-8"))
        deadline = time.monotonic() + self.io_timeout_s
        while data:
            self._await(self._send_fd(), deadline, read=False)
            try:
                n = self._write_chunk(data)
            except (BlockingIOError, InterruptedError):
                continue
            except (BrokenPipeError, ConnectionError, OSError) as e:
                raise self._fail(TransportClosed(
                    f"worker pipe/socket broke mid-send: {e}")) from e
            data = data[n:]
            # progress resets the clock: the deadline bounds SILENCE,
            # not total batch size
            deadline = time.monotonic() + self.io_timeout_s
        self.counters["frames_sent"] += len(lines)

    def send_frame(self, obj: dict) -> None:
        self.send_lines([json.dumps(obj, sort_keys=True)])

    def recv_line(self, timeout_s: float | None = None) -> str | None:
        """One newline-terminated frame, or None on clean EOF."""
        deadline = time.monotonic() + (self.io_timeout_s
                                       if timeout_s is None
                                       else float(timeout_s))
        while True:
            nl = self._rbuf.find(b"\n")
            if nl >= 0:
                line = self._rbuf[:nl].decode("utf-8")
                del self._rbuf[:nl + 1]
                self.counters["frames_recv"] += 1
                return line
            self._await(self._recv_fd(), deadline, read=True)
            try:
                chunk = self._read_chunk(1 << 16)
            except (BlockingIOError, InterruptedError):
                continue
            except (ConnectionError, OSError) as e:
                raise self._fail(TransportClosed(
                    f"worker pipe/socket broke mid-recv: {e}")) from e
            if not chunk:
                if self._rbuf:
                    raise self._fail(TransportClosed(
                        "EOF with a torn partial line buffered"))
                return None
            self._rbuf += chunk


class PipeTransport(LineTransport):
    """The stdin/stdout pipe pair of a spawned worker subprocess.

    Takes ownership of the fds: they are switched to non-blocking and
    all I/O bypasses the ``subprocess`` file objects (mixing buffered
    writes with raw fd writes would tear frames)."""

    def __init__(self, proc, io_timeout_s: float = DEFAULT_IO_TIMEOUT_S):
        super().__init__(io_timeout_s)
        self.proc = proc
        self._wfd = proc.stdin.fileno()
        self._rfd = proc.stdout.fileno()
        os.set_blocking(self._wfd, False)
        os.set_blocking(self._rfd, False)
        self.counters["connect_attempts"] = 1

    def _recv_fd(self) -> int:
        return self._rfd

    def _send_fd(self) -> int:
        return self._wfd

    def _read_chunk(self, n: int) -> bytes:
        return os.read(self._rfd, n)

    def _write_chunk(self, data) -> int:
        return os.write(self._wfd, data)

    def close(self) -> None:
        """Half-close the worker's stdin (EOF = graceful drain-out);
        stdout stays open so the tail responses remain readable."""
        if not self.closed:
            self.closed = True
            try:
                self.proc.stdin.close()
            except (BrokenPipeError, OSError):
                pass


class TcpTransport(LineTransport):
    """A socket to a ``serve --worker --listen`` process on any host."""

    def __init__(self, sock: socket.socket,
                 io_timeout_s: float = DEFAULT_IO_TIMEOUT_S):
        super().__init__(io_timeout_s)
        self.sock = sock
        self.sock.setblocking(False)

    @classmethod
    def connect(cls, addr: tuple, *,
                io_timeout_s: float = DEFAULT_IO_TIMEOUT_S,
                attempts: int = 5, backoff_s: float = 0.05,
                sleep=time.sleep) -> "TcpTransport":
        """Dial a worker with exponential backoff (the worker may still
        be loading its checkpoint).  Exhaustion raises the last
        ``OSError`` stamped ``phase="connect"`` plus ``with_retry``'s
        ``attempts``/``total_backoff_s`` history."""
        from mfm_tpu.data.etl import with_retry

        host, port = addr[0], int(addr[1])
        made: list = []

        def dial():
            made.append(1)
            return socket.create_connection((host, port),
                                            timeout=io_timeout_s)
        try:
            sock = with_retry(dial, attempts=attempts,
                              backoff_s=backoff_s, sleep=sleep,
                              exponential=True, retryable=(OSError,),
                              phase="connect")
        except OSError:
            raise
        t = cls(sock, io_timeout_s)
        t.counters["connect_attempts"] = len(made)
        t.counters["reconnects"] = max(0, len(made) - 1)
        return t

    def _recv_fd(self) -> int:
        return self.sock.fileno()

    def _send_fd(self) -> int:
        return self.sock.fileno()

    def _read_chunk(self, n: int) -> bytes:
        return self.sock.recv(n)

    def _write_chunk(self, data) -> int:
        return self.sock.send(data)

    def close(self) -> None:
        """Half-close the write side (EOF = graceful drain-out) so the
        worker's tail responses remain readable, like the pipe path."""
        if not self.closed:
            self.closed = True
            try:
                self.sock.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def abort(self) -> None:
        self.closed = True
        try:
            self.sock.close()
        except OSError:
            pass


def serve_worker_socket(server, host: str, port: int, *,
                        run_worker=None, announce=None,
                        poll_on_flush: bool = True) -> dict:
    """Worker side of the TCP transport: bind, accept ONE frontend,
    run the ordinary :func:`~mfm_tpu.serve.replica.run_worker` loop over
    the connection's file objects, and return the worker's serve
    summary when the frontend hangs up (EOF = drain-out, exactly like a
    closed stdin).  One connection per worker process keeps the process
    model identical to the pipe fleet — a frontend that needs the
    worker again restarts it, it does not reattach."""
    if run_worker is None:
        from mfm_tpu.serve.replica import run_worker
    ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, int(port)))
        ls.listen(1)
        if announce is not None:
            announce(ls.getsockname()[:2])
        conn, _addr = ls.accept()
    finally:
        ls.close()
    try:
        rfile = conn.makefile("r", encoding="utf-8")
        wfile = conn.makefile("w", encoding="utf-8")
        return run_worker(server, rfile, wfile,
                          poll_on_flush=poll_on_flush)
    finally:
        try:
            conn.close()
        except OSError:
            pass
